#!/usr/bin/env python
"""concheck: the whole-engine static concurrency soundness pass
(ISSUE 11) — the build-time half of the layer whose runtime half is
presto_tpu/obs/sanitizer.py.

Reference: the concurrency guarantees the Java original gets from
error-prone's `@GuardedBy` checking plus lock-ordering review; here
three AST rules over the whole presto_tpu/ tree:

  con-registry   the lock inventory. Every lock/Condition is created
                 through obs.sanitizer.make_lock/make_condition with a
                 canonical site name (module.Class.attr) declared in
                 sanitizer.LOCK_REGISTRY; raw threading.Lock/RLock/
                 Condition construction outside the sanitizer is a
                 finding (an uninstrumented lock is invisible to the
                 runtime sanitizer AND to this pass's naming). Every
                 threading.Thread target is declared in
                 sanitizer.THREAD_REGISTRY. Stale registry entries
                 fail like stale QUERY_COUNTERS entries.
  con-graph      the static lock-acquisition graph: which locks can be
                 HELD WHILE ACQUIRING which others — lexical `with`
                 nesting plus calls resolved ONE level deep (a call
                 made under lock A to a function that acquires lock B
                 is an A->B edge; `*_locked` helper methods count as
                 holding their class's locks, the convention the
                 runtime sanitizer keeps honest). A cycle is a
                 potential deadlock and fails the build.
  con-blocking   no blocking call (time.sleep, urllib urlopen,
                 subprocess, jax.device_put/device_get/
                 block_until_ready) while any registered lock is held
                 — directly, inside a `*_locked` helper, or one call
                 level deep. A deliberate exception carries
                 `# concheck: blocking-ok - <why>` on the call line
                 (or the line above).

Known approximations, chosen to be safe-but-quiet: locks are tracked
per NAME (class granularity); call resolution is by method/function
name across the tree (an over-approximation — same-named methods all
count); unresolvable receivers (`x._lock` where several classes own a
`_lock`) are treated as held for the blocking rule but excluded from
the graph so ambiguity can never fabricate a cycle.

Run: `python tools/concheck.py` (exit 1 on findings); tier-1 runs the
same checks via tests/test_concheck.py, and tools/ci_static.sh runs
them as the third static gate next to lint + plan_audit.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # direct `python tools/concheck.py` runs
    sys.path.insert(0, REPO)

from tools.lint import (  # noqa: E402
    _LOCK_EXEMPT_FILES,
    Finding,
    _dotted,
    _parse,
    _py_files,
    _rel,
)

# the instrumentation layer itself (owns the one raw meta-lock): one
# shared exemption list with the lint locks rule, so the two gates
# can never disagree about what is instrumentation-layer code
_EXEMPT_FILES = _LOCK_EXEMPT_FILES

_BLOCKING_OK = re.compile(r"#\s*concheck:\s*blocking-ok\s*-\s*\S")

_LOCK_CTORS = ("Lock", "RLock", "Condition")
_FACTORIES = ("make_lock", "make_condition")

# blocking tails; subprocess entry points additionally require the
# `subprocess.` prefix ("run"/"call" alone are far too generic)
_BLOCKING_TAILS = {
    "sleep": "stalls the holder while every other thread queues on "
             "the lock",
    "urlopen": "network I/O under a lock serializes the engine behind "
               "a peer's latency",
    "device_put": "a device transfer under a lock serializes readers "
                  "behind the accelerator",
    "device_get": "a device sync under a lock serializes readers "
                  "behind the accelerator",
    "block_until_ready": "a device fence under a lock serializes "
                         "readers behind the accelerator",
    # the metered choke points (exec/xfer.py) are still device syncs:
    # routing a crossing does not make it lock-safe
    "to_host": "a metered d2h pull under a lock serializes readers "
               "behind the accelerator (use PageStore.put_host / "
               "host_pages for already-host pytrees)",
    "to_device": "a metered h2d stage under a lock serializes readers "
                 "behind the accelerator",
    "np_host": "a metered d2h view under a lock serializes readers "
               "behind the accelerator when the array is "
               "device-backed",
    # lazy spool materialization (dist/spool.spool_blob) is a d2h pull
    # PLUS serialization: the device-sync helper ISSUE 13 added to the
    # exchange plane — never under a task/registry lock
    "spool_blob": "lazy spool materialization (d2h + serialize) under "
                  "a lock stalls every consumer and status poll "
                  "behind the accelerator",
}
_SUBPROCESS_TAILS = ("run", "call", "check_call", "check_output",
                     "Popen")


def _is_blocking(dotted: Optional[str]) -> Optional[str]:
    if not dotted:
        return None
    tail = dotted.rsplit(".", 1)[-1]
    if tail in _BLOCKING_TAILS:
        return dotted
    if tail in _SUBPROCESS_TAILS and "subprocess" in dotted:
        return dotted
    return None


def _modrel(path: str) -> str:
    """Dotted module path under presto_tpu/ ('cache.store'); files
    outside the tree (seeded test files) use their basename."""
    rel = os.path.relpath(os.path.abspath(path),
                          os.path.join(REPO, "presto_tpu"))
    if rel.startswith(".."):
        rel = os.path.basename(path)
    return rel[:-3].replace(os.sep, ".") if rel.endswith(".py") \
        else rel.replace(os.sep, ".")


def _body_walk(node: ast.AST):
    """Walk a function body WITHOUT descending into nested function/
    class definitions (closures are separate functions with their own
    lock context) or lambdas."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


class _Fn:
    """One function/method with its lock-resolution context."""

    def __init__(self, node, module: "_Module", cls_name: Optional[str]):
        self.node = node
        self.module = module
        self.cls_name = cls_name
        self.name = node.name
        self.qual = (f"{module.modrel}."
                     f"{cls_name + '.' if cls_name else ''}{node.name}")


class _Module:
    def __init__(self, path: str):
        self.path = path
        self.rel = _rel(path)
        self.modrel = _modrel(path)
        self.tree, self.lines = _parse(path)
        # lock attr -> canonical name, per class / module-level
        self.class_locks: Dict[str, Dict[str, str]] = {}
        self.module_locks: Dict[str, str] = {}
        # (expected canonical, literal-or-None, line, has_name_arg)
        self.factory_sites: List[Tuple[str, Optional[str], int, bool]] \
            = []
        self.raw_sites: List[Tuple[int, str]] = []
        self.thread_targets: List[Tuple[Optional[str], int]] = []
        self.functions: List[_Fn] = []

    def escape_ok(self, line: int) -> bool:
        ctx = "\n".join(self.lines[max(line - 2, 0):line])
        return bool(_BLOCKING_OK.search(ctx))


def _name_literal(call: ast.Call) -> Tuple[Optional[str], bool]:
    """(string literal of the name argument, name-arg-present)."""
    args = list(call.args)
    for kw in call.keywords:
        if kw.arg == "name":
            args.insert(0, kw.value)
    if not args:
        return None, False
    a = args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        return a.value, True
    return None, True


def _has_lock_kwarg(call: ast.Call) -> bool:
    return any(kw.arg == "lock" for kw in call.keywords)


def collect(paths: List[str]) -> List[_Module]:
    mods: List[_Module] = []
    for path in paths:
        if _rel(path) in _EXEMPT_FILES:
            continue
        m = _Module(path)
        mods.append(m)

        def note_lock(owner_cls: Optional[str], attr: str,
                      call: ast.Call, line: int) -> None:
            tail = (_dotted(call.func) or "").rsplit(".", 1)[-1]
            canonical = (f"{m.modrel}."
                         f"{owner_cls + '.' if owner_cls else ''}"
                         f"{attr}")
            if tail in _FACTORIES:
                if tail == "make_condition" and _has_lock_kwarg(call):
                    # alias: Condition fronting an existing lock — the
                    # attr resolves to the backing lock's name
                    lk = None
                    for kw in call.keywords:
                        if kw.arg == "lock" and isinstance(
                                kw.value, ast.Attribute):
                            lk = kw.value.attr
                    owner = m.class_locks.get(owner_cls or "", {})
                    canonical = owner.get(lk, canonical)
                else:
                    literal, has = _name_literal(call)
                    m.factory_sites.append(
                        (canonical, literal, line, has))
                    if literal:
                        canonical = literal
            else:
                m.raw_sites.append((line, tail))
            if owner_cls is None:
                m.module_locks[attr] = canonical
            else:
                m.class_locks.setdefault(owner_cls, {})[attr] = \
                    canonical

        # pass 1: lock definitions + thread targets + functions
        def scan(node, cls_name: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    scan(child, child.name)
                    continue
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    m.functions.append(_Fn(child, m, cls_name))
                    scan(child, cls_name)
                    continue
                if isinstance(child, ast.Assign) and isinstance(
                        child.value, ast.Call):
                    tail = (_dotted(child.value.func) or
                            "").rsplit(".", 1)[-1]
                    if tail in _LOCK_CTORS + _FACTORIES:
                        root = (_dotted(child.value.func) or
                                "").split(".", 1)[0]
                        is_threading = (tail in _LOCK_CTORS and
                                        root == "threading")
                        if is_threading or tail in _FACTORIES:
                            for t in child.targets:
                                if isinstance(t, ast.Attribute) and \
                                        isinstance(t.value, ast.Name) \
                                        and t.value.id in ("self",
                                                           "cls"):
                                    note_lock(cls_name, t.attr,
                                              child.value,
                                              child.lineno)
                                elif isinstance(t, ast.Name):
                                    note_lock(
                                        cls_name if isinstance(
                                            node, ast.ClassDef)
                                        else None,
                                        t.id, child.value,
                                        child.lineno)
                scan(child, cls_name)

        scan(m.tree, None)
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Call) and \
                    (_dotted(node.func) or "").endswith(
                        "threading.Thread"):
                target = None
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = _dotted(kw.value)
                m.thread_targets.append((target, node.lineno))
    return mods


class _Index:
    """Cross-module resolution index."""

    def __init__(self, mods: List[_Module]):
        self.mods = mods
        self.fn_by_name: Dict[str, List[_Fn]] = {}
        self.init_by_class: Dict[str, List[_Fn]] = {}
        self.attr_owners: Dict[str, Set[str]] = {}
        for m in mods:
            for fn in m.functions:
                self.fn_by_name.setdefault(fn.name, []).append(fn)
                if fn.name == "__init__" and fn.cls_name:
                    self.init_by_class.setdefault(
                        fn.cls_name, []).append(fn)
            for cls, locks in m.class_locks.items():
                for attr, canon in locks.items():
                    self.attr_owners.setdefault(attr, set()).add(canon)
            for attr, canon in m.module_locks.items():
                self.attr_owners.setdefault(attr, set()).add(canon)
        self._acquires: Dict[int, List[str]] = {}
        self._blocking: Dict[int, List[Tuple[str, int]]] = {}

    # ------------------------------------------------- lock resolution
    def resolve_lock(self, expr, fn: _Fn) -> Optional[str]:
        """Canonical lock name for a `with` target; '?attr' when the
        receiver is ambiguous (held for blocking, excluded from the
        graph); None when it is not a known lock."""
        if isinstance(expr, ast.Name):
            return fn.module.module_locks.get(expr.id)
        if not isinstance(expr, ast.Attribute):
            return None
        attr = expr.attr
        if isinstance(expr.value, ast.Name):
            recv = expr.value.id
            if recv in ("self", "cls") and fn.cls_name:
                hit = fn.module.class_locks.get(
                    fn.cls_name, {}).get(attr)
                if hit:
                    return hit
            for m in self.mods:  # ClassName._class_lock
                if recv in m.class_locks and \
                        attr in m.class_locks[recv]:
                    return m.class_locks[recv][attr]
        owners = self.attr_owners.get(attr, set())
        if len(owners) == 1:
            return next(iter(owners))
        if owners:
            return f"?{attr}"
        return None

    def resolve_callees(self, call: ast.Call) -> List[_Fn]:
        name = _dotted(call.func)
        if not name:
            return []
        tail = name.rsplit(".", 1)[-1]
        if tail in self.init_by_class:
            return self.init_by_class[tail]
        return self.fn_by_name.get(tail, [])

    # --------------------------------------- per-function derived facts
    def direct_acquires(self, fn: _Fn) -> List[str]:
        got = self._acquires.get(id(fn))
        if got is None:
            got = []
            for n in _body_walk(fn.node):
                if isinstance(n, ast.With):
                    for item in n.items:
                        canon = self.resolve_lock(
                            item.context_expr, fn)
                        if canon and not canon.startswith("?"):
                            got.append(canon)
            self._acquires[id(fn)] = got
        return got

    def direct_blocking(self, fn: _Fn) -> List[Tuple[str, int]]:
        got = self._blocking.get(id(fn))
        if got is None:
            got = []
            for n in _body_walk(fn.node):
                if isinstance(n, ast.Call):
                    bad = _is_blocking(_dotted(n.func))
                    if bad:
                        got.append((bad, n.lineno))
            self._blocking[id(fn)] = got
        return got


# ------------------------------------------------------- rule: registry
def check_registry(mods: List[_Module], lock_registry=None,
                   thread_registry=None,
                   full_sweep: bool = False) -> List[Finding]:
    if lock_registry is None or thread_registry is None:
        from presto_tpu.obs import sanitizer as SAN

        lock_registry = (SAN.LOCK_REGISTRY if lock_registry is None
                         else lock_registry)
        thread_registry = (SAN.THREAD_REGISTRY if thread_registry
                           is None else thread_registry)
    out: List[Finding] = []
    seen_locks: Set[str] = set()
    seen_threads: Set[str] = set()
    for m in mods:
        for line, tail in m.raw_sites:
            out.append(Finding(
                "con-registry", m.rel, line,
                f"raw threading.{tail}() construction — create engine "
                f"locks through obs.sanitizer.make_lock/make_condition "
                f"so the runtime sanitizer can instrument them and "
                f"this pass can name them"))
        for canonical, literal, line, has_name in m.factory_sites:
            if not has_name or literal is None:
                out.append(Finding(
                    "con-registry", m.rel, line,
                    f"lock factory call needs a string-literal site "
                    f"name (expected {canonical!r}) — dynamic names "
                    f"defeat the registry cross-check"))
                continue
            seen_locks.add(literal)
            if literal != canonical:
                out.append(Finding(
                    "con-registry", m.rel, line,
                    f"lock name {literal!r} does not match its site — "
                    f"the canonical name here is {canonical!r} "
                    f"(module.Class.attr), which is what the runtime "
                    f"sanitizer's reports and the lock graph key on"))
            if literal not in lock_registry:
                out.append(Finding(
                    "con-registry", m.rel, line,
                    f"lock {literal!r} is not declared in "
                    f"obs.sanitizer.LOCK_REGISTRY — declare it with "
                    f"help text (the QUERY_COUNTERS discipline "
                    f"applied to locks)"))
        for target, line in m.thread_targets:
            if target is None:
                out.append(Finding(
                    "con-registry", m.rel, line,
                    "threading.Thread with a dynamic target — use a "
                    "named method so the thread inventory stays "
                    "auditable"))
                continue
            key = f"{m.modrel}:{target}"
            seen_threads.add(key)
            if key not in thread_registry:
                out.append(Finding(
                    "con-registry", m.rel, line,
                    f"thread target {key!r} is not declared in "
                    f"obs.sanitizer.THREAD_REGISTRY — declare it with "
                    f"help text"))
    if full_sweep:
        for name in sorted(set(lock_registry) - seen_locks):
            out.append(Finding(
                "con-registry", "presto_tpu/obs/sanitizer.py", 1,
                f"LOCK_REGISTRY declares {name!r} but no "
                f"make_lock/make_condition site exists (stale entry?)"))
        for name in sorted(set(thread_registry) - seen_threads):
            out.append(Finding(
                "con-registry", "presto_tpu/obs/sanitizer.py", 1,
                f"THREAD_REGISTRY declares {name!r} but no "
                f"threading.Thread site exists (stale entry?)"))
    return out


# ------------------------------------------------- graph + blocking walk
def _held_regions(idx: _Index, fn: _Fn):
    """Yield (held_names, node) for every Call and With reached while
    at least one lock is held in ``fn`` (lexical; `*_locked` methods
    start holding their class's locks)."""
    held0: List[str] = []
    if fn.name.endswith("_locked") and fn.cls_name:
        held0 = sorted(set(
            fn.module.class_locks.get(fn.cls_name, {}).values()))

    def walk(node, held: List[str]):
        """Process ``node`` itself, then descend (nested defs/lambdas
        are separate functions with their own lock context)."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, ast.With):
            names = []
            for item in node.items:
                canon = idx.resolve_lock(item.context_expr, fn)
                if canon:
                    names.append(canon)
            if names and held:
                yield held, node
            inner = held + names
            for stmt in node.body:
                yield from walk(stmt, inner)
            # with-item expressions themselves evaluate un-held
            return
        if isinstance(node, ast.Call) and held:
            yield held, node
        for child in ast.iter_child_nodes(node):
            yield from walk(child, held)

    for stmt in ast.iter_child_nodes(fn.node):
        yield from walk(stmt, held0)


def build_lock_graph(idx: _Index):
    """edges: (held, acquired) -> 'path:line' witness site."""
    edges: Dict[Tuple[str, str], str] = {}

    def note(h: str, m: str, rel: str, line: int):
        if h.startswith("?") or m.startswith("?") or h == m:
            return
        edges.setdefault((h, m), f"{rel}:{line}")

    for m in idx.mods:
        for fn in m.functions:
            for held, node in _held_regions(idx, fn):
                if isinstance(node, ast.With):
                    for item in node.items:
                        canon = idx.resolve_lock(item.context_expr, fn)
                        if canon:
                            for h in held:
                                note(h, canon, m.rel, node.lineno)
                elif isinstance(node, ast.Call):
                    for callee in idx.resolve_callees(node):
                        for acq in idx.direct_acquires(callee):
                            for h in held:
                                note(h, acq, m.rel, node.lineno)
    return edges


def check_cycles(edges) -> List[Finding]:
    adj: Dict[str, List[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, []).append(b)
    out: List[Finding] = []
    seen_cycles: Set[Tuple[str, ...]] = set()

    def dfs(start: str):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in adj.get(node, ()):
                if nxt == start:
                    cyc = path + [start]
                    key = tuple(sorted(set(cyc)))
                    if key in seen_cycles:
                        continue
                    seen_cycles.add(key)
                    hops = " -> ".join(cyc)
                    sites = "; ".join(
                        f"{a}->{b} at {edges[(a, b)]}"
                        for a, b in zip(cyc, cyc[1:]))
                    site = edges[(cyc[0], cyc[1])]
                    rel, line = site.rsplit(":", 1)
                    out.append(Finding(
                        "con-graph", rel, int(line),
                        f"lock-order cycle (potential deadlock): "
                        f"{hops} [{sites}] — pick one global order "
                        f"and acquire in it, or drop the nested "
                        f"acquisition"))
                elif nxt not in path and len(path) < 16:
                    stack.append((nxt, path + [nxt]))

    for start in sorted(adj):
        dfs(start)
    return out


def check_blocking(idx: _Index) -> List[Finding]:
    blocking_fn_names = {
        fn.name for m in idx.mods for fn in m.functions
        if idx.direct_blocking(fn)
    }
    out: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()

    def note(m: _Module, line: int, held, msg: str):
        key = (m.rel, line, msg[:60])
        if key in seen or m.escape_ok(line):
            return
        seen.add(key)
        out.append(Finding(
            "con-blocking", m.rel, line,
            f"{msg} while holding {'/'.join(sorted(set(held)))} — "
            f"move it off the lock or annotate "
            f"`# concheck: blocking-ok - <why>`"))

    for m in idx.mods:
        for fn in m.functions:
            for held, node in _held_regions(idx, fn):
                if not isinstance(node, ast.Call):
                    continue
                bad = _is_blocking(_dotted(node.func))
                if bad:
                    why = _BLOCKING_TAILS.get(
                        bad.rsplit(".", 1)[-1], "blocks the holder")
                    note(m, node.lineno, held,
                         f"blocking call {bad}() [{why}]")
                    continue
                for callee in idx.resolve_callees(node):
                    for bad, bline in idx.direct_blocking(callee):
                        note(m, node.lineno, held,
                             f"call into {callee.qual}() which makes "
                             f"blocking call {bad}() (line {bline})")
                    for n2 in _body_walk(callee.node):
                        if isinstance(n2, ast.Call):
                            t2 = (_dotted(n2.func) or
                                  "").rsplit(".", 1)[-1]
                            if t2 in blocking_fn_names and \
                                    not callee.module.escape_ok(
                                        n2.lineno):
                                note(m, node.lineno, held,
                                     f"call into {callee.qual}() "
                                     f"which calls {t2}() (line "
                                     f"{n2.lineno}), a function that "
                                     f"blocks directly")
    return out


# ---------------------------------------------------------------- driver
def run_concheck(paths: Optional[List[str]] = None,
                 lock_registry=None, thread_registry=None
                 ) -> List[Finding]:
    full = paths is None
    if paths is None:
        paths = _py_files("presto_tpu")
    mods = collect(paths)
    idx = _Index(mods)
    findings = check_registry(mods, lock_registry=lock_registry,
                              thread_registry=thread_registry,
                              full_sweep=full)
    findings += check_cycles(build_lock_graph(idx))
    findings += check_blocking(idx)
    return findings


def main() -> int:
    import time

    t0 = time.monotonic()
    findings = run_concheck()
    for f in findings:
        print(f)
    mods = len(_py_files("presto_tpu"))
    print(f"# concheck: {len(findings)} finding(s) across {mods} "
          f"files in {time.monotonic() - t0:.1f}s", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
