"""Engine-invariant linter: AST checks for the repo-specific rules no
generic linter knows.

Reference: presto-main's checkstyle + custom build-time validations
(e.g. the annotation processors that fail the build when a config
property lacks documentation). Each rule here machine-checks an
invariant that previous rounds enforced by hand-fixing after a test
tripped (see CHANGES.md: every PR includes session-prop/etc-key/
counter plumbing fixes):

  session-props   every session property in session.py has an etc key
                  registered in config.ETC_SESSION_KEYS, a typed
                  default, a non-empty doc description, a README doc
                  row, and a consumption site (session.get(...)).
  counters        every integer counter the executor family maintains
                  (initialized to 0 in __init__, incremented with +=
                  in exec/ or dist/) is declared in
                  exec/counters.QUERY_COUNTERS — the registry every
                  surfacing layer (EXPLAIN ANALYZE, /metrics,
                  system.metrics, analyze_rung) renders.
  excepts         no bare `except:`; a broad `except Exception` must
                  re-raise or carry an explained annotation
                  (`# noqa: BLE001 - <why>` or `# lint: broad-ok -
                  <why>`).
  locks           EVERY class in presto_tpu/ owning a threading lock
                  or Condition (created directly or via
                  obs.sanitizer.make_lock/make_condition) declares its
                  shared attributes (`_shared_attrs`) or carries an
                  explicit `# lint: single-threaded - <why>`
                  annotation; writes to declared attributes outside
                  __init__ happen under `with self.<lock>`, and an
                  under-lock write to an UNdeclared attribute fails
                  (the declaration is the reviewable contract). The
                  runtime half of the same contract is
                  obs/sanitizer.py; the acquisition-ORDER half is
                  tools/concheck.py.
  purity          no time/random/uuid/id() reachable from jit-cache
                  key expressions or from functions handed to
                  jax.jit/vmap/lax.scan/self._jit (a key or traced
                  program depending on wall clock or identity breaks
                  canonicalization and the persistent compile cache).
  spans           every trace-span kind emitted anywhere (a constant
                  first argument to a .begin(...)/.complete(...) span
                  recorder call) is declared in obs.SPAN_KINDS, and
                  every declared kind has an emission site — the
                  QUERY_COUNTERS discipline applied to the trace
                  vocabulary, so the QueryInfo tree, Chrome export,
                  and analyze_rung's phase split cannot drift.

Run: `python -m tools.lint` (exit 1 on findings); tier-1 runs the
same checks via tests/test_static_analysis.py, and tools/ci_static.sh
bundles them with the plan audit as the pre-PR gate.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Set, Tuple

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

# the instrumentation layer itself is exempt from the lock-discipline
# sweep (its wrapper class OWNS a raw lock by design; concheck exempts
# it from the raw-lock rule for the same reason)
_LOCK_EXEMPT_FILES = ("presto_tpu/obs/sanitizer.py",)

# the broad-except annotation: a trailing comment on the except line
# (or the line above) naming the suppression AND a reason after " - "
_BROAD_OK = re.compile(r"#\s*(noqa: BLE001|lint:\s*broad-ok)\s*-\s*\S")
_UNLOCKED_OK = re.compile(r"#\s*lint:\s*unlocked-ok\s*-\s*\S")
_SINGLE_THREADED_OK = re.compile(
    r"#\s*lint:\s*single-threaded\s*-\s*\S")

# callables that must not be reachable from jit keys / traced code
_IMPURE_CALLS = {
    "id": "object identity (varies per process/run)",
    "time.time": "wall clock",
    "time.monotonic": "wall clock",
    "time.perf_counter": "wall clock",
    "time.time_ns": "wall clock",
    "random.random": "RNG",
    "random.randint": "RNG",
    "random.Random": "RNG",
    "uuid.uuid4": "RNG identity",
    "uuid.uuid1": "host identity",
    "datetime.now": "wall clock",
    "np.random": "RNG",
    "numpy.random": "RNG",
}


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _py_files(*rel_roots: str) -> List[str]:
    out = []
    for root in rel_roots:
        abs_root = os.path.join(REPO, root)
        if os.path.isfile(abs_root):
            out.append(abs_root)
            continue
        for dirpath, dirnames, filenames in os.walk(abs_root):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and
                           not d.startswith(".")]
            out.extend(os.path.join(dirpath, f)
                       for f in filenames if f.endswith(".py"))
    return sorted(out)


def _rel(path: str) -> str:
    return os.path.relpath(path, REPO)


def _parse(path: str) -> Tuple[ast.AST, List[str]]:
    with open(path) as f:
        src = f.read()
    return ast.parse(src, filename=path), src.splitlines()


def _dotted(node: ast.AST) -> Optional[str]:
    """Dotted name of a call target: Name -> 'f', Attribute chains ->
    'a.b.c'; None for dynamic targets."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# ---------------------------------------------------------- rule: excepts
def check_excepts(paths: List[str]) -> List[Finding]:
    out: List[Finding] = []
    for path in paths:
        tree, lines = _parse(path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                out.append(Finding(
                    "excepts", _rel(path), node.lineno,
                    "bare `except:` — name the exception types (a "
                    "bare except swallows KeyboardInterrupt and "
                    "engine control-flow exceptions)"))
                continue
            names = []
            types = (node.type.elts
                     if isinstance(node.type, ast.Tuple)
                     else [node.type])
            for t in types:
                n = _dotted(t)
                if n:
                    names.append(n.rsplit(".", 1)[-1])
            if not ({"Exception", "BaseException"} & set(names)):
                continue
            # re-raise in the handler body is self-documenting
            if any(isinstance(x, ast.Raise) for b in node.body
                   for x in ast.walk(b)):
                continue
            ctx = "\n".join(lines[max(node.lineno - 2, 0):node.lineno])
            if _BROAD_OK.search(ctx):
                continue
            out.append(Finding(
                "excepts", _rel(path), node.lineno,
                "broad `except Exception` without re-raise or an "
                "explained annotation — narrow the types, re-raise, "
                "or annotate `# noqa: BLE001 - <why this is safe>`"))
    return out


# ------------------------------------------------------ rule: session-props
def check_session_props() -> List[Finding]:
    from presto_tpu import config as CFG
    from presto_tpu.session import SYSTEM_SESSION_PROPERTIES

    out: List[Finding] = []
    sess_path = os.path.join(REPO, "presto_tpu/session.py")
    mapped = set(CFG.ETC_SESSION_KEYS.values())
    for name, prop in sorted(SYSTEM_SESSION_PROPERTIES.items()):
        if not (prop.description or "").strip():
            out.append(Finding(
                "session-props", _rel(sess_path), 1,
                f"property {name!r} has an empty description (the "
                f"SHOW SESSION doc row)"))
        if prop.type not in (bool, int, str):
            out.append(Finding(
                "session-props", _rel(sess_path), 1,
                f"property {name!r} has unsupported type "
                f"{prop.type!r} (bool|int|str)"))
        elif not isinstance(prop.default, prop.type) and not (
            prop.type is int and isinstance(prop.default, int)
        ):
            out.append(Finding(
                "session-props", _rel(sess_path), 1,
                f"property {name!r} default {prop.default!r} is not "
                f"a {prop.type.__name__}"))
        if name not in mapped:
            out.append(Finding(
                "session-props", _rel(sess_path), 1,
                f"property {name!r} has no etc key in "
                f"config.ETC_SESSION_KEYS — deployments cannot pin "
                f"it fleet-wide (register e.g. "
                f"'{name.replace('_', '-')}')"))
    for etc_key, name in sorted(CFG.ETC_SESSION_KEYS.items()):
        if name not in SYSTEM_SESSION_PROPERTIES:
            out.append(Finding(
                "session-props", "presto_tpu/config.py", 1,
                f"etc key {etc_key!r} names unknown session "
                f"property {name!r}"))
    # consumption: every property must be read somewhere in the engine
    consumed: Set[str] = set()
    for path in _py_files("presto_tpu", "tools", "bench.py"):
        tree, _ = _parse(path)
        for node in ast.walk(tree):
            # READS only — a session.set() write is not consumption
            # (a write-only property is exactly the plumbing gap this
            # rule exists to flag)
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("get", "is_set") and \
                    node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                consumed.add(node.args[0].value)
    for name in sorted(set(SYSTEM_SESSION_PROPERTIES) - consumed):
        out.append(Finding(
            "session-props", _rel(sess_path), 1,
            f"property {name!r} is declared but never consumed "
            f"(no session.get/is_set site in the engine)"))
    # doc row: the etc key must appear in README's config table
    with open(os.path.join(REPO, "README.md")) as f:
        readme = f.read()
    for etc_key in sorted(CFG.ETC_SESSION_KEYS):
        if etc_key not in readme:
            out.append(Finding(
                "session-props", "README.md", 1,
                f"etc key {etc_key!r} is undocumented — add it to "
                f"README's deployment-config table"))
    # PR-13 mixed-pool caveat pin (ISSUE 18 satellite): the Pallas
    # exchange partition-id hash is NOT compatible with the splitmix64
    # tier, so pallas-join.enabled's doc row must carry the warning
    # that a per-process backend auto-probe would mis-route
    # co-partitioned keys on a mixed pool — a silently-dropped caveat
    # here re-opens a wrong-results hole, hence a build gate
    pj_row = next(
        (ln for ln in readme.splitlines()
         if ln.strip().startswith("| `pallas-join.enabled`")), "")
    if "mixed pool" not in pj_row or "mis-route" not in pj_row:
        out.append(Finding(
            "session-props", "README.md", 1,
            "the `pallas-join.enabled` config-table row must state "
            "the mixed-pool hashing caveat (Pallas partition ids "
            "are not splitmix64-compatible; auto-probing the "
            "backend per process would mis-route co-partitioned "
            "keys)"))
    return out


# --------------------------------------------------------- rule: counters
# executor attributes that look like counters but are deliberately not
# in the per-query registry, with the reason
_COUNTER_EXEMPT = {
    "host_spill_bytes_used": "byte volume, reported via "
                             "host_spill_pages + page sizes",
    "_capacity_boost": "retry-ladder state, not a counter",
    "_oom_divisor": "retry-ladder state, not a counter",
    "_live_bytes": "accounting intermediate",
    "peak_memory_bytes": "high-water gauge surfaced as "
                         "peak_device_bytes (computed entry)",
    "compile_wall_s": "float wall surfaced as a computed entry",
    "transfer_wall_s": "float wall surfaced as a computed entry "
                       "(exec/xfer.py crossing wall)",
}


# the classes whose integer state IS the per-query counter surface
_COUNTER_CLASSES = ("Executor", "DistExecutor", "DcnRunner")


def check_counters() -> List[Finding]:
    from presto_tpu.exec.counters import QUERY_COUNTERS

    out: List[Finding] = []
    # counters = attrs initialized to integer 0 in the __init__ of an
    # executor-family class AND incremented with += anywhere in exec/
    # or dist/ (a PageStore's internal byte tally is not a query
    # counter; the executor's classes define the observable surface)
    zero_init: Dict[str, Tuple[str, int]] = {}
    incremented: Dict[str, Tuple[str, int]] = {}
    written: Set[str] = set()  # non-__init__ writes (registry health)
    for path in _py_files("presto_tpu/exec", "presto_tpu/dist"):
        tree, _ = _parse(path)
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            in_counter_cls = cls.name in _COUNTER_CLASSES
            for meth in (n for n in cls.body
                         if isinstance(n, ast.FunctionDef)):
                for node in ast.walk(meth):
                    if isinstance(node, ast.Assign) and \
                            meth.name == "__init__" and \
                            in_counter_cls and \
                            len(node.targets) == 1 and \
                            isinstance(node.targets[0],
                                       ast.Attribute) and \
                            isinstance(node.targets[0].value,
                                       ast.Name) and \
                            node.targets[0].value.id == "self" and \
                            isinstance(node.value, ast.Constant) and \
                            node.value.value == 0 and \
                            not isinstance(node.value.value, bool):
                        zero_init.setdefault(
                            node.targets[0].attr,
                            (_rel(path), node.lineno))
                    if meth.name != "__init__" and isinstance(
                            node, (ast.Assign, ast.AugAssign)):
                        tgts = (node.targets if isinstance(
                            node, ast.Assign) else [node.target])
                        for t in tgts:
                            if isinstance(t, ast.Attribute):
                                written.add(t.attr)
                    if isinstance(node, ast.AugAssign) and \
                            isinstance(node.op, ast.Add) and \
                            isinstance(node.target, ast.Attribute):
                        incremented.setdefault(
                            node.target.attr,
                            (_rel(path), node.lineno))
    counters = set(zero_init) & set(incremented)
    for name in sorted(counters):
        if name in QUERY_COUNTERS or name in _COUNTER_EXEMPT:
            continue
        path, line = incremented[name]
        out.append(Finding(
            "counters", path, line,
            f"counter {name!r} (zero-initialized and incremented) is "
            f"not declared in exec/counters.QUERY_COUNTERS — it will "
            f"not reach EXPLAIN ANALYZE, /metrics, system.metrics, "
            f"or analyze_rung"))
    for name in sorted(QUERY_COUNTERS):
        if name not in zero_init or name not in written:
            out.append(Finding(
                "counters", "presto_tpu/exec/counters.py", 1,
                f"registry declares {name!r} but no executor-family "
                f"zero-init + write site exists in exec/ or dist/ "
                f"(stale entry?)"))
    return out


# ------------------------------------------------------------ rule: locks
# a lock-owning class is detected by VALUE, not attribute name: any
# assignment whose RHS constructs a threading primitive or goes
# through the sanitizer factory counts, so `_fault_lock`, `_cv`, and
# class-level `_instances_lock` all bind their owner to the contract
_LOCKISH_TAILS = ("Lock", "RLock", "Condition",
                  "make_lock", "make_condition")


def _lockish(value: ast.AST) -> bool:
    return isinstance(value, ast.Call) and \
        (_dotted(value.func) or "").rsplit(".", 1)[-1] in _LOCKISH_TAILS


def _lock_classes(tree: ast.AST) -> List[Tuple[ast.ClassDef, Set[str]]]:
    """(class, lock-attribute names) for every lock-owning class."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        attrs: Set[str] = set()
        for stmt in node.body:  # class-level locks (Name targets)
            if isinstance(stmt, ast.Assign) and _lockish(stmt.value):
                attrs.update(t.id for t in stmt.targets
                             if isinstance(t, ast.Name))
        for sub in ast.walk(node):  # instance locks (self.X targets)
            if isinstance(sub, ast.Assign) and _lockish(sub.value):
                attrs.update(t.attr for t in sub.targets
                             if isinstance(t, ast.Attribute) and
                             isinstance(t.value, ast.Name) and
                             t.value.id == "self")
        if attrs:
            out.append((node, attrs))
    return out


def _declared_shared(cls: ast.ClassDef) -> Optional[Set[str]]:
    for stmt in cls.body:
        if isinstance(stmt, ast.Assign) and \
                any(isinstance(t, ast.Name) and
                    t.id == "_shared_attrs" for t in stmt.targets):
            try:
                return set(ast.literal_eval(stmt.value))
            except ValueError:
                return set()
    return None


class _LockWalk(ast.NodeVisitor):
    """Per-method walk tracking lexical `with self.<lock>:` nesting
    for the owning class's detected lock attributes (a Condition
    fronting the lock counts: holding it IS holding the lock)."""

    def __init__(self, lock_attrs: Optional[Set[str]] = None):
        self.lock_attrs = lock_attrs or {"_lock", "lock"}
        self.depth = 0
        # attr -> [(line, under_lock)]
        self.writes: List[Tuple[str, int, bool]] = []

    def visit_With(self, node: ast.With):
        # only SELF's lock protects self's shared attributes — a
        # `with q.lock:` on some other object must not count
        locked = any(
            isinstance(item.context_expr, ast.Attribute) and
            item.context_expr.attr in self.lock_attrs and
            isinstance(item.context_expr.value, ast.Name) and
            item.context_expr.value.id in ("self", "cls")
            for item in node.items
        )
        if locked:
            self.depth += 1
        self.generic_visit(node)
        if locked:
            self.depth -= 1

    def _record(self, target, line):
        # self.attr = / self.attr += / self.attr[k] =
        if isinstance(target, ast.Subscript):
            target = target.value
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            self.writes.append((target.attr, line, self.depth > 0))

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            self._record(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._record(node.target, node.lineno)
        self.generic_visit(node)


def check_locks(paths=None) -> List[Finding]:
    out: List[Finding] = []
    if paths is None:
        paths = [_rel(p) for p in _py_files("presto_tpu")
                 if _rel(p) not in _LOCK_EXEMPT_FILES]
    for rel in paths:
        path = rel if os.path.isabs(rel) else os.path.join(REPO, rel)
        rel = _rel(path)
        tree, lines = _parse(path)
        for cls, lock_attrs in _lock_classes(tree):
            declared = _declared_shared(cls)
            observed: Dict[str, int] = {}
            unlocked: List[Tuple[str, int]] = []
            for meth in (n for n in cls.body
                         if isinstance(n, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))):
                walker = _LockWalk(lock_attrs | {"_lock", "lock"})
                # `*_locked` helper convention: the suffix documents
                # "caller holds the lock" — the walker starts held.
                # The convention's HONESTY is enforced at runtime by
                # obs/sanitizer.py (a caller that doesn't hold the
                # lock trips the unlocked-shared-write check live)
                if meth.name.endswith("_locked"):
                    walker.depth = 1
                walker.visit(meth)
                init = meth.name == "__init__"
                for attr, line, under in walker.writes:
                    if attr in lock_attrs or attr.endswith("lock"):
                        continue
                    if under:
                        observed.setdefault(attr, line)
                    elif not init:
                        unlocked.append((attr, line))
            if declared is None:
                ctx = "\n".join(
                    lines[max(cls.lineno - 2, 0):cls.lineno])
                if not _SINGLE_THREADED_OK.search(ctx):
                    out.append(Finding(
                        "locks", rel, cls.lineno,
                        f"class {cls.name} owns a lock "
                        f"({sorted(lock_attrs)}) but declares no "
                        f"`_shared_attrs` — declare the shared set "
                        f"(observed under-lock writes: "
                        f"{sorted(observed)}) so the race contract "
                        f"is reviewable, or annotate the class "
                        f"`# lint: single-threaded - <why>`"))
                declared = set(observed)
            declared = declared or set()
            for attr in sorted(set(observed) - declared):
                out.append(Finding(
                    "locks", rel, observed[attr],
                    f"class {cls.name}: attribute {attr!r} is "
                    f"written under the lock but missing from "
                    f"_shared_attrs"))
            for attr, line in unlocked:
                if attr not in declared:
                    continue
                ctx = "\n".join(lines[max(line - 2, 0):line])
                if _UNLOCKED_OK.search(ctx):
                    continue
                out.append(Finding(
                    "locks", rel, line,
                    f"class {cls.name}: shared attribute {attr!r} "
                    f"written OUTSIDE `with self._lock` — a write "
                    f"race with the background thread (annotate "
                    f"`# lint: unlocked-ok - <why>` if provably "
                    f"single-threaded)"))
    return out


# ----------------------------------------------------------- rule: purity
def _impure_name(call: ast.Call) -> Optional[str]:
    name = _dotted(call.func)
    if name is None:
        return None
    if name in _IMPURE_CALLS:
        return name
    # match module-qualified tails: _time.monotonic, np.random.normal
    for bad in _IMPURE_CALLS:
        if "." in bad and (name.endswith("." + bad)
                           or name.startswith(bad + ".")
                           or ("." in name and
                               name.split(".", 1)[1] == bad)):
            return bad
    return None


def _scan_key_expr(expr, path, out: List[Finding]) -> None:
    """Flag impure calls / dict literals inside a jit-key expression."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call):
            bad = _impure_name(sub)
            if bad:
                out.append(Finding(
                    "purity", _rel(path), sub.lineno,
                    f"jit-cache key computed from {bad}() "
                    f"[{_IMPURE_CALLS[bad]}] — keys must be "
                    f"canonical and re-key byte-identical"))
        if isinstance(sub, ast.Dict):
            out.append(Finding(
                "purity", _rel(path), sub.lineno,
                "jit-cache key contains a dict literal "
                "(iteration-order-dependent)"))


def check_purity(paths=None) -> List[Finding]:
    out: List[Finding] = []
    for path in (paths or _py_files("presto_tpu/exec",
                                    "presto_tpu/ops",
                                    "presto_tpu/dist")):
        tree, _ = _parse(path)
        # module-local function defs by name (incl. nested). Same-name
        # nested defs (the dist executor's many `body` closures) ALL
        # collect — traced-reachability checks every candidate, an
        # over-approximation in the safe direction.
        defs: Dict[str, List[ast.FunctionDef]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef):
                defs.setdefault(node.name, []).append(node)
        # simple `name = <expr>` assignments resolved WITHIN the
        # enclosing function only, so a key built as `key = (...)`
        # then `self._jit_cache[key] = ...` (the dist executor's
        # direct-cache pattern) checks, while an unrelated `key =
        # id(node)` in a DIFFERENT method (e.g. a non-jit memo) does
        # not bleed into the candidates
        enclosing: Dict[int, ast.FunctionDef] = {}

        def _map_parents(fn_stack, node):
            if isinstance(node, ast.FunctionDef):
                fn_stack = fn_stack + [node]
            enclosing[id(node)] = fn_stack[-1] if fn_stack else None
            for child in ast.iter_child_nodes(node):
                _map_parents(fn_stack, child)

        _map_parents([], tree)

        def local_exprs(store_node, name: str) -> List[ast.AST]:
            fn = enclosing.get(id(store_node))
            if fn is None:
                return []
            return [n.value for n in ast.walk(fn)
                    if isinstance(n, ast.Assign) and
                    len(n.targets) == 1 and
                    isinstance(n.targets[0], ast.Name) and
                    n.targets[0].id == name]

        def impure_in(fn: ast.FunctionDef, seen: Set[str]):
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    bad = _impure_name(sub)
                    if bad:
                        return bad, sub.lineno
                    callee = _dotted(sub.func)
                    if callee in defs and callee not in seen:
                        seen.add(callee)
                        for cand in defs[callee]:
                            hit = impure_in(cand, seen)
                            if hit:
                                return hit
            return None

        def check_traced(fname: str):
            for cand in defs.get(fname, ()):
                hit = impure_in(cand, {fname})
                if hit:
                    bad, line = hit
                    out.append(Finding(
                        "purity", _rel(path), line,
                        f"{bad}() [{_IMPURE_CALLS[bad]}] reachable "
                        f"from traced function {fname!r} — traced "
                        f"programs must be replay-deterministic"))

        for node in ast.walk(tree):
            # direct-cache stores: self._jit_cache[key] = jit(...)
            if isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Subscript) and \
                    isinstance(node.targets[0].value,
                               ast.Attribute) and \
                    node.targets[0].value.attr == "_jit_cache":
                sl = node.targets[0].slice
                exprs = ([sl] if not isinstance(sl, ast.Name)
                         else local_exprs(node, sl.id))
                for e in exprs:
                    _scan_key_expr(e, path, out)
                continue
            if not isinstance(node, ast.Call):
                continue
            target = _dotted(node.func) or ""
            # (a) jit-key expressions: first arg of self._jit(key, fn)
            if target.endswith("_jit") and node.args:
                _scan_key_expr(node.args[0], path, out)
            # (b) traced entry points: fn args of jit/vmap/scan/
            #     shard_map/pallas_call/_jit
            tail = target.rsplit(".", 1)[-1]
            if tail in ("jit", "vmap", "scan", "shard_map",
                        "pallas_call") or target.endswith("_jit"):
                cand = node.args[1:] if target.endswith("_jit") \
                    else node.args[:1]
                for arg in cand:
                    fname = None
                    if isinstance(arg, ast.Name):
                        fname = arg.id
                    elif isinstance(arg, ast.Call) and \
                            (_dotted(arg.func) or "").endswith(
                                "partial") and arg.args and \
                            isinstance(arg.args[0], ast.Name):
                        fname = arg.args[0].id
                    if fname:
                        check_traced(fname)
    return out


# ------------------------------------------------------------ rule: spans
# the span-recorder emission methods (obs/trace.QueryTrace; _new is
# the internal constructor the root "query" span uses). A call
# `<anything>.begin("kind", ...)` / `.complete("kind", ...)` with a
# constant first argument IS an emission site; dynamic kinds (the
# ingest path re-materializing remote spans) are invisible here by
# design — every dynamic kind originates at some constant site.
_SPAN_EMIT_METHODS = ("begin", "complete", "_new")


def check_spans(paths=None) -> List[Finding]:
    from presto_tpu.obs import SPAN_KINDS

    out: List[Finding] = []
    emitted: Dict[str, Tuple[str, int]] = {}
    for path in (paths or _py_files("presto_tpu", "tools",
                                    "bench.py")):
        tree, _ = _parse(path)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _SPAN_EMIT_METHODS and \
                    node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                emitted.setdefault(node.args[0].value,
                                   (_rel(path), node.lineno))
    for kind, (path, line) in sorted(emitted.items()):
        if kind not in SPAN_KINDS:
            out.append(Finding(
                "spans", path, line,
                f"span kind {kind!r} is emitted but not declared in "
                f"obs.SPAN_KINDS — trace surfaces (QueryInfo tree, "
                f"Chrome export, analyze_rung) would carry an "
                f"undocumented vocabulary; declare it with help text"))
    for kind in sorted(set(SPAN_KINDS) - set(emitted)):
        out.append(Finding(
            "spans", "presto_tpu/obs/__init__.py", 1,
            f"SPAN_KINDS declares {kind!r} but no "
            f".begin()/.complete() emission site exists in the "
            f"engine (stale entry?)"))
    return out


# ----------------------------------------------------------------- driver
ALL_RULES = ("excepts", "session-props", "counters", "locks",
             "purity", "spans")


def run_lint(rules=ALL_RULES) -> List[Finding]:
    findings: List[Finding] = []
    if "excepts" in rules:
        findings += check_excepts(
            _py_files("presto_tpu", "tools", "bench.py"))
    if "session-props" in rules:
        findings += check_session_props()
    if "counters" in rules:
        findings += check_counters()
    if "locks" in rules:
        findings += check_locks()
    if "purity" in rules:
        findings += check_purity()
    if "spans" in rules:
        findings += check_spans()
    return findings
