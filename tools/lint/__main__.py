"""CLI: `python -m tools.lint [rule ...]` — run the engine-invariant
lint rules (default: all) and exit 1 on findings. The pre-PR gate
(tools/ci_static.sh) and tier-1 (tests/test_static_analysis.py) run
the same code."""

import sys

from tools.lint import ALL_RULES, run_lint


def main(argv) -> int:
    rules = tuple(argv) or ALL_RULES
    unknown = set(rules) - set(ALL_RULES)
    if unknown:
        print(f"unknown rules: {sorted(unknown)} "
              f"(known: {list(ALL_RULES)})", file=sys.stderr)
        return 2
    findings = run_lint(rules)
    for f in findings:
        print(f)
    print(f"# tools.lint: {len(findings)} finding"
          f"{'s' if len(findings) != 1 else ''} across "
          f"{len(rules)} rule{'s' if len(rules) != 1 else ''}",
          file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
