#!/usr/bin/env bash
# Pre-PR static gate (ISSUE 6 + ISSUE 11 + ISSUE 12 + ISSUE 16): the
# engine-invariant linter, the concurrency soundness pass (lock
# registry + acquisition graph + blocking-under-lock), the
# host<->device transfer audit (transfer registry + plane
# classification + choke-point routing), the full plan audit
# (bench rungs + TPC-H/TPC-DS corpus, strict mode), and the wire-serde
# property suite (codec x type round-trip matrix, byte-stability,
# truncation/corruption rejection — the pure-serde subset; the
# WorkerServer-backed streaming/pool tests stay in tier 1), plus the
# sanitized serving smoke (ISSUE 17: a bounded loadbench pass racing
# the concurrent-admission/batching locks under the runtime
# sanitizer), and the interpret-mode Pallas smoke (ISSUE 18: radix
# join + segmented reduction vs host oracles, no device needed).
# All legs but the smokes are pure host Python — nothing
# compiles or touches a device — so the whole gate runs in well under
# 90 s on the 2-core box (combined budget: <= 30 s for the static
# rules, the rest for the plan audit + serde suite + smoke).
# bench.py --prewarm runs the same plan verifier per rung before
# compiling.
#
# Usage: tools/ci_static.sh   (exit nonzero on any finding/violation)
set -euo pipefail
cd "$(dirname "$0")/.."

t0=$(date +%s)
echo "# ci_static: engine-invariant lint (python -m tools.lint)" >&2
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m tools.lint

echo "# ci_static: concurrency soundness (tools/concheck.py)" >&2
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python tools/concheck.py

echo "# ci_static: transfer audit (tools/xfercheck.py)" >&2
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python tools/xfercheck.py

echo "# ci_static: plan audit (tools/plan_audit.py)" >&2
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python tools/plan_audit.py

echo "# ci_static: wire-serde property suite (tests/test_wire_serde.py)" >&2
# pure-serde subset: everything that does not spin a WorkerServer
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
    tests/test_wire_serde.py -q -p no:cacheprovider \
    -k "not spooled_task and not connpool and not streaming \
        and not q3_family and not executor_surface"

echo "# ci_static: interpret-mode Pallas smoke (tools/pallas_smoke.py)" >&2
# ISSUE 18: radix hash-join probe + segmented reduction on a seeded
# page, oracle-checked in pure CPU interpret mode — no device, < 5 s
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python tools/pallas_smoke.py

echo "# ci_static: sanitized serving smoke (tools/loadbench.py)" >&2
# ISSUE 17: a bounded concurrent-load pass with the lock sanitizer
# armed — N protocol clients x the shared result cache x cache-aware
# admission x the cross-query launch batcher race deliberately; any
# lock-order inversion or unlocked shared-attr write fails the gate
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m tools.loadbench \
    --sanitize --smoke > /dev/null

echo "# ci_static: clean in $(( $(date +%s) - t0 ))s" >&2
