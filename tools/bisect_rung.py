"""Run ONE bench rung in a fresh process with immediate decode.

Usage: python tools/bisect_rung.py {tpch|tpcds} QID SF [k=v ...]

Isolates axon >=4M-row kernel-fault / slow-D2H diagnosis (see
.claude/skills/verify/SKILL.md): a rung whose decode hangs or raises
UNAVAILABLE here has a faulting buffer somewhere in its pipeline;
bench.py's orchestrator runs every phase in bounded children, so use
this to bisect exactly which rung (or which session-property
configuration, e.g. spill_threshold_bytes=33554432) misbehaves.
"""

import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from tools._common import configure_jax, make_runner, queries  # noqa: E402


def main() -> int:
    suite, qid, sf = sys.argv[1], int(sys.argv[2]), float(sys.argv[3])
    jax = configure_jax()
    runner = make_runner(suite, sf, props=sys.argv[4:])
    plan = runner.plan(queries(suite)[qid])
    ex = runner.executor
    pages = []
    from presto_tpu.devsync import drain

    for label in ("compile", "steady", "steady2"):
        t0 = time.time()
        ex._pending_overflow = []
        pages = list(ex.pages(plan))
        # drain protocol (SKILL: block_until_ready returns at dispatch
        # on axon) — honest wall = dispatch + FIFO-draining read
        drain(pages)
        ex._stream_cache = {}
        print(f"{label} {time.time() - t0:.3f}s", flush=True)
    flags = list(ex._pending_overflow)
    t0 = time.time()
    rows = []
    for p in pages:
        rows.extend(p.to_pylist())
    decode_s = time.time() - t0
    overflow = any(bool(f) for f in flags)
    print(f"decode {decode_s:.1f}s rows={len(rows)} "
          f"overflow={overflow}", flush=True)
    print("sample:", rows[0] if rows else None, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
