"""Static per-rung HBM-footprint audit: predict every device buffer a
rung's plan will allocate (exec/membudget.py — the SAME sizing
functions the executor calls, so prediction and execution cannot
drift), check the prediction against the device-memory budget and the
axon >=4M-row fault line, and optionally execute the rung to compare
the model against the measured peak.

Exit status (wired into bench.py --prewarm so regressions surface
before timing):
  0  every planned buffer fits its budget and the fault line, and —
     with --execute — the model's largest buffer is within 2x of the
     measured peak_device_bytes
  1  a pipeline plans over budget / over the fault line, or the model
     missed the measured peak by more than 2x

Usage: hbm_audit.py {tpch|tpcds} QID SF [k=v session props...]
                    [--execute] [--budget BYTES] [--fault-rows N]

--budget / --fault-rows force the governor's inputs (e.g. audit an
SF10 plan under TPU assumptions from a CPU box: --fault-rows 2097152).
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from tools._common import configure_jax, make_runner, queries  # noqa: E402


def main() -> int:
    argv = list(sys.argv[1:])
    budget = fault = None
    execute = "--execute" in argv
    if execute:
        argv.remove("--execute")
    if "--budget" in argv:
        i = argv.index("--budget")
        budget = int(argv[i + 1])
        del argv[i:i + 2]
    if "--fault-rows" in argv:
        i = argv.index("--fault-rows")
        fault = int(argv[i + 1])
        del argv[i:i + 2]
    suite, qid, sf = argv[0], int(argv[1]), float(argv[2])
    props = argv[3:]
    configure_jax()
    from presto_tpu.exec import membudget as MB

    runner = make_runner(suite, sf, props=props)
    ex = runner.executor
    if budget is not None:
        ex.device_memory_budget = budget
    if fault is not None:
        ex.fault_rows = fault
    plan = runner.plan(queries(suite)[qid])
    report = MB.audit(ex, plan)
    print(MB.render(report))
    rc = 0
    for b in report.over_fault_line():
        print(f"OVER FAULT LINE: {b.label} plans {b.rows} rows "
              f">= {report.fault_rows}")
        rc = 1
    for b in report.over_budget():
        print(f"OVER BUDGET: {b.label} plans {b.bytes} bytes "
              f"> {report.budget}")
        rc = 1
    if execute:
        from presto_tpu.devsync import drain

        ex._pending_overflow = []
        ex.peak_memory_bytes = 0
        ex.memory_chunked_pipelines = 0
        pages = list(ex.pages(plan))
        drain(pages)
        ex._release_stream_cache()
        measured = ex.peak_memory_bytes
        model = report.max_buffer_bytes
        print(f"measured peak_device_bytes={measured} "
              f"model max buffer={model} "
              f"memory_chunked_pipelines={ex.memory_chunked_pipelines}")
        # the model sizes ALLOCATIONS; the measured peak is the largest
        # page the accounting saw. >2x apart in either direction means
        # the model no longer describes the executor — fail loudly.
        if measured and model and (
            model > 2 * measured or measured > 2 * model
        ):
            print(f"MODEL MISS: model {model} vs measured {measured} "
                  f"(>2x apart)")
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
