"""Static plan audit: sweep every bench-rung plan and the TPC-H/
TPC-DS test corpus through the pre-compile plan verifier
(exec/plan_check.py, strict mode) and exit nonzero on any violation.

Reference: presto-verifier's suite replay, applied to PLANS instead of
results — the point is catching invariant drift (schema-inconsistent
edges, off-ladder capacities, non-canonical jit keys, missing split
determinism) across the WHOLE query corpus before a PR lands, not
after a bench rung hangs on real hardware. Planning is pure host
Python; nothing traces, compiles, or touches a device, so the sweep
is cheap enough for the pre-PR gate (tools/ci_static.sh) and for
`bench.py --prewarm`, which runs the same verifier per rung.

Usage:
    python tools/plan_audit.py                 # rungs + both corpora
    python tools/plan_audit.py --rungs         # bench rungs only
    python tools/plan_audit.py --corpus tpch   # one corpus only
    python tools/plan_audit.py --sf 0.001      # corpus scale factor
"""

import argparse
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from tools._common import make_runner, queries  # noqa: E402


def _audit_one(runner, label: str, sql: str, failures: list) -> None:
    from presto_tpu.exec import plan_check as PC

    try:
        plan = runner.plan(sql)
    except Exception as e:  # noqa: BLE001 - a plan failure is a verdict
        failures.append((label, [f"planning failed: {e!r}"]))
        print(f"# {label}: PLANNING FAILED {e!r}", file=sys.stderr)
        return
    try:
        PC.verify(runner.executor, plan, strict=True)
    except PC.PlanCheckError as e:
        failures.append((label, e.violations))
        print(f"# {label}: {len(e.violations)} violation(s)",
              file=sys.stderr)
        for v in e.violations:
            print(f"#   - {v}", file=sys.stderr)
    else:
        print(f"# {label}: ok", file=sys.stderr)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rungs", action="store_true",
                    help="bench rungs only")
    ap.add_argument("--corpus", choices=("tpch", "tpcds", "all"),
                    default=None, help="corpus only (default both "
                    "plus rungs)")
    ap.add_argument("--sf", type=float, default=0.001,
                    help="corpus scale factor (planning-only)")
    args = ap.parse_args()
    do_rungs = args.rungs or args.corpus is None
    corpora = ([] if args.rungs else
               ["tpch", "tpcds"] if args.corpus in (None, "all")
               else [args.corpus])

    t0 = time.time()
    failures: list = []
    n = 0
    if do_rungs:
        from bench import RUNGS

        for name, suite, qid, sf, props in RUNGS:
            # plan at the rung's REAL scale + session props (generator
            # connectors are lazy — row counts, not rows); the bench
            # prewarm path verifies the same plans before compiling
            runner = make_runner(suite, sf, props)
            _audit_one(runner, f"rung {name}",
                       queries(suite)[qid], failures)
            n += 1
    for suite in corpora:
        runner = make_runner(suite, args.sf)
        for qid, sql in sorted(queries(suite).items()):
            _audit_one(runner, f"{suite} q{qid}", sql, failures)
            n += 1
    wall = time.time() - t0
    print(f"# plan_audit: {n} plans, {len(failures)} with violations, "
          f"{wall:.1f}s", file=sys.stderr)
    if failures:
        print("PLAN AUDIT FAILED:")
        for label, violations in failures:
            for v in violations:
                print(f"  {label}: {v}")
        return 1
    print(f"plan audit clean: {n} plans verified in {wall:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
