"""Static plan audit: sweep every bench-rung plan and the TPC-H/
TPC-DS test corpus through the pre-compile plan verifier
(exec/plan_check.py, strict mode) and exit nonzero on any violation.

Reference: presto-verifier's suite replay, applied to PLANS instead of
results — the point is catching invariant drift (schema-inconsistent
edges, off-ladder capacities, non-canonical jit keys, missing split
determinism) across the WHOLE query corpus before a PR lands, not
after a bench rung hangs on real hardware. Planning is pure host
Python; nothing traces, compiles, or touches a device, so the sweep
is cheap enough for the pre-PR gate (tools/ci_static.sh) and for
`bench.py --prewarm`, which runs the same verifier per rung.

Usage:
    python tools/plan_audit.py                 # rungs + both corpora
    python tools/plan_audit.py --rungs         # bench rungs only
    python tools/plan_audit.py --corpus tpch   # one corpus only
    python tools/plan_audit.py --sf 0.001      # corpus scale factor
"""

import argparse
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from tools._common import make_runner, queries  # noqa: E402


def _seeded_misestimate_sweep(runner, label: str, dag,
                              failures: list) -> int:
    """ISSUE 15: drive the runtime re-planner over this DAG with
    SYNTHETIC >=10x-off observations (alternating over- and under-
    estimates, plus an 80/20 skewed partition histogram) at every
    stage boundary, and require the LIVE DAG to pass STRICT
    verification after each replan — whether the mutation applied or
    rolled back. This is the adaptive analog of the broken-plan
    mutation suite: the re-planner must never leave the DAG in a
    state the verifier cannot prove. Returns the number of applied
    re-plans (0 = every boundary was a no-op or clean rollback)."""
    from presto_tpu.adaptive import Replanner, StageStats
    from presto_tpu.exec import plan_check as PC

    ex = runner.executor
    rp = Replanner(ex, dag, broadcast_rows=1 << 21,
                   max_replans=16, strict=True)
    dispatched: set = set()
    applied = 0
    for frag in dag.fragments:
        dispatched.add(frag.fid)
        est = max(int(ex.estimate_rows(frag.root)), 2)
        obs = est * 10 if frag.fid % 2 else max(est // 10, 1)
        hot = max(int(obs * 0.8), 1)
        rp.observe(StageStats(
            fid=frag.fid, rows=obs, bytes=obs * 16,
            part_rows=(hot, max(obs - hot, 0)),
            part_bytes=(hot * 16, max(obs - hot, 0) * 16),
            task_rows=(obs // 2, obs - obs // 2),
            # ISSUE 17: measured wire bytes 8x under raw (a typical
            # per-column codec ratio) so the sweep drives the
            # freight-costed broadcast test through replan+verify
            wire_bytes=obs * 2,
        ))
        out = rp.replan(set(dispatched))
        if out is not None and not out.rejected:
            applied += 1
        try:
            PC.verify_dag(ex, dag, strict=True)
        except PC.PlanCheckError as e:
            failures.append((label, [
                f"[adaptive seeded-misestimate, after stage "
                f"{frag.fid}] {v}" for v in e.violations]))
            print(f"# {label}: ADAPTIVE SWEEP FAILED after stage "
                  f"{frag.fid}", file=sys.stderr)
            return applied
    return applied


def _wire_misestimate_case(failures: list) -> None:
    """ISSUE 17: one seeded wire-misestimate pin. A build whose RAW
    spool bytes blow the broadcast byte share but whose MEASURED
    post-codec wire bytes fit (scan-ordered keys delta+deflate to
    almost nothing, ROOFLINE §14) must pass the re-planner's
    broadcast test — and the pre-wire-stats behavior (raw-byte
    costing) must be reproduced exactly by wire_bytes=0, so legacy
    producers never get mis-flipped."""
    from presto_tpu.adaptive import Replanner, StageStats

    rp = Replanner(None, None, broadcast_bytes=1 << 20)
    kw = dict(fid=0, rows=1 << 16, part_rows=(1 << 16,),
              part_bytes=(1 << 24,), task_rows=(1 << 16,))
    raw_only = StageStats(bytes=1 << 24, **kw)
    measured = StageStats(bytes=1 << 24, wire_bytes=1 << 18, **kw)
    still_fat = StageStats(bytes=1 << 24, wire_bytes=1 << 22, **kw)
    checks = [
        (not rp._fits_broadcast(raw_only),
         "raw 16MiB build with no wire stats must NOT fit a 1MiB "
         "broadcast share"),
        (rp._fits_broadcast(measured),
         "16MiB build measuring 256KiB on the wire must fit a 1MiB "
         "broadcast share"),
        (not rp._fits_broadcast(still_fat),
         "build measuring 4MiB on the wire must NOT fit a 1MiB "
         "broadcast share"),
    ]
    bad = [msg for ok, msg in checks if not ok]
    if bad:
        failures.append(("wire-misestimate case", bad))
        for msg in bad:
            print(f"# wire-misestimate case: {msg}", file=sys.stderr)
    else:
        print("# wire-misestimate case: ok", file=sys.stderr)


def _ici_flip_case(failures: list) -> None:
    """ISSUE 18: one seeded ICI-vs-spool flip pin. Identical freight,
    two observed planes: a spooled build whose wire bytes fit the
    broadcast byte share flips to broadcast, but the SAME build
    observed on the ICI plane (ici_bytes > 0 — its repartition edge
    already lowered to the in-program all_to_all) must NOT flip:
    broadcast reads are spool reads, so the flip would move freight
    the current plan ships over the interconnect back onto the
    serde+HTTP wire. The re-planner charges that an ICI_WIRE_RATIO
    budget handicap (adaptive/replanner.py)."""
    from presto_tpu.adaptive import Replanner, StageStats

    rp = Replanner(None, None, broadcast_bytes=1 << 20)
    kw = dict(fid=0, rows=1 << 14, part_rows=(1 << 14,),
              part_bytes=(1 << 19,), task_rows=(1 << 14,))
    spooled = StageStats(bytes=1 << 19, wire_bytes=1 << 19, **kw)
    on_ici = StageStats(bytes=1 << 19, ici_bytes=1 << 19, **kw)
    tiny_on_ici = StageStats(bytes=1 << 13, ici_bytes=1 << 13, **kw)
    checks = [
        (rp._fits_broadcast(spooled),
         "512KiB spooled build must fit a 1MiB broadcast share "
         "(the spool-plane flip this case contrasts against)"),
        (not rp._fits_broadcast(on_ici),
         "the SAME 512KiB build observed on the ICI plane must NOT "
         "flip — broadcast would move its freight back onto the "
         "wire"),
        (rp._fits_broadcast(tiny_on_ici),
         "an 8KiB ICI-plane build must still flip (fits even the "
         "ICI_WIRE_RATIO-shrunk share — truly tiny builds beat any "
         "exchange)"),
    ]
    bad = [msg for ok, msg in checks if not ok]
    if bad:
        failures.append(("ici-flip case", bad))
        for msg in bad:
            print(f"# ici-flip case: {msg}", file=sys.stderr)
    else:
        print("# ici-flip case: ok", file=sys.stderr)


def _audit_one(runner, label: str, sql: str, failures: list,
               dag_stats: list, replans: list) -> None:
    from presto_tpu.dist.fragmenter import fragment_dag
    from presto_tpu.exec import plan_check as PC

    try:
        plan = runner.plan(sql)
    except Exception as e:  # noqa: BLE001 - a plan failure is a verdict
        failures.append((label, [f"planning failed: {e!r}"]))
        print(f"# {label}: PLANNING FAILED {e!r}", file=sys.stderr)
        return
    try:
        PC.verify(runner.executor, plan, strict=True)
    except PC.PlanCheckError as e:
        failures.append((label, e.violations))
        print(f"# {label}: {len(e.violations)} violation(s)",
              file=sys.stderr)
        for v in e.violations:
            print(f"#   - {v}", file=sys.stderr)
        return
    # ISSUE 7: fragment the SAME plan through the general stage-DAG
    # cutter and verify the resulting multi-stage DAG (RemoteSource
    # types vs origin-fragment output across every exchange hop,
    # repartition-key sanity, co-partitioned join agreement). Pure
    # host planning — no trace/compile — so the sweep stays cheap.
    try:
        dag = fragment_dag(runner.executor, plan, runner.catalogs)
    except Exception as e:  # noqa: BLE001 - a cut failure is a verdict
        failures.append((label, [f"fragment_dag failed: {e!r}"]))
        print(f"# {label}: FRAGMENTATION FAILED {e!r}",
              file=sys.stderr)
        return
    if dag is not None:
        try:
            PC.verify_dag(runner.executor, dag)
        except PC.PlanCheckError as e:
            failures.append((label, [f"[stage-dag] {v}"
                                     for v in e.violations]))
            print(f"# {label}: {len(e.violations)} DAG violation(s)",
                  file=sys.stderr)
            for v in e.violations:
                print(f"#   - {v}", file=sys.stderr)
            return
        dag_stats.append(len(dag.fragments))
        # ISSUE 15: the seeded-misestimate adaptive sweep runs over
        # the SAME (already statically-verified) DAG — mutating it is
        # fine, nothing re-reads it after this point
        applied = _seeded_misestimate_sweep(runner, label, dag,
                                            failures)
        replans.append(applied)
        print(f"# {label}: ok ({len(dag.fragments)}-stage dag, "
              f"{applied} seeded re-plans)", file=sys.stderr)
    else:
        print(f"# {label}: ok (not dag-distributable)",
              file=sys.stderr)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rungs", action="store_true",
                    help="bench rungs only")
    ap.add_argument("--corpus", choices=("tpch", "tpcds", "all"),
                    default=None, help="corpus only (default both "
                    "plus rungs)")
    ap.add_argument("--sf", type=float, default=0.001,
                    help="corpus scale factor (planning-only)")
    args = ap.parse_args()
    do_rungs = args.rungs or args.corpus is None
    corpora = ([] if args.rungs else
               ["tpch", "tpcds"] if args.corpus in (None, "all")
               else [args.corpus])

    t0 = time.time()
    failures: list = []
    dag_stats: list = []
    replans: list = []
    n = 0
    _wire_misestimate_case(failures)
    _ici_flip_case(failures)
    if do_rungs:
        from bench import RUNGS

        for name, suite, qid, sf, props in RUNGS:
            # plan at the rung's REAL scale + session props (generator
            # connectors are lazy — row counts, not rows); the bench
            # prewarm path verifies the same plans before compiling
            runner = make_runner(suite, sf, props)
            _audit_one(runner, f"rung {name}",
                       queries(suite)[qid], failures, dag_stats,
                       replans)
            n += 1
    for suite in corpora:
        runner = make_runner(suite, args.sf)
        for qid, sql in sorted(queries(suite).items()):
            _audit_one(runner, f"{suite} q{qid}", sql, failures,
                       dag_stats, replans)
            n += 1
    wall = time.time() - t0
    multi = sum(1 for s in dag_stats if s >= 2)
    print(f"# plan_audit: {n} plans, {len(failures)} with violations, "
          f"{len(dag_stats)} dag-distributable "
          f"({multi} multi-stage), {sum(replans)} seeded adaptive "
          f"re-plans applied, {wall:.1f}s", file=sys.stderr)
    if failures:
        print("PLAN AUDIT FAILED:")
        for label, violations in failures:
            for v in violations:
                print(f"  {label}: {v}")
        return 1
    print(f"plan audit clean: {n} plans verified "
          f"({len(dag_stats)} stage DAGs, {sum(replans)} seeded "
          f"adaptive re-plans) in {wall:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
