"""Honest per-node breakdown of one bench rung on the real chip:
EXPLAIN ANALYZE with the executor's stats_drain mode, which drains the
axon execution queue after every page so per-node wall times are device
time, not dispatch time (see bench.py docstring for the timing model).

Usage: analyze_rung.py {tpch|tpcds} QID SF [k=v session props...]
"""

import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from tools._common import configure_jax, make_runner, queries  # noqa: E402


def main() -> int:
    suite, qid, sf = sys.argv[1], int(sys.argv[2]), float(sys.argv[3])
    configure_jax()
    runner = make_runner(suite, sf, props=sys.argv[4:])
    sql = queries(suite)[qid]
    plan = runner.plan(sql)
    ex = runner.executor
    # warm compile + first-flush out of the way (un-timed)
    t0 = time.time()
    ex.execute(plan)
    print(f"# warm run (compile + flush): {time.time() - t0:.1f}s",
          file=sys.stderr)
    ex.stats_drain = True
    # lifecycle trace for the analyzed run (obs/trace.py): the
    # critical-path and phase-split summaries below read it, and the
    # same spans back the /v1/query tree on a server
    from presto_tpu import obs as OBS

    tr = OBS.QueryTrace(f"rung-{suite}-q{qid}-sf{sf}")
    OBS.attach(ex, tr)
    t0 = time.time()
    _names, _rows, stats = ex.execute_with_stats(plan)
    total = time.time() - t0
    OBS.finalize(ex, tr, os.environ.get("PRESTO_TPU_TRACE_DIR"))
    from presto_tpu.runner import explain_text

    print(explain_text(plan, stats=stats))
    # critical path: the slowest span chain root -> leaf, plus the
    # per-kind wall split (queue vs run vs fetch on distributed
    # traces; attempt/operator locally)
    cp = OBS.critical_path(tr)
    print("# critical path: " + " -> ".join(
        f"{s['kind']}:{s['name']}={s['ms']}ms" for s in cp["chain"]
    ) if cp["chain"] else "# critical path: (no spans)",
        file=sys.stderr)
    print("# phase split (ms): " + ", ".join(
        f"{k}={v}" for k, v in cp["by_kind_ms"].items()
    ), file=sys.stderr)
    # gather accounting + fusion engagement for the analyzed run (the
    # late-materialization / fused-partial-agg observability contract)
    ctr = stats.get("counters", {})
    if ctr:
        print("# counters: " + ", ".join(
            f"{k}={ctr[k]}" for k in sorted(ctr)
        ), file=sys.stderr)
    if ctr.get("program_launches"):
        # launch amortization (ROOFLINE §7): at ~6ms of tunnel tax per
        # launch, the fused scan phase's dispatch floor is launches*6ms
        print(f"# launch amortization: {ctr['program_launches']} "
              f"fused-scan launches x ~6ms tunnel tax, "
              f"{ctr['splits_per_launch']} splits/launch "
              f"(split_batch_size folds the per-split driver loop "
              f"into XLA)", file=sys.stderr)
    # memory governor (ROOFLINE §8): measured largest buffer vs the
    # static model's prediction for the same plan
    from presto_tpu.exec import membudget as MB

    report = MB.audit(ex, plan)
    print(f"# hbm governor: peak_device_bytes="
          f"{ctr.get('peak_device_bytes', 0)} "
          f"(model max {report.max_buffer_bytes}, "
          f"pipeline peak {report.peak_bytes}), "
          f"memory_chunked_pipelines="
          f"{ctr.get('memory_chunked_pipelines', 0)} "
          f"(model planned {report.chunked_count})", file=sys.stderr)
    # fault tolerance (ISSUE 5): a rung that needed device-OOM
    # degradation (or, behind a DCN coordinator, task re-dispatch) is
    # reporting a real HBM-model miss — BENCH_DETAILS carries the same
    # counters so the driver's artifact shows it too
    print(f"# fault tolerance: device_oom_retries="
          f"{ctr.get('device_oom_retries', 0)} "
          f"task_retries={ctr.get('task_retries', 0)} "
          f"workers_excluded={ctr.get('workers_excluded', 0)} "
          f"deadline_ms_remaining="
          f"{ctr.get('deadline_ms_remaining', -1)}", file=sys.stderr)
    # result cache (ISSUE 10, presto_tpu/cache/): hit/miss for the
    # analyzed run plus the store's hit rate so far in this process —
    # a repeated rung with hits=0 means its plan is uncacheable or the
    # session left result_cache_enabled off
    hits = ctr.get("result_cache_hits", 0)
    misses = ctr.get("result_cache_misses", 0)
    looked = hits + misses
    print(f"# result cache: hits={hits} misses={misses} "
          f"hit_rate={hits / looked if looked else 0.0:.2f} "
          f"evictions={ctr.get('result_cache_evictions', 0)} "
          f"invalidations={ctr.get('result_cache_invalidations', 0)}",
          file=sys.stderr)
    # transfer ledger (ISSUE 12/13, exec/xfer.py): the rung's measured
    # host<->device copy tax, plus the device-resident data plane's
    # two deltas — mesh-local exchange edges (serde skipped, zero
    # crossings when device-resident) and donated-program invocations
    print(f"# transfer ledger: h2d_bytes={ctr.get('h2d_bytes', 0)} "
          f"d2h_bytes={ctr.get('d2h_bytes', 0)} "
          f"h2d_transfers={ctr.get('h2d_transfers', 0)} "
          f"d2h_transfers={ctr.get('d2h_transfers', 0)} "
          f"transfer_wall_s={ctr.get('transfer_wall_s', 0.0)} "
          f"mesh_local_exchanges={ctr.get('mesh_local_exchanges', 0)} "
          f"buffers_donated={ctr.get('buffers_donated', 0)} "
          f"ici_exchanges={ctr.get('ici_exchanges', 0)} "
          f"ici_bytes={ctr.get('ici_bytes', 0)} "
          f"pallas_kernels_used={ctr.get('pallas_kernels_used', 0)}",
          file=sys.stderr)
    print(f"# analyzed wall (incl. per-page drain overhead): {total:.2f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
