"""Render README's Measured table FROM the committed BENCH_DETAILS.json
(VERDICT r3 #10: the docs must be generated from the artifact, never
hand-copied). Prints a markdown table; `--write` splices it into
README.md between the BENCH-TABLE markers.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BEGIN = "<!-- BENCH-TABLE BEGIN (tools/readme_bench_table.py) -->"
END = "<!-- BENCH-TABLE END -->"


def render() -> str:
    with open(os.path.join(REPO, "BENCH_DETAILS.json")) as f:
        d = json.load(f)
    lines = [
        BEGIN,
        "| rung | steady (s) | rows | validated | vs sqlite |",
        "|---|---|---|---|---|",
    ]
    for name in sorted(d.get("rungs", {})):
        r = d["rungs"][name]
        if r.get("steady_s") is not None:
            steady = f"{r['steady_s']:.3f}"
        else:
            steady = f"— ({(r.get('time_error') or '?')[:40]})"
        rows = r.get("result_rows", "—")
        valid = "yes" if r.get("valid") else "no"
        boost = r.get("capacity_boost", 0)
        if valid == "yes" and boost > 1:
            # honest-but-boosted: timed at the settled capacity rung
            valid = f"yes (boost {boost})"
        sp = r.get("speedup_vs_sqlite")
        sp = f"{sp}x" if sp else "—"
        lines.append(f"| {name} | {steady} | {rows} | {valid} | {sp} |")
    lines.append(
        f"\nHonest drain-protocol timing (see ROOFLINE.md); backend "
        f"{d.get('backend', '?')} on {d.get('device', '?')}."
    )
    lines.append(END)
    return "\n".join(lines)


def main() -> int:
    table = render()
    if "--write" in sys.argv:
        path = os.path.join(REPO, "README.md")
        src = open(path).read()
        if BEGIN in src and END in src:
            head = src[: src.index(BEGIN)]
            tail = src[src.index(END) + len(END):]
            open(path, "w").write(head + table + tail)
            print("README.md updated")
        else:
            print("markers not found in README.md", file=sys.stderr)
            return 1
    else:
        print(table)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
