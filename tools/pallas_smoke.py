#!/usr/bin/env python
"""Interpret-mode Pallas smoke (ISSUE 18): the ci_static leg that
proves the two device-native kernel tiers still produce ORACLE-exact
results on a seeded page, in seconds, with no device.

Two checks, both pure CPU interpret mode (the same posture tier-1's
parity suites pin, compressed to one seeded case each):

  radix join      ops/pallas_join build_index + probe_index on a
                  4096-row build (> DIM_MAX_BUILD, so the true
                  radix-partitioned tier runs, not the small-dim
                  tile), checked against a numpy searchsorted oracle
                  over duplicate hashes, an invalid band, and an
                  absent-hash probe band;
  segmented sum   ops/pallas_agg segmented_sum_i64 / segmented_count
                  against a host oracle over seeded group ids,
                  including empty groups and values that overflow
                  int32 partial sums (the 16x4-bit limb exactness
                  argument, checked not trusted).

Budget: < 5 s on the 2-core box — one pallas_call compile each in
interpret mode. Run: `python tools/pallas_smoke.py` (exit 1 on any
mismatch); tools/ci_static.sh runs it as the Pallas leg.
"""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np  # noqa: E402


def _check_radix_join() -> int:
    import jax.numpy as jnp

    from presto_tpu.ops import pallas_join as PJ

    rng = np.random.default_rng(18)
    nb, np_ = 1 << 12, 2000
    assert nb > PJ.DIM_MAX_BUILD  # pin: this leg exercises the RADIX tier
    # duplicate hashes from a small universe, spread across the u64
    # range so the radix bucketing (top bits) actually disperses them
    bhash = rng.choice(500, size=nb).astype(np.uint64) * np.uint64(
        0x9E3779B97F4A7C15
    )
    bvalid = rng.random(nb) > 0.1  # an invalid band the probe must skip
    # probe: half present hashes, half absent (universe shifted by 1)
    phash = np.concatenate([
        rng.choice(500, size=np_ // 2).astype(np.uint64),
        rng.choice(500, size=np_ - np_ // 2).astype(np.uint64)
        * np.uint64(2) + np.uint64(1),
    ]) * np.uint64(0x9E3779B97F4A7C15)
    layout = PJ.plan_layout(nb)
    if layout[0] != "radix":
        print(f"# pallas_smoke: expected radix layout for {nb}-row "
              f"build, got {layout[0]!r}", file=sys.stderr)
        return 1
    tabs, perm, overflow = PJ.build_index(
        jnp.asarray(bhash), jnp.asarray(bvalid), layout
    )
    if bool(overflow):
        print("# pallas_smoke: unexpected build_index overflow",
              file=sys.stderr)
        return 1
    start, cnt = PJ.probe_index(
        jnp.asarray(phash), tabs, layout, interpret=True
    )
    start, cnt = np.asarray(start), np.asarray(cnt)
    # oracle: counts of equal-hash VALID build rows, segments located
    # in the poison-sorted build order (invalid rows sort last)
    poisoned = np.where(bvalid, bhash, np.uint64(0xFFFFFFFFFFFFFFFF))
    sh = np.sort(poisoned, kind="stable")
    want_lo = np.searchsorted(sh, phash, side="left")
    want_cnt = (
        np.searchsorted(sh, phash, side="right") - want_lo
    ).astype(cnt.dtype)
    if not np.array_equal(cnt, want_cnt):
        bad = int(np.sum(cnt != want_cnt))
        print(f"# pallas_smoke: radix join match-count mismatch on "
              f"{bad}/{np_} probe rows", file=sys.stderr)
        return 1
    hit = want_cnt > 0
    if not np.array_equal(start[hit], want_lo[hit].astype(start.dtype)):
        print("# pallas_smoke: radix join segment-start mismatch",
              file=sys.stderr)
        return 1
    # the permutation really is the hash-sort of the poisoned build
    if not np.array_equal(np.asarray(perm)[: nb], np.argsort(
            poisoned, kind="stable").astype(np.asarray(perm).dtype)[: nb]):
        print("# pallas_smoke: build perm is not the hash-sort order",
              file=sys.stderr)
        return 1
    return 0


def _check_segmented_sum() -> int:
    import jax.numpy as jnp

    from presto_tpu.ops import pallas_agg as PA

    rng = np.random.default_rng(18)
    n, groups = 3000, 97  # group 13 deliberately left empty
    ids = rng.integers(0, groups, n)
    ids[ids == 13] = 14
    # values big enough that a 32-bit partial sum would wrap
    vals = rng.integers(-(1 << 40), 1 << 40, n)
    got = np.asarray(PA.segmented_sum_i64(
        jnp.asarray(vals), jnp.asarray(ids), groups, interpret=True))
    want = np.zeros(groups, dtype=object)
    for g, v in zip(ids, vals):
        want[g] += int(v)
    if not np.array_equal(got, want.astype(np.int64)):
        print("# pallas_smoke: segmented_sum_i64 mismatch vs host "
              "oracle", file=sys.stderr)
        return 1
    cgot = np.asarray(PA.segmented_count(
        jnp.asarray(ids), groups, interpret=True))
    cwant = np.bincount(ids, minlength=groups)
    if not np.array_equal(cgot, cwant):
        print("# pallas_smoke: segmented_count mismatch vs host "
              "oracle", file=sys.stderr)
        return 1
    return 0


def main() -> int:
    t0 = time.monotonic()
    rc = _check_radix_join() | _check_segmented_sum()
    wall = time.monotonic() - t0
    if rc == 0:
        print(f"# pallas_smoke: radix join + segmented reduction "
              f"oracle-exact in {wall:.1f}s (interpret mode)",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
