"""Honest per-op microbenchmarks on the axon TPU runtime.

Timing protocol (round-4 discovery, see bench.py docstring): on axon,
`jax.block_until_ready` returns at dispatch — it does NOT wait for
device completion. Queued work drains only when a device->host read
forces it. So every measurement here is a dispatch+drain cycle:

    t0; dispatch N launches; np.asarray(last.ravel()[0]); t1

The first cycle per program pays a one-time flush and is discarded;
subsequent cycles are stable (+-5%). The tiny read's own cost (~0.1s
when the queue is empty) amortizes over N.

Usage: python tools/microbench.py [rows_log2=18]
"""

import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from tools._common import configure_jax  # noqa: E402


def main() -> int:
    rows_log2 = int(sys.argv[1]) if len(sys.argv) > 1 else 18
    n = 1 << rows_log2
    jax = configure_jax()
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    vals = jnp.ones((n,), jnp.int64)
    ids4096 = jnp.arange(n, dtype=jnp.int32) % 4096
    ids4 = ids4096 % 4
    fvals = vals.astype(jnp.float32)
    np.asarray(vals[0])  # initial flush

    from presto_tpu.devsync import drain

    def cycle(tag, f, *args, reps=20, cycles=3):
        y = f(*args)
        drain(y)  # warm + first flush
        best = None
        for _ in range(cycles):
            t0 = time.time()
            for _ in range(reps):
                y = f(*args)
            drain(y)
            dt = (time.time() - t0) / reps
            best = dt if best is None else min(best, dt)
        rate = n / best / 1e6
        print(f"{tag:44s} {best*1e3:8.2f} ms  {rate:9.0f} M rows/s")
        return best

    jit = jax.jit
    cycle("noop (launch overhead)", jit(lambda v: v[:8] * 2), vals)
    cycle("elementwise i64 mul+add", jit(lambda v: v * 2 + 1), vals)
    cycle("reduce-sum i64", jit(lambda v: jnp.sum(v)), vals)
    cycle("scatter segsum G=4096", jit(
        lambda v, i: jax.ops.segment_sum(v, i, num_segments=4096)),
        vals, ids4096)
    cycle("scatter segsum G=4", jit(
        lambda v, i: jax.ops.segment_sum(v, i, num_segments=4)),
        vals, ids4)
    cycle("scatter segsum G=4096 sorted-flag", jit(
        lambda v, i: jax.ops.segment_sum(
            v, i, num_segments=4096, indices_are_sorted=True)),
        vals, jnp.sort(ids4096))

    def where_agg(v, i):
        return jnp.stack([jnp.sum(jnp.where(i == g, v, 0))
                          for g in range(4)])
    cycle("where+sum x4 i64", jit(where_agg), vals, ids4)

    def onehot_i8(v, i, G):
        # exact int64 aggregation on the MXU: 8x8-bit limb decompose,
        # i8 one-hot, dot with i32 accumulation, recombine
        oh = (i[:, None] == jnp.arange(G, dtype=jnp.int32)[None, :]
              ).astype(jnp.int8)
        limbs = jnp.stack(
            [((v >> (8 * k)) & 0xFF).astype(jnp.int8) for k in range(8)]
        )  # (8, n)
        acc = jax.lax.dot_general(
            limbs, oh, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # (8, G)
        return jnp.sum(acc.astype(jnp.int64)
                       << (8 * jnp.arange(8, dtype=jnp.int64))[:, None],
                       axis=0)
    cycle("one-hot i8 matmul G=4 (exact)", jit(
        lambda v, i: onehot_i8(v, i, 4)), vals, ids4)
    cycle("one-hot i8 matmul G=64 (exact)", jit(
        lambda v, i: onehot_i8(v, i, 64)), vals, ids4096 % 64)
    cycle("one-hot i8 matmul G=1024 (exact)", jit(
        lambda v, i: onehot_i8(v, i, 1024)), vals, ids4096 % 1024)

    cycle("one-hot f32 matmul G=4", jit(
        lambda v, i: (v.astype(jnp.float32)[None, :]
                      @ jax.nn.one_hot(i, 4, dtype=jnp.float32))),
        fvals, ids4)
    cycle("sort [i32 key, i64 val]", jit(
        lambda v, i: jax.lax.sort([i, v], num_keys=1)), vals, ids4096)
    cycle("argsort i32", jit(lambda i: jnp.argsort(i)), ids4096)
    cycle("cumsum i64", jit(lambda v: jnp.cumsum(v)), vals)
    cycle("gather 256k from 256k", jit(
        lambda v, i: v[i]), vals, ids4096 * 0 + jnp.arange(n) % n)
    return 0


if __name__ == "__main__":
    sys.exit(main())
