"""Run one rung and report WHICH executor site raised each overflow flag.

Usage: python tools/debug_overflow.py {tpch|tpcds} QID SF [k=v ...]

bench.py only records "capacity overflow at initial capacities" — this
tool wraps Executor._pending_overflow so every appended device flag
carries the Python call site that produced it, then decodes the flags
and prints the sites whose flag is True. Use it to find the node whose
planner capacity estimate is short (the fix belongs in
sql/planner.py's estimates or the executor's clamps, not in boosting).
"""

import os
import sys
import time
import traceback

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from tools._common import configure_jax, make_runner, queries  # noqa: E402


class TracedList(list):
    def __init__(self):
        super().__init__()
        self.sites = []

    def append(self, flag):
        frame = None
        for fr in reversed(traceback.extract_stack(limit=8)):
            if "presto_tpu" in fr.filename:
                frame = fr
                break
        self.sites.append(
            f"{os.path.basename(frame.filename)}:{frame.lineno} "
            f"{frame.name}" if frame else "?")
        super().append(flag)

    def extend(self, flags):
        for f in flags:
            self.append(f)


def main() -> int:
    import numpy as np

    suite, qid, sf = sys.argv[1], int(sys.argv[2]), float(sys.argv[3])
    configure_jax()
    runner = make_runner(suite, sf, props=sys.argv[4:])
    plan = runner.plan(queries(suite)[qid])
    ex = runner.executor
    ex._pending_overflow = TracedList()
    t0 = time.time()
    pages = list(ex.pages(plan))
    rows = 0
    for p in pages:
        rows += len(p.to_pylist())
    print(f"wall {time.time() - t0:.1f}s rows={rows}", flush=True)
    tl = ex._pending_overflow
    n_true = 0
    for site, flag in zip(tl.sites, tl):
        v = bool(np.asarray(flag).any())
        if v:
            n_true += 1
            print(f"OVERFLOW at {site}", flush=True)
    print(f"{n_true}/{len(tl)} flags true", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
