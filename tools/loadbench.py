"""Concurrent-load benchmark for /v1/statement — the BENCH surface
ROADMAP item 1 names, exercised here (ISSUE 10) to prove the
process-shared result cache is safe and effective under concurrency.

Reference workload model: dashboard-style production traffic is
dominated by REPEATED statements with a tail of unique ones. The deck
mixes both: each client thread loops over a shuffled deck of
``--repeat-frac`` repeated statements (drawn from a small fixed set —
these should collapse to cache hits after first execution) and unique
statements (a varying literal defeats the cache — these measure the
real execution floor under concurrency).

Reported (one JSON line on stdout, like bench.py's driver contract):
  clients, duration_s, queries, errors, qps,
  p50_ms / p99_ms  — read from the server's OWN
      ``presto_tpu_query_latency_seconds`` /metrics histogram (the
      PR 9 surface; bucket-interpolated exactly like obs/histo.py,
      and over the server's whole query population — client-side
      stopwatches would double-count protocol polling),
  cache_hits / cache_misses / cache_hit_rate — from the
      ``presto_tpu_result_cache_*`` counters (the process-shared
      store's totals),
  h2d_bytes / d2h_bytes / transfer_wall_ms — aggregate host<->device
      copy tax of the run (ISSUE 12, the ``presto_tpu_h2d_bytes``/
      ``d2h_bytes``/``transfer_wall_seconds`` process totals from
      exec/xfer.py, base-subtracted), visible next to QPS/p99 so a
      serving-path change that re-introduces redundant crossings
      shows up in the same JSON line that grades its latency,
  exchange_wire_bytes / exchange_raw_bytes /
  exchange_fetch_reused_conns — wire efficiency of the exchange plane
      (ISSUE 16, the ``presto_tpu_exchange_*`` process totals from
      dist/serde.py codecs and dist/connpool.py keep-alive reuse,
      base-subtracted; 0 on single-process runs where no page ever
      crosses the DCN boundary),
  program_launches / launches_per_query / cross_query_batches /
  cross_query_batched_queries / queries_per_launch — cross-query
      launch batching economics (ISSUE 17; ``--batching true|false``
      pins the session knob on every client for the A/B, and
      launches_per_query divides this run's launches by EXECUTED
      queries — cache replays launch nothing),
  admission_cache_bypasses / peak_queued — cache-aware admission:
      replays that skipped the resource-group queue entirely, next to
      the lifetime peak admission queue depth they kept down,
  hit_rate_cold / hit_rate_warm — the run split at its midpoint with
      PER-ROUND base subtraction of the store process totals (ISSUE
      19): cold carries the deck's compulsory first-execution misses,
      warm is steady state — one blended ratio understated warm
      exactly when runs were short,
  cache_warm_loads / cache_manifest_drops / cache_remote_hits /
  cache_subsumed_hits — the fleet-reuse tallies, base-subtracted.

Fleet-reuse modes (ISSUE 19):
  ``--restart-after N`` — N rounds, server + shared store torn down
      (only the ``--persist-dir`` manifest/payload files survive),
      N more rounds; post-restart rounds must show
      cache_warm_loads >= 1 and hit_rate_warm back at pre-restart
      level (the persistent warm-start acceptance).
  ``--fleet N`` — N subprocess workers under a DcnRunner: cold deck,
      heartbeat bloom refresh, then warm rounds served from peers'
      fragment caches (cache_remote_hits) over the pooled fetch
      plane; client-side p50/p99 per phase.

``--sanitize`` (ISSUE 11) arms the runtime lock sanitizer
(presto_tpu/obs/sanitizer.py) before the self-hosted server builds a
single lock, so N protocol clients x the shared ResultCache x the
admission arbiter x per-query executor threads race the instrumented
engine deliberately; the run FAILS (exit 1, violations printed) if
any lock-order inversion or unlocked shared-attr write is observed,
and the JSON gains ``sanitizer_violations``. This is the CI shape of
ROADMAP item 1(d)'s "cache on by default" prerequisite.

Usage:
  python -m tools.loadbench                      # self-hosted server
  python -m tools.loadbench --server http://...  # external server
  python -m tools.loadbench --clients 16 --duration 20 --no-cache
  python -m tools.loadbench --sanitize --clients 8 --duration 10
"""

from __future__ import annotations

import argparse
import json
import random
import re
import sys
import threading
import time
import urllib.request

from tools._common import REPO  # noqa: F401  (sys.path side effect)

# repeated deck: the Q1/Q3-style aggregates dashboards poll (small-SF
# tpch so the self-hosted mode is fast on CPU)
REPEATED_STATEMENTS = [
    "select l_returnflag, l_linestatus, count(*), sum(l_quantity), "
    "sum(l_extendedprice) from lineitem group by l_returnflag, "
    "l_linestatus order by l_returnflag, l_linestatus",
    "select count(*), sum(l_extendedprice * l_discount) from lineitem "
    "where l_discount between 5 and 7",
    "select o_orderpriority, count(*) from orders "
    "group by o_orderpriority order by o_orderpriority",
]
# unique-statement template: the varying literal moves the canonical
# statement fingerprint, so every instance misses by construction
UNIQUE_TEMPLATE = (
    "select count(*), sum(l_quantity) from lineitem "
    "where l_partkey > {}"
)


def _scrape_metrics(server: str) -> str:
    with urllib.request.urlopen(f"{server}/metrics", timeout=30) as r:
        return r.read().decode()


def _metric(text: str, name: str) -> int:
    m = re.search(rf"^{re.escape(name)} (\d+)", text, re.M)
    return int(m.group(1)) if m else 0


def _metric_f(text: str, name: str) -> float:
    m = re.search(rf"^{re.escape(name)} ([\d.eE+-]+)", text, re.M)
    return float(m.group(1)) if m else 0.0


def _histo_quantile(text: str, name: str, q: float,
                    base: dict = None) -> float:
    """Bucket-interpolated quantile over a Prometheus cumulative
    histogram (the obs/histo.py estimate, recomputed from exposition
    text; ``base`` subtracts a pre-run scrape so only this run's
    observations count)."""
    pat = re.compile(
        rf'^{re.escape(name)}_bucket{{le="([^"]+)"}} (\d+)', re.M)
    cum = [(float("inf") if le == "+Inf" else float(le), int(c))
           for le, c in pat.findall(text)]
    if not cum:
        return 0.0
    cum.sort()
    base_map = dict(base or {})
    counts, prev = [], 0
    for le, c in cum:
        c -= base_map.get(le, 0)
        counts.append((le, max(c - prev, 0)))
        prev = max(c, prev)
    total = sum(c for _, c in counts)
    if total == 0:
        return 0.0
    rank = q * total
    seen, lo = 0, 0.0
    for le, c in counts:
        if seen + c >= rank and c > 0:
            hi = le if le != float("inf") else lo
            frac = (rank - seen) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        seen += c
        lo = le
    return lo


def _histo_base(text: str, name: str) -> dict:
    pat = re.compile(
        rf'^{re.escape(name)}_bucket{{le="([^"]+)"}} (\d+)', re.M)
    return {(float("inf") if le == "+Inf" else float(le)): int(c)
            for le, c in pat.findall(text)}


def run_load(server: str, clients: int, duration_s: float,
             repeat_frac: float, cache: bool, seed: int = 0,
             batching: str = "auto", warmup_s: float = 0.0,
             batch_wait_ms: int = None,
             persist_dir: str = None) -> dict:
    from presto_tpu.client import StatementClient

    lock = threading.Lock()
    tally = {"queries": 0, "errors": 0, "rows": 0}

    def worker(idx: int, stop_at: float, record: bool) -> None:
        rng = random.Random(seed * 1000 + idx)
        cl = StatementClient(server, user=f"load{idx}",
                             catalog="tpch")
        # explicit both ways: the concurrent server path now DEFAULTS
        # the result cache on (ISSUE 17), so the --no-cache baseline
        # must actively opt out, not merely stay silent
        cl.session_properties["result_cache_enabled"] = (
            "true" if cache else "false")
        if persist_dir:
            # warm-start tier (ISSUE 19): the server-side runners
            # (re)bind the shared store's persister and warm-load the
            # manifest on the first enabled session after a restart
            cl.session_properties["result_cache_persist_dir"] = \
                persist_dir
        # cross-query launch batching A/B (ISSUE 17): "auto" rides the
        # server default; "true"/"false" pin the session knob so the
        # same deck grades launches-per-query batched vs solo
        if batching != "auto":
            cl.session_properties["cross_query_batching"] = batching
        if batch_wait_ms is not None:
            cl.session_properties["cross_query_batch_wait_ms"] = str(
                batch_wait_ms)
        uniq = idx * 1_000_000  # per-client namespace: no cross-client
        while time.time() < stop_at:  # accidental repeats
            if rng.random() < repeat_frac:
                sql = rng.choice(REPEATED_STATEMENTS)
            else:
                uniq += 1
                sql = UNIQUE_TEMPLATE.format(uniq)
            try:
                res = cl.execute(sql)
                ok = res.error is None
            except Exception:  # noqa: BLE001 - a load generator
                ok = False     # counts failures, it never crashes
                res = None
            if not record:
                continue
            with lock:
                tally["queries"] += 1
                if not ok:
                    tally["errors"] += 1
                elif res is not None:
                    tally["rows"] += len(res.rows)

    if warmup_s > 0:
        # steady-state stance: run the same deck off the books first so
        # jit compiles (solo AND the width-bucketed xq_batch variants)
        # land outside the measured window — the serving-bench analogue
        # of bench.py --prewarm
        warm_stop = time.time() + warmup_s
        warm = [threading.Thread(target=worker,
                                 args=(i, warm_stop, False), daemon=True)
                for i in range(clients)]
        for t in warm:
            t.start()
        for t in warm:
            t.join(timeout=warmup_s * 4 + 60)

    pre = _scrape_metrics(server)
    hname = "presto_tpu_query_latency_seconds"
    base_hist = _histo_base(pre, hname)
    base_hits = _metric(pre, "presto_tpu_result_cache_hits_total")
    base_miss = _metric(pre, "presto_tpu_result_cache_misses_total")
    base_h2d = _metric(pre, "presto_tpu_h2d_bytes")
    base_d2h = _metric(pre, "presto_tpu_d2h_bytes")
    base_wall = _metric_f(pre, "presto_tpu_transfer_wall_seconds")
    base_wire = _metric(pre, "presto_tpu_exchange_wire_bytes_total")
    base_eraw = _metric(pre, "presto_tpu_exchange_raw_bytes_total")
    base_reuse = _metric(
        pre, "presto_tpu_exchange_fetch_reused_conns_total")
    base_launch = _metric(pre, "presto_tpu_program_launches")
    base_xq = _metric(pre, "presto_tpu_cross_query_batches_total")
    base_xqq = _metric(
        pre, "presto_tpu_cross_query_batched_queries_total")
    base_bypass = _metric(
        pre, "presto_tpu_admission_cache_bypasses_total")
    base_wload = _metric(pre, "presto_tpu_cache_warm_loads_total")
    base_mdrop = _metric(
        pre, "presto_tpu_cache_manifest_drops_total")
    base_rhit = _metric(pre, "presto_tpu_cache_remote_hits_total")
    base_subs = _metric(
        pre, "presto_tpu_cache_subsumed_hits_total")

    t0 = time.time()
    stop_at = t0 + duration_s
    threads = [threading.Thread(target=worker,
                                args=(i, stop_at, True), daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    # ISSUE 19 hit-rate fix: one run-wide ratio buried the story —
    # the FIRST pass over the deck must miss (cold compulsory
    # misses), so steady state looked worse the shorter the run. A
    # midpoint scrape splits the window into a cold round and a warm
    # round, each base-subtracted against ITS OWN starting store
    # process totals.
    nap = t0 + duration_s / 2 - time.time()
    if nap > 0:
        time.sleep(nap)
    try:
        mid = _scrape_metrics(server)
    except Exception:  # noqa: BLE001 - advisory midpoint
        mid = pre
    for t in threads:
        t.join(timeout=duration_s * 4 + 60)
    wall = time.time() - t0

    post = _scrape_metrics(server)
    hits = _metric(post, "presto_tpu_result_cache_hits_total") - base_hits
    misses = (_metric(post, "presto_tpu_result_cache_misses_total")
              - base_miss)
    looked = hits + misses
    mid_hits = _metric(mid, "presto_tpu_result_cache_hits_total")
    mid_miss = _metric(mid, "presto_tpu_result_cache_misses_total")
    cold_h, cold_m = mid_hits - base_hits, mid_miss - base_miss
    warm_h = hits - cold_h
    warm_m = misses - cold_m
    cold_n, warm_n = cold_h + cold_m, warm_h + warm_m
    # launch economics (ISSUE 17): the dispatch-amortization headline.
    # launches_per_query divides the run's program launches by the
    # queries that actually EXECUTED (cache hits replay zero launches
    # and would flatter the ratio) — the A/B acceptance reads this
    # batched vs solo on a --no-cache run
    launches = _metric(post, "presto_tpu_program_launches") - base_launch
    executed = max(tally["queries"] - hits, 1)
    return {
        "clients": clients,
        "duration_s": round(wall, 2),
        "repeat_frac": repeat_frac,
        "result_cache": cache,
        "queries": tally["queries"],
        "errors": tally["errors"],
        "rows": tally["rows"],
        "qps": round(tally["queries"] / wall, 2) if wall else 0.0,
        "p50_ms": round(
            _histo_quantile(post, hname, 0.50, base_hist) * 1000, 1),
        "p99_ms": round(
            _histo_quantile(post, hname, 0.99, base_hist) * 1000, 1),
        "cache_hits": hits,
        "cache_misses": misses,
        "cache_hit_rate": round(hits / looked, 3) if looked else 0.0,
        # per-round rates (ISSUE 19): cold = first half of the
        # window (carries the deck's compulsory misses), warm =
        # second half (steady state; a persisted warm start lifts
        # THIS number back to the pre-restart level immediately)
        "hit_rate_cold": round(cold_h / cold_n, 3) if cold_n else 0.0,
        "hit_rate_warm": round(warm_h / warm_n, 3) if warm_n else 0.0,
        # fleet-reuse tallies (ISSUE 19), base-subtracted like every
        # other store process total
        "cache_warm_loads": _metric(
            post, "presto_tpu_cache_warm_loads_total") - base_wload,
        "cache_manifest_drops": _metric(
            post, "presto_tpu_cache_manifest_drops_total") - base_mdrop,
        "cache_remote_hits": _metric(
            post, "presto_tpu_cache_remote_hits_total") - base_rhit,
        "cache_subsumed_hits": _metric(
            post, "presto_tpu_cache_subsumed_hits_total") - base_subs,
        "h2d_bytes": _metric(post, "presto_tpu_h2d_bytes") - base_h2d,
        "d2h_bytes": _metric(post, "presto_tpu_d2h_bytes") - base_d2h,
        "transfer_wall_ms": round(
            (_metric_f(post, "presto_tpu_transfer_wall_seconds")
             - base_wall) * 1000, 1),
        # exchange wire efficiency (ISSUE 16): post-codec vs pre-codec
        # bytes crossing the DCN boundary, and keep-alive reuse, from
        # the dist/serde + dist/connpool process totals on /metrics
        # (0 on single-process runs — no page ever serializes)
        "exchange_wire_bytes": _metric(
            post, "presto_tpu_exchange_wire_bytes_total") - base_wire,
        "exchange_raw_bytes": _metric(
            post, "presto_tpu_exchange_raw_bytes_total") - base_eraw,
        "exchange_fetch_reused_conns": _metric(
            post, "presto_tpu_exchange_fetch_reused_conns_total")
            - base_reuse,
        # cross-query launch batching (ISSUE 17)
        "batching": batching,
        "program_launches": launches,
        "launches_per_query": round(launches / executed, 3),
        "cross_query_batches": _metric(
            post, "presto_tpu_cross_query_batches_total") - base_xq,
        "cross_query_batched_queries": _metric(
            post, "presto_tpu_cross_query_batched_queries_total")
            - base_xqq,
        "queries_per_launch": _metric(
            post, "presto_tpu_queries_per_launch"),
        "admission_cache_bypasses": _metric(
            post, "presto_tpu_admission_cache_bypasses_total")
            - base_bypass,
        "peak_queued": _metric(post, "presto_tpu_peak_queued"),
    }


def run_append_load(writers: int, readers: int, duration_s: float,
                    rows_per_append: int, seed: int = 0) -> dict:
    """Mixed streaming mode (ISSUE 14): ``writers`` threads advance an
    append-log stream while ``readers`` threads refresh a registered
    materialized view through the IVM path (streaming/ivm.py). Refresh
    walls are measured per reader call (p50/p99) and the registry
    counters (``ivm_refreshes`` / ``ivm_full_recomputes`` /
    ``delta_pages_folded`` / ``stream_appends_seen``) come off the
    shared counter-sink executor — the same numbers EXPLAIN ANALYZE,
    /metrics, and system.metrics would render. This is also the
    appender x tailer concurrency harness: run with ``--sanitize`` to
    race the instrumented stream/view/cache locks deliberately."""
    from presto_tpu import types as T
    from presto_tpu.connectors.stream import StreamConnector
    from presto_tpu.runner import LocalRunner
    from presto_tpu.streaming import ivm as IVM

    rng = random.Random(seed)
    conn = StreamConnector()
    conn.create_table(
        "events", ["k", "v"], [T.BIGINT, T.DOUBLE],
        [(rng.randrange(64), rng.random() * 100.0)
         for _ in range(4 * rows_per_append)],
    )
    runner = LocalRunner({"stream": conn}, default_catalog="stream",
                         page_rows=1 << 13)
    view = IVM.IvmRegistry().register(
        runner, "dash",
        "select k, count(*), sum(v) from events group by k order by k",
    )
    sink = runner.executor
    # settle + compile off the timed path (the bench --prewarm stance)
    IVM.refresh(view, session=runner.session, sink=sink)

    stop_at = time.time() + duration_s
    lock = threading.Lock()
    tally = {"appends": 0, "rows_appended": 0, "refreshes": 0,
             "errors": 0}
    walls: list = []

    def writer(idx: int) -> None:
        wrng = random.Random(seed * 1000 + idx)
        while time.time() < stop_at:
            batch = [(wrng.randrange(64), wrng.random() * 100.0)
                     for _ in range(rows_per_append)]
            try:
                conn.append("events", batch)
            except Exception:  # noqa: BLE001 - a load generator
                with lock:     # counts failures, it never crashes
                    tally["errors"] += 1
                continue
            sink.count_stream_append()
            with lock:
                tally["appends"] += 1
                tally["rows_appended"] += len(batch)
            time.sleep(0.01)  # pace: leave the readers CPU to fold

    def reader(idx: int) -> None:
        while time.time() < stop_at:
            conn.wait_for_offset(
                "events", view.settled_offset(), 0.2)
            t0 = time.perf_counter()
            try:
                IVM.refresh(view, session=runner.session, sink=sink)
            except Exception:  # noqa: BLE001 - a load generator
                with lock:     # counts failures, it never crashes
                    tally["errors"] += 1
                continue
            wall = time.perf_counter() - t0
            with lock:
                tally["refreshes"] += 1
                walls.append(wall)

    threads = (
        [threading.Thread(target=writer, args=(i,), daemon=True)
         for i in range(writers)]
        + [threading.Thread(target=reader, args=(i,), daemon=True)
           for i in range(readers)]
    )
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s * 4 + 60)
    wall = time.time() - t0

    walls.sort()

    def pct(q: float) -> float:
        if not walls:
            return 0.0
        return walls[min(int(q * len(walls)), len(walls) - 1)]

    return {
        "mode": "append-writers",
        "writers": writers,
        "readers": readers,
        "duration_s": round(wall, 2),
        "appends": tally["appends"],
        "rows_appended": tally["rows_appended"],
        "refreshes": tally["refreshes"],
        "errors": tally["errors"],
        "refresh_p50_ms": round(pct(0.50) * 1000, 2),
        "refresh_p99_ms": round(pct(0.99) * 1000, 2),
        "ivm_refreshes": sink.ivm_refreshes,
        "ivm_full_recomputes": sink.ivm_full_recomputes,
        "delta_pages_folded": sink.delta_pages_folded,
        "stream_appends_seen": sink.stream_appends_seen,
        "final_offset": conn.offset("events"),
        "view_watermark": view.settled_offset(),
    }


def run_fleet_bench(fleet_n: int, duration_s: float, scale: float,
                    seed: int = 0) -> dict:
    """Fleet-reuse mode (ISSUE 19): ``fleet_n`` subprocess workers
    under one DcnRunner coordinator. Round 1 runs the repeated deck
    cold (every split share computes on its worker), a heartbeat
    refresh pulls the workers' bloom cache summaries, then warm
    rounds run until the duration budget — the coordinator probe
    short-circuits dispatch with fragment pages replayed over the
    pooled spool-fetch plane. Client-side walls p50/p99 per phase,
    plus the coordinator's cache_remote_hits."""
    import os
    import subprocess

    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.dist.dcn import DcnRunner

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    procs, uris = [], []
    for _ in range(fleet_n):
        p = subprocess.Popen(
            [sys.executable, "-m", "presto_tpu.server.worker",
             "--port", "0", "--suite", "tpch",
             "--scale", str(scale), "--page-rows", str(1 << 13)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=env, text=True,
        )
        info = json.loads(p.stdout.readline())
        procs.append(p)
        uris.append(f"http://127.0.0.1:{info['port']}")
    coord = DcnRunner(
        {"tpch": TpchConnector(scale)}, uris,
        default_catalog="tpch", page_rows=1 << 13,
        session_props={"result_cache_enabled": "true"},
    )
    cold_walls, warm_walls = [], []
    errors = 0
    try:
        for sql in REPEATED_STATEMENTS:
            t0 = time.perf_counter()
            try:
                coord.execute(sql)
            except Exception:  # noqa: BLE001 - a load generator
                errors += 1    # counts failures, it never crashes
                continue
            cold_walls.append(time.perf_counter() - t0)
        coord.heartbeat.check_once()  # pull cacheSummary blooms
        stop_at = time.time() + duration_s
        while time.time() < stop_at:
            for sql in REPEATED_STATEMENTS:
                t0 = time.perf_counter()
                try:
                    coord.execute(sql)
                except Exception:  # noqa: BLE001 - a load generator
                    errors += 1    # counts failures, never crashes
                    continue
                warm_walls.append(time.perf_counter() - t0)
    finally:
        ex = coord.runner.executor
        coord.close()
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001 - teardown best-effort
                p.kill()

    def pct(walls, q):
        if not walls:
            return 0.0
        walls = sorted(walls)
        return walls[min(int(q * len(walls)), len(walls) - 1)]

    return {
        "mode": "fleet",
        "workers": fleet_n,
        "duration_s": duration_s,
        "queries": len(cold_walls) + len(warm_walls),
        "errors": errors,
        "cold_p50_ms": round(pct(cold_walls, 0.5) * 1000, 1),
        "cold_p99_ms": round(pct(cold_walls, 0.99) * 1000, 1),
        "warm_p50_ms": round(pct(warm_walls, 0.5) * 1000, 1),
        "warm_p99_ms": round(pct(warm_walls, 0.99) * 1000, 1),
        "cache_remote_hits": ex.cache_remote_hits,
        # split shares served per warm query (== worker count when
        # every leaf task short-circuited)
        "remote_hits_per_query": round(
            ex.cache_remote_hits / max(len(warm_walls), 1), 3),
    }


_ROUND_KEYS = (
    "queries", "errors", "qps", "p50_ms", "p99_ms", "cache_hits",
    "cache_misses", "cache_hit_rate", "hit_rate_cold",
    "hit_rate_warm", "cache_warm_loads", "cache_manifest_drops",
)


def run_restart_bench(args, persist_dir: str) -> dict:
    """Warm-start mode (ISSUE 19): ``--restart-after N`` runs N load
    rounds against a self-hosted server, tears the server AND the
    process-shared store down (process-death semantics: only the
    manifest + payload files under ``persist_dir`` survive), boots a
    fresh server and runs N more rounds. The acceptance read:
    post-restart rounds report cache_warm_loads >= 1 and a
    hit_rate_warm back at the pre-restart level instead of re-paying
    every compulsory miss."""
    from presto_tpu.cache import store as cache_store
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.server.http_server import PrestoTpuServer

    def boot():
        srv = PrestoTpuServer(
            {"tpch": TpchConnector(scale=args.scale)},
            port=0, memory_budget_bytes=1 << 32,
        )
        return srv, f"http://127.0.0.1:{srv.start()}"

    def round_(server):
        full = run_load(server, args.clients, args.duration,
                        args.repeat_frac, cache=not args.no_cache,
                        seed=args.seed, batching=args.batching,
                        batch_wait_ms=args.batch_wait_ms,
                        persist_dir=persist_dir)
        return {k: full[k] for k in _ROUND_KEYS}

    rounds = []
    srv, server = boot()
    try:
        for _ in range(args.restart_after):
            rounds.append(round_(server))
        srv.stop()
        # process-death semantics for the shared store: entries and
        # the persister binding vanish; disk survives
        rc = cache_store.shared_cache_if_exists()
        if rc is not None:
            rc.configure(persist_dir="")
            rc.clear()
        cache_store._shared = None
        srv, server = boot()
        for _ in range(args.restart_after):
            rounds.append(round_(server))
    finally:
        srv.stop()
    n = args.restart_after
    return {
        "mode": "restart",
        "restart_after": n,
        "persist_dir": persist_dir,
        "rounds": rounds,
        "errors": sum(r["errors"] for r in rounds),
        "warm_loads_after_restart": sum(
            r["cache_warm_loads"] for r in rounds[n:]),
        "hit_rate_warm_pre": rounds[n - 1]["hit_rate_warm"],
        "hit_rate_warm_post": rounds[n]["hit_rate_warm"],
    }


# the 3-stage DAG shape every restart-coordinator cycle parks at its
# final drain (all producer stages spooled) — the same query the chaos
# kill-coordinator mode and tests/test_checkpoint.py pin
_RESTART_DAG_QUERY = (
    "select n_name, count(*), sum(top.c_count) from nation join ("
    "  select c_nationkey nk, c_custkey ck, count(o_orderkey) c_count"
    "  from customer left join orders on c_custkey = o_custkey"
    "  group by c_nationkey, c_custkey) top on n_nationkey = top.nk "
    "group by n_name order by n_name"
)


def run_restart_coordinator_bench(args) -> dict:
    """Coordinator-HA mode (ISSUE 20): ``--restart-coordinator N``
    runs N kill/re-attach cycles. Each cycle parks a spooled
    multi-stage query at its final drain (every producer stage
    checkpointed), replaces the coordinator (stop + fresh server on
    the same checkpoint journal), drains the client's persisted
    nextUri against the successor, and then serves a few fresh
    statements. Reports the re-attach success rate, the re-attach
    drain wall (boot-to-last-row, client stopwatch — these are
    N one-shot recoveries, not a histogram population) and the
    post-restart fresh-query wall, each as p50/p99 over cycles."""
    import shutil
    import tempfile

    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.runner import LocalRunner
    from presto_tpu.server.http_server import PrestoTpuServer
    from presto_tpu.server.worker import WorkerServer

    page_rows = 1 << 13
    hdrs = {"X-Presto-Session": "stage_scheduler=true",
            "Content-Type": "text/plain"}

    def post(port, sql):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/statement",
            data=sql.encode(), headers=hdrs)
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read().decode())

    def drain(doc):
        rows = []
        while True:
            if doc.get("error"):
                raise RuntimeError(str(doc["error"]))
            rows.extend(doc.get("data") or [])
            nxt = doc.get("nextUri")
            if not nxt:
                return rows
            time.sleep(0.01)
            with urllib.request.urlopen(nxt, timeout=60) as r:
                doc = json.loads(r.read().decode())

    oracle = LocalRunner({"tpch": TpchConnector(args.scale)},
                         page_rows=page_rows)
    want = sorted(map(repr, map(list, oracle.execute(
        _RESTART_DAG_QUERY).rows)))

    workers = [
        WorkerServer({"tpch": TpchConnector(args.scale)},
                     node_id=f"w{i}", default_catalog="tpch",
                     page_rows=page_rows)
        for i in range(2)
    ]
    uris = [f"http://127.0.0.1:{w.start()}" for w in workers]

    def boot(ckdir):
        srv = PrestoTpuServer(
            {"tpch": TpchConnector(scale=args.scale)}, port=0,
            page_rows=page_rows, worker_uris=uris,
            checkpoint_dir=ckdir)
        srv.start()
        return srv

    n = args.restart_coordinator
    reattached = 0
    errors = 0
    reattach_walls = []
    fresh_walls = []
    try:
        for _ in range(n):
            ckdir = tempfile.mkdtemp(prefix="loadbench_ckpt_")
            park = threading.Event()
            srv = srv2 = None
            try:
                srv = boot(ckdir)

                def hook(sched, _park=park):
                    _park.wait(300)
                    raise RuntimeError(
                        "superseded coordinator: parked root drain")

                srv._dcn._root_hook = hook
                qid = post(srv.port, _RESTART_DAG_QUERY)["id"]
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    rec = srv._journal.pending().get(qid)
                    if rec and rec.get("root") and \
                            rec.get("root_inputs") and \
                            all(str(f) in rec["stages"]
                                for f in rec["root_inputs"]):
                        break
                    time.sleep(0.05)
                else:
                    raise RuntimeError("barriers never journaled")
                q = srv.manager.get(qid)
                if q is not None and q.checkpoint is not None:
                    q.checkpoint.detach()  # dead processes don't write
                srv.stop()

                t0 = time.monotonic()
                srv2 = boot(ckdir)
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{srv2.port}"
                        f"/v1/statement/{qid}/0", timeout=60) as r:
                    doc = json.loads(r.read().decode())
                got = drain(doc)
                reattach_walls.append(
                    (time.monotonic() - t0) * 1000.0)
                ex = srv2._runner.executor
                if (sorted(map(repr, map(list, got))) == want
                        and ex.coordinator_reattaches >= 1):
                    reattached += 1
                else:
                    errors += 1
                # post-restart serving health: fresh statements on the
                # successor, client-stopwatch walls
                for sql in REPEATED_STATEMENTS:
                    t1 = time.monotonic()
                    drain(post(srv2.port, sql))
                    fresh_walls.append(
                        (time.monotonic() - t1) * 1000.0)
            except Exception as e:  # noqa: BLE001 - bench verdict
                errors += 1
                print(f"# restart-coordinator cycle failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
            finally:
                park.set()
                for s in (srv, srv2):
                    if s is not None:
                        s.stop()
                shutil.rmtree(ckdir, ignore_errors=True)
    finally:
        for w in workers:
            w.stop()

    def pct(walls, q):
        if not walls:
            return 0.0
        s = sorted(walls)
        return s[min(int(q * len(s)), len(s) - 1)]

    return {
        "mode": "restart-coordinator",
        "cycles": n,
        "errors": errors,
        "reattach_rate": (reattached / n) if n else 0.0,
        "reattach_p50_ms": round(pct(reattach_walls, 0.50), 1),
        "reattach_p99_ms": round(pct(reattach_walls, 0.99), 1),
        "post_restart_p50_ms": round(pct(fresh_walls, 0.50), 1),
        "post_restart_p99_ms": round(pct(fresh_walls, 0.99), 1),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--server", default=None,
                    help="existing server URL; default boots one "
                         "in-process (tpch sf0.01, concurrent path)")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--repeat-frac", type=float, default=0.8,
                    help="fraction of statements drawn from the "
                         "repeated (cacheable-hit) deck")
    ap.add_argument("--scale", type=float, default=0.01)
    ap.add_argument("--no-cache", action="store_true",
                    help="run the same load without the result cache "
                         "(the A/B baseline)")
    ap.add_argument("--batching", choices=("auto", "true", "false"),
                    default="auto",
                    help="cross_query_batching session knob pinned on "
                         "every client (ISSUE 17); 'auto' rides the "
                         "server default — batched on the concurrent "
                         "path, solo everywhere else")
    ap.add_argument("--warmup", type=float, default=0.0,
                    help="seconds of unmeasured same-deck load before "
                         "the measured window, so compiles settle "
                         "first (steady-state A/B stance)")
    ap.add_argument("--batch-wait-ms", type=int, default=None,
                    help="pin cross_query_batch_wait_ms on every "
                         "client (gather-window sweep knob)")
    ap.add_argument("--smoke", action="store_true",
                    help="bounded CI shape: caps clients/duration so "
                         "the sanitizer leg finishes in seconds while "
                         "still racing every serving-path lock")
    ap.add_argument("--sanitize", action="store_true",
                    help="arm the runtime lock sanitizer over the "
                         "self-hosted server and fail on any "
                         "violation (concurrency soundness gate)")
    ap.add_argument("--append-writers", type=int, default=0,
                    help="mixed STREAMING mode (ISSUE 14): this many "
                         "writer threads append to a stream while "
                         "--clients reader threads refresh a "
                         "registered materialized view incrementally; "
                         "records refresh p50/p99 + the ivm_* "
                         "registry counters")
    ap.add_argument("--rows-per-append", type=int, default=512)
    ap.add_argument("--restart-after", type=int, default=0,
                    help="warm-start mode (ISSUE 19): run this many "
                         "load rounds, restart the self-hosted "
                         "server (shared store torn down; only the "
                         "--persist-dir files survive), run the same "
                         "number again; reports per-round hit rates "
                         "and cache_warm_loads after the restart")
    ap.add_argument("--persist-dir", default=None,
                    help="result_cache_persist_dir for the clients' "
                         "sessions (default: a fresh temp dir when "
                         "--restart-after is set)")
    ap.add_argument("--restart-coordinator", type=int, default=0,
                    help="coordinator-HA mode (ISSUE 20): run this "
                         "many kill/re-attach cycles — each parks a "
                         "spooled multi-stage query at its final "
                         "drain, replaces the coordinator on the "
                         "same checkpoint journal, resumes the "
                         "client's nextUri stream, then serves fresh "
                         "statements; reports reattach_rate and "
                         "re-attach / post-restart p50/p99")
    ap.add_argument("--fleet", type=int, default=0,
                    help="fleet-reuse mode (ISSUE 19): boot this "
                         "many subprocess workers under a DcnRunner "
                         "and run the repeated deck cold, then warm "
                         "— warm rounds serve leaf fragments from "
                         "peers' caches (cache_remote_hits)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    if args.smoke:
        args.clients = min(args.clients, 4)
        args.duration = min(args.duration, 3.0)
        args.warmup = min(args.warmup, 2.0)
        args.scale = min(args.scale, 0.01)

    san = None
    if args.sanitize:
        # arm BEFORE the server (and its module-level locks) exists —
        # instrumentation is a lock-creation-time choice
        from presto_tpu.obs import sanitizer as san

        san.arm()
        san.reset()
        if args.server is not None:
            print("# --sanitize instruments THIS process only; the "
                  "external server runs unsanitized", file=sys.stderr)

    if args.fleet > 0:
        out = run_fleet_bench(args.fleet, args.duration, args.scale,
                              seed=args.seed)
        if san is not None:
            out["sanitizer_violations"] = san.violation_count()
            if out["sanitizer_violations"]:
                print(san.report(), file=sys.stderr)
        print(json.dumps(out, sort_keys=True))
        return 1 if out["errors"] or out.get(
            "sanitizer_violations") else 0

    if args.restart_coordinator > 0:
        out = run_restart_coordinator_bench(args)
        if san is not None:
            out["sanitizer_violations"] = san.violation_count()
            if out["sanitizer_violations"]:
                print(san.report(), file=sys.stderr)
        print(json.dumps(out, sort_keys=True))
        return 1 if out["errors"] or out.get(
            "sanitizer_violations") else 0

    if args.restart_after > 0:
        if args.server is not None:
            print("# --restart-after self-hosts; --server ignored",
                  file=sys.stderr)
        persist_dir = args.persist_dir
        if not persist_dir:
            import tempfile

            persist_dir = tempfile.mkdtemp(prefix="loadbench_rc_")
        out = run_restart_bench(args, persist_dir)
        if san is not None:
            out["sanitizer_violations"] = san.violation_count()
            if out["sanitizer_violations"]:
                print(san.report(), file=sys.stderr)
        print(json.dumps(out, sort_keys=True))
        return 1 if out["errors"] or out.get(
            "sanitizer_violations") else 0

    if args.append_writers > 0:
        out = run_append_load(
            args.append_writers, args.clients, args.duration,
            args.rows_per_append, seed=args.seed,
        )
        if san is not None:
            out["sanitizer_violations"] = san.violation_count()
            if out["sanitizer_violations"]:
                print(san.report(), file=sys.stderr)
        print(json.dumps(out, sort_keys=True))
        return 1 if out["errors"] or out.get(
            "sanitizer_violations") else 0

    srv = None
    server = args.server
    if server is None:
        from presto_tpu.connectors.tpch import TpchConnector
        from presto_tpu.server.http_server import PrestoTpuServer

        # memory arbiter on => the CONCURRENT QueryManager path: each
        # query gets its own runner/executor, all sharing the one
        # result-cache store — exactly the contention this tool exists
        # to exercise
        srv = PrestoTpuServer(
            {"tpch": TpchConnector(scale=args.scale)},
            port=0, memory_budget_bytes=1 << 32,
        )
        port = srv.start()
        server = f"http://127.0.0.1:{port}"
        print(f"# self-hosted server on {server}", file=sys.stderr)
    try:
        out = run_load(server, args.clients, args.duration,
                       args.repeat_frac, cache=not args.no_cache,
                       seed=args.seed, batching=args.batching,
                       warmup_s=args.warmup,
                       batch_wait_ms=args.batch_wait_ms,
                       persist_dir=args.persist_dir)
    finally:
        if srv is not None:
            srv.stop()
    if san is not None:
        out["sanitizer_violations"] = san.violation_count()
        if out["sanitizer_violations"]:
            print(san.report(), file=sys.stderr)
    print(json.dumps(out, sort_keys=True))
    return 1 if out["errors"] or out.get("sanitizer_violations") else 0


if __name__ == "__main__":
    sys.exit(main())
