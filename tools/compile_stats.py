#!/usr/bin/env python3
"""Compile-cost breakdown for one bench rung: cold-vs-warm compile
walls, persistent-cache hit/miss counts, and the distribution of
per-program backend-compile times (presto_tpu/compilecache.py).

Runs the rung twice in one process. The FIRST run shows what a fresh
process pays (persistent-cache hits replace compiles when the cache
dir is warm); the SECOND run certifies the canonicalization contract:
programs_compiled MUST be 0 — same query, same shapes, nothing new to
compile (exec/shapes.py bucket ladder + canonical jit keys).

Usage: compile_stats.py {tpch|tpcds} QID SF [k=v session props...]
Prints one JSON document to stdout.
"""

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from tools._common import configure_jax, make_runner, queries  # noqa: E402


def main() -> int:
    suite, qid, sf = sys.argv[1], int(sys.argv[2]), float(sys.argv[3])
    configure_jax()
    from presto_tpu import compilecache as cc

    runner = make_runner(suite, sf, props=sys.argv[4:])
    sql = queries(suite)[qid]
    plan = runner.plan(sql)
    ex = runner.executor

    out = {
        "suite": suite, "query": qid, "sf": sf,
        "cache_dir": cc.cache_dir(), "runs": [],
    }
    for label in ("cold", "warm"):
        base = cc.snapshot()
        walls_before = len(cc.compile_walls())
        t0 = time.time()
        ex.execute(plan)
        wall = time.time() - t0
        d = cc.delta(base)
        d["label"] = label
        d["wall_s"] = round(wall, 3)
        d["steady_wall_s"] = round(max(wall - d["compile_wall_s"], 0), 3)
        walls = cc.compile_walls()[walls_before:]
        d["per_program_walls_s"] = [
            round(w, 4) for w in sorted(walls, reverse=True)[:20]
        ]
        out["runs"].append(d)
        print(f"# {label}: wall {wall:.2f}s, compiled "
              f"{d['programs_compiled']} programs "
              f"({d['compile_wall_s']}s), "
              f"{d['program_cache_hits']} persistent-cache hits",
              file=sys.stderr)
    warm = out["runs"][1]
    out["canonical_ok"] = (
        warm["programs_compiled"] == 0
        and warm["persistent_cache_misses"] == 0
    )
    print(json.dumps(out, indent=1))
    return 0 if out["canonical_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
