#!/usr/bin/env python
"""xfercheck: the whole-engine static host<->device transfer audit
(ISSUE 12) — the build-time half of the layer whose runtime half is
presto_tpu/exec/xfer.py (the metered choke points).

Reference: the Java engine's data plane never leaves the operator tier
— Pages cross a boundary only at the serialized exchange, and that
boundary is one audited code path. The TPU build crosses host<->HBM in
many more places (device_put/device_get, numpy coercions of device
values, sync fences), so this pass applies the registry discipline of
QUERY_COUNTERS (PR 6) and LOCK_REGISTRY (PR 11) to transfers:

  xfer-registry  the crossing inventory. Every transfer-primitive call
                 site (attributed to its enclosing top-level function,
                 nested defs/closures included — the concheck
                 convention) must be declared in
                 exec/xfer.TRANSFER_REGISTRY with a direction
                 (h2d / d2h / h2d+d2h) that COVERS the primitives
                 observed at the site, a plane (data / control), and a
                 non-empty one-line justification. Stale registry rows
                 fail like stale QUERY_COUNTERS entries.
  xfer-plane     plane honesty: a `data`-plane row must name a site in
                 a module listed in exec/xfer.DATA_PLANE_MODULES (the
                 per-page query path). `control` rows may live
                 anywhere (setup code exists inside query modules
                 too).
  xfer-choke     routing: inside DATA_PLANE_MODULES, RAW primitives
                 (jax.device_put / jax.device_get / block_until_ready
                 / numpy coercions / .item() / scalar casts of device
                 values) must be replaced by the metered choke points
                 xfer.to_host / xfer.to_device / xfer.np_host — an
                 unrouted crossing is invisible to the transfer
                 counters, spans, and the bench ledger. A deliberate
                 exception carries `# xfercheck: raw-ok - <why>` on
                 the call line (or the line above). exec/xfer.py
                 itself is the one exempt module (it IS the routing).

Primitive recognition, chosen safe-but-quiet like concheck's:
`np.asarray`/`np.array` count only when the argument is not an
obvious host construction (list/tuple/dict/set/comprehension/literal
or a list()/sorted()/range()-style call) — a LUT built from Python
values never crosses. Bare float()/int()/bool() casts count only over
a `*.num_rows()` call (the engine's known device-scalar producer);
the general scalar-cast case is statically unresolvable and is
covered dynamically by routing through the choke points.
`jnp.asarray`/`jnp.array` of a non-literal argument counts as an h2d
primitive (ISSUE 13 closed this gap — a jnp coercion of a HOST array
is an undeclared device_put): sites inside traced kernel builders
escape with raw-ok and declare plane `control` (trace-time constant
embedding), driver-level sites route through the choke points like
any other crossing. `def __array__` on an engine class would be an
implicit coercion hook and is flagged wherever it appears.

Run: `python tools/xfercheck.py` (exit 1 on findings); tier-1 runs the
same checks via tests/test_xfercheck.py, and tools/ci_static.sh runs
them as the fourth static gate next to lint + concheck + plan_audit.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # direct `python tools/xfercheck.py` runs
    sys.path.insert(0, REPO)

from tools.concheck import _modrel  # noqa: E402
from tools.lint import (  # noqa: E402
    Finding,
    _dotted,
    _parse,
    _py_files,
    _rel,
)

_RAW_OK = re.compile(r"#\s*xfercheck:\s*raw-ok\s*-\s*\S")

# the metering layer itself: the only module whose raw primitives are
# the point rather than a leak
_CHOKE_MODULE = "exec.xfer"

_NP_ROOTS = ("np", "numpy", "_np", "onp")
# jnp.asarray/jnp.array of a HOST array is an h2d staging the gate
# must see (ISSUE 13 closed this gap): inside traced code it is
# trace-time embedding (sites escape with raw-ok / declare plane
# `control`), but at driver level it is a real, unmetered device_put
_JNP_ROOTS = ("jnp",)
_HOST_CALL_TAILS = ("list", "sorted", "range", "len", "tuple", "dict",
                    "set", "zeros", "ones", "empty", "arange", "full")
_CHOKE_TAILS = {
    "to_host": "d2h",
    "to_device": "h2d",
    "np_host": "d2h",
}
_CHOKE_ROOTS = ("xfer", "XF")

_DIRECTIONS = ("h2d", "d2h", "h2d+d2h")
_PLANES = ("data", "control")


def _host_literal(node: ast.AST) -> bool:
    """True when the expression is an obvious HOST construction that a
    numpy coercion cannot turn into a device transfer."""
    if isinstance(node, (ast.List, ast.Tuple, ast.Set, ast.Dict,
                         ast.ListComp, ast.SetComp, ast.DictComp,
                         ast.GeneratorExp, ast.Constant)):
        return True
    if isinstance(node, ast.BinOp):
        if isinstance(node.op, ast.Mult) and (
                isinstance(node.left, (ast.List, ast.Tuple))
                or isinstance(node.right, (ast.List, ast.Tuple))):
            return True  # [x] * n replication is a host construction
        return _host_literal(node.left) and _host_literal(node.right)
    if isinstance(node, ast.BoolOp):
        return all(_host_literal(v) for v in node.values)
    if isinstance(node, ast.Starred):
        return _host_literal(node.value)
    if isinstance(node, ast.Call):
        tail = (_dotted(node.func) or "").rsplit(".", 1)[-1]
        return tail in _HOST_CALL_TAILS
    return False


def _primitive_of(call: ast.Call) -> Optional[Tuple[str, bool]]:
    """(direction-kind, raw?) when ``call`` is a transfer primitive or
    a choke-point call; None otherwise."""
    dotted = _dotted(call.func)
    if not dotted:
        return None
    root = dotted.split(".", 1)[0]
    tail = dotted.rsplit(".", 1)[-1]
    if tail in _CHOKE_TAILS and root in _CHOKE_ROOTS:
        return _CHOKE_TAILS[tail], False
    if tail == "device_put":
        return "h2d", True
    if tail == "device_get":
        return "d2h", True
    if tail == "block_until_ready":
        return "d2h", True
    if tail == "item" and isinstance(call.func, ast.Attribute) and \
            not call.args and not call.keywords:
        return "d2h", True
    if tail in ("asarray", "array") and root in _NP_ROOTS:
        if call.args and not _host_literal(call.args[0]):
            return "d2h", True
        return None
    if tail in ("asarray", "array") and root in _JNP_ROOTS:
        if call.args and not _host_literal(call.args[0]):
            return "h2d", True
        return None
    if dotted in ("float", "int", "bool") and len(call.args) == 1:
        a = call.args[0]
        if isinstance(a, ast.Call) and \
                (_dotted(a.func) or "").endswith("num_rows"):
            return "d2h", True
    return None


class _Site:
    """One registry-granularity site: a top-level function (or the
    bare module) holding >=1 primitive call."""

    def __init__(self, qual: str, modrel: str, rel: str):
        self.qual = qual
        self.modrel = modrel
        self.rel = rel
        self.kinds: Set[str] = set()
        # (line, kind, raw, escaped)
        self.calls: List[Tuple[int, str, bool, bool]] = []


def collect(paths: List[str]) -> Dict[str, _Site]:
    sites: Dict[str, _Site] = {}
    for path in paths:
        modrel = _modrel(path)
        rel = _rel(path)
        tree, lines = _parse(path)

        def escaped(line: int) -> bool:
            ctx = "\n".join(lines[max(line - 2, 0):line])
            return bool(_RAW_OK.search(ctx))

        def note(qual: str, node: ast.Call) -> None:
            prim = _primitive_of(node)
            if prim is None:
                return
            kind, raw = prim
            site = sites.setdefault(qual, _Site(qual, modrel, rel))
            site.kinds.add(kind)
            site.calls.append((node.lineno, kind, raw,
                               escaped(node.lineno)))

        def walk(node: ast.AST, cls: Optional[str],
                 fn_qual: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    # classes under a function stay attributed to it
                    walk(child, child.name if fn_qual is None else cls,
                         fn_qual)
                    continue
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    if child.name == "__array__" and cls is not None:
                        # an implicit-coercion hook IS a transfer site
                        q = f"{modrel}.{cls}.__array__"
                        site = sites.setdefault(
                            q, _Site(q, modrel, rel))
                        site.kinds.add("d2h")
                        site.calls.append(
                            (child.lineno, "d2h", True,
                             escaped(child.lineno)))
                    if fn_qual is None:
                        q = (f"{modrel}."
                             f"{cls + '.' if cls else ''}{child.name}")
                        walk(child, cls, q)
                    else:  # nested def: attribute to the enclosing fn
                        walk(child, cls, fn_qual)
                    continue
                if isinstance(child, ast.Call):
                    note(fn_qual or modrel, child)
                walk(child, cls, fn_qual)

        walk(tree, None, None)
    return sites


def check_sites(sites: Dict[str, _Site], registry, data_modules,
                full_sweep: bool) -> List[Finding]:
    out: List[Finding] = []
    for qual in sorted(sites):
        site = sites[qual]
        line = site.calls[0][0]
        entry = registry.get(qual)
        if entry is None:
            prims = ", ".join(sorted({k for _, k, _, _ in site.calls}))
            out.append(Finding(
                "xfer-registry", site.rel, line,
                f"transfer site {qual!r} ({prims}) is not declared in "
                f"exec/xfer.TRANSFER_REGISTRY — declare direction, "
                f"plane (data/control), and a one-line justification "
                f"(the QUERY_COUNTERS discipline applied to "
                f"host<->device crossings)"))
        else:
            direction, plane, why = (tuple(entry) + ("", "", ""))[:3]
            if direction not in _DIRECTIONS or plane not in _PLANES \
                    or not str(why).strip():
                out.append(Finding(
                    "xfer-registry", site.rel, line,
                    f"registry row for {qual!r} is malformed — need "
                    f"(direction in {_DIRECTIONS}, plane in "
                    f"{_PLANES}, non-empty justification), got "
                    f"{entry!r}"))
            else:
                covered = (set(direction.split("+"))
                           if direction != "h2d+d2h"
                           else {"h2d", "d2h"})
                # escaped raw calls are asserted non-crossings (or
                # deliberately raw) — only unescaped primitives must
                # agree with the declared direction
                live = {k for _, k, _, esc in site.calls if not esc}
                missing = live - covered
                if missing:
                    out.append(Finding(
                        "xfer-registry", site.rel, line,
                        f"registry row for {qual!r} declares "
                        f"direction {direction!r} but the site also "
                        f"crosses {'/'.join(sorted(missing))} — "
                        f"declare the direction that covers every "
                        f"primitive at the site"))
                if plane == "data" and site.modrel not in data_modules:
                    out.append(Finding(
                        "xfer-plane", site.rel, line,
                        f"{qual!r} is declared plane='data' but "
                        f"module {site.modrel!r} is not in "
                        f"exec/xfer.DATA_PLANE_MODULES — data-plane "
                        f"crossings live on the per-page query path; "
                        f"reclassify as 'control' or add the module "
                        f"to the data plane deliberately"))
        if site.modrel in data_modules and \
                site.modrel != _CHOKE_MODULE:
            for cline, kind, raw, esc in site.calls:
                if raw and not esc:
                    out.append(Finding(
                        "xfer-choke", site.rel, cline,
                        f"raw {kind} primitive in data-plane module "
                        f"{site.modrel!r} — route through "
                        f"xfer.to_host/to_device/np_host so the "
                        f"crossing is metered (counters, spans, bench "
                        f"ledger), or annotate "
                        f"`# xfercheck: raw-ok - <why>`"))
    if full_sweep:
        for qual in sorted(set(registry) - set(sites)):
            out.append(Finding(
                "xfer-registry", "presto_tpu/exec/xfer.py", 1,
                f"TRANSFER_REGISTRY declares {qual!r} but no transfer "
                f"primitive exists at that site (stale entry?)"))
    return out


def run_xfercheck(paths: Optional[List[str]] = None, registry=None,
                  data_modules=None) -> List[Finding]:
    full = paths is None
    if paths is None:
        paths = _py_files("presto_tpu")
    if registry is None or data_modules is None:
        from presto_tpu.exec import xfer as XFER

        registry = (XFER.TRANSFER_REGISTRY if registry is None
                    else registry)
        data_modules = (XFER.DATA_PLANE_MODULES if data_modules is None
                        else data_modules)
    sites = collect(paths)
    return check_sites(sites, registry, data_modules, full)


def main() -> int:
    import time

    t0 = time.monotonic()
    findings = run_xfercheck()
    for f in findings:
        print(f)
    nfiles = len(_py_files("presto_tpu"))
    print(f"# xfercheck: {len(findings)} finding(s) across {nfiles} "
          f"files in {time.monotonic() - t0:.1f}s", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
