"""Shared setup for the bench tools: one place for the sys.path hack,
the persistent compile cache, and session-property application (mirrors
LocalRunner.execute's session->executor wiring so a tool driving the
executor directly behaves like the engine would)."""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def configure_jax():
    import jax

    from presto_tpu import compilecache

    # min_compile_secs=0: cache EVERY program — retry-ladder rungs and
    # small per-page kernels matter as much as the big fused programs
    # when the alternative is the remote axon compiler (compilecache.py)
    compilecache.enable_persistent_cache(
        os.environ.get(
            "PRESTO_TPU_COMPILE_CACHE_DIR",
            os.path.join(REPO, ".jax_cache"),
        )
    )
    return jax


def make_runner(suite: str, sf: float, props=(), cached: bool = False):
    """LocalRunner over the named generator suite with k=v session
    properties applied to both the session and the live executor.
    cached=True wraps the connector in the device-resident page cache
    (scan = HBM read after the first streaming, the memory-connector
    analog) for generate-vs-query attribution."""
    from presto_tpu.connectors.cached import CachingConnector
    from presto_tpu.connectors.tpcds import TpcdsConnector
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.runner import LocalRunner

    cls = TpchConnector if suite == "tpch" else TpcdsConnector
    conn = cls(scale=sf)
    if cached:
        conn = CachingConnector(conn)
    runner = LocalRunner({suite: conn}, default_catalog=suite)
    for kv in props:
        k, v = kv.split("=", 1)
        runner.session.set(k, v)
    # session -> executor for direct executor drivers (bisect_rung
    # times ex.pages without execute())
    runner.apply_session()
    return runner


def queries(suite: str):
    if suite == "tpch":
        from tests.tpch_queries import QUERIES

        return QUERIES
    from tests.tpcds_queries import QUERIES

    return QUERIES
