"""Validate ONE bench rung in a fresh process: run the query once
end-to-end (decode included), print a single JSON line for bench.py.

Why a subprocess: on the axon runtime any device->host read degrades the
whole process (and some transfers are pathologically slow or hang), so
bench.py keeps its timing child D2H-clean and farms decoding out here,
one bounded child per rung — a slow or faulting rung then cannot poison
the other rungs' validation (observed 2026-07-30: a single >=4M-row
buffer hang lost a full ladder's decode phase).

Usage: validate_rung.py {tpch|tpcds} QID SF [k=v session props...]
"""

import json
import os
import sys
import time
import zlib

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from tools._common import configure_jax, make_runner, queries  # noqa: E402


def main() -> int:
    suite, qid, sf = sys.argv[1], int(sys.argv[2]), float(sys.argv[3])
    configure_jax()
    runner = make_runner(suite, sf, props=sys.argv[4:])
    t0 = time.time()
    result = runner.execute(queries(suite)[qid])
    wall = time.time() - t0
    # order-insensitive row checksum (verifier-style) so runs can be
    # compared across processes/rounds without shipping rows
    csum = 0
    for row in result.rows:
        csum = (csum + zlib.crc32(repr(row).encode())) & 0xFFFFFFFF
    print(json.dumps({
        "rows": len(result.rows),
        "wall_with_decode_s": round(wall, 2),
        "checksum_crc32": csum,
        "capacity_boost": runner.executor._capacity_boost,
        "pallas_joins_used": runner.executor.pallas_joins_used,
        "head": [str(v)[:24] for v in (result.rows[0] if result.rows
                                       else [])],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
