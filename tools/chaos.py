"""Chaos harness: a small TPC-H query matrix under randomized fault
schedules (delay / drop / kill / submit-drop, seeded RNG) against the
fault-tolerant DCN slice (dist/dcn.py task retry + query deadlines)
AND the general stage-DAG scheduler (dist/scheduler.py spooled
exchanges + non-leaf replay).

Every iteration picks a query and a fault mode, applies the fault to a
random worker via the runtime POST /v1/fault surface, executes through
a DcnRunner with task_retry_attempts enabled, and compares the rows
against a single-process oracle computed once up front. Killed workers
reboot on the SAME port between iterations (the coordinator's excluded
set re-admits them on a fresh ping — the node-rejoin model). Exits
nonzero on ANY wrong result, unexpected error, or hang past the query
deadline.

The "dag" query is a 3-stage shape the legacy cuts cannot distribute
(left join under an aggregation under a join) and runs through the
stage scheduler; the kill-nonleaf mode pins the ISSUE-7 recovery
contract — a worker killed while serving spool fetches mid-DAG must
recover via spooled NON-LEAF replay (`--mode kill-nonleaf` exits
nonzero if no nonleaf_replays were recorded across the run).

``--sanitize`` (ISSUE 11) arms the runtime lock sanitizer
(presto_tpu/obs/sanitizer.py) in the coordinator AND every worker
subprocess (via the environment), so randomized fault schedules also
race the instrumented locks; the run fails if any process observed a
lock-order inversion or unlocked shared-attr write (workers report
their count on /v1/info).

Usage: chaos.py [--iterations 20] [--seed 0] [--scale 0.01]
                [--workers 2] [--deadline-ms 180000]
                [--mode kill-nonleaf] [--sanitize]
"""

import argparse
import collections
import json
import os
import random
import subprocess
import sys
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PAGE_ROWS = 1 << 13
FAULT_KEYS = (
    "FAULT_DELAY_MS", "FAULT_DROP_EVERY", "FAULT_KILL_AFTER_FETCHES",
    "FAULT_SUBMIT_DROP_EVERY", "FAULT_DEVICE_OOM",
    "FAULT_TASK_EXEC_DELAY_MS", "FAULT_SPOOL_CORRUPT_EVERY",
    "FAULT_COORD_STALL_MS",
)
FAULT_MODES = ("none", "delay", "drop", "kill", "submit-drop",
               "kill-nonleaf", "corrupt")
# kill-coordinator is not a per-iteration worker fault: it SIGKILLs
# the coordinator subprocess mid-query and re-attaches on a successor
# (run_kill_coordinator below), so it is --mode-only, never random
ALL_MODES = FAULT_MODES + ("kill-coordinator",)

# the 3-stage DAG shape (left join -> hash agg -> join -> agg) the
# legacy agg/union cuts fall back local on; the stage scheduler
# distributes it and spools every exchange
DAG_QUERY = (
    "select n_name, count(*), sum(top.c_count) from nation join ("
    "  select c_nationkey nk, c_custkey ck, count(o_orderkey) c_count"
    "  from customer left join orders on c_custkey = o_custkey"
    "  group by c_nationkey, c_custkey) top on n_nationkey = top.nk "
    "group by n_name order by n_name"
)


def query_matrix():
    from tests.tpch_queries import QUERIES

    return {
        "q1": QUERIES[1],
        "q6": QUERIES[6],
        "q3": QUERIES[3],
        "approx": (
            "select o_orderpriority, approx_distinct(o_custkey), "
            "sum(o_totalprice) from orders group by o_orderpriority"
        ),
        "dag": DAG_QUERY,
    }


def rows_equal(a, b) -> bool:
    return collections.Counter(map(repr, a)) == collections.Counter(
        map(repr, b)
    )


class Worker:
    """One subprocess worker, rebootable on a sticky port."""

    def __init__(self, scale: float):
        self.scale = scale
        self.port = 0  # 0 = OS-assigned on first boot, sticky after
        self.proc = None
        self.uri = ""

    def boot(self) -> None:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        for k in FAULT_KEYS:
            env.pop(k, None)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "presto_tpu.server.worker",
             "--port", str(self.port), "--suite", "tpch",
             "--scale", str(self.scale),
             "--page-rows", str(PAGE_ROWS)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=env, cwd=REPO, text=True,
        )
        line = self.proc.stdout.readline()
        info = json.loads(line)
        self.port = info["port"]  # sticky: reboots keep the uri stable
        self.uri = f"http://127.0.0.1:{self.port}"

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def ensure(self) -> bool:
        """Reboot if dead; True when a reboot happened."""
        if self.alive():
            return False
        if self.proc is not None:
            self.proc.wait(timeout=10)
        # the killed process's port lingers in TIME_WAIT briefly;
        # retry the bind a few times before giving up
        for attempt in range(10):
            try:
                self.boot()
                return True
            except (json.JSONDecodeError, ValueError):
                time.sleep(0.3 * (attempt + 1))
        raise RuntimeError(f"worker on port {self.port} failed to boot")

    def set_fault(self, config) -> None:
        req = urllib.request.Request(
            f"{self.uri}/v1/fault",
            data=json.dumps(config).encode(),
            headers={"Content-Type": "application/json"},
        )
        urllib.request.urlopen(req, timeout=5).close()

    def kill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)


class Coordinator:
    """One coordinator subprocess over a worker fleet + a durable
    checkpoint journal — the kill-coordinator mode's victim. A
    ``stall_ms`` boot parks every stage-DAG query between the last
    stage barrier and the final drain (FAULT_COORD_STALL_MS,
    dist/scheduler._pre_root_hook): the deterministic window where
    every producer spool is live and nothing was consumed."""

    def __init__(self, scale: float, worker_uris, ckdir: str,
                 stall_ms: int = 0):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        for k in FAULT_KEYS:
            env.pop(k, None)
        if stall_ms:
            env["FAULT_COORD_STALL_MS"] = str(stall_ms)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "presto_tpu.server.http_server",
             "--port", "0", "--scale", str(scale),
             "--page-rows", str(PAGE_ROWS),
             "--workers", ",".join(worker_uris),
             "--checkpoint-dir", ckdir],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=env, cwd=REPO, text=True,
        )
        self.port = json.loads(self.proc.stdout.readline())["port"]
        self.uri = f"http://127.0.0.1:{self.port}"

    def submit(self, sql: str) -> dict:
        req = urllib.request.Request(
            f"{self.uri}/v1/statement", data=sql.encode(),
            headers={"X-Presto-Session": "stage_scheduler=true",
                     "Content-Type": "text/plain"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read().decode())

    def metric(self, name: str) -> float:
        """One counter off /metrics (Prometheus text)."""
        with urllib.request.urlopen(f"{self.uri}/metrics",
                                    timeout=10) as r:
            for ln in r.read().decode().splitlines():
                if ln.startswith(f"presto_tpu_{name}"):
                    return float(ln.rsplit(None, 1)[1])
        return 0.0

    def sanitizer_violations(self) -> int:
        try:
            with urllib.request.urlopen(f"{self.uri}/v1/info",
                                        timeout=5) as r:
                return int(json.load(r).get(
                    "sanitizerViolations", 0) or 0)
        except (OSError, ValueError):
            return 0

    def sigkill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)


def _wait_for_journal_barriers(ckdir: str, qid: str,
                               timeout: float = 60.0) -> None:
    """Poll the journal directory (read-only, from the parent) until
    ``qid`` has its root fragment + every feeding stage checkpointed —
    the coordinator is then inside its stall window."""
    from presto_tpu.cache.persist import read_manifest_doc

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            doc = read_manifest_doc(ckdir, stem="journal")
        except ValueError:
            doc = None
        rec = ((doc or {}).get("entries") or {}).get(qid)
        if rec and rec.get("root") and rec.get("root_inputs") and \
                all(str(f) in rec.get("stages", {})
                    for f in rec["root_inputs"]):
            return
        time.sleep(0.05)
    raise RuntimeError(
        f"query {qid}: stage/root barriers never reached the journal")


def _drain_statement(uri: str, qid: str, deadline_s: float):
    """Restart-tolerant protocol drain from token 0: the successor
    coordinator may still be re-attaching when the first poll lands,
    so transient refusals retry until the deadline."""
    rows = []
    url = f"{uri}/v1/statement/{qid}/0"
    deadline = time.monotonic() + deadline_s
    while True:
        if time.monotonic() > deadline:
            raise RuntimeError(f"query {qid}: drain past deadline")
        try:
            with urllib.request.urlopen(url, timeout=30) as r:
                doc = json.loads(r.read().decode())
        except (OSError, ValueError):
            time.sleep(0.2)
            continue
        if doc.get("error"):
            raise RuntimeError(str(doc["error"]))
        rows.extend(doc.get("data") or [])
        nxt = doc.get("nextUri")
        if not nxt:
            return rows
        url = nxt
        time.sleep(0.02)


def run_kill_coordinator(args, san) -> int:
    """The ISSUE-20 acceptance loop: a multi-stage distributed query
    with every producer stage spooled survives the coordinator being
    SIGKILLed mid-query — the successor process on the same
    --checkpoint-dir re-attaches, the client's nextUri stream resumes,
    rows equal the single-process oracle, coordinator_reattaches >= 1.
    Exits nonzero on any wrong result, error, hang, or (with
    --sanitize) any sanitizer violation in any process."""
    import shutil
    import tempfile

    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.runner import LocalRunner

    print(f"# oracle: single-process run at SF{args.scale}", flush=True)
    single = LocalRunner({"tpch": TpchConnector(args.scale)},
                         page_rows=PAGE_ROWS)
    # tuples -> lists: protocol rows arrive as JSON arrays and
    # rows_equal compares reprs
    want = [list(r) for r in single.execute(DAG_QUERY).rows]

    workers = [Worker(args.scale) for _ in range(args.workers)]
    for w in workers:
        w.boot()
    uris = [w.uri for w in workers]
    failures = 0
    violations = 0
    reattaches_total = 0
    try:
        for i in range(args.iterations):
            for w in workers:
                w.ensure()
            ckdir = tempfile.mkdtemp(prefix="presto-tpu-ckpt-")
            status = "ok"
            t0 = time.monotonic()
            coord = succ = None
            try:
                # boot A stalled wide open, submit, wait for the
                # barriers, SIGKILL mid-stall: every producer spool is
                # live, nothing consumed, the journal has it all
                coord = Coordinator(args.scale, uris, ckdir,
                                    stall_ms=args.deadline_ms)
                qid = coord.submit(DAG_QUERY)["id"]
                _wait_for_journal_barriers(ckdir, qid)
                coord.sigkill()
                # boot B on the same journal; the client re-polls its
                # persisted nextUri against the successor
                succ = Coordinator(args.scale, uris, ckdir)
                got = _drain_statement(
                    succ.uri, qid, args.deadline_ms / 1000.0)
                got = [list(r) for r in got]
                if not rows_equal(got, want):
                    status = "WRONG RESULT"
                    failures += 1
                re_n = succ.metric("coordinator_reattaches")
                reattaches_total += int(re_n)
                if re_n < 1:
                    status = "NO REATTACH RECORDED"
                    failures += 1
                if san is not None:
                    violations += succ.sanitizer_violations()
            except Exception as e:  # noqa: BLE001 - harness verdict
                status = f"ERROR {type(e).__name__}: {e}"
                failures += 1
            finally:
                for c in (coord, succ):
                    if c is not None:
                        c.sigkill()
                shutil.rmtree(ckdir, ignore_errors=True)
            wall = time.monotonic() - t0
            if wall * 1000 > args.deadline_ms:
                status += " + HANG past deadline"
                failures += 1
            print(f"iter {i:02d} q=dag    fault=kill-coordinator "
                  f"wall={wall:6.2f}s: {status}", flush=True)
    finally:
        if san is not None:
            import http.client

            for w in workers:
                if not w.alive():
                    continue
                try:
                    with urllib.request.urlopen(
                            f"{w.uri}/v1/info", timeout=5) as r:
                        violations += int(json.load(r).get(
                            "sanitizerViolations", 0) or 0)
                except (OSError, ValueError,
                        http.client.HTTPException):
                    pass
            if violations:
                print(f"# chaos: {violations} sanitizer violation(s) "
                      f"across coordinator/worker processes")
                failures += violations
            if san.violation_count():
                print(san.report())
                failures += san.violation_count()
        for w in workers:
            w.kill()
    if reattaches_total < args.iterations:
        print(f"# chaos: only {reattaches_total} re-attaches across "
              f"{args.iterations} kill-coordinator iterations")
    print(f"# chaos: {args.iterations} iterations, {failures} failures,"
          f" coordinator_reattaches={reattaches_total}")
    return 1 if failures else 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iterations", type=int, default=20)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--scale", type=float, default=0.01)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--deadline-ms", type=int, default=180_000)
    ap.add_argument("--mode", choices=ALL_MODES, default=None,
                    help="pin every iteration to one fault mode "
                    "(kill-nonleaf additionally requires at least "
                    "one nonleaf_replay across the run; "
                    "kill-coordinator SIGKILLs the coordinator "
                    "subprocess mid-query and re-attaches on a "
                    "successor over the same checkpoint journal)")
    ap.add_argument("--sanitize", action="store_true",
                    help="arm the runtime lock sanitizer in the "
                    "coordinator and every worker; fail on any "
                    "observed violation")
    args = ap.parse_args()

    san = None
    if args.sanitize:
        # before ANY presto_tpu import creates a lock, here and (via
        # the inherited environment) in every worker subprocess
        os.environ["PRESTO_TPU_LOCK_SANITIZER"] = "1"
        from presto_tpu.obs import sanitizer as san

        san.arm()
        san.reset()

    if args.mode == "kill-coordinator":
        return run_kill_coordinator(args, san)

    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.dist.dcn import DcnRunner
    from presto_tpu.runner import LocalRunner

    rng = random.Random(args.seed)
    matrix = query_matrix()

    print(f"# oracle: single-process run at SF{args.scale}", flush=True)
    single = LocalRunner({"tpch": TpchConnector(args.scale)},
                         page_rows=PAGE_ROWS)
    want = {name: single.execute(sql).rows
            for name, sql in matrix.items()}

    workers = [Worker(args.scale) for _ in range(args.workers)]
    for w in workers:
        w.boot()
    coord = DcnRunner(
        {"tpch": TpchConnector(args.scale)},
        [w.uri for w in workers],
        default_catalog="tpch", page_rows=PAGE_ROWS,
        session_props={
            "task_retry_attempts": 2,
            "retry_backoff_ms": 50,
            "query_max_run_time": args.deadline_ms,
            # the dag query engages the stage scheduler via the auto
            # gate (the legacy cuts cannot distribute its shape)
            "agg_gather_capacity": 64,
        },
    )
    ex = coord.runner.executor

    failures = 0
    worker_violations = 0
    _seen_violations = {}  # worker -> count at last successful poll

    def poll_worker_violations() -> None:
        """Accumulate sanitizer violations from live workers' /v1/info.
        A killed worker's in-process list dies with it, so this runs
        EVERY iteration (before the next fault schedule can kill
        anyone) — a kill loses at most one iteration's window, not the
        whole run's. Per-worker deltas: a count that went DOWN means
        the worker rebooted (fresh process), so the new count adds in
        full instead of being masked by the old high-water mark."""
        nonlocal worker_violations
        import http.client

        for w in workers:
            if not w.alive():
                continue
            try:
                with urllib.request.urlopen(
                        f"{w.uri}/v1/info", timeout=5) as r:
                    n = int(json.load(r).get(
                        "sanitizerViolations", 0) or 0)
            except (OSError, ValueError, http.client.HTTPException):
                continue  # dying mid-response: retry next iteration
            last = _seen_violations.get(w, 0)
            worker_violations += n - last if n >= last else n
            _seen_violations[w] = n

    try:
        for i in range(args.iterations):
            mode = args.mode or rng.choice(FAULT_MODES)
            # kill-during-non-leaf-stage schedule: the victim dies
            # while serving spool fetches mid-DAG — recovery must come
            # from spooled replay, not leaf re-generation alone
            qname = ("dag" if mode == "kill-nonleaf"
                     else rng.choice(sorted(matrix)))
            for w in workers:
                w.ensure()
            if san is not None:
                poll_worker_violations()
            victim = rng.choice(workers)
            config = {
                "none": {},
                "delay": {"FAULT_DELAY_MS": rng.choice((10, 30, 60))},
                "drop": {"FAULT_DROP_EVERY": rng.choice((2, 3))},
                "kill": {"FAULT_KILL_AFTER_FETCHES":
                         rng.choice((1, 2))},
                "submit-drop": {"FAULT_SUBMIT_DROP_EVERY": 2},
                "kill-nonleaf": {"FAULT_KILL_AFTER_FETCHES":
                                 rng.choice((1, 2))},
                # sparse wire bit-rot: every nth served results body
                # flips one bit; the PR-16 PageWireError path must
                # absorb it via bounded same-token re-fetches
                "corrupt": {"FAULT_SPOOL_CORRUPT_EVERY":
                            rng.choice((5, 9))},
            }[mode]
            for w in workers:
                w.set_fault(config if w is victim else {})
            retries0, excl0 = ex.task_retries, ex.workers_excluded
            nonleaf0 = ex.nonleaf_replays
            t0 = time.monotonic()
            status = "ok"
            try:
                got = coord.execute(matrix[qname])
                if not rows_equal(got, want[qname]):
                    status = "WRONG RESULT"
                    failures += 1
            except Exception as e:  # noqa: BLE001 - harness verdict
                status = f"ERROR {type(e).__name__}: {e}"
                failures += 1
            wall = time.monotonic() - t0
            if wall * 1000 > args.deadline_ms:
                status += " + HANG past deadline"
                failures += 1
            print(f"iter {i:02d} q={qname:<6} fault={mode:<12} "
                  f"wall={wall:6.2f}s task_retries="
                  f"+{ex.task_retries - retries0} excluded="
                  f"+{ex.workers_excluded - excl0} nonleaf="
                  f"+{ex.nonleaf_replays - nonleaf0} dist="
                  f"{coord.last_distribution}: {status}", flush=True)
    finally:
        if san is not None:
            # final poll before teardown picks up the last iteration's
            # window (the per-iteration polls covered everything else)
            poll_worker_violations()
            if worker_violations:
                print(f"# chaos: workers recorded {worker_violations} "
                      f"sanitizer violation(s) across the run")
                failures += worker_violations
            if san.violation_count():
                print(san.report())
                failures += san.violation_count()
        coord.close()
        for w in workers:
            w.kill()
    if args.mode == "kill-nonleaf" and ex.nonleaf_replays == 0:
        print("# chaos: kill-nonleaf run recorded ZERO nonleaf_replays"
              " — the spooled-replay path was never exercised")
        failures += 1
    print(f"# chaos: {args.iterations} iterations, {failures} failures,"
          f" task_retries={ex.task_retries} "
          f"workers_excluded={ex.workers_excluded} "
          f"nonleaf_replays={ex.nonleaf_replays} "
          f"release_skips={coord.release_skips}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
