"""Device-memory governor (exec/membudget.py): plan-time HBM budget
accounting + chunked pipeline rewrites.

Reference: presto-main memory/MemoryPool + the spill decisions made
under memory pressure — except the TPU translation decides BEFORE
compile: every buffer capacity rides the shapes.py ladder, so a
pipeline's footprint is static. These tests force tiny artificial
budgets (and fault lines) at SF0.01 so the chunked rewrites engage on
CPU, and pin (a) sqlite-oracle / default-budget parity — chunked
execution must be exactly the same answer — and (b) the
memory_chunked_pipelines / peak_device_bytes observability contract.
"""

import collections

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec import membudget as MB
from presto_tpu.exec import shapes as SH
from presto_tpu.runner import LocalRunner


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(0.01)


@pytest.fixture(scope="module")
def base(conn):
    return LocalRunner({"tpch": conn}, page_rows=1 << 13)


def _rows_equal(a, b):
    return collections.Counter(map(repr, a)) == collections.Counter(
        map(repr, b)
    )


JOIN_Q = (
    "select o_orderkey, sum(l_extendedprice), count(*) "
    "from orders, lineitem where o_orderkey = l_orderkey "
    "group by o_orderkey order by 2 desc, 1 limit 7"
)
SCAN_AGG_Q = (
    "select l_returnflag, l_linestatus, sum(l_quantity), "
    "sum(l_extendedprice), count(*) from lineitem "
    "where l_shipdate <= date '1998-09-02' "
    "group by l_returnflag, l_linestatus order by 1, 2"
)


# ------------------------------------------------------------- model
def test_resolve_budget_cpu_is_generous():
    # auto on CPU: tier-1 behavior must not change without a forced
    # tiny budget
    assert MB.resolve_budget(0, "cpu") == MB.CPU_BUDGET
    assert MB.resolve_budget(12345, "cpu") == 12345
    assert MB.resolve_budget(12345, "tpu") == 12345


def test_rows_cap_on_ladder():
    cap = MB.rows_cap(100, 1 << 20, None, 4)  # 256 KiB share / 100 B
    assert cap is not None
    assert cap & (cap - 1) == 0  # power of two (rounded DOWN)
    assert cap * 100 <= (1 << 20) // 4
    # fault line wins when tighter
    assert MB.rows_cap(1, 1 << 40, 4096, 4) == 4096
    assert MB.rows_cap(100, 0, None, 4) is None


def test_parts_for_fits_both_caps():
    # 64M rows at 32 B against a 2M-row line: 32 passes
    assert SH.parts_for(60_000_000, 32, rows_cap=1 << 21,
                        bytes_cap=None) == 32
    # byte cap binds harder than the row cap (but never past the
    # 256-pass ceiling the legacy _spill_partitions shares)
    p = SH.parts_for(1 << 20, 1024, rows_cap=1 << 21,
                     bytes_cap=1 << 22)
    assert p == 256  # 1 GiB / 4 MiB
    assert SH.parts_for(100, 8, rows_cap=None, bytes_cap=None) == 1
    assert SH.parts_for(1 << 30, 64, rows_cap=8, bytes_cap=8) == 256


def test_buffer_bytes_is_the_allocation():
    # the model predicts LADDER allocations, not raw row counts
    assert SH.buffer_bytes(1000, 10) == 1024 * 10


# ------------------------------------- forced chunked rewrites (CPU)
def test_tiny_budget_chunks_join_oracle_exact(conn, base):
    """A budget small enough that the Q3-shaped join cannot hold its
    build in one pass: the governor grace-partitions it, probe pages
    position-chunk, and the answer is bit-identical."""
    r = LocalRunner({"tpch": conn}, page_rows=1 << 13)
    r.session.set("device_memory_budget", 1 << 21)  # 2 MiB
    r.session.set("generated_join_enabled", False)  # force real builds
    want = base.execute(JOIN_Q).rows
    got = r.execute(JOIN_Q).rows
    assert r.executor.memory_chunked_pipelines > 0, (
        "tiny budget should have forced a chunked rewrite"
    )
    assert _rows_equal(want, got), (want[:3], got[:3])


def test_tiny_budget_chunks_scan_agg_oracle_exact(conn, base):
    """Generation-chunked scan: page size shrinks to fit the budget
    share, the Q1-shaped pipeline streams through smaller resident
    buffers, same answer (the SF100 mechanism at SF0.01)."""
    r = LocalRunner({"tpch": conn}, page_rows=1 << 13)
    r.session.set("device_memory_budget", 1 << 20)  # 1 MiB
    want = base.execute(SCAN_AGG_Q).rows
    got = r.execute(SCAN_AGG_Q).rows
    ex = r.executor
    assert ex.memory_chunked_pipelines > 0
    schema = conn.table_schema("lineitem")
    types = [schema.column_type(c) for c in schema.column_names()]
    assert ex._governed_target_rows(types, count=False) < (1 << 13)
    assert _rows_equal(want, got), (want[:3], got[:3])


def test_fault_rows_ceiling_chunks_everything(conn, base):
    """Forcing the device fault line down to 4k rows (the CPU stand-in
    for the axon >=4M-row fault) bounds every governed buffer — scan
    pages, join builds, join outputs — and execution stays exact."""
    r = LocalRunner({"tpch": conn}, page_rows=1 << 13)
    r.apply_session()
    r.executor.fault_rows = 1 << 12
    for q in (JOIN_Q, SCAN_AGG_Q):
        want = base.execute(q).rows
        got = r.execute(q).rows
        assert _rows_equal(want, got), (q, want[:3], got[:3])
    assert r.executor.memory_chunked_pipelines > 0


def test_sqlite_oracle_parity_under_tiny_budget(conn):
    """BASELINE.md's correctness gate against the forced-chunked
    engine: sqlite computes the same join-aggregate."""
    from tests.oracle import load_sqlite

    r = LocalRunner({"tpch": conn}, page_rows=1 << 13)
    r.session.set("device_memory_budget", 1 << 21)
    r.session.set("generated_join_enabled", False)
    got = r.execute(JOIN_Q).rows
    assert r.executor.memory_chunked_pipelines > 0
    db = load_sqlite(conn, ["orders", "lineitem"])
    want = db.execute(
        "select o_orderkey, sum(l_extendedprice), count(*) "
        "from orders join lineitem on o_orderkey = l_orderkey "
        "group by o_orderkey order by 2 desc, 1 limit 7"
    ).fetchall()
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g[0] == w[0] and g[2] == w[2]
        assert abs(g[1] - w[1]) < 1e-4 * max(abs(w[1]), 1)


# --------------------------------------------------- observability
def test_explain_analyze_exposes_governor_counters(conn):
    r = LocalRunner({"tpch": conn}, page_rows=1 << 13)
    r.session.set("device_memory_budget", 1 << 20)
    r.apply_session()
    plan = r.plan(SCAN_AGG_Q)
    _names, _rows, stats = r.executor.execute_with_stats(plan)
    ctr = stats["counters"]
    assert ctr["peak_device_bytes"] > 0
    assert ctr["memory_chunked_pipelines"] > 0
    # and they render into the EXPLAIN ANALYZE text
    from presto_tpu.runner import explain_text

    text = explain_text(plan, stats=stats)
    assert "peak_device_bytes" in text
    assert "memory_chunked_pipelines" in text


def test_static_audit_matches_execution_decisions(conn):
    """membudget.audit predicts chunked rewrites from the plan alone —
    same sizing functions, no execution."""
    r = LocalRunner({"tpch": conn}, page_rows=1 << 13)
    r.session.set("device_memory_budget", 1 << 21)
    r.session.set("generated_join_enabled", False)
    r.apply_session()
    plan = r.plan(JOIN_Q)
    report = MB.audit(r.executor, plan)
    assert report.budget == 1 << 21
    assert report.chunked_count > 0
    assert report.buffers  # scans + build + output recorded
    assert report.max_buffer_bytes > 0
    # rendering never touches the device
    assert "governed rewrites" in MB.render(report)


def test_stats_driven_broadcast_flips_with_size(conn, base):
    """Satellite: the broadcast-vs-partitioned decision follows the
    build side's BYTE footprint against the per-chip share (exact
    generator row counts x row width), not a fixed row threshold — the
    same plan flips as the budget share moves across the build size."""
    from presto_tpu.exec import plan as P
    from presto_tpu.dist.fragmenter import add_exchanges

    plan = base.plan(
        "select o_orderkey, c_custkey from customer, orders "
        "where c_custkey = o_custkey"
    )

    def kinds(n, out):
        if isinstance(n, P.Exchange):
            out.append(n.kind)
        for c in n.children():
            kinds(c, out)
        return out

    roomy, _ = add_exchanges(
        plan, base.catalogs,
        broadcast_bytes=1 << 40, row_bytes_of=lambda n: 64,
    )
    tight, _ = add_exchanges(
        plan, base.catalogs,
        broadcast_bytes=64, row_bytes_of=lambda n: 64,
    )
    assert "broadcast" in kinds(roomy, [])
    assert "broadcast" not in kinds(tight, [])
    assert "repartition" in kinds(tight, [])


def test_dist_budget_is_mesh_share(conn):
    from presto_tpu.dist.executor import DistExecutor, make_mesh

    mesh = make_mesh(2)
    ex = DistExecutor({"tpch": conn}, mesh)
    ex.device_memory_budget = 1 << 30
    from presto_tpu.exec.executor import Executor

    solo = Executor({"tpch": conn})
    solo.device_memory_budget = 1 << 30
    assert ex._budget() == 2 * solo._budget()


def test_etc_key_seeds_session_default(tmp_path):
    from presto_tpu.config import server_from_etc

    (tmp_path / "catalog").mkdir()
    (tmp_path / "config.properties").write_text(
        "http-server.http.port=0\n"
        "device-memory.budget=123456789\n"
    )
    (tmp_path / "catalog" / "tiny.properties").write_text(
        "connector.name=tpch\ntpch.scale-factor=0.001\n"
    )
    server = server_from_etc(str(tmp_path))
    from presto_tpu.session import Session

    session = Session()
    runner = server.manager._runner_factory(session)
    assert session.get("device_memory_budget") == 123456789
    runner.apply_session()
    assert runner.executor.device_memory_budget == 123456789


# -------------------------------------------- SF10/SF100 dry audits
@pytest.mark.slow
def test_sf10_join_plans_stay_under_fault_line():
    """The acceptance criterion behind deleting BENCH_INCLUDE_SF10_JOINS:
    under TPU assumptions (default HBM budget, the axon fault line),
    every buffer the governor plans for the Q3/Q5 SF10 join pipelines
    stays under the >=4M-row line BY CONSTRUCTION. Static — no pages
    are generated; the SF10 connector is just metadata here."""
    from tests.tpch_queries import QUERIES

    conn10 = TpchConnector(10.0)
    r = LocalRunner({"tpch": conn10}, page_rows=1 << 18)
    r.apply_session()
    ex = r.executor
    ex.device_memory_budget = MB.DEFAULT_TPU_HBM * 7 // 8
    ex.fault_rows = SH.SAFE_BUFFER_ROWS
    for qid in (3, 5):
        report = MB.audit(ex, r.plan(QUERIES[qid]))
        over = [b for b in report.buffers
                if b.rows >= SH.DEVICE_FAULT_ROWS]
        assert not over, (qid, [(b.label, b.rows) for b in over])
        assert not report.over_budget(), (
            qid, [(b.label, b.bytes) for b in report.over_budget()])


@pytest.mark.slow
def test_sf100_scan_agg_plans_fixed_resident_buffers():
    """The q1_sf100 on-ramp: 600M rows stream through governed
    fixed-size generation buffers — the plan's footprint is independent
    of the table size."""
    from tests.tpch_queries import QUERIES

    conn100 = TpchConnector(100.0)
    r = LocalRunner({"tpch": conn100}, page_rows=1 << 20)
    r.apply_session()
    ex = r.executor
    ex.device_memory_budget = MB.DEFAULT_TPU_HBM * 7 // 8
    ex.fault_rows = SH.SAFE_BUFFER_ROWS
    for qid in (1, 6):
        report = MB.audit(ex, r.plan(QUERIES[qid]))
        assert report.ok, (qid, MB.render(report))
        assert report.max_buffer_bytes < report.budget
