"""ARRAY / MAP / ROW values + UNNEST.

Reference: spi/block/{Array,Map,Row}Block + operator/UnnestOperator.java
+ operator/scalar/{Array,Map}Functions. TPU translation: complex values
are dictionary-coded (host tuples, i32 codes) — per-distinct-value work
at trace time, vectorized gathers per row; UNNEST expands by the max
array length over the dictionary (a compile-time constant) with a
validity mask for shorter arrays.
"""

import collections

import pytest

from presto_tpu import types as T
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runner import LocalRunner


@pytest.fixture(scope="module")
def runner():
    mem = MemoryConnector()
    mem.create_table(
        "docs", ["id", "tags"], [T.BIGINT, T.ArrayType(T.VARCHAR)],
        [(1, ("red", "blue")), (2, ("green",)), (3, ()), (4, None),
         (5, ("red",))],
    )
    mem.create_table(
        "nums", ["id", "xs"], [T.BIGINT, T.ArrayType(T.BIGINT)],
        [(1, (10, 20, 30)), (2, (5,)), (3, (7, 7))],
    )
    return LocalRunner(
        {"memory": mem, "tpch": TpchConnector(0.001)},
        default_catalog="memory",
    )


def one(runner, expr):
    return runner.execute(
        f"select {expr} from tpch.region limit 1"
    ).rows[0]


def test_array_literal_functions(runner):
    assert one(runner, "cardinality(array[1,2,3])") == (3,)
    assert one(runner, "element_at(array[10,20,30], 2)") == (20,)
    assert one(runner, "element_at(array[10], 5)") == (None,)
    assert one(runner, "contains(array[1,2,3], 2)") == (True,)
    assert one(runner, "contains(array[1,2,3], 9)") == (False,)
    assert one(runner, "array_min(array[3,1,2]), array_max(array[3,1,2])"
               ) == (1, 3)
    assert one(runner, "cardinality(array[])") == (0,)


def test_map_functions(runner):
    assert one(
        runner,
        "element_at(map(array['a','b'], array[1,2]), 'b')"
    ) == (2,)
    assert one(
        runner,
        "element_at(map(array['a'], array[1]), 'zz')"
    ) == (None,)
    assert one(
        runner, "cardinality(map(array['a','b'], array[1,2]))"
    ) == (2,)
    assert one(
        runner, "map_keys(map(array['a','b'], array[1,2]))"
    ) == (("a", "b"),)
    assert one(
        runner, "map_values(map(array['a','b'], array[1,2]))"
    ) == ((1, 2),)


def test_row_functions(runner):
    assert one(runner, "element_at(row(7, 'x'), 1)") == (7,)
    assert one(runner, "element_at(row(7, 'x'), 2)") == ("x",)


def test_unnest_literal(runner):
    assert runner.execute(
        "select x from unnest(array[5,6,7]) as t(x)"
    ).rows == [(5,), (6,), (7,)]
    assert runner.execute(
        "select x, o from unnest(array['a','b']) with ordinality "
        "as t(x, o)"
    ).rows == [("a", 1), ("b", 2)]
    assert runner.execute(
        "select sum(x) from unnest(array[1,2,3,4]) as t(x)"
    ).rows == [(10,)]


def test_unnest_lateral_over_table(runner):
    rows = runner.execute(
        "select r_name, x from tpch.region cross join "
        "unnest(array[1,2]) as t(x) order by r_name, x limit 4"
    ).rows
    assert rows == [("AFRICA", 1), ("AFRICA", 2), ("AMERICA", 1),
                    ("AMERICA", 2)]


def test_array_column_scan_and_unnest(runner):
    # NULL and empty arrays produce no rows (CROSS JOIN UNNEST)
    assert runner.execute(
        "select id, t from docs cross join unnest(tags) as u(t) "
        "order by id, t"
    ).rows == [(1, "blue"), (1, "red"), (2, "green"), (5, "red")]
    # group over unnested elements
    assert runner.execute(
        "select t, count(*) from docs cross join unnest(tags) as u(t) "
        "group by t order by t"
    ).rows == [("blue", 1), ("green", 1), ("red", 2)]
    # cardinality of a column; NULL array stays NULL
    assert runner.execute(
        "select id, cardinality(tags) from docs order by id"
    ).rows == [(1, 2), (2, 1), (3, 0), (4, None), (5, 1)]


def test_unnest_numeric_aggregation(runner):
    assert runner.execute(
        "select id, sum(x) from nums cross join unnest(xs) as u(x) "
        "group by id order by id"
    ).rows == [(1, 60), (2, 5), (3, 14)]


def test_group_by_array_column(runner):
    # arrays are grouping-comparable through dictionary canonicalization
    rows = runner.execute(
        "select tags, count(*) from docs where tags is not null "
        "group by tags order by 2 desc limit 2"
    ).rows
    assert rows[0][1] == 1  # all distinct arrays here


def test_unnest_distributed(runner):
    import jax

    from presto_tpu.dist.executor import make_mesh

    assert len(jax.devices()) >= 8
    dist = LocalRunner(
        {"tpch": TpchConnector(0.005)}, page_rows=1 << 13,
        mesh=make_mesh(8),
        dist_options=dict(broadcast_rows=64, gather_capacity=16),
    )
    single = LocalRunner({"tpch": TpchConnector(0.005)},
                         page_rows=1 << 13)
    q = ("select n_regionkey, sum(x) from nation cross join "
         "unnest(array[1,2,3]) as t(x) group by n_regionkey")
    a = single.execute(q).rows
    b = dist.execute(q).rows
    assert collections.Counter(a) == collections.Counter(b)
