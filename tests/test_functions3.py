"""Round-3 function-breadth batch: SQL-level checks of the new scalar
builtins (math/regexp/string/temporal/conditional) against Python-
computed expectations over tiny generated tables.

Reference test pattern: presto-main operator/scalar/* TestNN classes
assert single expressions via FunctionAssertions; our analog drives the
whole engine (parse -> plan -> jit) per expression, so coverage here
also exercises type resolution and constant handling end to end.
"""

import datetime
import math

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runner import LocalRunner


@pytest.fixture(scope="module")
def runner():
    return LocalRunner(
        {"tpch": TpchConnector(0.001)}, page_rows=1 << 12
    )


def one(runner, expr, frm="region"):
    rows = runner.execute(f"select {expr} from {frm} limit 1").rows
    return rows[0][0]


@pytest.mark.parametrize("expr,want", [
    ("log2(8e0)", 3.0),
    ("log10(1000e0)", 3.0),
    ("log(3e0, 81e0)", 4.0),
    ("cbrt(-27e0)", -3.0),
    ("mod(10, 3)", 1),
    ("mod(-10, 3)", -1),
    ("sign(-5)", -1),
    ("truncate(-1.7e0)", -1.0),
    ("degrees(pi())", 180.0),
    ("width_bucket(5e0, 0e0, 10e0, 5)", 3),
    ("atan2(1e0, 1e0)", math.pi / 4),
    ("is_nan(nan())", True),
    ("is_finite(infinity())", False),
    ("is_infinite(infinity())", True),
])
def test_math(runner, expr, want):
    got = one(runner, expr)
    if isinstance(want, float):
        assert got == pytest.approx(want, rel=1e-12), expr
    else:
        assert got == want, expr


def test_trig(runner):
    assert one(runner, "sin(0e0)") == 0.0
    assert one(runner, "cos(0e0)") == 1.0
    assert one(runner, "tanh(0e0)") == 0.0
    assert one(runner, "acos(1e0)") == 0.0


def test_mod_by_zero_is_null(runner):
    assert one(runner, "mod(10, 0)") is None


def test_nullif(runner):
    assert one(runner, "nullif(3, 3)") is None
    assert one(runner, "nullif(3, 4)") == 3
    assert one(runner, "nullif(r_name, 'AFRICA')",
               "region where r_regionkey = 0") is None
    assert one(runner, "nullif(r_name, 'ASIA')",
               "region where r_regionkey = 0") == "AFRICA"


def test_regexp(runner):
    assert one(runner, "regexp_like(r_name, '^AF')",
               "region where r_regionkey = 0") is True
    assert one(runner, "regexp_like(r_name, 'ZZZ')",
               "region where r_regionkey = 0") is False
    assert one(runner, "regexp_extract(r_name, '([A-Z]+)ICA', 1)",
               "region where r_regionkey = 0") == "AFR"
    assert one(runner, "regexp_replace(r_name, 'AFR', 'X')",
               "region where r_regionkey = 0") == "XICA"


def test_regexp_extract_no_match_is_null(runner):
    assert one(runner, "regexp_extract(r_name, 'ZZZ')",
               "region where r_regionkey = 0") is None


def test_date_diff_truncates_toward_zero(runner):
    # 2h elapsed across a midnight boundary: 0 complete days, not 1;
    # negative diffs truncate toward zero (-1h30 -> -1 hour, not -2)
    rows = runner.execute(
        "select date_diff('day', from_unixtime(82800e0), "
        "from_unixtime(90000e0)), "
        "date_diff('hour', from_unixtime(5400e0), from_unixtime(0e0)) "
        "from region limit 1"
    ).rows
    assert rows[0] == (0, -1)


def test_string_batch(runner):
    frm = "region where r_regionkey = 0"  # AFRICA
    assert one(runner, "length(r_name)", frm) == 6
    assert one(runner, "reverse(r_name)", frm) == "ACIRFA"
    assert one(runner, "strpos(r_name, 'RIC')", frm) == 3
    assert one(runner, "strpos(r_name, 'ZZ')", frm) == 0
    assert one(runner, "replace(r_name, 'AFR', 'AMER')", frm) == "AMERICA"
    assert one(runner, "lpad(r_name, 8, '*')", frm) == "**AFRICA"
    assert one(runner, "rpad(r_name, 8, '*')", frm) == "AFRICA**"
    assert one(runner, "split_part(r_name, 'R', 1)", frm) == "AF"
    assert one(runner, "codepoint(r_name)", frm) == ord("A")


def test_temporal_batch(runner):
    # o_orderdate values are real dates; compare against Python math
    rows = runner.execute(
        "select o_orderdate, date_trunc('month', o_orderdate), "
        "date_trunc('year', o_orderdate), "
        "date_add('day', 31, o_orderdate), "
        "date_add('month', 2, o_orderdate), "
        "date_diff('day', o_orderdate, date_add('day', 45, o_orderdate)),"
        "date_diff('month', o_orderdate, date_add('day', 65, o_orderdate))"
        " from orders limit 50"
    ).rows
    epoch = datetime.date(1970, 1, 1)

    def day(v):
        return epoch + datetime.timedelta(days=int(v))

    for (d, tm, ty, plus31, plus2m, diff45, diffm) in rows:
        base = day(d)
        assert day(tm) == base.replace(day=1)
        assert day(ty) == base.replace(month=1, day=1)
        assert day(plus31) == base + datetime.timedelta(days=31)
        m0 = base.month - 1 + 2
        y, m = base.year + m0 // 12, m0 % 12 + 1
        import calendar

        dd = min(base.day, calendar.monthrange(y, m)[1])
        assert day(plus2m) == datetime.date(y, m, dd)
        assert diff45 == 45
        plus65 = base + datetime.timedelta(days=65)
        months = (plus65.year - base.year) * 12 + (
            plus65.month - base.month
        )
        if plus65.day < base.day:
            months -= 1
        assert diffm == months, (d, diffm, months)


def test_week_trunc_is_monday(runner):
    rows = runner.execute(
        "select date_trunc('week', o_orderdate) from orders limit 20"
    ).rows
    epoch = datetime.date(1970, 1, 1)
    for (d,) in rows:
        monday = epoch + datetime.timedelta(days=int(d))
        assert monday.weekday() == 0


def test_unixtime_roundtrip(runner):
    rows = runner.execute(
        "select to_unixtime(from_unixtime(1456e0)) from region limit 1"
    ).rows
    assert rows[0][0] == pytest.approx(1456.0)
