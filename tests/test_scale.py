"""Scale validation: checksum-verified parity at SF well above the toy
test scale, exercising multi-page streams, capacity-boost retries, and
the verifier checksum harness (VERDICT round-1 item 4).

On published answer sets: the TPC-H generator here is spec-shaped
(schemas, distributions, key structure follow TPC-H 4.2.3) but is NOT a
bit-exact dbgen clone — its value streams come from xxhash-keyed draws,
not dbgen's LCG streams — so the published SF1 answer set does not apply
to this data. Cross-engine validation instead runs the same queries over
the SAME generated rows in sqlite (tests/test_sql_tpch.py does this for
all 22 queries) and at SF0.1 here; single-vs-distributed parity is
checksum-verified below.
"""

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runner import LocalRunner
from presto_tpu.verifier import assert_same_results, checksum_rows
from tests.tpch_queries import QUERIES

SF = 0.1  # 20x the toy suite; ~600k lineitem slots


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(SF)


@pytest.fixture(scope="module")
def runner(conn):
    return LocalRunner({"tpch": conn}, page_rows=1 << 15)


def test_checksum_utility_properties():
    rows = [(1, "a", 2.5), (2, "b", None), (3, "a", 0.0)]
    base = checksum_rows(rows)
    # order-insensitive
    assert checksum_rows(list(reversed(rows))) == base
    # value-sensitive
    assert checksum_rows([(1, "a", 2.5), (2, "b", None),
                          (3, "a", 1.0)]) != base
    # count-sensitive
    assert checksum_rows(rows[:2])["count"] == 2


@pytest.mark.parametrize("qid", [1, 3, 6])
def test_sf01_engine_vs_sqlite(qid, conn, runner):
    from tests.oracle import load_sqlite
    from tests.test_sql_tpch import ENGINE_SQL, ORACLE, compare

    tables = {
        1: ["lineitem"],
        3: ["customer", "orders", "lineitem"],
        6: ["lineitem"],
    }[qid]
    db = load_sqlite(conn, tables)
    got = runner.execute(ENGINE_SQL[qid]).rows
    want = db.execute(ORACLE[qid][0]).fetchall()
    compare(qid, got, want, ORACLE[qid][1])


def test_small_pages_force_capacity_paths(conn):
    """Tiny page_rows force multi-page streams, partial-agg capacity
    clipping, and the query-level boost retry; results must be identical
    to the comfortable configuration (checksum compare)."""
    wide = LocalRunner({"tpch": conn}, page_rows=1 << 15)
    tight = LocalRunner({"tpch": conn}, page_rows=1 << 10)
    for qid in (1, 6, 4):
        a = wide.execute(QUERIES[qid]).rows
        b = tight.execute(QUERIES[qid]).rows
        assert_same_results(a, b, label=f"Q{qid} page_rows 32k vs 1k")


def test_single_vs_distributed_checksum(conn):
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    from presto_tpu.dist.executor import make_mesh

    single = LocalRunner({"tpch": conn}, page_rows=1 << 15)
    dist = LocalRunner(
        {"tpch": conn}, page_rows=1 << 15, mesh=make_mesh(8)
    )
    for qid in (1, 6, 12):
        a = single.execute(QUERIES[qid]).rows
        b = dist.execute(QUERIES[qid]).rows
        assert_same_results(a, b, label=f"Q{qid} single vs dist @ SF{SF}")
