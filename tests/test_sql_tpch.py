"""SQL-level TPC-H correctness: every query runs through parse → plan →
execute and is checked against sqlite3 running an encoding-adapted oracle
version over the same data (SURVEY §5 ring 2; reference analog:
AbstractTestQueries + H2QueryRunner).

Oracle adaptation rules: decimals are unscaled ints (0.06 -> 6 at scale 2),
dates are epoch days, extract(year) becomes strftime over unixepoch.
Comparison: multiset of rows; float columns with tolerance; engine decimal
averages are round-half-up ints, compared within 0.51 of sqlite's float.
"""

import collections
import datetime

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runner import LocalRunner
from tests.oracle import load_sqlite
from tests.tpch_queries import QUERIES

EPOCH = datetime.date(1970, 1, 1)


def days(y, m, d):
    return (datetime.date(y, m, d) - EPOCH).days


def year_sql(col):
    return f"CAST(strftime('%Y', {col}*86400, 'unixepoch') AS INTEGER)"


SF = 0.005


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(SF)


@pytest.fixture(scope="module")
def runner(conn):
    return LocalRunner({"tpch": conn}, page_rows=1 << 15)


@pytest.fixture(scope="module")
def db(conn):
    return load_sqlite(conn, conn.tables())


# Per-query oracle SQL + per-column compare mode.
# modes: None/exact, 'f' float-tolerance, 'r' round-half-up int vs float
ORACLE = {
    1: (
        f"""
        SELECT l_returnflag, l_linestatus, SUM(l_quantity),
               SUM(l_extendedprice),
               SUM(l_extendedprice * (100 - l_discount)),
               SUM(l_extendedprice * (100 - l_discount) * (100 + l_tax)),
               AVG(l_quantity), AVG(l_extendedprice), AVG(l_discount),
               COUNT(*)
        FROM lineitem WHERE l_shipdate <= {days(1998, 12, 1) - 90}
        GROUP BY 1, 2 ORDER BY 1, 2
        """,
        {6: "r", 7: "r", 8: "r"},
    ),
    2: (
        f"""
        SELECT s_acctbal, s_name, n_name, p_partkey, p_mfgr, s_address,
               s_phone, s_comment
        FROM part, supplier, partsupp, nation, region
        WHERE p_partkey = ps_partkey AND s_suppkey = ps_suppkey
          AND p_size = 15 AND p_type LIKE '%BRASS'
          AND s_nationkey = n_nationkey AND n_regionkey = r_regionkey
          AND r_name = 'EUROPE'
          AND ps_supplycost = (
            SELECT MIN(ps_supplycost) FROM partsupp, supplier, nation,
                 region
            WHERE p_partkey = ps_partkey AND s_suppkey = ps_suppkey
              AND s_nationkey = n_nationkey AND n_regionkey = r_regionkey
              AND r_name = 'EUROPE')
        ORDER BY s_acctbal DESC, n_name, s_name, p_partkey LIMIT 100
        """,
        {},
    ),
    3: (
        f"""
        SELECT l_orderkey,
               SUM(l_extendedprice * (100 - l_discount)), o_orderdate,
               o_shippriority
        FROM customer, orders, lineitem
        WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
          AND l_orderkey = o_orderkey
          AND o_orderdate < {days(1995, 3, 15)}
          AND l_shipdate > {days(1995, 3, 15)}
        GROUP BY l_orderkey, o_orderdate, o_shippriority
        ORDER BY 2 DESC, o_orderdate, l_orderkey LIMIT 10
        """,
        {},
    ),
    4: (
        f"""
        SELECT o_orderpriority, COUNT(*) FROM orders
        WHERE o_orderdate >= {days(1993, 7, 1)}
          AND o_orderdate < {days(1993, 10, 1)}
          AND EXISTS (SELECT 1 FROM lineitem
                      WHERE l_orderkey = o_orderkey
                        AND l_commitdate < l_receiptdate)
        GROUP BY o_orderpriority ORDER BY o_orderpriority
        """,
        {},
    ),
    5: (
        f"""
        SELECT n_name, SUM(l_extendedprice * (100 - l_discount))
        FROM customer, orders, lineitem, supplier, nation, region
        WHERE c_custkey = o_custkey AND l_orderkey = o_orderkey
          AND l_suppkey = s_suppkey AND c_nationkey = s_nationkey
          AND s_nationkey = n_nationkey AND n_regionkey = r_regionkey
          AND r_name = 'ASIA'
          AND o_orderdate >= {days(1994, 1, 1)}
          AND o_orderdate < {days(1995, 1, 1)}
        GROUP BY n_name ORDER BY 2 DESC
        """,
        {},
    ),
    6: (
        f"""
        SELECT SUM(l_extendedprice * l_discount) FROM lineitem
        WHERE l_shipdate >= {days(1994, 1, 1)}
          AND l_shipdate < {days(1995, 1, 1)}
          AND l_discount BETWEEN 5 AND 7 AND l_quantity < 2400
        """,
        {},
    ),
    7: (
        f"""
        SELECT supp_nation, cust_nation, l_year, SUM(volume) FROM (
          SELECT n1.n_name AS supp_nation, n2.n_name AS cust_nation,
                 {year_sql('l_shipdate')} AS l_year,
                 l_extendedprice * (100 - l_discount) AS volume
          FROM supplier, lineitem, orders, customer, nation n1, nation n2
          WHERE s_suppkey = l_suppkey AND o_orderkey = l_orderkey
            AND c_custkey = o_custkey AND s_nationkey = n1.n_nationkey
            AND c_nationkey = n2.n_nationkey
            AND ((n1.n_name = 'FRANCE' AND n2.n_name = 'GERMANY')
              OR (n1.n_name = 'GERMANY' AND n2.n_name = 'FRANCE'))
            AND l_shipdate BETWEEN {days(1995, 1, 1)}
                AND {days(1996, 12, 31)})
        GROUP BY 1, 2, 3 ORDER BY 1, 2, 3
        """,
        {},
    ),
    8: (
        f"""
        SELECT o_year,
               CAST(SUM(CASE WHEN nation = 'BRAZIL' THEN volume ELSE 0 END)
                    AS REAL) / SUM(volume)
        FROM (
          SELECT {year_sql('o_orderdate')} AS o_year,
                 l_extendedprice * (100 - l_discount) AS volume,
                 n2.n_name AS nation
          FROM part, supplier, lineitem, orders, customer, nation n1,
               nation n2, region
          WHERE p_partkey = l_partkey AND s_suppkey = l_suppkey
            AND l_orderkey = o_orderkey AND o_custkey = c_custkey
            AND c_nationkey = n1.n_nationkey
            AND n1.n_regionkey = r_regionkey AND r_name = 'AMERICA'
            AND s_nationkey = n2.n_nationkey
            AND o_orderdate BETWEEN {days(1995, 1, 1)}
                AND {days(1996, 12, 31)}
            AND p_type = 'ECONOMY ANODIZED STEEL')
        GROUP BY o_year ORDER BY o_year
        """,
        {1: "f"},
    ),
    9: (
        f"""
        SELECT nation, o_year, SUM(amount) FROM (
          SELECT n_name AS nation, {year_sql('o_orderdate')} AS o_year,
                 l_extendedprice * (100 - l_discount)
                   - ps_supplycost * l_quantity AS amount
          FROM part, supplier, lineitem, partsupp, orders, nation
          WHERE s_suppkey = l_suppkey AND ps_suppkey = l_suppkey
            AND ps_partkey = l_partkey AND p_partkey = l_partkey
            AND o_orderkey = l_orderkey AND s_nationkey = n_nationkey
            AND p_name LIKE '%green%')
        GROUP BY nation, o_year ORDER BY nation, o_year DESC
        """,
        {},
    ),
    10: (
        f"""
        SELECT c_custkey, c_name, SUM(l_extendedprice * (100 - l_discount)),
               c_acctbal, n_name, c_address, c_phone, c_comment
        FROM customer, orders, lineitem, nation
        WHERE c_custkey = o_custkey AND l_orderkey = o_orderkey
          AND o_orderdate >= {days(1993, 10, 1)}
          AND o_orderdate < {days(1994, 1, 1)}
          AND l_returnflag = 'R' AND c_nationkey = n_nationkey
        GROUP BY c_custkey, c_name, c_acctbal, c_phone, n_name, c_address,
                 c_comment
        ORDER BY 3 DESC, c_custkey LIMIT 20
        """,
        {},
    ),
    11: (
        """
        SELECT ps_partkey, SUM(ps_supplycost * ps_availqty)
        FROM partsupp, supplier, nation
        WHERE ps_suppkey = s_suppkey AND s_nationkey = n_nationkey
          AND n_name = 'GERMANY'
        GROUP BY ps_partkey
        HAVING SUM(ps_supplycost * ps_availqty) > (
          SELECT SUM(ps_supplycost * ps_availqty) * 0.0001
          FROM partsupp, supplier, nation
          WHERE ps_suppkey = s_suppkey AND s_nationkey = n_nationkey
            AND n_name = 'GERMANY')
        ORDER BY 2 DESC, ps_partkey
        """,
        {},
    ),
    12: (
        f"""
        SELECT l_shipmode,
               SUM(CASE WHEN o_orderpriority = '1-URGENT'
                         OR o_orderpriority = '2-HIGH' THEN 1 ELSE 0 END),
               SUM(CASE WHEN o_orderpriority <> '1-URGENT'
                        AND o_orderpriority <> '2-HIGH' THEN 1 ELSE 0 END)
        FROM orders, lineitem
        WHERE o_orderkey = l_orderkey AND l_shipmode IN ('MAIL', 'SHIP')
          AND l_commitdate < l_receiptdate AND l_shipdate < l_commitdate
          AND l_receiptdate >= {days(1994, 1, 1)}
          AND l_receiptdate < {days(1995, 1, 1)}
        GROUP BY l_shipmode ORDER BY l_shipmode
        """,
        {},
    ),
    13: (
        """
        SELECT c_count, COUNT(*) FROM (
          SELECT c_custkey, COUNT(o_orderkey) AS c_count
          FROM customer LEFT OUTER JOIN orders
            ON c_custkey = o_custkey
           AND o_comment NOT LIKE '%special%requests%'
          GROUP BY c_custkey)
        GROUP BY c_count ORDER BY 2 DESC, c_count DESC
        """,
        {},
    ),
    14: (
        f"""
        SELECT 100.00 * SUM(CASE WHEN p_type LIKE 'PROMO%'
                            THEN l_extendedprice * (100 - l_discount)
                            ELSE 0 END)
               / SUM(l_extendedprice * (100 - l_discount))
        FROM lineitem, part
        WHERE l_partkey = p_partkey
          AND l_shipdate >= {days(1995, 9, 1)}
          AND l_shipdate < {days(1995, 10, 1)}
        """,
        {0: "f"},
    ),
    15: (
        f"""
        WITH revenue AS (
          SELECT l_suppkey AS supplier_no,
                 SUM(l_extendedprice * (100 - l_discount)) AS total_revenue
          FROM lineitem
          WHERE l_shipdate >= {days(1996, 1, 1)}
            AND l_shipdate < {days(1996, 4, 1)}
          GROUP BY l_suppkey)
        SELECT s_suppkey, s_name, s_address, s_phone, total_revenue
        FROM supplier, revenue
        WHERE s_suppkey = supplier_no
          AND total_revenue = (SELECT MAX(total_revenue) FROM revenue)
        ORDER BY s_suppkey
        """,
        {4: "f"},
    ),
    16: (
        """
        SELECT p_brand, p_type, p_size, COUNT(DISTINCT ps_suppkey)
        FROM partsupp, part
        WHERE p_partkey = ps_partkey AND p_brand <> 'Brand#45'
          AND p_type NOT LIKE 'MEDIUM POLISHED%'
          AND p_size IN (49, 14, 23, 45, 19, 3, 36, 9)
          AND ps_suppkey NOT IN (
            SELECT s_suppkey FROM supplier
            WHERE s_comment LIKE '%Customer%Complaints%')
        GROUP BY p_brand, p_type, p_size
        ORDER BY 4 DESC, p_brand, p_type, p_size
        """,
        {},
    ),
    17: (
        """
        SELECT CAST(SUM(l_extendedprice) AS REAL) / 100.0 / 7.0
        FROM lineitem, part
        WHERE p_partkey = l_partkey AND p_brand = 'Brand#23'
          AND p_container = 'MED BOX'
          AND l_quantity < (
            SELECT 0.2 * AVG(l_quantity) FROM lineitem
            WHERE l_partkey = p_partkey)
        """,
        {0: "f"},
    ),
    18: (
        """
        SELECT c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
               SUM(l_quantity)
        FROM customer, orders, lineitem
        WHERE o_orderkey IN (
            SELECT l_orderkey FROM lineitem GROUP BY l_orderkey
            HAVING SUM(l_quantity) > 30000)
          AND c_custkey = o_custkey AND o_orderkey = l_orderkey
        GROUP BY c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
        ORDER BY o_totalprice DESC, o_orderdate, o_orderkey LIMIT 100
        """,
        {},
    ),
    19: (
        """
        SELECT SUM(l_extendedprice * (100 - l_discount))
        FROM lineitem, part
        WHERE (p_partkey = l_partkey AND p_brand = 'Brand#12'
            AND p_container IN ('SM CASE','SM BOX','SM PACK','SM PKG')
            AND l_quantity >= 100 AND l_quantity <= 1100
            AND p_size BETWEEN 1 AND 5
            AND l_shipmode IN ('AIR', 'AIR REG')
            AND l_shipinstruct = 'DELIVER IN PERSON')
          OR (p_partkey = l_partkey AND p_brand = 'Brand#23'
            AND p_container IN ('MED BAG','MED BOX','MED PKG','MED PACK')
            AND l_quantity >= 1000 AND l_quantity <= 2000
            AND p_size BETWEEN 1 AND 10
            AND l_shipmode IN ('AIR', 'AIR REG')
            AND l_shipinstruct = 'DELIVER IN PERSON')
          OR (p_partkey = l_partkey AND p_brand = 'Brand#34'
            AND p_container IN ('LG CASE','LG BOX','LG PACK','LG PKG')
            AND l_quantity >= 2000 AND l_quantity <= 3000
            AND p_size BETWEEN 1 AND 15
            AND l_shipmode IN ('AIR', 'AIR REG')
            AND l_shipinstruct = 'DELIVER IN PERSON')
        """,
        {},
    ),
    20: (
        f"""
        SELECT s_name, s_address FROM supplier, nation
        WHERE s_suppkey IN (
            SELECT ps_suppkey FROM partsupp
            WHERE ps_partkey IN (
                SELECT p_partkey FROM part WHERE p_name LIKE 'forest%')
              AND ps_availqty > (
                SELECT 0.5 * SUM(l_quantity) FROM lineitem
                WHERE l_partkey = ps_partkey AND l_suppkey = ps_suppkey
                  AND l_shipdate >= {days(1994, 1, 1)}
                  AND l_shipdate < {days(1995, 1, 1)}))
          AND s_nationkey = n_nationkey AND n_name = 'CANADA'
        ORDER BY s_name
        """,
        {},
    ),
    21: (
        """
        SELECT s_name, COUNT(*) FROM supplier, lineitem l1, orders, nation
        WHERE s_suppkey = l1.l_suppkey AND o_orderkey = l1.l_orderkey
          AND o_orderstatus = 'F'
          AND l1.l_receiptdate > l1.l_commitdate
          AND EXISTS (SELECT 1 FROM lineitem l2
                      WHERE l2.l_orderkey = l1.l_orderkey
                        AND l2.l_suppkey <> l1.l_suppkey)
          AND NOT EXISTS (SELECT 1 FROM lineitem l3
                          WHERE l3.l_orderkey = l1.l_orderkey
                            AND l3.l_suppkey <> l1.l_suppkey
                            AND l3.l_receiptdate > l3.l_commitdate)
          AND s_nationkey = n_nationkey AND n_name = 'SAUDI ARABIA'
        GROUP BY s_name ORDER BY 2 DESC, s_name LIMIT 100
        """,
        {},
    ),
    22: (
        """
        SELECT cntrycode, COUNT(*), SUM(c_acctbal) FROM (
          SELECT SUBSTR(c_phone, 1, 2) AS cntrycode, c_acctbal
          FROM customer
          WHERE SUBSTR(c_phone, 1, 2) IN
                ('13', '31', '23', '29', '30', '18', '17')
            AND c_acctbal > (
              SELECT AVG(c_acctbal) FROM customer
              WHERE c_acctbal > 0
                AND SUBSTR(c_phone, 1, 2) IN
                    ('13', '31', '23', '29', '30', '18', '17'))
            AND NOT EXISTS (
              SELECT 1 FROM orders WHERE o_custkey = c_custkey))
        GROUP BY cntrycode ORDER BY cntrycode
        """,
        {},
    ),
}

# engine-side query text tweaks for deterministic comparison (extra
# tiebreaker sort keys on limited queries; quantity threshold scale in
# Q18's oracle already matches the engine's decimal encoding)
ENGINE_SQL = dict(QUERIES)
ENGINE_SQL[3] = QUERIES[3].replace(
    "order by revenue desc, o_orderdate",
    "order by revenue desc, o_orderdate, l_orderkey")
ENGINE_SQL[10] = QUERIES[10].replace(
    "order by revenue desc",
    "order by revenue desc, c_custkey")
ENGINE_SQL[18] = QUERIES[18].replace(
    "order by o_totalprice desc, o_orderdate",
    "order by o_totalprice desc, o_orderdate, o_orderkey")
ENGINE_SQL[11] = QUERIES[11].replace(
    "order by value desc",
    "order by value desc, ps_partkey")


def compare(qnum, engine_rows, oracle_rows, modes):
    assert len(engine_rows) == len(oracle_rows), (
        f"Q{qnum}: row count {len(engine_rows)} vs {len(oracle_rows)}\n"
        f"engine: {engine_rows[:3]}\noracle: {oracle_rows[:3]}"
    )

    def norm(row, is_engine):
        out = []
        for j, v in enumerate(row):
            mode = modes.get(j)
            if mode == "f":
                out.append(round(float(v), 6) if v is not None else None)
            elif mode == "r":
                # engine: round-half-up int; oracle: float — bucket both
                out.append(None if v is None else round(float(v)))
            else:
                out.append(v)
        return tuple(out)

    e_rows = [norm(r, True) for r in engine_rows]
    o_rows = [norm(tuple(r), False) for r in oracle_rows]
    if any(m == "f" for m in modes.values()):
        # compare float columns with relative tolerance, row-aligned
        for i, (er, orow) in enumerate(zip(e_rows, o_rows)):
            for j, (ev, ov) in enumerate(zip(er, orow)):
                if modes.get(j) == "f" and ev is not None and ov is not None:
                    assert abs(ev - ov) <= 1e-6 * max(1.0, abs(ov)), (
                        f"Q{qnum} row {i} col {j}: {ev} != {ov}"
                    )
                else:
                    assert ev == ov, f"Q{qnum} row {i} col {j}: {ev}!={ov}"
        return
    assert collections.Counter(e_rows) == collections.Counter(o_rows), (
        f"Q{qnum} rows differ\nengine head: {e_rows[:4]}\n"
        f"oracle head: {o_rows[:4]}"
    )
    # ordered queries: also require exact sequence
    assert e_rows == o_rows, f"Q{qnum}: ordering differs"


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_query(qnum, runner, db):
    oracle_sql, modes = ORACLE[qnum]
    result = runner.execute(ENGINE_SQL[qnum])
    oracle_rows = db.execute(oracle_sql).fetchall()
    compare(qnum, result.rows, oracle_rows, modes)


def test_explain(runner):
    res = runner.execute("explain " + QUERIES[3])
    text = "\n".join(r[0] for r in res.rows)
    assert "TableScan" in text and "Join" in text and "TopN" in text
