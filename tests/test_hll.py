"""approx_distinct (HyperLogLog) — kernel accuracy + SQL integration.

Reference: presto-main src/test .../operator/aggregation/
TestApproximateCountDistinctAggregation.java (asserts estimates within
the configured standard error). Our M_REGS=256 registers give SE ~6.5%;
tests assert within 4 standard errors (26%) for robustness plus a
tighter sanity bound on larger cardinalities.
"""

import collections

import jax.numpy as jnp
import numpy as np
import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.ops import hll as HLL
from presto_tpu.runner import LocalRunner


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(0.01)


@pytest.fixture(scope="module")
def runner(conn):
    return LocalRunner({"tpch": conn}, page_rows=1 << 13)


def _exact_vs_est(rows):
    for row in rows:
        est, exact = row[-2], row[-1]
        err = abs(est - exact) / max(exact, 1)
        assert err < 0.26, f"estimate {est} vs exact {exact} ({err:.2%})"


def test_kernel_estimate_accuracy(rng):
    from presto_tpu.ops import hashing as H

    for n in (10, 500, 20_000):
        vals = jnp.asarray(
            rng.integers(0, 1 << 60, size=n * 2) % n, dtype=jnp.int64
        )
        h = H.hash_columns([vals.astype(jnp.uint64)], [None])
        valid = jnp.ones((n * 2,), dtype=jnp.bool_)
        words = HLL.global_insert(valid, h)
        est = int(HLL.estimate(words)[0])
        exact = len(np.unique(np.asarray(vals)))
        assert abs(est - exact) / exact < 0.26, (n, est, exact)


def test_kernel_merge_equals_single_pass(rng):
    from presto_tpu.ops import hashing as H

    vals = jnp.asarray(rng.integers(0, 5000, size=8192), dtype=jnp.int64)
    h = H.hash_columns([vals.astype(jnp.uint64)], [None])
    valid = jnp.ones((8192,), dtype=jnp.bool_)
    whole = HLL.global_insert(valid, h)
    # split into two halves, insert separately, merge
    half = jnp.arange(8192) < 4096
    w1 = HLL.global_insert(valid & half, h)
    w2 = HLL.global_insert(valid & ~half, h)
    stacked = tuple(
        jnp.concatenate([a, b]) for a, b in zip(w1, w2)
    )
    merged = HLL.global_merge(jnp.ones((2,), dtype=jnp.bool_), stacked)
    assert int(HLL.estimate(whole)[0]) == int(HLL.estimate(merged)[0])


def test_sql_global(runner):
    rows = runner.execute(
        "select approx_distinct(o_custkey), count(distinct o_custkey) "
        "from orders"
    ).rows
    _exact_vs_est(rows)


def test_sql_grouped(runner):
    rows = runner.execute(
        "select o_orderpriority, approx_distinct(o_custkey), "
        "count(distinct o_custkey) from orders group by o_orderpriority"
    ).rows
    assert len(rows) == 5
    _exact_vs_est(rows)


def test_sql_string_input(runner):
    rows = runner.execute(
        "select approx_distinct(c_mktsegment) from customer"
    ).rows
    assert rows[0][0] == 5  # linear-counting regime is near-exact


def test_sql_nulls_and_empty(runner):
    # empty input -> 0 (reference semantics)
    assert runner.execute(
        "select approx_distinct(o_custkey) from orders "
        "where o_orderkey < 0"
    ).rows == [(0,)]


def test_sql_spill_partitioned(conn, runner):
    q = (
        "select o_custkey, approx_distinct(o_orderkey), "
        "count(distinct o_orderkey) from orders group by o_custkey "
        "order by 1 limit 20"
    )
    want = runner.execute(q).rows
    sp = LocalRunner({"tpch": conn}, page_rows=1 << 13)
    sp.session.set("spill_threshold_bytes", 1 << 15)
    got = sp.execute(q).rows
    assert sp.executor.spill_partitions_used > 1
    assert got == want


def test_sql_distributed(conn, runner):
    import jax

    from presto_tpu.dist.executor import make_mesh

    assert len(jax.devices()) >= 8
    dist = LocalRunner(
        {"tpch": conn}, page_rows=1 << 13, mesh=make_mesh(8),
        dist_options=dict(broadcast_rows=64, gather_capacity=16),
    )
    for q in (
        "select o_orderpriority, approx_distinct(o_custkey) "
        "from orders group by o_orderpriority",
        "select approx_distinct(o_custkey) from orders",
    ):
        a = collections.Counter(map(repr, runner.execute(q).rows))
        b = collections.Counter(map(repr, dist.execute(q).rows))
        assert a == b, q
