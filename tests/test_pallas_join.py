"""Pallas hash-join probe kernel: correctness vs a numpy oracle in
interpret mode (runs on the CPU CI mesh; the real-TPU lowering is
exercised by bench.py's join microbench)."""

import numpy as np
import pytest

import jax.numpy as jnp

from presto_tpu.ops import pallas_join as PJ


def oracle(build_keys, build_valid, probe_keys, probe_valid):
    lookup = {
        int(k): i
        for i, (k, v) in enumerate(zip(build_keys, build_valid)) if v
    }
    return np.array([
        lookup.get(int(k), -1) if v else -1
        for k, v in zip(probe_keys, probe_valid)
    ], dtype=np.int32)


@pytest.mark.parametrize("seed", [0, 1])
def test_probe_matches_oracle(seed):
    rng = np.random.default_rng(seed)
    nb, np_ = 1000, 4096
    build = rng.choice(100000, size=nb, replace=False).astype(np.uint64)
    bvalid = rng.random(nb) < 0.9
    probe = rng.choice(100000, size=np_).astype(np.uint64)
    pvalid = rng.random(np_) < 0.95
    rid, overflow = PJ.join_unique(
        jnp.asarray(build), jnp.asarray(bvalid),
        jnp.asarray(probe), jnp.asarray(pvalid), interpret=True,
    )
    assert not bool(overflow)
    got = np.asarray(rid)
    want = oracle(build, bvalid, probe, pvalid)
    assert np.array_equal(got, want)


def test_probe_colliding_hashes():
    # keys crafted to collide in the table's low bits: chain probing must
    # still resolve every one of them
    build = np.arange(0, 64 * 1024, 1024, dtype=np.uint64)  # 64 keys
    bvalid = np.ones(64, bool)
    probe = np.concatenate([build, build + 1])  # half match, half miss
    pvalid = np.ones(128, bool)
    rid, overflow = PJ.join_unique(
        jnp.asarray(build), jnp.asarray(bvalid),
        jnp.asarray(probe), jnp.asarray(pvalid), interpret=True,
    )
    assert not bool(overflow)
    got = np.asarray(rid)
    assert np.array_equal(got[:64], np.arange(64, dtype=np.int32))
    assert np.all(got[64:] == -1)


def _ranges_oracle(bhash, bvalid, phash):
    """(start, count) per probe hash over the poison-sorted build order."""
    poisoned = np.where(bvalid, bhash, np.uint64(0xFFFFFFFFFFFFFFFF))
    order = np.argsort(poisoned, kind="stable")
    sh = poisoned[order]
    lo = np.searchsorted(sh, phash, side="left")
    hi = np.searchsorted(sh, phash, side="right")
    return lo.astype(np.int32), (hi - lo).astype(np.int32), order


@pytest.mark.parametrize(
    "layout", [("radix", (1, 4096)), ("radix", (4, 1024)), ("dim", 16)]
)
def test_ranges_match_oracle(layout):
    # duplicate keys: draws from a small universe so hash segments have
    # length > 1; multi-bucket/multi-tile layouts exercise the
    # partitioned tables
    rng = np.random.default_rng(3)
    nb, np_ = 1500, 4096
    bhash = rng.choice(500, size=nb).astype(np.uint64) * np.uint64(
        0x9E3779B97F4A7C15
    )
    bvalid = rng.random(nb) < 0.9
    phash = np.concatenate([
        rng.choice(500, size=np_ - 64).astype(np.uint64)
        * np.uint64(0x9E3779B97F4A7C15),
        rng.integers(1, 2**63, size=64, dtype=np.uint64),  # misses
    ])
    tabs, perm, overflow = PJ.build_index(
        jnp.asarray(bhash), jnp.asarray(bvalid), layout
    )
    assert not bool(overflow)
    start, cnt = PJ.probe_index(
        jnp.asarray(phash), tabs, layout, interpret=True
    )
    want_lo, want_cnt, want_order = _ranges_oracle(bhash, bvalid, phash)
    got_start, got_cnt = np.asarray(start), np.asarray(cnt)
    assert np.array_equal(got_cnt, want_cnt)
    hit = want_cnt > 0
    assert np.array_equal(got_start[hit], want_lo[hit])
    assert np.all(got_start[~hit] == -1)
    # the index's sorted order groups equal hashes contiguously
    sh = np.where(bvalid, bhash, np.uint64(0xFFFFFFFFFFFFFFFF))[
        np.asarray(perm)
    ]
    assert np.array_equal(sh, np.sort(sh))


def test_poison_hash_conflict_raises_overflow():
    # a VALID row whose hash equals the poison value (identity-encoded
    # BIGINT -1, or a 2^-64 real-hash collision) could interleave with
    # poisoned invalid rows and silently lose matches — build_index
    # must exclude it and raise the overflow escape so the query
    # retries on the exact sort join
    MAXH = np.uint64(0xFFFFFFFFFFFFFFFF)
    bhash = np.array([MAXH, 5, MAXH, 7], dtype=np.uint64)
    bvalid = np.array([True, False, True, True])
    layout = ("radix", (1, 64))
    tabs, perm, overflow = PJ.build_index(
        jnp.asarray(bhash), jnp.asarray(bvalid), layout
    )
    assert bool(overflow)
    # the excluded rows are not in the table; ordinary segments intact
    start, cnt = PJ.probe_index(
        jnp.asarray(np.array([MAXH, 7, 6], dtype=np.uint64)),
        tabs, layout, interpret=True,
    )
    start, cnt = np.asarray(start), np.asarray(cnt)
    assert cnt[0] == 0  # MAX-hash rows excluded, not half-returned
    assert cnt[1] == 1 and cnt[2] == 0


def test_big_key_values():
    # full 64-bit keys (hash encodings) round-trip through the lo/hi split
    rng = np.random.default_rng(7)
    build = rng.integers(0, 2**63, size=256, dtype=np.uint64)
    build = np.unique(build)
    nb = len(build)
    probe = np.concatenate([build[: nb // 2],
                            rng.integers(0, 2**63, size=128,
                                         dtype=np.uint64)])
    pad = (-len(probe)) % 128
    probe = np.concatenate([probe, np.zeros(pad, np.uint64)])
    rid, overflow = PJ.join_unique(
        jnp.asarray(build), jnp.asarray(np.ones(nb, bool)),
        jnp.asarray(probe), jnp.asarray(np.ones(len(probe), bool)),
        interpret=True,
    )
    got = np.asarray(rid)
    assert np.array_equal(got[: nb // 2], np.arange(nb // 2))
