"""Pallas hash-join probe kernel: correctness vs a numpy oracle in
interpret mode (runs on the CPU CI mesh; the real-TPU lowering is
exercised by bench.py's join microbench)."""

import numpy as np
import pytest

import jax.numpy as jnp

from presto_tpu.ops import pallas_join as PJ


def oracle(build_keys, build_valid, probe_keys, probe_valid):
    lookup = {
        int(k): i
        for i, (k, v) in enumerate(zip(build_keys, build_valid)) if v
    }
    return np.array([
        lookup.get(int(k), -1) if v else -1
        for k, v in zip(probe_keys, probe_valid)
    ], dtype=np.int32)


@pytest.mark.parametrize("seed", [0, 1])
def test_probe_matches_oracle(seed):
    rng = np.random.default_rng(seed)
    nb, np_ = 1000, 4096
    build = rng.choice(100000, size=nb, replace=False).astype(np.uint64)
    bvalid = rng.random(nb) < 0.9
    probe = rng.choice(100000, size=np_).astype(np.uint64)
    pvalid = rng.random(np_) < 0.95
    rid, overflow = PJ.join_unique(
        jnp.asarray(build), jnp.asarray(bvalid),
        jnp.asarray(probe), jnp.asarray(pvalid), interpret=True,
    )
    assert not bool(overflow)
    got = np.asarray(rid)
    want = oracle(build, bvalid, probe, pvalid)
    assert np.array_equal(got, want)


def test_probe_colliding_hashes():
    # keys crafted to collide in the table's low bits: chain probing must
    # still resolve every one of them
    build = np.arange(0, 64 * 1024, 1024, dtype=np.uint64)  # 64 keys
    bvalid = np.ones(64, bool)
    probe = np.concatenate([build, build + 1])  # half match, half miss
    pvalid = np.ones(128, bool)
    rid, overflow = PJ.join_unique(
        jnp.asarray(build), jnp.asarray(bvalid),
        jnp.asarray(probe), jnp.asarray(pvalid), interpret=True,
    )
    assert not bool(overflow)
    got = np.asarray(rid)
    assert np.array_equal(got[:64], np.arange(64, dtype=np.int32))
    assert np.all(got[64:] == -1)


def test_big_key_values():
    # full 64-bit keys (hash encodings) round-trip through the lo/hi split
    rng = np.random.default_rng(7)
    build = rng.integers(0, 2**63, size=256, dtype=np.uint64)
    build = np.unique(build)
    nb = len(build)
    probe = np.concatenate([build[: nb // 2],
                            rng.integers(0, 2**63, size=128,
                                         dtype=np.uint64)])
    pad = (-len(probe)) % 128
    probe = np.concatenate([probe, np.zeros(pad, np.uint64)])
    rid, overflow = PJ.join_unique(
        jnp.asarray(build), jnp.asarray(np.ones(nb, bool)),
        jnp.asarray(probe), jnp.asarray(np.ones(len(probe), bool)),
        interpret=True,
    )
    got = np.asarray(rid)
    assert np.array_equal(got[: nb // 2], np.arange(nb // 2))
