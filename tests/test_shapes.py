"""Shape canonicalization + compilation-reuse layer tests.

The contract (exec/shapes.py + compilecache.py): every dynamic
capacity quantizes onto one power-of-two bucket ladder and jit-cache
keys name canonical program content, so nearby planner estimates,
boosted retries, and repeated runs REUSE compiled programs instead of
minting fresh shapes — `programs_compiled` stays flat on a warmed run.
"""

import dataclasses

import numpy as np
import pytest

from presto_tpu import compilecache as CC
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec import plan as P
from presto_tpu.exec import shapes as SH
from presto_tpu.exec.executor import Executor


# ------------------------------------------------------------- ladder
def test_bucket_ladder_properties():
    assert SH.bucket(0) == SH.LADDER_MIN
    assert SH.bucket(8) == 8
    assert SH.bucket(9) == 16
    assert SH.bucket(1000) == 1024
    assert SH.bucket(1024) == 1024
    for n in (1, 7, 100, 4097, 1 << 20):
        b = SH.bucket(n)
        assert b >= n and b & (b - 1) == 0
    # next_bucket is STRICTLY above its argument (the retry re-entry
    # rung), and still on the ladder
    assert SH.next_bucket(8) == 16
    assert SH.next_bucket(9) == 16
    assert SH.next_bucket(16) == 32
    # boosted sizes stay on the ladder: bucket(est * boost) for a
    # pow2 boost is bucket(est) shifted — no off-ladder shapes
    for est in (100, 1000, 5000):
        assert (SH.bucket(est * SH.BOOST_STEP)
                == SH.bucket(est) * SH.BOOST_STEP)
    assert SH.next_boost(1) == SH.BOOST_STEP
    # chunk sizes land on the ladder (2x expected occupancy, floored)
    assert SH.chunk_bucket(1 << 20, 16) == (1 << 20) // 8
    assert SH.chunk_bucket(100, 64) == 1024


# ------------------------------------------- canonical page shapes
@pytest.fixture(scope="module")
def conn():
    return TpchConnector(scale=0.01)


def test_tail_splits_pad_to_bucketed_shapes(conn):
    # orders is a DENSE generator table: valid rows == table rows, so
    # padding is observable exactly (lineitem is slot-structured)
    total = conn.row_count("orders")
    pages = list(conn.pages(
        "orders", ["o_orderkey", "o_custkey"], target_rows=1 << 12
    ))
    # every page's shape is a ladder bucket (the tail split pads up
    # instead of minting an arbitrary program shape downstream)
    for p in pages:
        assert p.capacity == SH.bucket(p.capacity)
    # padded slots are invalid: row accounting is exact
    valid_rows = sum(int(np.asarray(p.valid).sum()) for p in pages)
    assert valid_rows == total
    # the tail split (total % 4096 = 2712 rows) shares the 4096 bucket
    # with the full splits: ONE program shape for the whole table
    assert {p.capacity for p in pages} == {1 << 12}


def _agg_plan(capacity: int) -> P.Output:
    scan = P.TableScan(
        catalog="tpch", table="lineitem",
        columns=("l_returnflag", "l_quantity"),
    )
    agg = P.Aggregation(
        source=scan,
        group_channels=(0,),
        aggregates=(
            P.AggSpec(function="sum", channel=1),
            P.AggSpec(function="count_star"),
        ),
        capacity=capacity,
    )
    return P.Output(source=agg, names=("flag", "s", "c"))


def _rows_sorted(rows):
    return sorted((str(r[0]), round(float(r[1]), 6), int(r[2]))
                  for r in rows)


def test_nearby_capacity_estimates_share_programs(conn):
    """Two plans differing only in the capacity estimate (same bucket)
    produce identical canonical shapes: the second run compiles
    NOTHING and re-traces nothing (jit-cache keys exclude the
    estimate; static caps quantize through the ladder)."""
    ex = Executor({"tpch": conn})
    _, rows1 = ex.execute(_agg_plan(1000))
    base = CC.snapshot()
    _, rows2 = ex.execute(_agg_plan(1010))  # same SH.bucket -> 1024
    d = CC.delta(base)
    assert ex.programs_compiled == 0
    assert d["programs_compiled"] == 0
    # no persistent-cache lookups either: nothing was even re-traced
    assert d["persistent_cache_misses"] == 0
    assert _rows_sorted(rows1) == _rows_sorted(rows2)


def test_overflow_retry_reuses_cached_programs(conn):
    """A capacity-overflow retry climbs the SHARED ladder: re-running
    the same overflowing query compiles zero fresh shapes (every
    boosted rung's programs were cached by the first run)."""
    # l_quantity has 50 distinct values; capacity 8 under-estimates,
    # so the query climbs the boost ladder before succeeding
    plan = P.Output(
        source=P.Aggregation(
            source=P.TableScan(
                catalog="tpch", table="lineitem",
                columns=("l_quantity", "l_orderkey"),
            ),
            group_channels=(0,),
            aggregates=(P.AggSpec(function="count_star"),),
            capacity=8,
        ),
        names=("q", "c"),
    )
    ex = Executor({"tpch": conn})
    _, rows1 = ex.execute(plan)
    assert len(rows1) == 50  # the retry actually happened and finished
    base = CC.snapshot()
    _, rows2 = ex.execute(plan)
    d = CC.delta(base)
    assert ex.programs_compiled == 0
    assert d["programs_compiled"] == 0
    assert sorted(rows1) == sorted(rows2)


def test_oracle_parity_under_bucketed_capacities(conn):
    """Bucketed capacities + padded tail pages change program shapes,
    never results: engine group-by matches a host-side oracle."""
    ex = Executor({"tpch": conn}, page_rows=1 << 14)  # forces tail pads
    _, rows = ex.execute(_agg_plan(1000))
    oracle = {}
    for page in conn.pages("lineitem", ["l_returnflag", "l_quantity"]):
        for flag, qty in page.to_pylist():
            s, c = oracle.get(flag, (0.0, 0))
            oracle[flag] = (s + float(qty), c + 1)
    want = sorted(
        (str(k), round(v[0], 6), v[1]) for k, v in oracle.items()
    )
    assert _rows_sorted(rows) == want


# ------------------------------------------------- cache/session wiring
def test_compile_cache_session_property(tmp_path):
    from presto_tpu.runner import LocalRunner

    runner = LocalRunner(
        {"tpch": TpchConnector(scale=0.001)}, default_catalog="tpch"
    )
    cache_dir = str(tmp_path / "cc")
    runner.session.set("compile_cache_dir", cache_dir)
    runner.apply_session()
    assert CC.cache_dir() == cache_dir
    # prewarm compiles the program set; a second prewarm finds
    # everything cached in-process
    runner.prewarm("select count(*) from lineitem")
    out = runner.prewarm("select count(*) from lineitem")
    assert out["programs_compiled"] == 0
    assert out["cache_dir"] == cache_dir


def test_explain_analyze_reports_compile_counters(conn):
    from presto_tpu.runner import LocalRunner

    runner = LocalRunner({"tpch": conn}, default_catalog="tpch")
    res = runner.execute(
        "explain analyze select count(*) from lineitem"
    )
    text = "\n".join(r[0] for r in res.rows)
    assert "programs_compiled=" in text
    assert "compile_wall_s=" in text
