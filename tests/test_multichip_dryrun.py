"""The driver's multi-chip gate, wired into the test suite.

__graft_entry__.dryrun_multichip is the contract the driver snapshot
checks between rounds: an n-device virtual mesh running the FULL
distributed engine step with exact single-device parity. It regressed
silently between snapshots once (VERDICT Weak #7) because nothing in
tier-1 exercised it — this wrapper makes any future break loud.

Runs in a SUBPROCESS because dryrun_multichip must set
XLA_FLAGS/JAX_PLATFORMS before jax initializes a backend, and the
pytest process (conftest.py) has long since latched its own 8-device
CPU config. Marked slow: it compiles the 8-device shard_map program
family (~minutes cold; the conftest-warmed persistent compile cache
makes repeat runs cheap).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_dryrun_multichip_subprocess():
    env = dict(os.environ)
    # fresh backend latch for the child; the persistent compile cache
    # (conftest default or the caller's override) carries over
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import __graft_entry__ as g; g.dryrun_multichip(8)",
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=1800,
    )
    assert proc.returncode == 0, (
        f"dryrun_multichip(8) failed (rc={proc.returncode})\n"
        f"stdout tail: {proc.stdout[-800:]}\n"
        f"stderr tail: {proc.stderr[-1500:]}"
    )
    assert "distributed == single" in proc.stdout
