"""Scalar-function breadth (functions_more): regexp_* completions,
string distances, varbinary/hash codecs, bitwise shifts, URL
extractors, array set algebra, map builders.

Reference: presto-main operator/scalar/{RegexpFunctions,
StringFunctions, VarbinaryFunctions, BitwiseFunctions, UrlFunctions,
ArrayFunctions, MapFunctions}. Expected values are hand-checked against
the reference semantics (python hashlib/zlib are the same algorithms).
"""

import hashlib

import pytest

from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.runner import LocalRunner


@pytest.fixture(scope="module")
def r():
    return LocalRunner({"mem": MemoryConnector()}, default_catalog="mem")


def one(r, sql):
    rows = r.execute(sql).rows
    assert len(rows) == 1 and len(rows[0]) == 1, rows
    return rows[0][0]


def test_regexp_family(r):
    assert one(r, "select regexp_extract_all('1a22b', '[0-9]+')") == \
        ("1", "22")
    assert one(
        r, "select regexp_extract_all('ab12cd', '([a-z])([0-9])', 2)"
    ) == ("1",)  # groups of the 'b1' match: 1->'b', 2->'1'
    assert one(r, "select regexp_count('1a2b3', '[0-9]')") == 3
    assert one(r, "select regexp_position('ab1', '[0-9]')") == 3
    assert one(r, "select regexp_position('abc', '[0-9]')") == -1
    assert one(r, "select regexp_split('1a2b', '[ab]')") == \
        ("1", "2", "")


def test_string_distances_and_transforms(r):
    assert one(
        r, "select levenshtein_distance('kitten', 'sitting')") == 3
    assert one(r, "select hamming_distance('abc', 'abd')") == 1
    assert one(r, "select hamming_distance('a', 'ab')") is None
    assert one(r, "select translate('abcda', 'ab', 'x')") == "xcdx"
    assert one(r, "select soundex('Robert')") == "R163"
    assert one(r, "select soundex('Rupert')") == "R163"
    assert one(r, "select luhn_check('79927398713')") is True
    assert one(r, "select luhn_check('79927398714')") is False
    # column (non-constant) pair path
    got = r.execute(
        "select levenshtein_distance(a, b) from ("
        "  select 'abc' a, 'axc' b union all select 'x', 'xyz')"
    ).rows
    assert sorted(v for (v,) in got) == [1, 2]


def test_varbinary_and_hashes(r):
    assert one(r, "select crc32(to_utf8('abc'))") == 891568578
    assert one(r, "select from_utf8(to_utf8('héllo'))") == "héllo"
    assert one(r, "select sha512(to_utf8('abc'))") == \
        hashlib.sha512(b"abc").digest()
    assert one(
        r, "select hmac_sha256(to_utf8('msg'), to_utf8('key'))"
    ) == __import__("hmac").new(b"key", b"msg", "sha256").digest()
    # xxhash64 over one 8-byte value matches the device kernel's
    # airlift-compatible hash(long) (little-endian bytes of 7)
    import numpy as np

    from presto_tpu.ops.hashing import xxhash64_host, xxhash64_u64
    want = int(np.asarray(
        xxhash64_u64(np.uint64(7))
    ).astype(np.uint64))
    assert xxhash64_host((7).to_bytes(8, "little")) == want
    # and the full byte-string algorithm matches the reference
    # implementation for every tail-length class
    xxhash = pytest.importorskip("xxhash")
    for data in (b"", b"a", b"abc", b"abcd", b"abcde",
                 bytes(range(33)), bytes(range(100))):
        assert xxhash64_host(data) == xxhash.xxh64(data).intdigest()


def test_shift_overflow_semantics(r):
    assert one(r, "select bitwise_left_shift(1, 64)") == 0
    assert one(r, "select bitwise_right_shift(-1, 64)") == 0
    assert one(
        r, "select bitwise_right_shift_arithmetic(-16, 64)") == -1


def test_serde_preserves_typed_dictionary_values():
    import jax.numpy as jnp

    from presto_tpu import types as T
    from presto_tpu.dist.serde import deserialize_page, serialize_page
    from presto_tpu.page import Block, Dictionary, Page

    pg = Page(blocks=(
        Block(data=jnp.zeros(4, jnp.int32), type=T.VARBINARY,
              dictionary=Dictionary([b"hello"])),
        Block(data=jnp.zeros(4, jnp.int32),
              type=T.ArrayType(T.BIGINT),
              dictionary=Dictionary([(1, 2, None)])),
    ), valid=jnp.ones(4, bool))
    out = deserialize_page(serialize_page(pg))
    assert out.blocks[0].dictionary.values[0] == b"hello"
    assert out.blocks[1].dictionary.values[0] == (1, 2, None)


def test_bitwise(r):
    assert one(r, "select bitwise_left_shift(1, 3)") == 8
    assert one(r, "select bitwise_right_shift(-1, 60)") == 15
    assert one(
        r, "select bitwise_right_shift_arithmetic(-16, 2)") == -4
    assert one(r, "select bit_length('ab')") == 16


def test_url_family(r):
    u = "'http://user@h.com:8080/a/b?q=1&r=2#frag'"
    assert one(r, f"select url_extract_host({u})") == "h.com"
    assert one(r, f"select url_extract_port({u})") == 8080
    assert one(r, f"select url_extract_path({u})") == "/a/b"
    assert one(r, f"select url_extract_protocol({u})") == "http"
    assert one(r, f"select url_extract_query({u})") == "q=1&r=2"
    assert one(r, f"select url_extract_fragment({u})") == "frag"
    assert one(r, "select url_encode('a b/c')") == "a%20b%2Fc"
    assert one(r, "select url_decode('a%20b')") == "a b"


def test_array_set_algebra(r):
    assert one(
        r, "select array_union(array[1,2,2], array[2,3])") == (1, 2, 3)
    assert one(
        r, "select array_intersect(array[1,2], array[2,3])") == (2,)
    assert one(
        r, "select array_except(array[1,2], array[2,3])") == (1,)
    assert one(
        r, "select arrays_overlap(array[1,2], array[2,9])") is True
    assert one(
        r, "select arrays_overlap(array[1,2], array[3])") is False
    assert one(r, "select zip(array[1,2], array[9])") == \
        ((1, 9), (2, None))
    assert one(
        r,
        "select zip_with(array[1,2], array[10,20], (x, y) -> x + y)"
    ) == (11, 22)
    # column inputs through the pair universe
    got = r.execute(
        "select array_union(a, b) from ("
        "  select array[1] a, array[2] b "
        "  union all select array[3], array[3])"
    ).rows
    assert sorted(v for (v,) in got) == [(1, 2), (3,)]


def test_map_builders(r):
    assert dict(one(
        r,
        "select map_concat(map(array[1], array[10]), "
        "map(array[1,2], array[11,12]))"
    )) == {1: 11, 2: 12}
    assert dict(one(
        r, "select split_to_map('a=1,b=2', ',', '=')"
    )) == {"a": "1", "b": "2"}
    assert dict(one(
        r,
        "select map_from_entries(map_entries(map(array[5], array[6])))"
    )) == {5: 6}
