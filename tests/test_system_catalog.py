"""The system catalog: live engine state as SQL tables.

Reference: presto-main SystemConnector (system.runtime.*),
information_schema, and the jmx connector's SQL-over-metrics (SURVEY
§6.5 keeps "SQL over the engine's own metrics" a build goal).
"""

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runner import LocalRunner


@pytest.fixture(scope="module")
def runner():
    return LocalRunner({"tpch": TpchConnector(0.01)}, page_rows=1 << 13)


def test_metadata_tables(runner):
    cats = runner.execute(
        "select catalog_name from system.catalogs order by 1"
    ).rows
    assert [c[0] for c in cats] == ["system", "tpch"]
    tabs = runner.execute(
        "select count(*) from system.tables where table_catalog = 'tpch'"
    ).rows
    assert tabs[0][0] == 8  # the 8 TPC-H tables
    cols = runner.execute(
        "select column_name, ordinal_position from system.columns "
        "where table_name = 'region' order by 2"
    ).rows
    assert [c[0] for c in cols] == [
        "r_regionkey", "r_name", "r_comment"
    ]


def test_session_and_functions_tables(runner):
    v = runner.execute(
        "select value from system.session_properties "
        "where name = 'tpu_offload_enabled'"
    ).rows
    assert v == [("true",)]
    n = runner.execute(
        "select count(*) from system.functions"
    ).rows[0][0]
    assert n >= 90  # the builtin registry


def test_joins_and_aggregation_over_system(runner):
    # the engine's own operators run over system pages (host staging)
    got = runner.execute(
        "select t.table_name, count(*) c from system.tables t, "
        "system.columns c where t.table_name = c.table_name "
        "and t.table_catalog = 'tpch' and c.table_catalog = 'tpch' "
        "group by 1 order by 2 desc, 1 limit 2"
    ).rows
    assert got[0][0] == "lineitem" and got[0][1] == 16


def test_session_properties_track_client_session():
    # the concurrent (memory-arbiter) path builds a runner per query
    # but shares the system connector — the table must show the
    # QUERYING client's session, not the bootstrap runner's
    from presto_tpu.client import StatementClient
    from presto_tpu.server.http_server import PrestoTpuServer

    srv = PrestoTpuServer({"tpch": TpchConnector(0.01)}, port=0,
                          page_rows=1 << 13,
                          memory_budget_bytes=1 << 32)
    srv.start()
    try:
        c = StatementClient(server=f"http://127.0.0.1:{srv.port}")
        c.session_properties["spill_threshold_bytes"] = "12345"
        got = c.execute(
            "select value from system.session_properties "
            "where name = 'spill_threshold_bytes'"
        ).rows
        assert got == [["12345"]] or got == [("12345",)], got
    finally:
        srv.stop()


def test_server_runtime_tables():
    from presto_tpu.client import StatementClient
    from presto_tpu.server.http_server import PrestoTpuServer

    srv = PrestoTpuServer({"tpch": TpchConnector(0.01)}, port=0,
                          page_rows=1 << 13)
    srv.start()
    try:
        c = StatementClient(server=f"http://127.0.0.1:{srv.port}")
        c.execute("select 1")
        rows = c.execute(
            "select state, count(*) from system.runtime_queries "
            "group by 1 order by 1"
        ).rows
        states = {r[0] for r in rows}
        assert "FINISHED" in states or "RUNNING" in states, rows
        nodes = c.execute("select uri, is_coordinator from system.nodes"
                          ).rows
        assert len(nodes) == 1 and int(nodes[0][1]) == 1
        m = c.execute(
            "select value from system.metrics "
            "where name = 'rows_returned_total'"
        ).rows
        assert int(m[0][0]) >= 1
    finally:
        srv.stop()
