"""ISSUE 16: the wire-efficient exchange plane.

Covers the three tentpole layers plus the satellites:
  - serde round-trip property suite over the codec x type matrix
    (dictionary, boolean, RLE, nulls, -0.0/NaN, decimal, varbinary,
    nested array/map/row) in every wire mode, with byte-stability of
    re-serialization (the replay-prefix sha256 contract);
  - version-byte rejection of unknown/old formats and pointed
    PageWireError on truncated blobs at EVERY prefix length;
  - the NaN-RLE fix (constant-NaN columns collapse; mixed +0.0/-0.0
    columns do NOT, and signs survive bit-exactly);
  - codec engagement size pins: narrowest-int downcast and boolean
    bitpack beat the raw wire by the expected factors;
  - streaming/ranged spool fetch: bounded in-flight-bytes responses,
    multi-request drain of a multi-page partition, frame/legacy
    byte equivalence;
  - connection pool: keep-alive reuse counted, loud fresh-connection
    fallback on a dead pooled destination, urlopen-compatible
    HTTPError semantics;
  - THE acceptance pin: the forced-partitioned q3-family exchange
    (host-spool path) ships >= 2x fewer exchange_wire_bytes than the
    zlib-only baseline with rows identical to the uncompressed path
    AND the sqlite oracle.
"""

import collections
import math
import struct
import urllib.error

import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.dist import connpool as CONNPOOL
from presto_tpu.dist import serde
from presto_tpu.dist import spool as SPOOL
from presto_tpu.dist.dcn import DcnRunner
from presto_tpu.page import Page
from presto_tpu.server.worker import (
    WorkerServer,
    local_runtime,
    route_task_get,
)
from tests.oracle import load_sqlite

SF = 0.01
PAGE_ROWS = 1 << 13

Q3_FAMILY = (
    "select o_orderkey, count(*) c from lineitem "
    "join orders on l_orderkey = o_orderkey "
    "where o_orderkey < 1000 group by o_orderkey order by o_orderkey"
)


def rows_equal(a, b):
    return collections.Counter(map(repr, a)) == collections.Counter(
        map(repr, b))


@pytest.fixture
def wire_mode():
    """Set-and-restore helper for the serde wire mode."""
    prev = []

    def set_mode(mode):
        prev.append(serde.set_wire_mode(mode))

    yield set_mode
    while prev:
        serde.set_wire_mode(prev.pop())


# ------------------------------------------------ codec x type matrix
_MATRIX = {
    "bigint": ([[1, -7, None, 2**40, 0, -1]], [T.BIGINT]),
    "bigint-downcast8": ([[i % 100 for i in range(300)]], [T.BIGINT]),
    "bigint-downcast16": ([[i * 7 for i in range(3000)]], [T.BIGINT]),
    "bigint-downcast32": ([[i * 100_000 for i in range(500)]],
                          [T.BIGINT]),
    "bigint-constant": ([[42] * 200], [T.BIGINT]),
    "double-specials": ([[1.5, -0.0, 0.0, None, float("nan"),
                          float("inf"), -float("inf"), 1e300]],
                        [T.DOUBLE]),
    "double-constant-nan": ([[float("nan")] * 500], [T.DOUBLE]),
    "all-null": ([[None] * 64], [T.BIGINT]),
    "boolean": ([[True, False, None, True] * 40], [T.BOOLEAN]),
    "boolean-constant": ([[True] * 333], [T.BOOLEAN]),
    "varchar-dict": ([["apple", "banana", None, "apple", "cherry"]
                      * 30], [T.VarcharType()]),
    "varbinary": ([[b"\x00\xff", b"abc", None, b"", b"\x00\xff"]],
                  [T.VarbinaryType()]),
    "decimal-short": ([[105, None, -205, 305, 0]],
                      [T.DecimalType(9, 2)]),
    "decimal-long": ([[10**25 + 7, -(10**30), None, 42, 0]],
                     [T.DecimalType(38, 2)]),
    "nested-array": ([[(1, 2, 3), (5,), None, (), (1, 2, 3)]],
                     [T.ArrayType(T.BIGINT)]),
    "nested-map": ([[(("a", 1), ("b", 2)), (("c", 3),), None, ()]],
                   [T.MapType(T.VarcharType(), T.BIGINT)]),
    "nested-row": ([[("x", 1), ("y", 2), None, ("x", 1)]],
                   [T.RowType(fields=(T.VarcharType(), T.BIGINT))]),
    "multi-column": ([[1, 2, None], [1.5, None, float("nan")],
                      ["a", "b", None]],
                     [T.BIGINT, T.DOUBLE, T.VarcharType()]),
}


@pytest.mark.parametrize("mode", ["full", "zlib", "raw"])
@pytest.mark.parametrize("case", sorted(_MATRIX))
def test_roundtrip_matrix(case, mode, wire_mode):
    """Every codec x type combination round-trips value-exactly in
    every wire mode, and RE-serialization is byte-identical (the
    replay prefix contract: dcn._prefix_matches compares rolling
    sha256 of wire bytes across re-fetches)."""
    wire_mode(mode)
    cols, types = _MATRIX[case]
    page = Page.from_arrays(cols, types)
    blob = serde.serialize_page(page)
    page2 = serde.deserialize_page(blob)
    assert rows_equal(page2.to_pylist(), page.to_pylist())
    assert serde.serialize_page(page2) == blob


def test_modes_agree_on_rows(wire_mode):
    """The codec plane changes bytes-on-wire, never values: full,
    zlib-baseline, and raw modes deserialize to identical rows."""
    cols, types = _MATRIX["multi-column"]
    page = Page.from_arrays(cols, types)
    out = {}
    for mode in ("full", "zlib", "raw"):
        wire_mode(mode)
        # compare by repr: the matrix carries NaN, and NaN != NaN
        out[mode] = repr(serde.deserialize_page(
            serde.serialize_page(page)).to_pylist())
    assert out["full"] == out["zlib"] == out["raw"]


# ----------------------------------------------- hardening satellites
def test_old_format_rejected_loudly():
    bad = b"PTP2" + struct.pack("<ii", 2, 2) + b"{}xx"
    with pytest.raises(serde.PageWireError, match="version"):
        serde.deserialize_page(bad)


def test_garbage_rejected():
    for blob in (b"", b"x", b"not a page at all", b"PTP"):
        with pytest.raises(serde.PageWireError):
            serde.deserialize_page(blob)


def test_every_truncation_raises_pointed_error():
    """A short read can NEVER misparse: every strict prefix of a
    valid blob raises PageWireError (pre-v3, np.frombuffer would
    silently read garbage at a bad offset)."""
    page = Page.from_arrays(
        [[1, 2, None, 4], ["a", None, "b", "a"]],
        [T.BIGINT, T.VarcharType()])
    blob = serde.serialize_page(page)
    for cut in range(len(blob)):
        with pytest.raises(serde.PageWireError):
            serde.deserialize_page(blob[:cut])


def test_corrupt_lengths_raise():
    page = Page.from_arrays([[1, 2, 3]], [T.BIGINT])
    blob = bytearray(serde.serialize_page(page))
    # header length pointing past the end of the blob
    blob[5:9] = struct.pack("<i", len(blob) + 100)
    with pytest.raises(serde.PageWireError, match="overrun"):
        serde.deserialize_page(bytes(blob))


def test_constant_nan_collapses_to_rle(wire_mode):
    """The pre-v3 detector used value equality (`arr == arr.flat[0]`),
    which is False for NaN — constant-NaN float columns (and NaN
    null-backings) never collapsed. v3 tests BYTES."""
    n = 4096
    page = Page.from_arrays([[float("nan")] * n], [T.DOUBLE])
    blob = serde.serialize_page(page)
    # an RLE'd data column ships ONE element, not n * 8 bytes
    assert len(blob) < n
    back = serde.deserialize_page(blob).to_pylist()
    assert all(math.isnan(r[0]) for r in back[:n])


def test_mixed_zero_signs_do_not_collapse():
    """-0.0 == 0.0 under value equality; byte equality keeps a mixed
    column off the RLE path so signs survive the wire bit-exactly."""
    vals = [0.0, -0.0, 0.0, -0.0, 0.0, 0.0]
    page = Page.from_arrays([vals], [T.DOUBLE])
    back = serde.deserialize_page(serde.serialize_page(page))
    got = [r[0] for r in back.to_pylist()]
    assert [math.copysign(1.0, v) for v in got] == \
        [math.copysign(1.0, v) for v in vals]


def test_downcast_and_boolpack_beat_raw(wire_mode):
    """Size pins for the codec chooser: narrowest-int downcast on a
    small-range int64 column and bitpack on a boolean column ship a
    fraction of the raw wire."""
    import random

    rng = random.Random(7)
    n = 8000
    ints = Page.from_arrays(
        [[rng.randrange(-100, 100) for _ in range(n)]], [T.BIGINT])
    bools = Page.from_arrays(
        [[rng.random() < 0.5 for _ in range(n)]], [T.BOOLEAN])
    wire_mode("raw")
    raw_i = len(serde.serialize_page(ints))
    raw_b = len(serde.serialize_page(bools))
    wire_mode("full")
    full_i = len(serde.serialize_page(ints))
    full_b = len(serde.serialize_page(bools))
    # random bytes defeat zlib: the structural codecs carry the win
    assert full_i * 3 < raw_i     # int64 -> int8 (+ frame overhead)
    assert full_b * 3 < raw_b     # bool -> bitmap
    for p, blob_mode in ((ints, "full"), (bools, "full")):
        wire_mode(blob_mode)
        assert rows_equal(
            serde.deserialize_page(serde.serialize_page(p)).to_pylist(),
            p.to_pylist())


def test_wire_counters_meter_serialize():
    page = Page.from_arrays([[1, 2, 3, None]], [T.BIGINT])
    t0 = serde.wire_totals()
    blob = serde.serialize_page(page)
    t1 = serde.wire_totals()
    assert t1["exchange_wire_bytes"] - t0["exchange_wire_bytes"] \
        == len(blob)
    assert t1["exchange_raw_bytes"] > t0["exchange_raw_bytes"]


# --------------------------------------------- streaming spool fetch
@pytest.fixture(scope="module")
def spooled_task():
    """One finished worker task with a multi-page spooled partition
    (small page_rows so the full orders scan spools dozens of
    pages)."""
    import json
    import time as _time

    from presto_tpu.dist import plan_serde
    from presto_tpu.dist.fragmenter import clip_for_shipping
    from presto_tpu.runner import LocalRunner

    w = WorkerServer({"tpch": TpchConnector(SF)}, node_id="ws1",
                     default_catalog="tpch", page_rows=256)
    uri = f"http://127.0.0.1:{w.start()}"
    r = LocalRunner({"tpch": TpchConnector(SF)}, page_rows=256)
    plan = r.plan("select o_orderkey, o_custkey from orders")
    payload = {
        "taskId": "wiretest.f0.t0",
        "sql": None,
        "splitTable": "orders",
        "splitIndex": 0,
        "splitCount": 1,
        "outputPartitions": 2,
        "outputKeys": [0],
        "session": {},
        "fragment": plan_serde.dumps(clip_for_shipping(plan)),
    }
    with CONNPOOL.request(f"{uri}/v1/task", method="POST",
                          data=json.dumps(payload).encode(),
                          headers={"Content-Type": "application/json"},
                          timeout=30) as resp:
        resp.read()
    deadline = _time.monotonic() + 60
    while _time.monotonic() < deadline:
        with CONNPOOL.request(f"{uri}/v1/task/wiretest.f0.t0",
                              timeout=10) as resp:
            st = __import__("json").loads(resp.read().decode())
        if st["state"] != "RUNNING":
            break
        _time.sleep(0.05)
    assert st["state"] == "FINISHED", st.get("error")
    yield uri, "wiretest.f0.t0"
    w.stop()


def test_streaming_fetch_bounds_inflight_bytes(spooled_task):
    """THE backpressure pin: draining a multi-page partition with a
    window far smaller than the partition takes MULTIPLE bounded
    responses — each response body stays under window + one page —
    and yields byte-identical blobs to the legacy single-blob
    protocol."""
    uri, tid = spooled_task
    rt = local_runtime(uri)
    task = rt.get_task(tid)
    npages = task.part_count(0)
    assert npages > 8, "fixture must spool a multi-page partition"

    # legacy single-blob walk (no ?max): the reference stream
    legacy = []
    token = 0
    while True:
        resp = route_task_get(rt, f"/v1/task/{tid}/results/{token}",
                              "part=0")
        status, headers, _, body = resp
        if status == 204:
            assert dict(headers)["X-Done"] == "1"
            break
        legacy.append(body)
        token = int(dict(headers)["X-Next-Token"])
    total_bytes = sum(map(len, legacy))
    biggest = max(map(len, legacy))

    window = max(biggest, 2048)
    assert total_bytes > 4 * window, "window must be << partition"

    # ranged walk: bounded responses, multiple round trips
    framed = []
    sizes = []
    multi_frame = 0
    token = 0
    requests = 0
    while True:
        resp = route_task_get(
            rt, f"/v1/task/{tid}/results/{token}",
            f"part=0&max={window}")
        status, headers, _, body = resp
        requests += 1
        if status == 204:
            assert dict(headers)["X-Done"] == "1"
            break
        hd = dict(headers)
        sizes.append(len(body))
        if int(hd["X-Frames"]) > 1:
            multi_frame += 1
        nxt = int(hd["X-Next-Token"])
        assert nxt - token == int(hd["X-Frames"])
        token = nxt
        buf = memoryview(body)
        while buf:
            (ln,) = struct.unpack_from("<q", buf, 0)
            framed.append(bytes(buf[8:8 + ln]))
            buf = buf[8 + ln:]
    assert framed == legacy
    assert requests > 1, "one window must not swallow the partition"
    assert multi_frame >= 1, "ranged responses must batch frames"
    assert max(sizes) <= window + biggest + 8 * npages

    # the HTTP client end: incremental frames, same bytes, same rows
    via_http = list(SPOOL.fetch_spool_blobs(uri, tid, 0,
                                            window_bytes=window))
    assert via_http == legacy
    rows = [r for b in via_http
            for r in serde.deserialize_page(b).to_pylist()]
    assert len(rows) == sum(
        len(serde.deserialize_page(b).to_pylist()) for b in legacy)


def test_streaming_fetch_multiple_http_requests(spooled_task):
    """The live-socket path: a small window forces several pooled
    HTTP round trips (counted on the worker's results-call tally),
    and blobs match an unbounded-window fetch."""
    uri, tid = spooled_task
    rt = local_runtime(uri)
    calls0 = rt._results_calls
    small = list(SPOOL.fetch_spool_blobs(uri, tid, 1,
                                         window_bytes=4096))
    calls_small = rt._results_calls - calls0
    big = list(SPOOL.fetch_spool_blobs(uri, tid, 1,
                                       window_bytes=1 << 30))
    assert small == big and small
    assert calls_small > 2


# ------------------------------------------------- connection pool
def test_connpool_reuses_keepalive_conns(spooled_task):
    uri, tid = spooled_task
    t0 = CONNPOOL.pool_totals()["exchange_fetch_reused_conns"]
    for _ in range(3):
        with CONNPOOL.request(f"{uri}/v1/task/{tid}", timeout=10) as r:
            r.read()
    assert CONNPOOL.pool_totals()["exchange_fetch_reused_conns"] \
        - t0 >= 2


def test_connpool_http_error_semantics(spooled_task):
    """urlopen-compatible errors: a 404 raises HTTPError with code,
    headers, and readable body intact (the X-Task-Error / 410
    handling on the fetch plane depends on this shape)."""
    uri, _ = spooled_task
    with pytest.raises(urllib.error.HTTPError) as ei:
        CONNPOOL.request(f"{uri}/v1/task/nope-never-existed",
                         timeout=10)
    assert ei.value.code == 404
    assert b"no such task" in ei.value.read()


def test_connpool_loud_fallback_on_dead_destination():
    """A stale pooled connection (peer closed the keep-alive socket
    between requests) fails over to a fresh connect ONCE — counted,
    and the request still succeeds; a genuinely dead destination
    raises URLError so the caller's bounded retry ladders keep their
    semantics."""
    w = WorkerServer({"tpch": TpchConnector(SF)}, node_id="dead1",
                     default_catalog="tpch", page_rows=PAGE_ROWS)
    port = w.start()
    uri = f"http://127.0.0.1:{port}"
    try:
        with CONNPOOL.request(f"{uri}/v1/info", timeout=10) as r:
            r.read()  # parks one keep-alive connection in the pool
        parked = CONNPOOL._POOL._conns.get(("http", f"127.0.0.1:{port}"))
        assert parked, "expected a parked keep-alive connection"
        # kill the OS socket out from under the pool while leaving
        # conn.sock set, so http.client does NOT silently reconnect —
        # the next request on the stale conn must fail over
        for c in parked:
            if c.sock is not None:
                c.sock.close()
        f0 = CONNPOOL.pool_totals()["exchange_pool_failovers"]
        with CONNPOOL.request(f"{uri}/v1/info", timeout=10) as r:
            assert r.status == 200
            r.read()
        assert CONNPOOL.pool_totals()["exchange_pool_failovers"] >= f0 + 1
    finally:
        w.stop()
        CONNPOOL.reset_pool()
    # genuinely dead destination: fresh connect refused -> URLError
    with pytest.raises(urllib.error.URLError):
        CONNPOOL.request("http://127.0.0.1:1/v1/info", timeout=5)


# ------------------------------------------- acceptance: wire bytes
@pytest.fixture(scope="module")
def workers():
    w1 = WorkerServer({"tpch": TpchConnector(SF)}, node_id="wq1",
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    w2 = WorkerServer({"tpch": TpchConnector(SF)}, node_id="wq2",
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    uris = [f"http://127.0.0.1:{w1.start()}",
            f"http://127.0.0.1:{w2.start()}"]
    yield uris
    w1.stop()
    w2.stop()


def _coord(workers, **props):
    defaults = {
        "stage_scheduler": "true",
        "join_distribution_type": "partitioned",
        "retry_backoff_ms": 20,
    }
    defaults.update(props)
    return DcnRunner({"tpch": TpchConnector(SF)}, workers,
                     default_catalog="tpch", page_rows=PAGE_ROWS,
                     session_props=defaults)


def _run_wire(workers, mode):
    prev = serde.set_wire_mode(mode)
    try:
        coord = _coord(workers, device_exchange_enabled="false")
        t0 = serde.wire_totals()
        rows = coord.execute(Q3_FAMILY)
        t1 = serde.wire_totals()
    finally:
        serde.set_wire_mode(prev)
    return rows, t1["exchange_wire_bytes"] - t0["exchange_wire_bytes"]


def test_q3_family_wire_bytes_halved(workers):
    """THE acceptance pin: the forced-partitioned q3-family exchange
    on the host-spool path ships >= 2x fewer exchange_wire_bytes
    under the v3 codecs than the zlib-only baseline, with rows
    identical to the uncompressed wire AND the sqlite oracle."""
    rows_full, wire_full = _run_wire(workers, "full")
    rows_zlib, wire_zlib = _run_wire(workers, "zlib")
    rows_raw, wire_raw = _run_wire(workers, "raw")
    assert wire_full > 0 and wire_zlib > 0
    assert rows_equal(rows_full, rows_raw)
    assert rows_equal(rows_full, rows_zlib)
    db = load_sqlite(TpchConnector(SF), ["lineitem", "orders"])
    assert rows_equal(rows_full, db.execute(Q3_FAMILY).fetchall())
    assert wire_zlib >= 2 * wire_full, (
        f"codec win too small: zlib-only {wire_zlib}B vs "
        f"full {wire_full}B ({wire_zlib / wire_full:.2f}x)")
    assert wire_raw > wire_zlib


def test_exchange_counters_on_executor_surface(workers):
    """exchange_wire_bytes / exchange_raw_bytes /
    exchange_fetch_reused_conns are registry counters: declared in
    QUERY_COUNTERS and visible on the coordinator executor after a
    distributed query (the workers share this process, so the
    thread-bound sinks land on in-process executors)."""
    from presto_tpu.exec.counters import QUERY_COUNTERS

    for name in ("exchange_wire_bytes", "exchange_raw_bytes",
                 "exchange_fetch_reused_conns"):
        assert name in QUERY_COUNTERS
    t0 = CONNPOOL.pool_totals()["exchange_fetch_reused_conns"]
    coord = _coord(workers, device_exchange_enabled="false")
    coord.execute(Q3_FAMILY)
    # connection reuse engaged on the shuffle plane for this query
    assert CONNPOOL.pool_totals()["exchange_fetch_reused_conns"] > t0
    # wire bytes metered somewhere on this process's executor family
    # (worker task executors run in-process under the module fixture)
    ex = coord.runner.executor
    assert ex.exchange_wire_bytes >= 0
    assert serde.wire_totals()["exchange_wire_bytes"] > 0
