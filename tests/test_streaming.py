"""ISSUE 14: the streaming subsystem — append-log connector
(connectors/stream.py), incremental view maintenance
(streaming/ivm.py), monotone offset tokens in the cache plane, and
tailing /v1/statement cursors.

Covers the subsystem contract by contract:
  - append-log semantics: offsets advance monotonically, delta scans
    emit only new pages, full scans compose with the ordinary engine;
  - THE acceptance pin: after an initial refresh over N rows,
    appending M << N rows and refreshing folds only the delta
    (delta_pages_folded >= 1, ivm_full_recomputes == 0, scanned-row
    accounting == M, not N) with rows identical to a cold full
    recompute AND the sqlite oracle (floats at the established
    9-sig-digit tolerance);
  - append -> refresh -> append -> refresh chains;
  - the loud full-recompute fallback (non-IVM-safe shapes,
    ivm_enabled=false) — counted, never silently wrong;
  - monotone offset tokens: a pinned-offset fragment entry still HITS
    after the log advances (the append path reclaims only live-head
    entries);
  - tailing cursors: exactly-the-delta rows per poll, the IVM path
    for registered view shapes, and a concurrent appender x 4 tailing
    clients at zero lock-sanitizer violations;
  - counter registration on every surface and the loadbench
    append-writers harness.
"""

import collections
import json
import random
import threading
import urllib.request

import pytest

from presto_tpu import types as T
from presto_tpu.cache import ResultCache, shared_cache_if_exists
from presto_tpu.connectors.stream import (
    StreamConnector,
    StreamWindowConnector,
)
from presto_tpu.runner import LocalRunner
from presto_tpu.streaming import ivm as IVM

PAGE_ROWS = 1 << 11

VIEW_SQL = ("select k, count(*), sum(v), max(v) from events "
            "group by k order by k")


def _mkconn(n_rows: int, seed: int = 0, groups: int = 8):
    rng = random.Random(seed)
    conn = StreamConnector()
    conn.create_table(
        "events", ["k", "v"], [T.BIGINT, T.DOUBLE],
        [(rng.randrange(groups), rng.random() * 100.0)
         for _ in range(n_rows)],
    )
    return conn, rng


def _runner(conn):
    return LocalRunner({"stream": conn}, default_catalog="stream",
                       page_rows=PAGE_ROWS)


def _batch(rng, m: int, groups: int = 8):
    return [(rng.randrange(groups), rng.random() * 100.0)
            for _ in range(m)]


def _rows_close(a, b, tol=1e-9):
    assert len(a) == len(b), f"{len(a)} vs {len(b)} rows"
    for ra, rb in zip(a, b):
        assert len(ra) == len(rb)
        for va, vb in zip(ra, rb):
            if isinstance(va, float) or isinstance(vb, float):
                assert abs(float(va) - float(vb)) <= tol * max(
                    1.0, abs(float(vb))), (va, vb)
            else:
                assert va == vb, (va, vb)


@pytest.fixture(autouse=True)
def _clean_shared_state():
    """The shared result cache and IVM registry are process-shared by
    design; tests must not leak entries/views into each other."""
    rc = shared_cache_if_exists()
    if rc is not None:
        rc.clear()
    reg = IVM.shared_registry_if_exists()
    if reg is not None:
        for v in reg.views():
            reg.unregister(v.name)
    yield
    rc = shared_cache_if_exists()
    if rc is not None:
        rc.clear()
    reg = IVM.shared_registry_if_exists()
    if reg is not None:
        for v in reg.views():
            reg.unregister(v.name)


# ------------------------------------------------- append-log connector
def test_append_advances_offset_and_token():
    conn, rng = _mkconn(100)
    assert conn.offset("events") == 100
    assert conn.snapshot_version("events") == "off:100"
    new = conn.append("events", _batch(rng, 7))
    assert new == 107
    assert conn.snapshot_version("events") == "off:107"
    assert conn.appends_seen("events") >= 2  # create seed + append


def test_delta_scan_emits_only_new_rows():
    conn, rng = _mkconn(500)
    base = conn.offset("events")
    batch = _batch(rng, 23)
    conn.append("events", batch)
    pages = list(conn.scan_from("events", base))
    got = [r for p in pages for r in p.to_pylist()]
    assert len(got) == 23
    _rows_close(got, batch)
    # a delta scan from the head is empty
    assert list(conn.scan_from("events", conn.offset("events"))) == []


def test_full_scan_composes_with_engine_and_oracle():
    from tests.oracle import load_sqlite

    conn, _rng = _mkconn(1200)
    r = _runner(conn)
    got = r.execute(VIEW_SQL).rows
    db = load_sqlite(conn, ["events"])
    want = db.execute(
        "select k, count(*), sum(v), max(v) from events "
        "group by k order by k").fetchall()
    _rows_close(got, [tuple(w) for w in want])


def test_window_connector_pins_range():
    conn, rng = _mkconn(300)
    w = StreamWindowConnector(conn, "events", 0, 300)
    assert w.row_count("events") == 300
    assert w.snapshot_version("events") == "off:300@0"
    assert w.pinned_offset("events") == 300
    conn.append("events", _batch(rng, 50))
    # the pin holds while the log advances
    assert w.row_count("events") == 300
    assert w.snapshot_version("events") == "off:300@0"
    w.set_range(300, 350)
    rows = [r for p in w.pages("events") for r in p.to_pylist()]
    assert len(rows) == 50


def test_wait_for_offset_wakes_on_append():
    conn, rng = _mkconn(10)
    got = {}

    def waiter():
        got["off"] = conn.wait_for_offset("events", 10, 10.0)

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    conn.append("events", _batch(rng, 3))
    t.join(timeout=10)
    assert not t.is_alive()
    assert got["off"] == 13
    # timeout path: no append, returns current offset
    assert conn.wait_for_offset("events", 13, 0.05) == 13


# ----------------------------------------------------- IVM: acceptance
def test_ivm_acceptance_pin():
    """THE acceptance contract: initial refresh over N rows, append
    M << N, refresh folds ONLY the delta — delta_pages_folded >= 1,
    ivm_full_recomputes == 0, scanned rows == M — and the rows equal
    a cold full recompute AND the sqlite oracle."""
    from tests.oracle import load_sqlite

    N, M = 4000, 64
    conn, rng = _mkconn(N)
    r = _runner(conn)
    sink = r.executor
    view = IVM.IvmRegistry().register(r, "dash", VIEW_SQL)
    assert view.ivm_safe, view.unsafe_reason

    _names, rows1, _types = IVM.refresh(
        view, session=r.session, sink=sink)
    assert sink.ivm_refreshes == 1
    assert sink.ivm_full_recomputes == 0
    assert view.last_delta_rows == N  # the initial fold covers the log

    conn.append("events", _batch(rng, M))
    folded_before = sink.delta_pages_folded
    _names, rows2, _types = IVM.refresh(
        view, session=r.session, sink=sink)
    assert sink.delta_pages_folded - folded_before >= 1
    assert sink.ivm_full_recomputes == 0
    assert sink.ivm_refreshes == 2
    # scanned-row accounting proportional to M, not N
    assert view.last_delta_rows == M

    cold = r.execute(VIEW_SQL).rows
    _rows_close(rows2, cold)
    db = load_sqlite(conn, ["events"])
    want = db.execute(
        "select k, count(*), sum(v), max(v) from events "
        "group by k order by k").fetchall()
    _rows_close(rows2, [tuple(w) for w in want])
    assert rows1 != rows2  # the delta really changed the aggregates


def test_ivm_chain_append_refresh_repeatedly():
    conn, rng = _mkconn(1500)
    r = _runner(conn)
    sink = r.executor
    view = IVM.IvmRegistry().register(r, "chain", VIEW_SQL)
    IVM.refresh(view, session=r.session, sink=sink)  # initial fold
    for i in range(4):
        conn.append("events", _batch(rng, 37 + i))
        _n, rows, _t = IVM.refresh(view, session=r.session, sink=sink)
        cold = r.execute(VIEW_SQL).rows
        _rows_close(rows, cold)
        assert view.last_delta_rows == 37 + i
    assert sink.ivm_full_recomputes == 0
    assert sink.ivm_refreshes == 5


def test_refresh_without_new_data_serves_settled_result():
    conn, _rng = _mkconn(800)
    r = _runner(conn)
    view = IVM.IvmRegistry().register(r, "idle", VIEW_SQL)
    _n, rows1, _t = IVM.refresh(view, session=r.session,
                                sink=r.executor)
    folded = r.executor.delta_pages_folded
    _n, rows2, _t = IVM.refresh(view, session=r.session,
                                sink=r.executor)
    assert rows1 == rows2
    assert r.executor.delta_pages_folded == folded  # nothing folded


# ------------------------------------------- IVM: loud fallback paths
def test_non_ivm_safe_global_agg_falls_back_loudly():
    conn, rng = _mkconn(600)
    r = _runner(conn)
    sink = r.executor
    sql = "select count(*), sum(v) from events"
    view = IVM.IvmRegistry().register(r, "glob", sql)
    assert not view.ivm_safe
    assert "global aggregation" in view.unsafe_reason
    _n, rows, _t = IVM.refresh(view, session=r.session, sink=sink)
    assert sink.ivm_full_recomputes == 1
    assert sink.ivm_refreshes == 0
    _rows_close(rows, r.execute(sql).rows)
    conn.append("events", _batch(rng, 10))
    _n, rows, _t = IVM.refresh(view, session=r.session, sink=sink)
    assert sink.ivm_full_recomputes == 2
    _rows_close(rows, r.execute(sql).rows)


def test_non_ivm_safe_join_falls_back_loudly():
    conn, _rng = _mkconn(300)
    r = _runner(conn)
    sql = ("select a.k, count(*) from events a join events b "
           "on a.k = b.k group by a.k order by a.k")
    view = IVM.IvmRegistry().register(r, "joined", sql)
    assert not view.ivm_safe
    _n, rows, _t = IVM.refresh(view, session=r.session,
                               sink=r.executor)
    assert r.executor.ivm_full_recomputes == 1
    _rows_close(rows, r.execute(sql).rows)


def test_ivm_disabled_forces_full_recompute():
    conn, rng = _mkconn(700)
    r = _runner(conn)
    sink = r.executor
    view = IVM.IvmRegistry().register(r, "gated", VIEW_SQL)
    assert view.ivm_safe
    r.session.set("ivm_enabled", False)
    _n, rows, _t = IVM.refresh(view, session=r.session, sink=sink)
    assert sink.ivm_full_recomputes == 1
    assert sink.ivm_refreshes == 0
    _rows_close(rows, r.execute(VIEW_SQL).rows)
    # re-enabling folds incrementally again (state re-folds from 0)
    r.session.set("ivm_enabled", True)
    conn.append("events", _batch(rng, 20))
    _n, rows, _t = IVM.refresh(view, session=r.session, sink=sink)
    assert sink.ivm_refreshes == 1
    _rows_close(rows, r.execute(VIEW_SQL).rows)


def test_unsafe_reasons_are_specific():
    conn, _rng = _mkconn(50)
    r = _runner(conn)
    assert IVM.ivm_unsafe_reason(r.plan(VIEW_SQL), r.catalogs) is None
    reason = IVM.ivm_unsafe_reason(
        r.plan("select array_agg(v) from events group by k"),
        r.catalogs)
    assert "array_agg" in reason
    # non-stream tables never maintain incrementally
    from presto_tpu.connectors.tpch import TpchConnector

    r2 = LocalRunner({"tpch": TpchConnector(0.01)},
                     page_rows=PAGE_ROWS)
    reason = IVM.ivm_unsafe_reason(
        r2.plan("select l_linestatus, count(*) from lineitem "
                "group by l_linestatus"), r2.catalogs)
    assert "append-only" in reason


def test_view_shape_match_is_offset_independent():
    conn, rng = _mkconn(400)
    r = _runner(conn)
    reg = IVM.IvmRegistry()
    view = reg.register(r, "shape", VIEW_SQL)
    conn.append("events", _batch(rng, 900))  # moves counts/capacities
    assert reg.match(r.plan(VIEW_SQL)) is view
    assert reg.match(
        r.plan("select k, count(*) from events group by k")) is None


# ------------------------------------- monotone offset tokens (cache)
def test_pinned_offset_entry_hits_while_log_advances():
    """The satellite fix: a stream-scan fragment entry at offset N
    still HITS for a reader pinned at N after the log has advanced —
    the append path advances (reclaims live-head entries only)
    instead of discarding."""
    conn, _rng = _mkconn(1000)
    N = conn.offset("events")
    ex, window = IVM.windowed_executor(
        {"stream": conn}, "stream", "events", like=None)
    window.set_range(0, N)
    ex.result_cache = ResultCache()
    helper = _runner(conn)
    plan = helper.plan(VIEW_SQL)
    _n, rows1 = ex.execute(plan)
    assert ex.result_cache_misses >= 1
    key = next(iter(ex.result_cache._entries))
    assert ex.result_cache.entry_watermark(key) == N

    # the log advances: only live-head entries reclaim
    dropped = ex.result_cache.advance_tables({("stream", "events")})
    assert dropped == 0
    conn.append("events", [(1, 5.0)])
    _n, rows2 = ex.execute(plan)  # still pinned at N
    assert ex.result_cache_hits >= 1
    assert rows1 == rows2


def test_live_head_entry_reclaimed_on_insert_advance():
    conn, _rng = _mkconn(400)
    r = _runner(conn)
    r.session.set("result_cache_enabled", True)
    r.apply_session()
    rc = r.executor.result_cache
    r.execute(VIEW_SQL)  # live-head entries (no watermark)
    assert rc.entry_count >= 1
    keys = list(rc._entries)
    assert all(rc.entry_watermark(k) is None for k in keys)
    appends_before = r.executor.stream_appends_seen
    r.execute("insert into events select 3, 7.5")
    # the advance path reclaimed the unreachable live-head entries
    # and counted the observed append batch
    assert rc.entry_count == 0
    assert r.executor.stream_appends_seen == appends_before + 1
    # fresh read at the new offset recomputes correctly
    got = r.execute(VIEW_SQL).rows
    _rows_close(got, r.execute(VIEW_SQL).rows)


def test_view_cache_entry_advances_in_place():
    conn, rng = _mkconn(500)
    r = _runner(conn)
    r.session.set("result_cache_enabled", True)
    r.apply_session()
    rc = r.executor.result_cache
    view = IVM.IvmRegistry().register(r, "cached", VIEW_SQL)
    IVM.refresh(view, session=r.session, sink=r.executor)
    assert rc.entry_watermark(view.cache_key) == 500
    inv_before = rc.invalidations
    conn.append("events", _batch(rng, 25))
    r._invalidate_caches("stream", "events", append=True)
    # the watermarked view entry SURVIVED the append
    assert rc.entry_watermark(view.cache_key) == 500
    IVM.refresh(view, session=r.session, sink=r.executor)
    # ...and the refresh ADVANCED it in place, not via invalidation
    assert rc.entry_watermark(view.cache_key) == 525
    assert rc.invalidations == inv_before


# --------------------------------------------------- tailing cursors
def _tail_req(url, data=None, method="GET", tail=True, poll_ms=400):
    h = {"X-Presto-User": "tailer", "X-Presto-Catalog": "stream"}
    if tail:
        h["X-Presto-Session"] = (
            f"stream_tail_enabled=true,stream_poll_ms={poll_ms}")
    req = urllib.request.Request(url, data=data, headers=h,
                                 method=method)
    with urllib.request.urlopen(req, timeout=60) as resp:
        return json.loads(resp.read().decode())


@pytest.fixture()
def tail_server():
    from presto_tpu.server.http_server import PrestoTpuServer

    conn, rng = _mkconn(60, groups=4)
    srv = PrestoTpuServer({"stream": conn}, default_catalog="stream",
                          port=0)
    port = srv.start()
    try:
        yield srv, conn, rng, f"http://127.0.0.1:{port}"
    finally:
        srv.stop()


def test_tail_cursor_delivers_exactly_the_delta(tail_server):
    srv, conn, rng, base = tail_server
    b = _tail_req(f"{base}/v1/statement",
                  b"select k, v from events where k = 1", "POST")
    assert b["stats"]["state"] == "RUNNING"
    assert "nextUri" in b
    initial = b.get("data", [])
    assert all(row[0] == 1 for row in initial)
    # idle poll: empty page, fresh nextUri (the tail heartbeat)
    b2 = _tail_req(b["nextUri"], poll_ms=100)
    assert "data" not in b2
    assert "nextUri" in b2
    batch = [(1, 999.5), (2, 1.0), (1, 123.25)]
    conn.append("events", batch)
    appends_before = srv._runner.executor.stream_appends_seen
    b3 = _tail_req(b2["nextUri"])
    assert b3.get("data") == [[1, 999.5], [1, 123.25]]
    # the poll observed the offset advance (counter surface)
    assert srv._runner.executor.stream_appends_seen > appends_before
    # cancel terminates the cursor: no nextUri on the next page
    _tail_req(f"{base}/v1/statement/{b['id']}", method="DELETE",
              tail=False)
    b4 = _tail_req(b3["nextUri"])
    assert "nextUri" not in b4
    assert b4["stats"]["state"] == "CANCELED"


def test_tail_cursor_rides_ivm_for_registered_view(tail_server):
    srv, conn, rng, base = tail_server
    reg = IVM.shared_registry()
    sql = "select k, count(*), sum(v) from events group by k order by k"
    reg.register(srv._runner, "live", sql)
    ex = srv._runner.executor
    b = _tail_req(f"{base}/v1/statement", sql.encode(), "POST")
    assert len(b["data"]) == 4  # the full initial snapshot
    assert ex.ivm_refreshes >= 1
    conn.append("events", [(0, 10.0), (0, 20.0)])
    folded_before = ex.delta_pages_folded
    b2 = _tail_req(b["nextUri"])
    # only the CHANGED aggregate row arrives, computed incrementally
    assert len(b2["data"]) == 1
    assert b2["data"][0][0] == 0
    assert ex.delta_pages_folded > folded_before
    assert ex.ivm_full_recomputes == 0
    assert ex.cursor_polls >= 2
    _tail_req(f"{base}/v1/statement/{b['id']}", method="DELETE",
              tail=False)


def test_non_stream_statement_ignores_tail_flag(tail_server):
    srv, conn, rng, base = tail_server
    b = _tail_req(f"{base}/v1/statement", b"select 1", "POST")
    # falls through to the normal protocol: the query FINISHES
    for _ in range(50):
        if "nextUri" not in b:
            break
        b = _tail_req(b["nextUri"])
    assert b["stats"]["state"] == "FINISHED"


def test_concurrent_appender_and_four_tailers(tail_server):
    """The PR-11 gate applied to the new subsystem: one appender
    races 4 tailing protocol clients; every client receives every
    log row exactly once (initial snapshot + deltas) and the armed
    lock sanitizer records ZERO violations."""
    from presto_tpu.obs import sanitizer as san

    srv, conn, rng, base = tail_server
    violations_before = san.violation_count()
    seed_rows = conn.host_rows("events")
    batches = [[(rng.randrange(4), 1000.0 + i * 100 + j)
                for j in range(25)] for i in range(8)]
    total = len(seed_rows) + sum(len(b) for b in batches)
    results = {}

    def tailer(idx: int) -> None:
        got = []
        b = _tail_req(f"{base}/v1/statement",
                      b"select k, v from events", "POST",
                      poll_ms=250)
        got.extend(b.get("data", []))
        while len(got) < total and "nextUri" in b:
            b = _tail_req(b["nextUri"], poll_ms=250)
            got.extend(b.get("data", []))
        results[idx] = (got, b["id"])

    threads = [threading.Thread(target=tailer, args=(i,), daemon=True)
               for i in range(4)]
    for t in threads:
        t.start()

    def appender() -> None:
        for batch in batches:
            conn.append("events", batch)

    a = threading.Thread(target=appender, daemon=True)
    a.start()
    a.join(timeout=30)
    for t in threads:
        t.join(timeout=60)
    assert all(not t.is_alive() for t in threads)

    want = collections.Counter(
        (int(k), float(v))
        for k, v in seed_rows + [r for b in batches for r in b]
    )
    for idx, (got, qid) in results.items():
        assert collections.Counter(
            (int(k), float(v)) for k, v in got) == want, (
            f"tailer {idx} row multiset diverged")
        _tail_req(f"{base}/v1/statement/{qid}", method="DELETE",
                  tail=False)
    assert san.violation_count() == violations_before
    assert srv._runner.executor.cursor_polls >= 4


# ------------------------------------------------ surfaces + harness
def test_counters_registered_and_surfaced(tail_server):
    from presto_tpu.exec import counters as CTRS

    for name in ("delta_pages_folded", "ivm_refreshes",
                 "ivm_full_recomputes", "cursor_polls",
                 "stream_appends_seen"):
        assert name in CTRS.QUERY_COUNTERS
    srv, conn, rng, base = tail_server
    snap = CTRS.snapshot(srv._runner.executor)
    assert "ivm_refreshes" in snap
    with urllib.request.urlopen(f"{base}/metrics", timeout=30) as r:
        text = r.read().decode()
    for metric in ("presto_tpu_ivm_refreshes_total",
                   "presto_tpu_delta_pages_folded_total",
                   "presto_tpu_cursor_polls_total",
                   "presto_tpu_stream_appends_seen_total",
                   "presto_tpu_ivm_full_recomputes_total"):
        assert metric in text


def test_loadbench_append_writers_smoke():
    from tools.loadbench import run_append_load

    out = run_append_load(writers=1, readers=1, duration_s=1.2,
                          rows_per_append=64, seed=0)
    assert out["errors"] == 0
    assert out["appends"] >= 1
    assert out["ivm_refreshes"] >= 1
    assert out["ivm_full_recomputes"] == 0
    assert out["stream_appends_seen"] == out["appends"]


# ------------------------------------------- review-hardened contracts
def test_failed_append_leaves_log_untouched():
    """A mid-batch arity error must not orphan rows below the offset:
    the whole batch validates before anything mutates."""
    conn, _rng = _mkconn(5)
    with pytest.raises(ValueError):
        conn.append("events", [(1, 2.0), (3,)])  # bad arity mid-batch
    assert conn.offset("events") == 5
    rows = conn.host_rows("events")
    assert len(rows) == 5
    conn.append("events", [(9, 9.0)])
    assert conn.offset("events") == 6
    assert conn.host_rows("events")[-1] == (9, 9.0)


def test_concurrent_full_refresh_never_regresses_watermark():
    """The losing concurrent refresher re-reads the log head after
    winning the _refreshing flag, so a full-recompute view can never
    publish an older snapshot over a newer one."""
    conn, rng = _mkconn(300)
    r = _runner(conn)
    sql = "select count(*), sum(v) from events"  # unsafe: always full
    view = IVM.IvmRegistry().register(r, "race", sql)
    errors = []

    def refresher():
        try:
            for _ in range(5):
                IVM.refresh(view, session=r.session, sink=r.executor)
        except Exception as e:  # noqa: BLE001 - surfaced by assert
            errors.append(e)

    threads = [threading.Thread(target=refresher, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    for _ in range(6):
        conn.append("events", _batch(rng, 11))
    for t in threads:
        t.join(timeout=60)
    assert not errors
    # the settled result covers the final offset exactly
    assert view.settled_offset() == conn.offset("events")
    _n, rows, _t = IVM.refresh(view, session=r.session,
                               sink=r.executor)
    _rows_close(rows, r.execute(sql).rows)


def test_tail_recompute_watches_every_scanned_stream(tail_server):
    """A cursor over a statement scanning TWO streams must deliver
    rows when EITHER advances (the recompute mode's multi-stream
    poll)."""
    srv, conn, rng, base = tail_server
    conn.create_table("dims", ["k", "name"], [T.BIGINT, T.VARCHAR],
                      [(i, f"g{i}") for i in range(4)])
    sql = ("select d.name, count(*) from events e join dims d "
           "on e.k = d.k group by d.name order by d.name")
    b = _tail_req(f"{base}/v1/statement", sql.encode(), "POST")
    assert "nextUri" in b and b.get("data")
    # append to the SECOND stream (the dimension): a 5th group joins
    conn.append("dims", [(3, "g3b")])  # k=3 rows now match twice? no:
    # g3b duplicates k=3 -> join fan-out changes counts for k=3
    b2 = _tail_req(b["nextUri"])
    assert b2.get("data"), "append to the non-primary stream was lost"
    assert any(row[0] == "g3b" for row in b2["data"])
    _tail_req(f"{base}/v1/statement/{b['id']}", method="DELETE",
              tail=False)


def test_tail_cursor_memory_stays_bounded(tail_server):
    """The never-finishing cursor trims rows past the retry horizon
    instead of retaining everything it ever emitted."""
    from presto_tpu.server.http_server import _TAIL_RETAIN_SPANS

    srv, conn, rng, base = tail_server
    b = _tail_req(f"{base}/v1/statement",
                  b"select k, v from events", "POST", poll_ms=100)
    qid = b["id"]
    q = srv.manager.get(qid)
    total = len(b.get("data", []))
    for i in range(_TAIL_RETAIN_SPANS + 6):
        conn.append("events", _batch(rng, 30))
        b = _tail_req(b["nextUri"], poll_ms=400)
        total += len(b.get("data", []))
    # every appended row was delivered exactly once...
    assert total == conn.offset("events")
    # ...but the cursor retains only the retry horizon, not the log
    assert len(q.tail.rows) < total
    _tail_req(f"{base}/v1/statement/{qid}", method="DELETE",
              tail=False)
