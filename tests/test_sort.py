"""Sort / limit kernel tests vs Python sorted() oracle (reference analog:
TestOrderByOperator, TestTopNOperator)."""

import numpy as np

from presto_tpu import BIGINT, DOUBLE, VarcharType
from presto_tpu.ops.sort import SortKey, limit_page, sort_page
from presto_tpu.page import Page


def _page():
    return Page.from_arrays(
        [
            [3, 1, 2, 1, None, 2],
            [0.5, 2.5, None, 1.0, 3.5, -1.0],
            ["b", "a", "c", None, "a", "b"],
        ],
        [BIGINT, DOUBLE, VarcharType()],
    )


def test_sort_single_key_asc_nulls_last():
    out = sort_page(_page(), [SortKey(0)])
    got = [r[0] for r in out.to_pylist()]
    assert got == [1, 1, 2, 2, 3, None]


def test_sort_desc_nulls_first():
    out = sort_page(_page(), [SortKey(0, ascending=False, nulls_first=True)])
    got = [r[0] for r in out.to_pylist()]
    assert got == [None, 3, 2, 2, 1, 1]


def test_sort_multi_key_stable_semantics():
    # default null ordering is NULLS LAST regardless of direction
    out = sort_page(_page(), [SortKey(0), SortKey(1, ascending=False)])
    got = [(r[0], r[1]) for r in out.to_pylist()]
    assert got == [(1, 2.5), (1, 1.0), (2, -1.0), (2, None), (3, 0.5), (None, 3.5)]


def test_sort_all_null_varchar():
    page = Page.from_arrays([[None, None, None]], [VarcharType()])
    out = sort_page(page, [SortKey(0)])
    assert out.to_pylist() == [(None,), (None,), (None,)]


def test_sort_on_varchar_dictionary():
    out = sort_page(_page(), [SortKey(2), SortKey(0)])
    got = [(r[2], r[0]) for r in out.to_pylist()]
    assert got == [
        ("a", 1),
        ("a", None),
        ("b", 2),
        ("b", 3),
        ("c", 2),
        (None, 1),
    ]


def test_sort_limit_offset():
    out = sort_page(_page(), [SortKey(0)], limit=3, offset=1)
    got = [r[0] for r in out.to_pylist()]
    assert got == [1, 2, 2]
    assert out.capacity == 3


def test_sort_floats_total_order(rng):
    vals = rng.normal(size=50).tolist() + [0.0, -0.0, float("inf"), -float("inf")]
    page = Page.from_arrays([vals], [DOUBLE])
    out = sort_page(page, [SortKey(0)])
    got = [r[0] for r in out.to_pylist()]
    assert got == sorted(vals)


def test_limit_without_sort_keeps_page_order():
    page = _page()
    out = limit_page(page, 2, offset=1)
    assert [r[0] for r in out.to_pylist()] == [1, 2]
