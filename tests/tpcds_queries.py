"""TPC-DS query texts for the engine dialect (BASELINE rung 5: Q17/Q64).

Reconstructed from the public TPC-DS specification's query templates with
the standard qualification-style substitutions — not copied from any
implementation. Deviations from the template, applied identically to the
sqlite oracle versions in test_sql_tpcds.py:
  - Q17 quarter 2001Q1 (qualification value); the catalog stdev column is
    the real stddev_samp (the spec template famously repeats the cov
    expression there).
  - Q64 uses syear 2000/2001 and appends deterministic ORDER BY
    tiebreakers (item_sk, b_street_number, c_street_number, cnt columns)
    so ordered comparison is well-defined under ties.
"""

Q17 = """
select i_item_id, i_item_desc, s_state,
       count(ss_quantity) as store_sales_quantitycount,
       avg(ss_quantity) as store_sales_quantityave,
       stddev_samp(ss_quantity) as store_sales_quantitystdev,
       stddev_samp(ss_quantity) / avg(ss_quantity)
           as store_sales_quantitycov,
       count(sr_return_quantity) as store_returns_quantitycount,
       avg(sr_return_quantity) as store_returns_quantityave,
       stddev_samp(sr_return_quantity) as store_returns_quantitystdev,
       stddev_samp(sr_return_quantity) / avg(sr_return_quantity)
           as store_returns_quantitycov,
       count(cs_quantity) as catalog_sales_quantitycount,
       avg(cs_quantity) as catalog_sales_quantityave,
       stddev_samp(cs_quantity) as catalog_sales_quantitystdev,
       stddev_samp(cs_quantity) / avg(cs_quantity)
           as catalog_sales_quantitycov
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_quarter_name = '2001Q1'
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk
  and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_quarter_name in ('2001Q1', '2001Q2', '2001Q3')
  and sr_customer_sk = cs_bill_customer_sk
  and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_quarter_name in ('2001Q1', '2001Q2', '2001Q3')
group by i_item_id, i_item_desc, s_state
order by i_item_id, i_item_desc, s_state
limit 100
"""

Q64 = """
with cs_ui as (
  select cs_item_sk,
         sum(cs_ext_list_price) as sale,
         sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit)
             as refund
  from catalog_sales, catalog_returns
  where cs_item_sk = cr_item_sk
    and cs_order_number = cr_order_number
  group by cs_item_sk
  having sum(cs_ext_list_price) >
         2 * sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit)
),
cross_sales as (
  select i_product_name as product_name, i_item_sk as item_sk,
         s_store_name as store_name, s_zip as store_zip,
         ad1.ca_street_number as b_street_number,
         ad1.ca_street_name as b_street_name,
         ad1.ca_city as b_city, ad1.ca_zip as b_zip,
         ad2.ca_street_number as c_street_number,
         ad2.ca_street_name as c_street_name,
         ad2.ca_city as c_city, ad2.ca_zip as c_zip,
         d1.d_year as syear, d2.d_year as fsyear, d3.d_year as s2year,
         count(*) as cnt, sum(ss_wholesale_cost) as s1,
         sum(ss_list_price) as s2, sum(ss_coupon_amt) as s3
  from store_sales, store_returns, cs_ui,
       date_dim d1, date_dim d2, date_dim d3,
       store, customer, customer_demographics cd1,
       customer_demographics cd2, promotion,
       household_demographics hd1, household_demographics hd2,
       customer_address ad1, customer_address ad2,
       income_band ib1, income_band ib2, item
  where ss_store_sk = s_store_sk
    and ss_sold_date_sk = d1.d_date_sk
    and ss_customer_sk = c_customer_sk
    and ss_cdemo_sk = cd1.cd_demo_sk
    and ss_hdemo_sk = hd1.hd_demo_sk
    and ss_addr_sk = ad1.ca_address_sk
    and ss_item_sk = i_item_sk
    and ss_item_sk = sr_item_sk
    and ss_ticket_number = sr_ticket_number
    and ss_item_sk = cs_ui.cs_item_sk
    and c_current_cdemo_sk = cd2.cd_demo_sk
    and c_current_hdemo_sk = hd2.hd_demo_sk
    and c_current_addr_sk = ad2.ca_address_sk
    and c_first_sales_date_sk = d2.d_date_sk
    and c_first_shipto_date_sk = d3.d_date_sk
    and ss_promo_sk = p_promo_sk
    and hd1.hd_income_band_sk = ib1.ib_income_band_sk
    and hd2.hd_income_band_sk = ib2.ib_income_band_sk
    and cd1.cd_marital_status <> cd2.cd_marital_status
    and i_color in ('purple', 'burlywood', 'indian', 'spring',
                    'floral', 'medium')
    and i_current_price between 64 and 74
    and i_current_price between 65 and 79
  group by i_product_name, i_item_sk, s_store_name, s_zip,
           ad1.ca_street_number, ad1.ca_street_name, ad1.ca_city,
           ad1.ca_zip, ad2.ca_street_number, ad2.ca_street_name,
           ad2.ca_city, ad2.ca_zip, d1.d_year, d2.d_year, d3.d_year
)
select cs1.product_name, cs1.store_name, cs1.store_zip,
       cs1.b_street_number, cs1.b_street_name, cs1.b_city, cs1.b_zip,
       cs1.c_street_number, cs1.c_street_name, cs1.c_city, cs1.c_zip,
       cs1.syear, cs1.cnt, cs1.s1, cs1.s2, cs1.s3,
       cs2.s1 as s1_2, cs2.s2 as s2_2, cs2.s3 as s3_2,
       cs2.syear as syear_2, cs2.cnt as cnt_2
from cross_sales cs1, cross_sales cs2
where cs1.item_sk = cs2.item_sk
  and cs1.syear = 2000
  and cs2.syear = 2001
  and cs2.cnt <= cs1.cnt
  and cs1.store_name = cs2.store_name
  and cs1.store_zip = cs2.store_zip
order by cs1.product_name, cs1.store_name, cs2.cnt,
         cs1.b_street_number, cs1.c_street_number,
         cs1.b_street_name, cs1.c_street_name, cs1.cnt
"""

# ---- round 3: web channel + remaining-dimension queries. Same
# reconstruction discipline; deviations (applied to both engines):
#   - Q93's template comma-joins reason against a LEFT join's null-able
#     sr_ columns, which the WHERE collapses to inner — written as the
#     equivalent inner joins.
#   - Q82 filters inventory weeks by inv_date_sk range instead of
#     d_date + INTERVAL arithmetic (sqlite has no INTERVAL).
#   - Qualification substitutions target this generator's value ranges
#     (month_seq 1176-87 = calendar 1998; reason/hour/price bands).

Q62 = """
select substr(w_warehouse_name, 1, 20) wh, sm_type, web_name,
       sum(case when ws_ship_date_sk - ws_sold_date_sk <= 30
                then 1 else 0 end) as d30,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 30
                 and ws_ship_date_sk - ws_sold_date_sk <= 60
                then 1 else 0 end) as d60,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 60
                then 1 else 0 end) as dmore
from web_sales, warehouse, ship_mode, web_site, date_dim
where d_month_seq between 1176 and 1187
  and ws_ship_date_sk = d_date_sk
  and ws_warehouse_sk = w_warehouse_sk
  and ws_ship_mode_sk = sm_ship_mode_sk
  and ws_web_site_sk = web_site_sk
group by substr(w_warehouse_name, 1, 20), sm_type, web_name
order by wh, sm_type, web_name
limit 100
"""

Q82 = """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, store_sales
where i_current_price between 62 and 92
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and inv_date_sk between 2450994 and 2451054
  and i_manufact_id in (129, 270, 821, 423)
  and inv_quantity_on_hand between 100 and 500
  and ss_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id, i_item_desc, i_current_price
limit 100
"""

Q93 = """
select ss_customer_sk, sum(act_sales) sumsales
from (select ss_customer_sk,
             case when sr_return_quantity is not null
                  then (ss_quantity - sr_return_quantity)
                       * ss_sales_price
                  else ss_quantity * ss_sales_price end act_sales
      from store_sales
           join store_returns on sr_item_sk = ss_item_sk
                             and sr_ticket_number = ss_ticket_number
           join reason on sr_reason_sk = r_reason_sk
      where r_reason_desc = 'Stopped working') t
group by ss_customer_sk
order by sumsales, ss_customer_sk
limit 100
"""

Q96 = """
select count(*) cnt
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = t_time_sk
  and ss_hdemo_sk = hd_demo_sk
  and ss_store_sk = s_store_sk
  and t_hour = 20
  and t_minute >= 30
  and hd_dep_count = 7
  and s_store_name = 'able'
order by cnt
limit 100
"""

QUERIES = {17: Q17, 62: Q62, 64: Q64, 82: Q82, 93: Q93, 96: Q96}

# ---- round 4: ten more store/catalog-channel queries. Same
# reconstruction discipline (public spec templates + qualification-style
# substitutions tuned to this generator's value ranges); deviations
# (applied identically to the sqlite oracles):
#   - Q7/Q26: the generator's promotion table has no p_channel_event;
#     the channel disjunction uses p_channel_tv instead.
#   - Q37/Q82 pattern: date windows expressed as inv/cs date_sk ranges
#     (sqlite has no INTERVAL arithmetic).
#   - Q19: the generator's item table has no i_manufact string column;
#     the manufacturer grouping uses i_manufact_id alone.

Q3 = """
select d_year, i_brand_id, i_brand,
       sum(ss_ext_sales_price) as sum_agg
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manufact_id = 128
  and d_moy = 11
group by d_year, i_brand_id, i_brand
order by d_year, sum_agg desc, i_brand_id
limit 100
"""

Q7 = """
select i_item_id,
       avg(ss_quantity) as agg1,
       avg(ss_list_price) as agg2,
       avg(ss_coupon_amt) as agg3,
       avg(ss_sales_price) as agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk
  and ss_promo_sk = p_promo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_tv = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""

Q19 = """
select i_brand_id as brand_id, i_brand as brand, i_manufact_id,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 8
  and d_moy = 11
  and d_year = 1999
  and ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5)
  and ss_store_sk = s_store_sk
group by i_brand_id, i_brand, i_manufact_id
order by ext_price desc, brand_id, i_manufact_id
limit 100
"""

Q25 = """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) as store_sales_profit,
       sum(sr_net_loss) as store_returns_loss,
       sum(cs_net_profit) as catalog_sales_profit
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_moy = 4
  and d1.d_year = 2001
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk
  and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_moy between 4 and 10
  and d2.d_year = 2001
  and sr_customer_sk = cs_bill_customer_sk
  and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_moy between 4 and 10
  and d3.d_year = 2001
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
"""

Q26 = """
select i_item_id,
       avg(cs_quantity) as agg1,
       avg(cs_list_price) as agg2,
       avg(cs_coupon_amt) as agg3,
       avg(cs_sales_price) as agg4
from catalog_sales, customer_demographics, date_dim, item, promotion
where cs_sold_date_sk = d_date_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk
  and cs_promo_sk = p_promo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_tv = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""

Q29 = """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_quantity) as store_sales_quantity,
       sum(sr_return_quantity) as store_returns_quantity,
       sum(cs_quantity) as catalog_sales_quantity
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_moy = 4
  and d1.d_year = 1999
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk
  and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_moy between 4 and 7
  and d2.d_year = 1999
  and sr_customer_sk = cs_bill_customer_sk
  and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_year in (1999, 2000, 2001)
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
"""

Q37 = """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, catalog_sales
where i_current_price between 68 and 98
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and inv_date_sk between 2450994 and 2451054
  and i_manufact_id in (677, 940, 694, 808)
  and inv_quantity_on_hand between 100 and 500
  and cs_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
"""

Q42 = """
select d_year, i_category_id, i_category,
       sum(ss_ext_sales_price) as total_sales
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 1
  and d_moy = 11
  and d_year = 2000
group by d_year, i_category_id, i_category
order by total_sales desc, d_year, i_category_id, i_category
limit 100
"""

Q52 = """
select d_year, i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 1
  and d_moy = 11
  and d_year = 2000
group by d_year, i_brand_id, i_brand
order by d_year, ext_price desc, brand_id
limit 100
"""

Q55 = """
select i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 28
  and d_moy = 11
  and d_year = 1999
group by i_brand_id, i_brand
order by ext_price desc, brand_id
limit 100
"""

QUERIES.update({3: Q3, 7: Q7, 19: Q19, 25: Q25, 26: Q26, 29: Q29,
                37: Q37, 42: Q42, 52: Q52, 55: Q55})
