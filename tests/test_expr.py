"""Expression layer tests: dual evaluation (jitted jax vs numpy oracle).

Reference test pattern: presto-main operator/scalar/FunctionAssertions
evaluates every expression both interpreted and bytecode-compiled and
compares — ours compares the numpy backend against the jax.jit backend
(SURVEY §5 ring-1 mapping).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.expr import ir
from presto_tpu.expr.eval import evaluate, evaluate_filter
from presto_tpu.page import Page


def np_page(page: Page) -> Page:
    return jax.tree_util.tree_map(np.asarray, page)


def dual_eval(expr, page, decode=True):
    """Evaluate under jit (jax) and plain numpy; assert identical; return
    (data, nulls) numpy arrays from the jax path."""

    @jax.jit
    def run(p):
        v = evaluate(expr, p, jnp)
        return v.data, v.nulls

    jd, jn = run(page)
    ov = evaluate(expr, np_page(page), np)
    od, on = ov.data, ov.nulls
    jd_np = (
        tuple(np.asarray(x) for x in jd)
        if isinstance(jd, tuple)
        else np.asarray(jd)
    )
    od_b = np.broadcast_to(od, np.shape(jd_np)) if not isinstance(
        od, tuple) else od
    valid = np.asarray(page.valid)
    if isinstance(jd_np, tuple):
        for a, b in zip(jd_np, od_b):
            np.testing.assert_array_equal(a[valid], np.asarray(b)[valid])
    else:
        nulls_j = np.zeros(valid.shape, bool) if jn is None else np.asarray(
            np.broadcast_to(jn, valid.shape))
        nulls_o = np.zeros(valid.shape, bool) if on is None else np.asarray(
            np.broadcast_to(on, valid.shape))
        np.testing.assert_array_equal(nulls_j[valid], nulls_o[valid])
        live = valid & ~nulls_j
        if jd_np.dtype.kind == "f":
            np.testing.assert_allclose(
                jd_np[live], np.asarray(od_b)[live], rtol=1e-12
            )
        else:
            np.testing.assert_array_equal(jd_np[live], np.asarray(od_b)[live])
    return jd_np, (None if jn is None else np.asarray(
        np.broadcast_to(jn, valid.shape)))


def bigint_page(*cols, nulls=None):
    types = [T.BIGINT] * len(cols)
    page = Page.from_arrays(list(cols), types)
    return page


class TestArithmetic:
    def test_add_mul(self):
        page = bigint_page([1, 2, 3, -4], [10, 20, 30, 40])
        e = ir.call(
            "add",
            ir.call("multiply", ir.input_ref(0, T.BIGINT),
                    ir.const(3, T.BIGINT)),
            ir.input_ref(1, T.BIGINT),
        )
        data, nulls = dual_eval(e, page)
        np.testing.assert_array_equal(data[:4], [13, 26, 39, 28])

    def test_division_by_zero_is_null(self):
        page = bigint_page([10, 7, -9], [2, 0, -2])
        e = ir.call("divide", ir.input_ref(0, T.BIGINT),
                    ir.input_ref(1, T.BIGINT))
        data, nulls = dual_eval(e, page)
        assert nulls is not None and bool(nulls[1])
        assert data[0] == 5 and data[2] == 4  # trunc toward zero

    def test_modulus_sign(self):
        page = bigint_page([7, -7, 7, -7], [3, 3, -3, -3])
        e = ir.call("modulus", ir.input_ref(0, T.BIGINT),
                    ir.input_ref(1, T.BIGINT))
        data, _ = dual_eval(e, page)
        np.testing.assert_array_equal(data[:4], [1, -1, 1, -1])

    def test_double_arith(self):
        page = Page.from_arrays(
            [[1.5, -2.25, 0.0], [2.0, 0.5, 3.0]], [T.DOUBLE, T.DOUBLE]
        )
        e = ir.call("divide", ir.input_ref(0, T.DOUBLE),
                    ir.input_ref(1, T.DOUBLE))
        data, _ = dual_eval(e, page)
        np.testing.assert_allclose(data[:3], [0.75, -4.5, 0.0])

    def test_null_propagation(self):
        page = Page.from_arrays([[1, None, 3], [None, 2, 3]],
                                [T.BIGINT, T.BIGINT])
        e = ir.call("add", ir.input_ref(0, T.BIGINT),
                    ir.input_ref(1, T.BIGINT))
        data, nulls = dual_eval(e, page)
        np.testing.assert_array_equal(nulls[:3], [True, True, False])
        assert data[2] == 6


class TestDecimal:
    def test_decimal_mul_rescale(self):
        t = T.DecimalType(12, 2)
        # 12.34 * 5.00 = 61.70 ; result scale 4 -> 617000
        page = Page.from_arrays([[1234, 100], [500, 250]], [t, t])
        e = ir.call("multiply", ir.input_ref(0, t), ir.input_ref(1, t))
        assert e.type.scale == 4
        data, _ = dual_eval(e, page)
        np.testing.assert_array_equal(data[:2], [617000, 25000])

    def test_decimal_add_mixed_scale(self):
        a, b = T.DecimalType(10, 2), T.DecimalType(10, 4)
        page = Page.from_arrays([[150], [12345]], [a, b])
        e = ir.call("add", ir.input_ref(0, a), ir.input_ref(1, b))
        assert e.type.scale == 4
        data, _ = dual_eval(e, page)
        assert data[0] == 15000 + 12345

    def test_decimal_div_round_half_up(self):
        t = T.DecimalType(10, 2)
        page = Page.from_arrays([[100, 100, -100], [300, 800, 300]], [t, t])
        e = ir.call("divide", ir.input_ref(0, t), ir.input_ref(1, t))
        data, _ = dual_eval(e, page)
        # 1.00/3.00 = 0.33 ; 1.00/8.00 = 0.13 (0.125 rounds up); -1/3 = -0.33
        np.testing.assert_array_equal(data[:3], [33, 13, -33])

    def test_q1_style_expression(self):
        # l_extendedprice * (1 - l_discount) * (1 + l_tax)
        price_t = T.DecimalType(12, 2)
        disc_t = T.DecimalType(12, 2)
        page = Page.from_arrays(
            [[1000_00, 2499_99], [5, 10], [8, 0]], [price_t, disc_t, disc_t]
        )
        one = ir.const(100, T.DecimalType(12, 2))
        e = ir.call(
            "multiply",
            ir.call(
                "multiply",
                ir.input_ref(0, price_t),
                ir.call("subtract", one, ir.input_ref(1, disc_t)),
            ),
            ir.call("add", one, ir.input_ref(2, disc_t)),
        )
        data, _ = dual_eval(e, page)
        # 1000.00 * 0.95 * 1.08 = 1026.00 at scale 6
        assert data[0] == 1026_000000


class TestComparisons:
    def test_int_cmp(self):
        page = bigint_page([1, 5, 3], [2, 5, 1])
        for op, expect in [
            ("lt", [True, False, False]),
            ("le", [True, True, False]),
            ("eq", [False, True, False]),
            ("ne", [True, False, True]),
            ("ge", [False, True, True]),
            ("gt", [False, False, True]),
        ]:
            e = ir.call(op, ir.input_ref(0, T.BIGINT),
                        ir.input_ref(1, T.BIGINT))
            data, _ = dual_eval(e, page)
            np.testing.assert_array_equal(data[:3], expect)

    def test_mixed_type_cmp(self):
        page = Page.from_arrays([[1, 2, 3]], [T.INTEGER])
        e = ir.call("ge", ir.input_ref(0, T.INTEGER), ir.const(2, T.BIGINT))
        data, _ = dual_eval(e, page)
        np.testing.assert_array_equal(data[:3], [False, True, True])

    def test_decimal_cmp_mixed_scale(self):
        a, b = T.DecimalType(10, 2), T.DecimalType(10, 4)
        page = Page.from_arrays([[150, 120], [15000, 12345]], [a, b])
        e = ir.call("eq", ir.input_ref(0, a), ir.input_ref(1, b))
        data, _ = dual_eval(e, page)
        np.testing.assert_array_equal(data[:2], [True, False])

    def test_string_eq_const(self):
        page = Page.from_arrays([["A", "R", "N", "R"]], [T.VARCHAR])
        e = ir.call("eq", ir.input_ref(0, T.VARCHAR),
                    ir.const("R", T.VARCHAR))
        data, _ = dual_eval(e, page)
        np.testing.assert_array_equal(data[:4], [False, True, False, True])

    def test_string_cmp_order_with_missing_literal(self):
        page = Page.from_arrays([["apple", "cherry", "beta"]], [T.VARCHAR])
        e = ir.call("lt", ir.input_ref(0, T.VARCHAR),
                    ir.const("banana", T.VARCHAR))
        data, _ = dual_eval(e, page)
        np.testing.assert_array_equal(data[:3], [True, False, False])

    def test_between(self):
        page = bigint_page([1, 5, 10, 15])
        e = ir.between(ir.input_ref(0, T.BIGINT), ir.const(5, T.BIGINT),
                       ir.const(10, T.BIGINT))
        data, _ = dual_eval(e, page)
        np.testing.assert_array_equal(data[:4], [False, True, True, False])

    def test_in_list(self):
        page = bigint_page([1, 2, 3, 4])
        e = ir.in_(ir.input_ref(0, T.BIGINT), ir.const(2, T.BIGINT),
                   ir.const(4, T.BIGINT))
        data, _ = dual_eval(e, page)
        np.testing.assert_array_equal(data[:4], [False, True, False, True])


class TestLogic:
    def test_and_3vl(self):
        page = Page.from_arrays(
            [[True, True, False, None, None, False],
             [True, None, None, None, True, False]],
            [T.BOOLEAN, T.BOOLEAN],
        )
        e = ir.and_(ir.input_ref(0, T.BOOLEAN), ir.input_ref(1, T.BOOLEAN))
        data, nulls = dual_eval(e, page)
        # T&T=T, T&N=N, F&N=F, N&N=N, N&T=N, F&F=F
        np.testing.assert_array_equal(
            nulls[:6], [False, True, False, True, True, False]
        )
        np.testing.assert_array_equal(data[0], True)
        np.testing.assert_array_equal(data[2], False)

    def test_or_3vl(self):
        page = Page.from_arrays(
            [[True, False, None, None], [None, None, True, None]],
            [T.BOOLEAN, T.BOOLEAN],
        )
        e = ir.or_(ir.input_ref(0, T.BOOLEAN), ir.input_ref(1, T.BOOLEAN))
        data, nulls = dual_eval(e, page)
        # T|N=T, F|N=N, N|T=T, N|N=N
        np.testing.assert_array_equal(nulls[:4], [False, True, False, True])
        assert data[0] and data[2]

    def test_is_null_coalesce(self):
        page = Page.from_arrays([[1, None, 3]], [T.BIGINT])
        e = ir.is_null(ir.input_ref(0, T.BIGINT))
        data, nulls = dual_eval(e, page)
        assert nulls is None
        np.testing.assert_array_equal(data[:3], [False, True, False])
        e2 = ir.coalesce(ir.input_ref(0, T.BIGINT), ir.const(99, T.BIGINT))
        data, nulls = dual_eval(e2, page)
        np.testing.assert_array_equal(data[:3], [1, 99, 3])

    def test_if_case(self):
        page = bigint_page([1, 5, 10])
        e = ir.if_(
            ir.call("gt", ir.input_ref(0, T.BIGINT), ir.const(4, T.BIGINT)),
            ir.const(1, T.BIGINT),
            ir.const(0, T.BIGINT),
        )
        data, _ = dual_eval(e, page)
        np.testing.assert_array_equal(data[:3], [0, 1, 1])

    def test_switch_first_match_wins(self):
        page = bigint_page([1, 5, 10])
        e = ir.switch(
            ir.call("ge", ir.input_ref(0, T.BIGINT), ir.const(10, T.BIGINT)),
            ir.const(100, T.BIGINT),
            ir.call("ge", ir.input_ref(0, T.BIGINT), ir.const(5, T.BIGINT)),
            ir.const(50, T.BIGINT),
            ir.const(0, T.BIGINT),
        )
        data, _ = dual_eval(e, page)
        np.testing.assert_array_equal(data[:3], [0, 50, 100])


class TestTemporal:
    def test_extract_parts(self):
        import datetime

        dates = [
            datetime.date(1994, 1, 1),
            datetime.date(1998, 12, 31),
            datetime.date(2000, 2, 29),
            datetime.date(1970, 1, 1),
        ]
        days = [(d - datetime.date(1970, 1, 1)).days for d in dates]
        page = Page.from_arrays([days], [T.DATE])
        for part, expect in [
            ("year", [1994, 1998, 2000, 1970]),
            ("month", [1, 12, 2, 1]),
            ("day", [1, 31, 29, 1]),
            ("quarter", [1, 4, 1, 1]),
        ]:
            e = ir.call(part, ir.input_ref(0, T.DATE))
            data, _ = dual_eval(e, page)
            np.testing.assert_array_equal(data[:4], expect)

    def test_date_interval_day_arith(self):
        import datetime

        epoch = datetime.date(1970, 1, 1)
        d0 = (datetime.date(1998, 12, 1) - epoch).days
        page = Page.from_arrays([[d0]], [T.DATE])
        e = ir.call(
            "subtract",
            ir.input_ref(0, T.DATE),
            ir.const(90 * 86_400_000_000, T.INTERVAL_DAY_TIME),
        )
        assert e.type == T.DATE
        data, _ = dual_eval(e, page)
        assert int(data[0]) == (datetime.date(1998, 9, 2) - epoch).days

    def test_date_interval_month_clamps(self):
        import datetime

        epoch = datetime.date(1970, 1, 1)
        d0 = (datetime.date(1995, 1, 31) - epoch).days
        page = Page.from_arrays([[d0]], [T.DATE])
        e = ir.call("add", ir.input_ref(0, T.DATE),
                    ir.const(1, T.INTERVAL_YEAR_MONTH))
        data, _ = dual_eval(e, page)
        assert int(data[0]) == (datetime.date(1995, 2, 28) - epoch).days

    def test_date_minus_date(self):
        page = Page.from_arrays([[100], [40]], [T.DATE, T.DATE])
        e = ir.call("subtract", ir.input_ref(0, T.DATE),
                    ir.input_ref(1, T.DATE))
        assert e.type == T.BIGINT
        data, _ = dual_eval(e, page)
        assert data[0] == 60


class TestStrings:
    def test_like(self):
        page = Page.from_arrays(
            [["PROMO BRUSHED", "STANDARD POLISHED", "PROMO PLATED"]],
            [T.VARCHAR],
        )
        e = ir.call("like", ir.input_ref(0, T.VARCHAR),
                    ir.const("PROMO%", T.VARCHAR))
        data, _ = dual_eval(e, page)
        np.testing.assert_array_equal(data[:3], [True, False, True])

    def test_like_underscore(self):
        page = Page.from_arrays([["cat", "cut", "cart"]], [T.VARCHAR])
        e = ir.call("like", ir.input_ref(0, T.VARCHAR),
                    ir.const("c_t", T.VARCHAR))
        data, _ = dual_eval(e, page)
        np.testing.assert_array_equal(data[:3], [True, True, False])

    def test_substr_and_compare(self):
        page = Page.from_arrays([["13-345", "31-999", "13-111"]], [T.VARCHAR])
        sub = ir.call("substr", ir.input_ref(0, T.VARCHAR),
                      ir.const(1, T.BIGINT), ir.const(2, T.BIGINT))
        e = ir.call("eq", sub, ir.const("13", T.VARCHAR))
        data, _ = dual_eval(e, page)
        np.testing.assert_array_equal(data[:3], [True, False, True])

    def test_length_lower(self):
        page = Page.from_arrays([["Abc", "XYZZY"]], [T.VARCHAR])
        e = ir.call("length", ir.input_ref(0, T.VARCHAR))
        data, _ = dual_eval(e, page)
        np.testing.assert_array_equal(data[:2], [3, 5])
        e2 = ir.call(
            "eq",
            ir.call("lower", ir.input_ref(0, T.VARCHAR)),
            ir.const("abc", T.VARCHAR),
        )
        data, _ = dual_eval(e2, page)
        np.testing.assert_array_equal(data[:2], [True, False])


class TestCastsAndMath:
    def test_casts(self):
        page = Page.from_arrays([[1, 2, 3]], [T.INTEGER])
        e = ir.cast(ir.input_ref(0, T.INTEGER), T.DOUBLE)
        data, _ = dual_eval(e, page)
        assert data.dtype == np.float64
        e2 = ir.cast(ir.input_ref(0, T.INTEGER), T.DecimalType(10, 2))
        data, _ = dual_eval(e2, page)
        np.testing.assert_array_equal(data[:3], [100, 200, 300])

    def test_double_round_half_up_cast(self):
        page = Page.from_arrays([[1.5, 2.5, -1.5, 0.4]], [T.DOUBLE])
        e = ir.cast(ir.input_ref(0, T.DOUBLE), T.BIGINT)
        data, _ = dual_eval(e, page)
        np.testing.assert_array_equal(data[:4], [2, 3, -2, 0])

    def test_round_sqrt(self):
        page = Page.from_arrays([[2.4, 2.5, -2.5]], [T.DOUBLE])
        e = ir.call("round", ir.input_ref(0, T.DOUBLE))
        data, _ = dual_eval(e, page)
        np.testing.assert_array_equal(data[:3], [2.0, 3.0, -3.0])
        p2 = Page.from_arrays([[4.0, 9.0]], [T.DOUBLE])
        e2 = ir.call("sqrt", ir.input_ref(0, T.DOUBLE))
        data, _ = dual_eval(e2, p2)
        np.testing.assert_array_equal(data[:2], [2.0, 3.0])


class TestFilter:
    def test_filter_q6_style(self):
        # l_discount between 0.05 and 0.07 and l_quantity < 24
        disc_t = T.DecimalType(12, 2)
        page = Page.from_arrays(
            [[5, 6, 8, 7], [1000, 3000, 1000, 1000]],
            [disc_t, T.DecimalType(12, 2)],
        )
        pred = ir.and_(
            ir.between(ir.input_ref(0, disc_t),
                       ir.const(5, disc_t), ir.const(7, disc_t)),
            ir.call("lt", ir.input_ref(1, T.DecimalType(12, 2)),
                    ir.const(2400, T.DecimalType(12, 2))),
        )

        @jax.jit
        def run(p):
            return evaluate_filter(pred, p, jnp).valid

        valid = np.asarray(run(page))
        ov = evaluate_filter(pred, np_page(page), np).valid
        np.testing.assert_array_equal(valid, np.asarray(ov))
        np.testing.assert_array_equal(valid[:4], [True, False, False, True])

    def test_filter_null_predicate_drops(self):
        page = Page.from_arrays([[1, None, 3]], [T.BIGINT])
        pred = ir.call("gt", ir.input_ref(0, T.BIGINT),
                       ir.const(0, T.BIGINT))
        ov = evaluate_filter(pred, np_page(page), np)
        np.testing.assert_array_equal(np.asarray(ov.valid)[:3],
                                      [True, False, True])
