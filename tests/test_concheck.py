"""ISSUE 11: the static concurrency soundness pass (tools/concheck.py)
plus the whole-tree extension of the lint `locks` rule.

Mirrors the PR-6 mutation-suite style: group 1 pins the repo itself
clean (the gate); group 2 seeds deliberately-broken concurrency shapes
in synthetic files and asserts each rule REJECTS them with a pointed
message (a rule that cannot fail is not a check); group 3 covers the
generalized lint locks rule (any lock attribute name, class-level
locks, the `*_locked` helper convention, the single-threaded escape).

Pure AST — no JAX, no devices.
"""

import textwrap

from tools.concheck import check_registry, collect, run_concheck

# --------------------------------------------------------------- gates


def test_repo_is_concheck_clean():
    """THE gate: zero findings across registry, lock graph, and
    blocking rules on the repo itself. A finding here is a real
    concurrency hazard (or an undeclared lock) — fix the engine or
    annotate WHY, don't relax the rule."""
    findings = run_concheck()
    assert not findings, "\n".join(str(f) for f in findings)


def test_concheck_registry_covers_every_engine_lock_and_thread():
    """The inventory is live: the full-tree sweep sees every
    LOCK_REGISTRY/THREAD_REGISTRY entry at a real site (no stale
    entries — enforced by the gate above being clean) and the
    registries are non-trivially populated."""
    from presto_tpu.obs import sanitizer as SAN

    assert len(SAN.LOCK_REGISTRY) >= 12
    assert len(SAN.THREAD_REGISTRY) >= 4
    for name, help_text in SAN.LOCK_REGISTRY.items():
        assert help_text.strip(), f"{name} has empty help text"


# ----------------------------------------------------- mutation suite


def _tmp_py(tmp_path, body: str, name: str = "seeded.py") -> str:
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def _rules(findings, rule):
    return [f for f in findings if f.rule == rule]


def test_mutation_lock_order_cycle_lexical(tmp_path):
    """A -> B in one method, B -> A in another: the classic two-thread
    deadlock, caught from pure `with` nesting."""
    path = _tmp_py(tmp_path, """
        import threading

        class X:
            _shared_attrs = ()
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()
            def forward(self):
                with self.a:
                    with self.b:
                        pass
            def backward(self):
                with self.b:
                    with self.a:
                        pass
    """)
    found = _rules(run_concheck(paths=[path]), "con-graph")
    assert found, "cycle not detected"
    msg = found[0].message
    assert "lock-order cycle" in msg and "deadlock" in msg
    assert "seeded.X.a" in msg and "seeded.X.b" in msg


def test_mutation_lock_order_cycle_one_call_deep(tmp_path):
    """The cross-method shape: lock A held while CALLING a helper that
    acquires B, opposite order elsewhere — resolved one call level
    deep, not just lexically."""
    path = _tmp_py(tmp_path, """
        import threading

        class X:
            _shared_attrs = ()
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()
            def forward(self):
                with self.a:
                    self.helper()
            def helper(self):
                with self.b:
                    pass
            def backward(self):
                with self.b:
                    with self.a:
                        pass
    """)
    found = _rules(run_concheck(paths=[path]), "con-graph")
    assert found, "call-deep cycle not detected"
    assert "seeded.X.a" in found[0].message
    assert "seeded.X.b" in found[0].message


def test_no_cycle_on_consistent_order(tmp_path):
    """The negative: consistent A-before-B nesting everywhere is NOT a
    finding (edges alone are fine; only cycles fail)."""
    path = _tmp_py(tmp_path, """
        import threading

        class X:
            _shared_attrs = ()
            def __init__(self):
                self.a = threading.Lock()
                self.b = threading.Lock()
            def m1(self):
                with self.a:
                    with self.b:
                        pass
            def m2(self):
                with self.a:
                    with self.b:
                        pass
    """)
    assert not _rules(run_concheck(paths=[path]), "con-graph")


def test_mutation_blocking_sleep_under_lock(tmp_path):
    path = _tmp_py(tmp_path, """
        import threading
        import time

        class X:
            _shared_attrs = ()
            def __init__(self):
                self._lock = threading.Lock()
            def bad(self):
                with self._lock:
                    time.sleep(0.1)
    """)
    found = _rules(run_concheck(paths=[path]), "con-blocking")
    assert found and "time.sleep" in found[0].message
    assert "seeded.X._lock" in found[0].message


def test_blocking_escape_comment_is_honored(tmp_path):
    path = _tmp_py(tmp_path, """
        import threading
        import time

        class X:
            _shared_attrs = ()
            def __init__(self):
                self._lock = threading.Lock()
            def annotated(self):
                with self._lock:
                    # concheck: blocking-ok - seeded test exemption
                    time.sleep(0.1)
    """)
    assert not _rules(run_concheck(paths=[path]), "con-blocking")


def test_mutation_blocking_one_call_level_deep(tmp_path):
    """A lock-held call into a function that blocks directly — the
    exact shape of the pre-fix ResultCache demotion (device_get inside
    PageStore.put, called from the under-lock _maintain path)."""
    path = _tmp_py(tmp_path, """
        import threading
        import urllib.request

        class X:
            _shared_attrs = ()
            def __init__(self):
                self._lock = threading.Lock()
            def bad(self):
                with self._lock:
                    self.fetch()
            def fetch(self):
                return urllib.request.urlopen("http://x").read()
    """)
    found = _rules(run_concheck(paths=[path]), "con-blocking")
    assert found, "one-level-deep blocking call not detected"
    assert "fetch" in found[0].message
    assert "urlopen" in found[0].message


def test_mutation_blocking_in_locked_helper(tmp_path):
    """`*_locked` methods are held-by-convention: a blocking call in
    one is flagged even with no lexical `with` in sight."""
    path = _tmp_py(tmp_path, """
        import threading
        import time

        class X:
            _shared_attrs = ()
            def __init__(self):
                self._lock = threading.Lock()
            def _evict_locked(self):
                time.sleep(0.1)
    """)
    found = _rules(run_concheck(paths=[path]), "con-blocking")
    assert found and "time.sleep" in found[0].message


def test_mutation_raw_lock_construction_flagged(tmp_path):
    path = _tmp_py(tmp_path, """
        import threading

        class X:
            _shared_attrs = ()
            def __init__(self):
                self._lock = threading.Lock()
    """)
    found = _rules(run_concheck(paths=[path]), "con-registry")
    assert any("raw threading.Lock()" in f.message for f in found)
    assert any("make_lock" in f.message for f in found)


def test_mutation_misnamed_and_undeclared_factory_lock(tmp_path):
    """A make_lock whose literal doesn't match its site, and one whose
    name is missing from LOCK_REGISTRY."""
    path = _tmp_py(tmp_path, """
        from presto_tpu.obs.sanitizer import make_lock

        class X:
            _shared_attrs = ()
            def __init__(self):
                self._lock = make_lock("totally.wrong.name")
    """)
    found = _rules(
        run_concheck(paths=[path], lock_registry={},
                     thread_registry={}), "con-registry")
    msgs = [f.message for f in found]
    assert any("does not match its site" in m and
               "'seeded.X._lock'" in m for m in msgs), msgs
    assert any("not declared" in m and "LOCK_REGISTRY" in m
               for m in msgs), msgs


def test_mutation_unregistered_thread_target(tmp_path):
    path = _tmp_py(tmp_path, """
        import threading

        class X:
            def go(self):
                threading.Thread(target=self._loop, daemon=True).start()
            def _loop(self):
                pass
    """)
    found = _rules(
        run_concheck(paths=[path], lock_registry={},
                     thread_registry={}), "con-registry")
    assert any("seeded:self._loop" in f.message and
               "THREAD_REGISTRY" in f.message for f in found)


def test_mutation_stale_registry_entries(tmp_path):
    """Registry entries with no site fail the full-sweep check, like
    stale QUERY_COUNTERS entries."""
    path = _tmp_py(tmp_path, """
        def nothing():
            pass
    """)
    mods = collect([path])
    found = check_registry(
        mods, lock_registry={"ghost.Lock._lock": "gone"},
        thread_registry={"ghost:self._loop": "gone"},
        full_sweep=True)
    msgs = [f.message for f in found]
    assert any("ghost.Lock._lock" in m and "stale" in m for m in msgs)
    assert any("ghost:self._loop" in m and "stale" in m for m in msgs)


# ------------------------------------- lint locks rule, generalized


def test_locks_rule_generalizes_to_any_lock_attr(tmp_path):
    """The PR-6 rule keyed on `_lock`/`lock` names; now ANY attribute
    assigned a threading primitive binds the contract (`_fault_lock`,
    `_cv`, ...)."""
    from tools.lint import check_locks

    path = _tmp_py(tmp_path, """
        import threading

        class Racy:
            _shared_attrs = ("n",)
            def __init__(self):
                self._fault_lock = threading.Lock()
                self.n = 0
            def locked_bump(self):
                with self._fault_lock:
                    self.n += 1
            def racy_bump(self):
                self.n += 1
    """)
    found = check_locks(paths=[path])
    assert any("OUTSIDE" in f.message for f in found), \
        [f.message for f in found]


def test_locks_rule_flags_undeclared_owner_even_without_writes(
        tmp_path):
    """Satellite 2: every lock owner must declare `_shared_attrs` or
    carry the single-threaded annotation — silence is no longer an
    option, even when no under-lock write exists yet."""
    from tools.lint import check_locks

    path = _tmp_py(tmp_path, """
        import threading

        class Silent:
            def __init__(self):
                self._cv = threading.Condition()

        # lint: single-threaded - built and polled by one test driver
        class Annotated:
            def __init__(self):
                self._cv = threading.Condition()
    """)
    found = check_locks(paths=[path])
    assert len(found) == 1, [f.message for f in found]
    assert "Silent" in found[0].message
    assert "_shared_attrs" in found[0].message
    assert "single-threaded" in found[0].message


def test_locks_rule_honors_locked_helper_convention(tmp_path):
    """Writes inside a `*_locked` method count as under-lock (the
    caller-holds-it convention the runtime sanitizer keeps honest)."""
    from tools.lint import check_locks

    path = _tmp_py(tmp_path, """
        import threading

        class Store:
            _shared_attrs = ("evictions",)
            def __init__(self):
                self._lock = threading.Lock()
                self.evictions = 0
            def drop(self):
                with self._lock:
                    self._evict_locked()
            def _evict_locked(self):
                self.evictions += 1
    """)
    assert check_locks(paths=[path]) == []


def test_locks_rule_class_level_lock_detected(tmp_path):
    """A class-body lock (the ProfileStore._instances_lock shape)
    makes the class a lock owner too."""
    from tools.lint import check_locks

    path = _tmp_py(tmp_path, """
        import threading

        class Registry:
            _instances_lock = threading.Lock()
            def __init__(self):
                self.n = 0
    """)
    found = check_locks(paths=[path])
    assert len(found) == 1 and "Registry" in found[0].message
