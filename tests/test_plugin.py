"""Plugin SPI: UDF registration + connector contribution + listener
wiring (reference: spi/Plugin.java + PluginManager install path)."""

import pytest

from presto_tpu import types as T
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.events import EventListener
from presto_tpu.plugin import Plugin, ScalarFunctionSpec, scalar_function
from presto_tpu.runner import LocalRunner


@scalar_function("double_it", [T.BIGINT], T.BIGINT)
def _double_it(xp, x):
    return x * 2


class _DemoPlugin(Plugin):
    name = "demo"

    def __init__(self):
        self.listener_events = []

    def connectors(self):
        mem = MemoryConnector()
        mem.create_table("plugin_t", ["k"], [T.BIGINT],
                         [(i,) for i in range(10)])
        return {"demo": mem}

    def scalar_functions(self):
        return [
            _double_it,
            ScalarFunctionSpec(
                "hypot2", (T.DOUBLE, T.DOUBLE), T.DOUBLE,
                lambda xp, a, b: xp.sqrt(a * a + b * b),
            ),
        ]

    def event_listeners(self):
        rec = self

        class L(EventListener):
            def query_completed(self, e):
                rec.listener_events.append(e.state)

        return [L()]


def test_udf_and_connector_through_sql():
    runner = LocalRunner(
        {"tpch": TpchConnector(0.001)}, plugins=[_DemoPlugin()]
    )
    rows = runner.execute(
        "select double_it(n_nationkey), hypot2(3.0, 4.0) "
        "from tpch.nation where n_nationkey = 7"
    ).rows
    assert rows == [(14, 5.0)]
    # plugin connector registered as a catalog
    rows = runner.execute(
        "select count(*), sum(k) from demo.plugin_t where k >= 5"
    ).rows
    assert rows == [(5, 35)]
    # UDFs compose with engine expressions and nulls propagate
    rows = runner.execute(
        "select double_it(cast(null as bigint))"
    ).rows
    assert rows == [(None,)]


def test_type_checking_of_udf_args():
    runner = LocalRunner(
        {"tpch": TpchConnector(0.001)}, plugins=[_DemoPlugin()]
    )
    with pytest.raises(Exception):
        runner.execute("select double_it('abc')")


def test_plugin_event_listener_on_server():
    from presto_tpu.client import StatementClient
    from presto_tpu.server import PrestoTpuServer

    plug = _DemoPlugin()
    srv = PrestoTpuServer(
        {"tpch": TpchConnector(0.001)}, port=0, plugins=[plug]
    )
    srv.start()
    try:
        c = StatementClient(server=f"http://127.0.0.1:{srv.port}")
        res = c.execute("select double_it(21)")
        assert res.rows == [[42]]
    finally:
        srv.stop()
    assert plug.listener_events == ["FINISHED"]


def test_duplicate_catalog_rejected():
    with pytest.raises(ValueError):
        LocalRunner(
            {"demo": MemoryConnector(), "tpch": TpchConnector(0.001)},
            plugins=[_DemoPlugin()],
        )
