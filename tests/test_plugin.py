"""Plugin SPI: UDF registration + connector contribution + listener
wiring (reference: spi/Plugin.java + PluginManager install path)."""

import pytest

from presto_tpu import types as T
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.events import EventListener
from presto_tpu.plugin import Plugin, ScalarFunctionSpec, scalar_function
from presto_tpu.runner import LocalRunner


@scalar_function("double_it", [T.BIGINT], T.BIGINT)
def _double_it(xp, x):
    return x * 2


class _DemoPlugin(Plugin):
    name = "demo"

    def __init__(self):
        self.listener_events = []

    def connectors(self):
        mem = MemoryConnector()
        mem.create_table("plugin_t", ["k"], [T.BIGINT],
                         [(i,) for i in range(10)])
        return {"demo": mem}

    def scalar_functions(self):
        return [
            _double_it,
            ScalarFunctionSpec(
                "hypot2", (T.DOUBLE, T.DOUBLE), T.DOUBLE,
                lambda xp, a, b: xp.sqrt(a * a + b * b),
            ),
        ]

    def event_listeners(self):
        rec = self

        class L(EventListener):
            def query_completed(self, e):
                rec.listener_events.append(e.state)

        return [L()]


def test_udf_and_connector_through_sql():
    runner = LocalRunner(
        {"tpch": TpchConnector(0.001)}, plugins=[_DemoPlugin()]
    )
    rows = runner.execute(
        "select double_it(n_nationkey), hypot2(3.0, 4.0) "
        "from tpch.nation where n_nationkey = 7"
    ).rows
    assert rows == [(14, 5.0)]
    # plugin connector registered as a catalog
    rows = runner.execute(
        "select count(*), sum(k) from demo.plugin_t where k >= 5"
    ).rows
    assert rows == [(5, 35)]
    # UDFs compose with engine expressions and nulls propagate
    rows = runner.execute(
        "select double_it(cast(null as bigint))"
    ).rows
    assert rows == [(None,)]


def test_type_checking_of_udf_args():
    runner = LocalRunner(
        {"tpch": TpchConnector(0.001)}, plugins=[_DemoPlugin()]
    )
    with pytest.raises(Exception):
        runner.execute("select double_it('abc')")


def test_plugin_event_listener_on_server():
    from presto_tpu.client import StatementClient
    from presto_tpu.server import PrestoTpuServer

    plug = _DemoPlugin()
    srv = PrestoTpuServer(
        {"tpch": TpchConnector(0.001)}, port=0, plugins=[plug]
    )
    srv.start()
    try:
        c = StatementClient(server=f"http://127.0.0.1:{srv.port}")
        res = c.execute("select double_it(21)")
        assert res.rows == [[42]]
    finally:
        srv.stop()
    assert plug.listener_events == ["FINISHED"]


def test_duplicate_catalog_rejected():
    with pytest.raises(ValueError):
        LocalRunner(
            {"demo": MemoryConnector(), "tpch": TpchConnector(0.001)},
            plugins=[_DemoPlugin()],
        )


# ------------------------------------------------ aggregate-function SPI

def _log_pre(data):
    import jax.numpy as jnp

    return jnp.log(jnp.maximum(data.astype(jnp.float64), 1e-300))


def _geo_mean_finalize(xp, states):
    (logsum, nulls), (count, _) = states
    n = xp.maximum(count, 1).astype(xp.float64)
    return xp.exp(logsum / n), nulls


class _AggPlugin(Plugin):
    name = "agg-demo"

    def aggregate_functions(self):
        from presto_tpu import types as T
        from presto_tpu.exec.agg_states import (
            AggregateFunctionSpec,
            StateCol,
        )
        from presto_tpu.ops import agg as A

        return [AggregateFunctionSpec(
            name="geometric_mean",
            state=(
                StateCol("logsum", A.SUM, A.SUM, T.DOUBLE,
                         pre=_log_pre),
                StateCol("count", A.COUNT, A.SUM, T.BIGINT),
            ),
            result=T.DOUBLE,
            finalize=_geo_mean_finalize,
        )]


def test_plugin_aggregate_function():
    """An @AggregationFunction-analog plugin aggregate resolves, plans,
    partial/final-splits, and finalizes like a builtin (reference:
    TestApproximateCountDistinctAggregation-style harness for custom
    aggs)."""
    import math

    r = LocalRunner(
        {"tpch": TpchConnector(0.01)}, plugins=[_AggPlugin()],
        page_rows=1 << 12,
    )
    # grouped: compare against exp(avg(ln(x))) computed by the engine
    rows = r.execute(
        "select o_orderpriority, geometric_mean(o_totalprice), "
        "avg(o_totalprice) from orders group by o_orderpriority "
        "order by 1"
    ).rows
    assert len(rows) == 5
    for _, gm, av in rows:
        assert 0 < gm < av  # AM-GM inequality, strict for spread data
    # global, validated numerically on a small table
    got = r.execute(
        "select geometric_mean(n_nationkey + 1) from nation"
    ).rows[0][0]
    want = math.exp(
        sum(math.log(k + 1) for k in range(25)) / 25
    )
    assert abs(got - want) / want < 1e-9


def test_plugin_type_registration():
    """Type plugin SPI (reference: spi/Plugin.getTypes +
    TypeRegistry.addType): a contributed named type resolves in CAST."""
    from presto_tpu import types as T
    from presto_tpu.plugin import Plugin

    class _TypePlugin(Plugin):
        name = "types"

        def types(self):
            # an alias type: resolves by name to an existing SqlType
            return {"money": T.DecimalType(18, 2)}

    r = LocalRunner(
        {"tpch": TpchConnector(0.01)}, plugins=[_TypePlugin()],
        page_rows=1 << 12,
    )
    got = r.execute(
        "select cast(o_totalprice as money) from orders "
        "where o_orderkey = 1"
    ).rows
    assert len(got) == 1
    assert T.parse_type("money") == T.DecimalType(18, 2)


def test_access_control_plugin():
    """Access control SPI (reference: spi/security/SystemAccessControl;
    denials raise AccessDeniedException): select, write, and session
    checks enforced at the reference's choke points."""
    import pytest as _pytest

    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.plugin import Plugin
    from presto_tpu.security import AccessControl, AccessDeniedError

    class _Restrictive(AccessControl):
        def check_can_select(self, user, catalog, table, columns):
            if table == "customer" and user != "admin":
                self.deny(f"select from {table}")

        def check_can_drop_table(self, user, catalog, table):
            self.deny(f"drop {table}")

        def check_can_set_session(self, user, name):
            if name == "tpu_offload_enabled":
                self.deny(f"set {name}")

    class _SecPlugin(Plugin):
        name = "security"

        def access_control(self):
            return _Restrictive()

    r = LocalRunner(
        {"tpch": TpchConnector(0.01), "memory": MemoryConnector()},
        plugins=[_SecPlugin()], page_rows=1 << 12,
    )
    # allowed table passes
    assert r.execute("select count(*) from nation").rows[0][0] == 25
    # denied table fails, including when buried in a subquery/join
    with _pytest.raises(AccessDeniedError):
        r.execute("select count(*) from customer")
    with _pytest.raises(AccessDeniedError):
        r.execute(
            "select count(*) from orders where o_custkey in "
            "(select c_custkey from customer)"
        )
    # write checks
    r.execute("create table memory.t1 as select 1 as x")
    with _pytest.raises(AccessDeniedError):
        r.execute("drop table memory.t1")
    # session check
    with _pytest.raises(AccessDeniedError):
        r.execute("set session tpu_offload_enabled = false")
    # metadata listings hide denied tables (reference: filterTables)
    listed = {
        t[0] for t in r.execute(
            "select table_name from system.tables "
            "where table_catalog = 'tpch'"
        ).rows
    }
    assert "customer" not in listed and "nation" in listed
    # view DDL checks are symmetric: create checked earlier, drop too
    r.execute("create view v_ok as select 1 as x")

    class _NoDrop(_Restrictive):
        def check_can_drop_view(self, user, catalog, name):
            self.deny(f"drop view {name}")

    r.access_control = _NoDrop()
    with _pytest.raises(AccessDeniedError):
        r.execute("drop view v_ok")
    r.access_control = _Restrictive()
    # user-sensitive allow: admin can read customer
    r.session.user = "admin"
    assert r.execute("select count(*) from customer").rows[0][0] > 0


def test_type_plugin_cannot_shadow_builtin():
    from presto_tpu import types as T
    from presto_tpu.plugin import Plugin

    class _Shadow(Plugin):
        def types(self):
            return {"decimal": T.DecimalType(10, 0)}

    import pytest as _pytest

    with _pytest.raises(ValueError):
        LocalRunner({"tpch": TpchConnector(0.01)}, plugins=[_Shadow()])


def test_install_rejects_unwired_access_control():
    # ADVICE r3: install() must not silently drop a contributed
    # AccessControl — only engine entry points can enforce one
    from presto_tpu.plugin import Plugin, install
    from presto_tpu.security import AccessControl

    class ACPlugin(Plugin):
        def access_control(self):
            return AccessControl()

    with pytest.raises(ValueError):
        install(ACPlugin())
