"""Slow-marked subprocess wrapper around tools/chaos.py so the chaos
harness cannot bit-rot: a short seeded run (randomized delay / drop /
kill / submit-drop schedules over real OS-process workers) must exit 0
— every query correct, no hangs past the query deadline.

The full matrix (`tools/chaos.py --iterations 20 --seed 0`) is the
acceptance gate; this wrapper keeps the harness wired into tier-1's
slow lane at an affordable iteration count.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_chaos_harness_exits_zero():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
         "--iterations", "4", "--seed", "0", "--scale", "0.005"],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=900,
    )
    assert proc.returncode == 0, (
        f"chaos harness failed:\n{proc.stdout}\n{proc.stderr}"
    )
    assert "failures" in proc.stdout


@pytest.mark.slow
def test_chaos_kill_nonleaf_recovers_via_spool_replay():
    """ISSUE 7: the kill-during-non-leaf-stage schedule — a worker
    killed while serving spooled-exchange fetches mid-DAG — must
    recover with single-process-identical rows via spooled NON-LEAF
    replay (the harness exits nonzero on zero nonleaf_replays)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
         "--iterations", "2", "--seed", "1", "--scale", "0.005",
         "--mode", "kill-nonleaf"],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=900,
    )
    assert proc.returncode == 0, (
        f"kill-nonleaf chaos failed:\n{proc.stdout}\n{proc.stderr}"
    )
    assert "nonleaf_replays=" in proc.stdout


@pytest.mark.slow
def test_chaos_kill_coordinator_reattaches():
    """ISSUE 20: the coordinator-loss schedule — the coordinator
    subprocess is SIGKILLed mid-query with every producer stage
    spooled, a successor boots on the same checkpoint journal, and the
    client's nextUri stream resumes with single-process-identical rows
    (the harness exits nonzero on any wrong result, hang, missing
    re-attach, or sanitizer violation)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
         "--iterations", "2", "--seed", "2", "--scale", "0.005",
         "--mode", "kill-coordinator", "--sanitize"],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=900,
    )
    assert proc.returncode == 0, (
        f"kill-coordinator chaos failed:\n{proc.stdout}\n{proc.stderr}"
    )
    assert "coordinator_reattaches=" in proc.stdout
