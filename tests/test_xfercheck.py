"""ISSUE 12: the static host<->device transfer audit
(tools/xfercheck.py) plus the dynamic metering contracts of the choke
points (presto_tpu/exec/xfer.py).

Mirrors the PR-6/PR-11 mutation-suite style: group 1 pins the repo
itself clean (the gate) and the registry live; group 2 seeds
deliberately-broken transfer shapes in synthetic files and asserts
each rule REJECTS them with a pointed message; group 3 covers the
runtime half — registry counters on every surface, `xfer` spans when
traced and only then, and span wall == transfer_wall_s.
"""

import re
import textwrap

import pytest

from presto_tpu.exec import xfer as XFER
from tools.xfercheck import run_xfercheck

# --------------------------------------------------------------- gates


def test_repo_is_xfercheck_clean():
    """THE gate: zero findings across registry, plane, and choke rules
    on the repo itself. A finding here is an unaccounted host<->device
    crossing — declare it (direction/plane/why), route it through
    exec/xfer.py, or annotate WHY it stays raw; don't relax the rule."""
    findings = run_xfercheck()
    assert not findings, "\n".join(str(f) for f in findings)


def test_transfer_registry_is_live_and_well_formed():
    """The inventory is non-trivially populated and every row carries
    a valid direction, a valid plane, and real help text (stale rows
    are excluded by the clean gate above)."""
    assert len(XFER.TRANSFER_REGISTRY) >= 15
    for site, (direction, plane, why) in \
            XFER.TRANSFER_REGISTRY.items():
        assert direction in ("h2d", "d2h", "h2d+d2h"), site
        assert plane in ("data", "control"), site
        assert why.strip(), f"{site} has empty justification"
    # the choke points themselves are declared data-plane sites
    for site in ("exec.xfer.to_host", "exec.xfer.to_device",
                 "exec.xfer.np_host"):
        assert site in XFER.TRANSFER_REGISTRY
    # the data plane names the per-page query modules
    assert "exec.pagestore" in XFER.DATA_PLANE_MODULES
    assert "dist.spool" in XFER.DATA_PLANE_MODULES


# ----------------------------------------------------- mutation suite


def _tmp_py(tmp_path, body: str, name: str = "seeded.py") -> str:
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def _rules(findings, rule):
    return [f for f in findings if f.rule == rule]


def test_mutation_undeclared_device_put(tmp_path):
    """An undeclared raw jax.device_put site fails the registry rule
    with the canonical site name in the message."""
    path = _tmp_py(tmp_path, """
        import jax

        def stage(page):
            return jax.device_put(page)
    """)
    found = _rules(
        run_xfercheck(paths=[path], registry={}, data_modules=set()),
        "xfer-registry")
    assert found, "undeclared device_put not detected"
    assert "seeded.stage" in found[0].message
    assert "TRANSFER_REGISTRY" in found[0].message


def test_mutation_stale_registry_row():
    """A registry row naming a site with no primitive fails the full
    sweep (the stale-entry discipline of QUERY_COUNTERS/LOCK_REGISTRY
    applied to transfers)."""
    registry = dict(XFER.TRANSFER_REGISTRY)
    registry["exec.nowhere.phantom_pull"] = (
        "d2h", "data", "a site that does not exist")
    found = _rules(run_xfercheck(registry=registry), "xfer-registry")
    assert any("phantom_pull" in f.message and "stale" in f.message
               for f in found)


def test_mutation_unrouted_data_plane_primitive(tmp_path):
    """A DECLARED site in a data-plane module still fails the choke
    rule when it uses the raw primitive instead of the xfer API — an
    unrouted crossing is invisible to the counters."""
    path = _tmp_py(tmp_path, """
        import jax

        def pull(page):
            return jax.device_get(page)
    """)
    findings = run_xfercheck(
        paths=[path],
        registry={"seeded.pull": ("d2h", "data", "spill pull")},
        data_modules={"seeded"},
    )
    assert not _rules(findings, "xfer-registry")
    choke = _rules(findings, "xfer-choke")
    assert choke, "raw data-plane primitive not flagged"
    assert "xfer.to_host" in choke[0].message


def test_mutation_wrong_plane_declaration(tmp_path):
    """A `data`-plane declaration for a site OUTSIDE the data-plane
    module list fails — plane classification is load-bearing (a data
    crossing in a setup module means either a misdeclared row or
    query work leaking out of the operator tier)."""
    path = _tmp_py(tmp_path, """
        import jax

        def warm(tree):
            return jax.device_get(tree)
    """)
    findings = run_xfercheck(
        paths=[path],
        registry={"seeded.warm": ("d2h", "data", "warmup pull")},
        data_modules={"somewhere.else"},
    )
    plane = _rules(findings, "xfer-plane")
    assert plane, "wrong-plane declaration not flagged"
    assert "DATA_PLANE_MODULES" in plane[0].message


def test_escape_comment_is_honored(tmp_path):
    """`# xfercheck: raw-ok - <why>` waives the choke rule (and the
    direction cross-check) for a deliberate raw primitive; the site
    still needs its registry row."""
    path = _tmp_py(tmp_path, """
        import jax

        def fence(tree):
            # xfercheck: raw-ok - sync fence, no bytes cross
            jax.block_until_ready(tree)
            return tree
    """)
    findings = run_xfercheck(
        paths=[path],
        registry={"seeded.fence": ("d2h", "data", "fence")},
        data_modules={"seeded"},
    )
    assert not findings, "\n".join(str(f) for f in findings)
    # ...but without the registry row the site still fails
    findings = run_xfercheck(paths=[path], registry={},
                             data_modules={"seeded"})
    assert _rules(findings, "xfer-registry")


def test_mutation_direction_mismatch(tmp_path):
    """A site whose primitives cross a direction the registry row does
    not declare fails — the declaration must cover the code."""
    path = _tmp_py(tmp_path, """
        import jax

        def roundtrip(page):
            return jax.device_put(jax.device_get(page))
    """)
    findings = run_xfercheck(
        paths=[path],
        registry={"seeded.roundtrip": ("h2d", "control", "stage")},
        data_modules=set(),
    )
    found = _rules(findings, "xfer-registry")
    assert any("d2h" in f.message and "direction" in f.message
               for f in found)


def test_coercion_heuristic_skips_host_constructions(tmp_path):
    """np.array/np.asarray over literals, comprehensions, and [x]*n
    replication are host constructions, not crossings; a coercion of
    an opaque value IS a potential crossing and needs declaring."""
    path = _tmp_py(tmp_path, """
        import numpy as np

        LUT = np.array([1, 2, 3], np.int64)

        def build(vals, cap):
            return np.array([v is None for v in vals] +
                            [True] * (cap - len(vals)))

        def pull(x):
            return np.asarray(x)
    """)
    findings = run_xfercheck(paths=[path], registry={},
                             data_modules=set())
    found = _rules(findings, "xfer-registry")
    assert len(found) == 1, [str(f) for f in found]
    assert "seeded.pull" in found[0].message


def test_nested_defs_attribute_to_enclosing_function(tmp_path):
    """Closures cannot hide a crossing: a primitive inside a nested
    def attributes to the enclosing top-level function (the concheck
    convention)."""
    path = _tmp_py(tmp_path, """
        import jax

        def outer(pages):
            def emit(p):
                return jax.device_get(p)
            return [emit(p) for p in pages]
    """)
    found = _rules(
        run_xfercheck(paths=[path], registry={}, data_modules=set()),
        "xfer-registry")
    assert found and "seeded.outer" in found[0].message


# ------------------------------------------------- dynamic contracts


@pytest.fixture()
def tiny_runner():
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.runner import LocalRunner

    r = LocalRunner({"tpch": TpchConnector(scale=0.001)},
                    default_catalog="tpch", page_rows=1 << 12)
    r.apply_session()
    return r


def test_transfer_counters_reach_explain_analyze(tiny_runner):
    """The four byte/count gauges ride the QUERY_COUNTERS registry and
    the float wall rides as a computed entry — one query's result
    decode alone crosses d2h, so the ledger is non-zero on any run."""
    plan = tiny_runner.plan(
        "select count(*), sum(n_nationkey) from nation")
    _n, _r, stats = tiny_runner.executor.execute_with_stats(plan)
    ctr = stats["counters"]
    for name in ("h2d_bytes", "d2h_bytes", "h2d_transfers",
                 "d2h_transfers", "transfer_wall_s"):
        assert name in ctr, name
    assert ctr["d2h_transfers"] >= 1
    assert ctr["d2h_bytes"] > 0
    assert ctr["transfer_wall_s"] >= 0.0


def test_transfer_gauges_are_per_query(tiny_runner):
    """Gauges reset at query start — a second query reports its own
    crossings, not an accumulation."""
    ex = tiny_runner.executor
    ex.execute(tiny_runner.plan("select count(*) from nation"))
    first = ex.d2h_bytes
    assert first > 0
    ex.execute(tiny_runner.plan("select count(*) from nation"))
    assert ex.d2h_bytes == first


def test_xfer_spans_when_traced_sum_matches_wall(tiny_runner):
    """A traced run shows `xfer` spans whose summed wall equals the
    query's transfer_wall_s (they are the same measurements), with
    byte attributes attached."""
    from presto_tpu import obs as OBS

    ex = tiny_runner.executor
    tr = OBS.QueryTrace("xfer-test")
    OBS.attach(ex, tr)
    ex.execute(tiny_runner.plan(
        "select n_regionkey, count(*) from nation group by "
        "n_regionkey order by n_regionkey"))
    spans = [s for s in tr.export() if s["kind"] == "xfer"]
    assert spans, "traced run produced no xfer spans"
    assert all(s["name"].startswith(("d2h:", "h2d:")) for s in spans)
    assert all(s["attrs"].get("bytes", 0) >= 0 for s in spans)
    span_wall = sum(s["t1"] - s["t0"] for s in spans)
    assert abs(span_wall - ex.transfer_wall_s) < 1e-6 + \
        0.01 * ex.transfer_wall_s
    OBS.finalize(ex, tr)


def test_no_xfer_spans_when_untraced(tiny_runner):
    """Tracing off: crossings still METER (counters move) but record
    no spans — the `is None` guard, pinned by trace_spans == 0."""
    ex = tiny_runner.executor
    assert ex.trace is None
    ex.execute(tiny_runner.plan("select count(*) from nation"))
    assert ex.trace_spans == 0
    assert ex.d2h_transfers >= 1


def test_transfer_counters_reach_metrics_and_system_metrics():
    """The server surfaces: /metrics exposition carries the byte/count
    gauges plus the transfer_wall_seconds gauge, and system.metrics
    rows carry the same names plus transfer_wall_ms — overlaid with
    the exec/xfer.py process totals like the result-cache counters."""
    from presto_tpu.client import StatementClient
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.server import PrestoTpuServer
    import urllib.request

    srv = PrestoTpuServer({"tpch": TpchConnector(scale=0.001)},
                          port=0, page_rows=1 << 12)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        c = StatementClient(server=base)
        res = c.execute("select count(*) from nation")
        assert res.error is None
        with urllib.request.urlopen(f"{base}/metrics",
                                    timeout=30) as r:
            text = r.read().decode()
        for name in ("presto_tpu_h2d_bytes", "presto_tpu_d2h_bytes",
                     "presto_tpu_h2d_transfers",
                     "presto_tpu_d2h_transfers",
                     "presto_tpu_transfer_wall_seconds"):
            assert re.search(rf"^{name} ", text, re.M), name
        # the query above decoded rows: the process total is live
        d2h = int(re.search(r"^presto_tpu_d2h_bytes (\d+)", text,
                            re.M).group(1))
        assert d2h > 0
        res = c.execute("select * from system.metrics")
        assert res.error is None
        names = {row[0] for row in res.rows}
        for name in ("h2d_bytes", "d2h_bytes", "h2d_transfers",
                     "d2h_transfers", "transfer_wall_ms"):
            assert name in names, name
    finally:
        srv.stop()


def test_to_host_and_np_host_meter_only_real_crossings():
    """Already-host input passes through unmetered (no bytes cross);
    device input meters its exact byte size — the property that makes
    host-served cache replays genuinely zero-cost on the ledger."""
    import jax.numpy as jnp
    import numpy as np

    base = XFER.process_totals()
    host = np.arange(16, dtype=np.int64)
    out = XFER.to_host(host)
    assert out is host
    assert XFER.np_host(host) is not None
    after = XFER.process_totals()
    assert after["d2h_bytes"] == base["d2h_bytes"]

    dev = jnp.arange(16, dtype=jnp.int64)
    pulled = XFER.np_host(dev)
    assert isinstance(pulled, np.ndarray)
    after2 = XFER.process_totals()
    assert after2["d2h_bytes"] - after["d2h_bytes"] == 16 * 8
    assert after2["d2h_transfers"] == after["d2h_transfers"] + 1

    base_h = XFER.process_totals()
    staged = XFER.to_device(host)
    assert staged is not None
    after3 = XFER.process_totals()
    assert after3["h2d_bytes"] - base_h["h2d_bytes"] == 16 * 8
