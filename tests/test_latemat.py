"""Late materialization for join chains + fused partial aggregation.

Reference: spi/block/DictionaryBlock.java (joins emit indirections over
the build PagesIndex; values materialize at the first consumer) and
operator/ScanFilterAndProjectOperator.java (pipeline fusion), extended
per ROOFLINE.md §4: carry build ROW IDS through the chain and gather
each carried column exactly once; compile scan→filter→project→partial
aggregation to one XLA program per split.

The counter tests use hand-built physical plans over the memory
connector so join order, build sides, and channel sets are pinned —
the assertions are exact, not directional."""

import collections
import dataclasses

import pytest

from presto_tpu import types as T
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec import plan as P
from presto_tpu.exec.executor import Executor
from presto_tpu.runner import LocalRunner


def _rows_equal(a, b):
    return collections.Counter(map(repr, a)) == collections.Counter(
        map(repr, b)
    )


def _chain_rig():
    """t1 ⋈ t2 ⋈ t3 on a shared key — the Q5-shaped probe spine."""
    mem = MemoryConnector()
    mem.create_table(
        "t1", ["k1", "a"], [T.BIGINT, T.BIGINT],
        [(i, i * 10) for i in range(100)],
    )
    mem.create_table(
        "t2", ["k2", "b", "c"], [T.BIGINT, T.BIGINT, T.BIGINT],
        [(i, i + 1, i + 2) for i in range(100)],
    )
    mem.create_table(
        "t3", ["k3", "d"], [T.BIGINT, T.BIGINT],
        [(i, -i) for i in range(100)],
    )
    scan1 = P.TableScan("mem", "t1", ("k1", "a"))
    scan2 = P.TableScan("mem", "t2", ("k2", "b", "c"))
    scan3 = P.TableScan("mem", "t3", ("k3", "d"))
    j1 = P.HashJoin(scan1, scan2, (0,), (0,), "inner")
    j2 = P.HashJoin(j1, scan3, (0,), (0,), "inner")
    return mem, j2


def test_chain_single_gather_per_carried_build_column():
    """The acceptance contract: on a multi-join chain, every carried
    build column is VALUE-gathered exactly once (at the chain
    boundary), however many joins it rides through."""
    mem, j2 = _chain_rig()
    ex = Executor({"mem": mem})
    _names, rows = ex.execute(j2)
    want = [(i, i * 10, i, i + 1, i + 2, i, -i) for i in range(100)]
    assert _rows_equal(rows, want)
    # join1 defers t2's 3 columns; join2 defers t3's 2 and carries
    # t2's 3 — one page per stream, so:
    #   deferred  = 3 (at j1) + 5 (at j2)        = 8
    #   gathered  = 3 (t2) + 2 (t3), ONCE each   = 5
    assert ex.gathers_materialized == 5
    assert ex.gathers_deferred == 8


def test_chain_disabled_matches_and_defers_nothing():
    mem, j2 = _chain_rig()
    ex_on = Executor({"mem": mem})
    ex_off = Executor({"mem": mem})
    ex_off.late_mat = False
    _n, rows_on = ex_on.execute(j2)
    _n, rows_off = ex_off.execute(j2)
    assert _rows_equal(rows_on, rows_off)
    assert ex_off.gathers_deferred == 0
    assert ex_off.gathers_materialized == 0


def test_left_join_null_build_side_survives_deferral():
    """LEFT-join pad rows (unmatched probe, null build side) must stay
    NULL through the indirection AND through a downstream join's
    composition: the id column's null mask gathers with probe_idx and
    ORs over the build nulls at materialization."""
    mem = MemoryConnector()
    mem.create_table(
        "p", ["k", "a"], [T.BIGINT, T.BIGINT],
        [(i, i) for i in range(20)],
    )
    mem.create_table(
        "b", ["bk", "v"], [T.BIGINT, T.BIGINT],
        [(i, 100 + i) for i in range(0, 20, 2)],  # evens only
    )
    mem.create_table(
        "t3", ["k3", "d"], [T.BIGINT, T.BIGINT],
        [(i, -i) for i in range(20)],
    )
    left = P.HashJoin(
        P.TableScan("mem", "p", ("k", "a")),
        P.TableScan("mem", "b", ("bk", "v")),
        (0,), (0,), "left",
    )
    top = P.HashJoin(
        left, P.TableScan("mem", "t3", ("k3", "d")),
        (0,), (0,), "inner",
    )
    ex = Executor({"mem": mem})
    _n, rows = ex.execute(top)
    want = [
        (i, i, i, 100 + i, i, -i) if i % 2 == 0
        else (i, i, None, None, i, -i)
        for i in range(20)
    ]
    assert _rows_equal(rows, want)
    # the interior left join defers b's 2 columns; the top join (chain
    # boundary, lazy probe) defers t3's 2 for free; every carried
    # column gathers once at the boundary
    assert ex.gathers_materialized == 4
    assert ex.gathers_deferred == 6


def test_single_boundary_join_stays_eager():
    """A lone (un-chained) join's consumer materializes immediately —
    deferring would only add a launch, so the boundary join runs the
    eager path and the counters stay zero."""
    mem = MemoryConnector()
    mem.create_table(
        "p", ["k", "a"], [T.BIGINT, T.BIGINT],
        [(i, i) for i in range(10)],
    )
    mem.create_table(
        "b", ["bk", "v"], [T.BIGINT, T.BIGINT],
        [(i, 100 + i) for i in range(10)],
    )
    join = P.HashJoin(
        P.TableScan("mem", "p", ("k", "a")),
        P.TableScan("mem", "b", ("bk", "v")),
        (0,), (0,), "inner",
    )
    ex = Executor({"mem": mem})
    _n, rows = ex.execute(join)
    assert _rows_equal(rows, [(i, i, i, 100 + i) for i in range(10)])
    assert ex.gathers_deferred == 0
    assert ex.gathers_materialized == 0


def test_lazy_filter_lifts_only_referenced_channels():
    """A filter between chained joins lifts exactly the deferred
    channels its predicate reads (prune.expr_channels liveness); the
    rest stay deferred to the boundary — total value gathers stay at
    one per carried column."""
    from presto_tpu.expr import ir

    mem, j2 = _chain_rig()
    j1 = j2.left
    scan3 = j2.right
    # filter on t2's `b` (logical channel 3 of j1's output) between
    # the joins: b > 10
    pred = ir.Call(
        "gt", (ir.InputRef(3, T.BIGINT), ir.Constant(10, T.BIGINT)),
        T.BOOLEAN,
    )
    filtered = P.Filter(j1, pred)
    top = P.HashJoin(filtered, scan3, (0,), (0,), "inner")
    ex = Executor({"mem": mem})
    _n, rows = ex.execute(top)
    want = [
        (i, i * 10, i, i + 1, i + 2, i, -i)
        for i in range(100) if i + 1 > 10
    ]
    assert _rows_equal(rows, want)
    # lift of `b` (1) + boundary gathers of k2, c, k3, d (4): still
    # exactly one value gather per carried column
    assert ex.gathers_materialized == 5


@pytest.fixture(scope="module")
def tpch_rig():
    conn = TpchConnector(0.01)
    runner = LocalRunner({"tpch": conn}, page_rows=1 << 13)
    return runner


Q5ISH = (
    "select n_name, sum(l_extendedprice * (1 - l_discount)) as rev "
    "from customer, orders, lineitem, supplier, nation "
    "where c_custkey = o_custkey and l_orderkey = o_orderkey "
    "and l_suppkey = s_suppkey and c_nationkey = s_nationkey "
    "and s_nationkey = n_nationkey "
    "group by n_name order by rev desc"
)


def test_q5_shaped_sql_parity_general_join_path(tpch_rig):
    """SQL-level parity on the Q5-shaped join chain through the GENERAL
    (materialized-build) path — generated joins off so the sort join +
    late materialization actually run."""
    r = tpch_rig
    r.session.set("generated_join_enabled", False)
    # late materialization is auto = TPU-only; the CPU test forces it
    r.session.set("late_materialization_enabled", "true")
    try:
        on = r.execute(Q5ISH).rows
        deferred = r.executor.gathers_deferred
        materialized = r.executor.gathers_materialized
        r.session.set("late_materialization_enabled", "false")
        off = r.execute(Q5ISH).rows
    finally:
        r.session.unset("generated_join_enabled")
        r.session.unset("late_materialization_enabled")
    assert deferred > 0 and materialized > 0
    # the chain composes: strictly fewer value gathers than the eager
    # engine's per-join gathers of the same carried columns
    assert materialized < deferred
    assert _rows_equal(on, off)


def test_q5ish_oracle_parity(tpch_rig):
    from tests.oracle import load_sqlite

    r = tpch_rig
    db = load_sqlite(
        r.catalogs["tpch"],
        ["customer", "orders", "lineitem", "supplier", "nation"],
    )
    r.session.set("generated_join_enabled", False)
    r.session.set("late_materialization_enabled", "true")
    try:
        got = r.execute(Q5ISH).rows
    finally:
        r.session.unset("generated_join_enabled")
        r.session.unset("late_materialization_enabled")
    # sqlite holds decimals as UNSCALED ints (cents); the engine's
    # decimal output is the matching unscaled int, so the comparison is
    # exact integer equality
    want = db.execute(
        "select n_name, sum(l_extendedprice * (100 - l_discount)) "
        "from customer, orders, lineitem, supplier, nation "
        "where c_custkey = o_custkey and l_orderkey = o_orderkey "
        "and l_suppkey = s_suppkey and c_nationkey = s_nationkey "
        "and s_nationkey = n_nationkey "
        "group by n_name order by 2 desc"
    ).fetchall()
    assert [(g[0], int(g[1])) for g in got] == [
        (w[0], int(w[1])) for w in want
    ]


# ---------------------------------------------------------------- fusion


Q1ISH = (
    "select l_returnflag, l_linestatus, sum(l_quantity), count(*) "
    "from lineitem where l_shipdate <= date '1998-09-02' "
    "group by l_returnflag, l_linestatus order by 1, 2"
)
Q6ISH = (
    "select sum(l_extendedprice * l_discount) from lineitem "
    "where l_discount between 0.05 and 0.07 and l_quantity < 24"
)


def test_fused_partial_agg_grouped(tpch_rig):
    """Q1-shaped scan→filter→project→partial-agg compiles through the
    fused pipeline (counter mirrors generated_joins_used) with exact
    parity against the unfused driver loop. Fusion is auto = TPU-only
    (the win is launch overhead), so the CPU test forces it on — same
    pattern as the Pallas-join interpret-mode tests."""
    r = tpch_rig
    r.session.set("fused_partial_agg_enabled", "true")
    try:
        on = r.execute(Q1ISH).rows
        assert r.executor.fused_partial_aggs >= 1
        r.session.set("fused_partial_agg_enabled", "false")
        off = r.execute(Q1ISH).rows
        assert r.executor.fused_partial_aggs == 0
    finally:
        r.session.unset("fused_partial_agg_enabled")
    assert on == off


def test_fused_partial_agg_global(tpch_rig):
    r = tpch_rig
    r.session.set("fused_partial_agg_enabled", "true")
    try:
        on = r.execute(Q6ISH).rows
        assert r.executor.fused_partial_aggs >= 1
        r.session.set("fused_partial_agg_enabled", "false")
        off = r.execute(Q6ISH).rows
    finally:
        r.session.unset("fused_partial_agg_enabled")
    assert on == off


def test_fused_partial_agg_shipped_plan_worker_path():
    """The distributed shape: a coordinator-planned PARTIAL fragment,
    serialized through plan_serde and executed over a round-robin
    SplitFilterConnector — exactly server/worker.py's shipped-plan
    path — must engage the fused pipeline too."""
    from presto_tpu.connectors.split_filter import SplitFilterConnector
    from presto_tpu.dist import plan_serde
    from presto_tpu.server.worker import find_partial_cut

    conn = TpchConnector(0.01)
    planner_runner = LocalRunner({"tpch": conn}, page_rows=1 << 13)
    plan = planner_runner.plan(Q1ISH)
    cut = find_partial_cut(plan)
    assert cut is not None
    partial = dataclasses.replace(cut, step="partial")
    fragment = plan_serde.loads(plan_serde.dumps(partial))

    worker_runner = LocalRunner(
        {"tpch": SplitFilterConnector(conn, "lineitem", 0, 2)},
        page_rows=1 << 13,
    )
    # the worker applies shipped session properties the same way
    # (server/worker.py _run_task); fusion is auto=TPU-only, so the
    # CPU test ships it force-enabled
    worker_runner.session.set("fused_partial_agg_enabled", "true")
    worker_runner.apply_session()
    ex = worker_runner.executor
    pages = list(ex.pages(fragment))
    assert pages, "worker fragment produced no state pages"
    assert ex.fused_partial_aggs >= 1, (
        "shipped-plan worker path did not fuse the partial aggregation"
    )
