"""ISSUE 13: the device-resident data plane.

Covers the three tentpole layers plus the satellites:
  - oracle parity of the DEVICE repartition kernel against the host
    splitmix64 path — same partition assignment per key type (int /
    float incl. -0.0 and NaN / bool / short+long decimal / dictionary)
    including the NULL sentinel;
  - ladder-bucket compaction + the skew->overflow-flag contract, and
    the Pallas partition-id variant (interpret mode, the CPU test
    path: self-consistent, in-range, partition-complete);
  - the acceptance pin: a forced-partitioned distributed q3-family
    query over same-process workers completes its EXCHANGE PHASE with
    zero h2d/d2h process-total deltas (measured at the last stage
    boundary via the scheduler's stage hook), zero h2d for the whole
    query, rows identical to the host-spool path AND the sqlite
    oracle, mesh_local_exchanges counted;
  - the fault-tolerance fallback: device-resident spools materialize
    host bytes LAZILY for HTTP consumers, and a worker lost
    mid-exchange still replays from surviving spools with identical
    rows;
  - buffer donation: buffers_donated >= 1 on an overflow-retry query
    with rows identical and peak_device_bytes no higher than the
    non-donated baseline; the membudget model discounts donated
    accumulators;
  - the xfercheck jnp.asarray gap is closed (seeded violation).
"""

import collections

import jax
import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.dist import spool as SPOOL
from presto_tpu.dist.dcn import DcnRunner
from presto_tpu.exec import xfer as XF
from presto_tpu.exec.executor import Executor
from presto_tpu.page import Page
from presto_tpu.runner import LocalRunner
from presto_tpu.server.worker import WorkerServer
from tests.oracle import load_sqlite

SF = 0.01
PAGE_ROWS = 1 << 13

# q3-family: forced-partitioned join + group-by over integer columns
# (decimal-free so the sqlite oracle compares exactly)
Q3_FAMILY = (
    "select o_orderkey, count(*) c from lineitem "
    "join orders on l_orderkey = o_orderkey "
    "where o_orderkey < 1000 group by o_orderkey order by o_orderkey"
)


def rows_equal(a, b):
    return collections.Counter(map(repr, a)) == collections.Counter(
        map(repr, b))


def _key_page():
    """One page exercising every partitionable key family with NULLs."""
    return Page.from_arrays(
        [
            [1, -7, None, 4, 0, 2**40, -1, 5],
            [1.5, -0.0, 0.0, None, float("nan"), 2.5, -3.5, 1e300],
            [True, False, None, True, False, True, False, True],
            ["a", "b", "a", None, "c", "b", "zz", "a"],
            [105, None, -205, 305, 0, 105, 42, 7],       # decimal(9,2)
            [10**20, -(10**20), None, 7, 0, 10**20, 1, 2],  # p>18
        ],
        [T.BIGINT, T.DOUBLE, T.BOOLEAN, T.VARCHAR,
         T.DecimalType(9, 2), T.DecimalType(30, 2)],
    )


def _device_hash(page, keys):
    luts = tuple(
        XF.to_device(SPOOL._dict_value_hashes(page.block(k).dictionary))
        if page.block(k).dictionary is not None else None
        for k in keys
    )
    return np.asarray(SPOOL.device_row_hash_u64(page, keys, luts))


# --------------------------------------------------- kernel parity
@pytest.mark.parametrize("keys", [(0,), (1,), (2,), (3,), (4,), (5,),
                                  (0, 1, 2, 3, 4, 5)])
def test_device_hash_parity_per_key_type(keys):
    """The jnp kernel computes the SAME splitmix64 value-hash as the
    host path for every key family — int, float (-0.0/NaN
    normalized), bool, dictionary VALUES, short and long decimal —
    with NULL keys on the fixed sentinel, so both tiers route every
    row to the same partition."""
    page = _key_page()
    host_page = jax.device_get(page)
    host = SPOOL.row_hash_u64(host_page, keys)
    dev = _device_hash(page, keys)
    assert np.array_equal(host, dev)
    for nparts in (2, 3, 8):
        assert np.array_equal(host % nparts, dev % nparts)


def test_device_partition_matches_host_partition():
    """Row multisets per partition agree between the tiers (device
    emits every partition incl. empties; host skips empties)."""
    page = _key_page()
    ex = Executor({"tpch": TpchConnector(SF)})
    ex.device_exchange = "true"
    for keys in ((0,), (3,), (0, 1)):
        dev = {
            p: sorted(map(repr, pp.to_pylist()))
            for p, pp in SPOOL.device_partition_pages(ex, page, keys, 4)
        }
        host = {
            p: sorted(map(repr, pp.to_pylist()))
            for p, pp in SPOOL.partition_host_page(
                jax.device_get(page), keys, 4)
        }
        for p in range(4):
            assert dev[p] == host.get(p, []), f"keys={keys} part={p}"


def test_device_partition_caps_ride_the_ladder():
    """Output pages land on ladder-bucket capacities; a skewed key
    (every row in one partition) overflows the chunk bucket and
    raises the deferred flag — the boosted-retry contract."""
    from presto_tpu.exec import shapes as SH

    n = 8192
    page = Page.from_arrays([[7] * n], [T.BIGINT])
    ex = Executor({"tpch": TpchConnector(SF)})
    ex.device_exchange = "true"
    parts = SPOOL.device_partition_pages(ex, page, (0,), 8)
    cap = SH.exchange_partition_cap(page.capacity, 8, 1)
    assert all(pp.capacity == cap for _, pp in parts)
    assert cap < n  # the skewed partition cannot hold every row
    assert bool(ex._overflow_flagged())
    # boosted re-entry sizes one rung family up, on the ladder
    ex2 = Executor({"tpch": TpchConnector(SF)})
    ex2.device_exchange = "true"
    ex2._capacity_boost = 4
    parts2 = SPOOL.device_partition_pages(ex2, page, (0,), 8)
    assert all(pp.capacity == 4 * cap for _, pp in parts2)


def test_pallas_partition_variant_interpret():
    """pallas_join_enabled=force runs the Pallas partition-id variant
    in interpret mode (the CPU test path): deterministic,
    partition-complete, and parity with itself across calls. It is
    NOT hash-compatible with the splitmix64 tier by design — routing
    needs only self-consistency within one exchange."""
    page = _key_page()
    ex = Executor({"tpch": TpchConnector(SF)})
    ex.device_exchange = "true"
    ex.pallas_join = "force"
    a = SPOOL.device_partition_pages(ex, page, (0, 1), 4)
    b = SPOOL.device_partition_pages(ex, page, (0, 1), 4)
    rows_a = [sorted(map(repr, pp.to_pylist())) for _, pp in a]
    rows_b = [sorted(map(repr, pp.to_pylist())) for _, pp in b]
    assert rows_a == rows_b
    total = sum(len(r) for r in rows_a)
    assert total == len(page.to_pylist())


# ------------------------------------------- acceptance: zero-crossing
@pytest.fixture(scope="module")
def workers():
    w1 = WorkerServer({"tpch": TpchConnector(SF)}, node_id="w1",
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    w2 = WorkerServer({"tpch": TpchConnector(SF)}, node_id="w2",
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    uris = [f"http://127.0.0.1:{w1.start()}",
            f"http://127.0.0.1:{w2.start()}"]
    yield uris
    w1.stop()
    w2.stop()


@pytest.fixture(scope="module")
def q3_base():
    """Single-node q3-family baseline rows, computed once: four tests
    compare distributed rows against it and the LocalRunner compile
    is the expensive part."""
    single = LocalRunner({"tpch": TpchConnector(SF)},
                         page_rows=PAGE_ROWS)
    return single.execute(Q3_FAMILY).rows


def _coord(workers, **props):
    defaults = {
        "stage_scheduler": "true",
        "join_distribution_type": "partitioned",
        "retry_backoff_ms": 20,
    }
    defaults.update(props)
    return DcnRunner({"tpch": TpchConnector(SF)}, workers,
                     default_catalog="tpch", page_rows=PAGE_ROWS,
                     session_props=defaults)


def test_mesh_local_exchange_zero_crossings(workers, q3_base):
    """THE acceptance pin: a forced-partitioned q3-family query over
    same-process workers with device_exchange_enabled records ZERO
    h2d/d2h process-total deltas for the exchange phase (snapshot at
    the last stage boundary — every worker emit and consumer ingest
    has happened by then), zero h2d for the whole query (only result
    decode crosses, d2h), and rows identical to both the host-spool
    path and the sqlite oracle."""
    base = q3_base

    # mesh_exchange_mode=false: this test pins the SPOOL plane's
    # ledger (per-partition spools + stats vectors); the ICI
    # all_to_all plane (ISSUE 18), which pulls no stats vectors at
    # all, has its own pin in test_ici_exchange_ledger_pin
    coord = _coord(workers, device_exchange_enabled="true",
                   mesh_exchange_mode="false")
    at_stage = {}

    def hook(fid):
        at_stage["totals"] = XF.process_totals()
        at_stage["spooled"] = coord.runner.executor \
            .spooled_exchange_pages

    coord._stage_hook = hook
    t0 = XF.process_totals()
    spooled0 = coord.runner.executor.spooled_exchange_pages
    try:
        rows = coord.execute(Q3_FAMILY)
    finally:
        coord._stage_hook = None
    t1 = XF.process_totals()
    assert coord.last_distribution == "stage-dag"
    # exchange phase: zero PAGE-DATA crossings end to end. The only
    # d2h is the adaptive spool-stats plane (ISSUE 15): ONE int64
    # per spooled partition entry — the per-partition row-count
    # vector the device partition program emits alongside the pages
    # (ROOFLINE §13). Pinning EXACT equality keeps the zero-copy
    # contract falsifiable: any real page pull would dwarf 8
    # bytes/entry.
    ex_h2d = at_stage["totals"]["h2d_bytes"] - t0["h2d_bytes"]
    ex_d2h = at_stage["totals"]["d2h_bytes"] - t0["d2h_bytes"]
    stats_bytes = 8 * (at_stage["spooled"] - spooled0)
    assert ex_h2d == 0, f"exchange phase staged {ex_h2d} bytes h2d"
    assert ex_d2h == stats_bytes, (
        f"exchange phase pulled {ex_d2h} bytes d2h — expected "
        f"exactly the spool-stats vectors ({stats_bytes} bytes)")
    # whole query: nothing ever stages back; decode (and the stats
    # vectors) are the only d2h
    assert t1["h2d_bytes"] - t0["h2d_bytes"] == 0
    assert t1["d2h_bytes"] - t0["d2h_bytes"] > 0
    assert coord.runner.executor.mesh_local_exchanges >= 1
    # parity: host-spool path and sqlite oracle
    host_rows = _coord(workers,
                       device_exchange_enabled="false").execute(
        Q3_FAMILY)
    assert rows_equal(rows, host_rows)
    assert rows_equal(rows, base)
    db = load_sqlite(TpchConnector(SF), ["lineitem", "orders"])
    want = db.execute(Q3_FAMILY).fetchall()
    assert rows_equal(rows, want)


def test_host_spool_path_pays_the_copy_tax(workers):
    """The transfer-ledger diff the tentpole is graded by: the
    host-spool path records real h2d AND d2h exchange volume for the
    same query the device tier completes at zero (the ROOFLINE §11
    d2h/h2d pair)."""
    coord = _coord(workers, device_exchange_enabled="false")
    t0 = XF.process_totals()
    coord.execute(Q3_FAMILY)
    t1 = XF.process_totals()
    assert t1["h2d_bytes"] - t0["h2d_bytes"] > 0
    assert t1["d2h_bytes"] - t0["d2h_bytes"] > 0


# ------------------------------------ fallback: lazy spools + replay
def test_lazy_spool_materializes_for_http(workers):
    """Device-resident spool entries hold Pages (no serialization at
    emit); an HTTP fetch — what a DCN-remote consumer or a replay
    does — lazily materializes byte-identical wire blobs, and the
    deserialized rows match the direct Page read."""
    import json
    import urllib.request

    from presto_tpu.dist import serde

    uri = workers[0]
    payload = {
        "taskId": "lazytest.f0.t0",
        "sql": None,
        "splitTable": "orders",
        "splitIndex": 0,
        "splitCount": 1,
        "outputPartitions": 3,
        "outputKeys": [0],
        "session": {"device_exchange_enabled": "true"},
        "fragment": None,
    }
    # ship a real fragment: scan orders, project keys
    r = LocalRunner({"tpch": TpchConnector(SF)}, page_rows=PAGE_ROWS)
    plan = r.plan("select o_orderkey, o_custkey from orders "
                  "where o_orderkey < 500")
    from presto_tpu.dist import plan_serde
    from presto_tpu.dist.fragmenter import clip_for_shipping

    payload["fragment"] = plan_serde.dumps(clip_for_shipping(plan))
    req = urllib.request.Request(
        f"{uri}/v1/task", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    urllib.request.urlopen(req, timeout=10).close()
    # wait for completion via status plane
    import time

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with urllib.request.urlopen(
                f"{uri}/v1/task/lazytest.f0.t0", timeout=5) as resp:
            st = json.loads(resp.read().decode())
        if st["state"] != "RUNNING":
            break
        time.sleep(0.05)
    assert st["state"] == "FINISHED", st.get("error")
    # the spool holds LAZY page entries (nothing serialized at emit)
    from presto_tpu.server.worker import local_runtime

    rt = local_runtime(uri)
    task = rt.get_task("lazytest.f0.t0")
    entries = [e for p in task.spool.parts for e in p._entries]
    assert entries and all(e[0] == "page" for e in entries)
    # direct Page read (the mesh-local path)
    direct = []
    for p in range(3):
        for page in SPOOL.local_source_pages(uri, "lazytest.f0.t0", p):
            direct.extend(page.to_pylist())
    # HTTP fetch (the remote/replay path): lazy materialization
    fetched = []
    for p in range(3):
        for blob in SPOOL.fetch_spool_blobs(uri, "lazytest.f0.t0", p):
            fetched.extend(serde.deserialize_page(blob).to_pylist())
        # byte-identical on re-fetch (replay prefix verification)
        again = list(SPOOL.fetch_spool_blobs(uri, "lazytest.f0.t0", p))
        assert again == list(SPOOL.fetch_spool_blobs(
            uri, "lazytest.f0.t0", p))
    assert rows_equal(direct, fetched)
    urllib.request.urlopen(urllib.request.Request(
        f"{uri}/v1/task/lazytest.f0.t0", method="DELETE"),
        timeout=5).close()


def test_worker_loss_mid_exchange_replays(workers, q3_base):
    """Forced fallback: a worker lost between stages (HTTP down AND
    out of the local-runtime registry, so the mesh-local path cannot
    serve its spools) still completes — the scheduler excludes the
    node and replays its tasks on the survivor, and rows match the
    healthy run. Uses its own workers so the module fixture survives."""
    w1 = WorkerServer({"tpch": TpchConnector(SF)}, node_id="k1",
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    w2 = WorkerServer({"tpch": TpchConnector(SF)}, node_id="k2",
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    uris = [f"http://127.0.0.1:{w1.start()}",
            f"http://127.0.0.1:{w2.start()}"]
    try:
        base = q3_base
        coord = _coord(uris, device_exchange_enabled="true",
                       task_retry_attempts=3)
        killed = {}

        def hook(fid):
            if not killed:
                killed["uri"] = True
                w1.stop()  # unregisters locally + kills HTTP

        coord._stage_hook = hook
        try:
            rows = coord.execute(Q3_FAMILY)
        finally:
            coord._stage_hook = None
        assert rows_equal(rows, base)
        assert coord.runner.executor.task_retries >= 1
    finally:
        w1.stop()
        w2.stop()


# -------------------------------------------------- buffer donation
def test_donation_overflow_retry_pin():
    """Acceptance: an overflow-retry query with donation forced
    reports buffers_donated >= 1 with rows identical to the
    non-donated baseline and peak_device_bytes no higher."""
    q = ("select n_regionkey, array_agg(n_nationkey) from nation "
         "group by n_regionkey")

    def run(donate):
        r = LocalRunner({"tpch": TpchConnector(SF)},
                        default_catalog="tpch", page_rows=PAGE_ROWS)
        r.session.set("buffer_donation_enabled", donate)
        # 5 nations per region vs 2 slots: guaranteed first-run
        # collect-state overflow onto the boost ladder
        r.session.set("array_agg_max_elements", 2)
        rows = r.execute(q).rows
        ex = r.executor
        return rows, ex

    rows_off, ex_off = run("false")
    rows_on, ex_on = run("true")
    assert ex_off.capacity_boost_retries > 0
    assert ex_on.capacity_boost_retries > 0
    assert rows_equal(rows_off, rows_on)
    assert ex_off.buffers_donated == 0
    assert ex_on.buffers_donated >= 1
    assert ex_on.peak_memory_bytes <= ex_off.peak_memory_bytes


def test_donation_oracle_parity_grouped_agg():
    """Donation changes allocations, never results: grouped
    aggregation with donation forced matches the sqlite oracle."""
    q = ("select l_orderkey, count(*), sum(l_quantity) from lineitem "
         "where l_orderkey < 400 group by l_orderkey "
         "order by l_orderkey")
    r = LocalRunner({"tpch": TpchConnector(SF)},
                    default_catalog="tpch", page_rows=PAGE_ROWS)
    r.session.set("buffer_donation_enabled", "true")
    rows = r.execute(q).rows
    assert r.executor.buffers_donated >= 1
    db = load_sqlite(TpchConnector(SF), ["lineitem"])
    want = db.execute(q).fetchall()
    assert rows_equal([tuple(x) for x in rows],
                      [tuple(x) for x in want])


def test_membudget_model_discounts_donated_state():
    """The footprint model learns donation: a donated fold
    accumulator counts half (merge in/out share one allocation), so
    the audited peak with donation on never exceeds the peak with it
    off — and the agg-state buffer is marked donated."""
    from presto_tpu.exec import membudget as MB

    r = LocalRunner({"tpch": TpchConnector(SF)},
                    default_catalog="tpch", page_rows=PAGE_ROWS)
    plan = r.plan("select l_orderkey, sum(l_quantity) from lineitem "
                  "group by l_orderkey")
    ex = r.executor
    ex.buffer_donation = "false"
    off = MB.audit(ex, plan)
    ex.buffer_donation = "true"
    on = MB.audit(ex, plan)
    assert on.peak_bytes <= off.peak_bytes
    donated = [b for b in on.buffers if b.donated]
    assert any(b.label == "agg state" for b in donated)
    assert not any(b.donated for b in off.buffers)
    for b in donated:
        assert b.live_bytes == b.bytes // 2


def test_donated_jit_wrapper_is_salted():
    """Flipping the donation knob mid-executor must not hand a
    donating program to a non-donating call site (the cache-key salt
    contract)."""
    ex = Executor({"tpch": TpchConnector(SF)})
    ex.buffer_donation = "true"
    f1 = ex._jit(("k",), lambda x: x + 1, donate_argnums=(0,))
    ex.buffer_donation = "false"
    f2 = ex._jit(("k",), lambda x: x + 1, donate_argnums=(0,))
    assert f1 is not f2
    import jax.numpy as jnp

    x = jnp.arange(4)
    assert np.array_equal(np.asarray(f2(x)), np.arange(4) + 1)
    assert np.array_equal(np.asarray(x), np.arange(4))  # NOT donated


# ------------------------- ISSUE 18: ICI all_to_all exchange plane
def _partition_rows(pairs_or_lists, nparts):
    """Normalize both planes' outputs to sorted row-repr lists per
    partition: spool plane yields (p, page) pairs, the ICI plane a
    list-of-page-lists indexed by partition."""
    out = [[] for _ in range(nparts)]
    if isinstance(pairs_or_lists, list) and pairs_or_lists and \
            isinstance(pairs_or_lists[0], list):
        for p, plist in enumerate(pairs_or_lists):
            for pp in plist:
                out[p].extend(map(repr, pp.to_pylist()))
    else:
        for p, pp in pairs_or_lists:
            out[p].extend(map(repr, pp.to_pylist()))
    return [sorted(r) for r in out]


# every key family at nparts=4, plus ONE nparts=2 case: the routing
# hash is nparts-independent (h % D), so one extra D pins the modulo
# plumbing without paying a shard_map compile per (keys, D) pair
@pytest.mark.parametrize("keys,nparts", [
    ((0,), 4), ((0,), 2), ((1,), 4), ((3,), 4), ((4,), 4), ((5,), 4),
    ((0, 1, 2, 3, 4, 5), 4),
])
def test_ici_vs_spool_partition_parity_per_key_type(keys, nparts):
    """The routing contract the fallback depends on: the all_to_all
    program and the spool partitioner put EVERY row in the SAME
    partition for every key family — NULL sentinel, -0.0/NaN
    normalization, dictionary VALUE hashes, short and long decimal —
    because both compute the identical splitmix64 row hash."""
    from presto_tpu.dist import executor as DX

    page = _key_page()
    ex = Executor({"tpch": TpchConnector(SF)})
    ex.device_exchange = "true"
    parts, nbytes = DX.ici_exchange_pages(ex, [page], keys, nparts)
    ici = _partition_rows(parts, nparts)
    spool = _partition_rows(
        list(SPOOL.device_partition_pages(ex, page, keys, nparts)),
        nparts)
    assert ici == spool, f"keys={keys} nparts={nparts}"
    assert sum(len(r) for r in ici) == len(page.to_pylist())
    assert nbytes > 0


def test_ici_skew_overflow_boosts_and_preserves_rows():
    """Seeded skew on the ICI path: every row hashes to ONE partition,
    overflowing the chunk-bucketed landing capacity — the OR-reduced
    overflow flag settles on the boost ladder (capacity_boost_retries
    counted) and no row is dropped."""
    from presto_tpu.dist import executor as DX

    n = 1 << 14
    page = Page.from_arrays([[7] * n], [T.BIGINT])
    ex = Executor({"tpch": TpchConnector(SF)})
    ex.device_exchange = "true"
    r0 = ex.capacity_boost_retries
    parts, _ = DX.ici_exchange_pages(ex, [page], (0,), 4)
    assert ex.capacity_boost_retries - r0 >= 1
    rows = [r for plist in parts for pp in plist
            for r in pp.to_pylist()]
    assert len(rows) == n
    nonempty = [p for p, plist in enumerate(parts)
                if any(pp.num_rows() for pp in plist)]
    assert len(nonempty) == 1  # the skewed key routes to ONE shard


def test_ici_exchange_ledger_pin(workers, q3_base, monkeypatch):
    """THE ISSUE-18 acceptance pin: on the mesh path the q3-family
    exchange phase crosses ZERO bytes in EITHER direction (no spool
    stats vectors — the collective pulls nothing) AND serializes ZERO
    spool blobs (the wire codec never runs), with ici_exchanges
    counted and rows identical to the spool plane and the sqlite
    oracle."""
    base = q3_base

    blobs = {"n": 0}
    real = SPOOL.spool_blob

    def counting_blob(page):
        blobs["n"] += 1
        return real(page)

    monkeypatch.setattr(SPOOL, "spool_blob", counting_blob)
    coord = _coord(workers, device_exchange_enabled="true")  # auto mesh
    snaps = []

    def hook(fid):
        snaps.append(XF.process_totals())

    coord._stage_hook = hook
    t0 = XF.process_totals()
    try:
        rows = coord.execute(Q3_FAMILY)
    finally:
        coord._stage_hook = None
    t1 = XF.process_totals()
    ex = coord.runner.executor
    assert coord.last_distribution == "stage-dag"
    assert ex.ici_exchanges >= 1
    assert ex.mesh_exchange_fallbacks == 0
    assert ex.ici_bytes > 0
    # q3's DAG is [repartition, repartition, gather]; each _stage_hook
    # boundary fires AFTER that stage's barrier AND its post-barrier
    # all_to_all, so the second-to-last snapshot closes the exchange
    # phase. (The final gather stage still pays the ISSUE-15 gather-
    # edge spool-stats pull — 8 bytes/page — which is NOT an exchange
    # crossing; the mesh plane deleted the repartition-edge stats
    # entirely, which is exactly what this pin holds at ZERO.)
    assert len(snaps) >= 2
    ex_h2d = snaps[-2]["h2d_bytes"] - t0["h2d_bytes"]
    ex_d2h = snaps[-2]["d2h_bytes"] - t0["d2h_bytes"]
    assert ex_h2d == 0, f"ICI exchange staged {ex_h2d} bytes h2d"
    assert ex_d2h == 0, f"ICI exchange pulled {ex_d2h} bytes d2h"
    assert blobs["n"] == 0, (
        f"mesh path serialized {blobs['n']} spool blobs — the wire "
        f"codec must never run on the ICI plane")
    # whole query: only result decode crosses (d2h)
    assert t1["h2d_bytes"] - t0["h2d_bytes"] == 0
    assert t1["d2h_bytes"] - t0["d2h_bytes"] > 0
    # parity: spool plane and sqlite oracle
    monkeypatch.setattr(SPOOL, "spool_blob", real)
    spool_rows = _coord(workers, device_exchange_enabled="true",
                        mesh_exchange_mode="false").execute(Q3_FAMILY)
    assert rows_equal(rows, spool_rows)
    assert rows_equal(rows, base)
    db = load_sqlite(TpchConnector(SF), ["lineitem", "orders"])
    assert rows_equal(rows, db.execute(Q3_FAMILY).fetchall())


def test_ici_trace_failure_falls_back_to_spool(workers, q3_base,
                                               monkeypatch):
    """Mid-query fallback: when the collective cannot lower (forced
    here by making ici_exchange_pages raise), the scheduler falls
    back LOUDLY to the spool partitioner — counted, logged — and the
    query still returns identical rows, because the fallback routes
    with the bit-identical splitmix64 hash."""
    from presto_tpu.dist import executor as DX

    base = q3_base

    def boom(ex, pages, keys, nparts):
        raise RuntimeError("forced trace failure")

    monkeypatch.setattr(DX, "ici_exchange_pages", boom)
    coord = _coord(workers, device_exchange_enabled="true")
    rows = coord.execute(Q3_FAMILY)
    ex = coord.runner.executor
    assert ex.mesh_exchange_fallbacks >= 1
    assert ex.ici_exchanges == 0
    assert rows_equal(rows, base)


# ------------------------------------------------- xfercheck jnp gap
def test_xfercheck_catches_jnp_asarray_of_host_array(tmp_path):
    """The satellite: a jnp.asarray of a non-literal argument is an
    h2d primitive the gate must see (undeclared -> finding); host
    literals stay exempt."""
    from tools.xfercheck import run_xfercheck

    bad = tmp_path / "presto_tpu" / "exec" / "victim.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import jax.numpy as jnp\n"
        "def stage(arr):\n"
        "    return jnp.asarray(arr)\n"
        "def literal_ok():\n"
        "    return jnp.asarray([1, 2, 3])\n"
    )
    findings = run_xfercheck([str(bad)])
    assert any(f.rule == "xfer-registry" and "stage" in f.message
               for f in findings)
    assert not any("literal_ok" in f.message for f in findings)
