"""ISSUE 15: adaptive execution — runtime re-planning at
spooled-exchange stage boundaries (presto_tpu/adaptive/).

Ring by ring:
  - the spool-stats plane: worker-reported per-partition row/byte
    counts are EXACT against the actually-fetched page streams across
    the host/disk/device spool tiers, and IDENTICAL after a replay of
    the same logical task (determinism — re-planning after a worker
    loss must not diverge);
  - the Replanner in isolation: skew hints, observe-only mode, and
    verify-failure rollback (the loud static-plan fallback);
  - skew pre-engagement on a worker: a skewHint task starts in the
    position-chunked rebalance (skew_preempted >= 1, zero boosts)
    where the un-hinted task discovers the hot build key by overflow;
  - THE acceptance (misestimated join corpus, build-side estimate
    >= 10x off): adaptive beats the static plan on wall clock with
    adaptive_replans >= 1, split_batch_fallbacks == 0, zero
    capacity_boost_retries on the re-planned stages, and rows
    identical to both the static plan and the sqlite oracle;
  - the distribution flip: a repartitioned build observed under the
    broadcast share is re-read broadcast-style and the pending probe
    producer degrades to a passthrough edge, rows unchanged.
"""

import collections
import json
import random
import time
import urllib.request

import pytest

from presto_tpu import types as T
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.dist.dcn import DcnRunner
from presto_tpu.runner import LocalRunner
from presto_tpu.server.worker import WorkerServer

PAGE_ROWS = 1 << 13


class Misestimate:
    """Connector wrapper lying about row counts — the corpus's
    misestimated-stats stand-in (data itself stays honest, so the
    sqlite oracle loads real rows)."""

    def __init__(self, inner, claims):
        self._inner = inner
        self._claims = dict(claims)

    def row_count(self, table):
        if table in self._claims:
            return self._claims[table]
        return self._inner.row_count(table)

    def host_rows(self, table, target_rows=1 << 20):
        # oracle loading reads the REAL rows (claims lie, data not)
        return list(self._inner._tables[table].rows)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _corpus():
    """The skewed/misestimated join corpus (memory connector).

    fact/dim:   the under-estimate rung — fact claims 5k rows
                (real 110k, >=10x off) so the planner's aggregation
                capacity starts ~8192 against ~76k real groups;
    bulk/small: the flip rung — the PROBE side (bulk) claims 300k
                (real 900, >=100x off) while the build side claims
                20k (real 40k), so the static plan partitions both
                sides and the observed-tiny probe flips to the
                broadcast build at the first stage boundary;
    sfact/sdim: the skew rung — sdim's build rows pile 70%+ onto one
                key.
    """
    mem = MemoryConnector()
    rnd = random.Random(7)
    n_fact, groups = 110_000, 76_000
    mem.create_table(
        "fact", ["k", "g", "v"], [T.BIGINT] * 3,
        [(rnd.randrange(50), i % groups, rnd.randrange(1000))
         for i in range(n_fact)])
    mem.create_table("dim", ["k", "w"], [T.BIGINT] * 2,
                     [(k, k * 10) for k in range(50)])
    mem.create_table(
        "bulk", ["k", "v"], [T.BIGINT] * 2,
        [(k % 900, k % 7) for k in range(900)])
    mem.create_table(
        "small", ["k", "w"], [T.BIGINT] * 2,
        [(rnd.randrange(900), rnd.randrange(100))
         for _ in range(40_000)])
    mem.create_table(
        "sfact", ["k", "v"], [T.BIGINT] * 2,
        [(4 + rnd.randrange(800), rnd.randrange(100))
         for _ in range(12_000)])
    sdim = [(3, i) for i in range(6_500)]
    sdim += [(4 + i % 500, i) for i in range(2_500)]
    mem.create_table("sdim", ["k", "w"], [T.BIGINT] * 2, sdim)
    return mem


CLAIMS = {
    "fact": 5_000,      # 22x under-estimate
    "bulk": 300_000,    # 333x over-estimate (the flip's probe side)
    "small": 20_000,
}

Q_SEED = ("select g, count(*) c, sum(v + w) s from fact "
          "join dim on fact.k = dim.k group by g "
          "order by s desc, g limit 100")
Q_FLIP = ("select w, count(*) c from bulk join small "
          "on bulk.k = small.k group by w")


@pytest.fixture(scope="module")
def cat():
    return Misestimate(_corpus(), CLAIMS)


@pytest.fixture(scope="module")
def workers(cat):
    w1 = WorkerServer({"mem": cat}, node_id="w1",
                      default_catalog="mem", page_rows=PAGE_ROWS)
    w2 = WorkerServer({"mem": cat}, node_id="w2",
                      default_catalog="mem", page_rows=PAGE_ROWS)
    uris = [f"http://127.0.0.1:{w1.start()}",
            f"http://127.0.0.1:{w2.start()}"]
    yield uris
    w1.stop()
    w2.stop()


@pytest.fixture(scope="module")
def single(cat):
    return LocalRunner({"mem": cat}, default_catalog="mem",
                       page_rows=PAGE_ROWS)


@pytest.fixture(scope="module")
def oracle_db(cat):
    from tests.oracle import load_sqlite

    return load_sqlite(cat, ["fact", "dim", "bulk", "small"])


def rows_equal(a, b):
    return collections.Counter(map(repr, a)) == \
        collections.Counter(map(repr, b))


_CATS = {}


def _coord(workers, adaptive=True, **props):
    defaults = {
        "retry_backoff_ms": 20,
        "stage_scheduler": "true",
        "agg_gather_capacity": 64,
        "adaptive_execution": "auto" if adaptive else "false",
    }
    defaults.update(props)
    return DcnRunner({"mem": _CATS["cat"]}, workers,
                     default_catalog="mem",
                     page_rows=PAGE_ROWS, session_props=defaults)


@pytest.fixture(autouse=True, scope="module")
def _stash_cat(cat):
    _CATS["cat"] = cat
    yield
    _CATS.clear()


def _run(coord, sql):
    t0 = time.time()
    rows = coord.execute(sql)
    wall = time.time() - t0
    ex = coord.runner.executor
    sched = coord.last_scheduler
    stage_boosts = {
        fid: sum(int((t.status or {}).get("boostRetries") or 0)
                 for t in ts)
        for fid, ts in sched.tasks.items()
    }
    return rows, wall, ex, sched, stage_boosts


# ------------------------------------------------------- acceptance
def test_adaptive_beats_static_on_misestimated_join(
        workers, single, oracle_db):
    """THE ISSUE 15 acceptance: on the misestimated join corpus
    (fact's estimate 22x low) the adaptive run re-plans the
    not-yet-dispatched consumer stages from exact spool stats and
    (a) applies >= 1 re-plan, (b) drives capacity_boost_retries to
    ZERO on the re-planned stages (the static run climbs the ladder
    there), (c) keeps split_batch_fallbacks at 0, (d) beats the
    static plan on wall clock, and (e) returns rows identical to the
    static plan AND the sqlite oracle."""
    want = oracle_db.execute(
        "select g, count(*) c, sum(v + w) s from fact "
        "join dim on fact.k = dim.k group by g "
        "order by s desc, g limit 100").fetchall()

    def one(adaptive):
        coord = _coord(workers, adaptive=adaptive)
        try:
            return _run(coord, Q_SEED)
        finally:
            coord.close()

    # untimed warm pass per mode: compiles land in the persistent
    # cache so the timed comparison measures execution, not XLA
    one(False)
    one(True)
    for attempt in range(3):  # retries absorb 2-core-box jitter —
        # the systematic term (4 extra stage re-executions on the
        # static ladder vs ~12 ms of replan wall) is what must win
        rows_s, wall_s, ex_s, sched_s, boosts_s = one(False)
        rows_a, wall_a, ex_a, sched_a, boosts_a = one(True)
        if wall_a < wall_s or attempt == 2:
            break
    # (a) re-plans applied, and only on the adaptive run
    assert ex_a.adaptive_replans >= 1
    assert ex_s.adaptive_replans == 0
    assert ex_a.adaptive_replan_rejected == 0
    assert ex_a.adaptive_capacity_seeds >= 1
    # (b) the static plan climbed the overflow ladder on the
    # re-planned (non-leaf) stages; adaptive starts at the settled
    # bucket — zero boosts anywhere in the query
    replanned = [f.fid for f in sched_a.dag.fragments if f.inputs]
    assert replanned, "corpus query must have non-leaf stages"
    assert sum(boosts_s[f] for f in replanned) >= 1, (
        f"static plan never overflowed — the corpus lost its "
        f"misestimate ({boosts_s})")
    assert all(boosts_a[f] == 0 for f in replanned), boosts_a
    assert ex_a.capacity_boost_retries == 0
    # (c) no split-batch fallbacks
    assert ex_a.split_batch_fallbacks == 0
    assert ex_s.split_batch_fallbacks == 0
    # (d) wall clock: the static run re-executes its final-agg stage
    # per ladder rung; adaptive runs it once at the observed bucket
    assert wall_a < wall_s, (
        f"adaptive {wall_a:.3f}s not faster than static "
        f"{wall_s:.3f}s (adaptive replans={ex_a.adaptive_replans}, "
        f"static stage boosts={boosts_s})")
    # (e) rows: adaptive == static == sqlite oracle (ordered query)
    assert list(map(tuple, rows_a)) == list(map(tuple, rows_s))
    assert [tuple(r) for r in rows_a] == [tuple(r) for r in want]


def test_dist_flip_broadcast_read_and_passthrough(
        workers, single, oracle_db):
    """The distribution flip: bulk (probe, claimed 300k) is observed
    at 900 rows — under the broadcast share — at its stage boundary,
    BEFORE the build-side producer dispatched. The re-planner swaps
    the join sides, reads the already-spooled partitions
    broadcast-style, and degrades the pending producer to a
    passthrough edge (no hashing, no partition compaction). Rows
    match the static plan and the oracle."""
    want = oracle_db.execute(
        "select w, count(*) c from bulk join small "
        "on bulk.k = small.k group by w").fetchall()
    coord_s = _coord(workers, adaptive=False,
                     broadcast_join_rows=4096)
    coord_a = _coord(workers, adaptive=True,
                     broadcast_join_rows=4096)
    try:
        rows_s, _, ex_s, sched_s, _ = _run(coord_s, Q_FLIP)
        assert all(f.output_kind != "passthrough"
                   for f in sched_s.dag.fragments)
        rows_a, _, ex_a, sched_a, boosts_a = _run(coord_a, Q_FLIP)
        assert ex_a.adaptive_dist_flips >= 1
        assert ex_a.adaptive_replans >= 1
        assert "broadcast" in sched_a.dag.reads.values()
        kinds = [f.output_kind for f in sched_a.dag.fragments]
        assert "passthrough" in kinds, kinds
        assert rows_equal(rows_a, rows_s)
        assert rows_equal(rows_a, want)
    finally:
        coord_s.close()
        coord_a.close()


def test_adaptive_execution_false_pins_static(workers):
    coord = _coord(workers, adaptive=False)
    try:
        _, _, ex, sched, _ = _run(coord, Q_FLIP)
        assert ex.adaptive_replans == 0
        assert ex.adaptive_dist_flips == 0
        assert sched.replanner is None
    finally:
        coord.close()


def test_observe_only_mode(workers):
    """adaptive_max_replans=0: the re-planner observes stats but
    never mutates the DAG."""
    coord = _coord(workers, adaptive=True, adaptive_max_replans=0)
    try:
        _, _, ex, sched, _ = _run(coord, Q_FLIP)
        assert sched.replanner is not None
        assert sched.replanner.stats  # observations accumulated
        assert ex.adaptive_replans == 0
        assert ex.adaptive_dist_flips == 0
        assert not sched.dag.reads
    finally:
        coord.close()


# ------------------------------------------------ replanner rollback
def test_rejected_replan_rolls_back(workers, monkeypatch):
    """A mutated DAG that fails verify_dag rolls back COMPLETELY —
    the static plan runs, counted on adaptive_replan_rejected."""
    from presto_tpu.exec import plan_check as PC

    real = PC.verify_dag

    def failing(ex, dag, strict=False):
        raise PC.PlanCheckError(["seeded verify failure"])

    coord = _coord(workers, adaptive=True)
    try:
        monkeypatch.setattr(PC, "verify_dag", failing)
        rows, _, ex, sched, _ = _run(coord, Q_FLIP)
        assert ex.adaptive_replans == 0
        assert ex.adaptive_replan_rejected >= 1
        # rollback left NO adaptive residue: the dag ran static
        assert not sched.dag.reads
        assert not sched.dag.hints
        assert all(f.output_kind != "passthrough"
                   for f in sched.dag.fragments)
        monkeypatch.setattr(PC, "verify_dag", real)
        coord2 = _coord(workers, adaptive=False)
        try:
            rows_s = coord2.execute(Q_FLIP)
        finally:
            coord2.close()
        assert rows_equal(rows, rows_s)
    finally:
        coord.close()


def test_reads_only_flip_counts_and_verifies(single, monkeypatch):
    """Regression: a flip that only mutates dag.reads (no tree
    rewrite — e.g. the build side flips while no est stamp changes)
    must still report an outcome, run verification, and respect the
    replan bound — it is a behavior mutation even though every
    fragment root is identity-preserved."""
    from presto_tpu.adaptive import Replanner, StageStats
    from presto_tpu.dist.fragmenter import fragment_dag
    from presto_tpu.exec import plan as P

    plan = single.plan(Q_FLIP)
    # pin the row threshold so the claimed sizes force a
    # co-partitioned join (the DCN tests do this via the session)
    dag = fragment_dag(single.executor, plan, single.catalogs,
                       broadcast_rows=4096)
    assert dag is not None
    # find the co-partitioned join's BUILD-side producer fid
    rf = None
    for f in dag.fragments:

        def find(n):
            nonlocal rf
            if isinstance(n, P.HashJoin) and \
                    isinstance(n.right, P.RemoteSource):
                rf = int(n.right.key[len("stage"):])
            for c in n.children():
                find(c)

        find(f.root)
    assert rf is not None
    assert dag.fragment(rf).output_kind == "repartition"
    rp = Replanner(single.executor, dag, broadcast_rows=1 << 20,
                   max_replans=4)
    # force the reads-only shape: est stamping suppressed, so the
    # ONLY mutation the flip makes is the dag.reads override
    monkeypatch.setattr(
        rp, "_reseed", lambda root, fid, out: root)
    rp.observe(StageStats(
        fid=rf, rows=500, bytes=8_000, part_rows=(250, 250),
        part_bytes=(4_000, 4_000), task_rows=(250, 250)))
    dispatched = {f.fid for f in dag.fragments}
    dispatched.discard([c for c in dag.consumers(rf)][0])
    out = rp.replan(dispatched)
    assert out is not None, (
        "reads-only flip reported as no-change — it bypassed "
        "verification, the bound, and the counters")
    assert not out.rejected
    assert out.dist_flips >= 1
    assert any(v == "broadcast" for v in dag.reads.values())
    # the bound applies to reads-only mutations too
    rp.replans_applied = rp.max_replans
    dag.reads.clear()
    out2 = rp.replan(dispatched)
    assert out2 is not None and out2.rejected
    assert not dag.reads  # rolled back


def test_replanner_skew_hint_unit(single):
    """Synthetic skewed histogram -> the consumer fragment gets the
    skew hint (the pre-engagement trigger in isolation)."""
    from presto_tpu.adaptive import Replanner, StageStats
    from presto_tpu.dist.fragmenter import fragment_dag

    plan = single.plan(Q_FLIP)
    dag = fragment_dag(single.executor, plan, single.catalogs,
                       **single._session_dist_options())
    assert dag is not None
    rp = Replanner(single.executor, dag, broadcast_rows=1,
                   max_replans=4)
    # producer 0 with a hot partition: ratio 2*0.9 = 1.8... use 4
    # partitions so max/mean = 3.2 crosses the 3.0 threshold
    rp.observe(StageStats(
        fid=0, rows=10_000, bytes=160_000,
        part_rows=(8_000, 700, 700, 600),
        part_bytes=(128_000, 11_200, 11_200, 9_600),
        task_rows=(5_000, 5_000)))
    out = rp.replan({0})
    assert out is not None and not out.rejected
    assert out.skew_hints >= 1
    consumers = dag.consumers(0)
    assert any(dag.hints.get(c, {}).get("skew") for c in consumers)


# ------------------------------------------- skew pre-engagement e2e
def _post_task(uri, payload):
    req = urllib.request.Request(
        f"{uri}/v1/task", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    urllib.request.urlopen(req, timeout=30).close()


def _wait_status(uri, task_id, timeout_s=120):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        with urllib.request.urlopen(
                f"{uri}/v1/task/{task_id}", timeout=10) as r:
            st = json.loads(r.read().decode())
        if st["state"] != "RUNNING":
            assert st["state"] == "FINISHED", st.get("error")
            return st
        time.sleep(0.05)
    raise AssertionError("task did not finish")


def _fetch_rows(uri, task_id, part=0):
    from presto_tpu.dist import serde, spool as SPOOL

    rows = []
    nbytes = 0
    for blob in SPOOL.fetch_spool_blobs(uri, task_id, part):
        nbytes += len(blob)
        rows.extend(serde.deserialize_page(blob).to_pylist())
    return rows, nbytes


def _skew_payload(single, task_id, skew_hint):
    from presto_tpu.dist import plan_serde

    plan = single.plan(
        "select sfact.k, v, w from sfact "
        "join sdim on sfact.k = sdim.k")
    payload = {
        "taskId": task_id,
        "fragment": plan_serde.dumps(plan),
        "splitTable": "sfact",
        "splitIndex": 0,
        "splitCount": 1,
        "outputPartitions": 1,
        "session": {"spill_threshold_bytes": 1 << 15,
                    "retry_backoff_ms": 20},
    }
    if skew_hint:
        payload["skewHint"] = True
    return payload


def test_skew_preengagement_on_worker(single, workers):
    """The (d) move end-to-end at the worker: sdim piles 6.5k build
    rows on one key, so the grace-partitioned join's hot partition
    overflows its chunk on the first attempt — UNLESS the payload
    carries the re-planner's skewHint, in which case the position-
    chunked rebalance engages at boost 1 (skew_preempted >= 1,
    boostRetries == 0) with identical rows."""
    uri = workers[0]
    _post_task(uri, _skew_payload(single, "skew-static.0", False))
    st_static = _wait_status(uri, "skew-static.0")
    _post_task(uri, _skew_payload(single, "skew-hint.0", True))
    st_hint = _wait_status(uri, "skew-hint.0")
    assert st_static["boostRetries"] >= 1, (
        "static task never overflowed — the corpus lost its hot key")
    assert st_static["skewPreempted"] == 0
    assert st_hint["skewPreempted"] >= 1
    assert st_hint["boostRetries"] == 0, st_hint
    rows_static, _ = _fetch_rows(uri, "skew-static.0")
    rows_hint, _ = _fetch_rows(uri, "skew-hint.0")
    want = single.execute(
        "select sfact.k, v, w from sfact "
        "join sdim on sfact.k = sdim.k").rows
    assert rows_equal(rows_static, want)
    assert rows_equal(rows_hint, want)


# ------------------------------------------------- spool-stats plane
def _stats_payload(single, task_id, session):
    from presto_tpu.dist import plan_serde

    plan = single.plan("select k, g from fact")
    return {
        "taskId": task_id,
        "fragment": plan_serde.dumps(plan),
        "splitTable": "fact",
        "splitIndex": 0,
        "splitCount": 1,
        "outputPartitions": 3,
        "outputKeys": [1],
        "session": dict(session),
    }


@pytest.mark.parametrize("tier,session", [
    ("host", {}),
    # a tiny resident budget demotes every blob to the DISK tier
    ("disk", {"spool_exchange_bytes": 1}),
    # the device tier spools partition Pages and counts INSIDE the
    # partition program (works interpreted on CPU)
    ("device", {"device_exchange_enabled": "true"}),
])
def test_spool_stats_exact_per_tier(single, workers, tier, session):
    """spoolRows is EXACT against the actually-fetched page streams
    per partition on every spool tier; spoolBytes matches the wire
    bytes on the blob tiers (the device tier reports the resident
    page footprint — the byte meaning the memory decisions want)."""
    uri = workers[1]
    task_id = f"stats-{tier}.0"
    _post_task(uri, _stats_payload(single, task_id, session))
    st = _wait_status(uri, task_id)
    assert "spoolRows" in st and "spoolBytes" in st
    assert len(st["spoolRows"]) == 3
    total = 0
    for p in range(3):
        rows, nbytes = _fetch_rows(uri, task_id, part=p)
        assert st["spoolRows"][p] == len(rows), (
            f"partition {p} on tier {tier}: reported "
            f"{st['spoolRows'][p]} vs fetched {len(rows)}")
        if tier != "device":
            assert st["spoolBytes"][p] == nbytes
        else:
            assert st["spoolBytes"][p] > 0
        total += len(rows)
    # the wrapper CLAIMS 5k; the stats plane reports the real 110k
    assert total == _CATS["cat"]._inner.row_count("fact")


def test_spool_stats_identical_after_replay(single, workers):
    """A replayed task (same fragment, same split share, new taskId)
    reports IDENTICAL spool stats — the determinism re-planning
    after a worker loss depends on (stats observed pre-loss must
    still describe the replacement spools)."""
    uri = workers[1]
    _post_task(uri, _stats_payload(single, "replay-a.0", {}))
    a = _wait_status(uri, "replay-a.0")
    _post_task(uri, _stats_payload(single, "replay-a.0.r1", {}))
    b = _wait_status(uri, "replay-a.0.r1")
    assert a["spoolRows"] == b["spoolRows"]
    assert a["spoolBytes"] == b["spoolBytes"]


# --------------------------------------------------- registry rings
def test_counters_registered(workers):
    from presto_tpu.exec.counters import QUERY_COUNTERS, snapshot

    coord = _coord(workers, adaptive=True)
    try:
        _run(coord, Q_FLIP)
        snap = snapshot(coord.runner.executor)
        for name in ("adaptive_replans", "adaptive_dist_flips",
                     "adaptive_capacity_seeds",
                     "adaptive_replan_rejected", "skew_preempted"):
            assert name in QUERY_COUNTERS
            assert name in snap
        assert snap["adaptive_replans"] >= 1
    finally:
        coord.close()


def test_replan_span_kind_declared():
    from presto_tpu import obs as OBS

    assert "replan" in OBS.SPAN_KINDS


def test_seeded_misestimate_sweep_clean(single):
    """The plan_audit sweep in miniature: synthetic 10x-off stats on
    a real corpus DAG, strict verification after every boundary."""
    from presto_tpu.dist.fragmenter import fragment_dag
    from tools.plan_audit import _seeded_misestimate_sweep

    plan = single.plan(Q_SEED)
    dag = fragment_dag(single.executor, plan, single.catalogs,
                       **single._session_dist_options())
    assert dag is not None
    failures = []
    _seeded_misestimate_sweep(single, "test", dag, failures)
    assert not failures, failures
