"""Predicate pushdown (TupleDomain analog): range extraction from
filters, generator split pruning via monotonic key inversion, memory
connector min/max stats pruning. Reference: spi/predicate/TupleDomain +
ConnectorSplitManager pushdown.
"""

import pytest

from presto_tpu import types as T
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec import plan as P
from presto_tpu.exec.pushdown import extract_ranges, push_scan_constraints
from presto_tpu.expr import ir
from presto_tpu.runner import LocalRunner


def _scan_constraints(plan):
    out = {}

    def walk(n):
        if isinstance(n, P.TableScan) and n.constraint:
            out[n.table] = dict(
                (c, (lo, hi)) for c, lo, hi in n.constraint
            )
        for k in n.children():
            walk(k)

    walk(plan)
    return out


class TestExtraction:
    def ref(self, ch=0):
        return ir.InputRef(ch, T.BIGINT)

    def lit(self, v):
        return ir.Constant(v, T.BIGINT)

    def test_comparisons_and_flips(self):
        pred = ir.and_(
            ir.call("ge", self.ref(), self.lit(10)),
            ir.call("lt", self.ref(), self.lit(20)),
            ir.call("gt", self.lit(100), self.ref(1)),  # flipped: #1 < 100
        )
        got = extract_ranges(pred, 2)
        assert got[0] == (10, 19)
        assert got[1] == (None, 99)

    def test_between_eq_in(self):
        pred = ir.and_(
            ir.between(self.ref(), self.lit(5), self.lit(9)),
            ir.SpecialForm(ir.IN, (
                self.ref(1), self.lit(3), self.lit(7), self.lit(5),
            ), T.BOOLEAN),
            ir.call("eq", self.ref(2), self.lit(42)),
        )
        got = extract_ranges(pred, 3)
        assert got[0] == (5, 9)
        assert got[1] == (3, 7)
        assert got[2] == (42, 42)

    def test_non_integer_and_unprovable_ignored(self):
        pred = ir.and_(
            ir.call("ge", ir.InputRef(0, T.DOUBLE),
                    ir.Constant(1.5, T.DOUBLE)),
            ir.call("eq", self.ref(1), self.ref(0)),  # col-col: no range
        )
        assert extract_ranges(pred, 2) == {}


class TestGeneratorPruning:
    @pytest.fixture(scope="class")
    def conn(self):
        return TpchConnector(0.01)

    @pytest.fixture(scope="class")
    def runner(self, conn):
        return LocalRunner({"tpch": conn}, page_rows=1 << 10)

    def test_plan_carries_constraint(self, runner):
        plan = runner.plan(
            "select count(*) from orders where o_orderkey between "
            "1000 and 2000"
        )
        cons = _scan_constraints(plan)
        assert cons["orders"]["o_orderkey"] == (1000, 2000)

    def test_split_pruning_correct_and_effective(self, conn, runner):
        # pruned scan must return exactly the unpruned result
        sql = ("select count(*), sum(o_orderkey) from orders "
               "where o_orderkey between 1000 and 2000")
        got = runner.execute(sql).rows
        # oracle: full scan in python
        rows = conn.host_rows("orders")
        keys = [r[0] for r in rows if 1000 <= r[0] <= 2000]
        assert got == [(len(keys), sum(keys))]
        # and the connector must actually drop splits
        all_splits = conn.splits("orders", 1 << 10)
        pruned = conn.prune_splits(
            "orders", all_splits, (("o_orderkey", 1000, 2000),)
        )
        assert 0 < len(pruned) < len(all_splits)

    def test_lineitem_aligned_pruning(self, conn, runner):
        sql = ("select count(*) from lineitem "
               "where l_orderkey <= 512")
        got = runner.execute(sql).rows[0][0]
        rows = conn.host_rows("lineitem", target_rows=1 << 16)
        want = sum(1 for r in rows if r[0] <= 512)
        assert got == want
        pruned = conn.prune_splits(
            "lineitem", conn.splits("lineitem", 1 << 10),
            (("l_orderkey", None, 512),),
        )
        assert len(pruned) < len(conn.splits("lineitem", 1 << 10))

    def test_date_dim_quarter_scan(self):
        from presto_tpu.connectors.tpcds import TpcdsConnector

        conn = TpcdsConnector(0.005)
        r = LocalRunner({"tpcds": conn}, default_catalog="tpcds",
                        page_rows=1 << 10)
        sql = ("select count(*) from date_dim "
               "where d_date_sk between 2451911 and 2452000")
        assert r.execute(sql).rows[0][0] == 90
        pruned = conn.prune_splits(
            "date_dim", conn.splits("date_dim", 1 << 10),
            (("d_date_sk", 2451911, 2452000),),
        )
        assert len(pruned) == 1


class TestMemoryStatsPruning:
    def test_min_max_split_pruning(self):
        mem = MemoryConnector()
        runner = LocalRunner({"memory": mem}, default_catalog="memory",
                             page_rows=1 << 8)
        # sorted values: later splits are prunable for small ranges
        mem.create_table(
            "t", ["k", "v"], [T.BIGINT, T.BIGINT],
            [(i, i * 2) for i in range(4096)],
        )
        got = runner.execute(
            "select count(*), sum(v) from t where k < 100"
        ).rows
        assert got == [(100, sum(i * 2 for i in range(100)))]
        splits = mem.splits("t", 1 << 8)
        pruned = mem.prune_splits("t", splits, (("k", None, 99),))
        assert len(pruned) == 1 and len(splits) == 16

    def test_all_null_split_dropped(self):
        mem = MemoryConnector()
        mem.create_table(
            "n", ["k"], [T.BIGINT],
            [(None,)] * 256 + [(5,)] * 256,
        )
        splits = mem.splits("n", 256)
        pruned = mem.prune_splits("n", splits, (("k", 0, 10),))
        assert len(pruned) == 1
        assert pruned[0].start_row == 256


class TestUnitSafety:
    def test_decimal_column_with_integer_literal_not_pruned_wrongly(self):
        """A bigint literal is in different units than a decimal(p,2)
        column's unscaled storage; the runtime rescales but split stats
        cannot — such predicates must extract NO range (pruning skipped)
        rather than a wrong one."""
        mem = MemoryConnector()
        runner = LocalRunner({"memory": mem}, default_catalog="memory",
                             page_rows=1 << 8)
        dec = T.DecimalType(10, 2)
        # values 0.00 .. 40.95 stored as unscaled cents 0..4095
        mem.create_table(
            "d", ["x"], [dec], [(i,) for i in range(4096)],
        )
        rows = runner.execute(
            "select count(*) from d where x < 5"
        ).rows
        assert rows == [(500,)]  # 0.00..4.99 — nothing wrongly pruned
        plan = runner.plan("select count(*) from d where x < 5")
        assert _scan_constraints(plan) == {}  # mixed units: no pushdown

    def test_same_scale_decimal_literal_still_prunes(self):
        mem = MemoryConnector()
        runner = LocalRunner({"memory": mem}, default_catalog="memory",
                             page_rows=1 << 8)
        dec = T.DecimalType(10, 2)
        mem.create_table(
            "d2", ["x"], [dec], [(i,) for i in range(4096)],
        )
        # 5.00 parses as decimal(_, 2): same scale, prunable
        rows = runner.execute(
            "select count(*) from d2 where x < 5.00"
        ).rows
        assert rows == [(500,)]
        cons = _scan_constraints(
            runner.plan("select count(*) from d2 where x < 5.00")
        )
        assert cons.get("d2", {}).get("x") == (None, 499)
