"""Ring-3 distributed tests: the full engine on a virtual 8-device mesh,
checked for exact result parity with single-device execution.

Reference: presto-tests tests/DistributedQueryRunner.java — a real
coordinator + N workers in one JVM running the shared correctness suites.
Our analog: DistExecutor over an 8-device CPU mesh (conftest forces
xla_force_host_platform_device_count=8) vs the single-stream Executor on
identical generated data. Two configurations:

  - default thresholds: small-SF plans broadcast/gather (the realistic
    shape at this scale),
  - forced thresholds: every join partitions both sides and every
    group-by repartitions its partial states — exercising the
    lax.all_to_all repartition exchange end to end.
"""

import collections

import jax
import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.dist.executor import make_mesh
from presto_tpu.dist.fragmenter import add_exchanges
from presto_tpu.exec import plan as P
from presto_tpu.runner import LocalRunner, explain_text
from tests.tpch_queries import QUERIES

SF = 0.005


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(SF)


@pytest.fixture(scope="module")
def single(conn):
    return LocalRunner({"tpch": conn}, page_rows=1 << 13)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must force 8 cpu devices"
    return make_mesh(8)


@pytest.fixture(scope="module")
def dist(conn, mesh):
    return LocalRunner({"tpch": conn}, page_rows=1 << 13, mesh=mesh)


@pytest.fixture(scope="module")
def dist_repart(conn, mesh):
    """Thresholds forced low so joins partition and group-bys
    repartition — the all_to_all paths."""
    return LocalRunner(
        {"tpch": conn}, page_rows=1 << 13, mesh=mesh,
        dist_options=dict(broadcast_rows=64, gather_capacity=16),
    )


def _canon_row(row):
    # floats compare to 9 significant digits: single-stream and mesh
    # execution sum in different orders (and canonical page-shape
    # padding changes the reduction tree), so float aggregates agree
    # to ulps, not bit-exactly; everything else stays exact
    return tuple(
        f"{v:.9e}" if isinstance(v, float) else repr(v) for v in row
    )


def rows_equal(a, b):
    return collections.Counter(map(_canon_row, a)) == collections.Counter(
        map(_canon_row, b)
    )


# every query family: scan/agg (1, 6), joins (3, 5, 10), semi/anti (4,
# 21, 22), correlated decorrelation (2, 17, 20), outer joins (13)
DEFAULT_QUERIES = [1, 2, 3, 4, 5, 6, 10, 13, 17, 20, 21, 22]
REPART_QUERIES = [1, 3, 6, 10, 13]


@pytest.mark.parametrize("qnum", DEFAULT_QUERIES)
def test_dist_matches_single(qnum, single, dist):
    from tests.test_sql_tpch import ENGINE_SQL

    a = single.execute(ENGINE_SQL[qnum]).rows
    b = dist.execute(ENGINE_SQL[qnum]).rows
    assert rows_equal(a, b), (
        f"Q{qnum} dist != single\nsingle: {a[:3]}\ndist: {b[:3]}"
    )


@pytest.mark.parametrize("qnum", REPART_QUERIES)
def test_dist_repartition_matches_single(qnum, single, dist_repart):
    from tests.test_sql_tpch import ENGINE_SQL

    a = single.execute(ENGINE_SQL[qnum]).rows
    b = dist_repart.execute(ENGINE_SQL[qnum]).rows
    assert rows_equal(a, b), (
        f"Q{qnum} repart != single\nsingle: {a[:3]}\ndist: {b[:3]}"
    )


def test_fragmenter_inserts_expected_exchanges(dist_repart):
    from tests.test_sql_tpch import ENGINE_SQL

    txt = explain_text(dist_repart.plan(ENGINE_SQL[3]))
    assert "Exchange[repartition" in txt
    assert "Exchange[gather]" in txt
    assert "step=partial" in txt and "step=final" in txt


def test_fragmenter_broadcast_small_build(dist):
    # nation/region builds are far below the broadcast threshold
    txt = explain_text(dist.plan(QUERIES[5]))
    assert "Exchange[broadcast]" in txt


def test_exchange_noop_single_device(single, conn):
    """A fragmented plan executes correctly on the single-stream Executor
    too (exchanges degrade to pass-through)."""
    from tests.test_sql_tpch import ENGINE_SQL

    plan = single.plan(ENGINE_SQL[6])
    frag, _ = add_exchanges(plan, single.catalogs)
    names, rows = single.executor.execute(frag)
    base = single.execute(ENGINE_SQL[6]).rows
    assert rows_equal(rows, base)


ROUND2_QUERIES = [
    # variance family through partial/final state merge across shards
    "select l_returnflag, stddev(l_quantity), var_samp(l_extendedprice),"
    " count(*) from lineitem group by l_returnflag",
    # global variance (gather of moment sums)
    "select stddev_pop(o_totalprice), variance(o_totalprice) from orders",
    # MarkDistinct: mixed DISTINCT/plain and multiple distinct columns
    "select count(distinct n_regionkey), count(distinct n_name), "
    "count(*) from nation",
    "select o_orderpriority, count(distinct o_custkey), sum(o_totalprice)"
    " from orders group by o_orderpriority",
]


@pytest.mark.parametrize("qi", range(len(ROUND2_QUERIES)))
def test_dist_round2_aggregates(qi, single, dist, dist_repart):
    """Round-2 aggregate features must hold on the mesh in both exchange
    configurations (broadcast/gather and forced all_to_all)."""
    q = ROUND2_QUERIES[qi]
    want = single.execute(q).rows
    assert rows_equal(dist.execute(q).rows, want)
    assert rows_equal(dist_repart.execute(q).rows, want)
