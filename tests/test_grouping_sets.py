"""GROUPING SETS / ROLLUP / CUBE via the GroupId operator.

Reference: presto-main operator/GroupIdOperator.java + plan/GroupIdNode
(input replicated per set with absent keys nulled and a group-id
channel). Oracle: the equivalent UNION ALL of plain GROUP BY queries —
each independently validated against sqlite by the main suite — since
sqlite itself lacks GROUPING SETS.
"""

import collections

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runner import LocalRunner


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(0.01)


@pytest.fixture(scope="module")
def runner(conn):
    return LocalRunner({"tpch": conn}, page_rows=1 << 13)


def rows_eq(a, b):
    return collections.Counter(map(repr, a)) == collections.Counter(
        map(repr, b)
    )


def test_rollup(runner):
    got = runner.execute(
        "select n_regionkey, n_nationkey, count(*), sum(n_nationkey) "
        "from nation group by rollup(n_regionkey, n_nationkey)"
    ).rows
    want = runner.execute(
        "select n_regionkey, n_nationkey, count(*), sum(n_nationkey) "
        "from nation group by n_regionkey, n_nationkey "
        "union all select n_regionkey, null, count(*), sum(n_nationkey) "
        "from nation group by n_regionkey "
        "union all select null, null, count(*), sum(n_nationkey) "
        "from nation"
    ).rows
    assert len(got) == 31 and rows_eq(got, want)


def test_cube(runner):
    got = runner.execute(
        "select o_orderpriority, o_orderstatus, count(*) from orders "
        "group by cube(o_orderpriority, o_orderstatus)"
    ).rows
    want = runner.execute(
        "select o_orderpriority, o_orderstatus, count(*) from orders "
        "group by o_orderpriority, o_orderstatus "
        "union all select o_orderpriority, null, count(*) from orders "
        "group by o_orderpriority "
        "union all select null, o_orderstatus, count(*) from orders "
        "group by o_orderstatus "
        "union all select null, null, count(*) from orders"
    ).rows
    assert rows_eq(got, want)


def test_grouping_sets_explicit(runner):
    got = runner.execute(
        "select o_orderstatus, o_orderpriority, count(*) from orders "
        "group by grouping sets ((o_orderstatus), (o_orderpriority), ())"
    ).rows
    want = runner.execute(
        "select o_orderstatus, null, count(*) from orders "
        "group by o_orderstatus "
        "union all select null, o_orderpriority, count(*) from orders "
        "group by o_orderpriority "
        "union all select null, null, count(*) from orders"
    ).rows
    assert rows_eq(got, want)


def test_rollup_distinguishes_real_nulls_by_gid(runner):
    """A real NULL key value and a rolled-up NULL must stay separate
    rows (the gid channel keeps them apart)."""
    from presto_tpu import types as T
    from presto_tpu.connectors.memory import MemoryConnector

    mem = MemoryConnector()
    mem.create_table(
        "t", ["k", "v"], [T.BIGINT, T.BIGINT],
        [(1, 10), (1, 20), (None, 5), (None, 7)],
    )
    r2 = LocalRunner({"memory": mem}, default_catalog="memory")
    got = r2.execute(
        "select k, count(*), sum(v) from t group by rollup(k)"
    ).rows
    # groups: k=1 (2 rows), k=NULL (2 rows), total (4 rows)
    assert collections.Counter(got) == collections.Counter(
        [(1, 2, 30), (None, 2, 12), (None, 4, 42)]
    )


def test_rollup_distributed_matches_single(conn, runner):
    import jax

    from presto_tpu.dist.executor import make_mesh

    assert len(jax.devices()) >= 8
    dist = LocalRunner(
        {"tpch": conn}, page_rows=1 << 13, mesh=make_mesh(8),
        dist_options=dict(broadcast_rows=64, gather_capacity=16),
    )
    q = ("select o_orderpriority, o_orderstatus, count(*), "
         "sum(o_totalprice) from orders "
         "group by rollup(o_orderpriority, o_orderstatus)")
    assert rows_eq(runner.execute(q).rows, dist.execute(q).rows)


def test_rollup_with_spill(conn, runner):
    sp = LocalRunner({"tpch": conn}, page_rows=1 << 13)
    sp.session.set("spill_threshold_bytes", 1 << 15)
    q = ("select o_custkey, count(*) from orders "
         "group by rollup(o_custkey) order by 2 desc, 1 limit 5")
    assert rows_eq(sp.execute(q).rows, runner.execute(q).rows)
    assert sp.executor.spill_partitions_used > 1


def test_distinct_aggs_with_grouping_sets_rejected(runner):
    from presto_tpu.sql.planner import PlanningError

    with pytest.raises(PlanningError):
        runner.execute(
            "select count(distinct o_custkey) from orders "
            "group by rollup(o_orderstatus)"
        )
