"""Hashing oracle tests: xxhash64 bit-exactness vs a pure-Python reference,
combiner semantics, checksum order-insensitivity (reference analog:
io.airlift.slice XxHash64 tests, presto-verifier checksum behavior)."""

import jax.numpy as jnp
import numpy as np

from presto_tpu.ops import hashing as H

MASK = (1 << 64) - 1


def _rotl(x, r):
    return ((x << r) | (x >> (64 - r))) & MASK


def xxhash64_py(value: int, seed: int = 0) -> int:
    """Pure-python xxhash64 of one 8-byte LE value (the reference's
    XxHash64.hash(long))."""
    P1 = 0x9E3779B185EBCA87
    P2 = 0xC2B2AE3D27D4EB4F
    P3 = 0x165667B19E3779F9
    P4 = 0x85EBCA77C2B2AE63
    P5 = 0x27D4EB2F165667C5
    v = value & MASK
    acc = (seed + P5 + 8) & MASK
    k1 = (v * P2) & MASK
    k1 = _rotl(k1, 31)
    k1 = (k1 * P1) & MASK
    acc ^= k1
    acc = (_rotl(acc, 27) * P1 + P4) & MASK
    acc ^= acc >> 33
    acc = (acc * P2) & MASK
    acc ^= acc >> 29
    acc = (acc * P3) & MASK
    acc ^= acc >> 32
    return acc


def test_xxhash64_matches_python_oracle(rng):
    vals = np.concatenate(
        [
            np.array([0, 1, -1, 2**63 - 1, -(2**63)], dtype=np.int64),
            rng.integers(-(2**62), 2**62, size=100, dtype=np.int64),
        ]
    )
    got = np.asarray(H.xxhash64_u64(jnp.asarray(vals)))
    for v, g in zip(vals, got):
        assert int(g) == xxhash64_py(int(v) & MASK), hex(int(v))


def test_combine_hash_is_31h_plus_x():
    h = H.combine_hash(jnp.uint64(7), jnp.uint64(5))
    assert int(h) == 7 * 31 + 5


def test_hash_columns_null_is_zero():
    col = jnp.asarray([3, 4], dtype=jnp.int64).astype(jnp.uint64)
    nulls = jnp.asarray([False, True])
    h = np.asarray(H.hash_columns([col], [nulls]))
    assert int(h[1]) == 0  # 31*0 + 0
    assert int(h[0]) == xxhash64_py(3)


def test_checksum_order_insensitive(rng):
    vals = rng.integers(0, 2**63, size=64, dtype=np.uint64)
    valid = rng.random(64) < 0.7
    c1 = H.checksum(jnp.asarray(vals), jnp.asarray(valid))
    sh = rng.permutation(64)
    c2 = H.checksum(jnp.asarray(vals[sh]), jnp.asarray(valid[sh]))
    assert int(c1) == int(c2)
    # flipping one row changes the checksum
    valid2 = valid.copy()
    valid2[np.argmax(valid)] = False
    c3 = H.checksum(jnp.asarray(vals), jnp.asarray(valid2))
    assert int(c1) != int(c3)
