"""Coordinator protocol tests: /v1/statement paging, session headers,
DDL via the wire, error surfaces, cancel, CLI client round trip.

Reference test analog: TestingPrestoServer + client protocol tests
(presto-main server/testing, presto-client)."""

import json
import urllib.request

import pytest

from presto_tpu.client import StatementClient
from presto_tpu.connectors.blackhole import BlackholeConnector
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.server import PrestoTpuServer


@pytest.fixture(scope="module")
def server():
    srv = PrestoTpuServer(
        {
            "tpch": TpchConnector(scale=0.001),
            "memory": MemoryConnector(),
            "blackhole": BlackholeConnector(),
        },
        port=0,  # ephemeral
        page_rows=1 << 12,
    )
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def client(server):
    return StatementClient(server=f"http://127.0.0.1:{server.port}")


def test_simple_query(client):
    res = client.execute("select 1 + 1 as two")
    assert res.error is None
    assert [c["name"] for c in res.columns] == ["two"]
    assert res.rows == [[2]]
    assert res.state == "FINISHED"


def test_scan_aggregate(client):
    res = client.execute(
        "select count(*), sum(n_nationkey) from nation"
    )
    assert res.error is None
    assert res.rows == [[25, 300]]
    assert res.columns[0]["type"] == "bigint"


def test_paged_results(client):
    # more rows than one protocol page (4096) forces nextUri paging
    res = client.execute(
        "select l_orderkey from lineitem"
    )
    assert res.error is None
    assert len(res.rows) > 4096


def test_ddl_roundtrip(client):
    res = client.execute(
        "create table memory.n2 as select n_name, n_regionkey from nation"
    )
    assert res.update_type == "CREATE TABLE AS"
    res = client.execute(
        "select count(*) from memory.n2"
    )
    assert res.rows == [[25]]
    res = client.execute("show tables from memory")
    assert ["n2"] in res.rows
    client.execute("drop table memory.n2")
    res = client.execute("show tables from memory")
    assert ["n2"] not in res.rows


def test_set_session_roundtrip(client):
    res = client.execute("set session tpu_offload_enabled = false")
    assert res.update_type == "SET SESSION"
    # client carries the property forward (X-Presto-Set-Session echo)
    assert client.session_properties["tpu_offload_enabled"] == "false"
    res = client.execute("select count(*) from region")
    assert res.rows == [[5]]
    client.execute("set session tpu_offload_enabled = true")
    assert client.session_properties["tpu_offload_enabled"] == "true"


def test_show_session(client):
    res = client.execute("show session")
    names = [r[0] for r in res.rows]
    assert "tpu_offload_enabled" in names
    assert "join_distribution_type" in names


def test_session_catalog(server):
    """X-Presto-Catalog steers unqualified names and write targets."""
    c = StatementClient(
        server=f"http://127.0.0.1:{server.port}", catalog="memory"
    )
    res = c.execute("create table t3 as select 42 as x")
    assert res.error is None, res.error
    assert res.update_type == "CREATE TABLE AS"
    res = c.execute("select x from t3")
    assert res.rows == [[42]]
    res = c.execute("show tables")
    assert ["t3"] in res.rows
    c.execute("drop table t3")


def test_error_surface(client):
    res = client.execute("select bogus_column from nation")
    assert res.error is not None
    assert res.state == "FAILED"
    assert "bogus_column" in res.error["message"]


def test_syntax_error(client):
    res = client.execute("selec 1")
    assert res.error is not None


def test_info_endpoints(server, client):
    base = f"http://127.0.0.1:{server.port}"
    with urllib.request.urlopen(f"{base}/v1/info") as r:
        info = json.loads(r.read())
    assert info["coordinator"] is True
    res = client.execute("select 1 as x")
    with urllib.request.urlopen(
        f"{base}/v1/query/{res.query_id}"
    ) as r:
        qinfo = json.loads(r.read())
    assert qinfo["state"] == "FINISHED"
    assert qinfo["rowCount"] == 1


def test_cli_execute(server, capsys):
    from presto_tpu.cli import main

    rc = main([
        "--server", f"http://127.0.0.1:{server.port}",
        "--execute", "select r_name from region order by r_name limit 2",
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "r_name" in out and "(2 rows)" in out


def test_metrics_endpoint(server, client):
    # run one query so counters are non-zero, then scrape
    client.execute("select 1")
    with urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/metrics"
    ) as resp:
        assert resp.status == 200
        assert "text/plain" in resp.headers["Content-Type"]
        body = resp.read().decode()
    assert "presto_tpu_uptime_seconds" in body
    assert 'presto_tpu_queries_total{state="FINISHED"}' in body
    assert "presto_tpu_rows_returned_total" in body


def test_event_listener_spi():
    """Reference: spi/eventlistener — created/completed events fire with
    final state; a throwing listener never fails the query."""
    from presto_tpu.events import EventListener

    seen = {"created": [], "completed": []}

    class Recorder(EventListener):
        def query_created(self, e):
            seen["created"].append(e)

        def query_completed(self, e):
            seen["completed"].append(e)

    class Thrower(EventListener):
        def query_created(self, e):
            raise RuntimeError("listener bug")

    srv = PrestoTpuServer(
        {"tpch": TpchConnector(scale=0.001)}, port=0,
        event_listeners=[Thrower(), Recorder()],
    )
    srv.start()
    try:
        c = StatementClient(server=f"http://127.0.0.1:{srv.port}")
        res = c.execute("select count(*) from nation")
        assert res.error is None
        bad = c.execute("select nope from nowhere")
        assert bad.error is not None
    finally:
        srv.stop()
    assert len(seen["created"]) == 2
    states = sorted(e.state for e in seen["completed"])
    assert states == ["FAILED", "FINISHED"]
    done = [e for e in seen["completed"] if e.state == "FINISHED"][0]
    assert done.row_count == 1 and done.wall_ms >= 0
    failed = [e for e in seen["completed"] if e.state == "FAILED"][0]
    assert failed.error_name


def test_heartbeat_failure_detector():
    """Reference: failureDetector/HeartbeatFailureDetector — a peer goes
    FAILED after consecutive missed pings and recovers on success."""
    from presto_tpu.server.heartbeat import HeartbeatFailureDetector

    peer = PrestoTpuServer({"tpch": TpchConnector(scale=0.001)}, port=0)
    peer.start()
    uri = f"http://127.0.0.1:{peer.port}"
    det = HeartbeatFailureDetector([uri], fail_after=2, timeout_s=0.5)
    det.check_once()
    assert det.is_alive(uri)
    assert det.snapshot()[0]["state"] == "ALIVE"
    peer.stop()
    det.check_once()
    assert det.is_alive(uri)  # one miss is not failure
    det.check_once()
    assert not det.is_alive(uri)
    assert det.snapshot()[0]["state"] == "FAILED"
    # node comes back: first success revives it (reference: rejoin
    # between queries)
    peer2 = PrestoTpuServer(
        {"tpch": TpchConnector(scale=0.001)}, port=peer.port
    )
    try:
        peer2.start()
        det.check_once()
        assert det.is_alive(uri)
    finally:
        peer2.stop()


def test_monitored_server_exposes_node_view():
    peer = PrestoTpuServer({"tpch": TpchConnector(scale=0.001)}, port=0)
    peer.start()
    mon = PrestoTpuServer(
        {"tpch": TpchConnector(scale=0.001)}, port=0,
        peer_uris=[f"http://127.0.0.1:{peer.port}"],
    )
    mon.start()
    try:
        mon.failure_detector.check_once()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{mon.port}/v1/node"
        ) as resp:
            nodes = json.loads(resp.read())
        assert len(nodes) == 1 and nodes[0]["state"] == "ALIVE"
    finally:
        mon.stop()
        peer.stop()


def test_resource_group_admission():
    """Reference: resourceGroups/* — queue-full rejection (429 /
    QUERY_QUEUE_FULL) and per-group running/queued accounting."""
    import json as _json
    import threading
    import urllib.error

    from presto_tpu.server.resource_groups import (
        ResourceGroupManager,
        ResourceGroupSpec,
    )

    rg = ResourceGroupManager([
        ResourceGroupSpec("tiny", ".*", hard_concurrency=1, max_queued=1),
    ])
    srv = PrestoTpuServer(
        {"tpch": TpchConnector(scale=0.001)}, port=0, resource_groups=rg,
    )
    srv.start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        # hold the device with a slowish query, then flood the queue
        slow_sql = ("select count(*) from lineitem l1, lineitem l2 "
                    "where l1.l_orderkey = l2.l_orderkey")
        results = []

        def run_slow():
            c = StatementClient(server=base)
            results.append(c.execute(slow_sql))

        threads = [threading.Thread(target=run_slow) for _ in range(3)]
        for t in threads:
            t.start()
        # with concurrency 1 + queue 1, at least one of three concurrent
        # submissions must be rejected with 429
        rejected = 0
        for t in threads:
            t.join()
        rejected = sum(
            1 for r in results
            if r.error and r.error.get("errorName") == "QUERY_QUEUE_FULL"
        )
        finished = sum(1 for r in results if r.error is None)
        assert finished >= 1 and rejected >= 1, [
            (r.state, r.error) for r in results
        ]
        with urllib.request.urlopen(base + "/v1/resourceGroup") as resp:
            snap = _json.loads(resp.read())
        assert snap[0]["name"] == "tiny"
        assert snap[0]["running"] == 0 and snap[0]["queued"] == 0
    finally:
        srv.stop()


# ------------------------------------------- concurrent query execution

def test_concurrent_queries_under_memory_budget():
    """With a memory budget configured, the global device lock is
    replaced by footprint admission (reference: ClusterMemoryManager):
    queries run CONCURRENTLY (overlapping RUNNING intervals), small
    queries interleave, and aggregate wall-clock beats strictly serial
    execution of the same workload."""
    import threading
    import time as _time

    queries = [
        "select count(*), sum(o_totalprice) from orders",
        "select o_orderpriority, count(*) from orders "
        "group by o_orderpriority",
        "select count(*) from lineitem where l_quantity < 25",
    ]

    def run_all(srv, concurrent):
        base = f"http://127.0.0.1:{srv.port}"
        results = [None] * len(queries)

        def one(i):
            c = StatementClient(server=base)
            results[i] = c.execute(queries[i]).rows

        t0 = _time.time()
        if concurrent:
            ts = [threading.Thread(target=one, args=(i,))
                  for i in range(len(queries))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        else:
            for i in range(len(queries)):
                one(i)
        return _time.time() - t0, results

    conn = TpchConnector(0.01)
    serial_srv = PrestoTpuServer({"tpch": conn}, port=0,
                                 page_rows=1 << 13)
    serial_srv.start()
    try:
        # warm compile caches through the serial server; best-of-3
        # timing (totals are tens of ms — single samples flake under
        # CI machine load)
        run_all(serial_srv, concurrent=False)
        serial_samples = []
        for _ in range(3):
            s, serial_rows = run_all(serial_srv, concurrent=False)
            serial_samples.append(s)
        serial_s = min(serial_samples)
    finally:
        serial_srv.stop()

    events = []

    class _Spy:
        def query_created(self, e):
            events.append(("start", e.query_id, _time.time()))

        def query_completed(self, e):
            events.append(("end", e.query_id, _time.time()))

    conc_srv = PrestoTpuServer(
        {"tpch": conn}, port=0, page_rows=1 << 13,
        memory_budget_bytes=1 << 32, event_listeners=[_Spy()],
    )
    conc_srv.start()
    try:
        run_all(conc_srv, concurrent=True)  # warm per-query runners
        events.clear()
        conc_samples = []
        for _ in range(3):
            s, conc_rows = run_all(conc_srv, concurrent=True)
            conc_samples.append(s)
        conc_s = min(conc_samples)
    finally:
        conc_srv.stop()

    assert conc_rows == serial_rows, "concurrent results diverged"
    # overlap evidence: some query started before another finished —
    # the functional claim (the device lock is gone)
    starts = sorted(t for k, _, t in events if k == "start")
    ends = sorted(t for k, _, t in events if k == "end")
    assert starts[1] < ends[0], "queries never overlapped"
    # wall-clock: CI has ONE cpu core, so concurrency cannot beat
    # serial on cpu-jax — the aggregate win needs a real accelerator
    # whose kernels overlap host work. Here we bound the overhead of
    # concurrent admission instead: not pathologically serialized.
    assert conc_s < serial_s * 1.5, (
        f"concurrent {conc_s:.2f}s much slower than serial "
        f"{serial_s:.2f}s"
    )


def test_memory_arbiter_serializes_oversized():
    """A query whose estimate exceeds the budget runs only when alone
    (progress guarantee), so results stay correct under a tiny
    budget."""
    conn = TpchConnector(0.01)
    srv = PrestoTpuServer(
        {"tpch": conn}, port=0, page_rows=1 << 13,
        memory_budget_bytes=1 << 16,  # far below any query's estimate
    )
    srv.start()
    try:
        c = StatementClient(server=f"http://127.0.0.1:{srv.port}")
        rows = c.execute(
            "select count(*) from orders, lineitem "
            "where o_orderkey = l_orderkey"
        ).rows
        assert rows[0][0] > 0
    finally:
        srv.stop()
