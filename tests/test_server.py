"""Coordinator protocol tests: /v1/statement paging, session headers,
DDL via the wire, error surfaces, cancel, CLI client round trip.

Reference test analog: TestingPrestoServer + client protocol tests
(presto-main server/testing, presto-client)."""

import json
import urllib.request

import pytest

from presto_tpu.client import StatementClient
from presto_tpu.connectors.blackhole import BlackholeConnector
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.server import PrestoTpuServer


@pytest.fixture(scope="module")
def server():
    srv = PrestoTpuServer(
        {
            "tpch": TpchConnector(scale=0.001),
            "memory": MemoryConnector(),
            "blackhole": BlackholeConnector(),
        },
        port=0,  # ephemeral
        page_rows=1 << 12,
    )
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def client(server):
    return StatementClient(server=f"http://127.0.0.1:{server.port}")


def test_simple_query(client):
    res = client.execute("select 1 + 1 as two")
    assert res.error is None
    assert [c["name"] for c in res.columns] == ["two"]
    assert res.rows == [[2]]
    assert res.state == "FINISHED"


def test_scan_aggregate(client):
    res = client.execute(
        "select count(*), sum(n_nationkey) from nation"
    )
    assert res.error is None
    assert res.rows == [[25, 300]]
    assert res.columns[0]["type"] == "bigint"


def test_paged_results(client):
    # more rows than one protocol page (4096) forces nextUri paging
    res = client.execute(
        "select l_orderkey from lineitem"
    )
    assert res.error is None
    assert len(res.rows) > 4096


def test_ddl_roundtrip(client):
    res = client.execute(
        "create table memory.n2 as select n_name, n_regionkey from nation"
    )
    assert res.update_type == "CREATE TABLE AS"
    res = client.execute(
        "select count(*) from memory.n2"
    )
    assert res.rows == [[25]]
    res = client.execute("show tables from memory")
    assert ["n2"] in res.rows
    client.execute("drop table memory.n2")
    res = client.execute("show tables from memory")
    assert ["n2"] not in res.rows


def test_set_session_roundtrip(client):
    res = client.execute("set session tpu_offload_enabled = false")
    assert res.update_type == "SET SESSION"
    # client carries the property forward (X-Presto-Set-Session echo)
    assert client.session_properties["tpu_offload_enabled"] == "false"
    res = client.execute("select count(*) from region")
    assert res.rows == [[5]]
    client.execute("set session tpu_offload_enabled = true")
    assert client.session_properties["tpu_offload_enabled"] == "true"


def test_show_session(client):
    res = client.execute("show session")
    names = [r[0] for r in res.rows]
    assert "tpu_offload_enabled" in names
    assert "join_distribution_type" in names


def test_session_catalog(server):
    """X-Presto-Catalog steers unqualified names and write targets."""
    c = StatementClient(
        server=f"http://127.0.0.1:{server.port}", catalog="memory"
    )
    res = c.execute("create table t3 as select 42 as x")
    assert res.error is None, res.error
    assert res.update_type == "CREATE TABLE AS"
    res = c.execute("select x from t3")
    assert res.rows == [[42]]
    res = c.execute("show tables")
    assert ["t3"] in res.rows
    c.execute("drop table t3")


def test_error_surface(client):
    res = client.execute("select bogus_column from nation")
    assert res.error is not None
    assert res.state == "FAILED"
    assert "bogus_column" in res.error["message"]


def test_syntax_error(client):
    res = client.execute("selec 1")
    assert res.error is not None


def test_info_endpoints(server, client):
    base = f"http://127.0.0.1:{server.port}"
    with urllib.request.urlopen(f"{base}/v1/info") as r:
        info = json.loads(r.read())
    assert info["coordinator"] is True
    res = client.execute("select 1 as x")
    with urllib.request.urlopen(
        f"{base}/v1/query/{res.query_id}"
    ) as r:
        qinfo = json.loads(r.read())
    assert qinfo["state"] == "FINISHED"
    assert qinfo["rowCount"] == 1


def test_cli_execute(server, capsys):
    from presto_tpu.cli import main

    rc = main([
        "--server", f"http://127.0.0.1:{server.port}",
        "--execute", "select r_name from region order by r_name limit 2",
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "r_name" in out and "(2 rows)" in out
