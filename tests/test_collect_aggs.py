"""array_agg / map_agg / approx_percentile — bounded collect-state
aggregates (ops/collect.py + executor collect branches).

Reference: presto-main operator/aggregation/ArrayAggregationFunction,
MapAggregationFunction, ApproximatePercentileAggregations. Engine
notes: per-group slots bounded by the array_agg_max_elements session
property (overflow lands on the boosted-retry ladder); percentiles are
EXACT within the bound (stronger than the reference's qdigest);
collect results decode at the client and cannot feed further device
expressions.
"""

import pytest

from presto_tpu import types as T
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.runner import LocalRunner


@pytest.fixture(scope="module")
def runner():
    conn = MemoryConnector()
    conn.create_table(
        "t", ["g", "x", "s", "d"],
        [T.BIGINT, T.BIGINT, T.VARCHAR, T.DOUBLE],
        [(1, 10, "a", 1.5), (1, 20, "b", 2.5), (2, 30, "c", 3.5),
         (2, None, "d", 4.5), (1, 40, "a", 0.5), (3, None, None, None)],
    )
    conn.create_table(
        "big", ["g", "x"], [T.BIGINT, T.BIGINT],
        [(i % 3, i) for i in range(40)],
    )
    # page_rows 16 forces multi-page partial->merge->final folding
    return LocalRunner({"mem": conn}, default_catalog="mem",
                       page_rows=1 << 4)


def q(runner, sql):
    return sorted(runner.execute(sql).rows)


def test_array_agg_grouped(runner):
    # null ELEMENTS are included (reference: "Null elements are
    # included in the aggregation")
    assert q(runner, "select g, array_agg(x) from t group by g") == [
        (1, (10, 20, 40)), (2, (30, None)), (3, (None,))]


def test_array_agg_global(runner):
    assert q(runner, "select array_agg(x) from t") == [
        ((10, 20, 30, None, 40, None),)]


def test_array_agg_strings_and_doubles(runner):
    assert q(runner, "select g, array_agg(s) from t group by g") == [
        (1, ("a", "b", "a")), (2, ("c", "d")), (3, (None,))]
    assert q(runner, "select array_agg(d) from t where g = 1") == [
        ((1.5, 2.5, 0.5),)]
    # float slot-encoding round-trips exactly, negatives included
    assert q(runner,
             "select array_agg(d * -3.25) from t where g = 2") == [
        ((-11.375, -14.625),)]


def test_array_agg_distinct(runner):
    rows = q(runner, "select array_agg(distinct s) from t where g = 1")
    assert sorted(rows[0][0]) == ["a", "b"]


def test_array_agg_multipage_fold(runner):
    rows = q(runner, "select g, array_agg(x) from big group by g")
    assert rows == [
        (0, tuple(range(0, 40, 3))),
        (1, tuple(range(1, 40, 3))),
        (2, tuple(range(2, 40, 3))),
    ]


def test_map_agg(runner):
    rows = q(runner, "select g, map_agg(s, x) from t "
                     "where s is not null and x is not null group by g")
    assert rows == [(1, (("a", 10), ("b", 20), ("a", 40))),
                    (2, (("c", 30),))]


def test_map_agg_null_semantics(runner):
    # null KEYS skipped; null VALUES preserved (reference semantics)
    rows = q(runner, "select g, map_agg(s, x) from t group by g")
    assert rows == [
        (1, (("a", 10), ("b", 20), ("a", 40))),
        (2, (("c", 30), ("d", None))),
        (3, None),  # zero non-null keys -> NULL (empty aggregate)
    ]


def test_approx_percentile(runner):
    assert q(runner, "select g, approx_percentile(x, 0.5) "
                     "from t group by g") == [
        (1, 20), (2, 30), (3, None)]
    assert q(runner, "select approx_percentile(x, 0.99) from t") == [
        (40,)]
    assert q(runner, "select approx_percentile(d, 0.5) from t") == [
        (2.5,)]


def test_collect_k_overflow_retries(runner):
    # a group larger than the slot bound rides the boosted-retry
    # ladder: K scales with the capacity boost until it fits
    runner.execute("set session array_agg_max_elements = 4")
    try:
        rows = q(runner, "select g, array_agg(x) from big group by g")
        assert rows[0] == (0, tuple(range(0, 40, 3)))
        assert runner.executor._capacity_boost > 1
    finally:
        runner.execute("set session array_agg_max_elements = 1024")
