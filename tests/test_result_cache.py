"""ISSUE 10: the two-level result cache (presto_tpu/cache/).

Covers the subsystem contract by contract:
  - the acceptance pin: a second identical cacheable execution
    completes with result_cache_hits >= 1 and program_launches == 0
    (fragment replay skips compile+launch);
  - hit/miss/evict/TTL counter contracts at the executor and store
    levels (demotion to the disk tier still serves hits);
  - sqlite-oracle parity on cache hits;
  - snapshot invalidation: DML to the writable memory connector bumps
    snapshot_version() and forces a miss with correct fresh rows —
    including the UPDATE case where the ROW COUNT does not change
    (the write counter, not cardinality, moves the token);
  - cacheability rules (system scans, volatile calls, remote sources,
    snapshot-less connectors never cache);
  - the process-shared store under concurrency: the same statement
    from 8 client threads executes at least once, the rest hit, all
    rows identical;
  - the CachingConnector key fix: canonical structural constraint
    encoding + snapshot versioning + the invalidation registration.
"""

import collections
import re
import threading
import time

import pytest

from presto_tpu import types as T
from presto_tpu.cache import (
    ResultCache,
    shared_cache_if_exists,
    uncacheable_reason,
)
from presto_tpu.connectors.cached import CachingConnector
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec import plan as P
from presto_tpu.expr.ir import Call
from presto_tpu.runner import LocalRunner

SF = 0.01
PAGE_ROWS = 1 << 13

AGG_Q = ("select l_returnflag, l_linestatus, count(*), "
         "sum(l_quantity), sum(l_extendedprice) from lineitem "
         "group by l_returnflag, l_linestatus "
         "order by l_returnflag, l_linestatus")
JOIN_Q = ("select o_orderpriority, count(*) c from orders join "
          "lineitem on o_orderkey = l_orderkey where l_quantity < 10 "
          "group by o_orderpriority order by o_orderpriority")


def _rows_equal(a, b):
    return collections.Counter(map(repr, a)) == collections.Counter(
        map(repr, b))


@pytest.fixture(autouse=True)
def _clean_shared_cache():
    """The store is process-shared by design; tests must not leak
    entries (or tallies another test asserts deltas over) into each
    other through it."""
    rc = shared_cache_if_exists()
    if rc is not None:
        rc.clear()
    yield
    rc = shared_cache_if_exists()
    if rc is not None:
        rc.clear()


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(SF)


@pytest.fixture()
def runner(conn):
    return LocalRunner({"tpch": conn}, page_rows=PAGE_ROWS)


# ----------------------------------------------------- acceptance pin
def test_second_run_hits_and_launches_zero(runner):
    """THE acceptance contract: identical cacheable plan, second
    execution serves from the fragment cache — >=1 hit, ZERO program
    launches (compile+launch skipped), identical rows."""
    ex = runner.executor
    ex.result_cache = ResultCache()
    plan = runner.plan(AGG_Q)
    _n1, rows1 = ex.execute(plan)
    assert ex.result_cache_misses >= 1
    assert ex.result_cache_hits == 0
    _n2, rows2 = ex.execute(plan)
    assert ex.result_cache_hits >= 1
    assert ex.program_launches == 0, (
        "a cache hit must not launch fused-scan programs")
    assert rows1 == rows2


def test_replan_same_sql_still_hits(runner):
    """A fresh plan object of the same SQL lands on the same key (the
    fingerprint is structural, not identity) — the dashboard repeat
    case where every request re-plans."""
    ex = runner.executor
    ex.result_cache = ResultCache()
    _1, rows1 = ex.execute(runner.plan(AGG_Q))
    _2, rows2 = ex.execute(runner.plan(AGG_Q))
    assert ex.result_cache_hits >= 1
    assert rows1 == rows2


def test_statement_cache_skips_executor(runner):
    """Level 2: the runner returns the finished row set for an
    identical statement without executing; per-attempt gauges read 0
    for the replayed query."""
    runner.session.set("result_cache_enabled", True)
    res1 = runner.execute(AGG_Q)
    ex = runner.executor
    hits_before = ex.result_cache_hits
    res2 = runner.execute(AGG_Q)
    assert ex.result_cache_hits > hits_before
    assert ex.program_launches == 0
    assert res1.rows == res2.rows
    assert res1.column_names == res2.column_names
    assert res1.column_types == res2.column_types


def test_statement_cache_hit_zero_transfers(runner):
    """ISSUE 12 acceptance pin: a statement-cache hit crosses the
    host<->device boundary ZERO times — no page replay, no decode
    pull; the transfer gauges read 0 for the replayed query."""
    from presto_tpu.exec import xfer as XFER

    runner.session.set("result_cache_enabled", True)
    runner.execute(AGG_Q)
    ex = runner.executor
    hits_before = ex.result_cache_hits
    base = XFER.process_totals()
    runner.execute(AGG_Q)
    assert ex.result_cache_hits > hits_before
    assert ex.d2h_bytes == 0 and ex.h2d_bytes == 0, (
        "a replayed statement must not touch the device")
    assert ex.d2h_transfers == 0 and ex.h2d_transfers == 0
    assert ex.transfer_wall_s == 0
    # the per-query gauges are RESET on the hit path, so the
    # falsifiable half of the pin is the process totals: nothing
    # anywhere in the process crossed during the replay
    after = XFER.process_totals()
    assert after["h2d_bytes"] == base["h2d_bytes"]
    assert after["d2h_bytes"] == base["d2h_bytes"]
    assert after["d2h_transfers"] == base["d2h_transfers"]
    assert after["h2d_transfers"] == base["h2d_transfers"]


def test_fragment_hit_serves_host_pages_zero_transfers(runner):
    """The first redundant crossing the transfer auditor surfaced
    (ISSUE 12 satellite): a fragment-cache hit whose pages feed only
    result serialization used to device_put every stored host page
    and pull it straight back at decode. The host-serve sink now
    replays host pages directly — a full-plan hit executes with zero
    crossings either way."""
    from presto_tpu.exec import xfer as XFER

    ex = runner.executor
    ex.result_cache = ResultCache()
    plan = runner.plan(AGG_Q)
    _n1, rows1 = ex.execute(plan)
    assert ex.result_cache_misses >= 1
    base = XFER.process_totals()
    _n2, rows2 = ex.execute(plan)
    assert ex.result_cache_hits >= 1
    assert rows1 == rows2
    assert ex.h2d_bytes == 0 and ex.d2h_bytes == 0, (
        "a host-served fragment replay must not round-trip the device")
    # and nothing leaked around the per-query gauges: the process
    # totals did not move either
    after = XFER.process_totals()
    assert after["h2d_bytes"] == base["h2d_bytes"]
    assert after["d2h_bytes"] == base["d2h_bytes"]


# ------------------------------------------------- counter contracts
def test_hit_miss_counters_explain_analyze(runner):
    """The four registry counters surface through execute_with_stats
    (and therefore EXPLAIN ANALYZE, /metrics, system.metrics — the
    exec/counters.py contract)."""
    ex = runner.executor
    ex.result_cache = ResultCache()
    plan = runner.plan(AGG_Q)
    _n, _r, stats = ex.execute_with_stats(plan)
    ctr = stats["counters"]
    for name in ("result_cache_hits", "result_cache_misses",
                 "result_cache_evictions",
                 "result_cache_invalidations"):
        assert name in ctr, name
    assert ctr["result_cache_misses"] >= 1
    _n, _r, stats = ex.execute_with_stats(plan)
    assert stats["counters"]["result_cache_hits"] >= 1


def test_store_eviction_under_budget():
    """LRU eviction: rows entries past the resident budget evict
    oldest-first and are counted."""
    rc = ResultCache(budget_bytes=1 << 14)
    big = [("x" * 64, i) for i in range(20)]
    ev = 0
    for i in range(8):
        ev += rc.put_rows(f"k{i}", ["a", "b"], big, ["varchar", "bigint"],
                          {("m", "t")})
    assert ev > 0
    assert rc.evictions == ev
    assert rc.resident_bytes() <= 1 << 14
    # oldest keys evicted, newest still present
    assert rc.get_rows("k7") is not None
    assert rc.get_rows("k0") is None


def test_pages_demote_to_disk_still_hit(runner):
    """Host budget pressure demotes LRU page entries to the disk-tier
    PageStore; a demoted entry still serves hits (loaded back under
    the store lock)."""
    ex = runner.executor
    ex.result_cache = ResultCache()
    p1 = runner.plan(AGG_Q)
    p2 = runner.plan(JOIN_Q)
    ex.execute(p1)
    ex.execute(p2)
    rc = ex.result_cache
    assert rc.entry_count >= 2
    total = rc.total_bytes()
    # shrink the budget below the resident set: page entries demote
    # (not evict — total stays), resident drops under the new budget
    rc.configure(budget_bytes=max(total // 2, 1024))
    assert rc.resident_bytes() <= rc.budget_bytes
    assert rc.total_bytes() == total
    _n, rows1 = ex.execute(p1)
    assert ex.result_cache_hits >= 1
    # the demoted replay is still exact
    base = LocalRunner({"tpch": runner.catalogs["tpch"]},
                       page_rows=PAGE_ROWS)
    assert _rows_equal(rows1, base.execute(AGG_Q).rows)


def test_oversized_entry_never_admitted(runner):
    ex = runner.executor
    ex.result_cache = ResultCache(budget_bytes=64)  # smaller than any
    ex.execute(runner.plan(AGG_Q))                  # result set
    assert ex.result_cache.entry_count == 0
    # and the run is simply a miss, not an error
    assert ex.result_cache_misses >= 1


def test_ttl_expiry(runner):
    """An entry older than result_cache_ttl_ms reads as a miss and is
    reclaimed (counted as an eviction — age-based reclaim)."""
    ex = runner.executor
    ex.result_cache = ResultCache(ttl_ms=80)
    plan = runner.plan(AGG_Q)
    ex.execute(plan)
    ex.execute(plan)
    assert ex.result_cache_hits == 1  # inside the TTL window: hit
    time.sleep(0.12)
    ex.execute(plan)
    assert ex.result_cache_hits == 1  # aged out: no new hit
    assert ex.result_cache_misses >= 2
    assert ex.result_cache.evictions >= 1


# ------------------------------------------------------ oracle parity
def test_oracle_parity_on_hits(runner, conn):
    """BASELINE.md's correctness gate applied to REPLAYED results: the
    hit rows match sqlite over the same generated data."""
    from tests.oracle import load_sqlite

    ex = runner.executor
    ex.result_cache = ResultCache()
    plan = runner.plan(JOIN_Q)
    ex.execute(plan)
    _n, got = ex.execute(plan)   # served from cache
    assert ex.result_cache_hits >= 1
    db = load_sqlite(conn, ["orders", "lineitem"])
    want = db.execute(
        "select o_orderpriority, count(*) from orders join lineitem "
        "on o_orderkey = l_orderkey where l_quantity < 1000 "
        "group by o_orderpriority order by o_orderpriority"
    ).fetchall()
    # l_quantity is decimal(12,2): engine-internal unscaled ints in
    # sqlite, so < 10 in SQL is < 1000 unscaled on the oracle side
    assert [tuple(r) for r in want] == [tuple(r) for r in got]


# ------------------------------------------- snapshot invalidation
@pytest.fixture()
def mem_runner():
    return LocalRunner(
        {"mem": MemoryConnector(), "tpch": TpchConnector(SF)},
        default_catalog="mem",
    )


def test_memory_dml_bumps_snapshot_and_misses(mem_runner):
    """INSERT moves snapshot_version -> the repeated statement misses
    and returns fresh (ground-truth-verified) rows."""
    r = mem_runner
    r.session.set("result_cache_enabled", True)
    r.execute("create table t as select 1 x, 10 y")
    r.execute("insert into t select 2, 20")
    conn = r.catalogs["mem"]
    v0 = conn.snapshot_version("t")
    q = "select count(*), sum(y) from t"
    res1 = r.execute(q)
    assert res1.rows == [(2, 30)]
    ex = r.executor
    hits0 = ex.result_cache_hits
    res2 = r.execute(q)
    assert ex.result_cache_hits > hits0          # unchanged data: hit
    assert res2.rows == [(2, 30)]
    r.execute("insert into t select 3, 300")
    assert conn.snapshot_version("t") != v0      # the token moved
    assert ex.result_cache_invalidations >= 1    # eager reclaim ran
    hits1 = ex.result_cache_hits
    res3 = r.execute(q)
    assert ex.result_cache_hits == hits1         # stale key: no hit
    assert res3.rows == [(3, 330)]               # fresh, correct


def test_update_same_cardinality_invalidates(mem_runner):
    """THE write-counter case: UPDATE preserves the row count, so a
    row-count-derived token would falsely serve the stale sum — the
    memory connector's explicit write version must force the miss."""
    r = mem_runner
    r.session.set("result_cache_enabled", True)
    r.execute("create table u as select 1 k, 100 v")
    r.execute("insert into u select 2, 200")
    q = "select sum(v) from u"
    assert r.execute(q).rows == [(300,)]
    assert r.execute(q).rows == [(300,)]         # cached
    rc0 = r.catalogs["mem"].row_count("u")
    v0 = r.catalogs["mem"].snapshot_version("u")
    r.execute("update u set v = 999 where k = 2")
    assert r.catalogs["mem"].row_count("u") == rc0   # same cardinality
    assert r.catalogs["mem"].snapshot_version("u") != v0
    assert r.execute(q).rows == [(1099,)]        # fresh rows, not 300


def test_view_replacement_moves_statement_key(mem_runner):
    """CREATE OR REPLACE VIEW must not serve the OLD view's cached
    rows: the statement key fingerprints the view-EXPANDED plan, so
    redefinition moves it."""
    r = mem_runner
    r.session.set("result_cache_enabled", True)
    r.execute("create table base as select 1 a, 2 b")
    r.execute("create view v as select a from base")
    assert r.execute("select * from v").rows == [(1,)]
    assert r.execute("select * from v").rows == [(1,)]  # cached
    r.execute("create or replace view v as select b from base")
    assert r.execute("select * from v").rows == [(2,)], (
        "stale pre-replacement view rows served from the cache")


def test_fragment_key_salted_by_session_config(runner):
    """Two sessions with different collect_k / page_rows must never
    address one fragment entry (the store is process-shared)."""
    ex = runner.executor
    ex.result_cache = ResultCache()
    plan = runner.plan(AGG_Q)
    ex._select_cache_points(plan)
    keys1 = {e[0] for e in ex._cache_points.values()}
    ex.collect_k = ex.collect_k * 2
    ex._select_cache_points(plan)
    keys2 = {e[0] for e in ex._cache_points.values()}
    ex.page_rows = ex.page_rows * 2
    ex._select_cache_points(plan)
    keys3 = {e[0] for e in ex._cache_points.values()}
    ex._cache_points = {}
    assert keys1 and keys1.isdisjoint(keys2)
    assert keys2.isdisjoint(keys3)


def test_memory_limit_enforced_on_replay(runner):
    """A cache hit still passes the per-query memory accounting: a
    limit that rejects the pages cold rejects them replayed."""
    from presto_tpu.exec.executor import MemoryBudgetExceeded

    ex = runner.executor
    ex.result_cache = ResultCache()
    plan = runner.plan(AGG_Q)
    ex.execute(plan)  # populate
    ex.max_memory_bytes = 8
    try:
        with pytest.raises(MemoryBudgetExceeded):
            ex.execute(plan)
    finally:
        ex.max_memory_bytes = None


def test_delete_and_drop_invalidate(mem_runner):
    r = mem_runner
    r.session.set("result_cache_enabled", True)
    r.execute("create table d as select 1 a union all select 2 a")
    q = "select count(*) from d"
    assert r.execute(q).rows == [(2,)]
    assert r.execute(q).rows == [(2,)]
    r.execute("delete from d where a = 2")
    assert r.execute(q).rows == [(1,)]


# --------------------------------------------------- cacheability rules
def test_system_scans_never_cache(runner):
    plan = runner.plan("select * from system.catalogs")
    reason = uncacheable_reason(plan, runner.catalogs)
    assert reason is not None and "system" in reason


def test_volatile_function_never_caches(runner):
    scan = P.TableScan("tpch", "nation", ("n_nationkey",))
    vol = P.Project(scan, (Call("random", (), T.DOUBLE),))
    reason = uncacheable_reason(P.Output(vol, ("r",)), runner.catalogs)
    assert reason is not None and "random" in reason


def test_remote_source_never_caches(runner):
    rs = P.RemoteSource((T.BIGINT,), key="stage1")
    assert uncacheable_reason(P.Output(rs, ("x",)),
                              runner.catalogs) is not None


def test_snapshotless_connector_never_caches(runner):
    class NoCount:
        def row_count(self, t):
            raise NotImplementedError

    from presto_tpu.connectors.base import Connector

    class NoSnap(Connector):
        pass

    cats = dict(runner.catalogs)
    cats["weird"] = NoSnap()
    plan = P.Output(
        P.Aggregation(P.TableScan("weird", "t", ("a",)), (), ()),
        ("c",))
    assert uncacheable_reason(plan, cats) is not None


def test_split_filter_token_carries_split_identity(conn):
    """Two tasks of one fragment on different split shares must never
    share a cache key: the SplitFilterConnector's snapshot token
    carries (index, count) for the filtered table — and only for it."""
    from presto_tpu.connectors.split_filter import (
        HashSplitConnector,
        SplitFilterConnector,
    )

    w0 = SplitFilterConnector(conn, "lineitem", 0, 2)
    w1 = SplitFilterConnector(conn, "lineitem", 1, 2)
    assert w0.snapshot_version("lineitem") != \
        w1.snapshot_version("lineitem")
    # unfiltered tables share the inner token (whole-table scans on
    # every worker ARE the same content)
    assert w0.snapshot_version("orders") == \
        w1.snapshot_version("orders")
    assert w0.snapshot_version("orders") == \
        conn.snapshot_version("orders")
    h0 = HashSplitConnector(conn, {"lineitem": "l_orderkey"}, 0, 2)
    h1 = HashSplitConnector(conn, {"lineitem": "l_orderkey"}, 1, 2)
    assert h0.snapshot_version("lineitem") != \
        h1.snapshot_version("lineitem")
    assert h0.snapshot_version("nation") == \
        conn.snapshot_version("nation")


# ------------------------------------------------ concurrent clients
def test_concurrent_clients_share_one_execution(conn):
    """Same statement from 8 concurrent protocol clients against one
    server: >= 1 real execution, the rest hit the process-shared
    store, every client gets identical rows."""
    from presto_tpu.client import StatementClient
    from presto_tpu.server.http_server import PrestoTpuServer
    import urllib.request

    srv = PrestoTpuServer({"tpch": conn}, port=0,
                          default_catalog="tpch")
    port = srv.start()
    try:
        results = [None] * 8
        errors = []

        def go(i):
            try:
                cl = StatementClient(f"http://127.0.0.1:{port}",
                                     user=f"u{i}", catalog="tpch")
                cl.session_properties["result_cache_enabled"] = "true"
                res = cl.execute(AGG_Q)
                assert res.error is None, res.error
                results[i] = res.rows
            except Exception as e:  # noqa: BLE001 - surfaced in the
                errors.append(e)    # main thread's assert below

        threads = [threading.Thread(target=go, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert all(r is not None for r in results)
        for r in results[1:]:
            assert r == results[0]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as f:
            metrics = f.read().decode()

        def metric(name):
            m = re.search(rf"^{name} (\d+)", metrics, re.M)
            return int(m.group(1)) if m else 0

        hits = metric("presto_tpu_result_cache_hits_total")
        misses = metric("presto_tpu_result_cache_misses_total")
        assert misses >= 1, "at least one real execution"
        assert hits >= 7, (
            f"8 identical statements should mostly hit (hits={hits}, "
            f"misses={misses})")
    finally:
        srv.stop()


# ------------------------------------------- CachingConnector key fix
class _CountingConnector(MemoryConnector):
    def __init__(self):
        super().__init__()
        self.pages_calls = 0

    def pages(self, table, columns=None, target_rows=1 << 20,
              constraint=None):
        self.pages_calls += 1
        return super().pages(table, columns, target_rows, constraint)


def test_caching_connector_canonical_constraint_key():
    """Structurally equal constraints built as distinct objects must
    share one cache entry (the repr() key split the cache whenever a
    constraint carried any non-literal; the canonical structural
    encoding cannot)."""
    inner = _CountingConnector()
    inner.create_table("t", ["a", "b"], [T.BIGINT, T.BIGINT],
                       [(i, i * 2) for i in range(10)])
    cc = CachingConnector(inner)
    c1 = (("a", 2, None),)
    c2 = tuple([("a", 2, None)])  # distinct object, same structure
    r1 = [p for p in cc.pages("t", constraint=c1)]
    assert inner.pages_calls == 1
    r2 = [p for p in cc.pages("t", constraint=c2)]
    assert inner.pages_calls == 1, "second scan must hit the cache"
    assert len(r1) == len(r2)


def test_caching_connector_snapshot_and_invalidate():
    """Wrapping a WRITABLE connector is safe now: the inner snapshot
    version rides in the page-cache key, and the invalidation path
    (runner._invalidate_caches -> invalidate()) reclaims bytes."""
    inner = _CountingConnector()
    inner.create_table("t", ["a"], [T.BIGINT], [(1,), (2,)])
    cc = CachingConnector(inner)
    rows = [r for p in cc.pages("t") for r in p.to_pylist()]
    assert len(rows) == 2
    assert inner.pages_calls == 1
    inner.insert("t", [(3,)])  # write THROUGH the wrapper's inner
    rows = [r for p in cc.pages("t") for r in p.to_pylist()]
    assert len(rows) == 3, "stale page list served after a write"
    assert inner.pages_calls == 2
    assert cc.cached_page_count > 0
    assert cc.invalidate("t") > 0
    assert cc.cached_page_count == 0


def test_runner_invalidation_reaches_wrapped_connector():
    """The runner's write path drops a wrapping page cache's stale
    lists through the registered invalidation hook."""
    inner = MemoryConnector()
    cc = CachingConnector(inner)
    r = LocalRunner({"mem": cc}, default_catalog="mem")
    r.execute("create table t as select 1 x")
    assert r.execute("select * from t").rows == [(1,)]
    r.execute("insert into t select 2")
    assert sorted(r.execute("select * from t").rows) == [(1,), (2,)]


# ---------------------------------------------- mesh-path residency
def _mesh_runner(conn, n=2):
    """A DistExecutor runner over an n-device CPU mesh (conftest
    forces the host platform device count)."""
    from presto_tpu.dist.executor import make_mesh
    from presto_tpu.session import Session

    return LocalRunner(
        {"tpch": conn}, default_catalog="tpch", page_rows=PAGE_ROWS,
        mesh=make_mesh(n),
        session=Session(catalog="tpch",
                        properties={"result_cache_enabled": True}),
    )


def test_mesh_root_hit_zero_crossings(conn):
    """Transfer-ledger pin (ISSUE 15 satellite): a fragment hit at
    the mesh root serves host pages straight through the extended
    sink chain (Output + gather-over-replicated pass-throughs) —
    ZERO h2d/d2h crossings on the replay."""
    from presto_tpu.exec import xfer as XF

    r = _mesh_runner(conn)
    r.apply_session()
    ex = r.executor
    plan = r.plan(AGG_Q)
    _, rows1 = ex.execute(plan)
    assert ex.result_cache_hits == 0
    base = XF.process_totals()
    _, rows2 = ex.execute(plan)
    assert rows1 == rows2
    assert ex.result_cache_hits >= 1
    assert ex.h2d_bytes == 0 and ex.d2h_bytes == 0
    # falsifiable process-totals delta, not just the per-query gauges
    now = XF.process_totals()
    assert now["h2d_bytes"] == base["h2d_bytes"]
    assert now["d2h_bytes"] == base["d2h_bytes"]


def test_mesh_midplan_replicated_point_hits(conn):
    """Mesh-path cache residency (ROADMAP item 6 remainder): a mesh
    query whose ROOT is uncacheable still caches its REPLICATED
    interior — the hit replays host pages (staged as mesh-replicated
    arrays only for the device consumer above) and SKIPS the
    gathered subtree's collectives entirely."""
    from presto_tpu.exec import plan as PP

    r = _mesh_runner(conn)
    r.apply_session()
    ex = r.executor
    base = r.plan("select l_returnflag rf, sum(l_quantity) s "
                  "from lineitem group by l_returnflag")
    # UniqueId above the interior makes the root uncacheable; the
    # replicated aggregated interior below is the mesh cache point
    plan = PP.Output(source=PP.UniqueId(source=base.source),
                     names=("rf", "s", "uid"))
    _, rows1 = ex.execute(plan)
    assert ex.result_cache_misses >= 1
    m0 = ex.mesh_local_exchanges
    _, rows2 = ex.execute(plan)
    assert rows1 == rows2
    assert ex.result_cache_hits >= 1, (
        "no mid-plan cache point selected on the mesh (replicated "
        "subtrees must be eligible)")
    # the replayed subtree's compiled collectives never ran again
    assert ex.mesh_local_exchanges == m0
