"""Split-batched execution: the per-SPLIT driver loop of a fused scan
pipeline folds into XLA (exec/executor._fused_stream, split_batch_size
session property).

Three batched program shapes are pinned here against the unbatched
driver loop and the sqlite oracle:

  - grouped scan-agg (Q1 shape): lax.scan over split indices with the
    partial-aggregation state as carry;
  - global scan-agg (Q6 shape): lax.scan stacking the per-split state
    rows (bit-exact concat of the unbatched states);
  - page-emitting chains: the fused body vmapped over a [B, n_pad]
    stacked batch, emitted as one page.

Batching is auto = TPU-only (the win is the per-launch tunnel tax —
ROOFLINE §7); every CPU test forces it on via the session property,
the same pattern as the Pallas-join / late-materialization suites.
"""

import dataclasses

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runner import LocalRunner
from tests.oracle import load_sqlite

Q1ISH = (
    "select l_returnflag, l_linestatus, sum(l_quantity), count(*) "
    "from lineitem where l_shipdate <= date '1998-09-02' "
    "group by l_returnflag, l_linestatus order by 1, 2"
)
Q6ISH = (
    "select sum(l_extendedprice * l_discount) from lineitem "
    "where l_discount between 0.05 and 0.07 and l_quantity < 24"
)


@pytest.fixture(scope="module")
def rig():
    conn = TpchConnector(0.01)
    # 8192-row pages over SF0.01 lineitem (~60k rows) = 13 live splits:
    # a NON-power-of-two count, so the single 16-bucket batch pads 3
    # tail slots with zero traced row counts every test exercises
    runner = LocalRunner({"tpch": conn}, page_rows=1 << 13)
    runner.session.set("fused_partial_agg_enabled", "true")
    return runner


def _run(runner, sql, batch):
    runner.session.set("split_batch_size", batch)
    try:
        rows = runner.execute(sql).rows
        ex = runner.executor
        return rows, {
            "launches": ex.program_launches,
            "splits": ex.splits_scanned,
            "fused": ex.fused_partial_aggs,
            "fallbacks": ex.split_batch_fallbacks,
        }
    finally:
        runner.session.unset("split_batch_size")


def test_q1_grouped_scan_carry_parity_and_launches(rig):
    """Q1 shape: the whole 13-split scan phase runs as ONE lax.scan
    program with the partial-agg state as carry — counter-verified,
    with exact parity against the unbatched driver loop AND sqlite."""
    on, c_on = _run(rig, Q1ISH, "64")
    off, c_off = _run(rig, Q1ISH, "false")
    assert c_on["fused"] >= 1 and c_on["fallbacks"] == 0
    assert c_on["launches"] <= 2  # acceptance bar: <= 2 for the phase
    assert c_on["splits"] == c_off["splits"]  # every real split ran
    assert c_off["launches"] == c_off["splits"]  # one per split before
    assert on == off
    db = load_sqlite(rig.catalogs["tpch"], ["lineitem"])
    want = db.execute(
        "select l_returnflag, l_linestatus, sum(l_quantity), count(*) "
        "from lineitem where l_shipdate <= 10471 "
        "group by l_returnflag, l_linestatus order by 1, 2"
    ).fetchall()
    assert [(r[0], r[1], int(r[2]), r[3]) for r in on] == [
        (w[0], w[1], int(w[2]), w[3]) for w in want
    ]


def test_q6_global_scan_stack_parity_and_launches(rig):
    """Q6 shape: global partial states stack inside one scanned
    program; decimal sums are exact integers, so batched == unbatched
    == sqlite with no tolerance."""
    on, c_on = _run(rig, Q6ISH, "64")
    off, c_off = _run(rig, Q6ISH, "false")
    assert c_on["launches"] <= 2 and c_on["fallbacks"] == 0
    assert c_off["launches"] == c_off["splits"]
    assert on == off
    db = load_sqlite(rig.catalogs["tpch"], ["lineitem"])
    # engine decimals are unscaled ints: discount 0.05 -> 5
    want = db.execute(
        "select sum(l_extendedprice * l_discount) from lineitem "
        "where l_discount between 5 and 7 and l_quantity < 2400"
    ).fetchone()
    assert int(on[0][0]) == int(want[0])


def test_page_emitting_vmap_batch_parity(rig):
    """A fused filter->project chain with NO agg tail takes the vmap
    path: B splits stack into one [B, n_pad] launch emitted as one
    page, and downstream results match per-split execution exactly."""
    sql = (
        "select l_orderkey, l_extendedprice from lineitem "
        "where l_quantity < 3 order by 1, 2"
    )
    on, c_on = _run(rig, sql, "64")
    off, c_off = _run(rig, sql, "false")
    assert c_on["launches"] < c_off["launches"]
    assert c_on["launches"] <= 2 and c_on["fallbacks"] == 0
    assert on == off


def test_tail_batch_padding_masks_rows(rig):
    """Forcing a small batch size makes ceil(13/4) = 4 chunks whose
    tail chunk (1 split) takes the per-split program — and a batch
    size of 8 leaves a 5-split tail chunk padded to its own 8-bucket.
    Both paddings must be pure masking: parity is exact."""
    base, _ = _run(rig, Q1ISH, "false")
    for b in ("4", "8"):
        rows, c = _run(rig, Q1ISH, b)
        assert rows == base, f"batch={b}"
        assert c["splits"] == 13
        assert c["launches"] == -(-13 // int(b))


def test_overflow_retry_reenters_ladder(rig):
    """A scanned program whose partial-agg capacity overflows must
    OR-reduce the flag across the batch and re-enter the existing
    boosted-retry ladder — same final boost as the unbatched loop,
    same (correct) results."""
    sql = (
        "select l_quantity, count(*) from lineitem "
        "group by l_quantity order by 1"
    )
    ex = rig.executor
    rig.session.set("agg_optimistic_rows", 8)  # 50 groups overflow 8
    try:
        on, c_on = _run(rig, sql, "64")
        boost_on = ex._capacity_boost
        off, _ = _run(rig, sql, "false")
        boost_off = ex._capacity_boost
    finally:
        rig.session.unset("agg_optimistic_rows")
    assert boost_on > 1 and boost_on == boost_off
    assert on == off and len(on) == 50


def test_worker_fragment_batches(rig):
    """The shipped-plan worker path (SplitFilterConnector declares
    fused_scan_ok): a worker's round-robin share of the splits folds
    into one launch too."""
    from presto_tpu.connectors.split_filter import SplitFilterConnector
    from presto_tpu.dist import plan_serde
    from presto_tpu.server.worker import find_partial_cut

    conn = rig.catalogs["tpch"]
    plan = rig.plan(Q1ISH)
    cut = find_partial_cut(plan)
    assert cut is not None
    fragment = plan_serde.loads(
        plan_serde.dumps(dataclasses.replace(cut, step="partial"))
    )
    worker = LocalRunner(
        {"tpch": SplitFilterConnector(conn, "lineitem", 0, 2)},
        page_rows=1 << 13,
    )
    worker.session.set("fused_partial_agg_enabled", "true")
    worker.session.set("split_batch_size", "64")
    worker.apply_session()
    ex = worker.executor
    pages = ex.stream_fragment(fragment, lambda p: p)
    assert pages and ex.fused_partial_aggs >= 1
    assert ex.program_launches == 1 and ex.splits_scanned == 7


def test_counters_in_explain_analyze(rig):
    """program_launches / splits_per_launch ride EXPLAIN ANALYZE's
    counters line (the observability contract of the acceptance
    criteria)."""
    rig.session.set("split_batch_size", "64")
    try:
        rig.apply_session()
        plan = rig.plan(Q6ISH)
        _n, _r, stats = rig.executor.execute_with_stats(plan)
    finally:
        rig.session.unset("split_batch_size")
    ctr = stats["counters"]
    assert ctr["program_launches"] >= 1
    assert ctr["splits_per_launch"] > 1
    from presto_tpu.runner import explain_text

    text = explain_text(plan, stats=stats)
    assert "program_launches" in text and "splits_per_launch" in text


def test_auto_is_tpu_only(rig):
    """auto = TPU-only (the pallas_joins_used policy): on this CPU
    suite the resolved max batch is 0 and nearby split counts share
    the per-split programs they always had."""
    rig.apply_session()  # default: auto
    ex = rig.executor
    assert ex.split_batch == "auto"
    assert ex._split_batch_max(8192, scanned=True) == 0
    assert ex._split_batch_max(8192, scanned=False) == 0
    # explicit int engages anywhere, floored to a ladder power of two
    ex.split_batch = 48
    assert ex._split_batch_max(8192, scanned=True) == 32
    # vmapped page batches bound B * n_pad under the kernel fault line
    ex.split_batch = 64
    assert ex._split_batch_max(1 << 20, scanned=False) == 4
    ex.split_batch = "auto"


def test_batch_buckets_share_programs(rig):
    """Nearby split counts land on the same batch bucket: re-running
    with the same shapes must compile nothing new (the shapes.py
    ladder composing with the persistent compile cache)."""
    _run(rig, Q6ISH, "64")  # warm the batched program
    ex = rig.executor
    jit_keys = set(ex._jit_cache)
    rows, c = _run(rig, Q6ISH, "64")
    assert set(ex._jit_cache) == jit_keys  # no new canonical programs
    assert c["launches"] <= 2
