"""SQL-level TPC-DS correctness (BASELINE rung 5): Q17 and Q64 run
through parse → plan → execute and are checked against sqlite3 running an
encoding-adapted oracle over the same generated rows (same pattern as
test_sql_tpch.py; reference analog: presto-tpcds + AbstractTestQueries).

Oracle adaptations: decimals are unscaled cents ints (64 -> 6400);
stddev_samp is registered as a Python aggregate UDF (sqlite has none).
"""

import collections
import math

import pytest

from presto_tpu.connectors.tpcds import TpcdsConnector
from presto_tpu.runner import LocalRunner
from tests.oracle import load_sqlite
from tests.tpcds_queries import QUERIES

SF = 0.01
# Q64's cross-channel chain (same item returned in consecutive years at
# the same store, within the qualified color/price band) is empty below
# SF ~0.025, and the 18-table plan takes many minutes of XLA compile on
# the 1-core CPU CI — so the Q64 correctness test runs at its own scale,
# opt-in via RUN_SLOW=1 (same pattern as test_tpu_smoke.py). It is part
# of the bench ladder on real hardware.
Q64_SF = 0.025


class _StddevSamp:
    """Welford accumulator registered as a sqlite aggregate UDF."""

    def __init__(self):
        self.n = 0
        self.mean = 0.0
        self.m2 = 0.0

    def step(self, v):
        if v is None:
            return
        self.n += 1
        d = v - self.mean
        self.mean += d / self.n
        self.m2 += d * (v - self.mean)

    def finalize(self):
        if self.n < 2:
            return None
        return math.sqrt(self.m2 / (self.n - 1))


@pytest.fixture(scope="module")
def conn():
    return TpcdsConnector(SF)


@pytest.fixture(scope="module")
def runner(conn):
    return LocalRunner({"tpcds": conn}, default_catalog="tpcds",
                       page_rows=1 << 16)


# only the tables this module's queries touch — inventory alone is
# ~940k rows at SF0.01 and would dominate fixture setup if loaded
# unconditionally
_ORACLE_TABLES = [
    "store_sales", "store_returns", "catalog_sales", "catalog_returns",
    "date_dim", "store", "item", "customer", "customer_address",
    "web_sales", "warehouse", "ship_mode", "web_site", "reason",
    "time_dim", "household_demographics", "inventory",
    "customer_demographics", "promotion",
]


@pytest.fixture(scope="module")
def db(conn):
    d = load_sqlite(conn, _ORACLE_TABLES)
    d.create_aggregate("stddev_samp", 1, _StddevSamp)
    return d


ORACLE_17 = QUERIES[17]  # integer quantities: no encoding adaptation

ORACLE_64 = QUERIES[64].replace(
    "between 64 and 74", "between 6400 and 7400"
).replace(
    "between 65 and 79", "between 6500 and 7900"
)

# Q82: i_current_price decimals are unscaled cents in both engines'
# shared rows; the literal band scales accordingly
ORACLE_82 = QUERIES[82].replace(
    "between 62 and 92", "between 6200 and 9200"
)

# float-tolerance columns of Q17: ave/stdev/cov per channel
Q17_FLOAT_COLS = {4, 5, 6, 8, 9, 10, 12, 13, 14}


# Q37 shares Q82's decimal-band adaptation
ORACLE_37 = QUERIES[37].replace(
    "between 68 and 98", "between 6800 and 9800"
)

# round-4 breadth queries: float cols (avg over ints -> sqlite float)
# and round cols (avg over cents decimals: engine yields round-half-up
# int cents, sqlite a float — bucket both to int, tpch "r" mode)
_DS_ORACLE = {
    3: (QUERIES[3], set(), set()),
    7: (QUERIES[7], {1}, {2, 3, 4}),
    17: (ORACLE_17, Q17_FLOAT_COLS, set()),
    19: (QUERIES[19], set(), set()),
    25: (QUERIES[25], set(), set()),
    26: (QUERIES[26], {1}, {2, 3, 4}),
    29: (QUERIES[29], set(), set()),
    37: (ORACLE_37, set(), set()),
    42: (QUERIES[42], set(), set()),
    52: (QUERIES[52], set(), set()),
    55: (QUERIES[55], set(), set()),
    62: (QUERIES[62], set(), set()),
    64: (ORACLE_64, set(), set()),
    82: (ORACLE_82, set(), set()),
    93: (QUERIES[93], set(), set()),
    96: (QUERIES[96], set(), set()),
}


def ds_oracle(qid: int):
    """(oracle sql, float-tolerance column set) per TPC-DS query —
    consumed by bench.py's oracle cross-check and sqlite baseline."""
    sql, float_cols, _round_cols = _DS_ORACLE[qid]
    return sql, float_cols


def _norm(row, float_cols, round_cols=frozenset()):
    out = []
    for j, v in enumerate(row):
        if v is None:
            out.append(None)
        elif j in float_cols:
            out.append(round(float(v), 6))
        elif j in round_cols:
            # round-half-up (engine decimal avgs round half up; python
            # round() is banker's)
            out.append(math.floor(float(v) + 0.5))
        else:
            out.append(v)
    return tuple(out)


def _compare(engine_rows, oracle_rows, float_cols, label,
             round_cols=frozenset()):
    assert len(engine_rows) == len(oracle_rows), (
        f"{label}: row count {len(engine_rows)} vs {len(oracle_rows)}\n"
        f"engine: {engine_rows[:3]}\noracle: {oracle_rows[:3]}"
    )
    e_rows = [_norm(r, float_cols, round_cols) for r in engine_rows]
    o_rows = [_norm(tuple(r), float_cols, round_cols)
              for r in oracle_rows]
    for i, (er, orow) in enumerate(zip(e_rows, o_rows)):
        for j, (ev, ov) in enumerate(zip(er, orow)):
            if j in float_cols and ev is not None and ov is not None:
                assert abs(ev - ov) <= 1e-6 * max(1.0, abs(ov)), (
                    f"{label} row {i} col {j}: {ev} != {ov}"
                )
            else:
                assert ev == ov, (
                    f"{label} row {i} col {j}: {ev!r} != {ov!r}"
                )


def test_q17(runner, db):
    got = runner.execute(QUERIES[17]).rows
    want = db.execute(ORACLE_17).fetchall()
    assert len(want) > 0, "oracle returned no rows — fixture too sparse"
    _compare(got, want, Q17_FLOAT_COLS, "Q17")


@pytest.mark.parametrize(
    "qid", [3, 7, 19, 25, 26, 29, 37, 42, 52, 55, 62, 82, 93, 96]
)
def test_breadth_queries(qid, runner, db):
    """Rounds 3-4 breadth: store/catalog/web channels, inventory,
    demographics, promotion, reason, time_dim, warehouse, ship_mode,
    web_site — each vs the sqlite oracle over the same rows."""
    sql, float_cols, round_cols = _DS_ORACLE[qid]
    got = runner.execute(QUERIES[qid]).rows
    want = db.execute(sql).fetchall()
    if qid == 96:
        # bare count: non-zero or the fixture verified nothing
        assert want[0][0] > 0, "Q96: fixture too sparse"
    else:
        assert len(want) > 0, (
            f"Q{qid}: oracle returned no rows — fixture too sparse"
        )
    _compare(got, want, float_cols, f"Q{qid}", round_cols)


@pytest.mark.skipif(
    not __import__("os").environ.get("RUN_SLOW"),
    reason="Q64 needs SF 0.025 + ~10 min of 1-core XLA compile; "
    "set RUN_SLOW=1",
)
def test_q64():
    conn64 = TpcdsConnector(Q64_SF)
    runner = LocalRunner({"tpcds": conn64}, default_catalog="tpcds",
                         page_rows=1 << 17)
    db = load_sqlite(conn64, conn64.tables())
    db.create_aggregate("stddev_samp", 1, _StddevSamp)
    got = runner.execute(QUERIES[64]).rows
    want = db.execute(ORACLE_64).fetchall()
    assert len(want) > 0, "oracle returned no rows — fixture too sparse"
    _compare(got, want, set(), "Q64")


def test_generator_invariants(conn):
    """Structural sanity of the generator itself (cheap, no engine)."""
    import numpy as np

    # date_dim calendar parts agree with python's calendar
    import datetime

    page = next(conn.pages("date_dim"))
    rows = page.to_pylist()
    assert len(rows) == conn.row_count("date_dim")
    cols = conn.table_schema("date_dim").column_names()
    i_sk = cols.index("d_date_sk")
    i_year = cols.index("d_year")
    i_moy = cols.index("d_moy")
    i_dom = cols.index("d_dom")
    i_qn = cols.index("d_quarter_name")
    base = datetime.date(1900, 1, 1)
    for probe in (0, 1, 58, 36524, 73048, 40177):
        r = rows[probe]
        d = base + datetime.timedelta(days=probe)
        assert r[i_sk] == 2415022 + probe
        assert (r[i_year], r[i_moy], r[i_dom]) == (d.year, d.month, d.day)
        assert r[i_qn] == f"{d.year}Q{(d.month - 1) // 3 + 1}"

    # demographics cross product: sk decodes bijectively on a sample
    cd = list(conn.pages("customer_demographics"))[0].to_pylist()
    seen = set(tuple(r[1:]) for r in cd)
    assert len(seen) == len(cd), "cd decode must be injective"

    # returns reference their sale: same item/ticket multiset subset
    ss = [r for p in conn.pages("store_sales") for r in p.to_pylist()]
    sr = [r for p in conn.pages("store_returns") for r in p.to_pylist()]
    ss_cols = conn.table_schema("store_sales").column_names()
    sr_cols = conn.table_schema("store_returns").column_names()
    ss_keys = collections.Counter(
        (r[ss_cols.index("ss_item_sk")],
         r[ss_cols.index("ss_ticket_number")]) for r in ss
    )
    for r in sr:
        k = (r[sr_cols.index("sr_item_sk")],
             r[sr_cols.index("sr_ticket_number")])
        assert ss_keys[k] >= 1
    # return ratio near the spec's ~10%
    assert 0.05 < len(sr) / len(ss) < 0.15
    # return quantity bounded by sale quantity per matching line is
    # guaranteed by construction (rqty = u % qty + 1); spot-check ranges
    qty_i = sr_cols.index("sr_return_quantity")
    assert all(1 <= r[qty_i] <= 100 for r in sr)
