"""Fault tolerance inside one process (ISSUE 5's inward half): the
executor's device-OOM degradation ladder (a caught RESOURCE_EXHAUSTED
re-enters execution under a tightened device-memory budget, so an
HBM-model miss becomes a slow correct query), the query_max_run_time
deadline, and the session/etc plumbing that governs both.

The DCN (cross-process) half lives in tests/test_dcn.py; the chaos
harness wrapper in tests/test_chaos.py.
"""

import time

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec.executor import QueryDeadlineExceeded
from presto_tpu.runner import LocalRunner
from presto_tpu.session import Session

SF = 0.01
PAGE_ROWS = 1 << 13

JOIN_SQL = (
    "select o_orderpriority, count(*), sum(l_quantity) "
    "from orders join lineitem on o_orderkey = l_orderkey "
    "group by o_orderpriority"
)


@pytest.fixture()
def runner():
    return LocalRunner({"tpch": TpchConnector(SF)},
                       page_rows=PAGE_ROWS)


@pytest.fixture(scope="module")
def oracle_rows():
    r = LocalRunner({"tpch": TpchConnector(SF)}, page_rows=PAGE_ROWS)
    return sorted(r.execute(JOIN_SQL).rows)


# ------------------------------------------------- device-OOM ladder
def test_injected_oom_retries_and_matches(runner, oracle_rows):
    """A device fault on the first attempt re-enters under a halved
    budget and returns correct rows (device_oom_retries observable)."""
    ex = runner.executor
    ex.inject_device_oom = 1
    rows = runner.execute(JOIN_SQL).rows
    assert sorted(rows) == oracle_rows
    assert ex.device_oom_retries == 1
    assert ex.inject_device_oom == 0
    assert ex._oom_divisor == 2  # the budget really tightened


def test_oom_with_forced_tiny_budget_stays_correct(runner,
                                                   oracle_rows):
    """The acceptance shape: forced tiny budget + forced device fault
    on a join — the retry runs under a TIGHTENED budget (the membudget
    governor re-plans chunked) and the rows stay oracle-correct."""
    runner.session.set("device_memory_budget", 1 << 22)
    ex = runner.executor
    ex.inject_device_oom = 1
    rows = runner.execute(JOIN_SQL).rows
    assert sorted(rows) == oracle_rows
    assert ex.device_oom_retries >= 1
    # tightened: half the forced budget, never raised above it
    assert ex._budget() <= (1 << 22) // 2


def test_pinned_mode_raises_through(runner):
    """task_retry_attempts=0 restores raise-through: the device fault
    surfaces instead of degrading (the classic failure model)."""
    runner.session.set("task_retry_attempts", 0)
    runner.executor.inject_device_oom = 1
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        runner.execute(JOIN_SQL)


def test_oom_budget_exhausted_raises(runner):
    """More faults than attempts: the ladder gives up loudly."""
    runner.session.set("task_retry_attempts", 2)
    runner.executor.inject_device_oom = 3  # one more than the budget
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        runner.execute(JOIN_SQL)


def test_non_device_errors_never_absorbed(runner):
    """The ladder gate is conservative: an engine programming error
    must surface on the FIRST attempt, not burn retries."""
    from presto_tpu.exec.executor import _is_device_fault

    assert not _is_device_fault(ValueError("bad plan"))
    assert not _is_device_fault(RuntimeError("capacity overflow"))
    assert _is_device_fault(
        RuntimeError("RESOURCE_EXHAUSTED: out of memory"))
    assert _is_device_fault(
        RuntimeError("Failed to allocate 123 bytes"))
    # engine control-flow exceptions subclass RuntimeError; QUOTING a
    # worker's device-fault text must not re-enter the ladder (the
    # exact-type gate)
    from presto_tpu.dist.dcn import DcnQueryFailed

    assert not _is_device_fault(DcnQueryFailed(
        "worker x task y: RESOURCE_EXHAUSTED: out of memory "
        "(task retries exhausted)"))
    # a NON-memory XlaRuntimeError is a bug to surface, not a
    # footprint to shrink — the markers must match for both types
    XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
    assert not _is_device_fault(XlaRuntimeError("INVALID_ARGUMENT: x"))
    assert _is_device_fault(
        XlaRuntimeError("RESOURCE_EXHAUSTED: Out of memory"))


def test_oom_counters_reset_per_query(runner, oracle_rows):
    ex = runner.executor
    ex.inject_device_oom = 1
    runner.execute(JOIN_SQL)
    assert ex.device_oom_retries == 1
    runner.execute("select count(*) from region")
    assert ex.device_oom_retries == 0  # per-query observability
    assert ex._oom_divisor == 1  # fresh query runs at full budget


def test_explain_analyze_exposes_ft_counters(runner):
    res = runner.execute(
        "explain analyze select count(*) from orders")
    text = "\n".join(r[0] for r in res.rows)
    assert "device_oom_retries=0" in text
    assert "task_retries=0" in text
    assert "workers_excluded=0" in text
    assert "deadline_ms_remaining=-1" in text  # no deadline set


# ------------------------------------------------------- deadlines
def test_query_deadline_expires(runner):
    runner.session.set("query_max_run_time", 1)  # 1ms: always expires
    with pytest.raises(QueryDeadlineExceeded):
        runner.execute(
            "select l_returnflag, count(*) from lineitem "
            "group by l_returnflag")


def test_query_deadline_zero_is_unlimited(runner):
    runner.session.set("query_max_run_time", 0)
    rows = runner.execute("select count(*) from region").rows
    assert rows == [(5,)]
    assert runner.executor.query_deadline is None


def test_deadline_remaining_reported(runner):
    runner.session.set("query_max_run_time", 300_000)
    res = runner.execute(
        "explain analyze select count(*) from region")
    text = "\n".join(r[0] for r in res.rows)
    assert "deadline_ms_remaining=" in text
    remaining = int(
        text.split("deadline_ms_remaining=")[1].split(",")[0]
        .split()[0])
    assert 0 < remaining <= 300_000


def test_query_manager_deadline_surfaces_failed():
    """The server path: a deadline expiry lands the query in FAILED
    with a timeout cause (reference: QueryTracker enforceTimeLimits),
    visible to listeners and /metrics."""
    from presto_tpu.server.http_server import QueryManager

    def factory(session):
        return LocalRunner({"tpch": TpchConnector(SF)},
                           page_rows=PAGE_ROWS, session=session)

    mgr = QueryManager(factory)
    session = Session(catalog="tpch",
                      properties={"query_max_run_time": 1})
    q = mgr.submit(
        "select l_returnflag, count(*) from lineitem "
        "group by l_returnflag", session)
    assert q.done.wait(timeout=120)
    assert q.state == "FAILED"
    assert q.error["errorName"] == "QueryDeadlineExceeded"


# ------------------------------------------------------- plumbing
def test_etc_keys_seed_session_defaults(tmp_path):
    (tmp_path / "config.properties").write_text(
        "task-retry.attempts=5\n"
        "task-retry.backoff-ms=250\n"
        "query.max-run-time-ms=60000\n"
    )
    (tmp_path / "catalog").mkdir()
    (tmp_path / "catalog" / "tpch.properties").write_text(
        "connector.name=tpch\ntpch.scale=0.001\n"
    )
    from presto_tpu.config import server_from_etc

    srv = server_from_etc(str(tmp_path), port=0)
    s = Session(catalog="tpch")
    srv.manager._runner_factory(s)  # seeds deployment-tier defaults
    assert s.get("task_retry_attempts") == 5
    assert s.get("retry_backoff_ms") == 250
    assert s.get("query_max_run_time") == 60000


def test_apply_session_wires_ft_knobs(runner):
    runner.session.set("task_retry_attempts", 4)
    runner.session.set("query_max_run_time", 120_000)
    runner.apply_session()
    ex = runner.executor
    assert ex.device_oom_attempts == 4
    assert ex.query_deadline is not None
    assert ex.query_deadline - time.monotonic() <= 120.0


def test_metrics_text_exposes_ft_counters(runner):
    from presto_tpu.server.http_server import QueryManager

    mgr = QueryManager(lambda s: runner)
    text = mgr.metrics_text(1.0, executor=runner.executor)
    assert "presto_tpu_task_retries_total 0" in text
    assert "presto_tpu_workers_excluded_total 0" in text
    assert "presto_tpu_device_oom_retries 0" in text
