"""ISSUE 11: the runtime lock sanitizer (presto_tpu/obs/sanitizer.py).

Each capability is pinned by a deliberately-misbehaving synthetic
owner: ordering inversions, re-entrant acquisition, unlocked
shared-attr writes, Condition integration, and the zero-cost off
path. The conftest arms the sanitizer suite-wide; these tests manage
the armed state explicitly so they pass standalone too.
"""

import threading

import pytest

from presto_tpu.obs import sanitizer as SAN


@pytest.fixture
def armed():
    """Armed sanitizer with clean state; restores prior arming."""
    was = SAN.is_armed()
    SAN.arm()
    SAN.reset()
    yield SAN
    SAN.reset()
    if not was:
        SAN.disarm()


# ----------------------------------------------------------- off path


def test_disarmed_returns_plain_primitives():
    was = SAN.is_armed()
    SAN.disarm()
    try:
        lk = SAN.make_lock("x.y.z")
        assert isinstance(lk, type(threading.Lock()))
        cv = SAN.make_condition("x.y.cv")
        assert isinstance(cv, threading.Condition)

        class Plain:
            _shared_attrs = ("n",)

            def __init__(self):
                self._lock = SAN.make_lock("x.Plain._lock")
                self.n = 0
                SAN.register_owner(self)

        p = Plain()
        assert type(p) is Plain  # no class swap when off
        p.n = 5  # unchecked when off
        assert SAN.violation_count() == 0
    finally:
        if was:
            SAN.arm()


# ------------------------------------------------------ held/ordering


def test_ordering_recorded_and_inversion_detected(armed):
    a = SAN.make_lock("t.A")
    b = SAN.make_lock("t.B")
    with a:
        with b:
            pass
    assert ("t.A", "t.B") in SAN.order_edges()
    assert SAN.violation_count() == 0
    with b:
        with a:  # the opposite order: classic deadlock shape
            pass
    v = SAN.violations()
    assert len(v) == 1 and "lock-order inversion" in v[0]
    assert "t.A" in v[0] and "t.B" in v[0]
    # both sites are named so the report is actionable
    assert "test_sanitizer.py" in v[0]


def test_consistent_order_is_silent(armed):
    a = SAN.make_lock("t.A")
    b = SAN.make_lock("t.B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert SAN.violation_count() == 0


def test_reentrant_acquire_raises_instead_of_deadlocking(armed):
    a = SAN.make_lock("t.R")
    with a:
        with pytest.raises(RuntimeError, match="re-entrant"):
            a.acquire()
    assert any("re-entrant" in v for v in SAN.violations())
    # the lock recovered: a fresh acquire works
    with a:
        pass


def test_release_clears_held_set(armed):
    a = SAN.make_lock("t.H")
    with a:
        assert a.held_by_me()
    assert not a.held_by_me()


# ------------------------------------------------- shared-attr checks


class _Owner:
    _shared_attrs = ("n",)

    def __init__(self):
        self._lock = SAN.make_lock("t.Owner._lock")
        self.n = 0
        SAN.register_owner(self)

    def bump_locked(self):
        with self._lock:
            self.n += 1

    def bump_racy(self):
        self.n += 1


def test_unlocked_shared_write_detected(armed):
    o = _Owner()
    o.bump_locked()
    assert SAN.violation_count() == 0
    o.bump_racy()
    v = SAN.violations()
    assert len(v) == 1 and "unlocked shared-attr write" in v[0]
    assert ".n" in v[0] and "t.Owner._lock" in v[0]
    assert o.n == 2  # the write itself still lands


def test_instrumented_class_keeps_name_and_isinstance(armed):
    o = _Owner()
    assert type(o).__name__ == "_Owner"
    assert isinstance(o, _Owner)


def test_unshared_attrs_are_not_checked(armed):
    o = _Owner()
    o.other = 7  # not in _shared_attrs: free to write anywhere
    assert SAN.violation_count() == 0


def test_multi_lock_owner_any_lock_satisfies(armed):
    """The TaskRuntime shape: several locks, a write under ANY of the
    registered ones passes (domain split is documented, not checked)."""

    class Two:
        _shared_attrs = ("x",)

        def __init__(self):
            self._a_lock = SAN.make_lock("t.Two._a_lock")
            self._b_lock = SAN.make_lock("t.Two._b_lock")
            self.x = 0
            SAN.register_owner(self, lock_attrs=("_a_lock", "_b_lock"))

    t = Two()
    with t._b_lock:
        t.x = 1
    assert SAN.violation_count() == 0
    t.x = 2
    assert SAN.violation_count() == 1


# -------------------------------------------------------- Conditions


def test_condition_fronts_sanitized_lock(armed):
    """make_condition integrates with threading.Condition: holding the
    Condition IS holding the backing sanitized lock, wait() keeps the
    held-set honest, and notify paths see ownership correctly."""

    class Arbiter:
        _shared_attrs = ("used",)

        def __init__(self):
            self._cv = SAN.make_condition("t.Arbiter._cv")
            self.used = 0
            SAN.register_owner(self, lock_attrs=("_cv",))

    a = Arbiter()
    with a._cv:
        a.used += 1       # under the condition's lock: clean
        a._cv.wait(0.01)  # releases + reacquires through the wrapper
        a.used += 1       # still owned after wait
        a._cv.notify_all()
    assert SAN.violation_count() == 0
    a.used = 0
    assert SAN.violation_count() == 1


def test_condition_alias_unifies_held_set(armed):
    """The ResourceGroupManager shape: a Condition built over an
    existing lock — acquiring either names the same lock."""
    lk = SAN.make_lock("t.Alias._lock")
    cv = SAN.make_condition(lock=lk)
    with cv:
        assert lk.held_by_me()
    assert not lk.held_by_me()


# ------------------------------------------------ cross-thread races


def test_real_two_thread_race_is_caught(armed):
    """The dynamic side earns its keep: a racy writer thread hammering
    an owner without the lock is observed as violations (not a crash,
    not silence)."""
    o = _Owner()
    stop = threading.Event()

    def racer():
        while not stop.is_set():
            o.bump_racy()

    t = threading.Thread(target=racer, daemon=True)
    t.start()
    for _ in range(50):
        o.bump_locked()
    stop.set()
    t.join(timeout=5)
    assert SAN.violation_count() > 0


def test_profile_store_instance_map_race_single_winner(tmp_path):
    """Pin the ISSUE-11 ProfileStore.at fix: construction happens
    OUTSIDE the class instance-map lock (no filesystem work under it),
    and racing lookups still converge on ONE shared instance."""
    from presto_tpu.obs.profile import ProfileStore

    d = str(tmp_path / "profiles")
    got = []

    def lookup():
        got.append(ProfileStore.at(d))

    threads = [threading.Thread(target=lookup) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(got) == 8
    assert all(s is got[0] for s in got), \
        "racing ProfileStore.at() returned different instances"


def test_engine_locks_are_instrumented_under_pytest():
    """The conftest arming reached the engine: a freshly built
    ResultCache (created AFTER arming) carries sanitized locks, so the
    serving-path stress test is actually exercising instrumentation."""
    if not SAN.is_armed():
        pytest.skip("sanitizer disarmed via PRESTO_TPU_LOCK_SANITIZER")
    from presto_tpu.cache.store import ResultCache

    rc = ResultCache(budget_bytes=1 << 20)
    assert isinstance(rc._lock, SAN._SanitizedLock)
    assert type(rc).__name__ == "ResultCache"
    assert getattr(type(rc), "_san_instrumented", False)
