"""ISSUE 9: query-lifecycle tracing (presto_tpu/obs/).

Covers the subsystem surface by surface:
  - span-tree shape for local and distributed (stage-DAG) execution
    (>= 3 stages; coordinator and worker task spans nest consistently
    on one clamped monotonic timeline);
  - recovery annotations: retry spans under injected submit faults,
    speculate spans under an injected straggler;
  - Chrome-trace JSON validity (sorted ts, complete X events, dur>=0);
  - /v1/query/{id} served LIVE mid-query and its agreement with
    system.runtime_tasks (one tree, two surfaces);
  - /metrics histogram exposition + bucket math;
  - observed-stats profile store: round-trip, and the acceptance
    contract — a repeated query skips the overflow-retry ladder
    (capacity_boost_retries = 0 on the second run, counter-pinned);
  - tracing-off overhead pinned at zero recorded spans;
  - the lint `spans` registry rule (clean repo + seeded violation).
"""

import collections
import json
import textwrap
import time
import urllib.error
import urllib.request

import pytest

from presto_tpu import obs as OBS
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.dist.dcn import DcnRunner
from presto_tpu.runner import LocalRunner
from presto_tpu.server.worker import WorkerServer

SF = 0.01
PAGE_ROWS = 1 << 13

# the 3+-stage shape from test_stagedag (join -> agg -> join -> agg):
# fragments into >= 3 stages with repartition/broadcast/gather edges
DAG_QUERY = (
    "select n_name, count(*), sum(top.c_count) from nation join ("
    "  select c_nationkey nk, c_custkey ck, count(o_orderkey) c_count"
    "  from customer left join orders on c_custkey = o_custkey"
    "  group by c_nationkey, c_custkey) top on n_nationkey = top.nk "
    "group by n_name order by n_name"
)


def rows_equal(a, b):
    return collections.Counter(map(repr, a)) == collections.Counter(
        map(repr, b))


@pytest.fixture(scope="module")
def single():
    return LocalRunner({"tpch": TpchConnector(SF)},
                       page_rows=PAGE_ROWS)


@pytest.fixture(scope="module")
def workers():
    w1 = WorkerServer({"tpch": TpchConnector(SF)}, node_id="w1",
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    w2 = WorkerServer({"tpch": TpchConnector(SF)}, node_id="w2",
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    uris = [f"http://127.0.0.1:{w1.start()}",
            f"http://127.0.0.1:{w2.start()}"]
    yield uris
    w1.stop()
    w2.stop()


def _make_coord(workers, listeners=(), **props):
    defaults = {"retry_backoff_ms": 20, "agg_gather_capacity": 64,
                "query_trace_enabled": "true"}
    defaults.update(props)
    return DcnRunner({"tpch": TpchConnector(SF)}, workers,
                     default_catalog="tpch", page_rows=PAGE_ROWS,
                     session_props=defaults, listeners=listeners)


def _post_fault(uri, **cfg):
    req = urllib.request.Request(
        f"{uri}/v1/fault", data=json.dumps(cfg).encode(),
        headers={"Content-Type": "application/json"})
    urllib.request.urlopen(req, timeout=5).close()


def _assert_chrome_valid(trace):
    ch = trace.to_chrome()
    events = ch["traceEvents"]
    assert events, "empty chrome trace"
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts), "chrome events not sorted by ts"
    for e in events:
        assert e["ph"] == "X"
        assert e["dur"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert e["name"]
    return ch


# ------------------------------------------------------ local tracing
def test_local_span_tree_shape(single):
    single.session.set("query_trace_enabled", True)
    try:
        res = single.execute(
            "select l_returnflag, count(*), sum(l_quantity) "
            "from lineitem group by l_returnflag")
    finally:
        single.session.unset("query_trace_enabled")
    assert len(res.rows) == 3
    tr = single.last_trace
    assert tr is not None
    kinds = collections.Counter(s.kind for s in tr.spans())
    assert kinds["query"] == 1
    assert kinds["execute"] >= 1
    assert kinds["attempt"] >= 1
    assert kinds["operator"] >= 3  # scan/agg/output at least
    # operator spans carry the EXPLAIN ANALYZE rows accounting
    ops = [s for s in tr.spans() if s.kind == "operator"]
    assert any(s.attrs.get("rows", 0) > 0 for s in ops)
    # the executor's registry counter saw the spans
    assert single.executor.trace_spans == tr.span_count
    # QueryInfo tree: one synthetic local stage, one task, its spans
    info = tr.to_info()
    assert [s["stageId"] for s in info["stages"]] == ["local"]
    task = info["stages"][0]["tasks"][0]
    assert task["state"] == "FINISHED"
    assert {sp["kind"] for sp in task["spans"]} >= {
        "attempt", "operator"}


def test_tracing_off_records_no_spans(single):
    # default: tracing off — the near-zero-cost contract is pinned by
    # the registry counter (no spans recorded anywhere this query)
    res = single.execute("select count(*) from nation")
    assert res.rows == [(25,)]
    assert single.last_trace is None
    assert single.executor.trace is None
    assert single.executor.trace_spans == 0
    from presto_tpu.exec.counters import QUERY_COUNTERS, snapshot

    assert "trace_spans" in QUERY_COUNTERS
    assert snapshot(single.executor)["trace_spans"] == 0


def test_chrome_trace_file_written_and_valid(single, tmp_path):
    single.session.set("query_trace_dir", str(tmp_path))
    try:
        single.execute("select max(o_totalprice) from orders")
    finally:
        single.session.unset("query_trace_dir")
    tr = single.last_trace
    assert tr is not None
    _assert_chrome_valid(tr)
    path = tmp_path / f"{tr.query_id}.trace.json"
    assert path.exists()
    with open(path) as f:
        data = json.load(f)
    assert data["traceEvents"]
    assert data["otherData"]["queryId"] == tr.query_id


def test_control_statements_write_no_trace(single, tmp_path):
    """SET SESSION / PREPARE never reach the executor: no junk trace
    file, and last_trace keeps the previous REAL query's timeline."""
    single.session.set("query_trace_dir", str(tmp_path))
    try:
        single.execute("select count(*) from region")
        real = single.last_trace
        assert real is not None
        n_files = len(list(tmp_path.iterdir()))
        single.execute("set session page_rows = 8192")
        single.execute("prepare p1 from select 1")
        assert single.last_trace is real, \
            "control statement clobbered the real query's trace"
        assert len(list(tmp_path.iterdir())) == n_files, \
            "control statement wrote a junk trace file"
    finally:
        single.session.unset("query_trace_dir")
        single.session.unset("page_rows")
        single.execute("deallocate prepare p1")


def test_unwritable_trace_dir_never_fails_query(single, tmp_path):
    """finalize() runs in the query's finally: an unwritable trace
    dir degrades to no file, never to a failed query."""
    blocker = tmp_path / "blocker"
    blocker.write_text("not a dir")
    single.session.set("query_trace_dir", str(blocker / "sub"))
    try:
        res = single.execute("select count(*) from region")
        assert res.rows == [(5,)]
        assert single.last_trace is not None  # traced, just unwritten
    finally:
        single.session.unset("query_trace_dir")


# ------------------------------------------------ distributed tracing
def test_distributed_dag_trace_three_stages(single, workers):
    """The acceptance shape: a distributed stage-DAG run records >= 3
    stage spans whose coordinator task spans contain the worker-side
    queue/run spans, nested consistently (clamped monotonic), and the
    Chrome export validates."""
    events = []
    from presto_tpu.events import EventListener

    class Rec(EventListener):
        def stage_completed(self, e):
            events.append(("stage", e))

        def task_completed(self, e):
            events.append(("task", e))

    coord = _make_coord(workers, listeners=[Rec()])
    try:
        want = single.execute(DAG_QUERY).rows
        got = coord.execute(DAG_QUERY)
        assert coord.last_distribution == "stage-dag"
        assert rows_equal(got, want)
        tr = coord.runner.last_trace
        assert tr is not None
        info = tr.to_info()
        stages = info["stages"]
        assert len(stages) >= 3, [s["stageId"] for s in stages]
        # every task span contains its worker-side spans (queue/run
        # shipped on the status plane, clamped into the coordinator
        # window — the cross-node nesting acceptance check)
        child_kinds = set()
        for st in stages:
            assert st["state"] == "FINISHED"
            for t in st["tasks"]:
                for sp in t["spans"]:
                    child_kinds.add(sp["kind"])
                    assert sp["startMs"] >= t["startMs"] - 1, (sp, t)
                    assert sp["endMs"] <= t["endMs"] + 1, (sp, t)
        assert {"dispatch", "queue", "run"} <= child_kinds, child_kinds
        # the coordinator's root-fragment drain + local execution spans
        all_kinds = {s.kind for s in tr.spans()}
        assert {"fetch", "execute", "attempt"} <= all_kinds
        _assert_chrome_valid(tr)
        # both workers appear in the timeline
        uris = {t.get("uri") for st in stages for t in st["tasks"]}
        assert set(workers) <= uris
        # EventListener SPI: every stage and task completion fired,
        # with worker-measured run walls on the task events
        stage_events = [e for k, e in events if k == "stage"]
        task_events = [e for k, e in events if k == "task"]
        assert len(stage_events) >= 3
        assert len(task_events) >= len(stage_events)
        assert any(e.run_ms > 0 for e in task_events)
        assert all(e.query_id == stage_events[0].query_id
                   for e in stage_events)
    finally:
        coord.close()


def test_legacy_cut_trace_ingests_worker_spans(single, workers):
    """The legacy (non-DAG) distributed cuts assemble a cross-node
    timeline too: dispatch/fetch on the coordinator plus the workers'
    shipped queue/run spans (fetched by one status poll per task)."""
    coord = _make_coord(workers, stage_scheduler="false")
    try:
        q = ("select l_returnflag, count(*), sum(l_quantity) "
             "from lineitem group by l_returnflag")
        got = coord.execute(q)
        assert coord.last_distribution in ("hash", "roundrobin")
        assert rows_equal(got, single.execute(q).rows)
        tr = coord.runner.last_trace
        kinds = collections.Counter(s.kind for s in tr.spans())
        assert kinds["dispatch"] == 2 and kinds["fetch"] == 2
        assert kinds["run"] >= 2, "worker spans not ingested"
        _assert_chrome_valid(tr)
    finally:
        coord.close()


def test_retry_span_under_submit_fault(single, workers):
    """Every submit to w2 is dropped (injected): initial dispatch
    recovers through _redispatch and the timeline carries the retry
    annotation (replay=False — the task never ran)."""
    coord = _make_coord(workers)
    _post_fault(workers[1], FAULT_SUBMIT_DROP_EVERY=1)
    try:
        want = single.execute(DAG_QUERY).rows
        got = coord.execute(DAG_QUERY)
        assert rows_equal(got, want)
        tr = coord.runner.last_trace
        retries = [s for s in tr.spans() if s.kind == "retry"]
        assert retries, "no retry span under injected submit fault"
        assert any(s.attrs.get("replay") is False for s in retries)
        assert all(s.attrs.get("cause") for s in retries)
    finally:
        _post_fault(workers[1])
        coord.close()


def test_speculate_span_under_straggler(single, workers):
    """A deterministic straggler (injected exec delay on w2) triggers
    speculation; the dispatched copy shows as a speculate span on the
    straggling task."""
    coord = _make_coord(workers, speculation_enabled=True)
    _post_fault(workers[1], FAULT_TASK_EXEC_DELAY_MS=4000)
    try:
        want = single.execute(DAG_QUERY).rows
        got = coord.execute(DAG_QUERY)
        assert rows_equal(got, want), "speculation duplicated rows"
        tr = coord.runner.last_trace
        specs = [s for s in tr.spans() if s.kind == "speculate"]
        assert specs, "no speculate span under injected straggler"
        assert coord.runner.executor.speculative_tasks_won > 0
    finally:
        _post_fault(workers[1])
        coord.close()


def test_listener_errors_counted_not_lost(single, workers):
    """A throwing listener never fails the query AND is no longer
    silent: every swallowed exception lands on the listener_errors
    registry counter."""
    from presto_tpu.events import EventListener

    class Bad(EventListener):
        def stage_completed(self, e):
            raise RuntimeError("boom")

        def task_completed(self, e):
            raise RuntimeError("boom")

    coord = _make_coord(workers, listeners=[Bad()])
    try:
        got = coord.execute(DAG_QUERY)
        assert rows_equal(got, single.execute(DAG_QUERY).rows)
        ex = coord.runner.executor
        assert ex.listener_errors > 0
        from presto_tpu.exec.counters import QUERY_COUNTERS, snapshot

        assert "listener_errors" in QUERY_COUNTERS
        assert snapshot(ex)["listener_errors"] == ex.listener_errors
    finally:
        coord.close()


# --------------------------------------------------- server surfaces
class _SlowTpch(TpchConnector):
    """Per-page sleep so a query is observably RUNNING while tests
    poll the live QueryInfo surface."""

    def page_for_split(self, split, columns=None):
        time.sleep(0.2)
        return super().page_for_split(split, columns)


@pytest.fixture(scope="module")
def server():
    from presto_tpu.server.http_server import PrestoTpuServer

    srv = PrestoTpuServer({"tpch": _SlowTpch(SF)}, port=0,
                          default_catalog="tpch",
                          page_rows=PAGE_ROWS)
    srv.start()
    yield srv
    srv.stop()


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def test_v1_query_live_then_final_and_runtime_tasks_agree(server):
    from presto_tpu.client import StatementClient

    base = f"http://127.0.0.1:{server.port}"
    c = StatementClient(server=base)
    # several slow pages -> seconds of RUNNING time to poll into
    res_holder = {}
    import threading

    def run():
        res_holder["res"] = c.execute(
            "select l_returnflag, count(*) from lineitem "
            "group by l_returnflag")

    t = threading.Thread(target=run)
    t.start()
    # live mid-query: poll until the tree shows a RUNNING task with
    # spans (the acceptance criterion: /v1/query/{id} serves the same
    # tree live)
    live = None
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        queries = _get_json(f"{base}/v1/query")
        running = [q for q in queries if q["state"] == "RUNNING"]
        if running:
            qi = _get_json(f"{base}/v1/query/{running[0]['queryId']}")
            if qi.get("stages") and qi["stages"][0]["tasks"]:
                live = qi
                break
        time.sleep(0.05)
    t.join(timeout=60)
    assert "res" in res_holder and res_holder["res"].error is None
    assert live is not None, "never observed a live QueryInfo tree"
    assert live["state"] == "RUNNING"
    assert live["stages"][0]["tasks"][0]["state"] == "RUNNING"
    qid = live["queryId"]
    # final tree: FINISHED with attempt/operator spans
    final = _get_json(f"{base}/v1/query/{qid}")
    assert final["state"] == "FINISHED"
    task = final["stages"][0]["tasks"][0]
    assert task["state"] == "FINISHED"
    assert {sp["kind"] for sp in task["spans"]} >= {"attempt",
                                                    "operator"}
    # system.runtime_tasks serves the SAME tree (agreement check)
    rows = c.execute(
        "select query_id, stage_id, task_id, state, wall_ms "
        "from system.runtime_tasks").rows
    mine = [r for r in rows if r[0] == qid]
    assert len(mine) == len(final["stages"][0]["tasks"])
    assert mine[0][1] == final["stages"][0]["stageId"]
    assert mine[0][2] == task["taskId"]
    assert mine[0][3] == "FINISHED"
    assert abs(int(mine[0][4]) - task["wallMs"]) < 5000


def test_metrics_histogram_exposition(server):
    from presto_tpu.client import StatementClient

    base = f"http://127.0.0.1:{server.port}"
    StatementClient(server=base).execute("select count(*) from nation")
    with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
        body = r.read().decode()
    for name in ("presto_tpu_query_latency_seconds",
                 "presto_tpu_stage_wall_seconds"):
        assert f"# TYPE {name} histogram" in body
        assert f'{name}_bucket{{le="+Inf"}}' in body
        assert f"{name}_sum" in body and f"{name}_count" in body
    # at least one completed query observed
    count_line = next(
        ln for ln in body.splitlines()
        if ln.startswith("presto_tpu_query_latency_seconds_count"))
    assert int(count_line.split()[-1]) >= 1
    # cumulative bucket monotonicity straight off the scrape
    buckets = [
        int(ln.split()[-1]) for ln in body.splitlines()
        if ln.startswith("presto_tpu_query_latency_seconds_bucket")
    ]
    assert buckets == sorted(buckets)


# ------------------------------------------------------ histogram math
def test_histogram_bucket_math():
    from presto_tpu.obs.histo import Histogram

    h = Histogram(bounds=(0.01, 0.1, 1.0))
    for v in (0.005, 0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    assert h.total == 5
    assert h.counts == [2, 1, 1, 1]  # <=10ms, <=100ms, <=1s, +Inf
    assert abs(h.sum - 5.56) < 1e-9
    # quantiles land in the right bucket
    assert h.quantile(0.3) <= 0.01
    assert 0.01 <= h.quantile(0.6) <= 0.1
    assert h.quantile(1.0) >= 1.0
    lines = h.prom_lines("x_seconds")
    assert lines[0] == "# TYPE x_seconds histogram"
    assert 'x_seconds_bucket{le="0.01"} 2' in lines
    assert 'x_seconds_bucket{le="0.1"} 3' in lines
    assert 'x_seconds_bucket{le="1"} 4' in lines
    assert 'x_seconds_bucket{le="+Inf"} 5' in lines
    assert "x_seconds_count 5" in lines


# ------------------------------------------------------ profile store
def test_profile_store_roundtrip(tmp_path, single):
    from presto_tpu.obs.profile import ProfileStore, plan_fingerprint

    store = ProfileStore(str(tmp_path))
    plan = single.plan("select count(*) from orders")
    key = store.key(plan, single.catalogs)
    assert store.lookup(key) is None
    store.record(key, {"capacity_boost": 4, "rows_out": 1})
    # fresh instance reads the persisted file (cross-process contract)
    store2 = ProfileStore(str(tmp_path))
    prof = store2.lookup(key)
    assert prof == {"capacity_boost": 4, "rows_out": 1}
    # fingerprints: stable across replans, sensitive to the plan and
    # to the connector snapshot (row counts)
    assert plan_fingerprint(plan, single.catalogs) == key
    plan2 = single.plan("select count(*) from orders")
    assert plan_fingerprint(plan2, single.catalogs) == key
    other = single.plan("select count(*) from customer")
    assert plan_fingerprint(other, single.catalogs) != key
    bigger = {"tpch": TpchConnector(0.02)}
    assert plan_fingerprint(plan, bigger) != key


def test_repeated_query_skips_boost_ladder(tmp_path):
    """THE acceptance contract: run 1 climbs the overflow-retry
    ladder (capacity_boost_retries > 0) and persists its settled
    bucket; run 2 — a fresh runner sharing only the profile dir —
    starts there and never boosts (capacity_boost_retries = 0,
    profile_store_hits >= 1), with identical rows."""
    q = ("select n_regionkey, array_agg(n_nationkey) from nation "
         "group by n_regionkey")

    def run():
        r = LocalRunner({"tpch": TpchConnector(SF)},
                        default_catalog="tpch", page_rows=PAGE_ROWS)
        r.session.set("stats_profile_dir", str(tmp_path))
        # 5 nations per region vs 2 slots: guaranteed first-run
        # collect-state overflow onto the boost ladder
        r.session.set("array_agg_max_elements", 2)
        rows = r.execute(q).rows
        ex = r.executor
        return (rows, ex.capacity_boost_retries,
                ex.profile_store_hits, ex._capacity_boost)

    rows1, retries1, hits1, boost1 = run()
    assert retries1 > 0 and boost1 > 1
    assert hits1 == 0
    rows2, retries2, hits2, boost2 = run()
    assert rows_equal(rows1, rows2)
    assert retries2 == 0, "second run climbed the ladder again"
    assert hits2 >= 1 and boost2 == boost1
    # counter-pinned through the registry
    from presto_tpu.exec.counters import QUERY_COUNTERS

    assert "capacity_boost_retries" in QUERY_COUNTERS
    assert "profile_store_hits" in QUERY_COUNTERS


# ------------------------------------------------------ lint coverage
def test_spans_lint_rule_clean_and_catches_seeded(tmp_path):
    from tools.lint import check_spans

    # the repo itself is clean (also covered by the full-lint gate)
    assert not check_spans()
    # a seeded undeclared kind is caught
    p = tmp_path / "seeded.py"
    p.write_text(textwrap.dedent("""
        def f(tr):
            tr.begin("bogus-kind", "x")
            tr.complete("also-bogus", "y", 0.0, 1.0)
    """))
    found = check_spans(paths=[str(p)])
    msgs = [f.message for f in found]
    assert any("bogus-kind" in m for m in msgs), msgs
    assert any("also-bogus" in m for m in msgs), msgs
    # every declared kind has an emission site (no stale entries) —
    # the reverse direction of the same registry discipline
    assert not [m for m in (str(f) for f in check_spans())
                if "stale" in m]


def test_span_ingest_clamps_skew():
    """The timing-source rule: remote spans re-base into the parent
    window and CLAMP — wall-clock skew can never produce a negative
    interval or a child escaping its parent."""
    tr = OBS.QueryTrace("q")
    parent = tr.begin("task", "t0")
    time.sleep(0.01)
    tr.end(parent)
    lo, hi = parent.t0, parent.t1
    n = tr.ingest([
        {"kind": "run", "name": "r", "t0": -5.0, "t1": 999.0},
        {"kind": "queue", "name": "k", "t0": 0.0, "t1": 0.001},
        {"kind": "junk"},  # malformed: dropped, not fatal
    ], parent, lo, hi)
    assert n == 2
    kids = [s for s in tr.spans()
            if s.parent_id == parent.span_id]
    for s in kids:
        assert lo <= s.t0 <= s.t1 <= hi
