"""Test harness configuration.

Mirrors the reference's test-ring strategy (SURVEY §5): all tests run on CPU
with a virtual 8-device mesh so distributed semantics are exercised without
TPU hardware (reference analog: DistributedQueryRunner boots a multi-node
cluster inside one JVM).

Env vars MUST be set before jax is imported anywhere.
"""

import os

# Arm the lock sanitizer (presto_tpu/obs/sanitizer.py) for the whole
# suite BEFORE any engine module creates a lock: every engine lock
# created under pytest is instrumented (held-set tracking, ordering,
# shared-attr write checks). Violations accumulate process-wide and
# never fail a test by themselves — tests/test_concurrent_serving.py
# races the serving path deliberately and asserts the count stays 0.
# Export PRESTO_TPU_LOCK_SANITIZER=0 to opt out.
os.environ.setdefault("PRESTO_TPU_LOCK_SANITIZER", "1")

# force CPU even if the ambient env targets a real TPU (axon tunnel)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# the axon sitecustomize imports jax at interpreter start, latching the
# platform before this file runs — override through the live config too
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compile cache for the whole suite: programs compile once
# per canonical shape per MACHINE, not per pytest process — repeated
# tier-1 runs pay the multi-minute compile wall (the dist suite's
# shard_map programs especially) only on the first cold run. The dir
# lives under /tmp so it survives across runs; point
# PRESTO_TPU_COMPILE_CACHE_DIR elsewhere (or at "") to move/disable.
from presto_tpu import compilecache as _cc  # noqa: E402

_cache_dir = os.environ.get(
    "PRESTO_TPU_COMPILE_CACHE_DIR", "/tmp/presto_tpu_compile_cache"
)
if _cache_dir:
    _cc.enable_persistent_cache(_cache_dir)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: drives the real TPU chip in a subprocess (opt-in via "
        "RUN_TPU_SMOKE=1)",
    )
    config.addinivalue_line(
        "markers",
        "slow: boots real OS processes / long compiles",
    )


@pytest.fixture
def rng():
    return np.random.default_rng(20260729)
