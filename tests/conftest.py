"""Test harness configuration.

Mirrors the reference's test-ring strategy (SURVEY §5): all tests run on CPU
with a virtual 8-device mesh so distributed semantics are exercised without
TPU hardware (reference analog: DistributedQueryRunner boots a multi-node
cluster inside one JVM).

Env vars MUST be set before jax is imported anywhere.
"""

import os

# force CPU even if the ambient env targets a real TPU (axon tunnel)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# the axon sitecustomize imports jax at interpreter start, latching the
# platform before this file runs — override through the live config too
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: drives the real TPU chip in a subprocess (opt-in via "
        "RUN_TPU_SMOKE=1)",
    )
    config.addinivalue_line(
        "markers",
        "slow: boots real OS processes / long compiles",
    )


@pytest.fixture
def rng():
    return np.random.default_rng(20260729)
