"""End-to-end single-device engine tests: hand-built physical plans for
TPC-H Q1/Q6/Q3-style pipelines validated against a sqlite oracle over the
same data (SURVEY §8.1 phase 3; BASELINE config 1 minimum slice).

Reference analog: presto-benchmark HandTpchQuery1 — a hand-wired operator
pipeline — checked the way presto-tests checks SQL against H2QueryRunner.
"""

import datetime

import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.connectors.tpch import DEC, TpchConnector
from presto_tpu.exec import (
    AggSpec,
    Aggregation,
    Executor,
    Filter,
    HashJoin,
    Limit,
    Output,
    Project,
    Sort,
    TableScan,
    TopN,
)
from presto_tpu.expr import ir
from presto_tpu.ops.sort import SortKey
from tests.oracle import load_sqlite, rows_match

EPOCH = datetime.date(1970, 1, 1)


def days(y, m, d):
    return (datetime.date(y, m, d) - EPOCH).days


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(0.005)


@pytest.fixture(scope="module")
def ex(conn):
    return Executor({"tpch": conn}, page_rows=1 << 14)


@pytest.fixture(scope="module")
def db(conn):
    return load_sqlite(
        conn, ["lineitem", "orders", "customer", "nation", "region"]
    )


def round_half_up(num: int, den: int) -> int:
    if den == 0:
        return 0
    sign = 1 if (num >= 0) == (den >= 0) else -1
    q, r = divmod(abs(num), abs(den))
    if 2 * r >= abs(den):
        q += 1
    return sign * q


class TestQ1:
    def plan(self):
        cutoff = days(1998, 12, 1) - 90
        scan = TableScan(
            "tpch", "lineitem",
            ("l_returnflag", "l_linestatus", "l_quantity",
             "l_extendedprice", "l_discount", "l_tax", "l_shipdate"),
        )
        filt = Filter(
            scan,
            ir.call("le", ir.input_ref(6, T.DATE), ir.const(cutoff, T.DATE)),
        )
        one = ir.const(100, DEC)
        ext = ir.input_ref(3, DEC)
        disc = ir.input_ref(4, DEC)
        tax = ir.input_ref(5, DEC)
        disc_price = ir.call("multiply", ext,
                             ir.call("subtract", one, disc))
        charge = ir.call("multiply", disc_price, ir.call("add", one, tax))
        proj = Project(
            filt,
            (
                ir.input_ref(0, T.VARCHAR), ir.input_ref(1, T.VARCHAR),
                ir.input_ref(2, DEC), ext, disc_price, charge, disc,
            ),
        )
        agg = Aggregation(
            proj,
            group_channels=(0, 1),
            aggregates=(
                AggSpec("sum", 2),      # sum_qty
                AggSpec("sum", 3),      # sum_base_price
                AggSpec("sum", 4),      # sum_disc_price
                AggSpec("sum", 5),      # sum_charge
                AggSpec("avg", 2),      # avg_qty
                AggSpec("avg", 3),      # avg_price
                AggSpec("avg", 6),      # avg_disc
                AggSpec("count_star", None),
            ),
            capacity=16,
        )
        sort = Sort(agg, (SortKey(0), SortKey(1)))
        return Output(sort, (
            "l_returnflag", "l_linestatus", "sum_qty", "sum_base_price",
            "sum_disc_price", "sum_charge", "avg_qty", "avg_price",
            "avg_disc", "count_order",
        ))

    def test_q1_vs_oracle(self, ex, db):
        cutoff = days(1998, 12, 1) - 90
        names, rows = ex.execute(self.plan())
        oracle = db.execute(
            f"""
            SELECT l_returnflag, l_linestatus,
                   SUM(l_quantity),
                   SUM(l_extendedprice),
                   SUM(l_extendedprice * (100 - l_discount)),
                   SUM(l_extendedprice * (100 - l_discount)
                       * (100 + l_tax)),
                   SUM(l_quantity), COUNT(*),
                   SUM(l_extendedprice),
                   SUM(l_discount)
            FROM lineitem WHERE l_shipdate <= {cutoff}
            GROUP BY 1, 2 ORDER BY 1, 2
            """
        ).fetchall()
        assert len(rows) == len(oracle) > 0
        expect = []
        for (rf, ls, sq, sbp, sdp, sc, sq2, cnt, sext, sdisc) in oracle:
            expect.append((
                rf, ls, sq, sbp, sdp, sc,
                round_half_up(sq, cnt),
                round_half_up(sext, cnt),
                round_half_up(sdisc, cnt),
                cnt,
            ))
        rows_match(rows, expect)


class TestQ6:
    def plan(self):
        lo, hi = days(1994, 1, 1), days(1995, 1, 1)
        scan = TableScan(
            "tpch", "lineitem",
            ("l_shipdate", "l_discount", "l_quantity", "l_extendedprice"),
        )
        pred = ir.and_(
            ir.call("ge", ir.input_ref(0, T.DATE), ir.const(lo, T.DATE)),
            ir.call("lt", ir.input_ref(0, T.DATE), ir.const(hi, T.DATE)),
            ir.between(ir.input_ref(1, DEC), ir.const(5, DEC),
                       ir.const(7, DEC)),
            ir.call("lt", ir.input_ref(2, DEC), ir.const(2400, DEC)),
        )
        filt = Filter(scan, pred)
        revenue = ir.call("multiply", ir.input_ref(3, DEC),
                          ir.input_ref(1, DEC))
        proj = Project(filt, (revenue,))
        agg = Aggregation(proj, (), (AggSpec("sum", 0),))
        return Output(agg, ("revenue",))

    def test_q6_vs_oracle(self, ex, db):
        lo, hi = days(1994, 1, 1), days(1995, 1, 1)
        names, rows = ex.execute(self.plan())
        (expect,) = db.execute(
            f"""
            SELECT SUM(l_extendedprice * l_discount) FROM lineitem
            WHERE l_shipdate >= {lo} AND l_shipdate < {hi}
              AND l_discount BETWEEN 5 AND 7 AND l_quantity < 2400
            """
        ).fetchone()
        assert len(rows) == 1
        assert rows[0][0] == expect


class TestQ3:
    def plan(self):
        cutoff = days(1995, 3, 15)
        cust = Filter(
            TableScan("tpch", "customer", ("c_custkey", "c_mktsegment")),
            ir.call("eq", ir.input_ref(1, T.VARCHAR),
                    ir.const("BUILDING", T.VARCHAR)),
        )
        orders = Filter(
            TableScan("tpch", "orders",
                      ("o_orderkey", "o_custkey", "o_orderdate",
                       "o_shippriority")),
            ir.call("lt", ir.input_ref(2, T.DATE),
                    ir.const(cutoff, T.DATE)),
        )
        # orders ⋈ customer on custkey (customer is the small build side)
        j1 = HashJoin(orders, cust, (1,), (0,))
        # channels: o_orderkey, o_custkey, o_orderdate, o_shippriority,
        #           c_custkey, c_mktsegment
        line = Filter(
            TableScan("tpch", "lineitem",
                      ("l_orderkey", "l_extendedprice", "l_discount",
                       "l_shipdate")),
            ir.call("gt", ir.input_ref(3, T.DATE),
                    ir.const(cutoff, T.DATE)),
        )
        j2 = HashJoin(line, j1, (0,), (0,))
        # channels: l_orderkey, l_extendedprice, l_discount, l_shipdate,
        #           o_orderkey, o_custkey, o_orderdate, o_shippriority, ...
        one = ir.const(100, DEC)
        revenue = ir.call(
            "multiply", ir.input_ref(1, DEC),
            ir.call("subtract", one, ir.input_ref(2, DEC)),
        )
        proj = Project(
            j2,
            (ir.input_ref(0, T.BIGINT), revenue,
             ir.input_ref(6, T.DATE), ir.input_ref(7, T.INTEGER)),
        )
        agg = Aggregation(
            proj, (0, 2, 3), (AggSpec("sum", 1),), capacity=1 << 14
        )
        # reorder to Q3 output: l_orderkey, revenue, o_orderdate,
        # o_shippriority (agg output is okey, odate, ship, sum)
        out = Project(
            agg,
            (ir.input_ref(0, T.BIGINT),
             ir.input_ref(3, T.DecimalType(38, 4)),
             ir.input_ref(1, T.DATE), ir.input_ref(2, T.INTEGER)),
        )
        topn = TopN(
            out,
            (SortKey(1, ascending=False), SortKey(2)),
            limit=10,
        )
        return Output(topn, ("l_orderkey", "revenue", "o_orderdate",
                             "o_shippriority"))

    def test_q3_vs_oracle(self, ex, db):
        cutoff = days(1995, 3, 15)
        names, rows = ex.execute(self.plan())
        oracle = db.execute(
            f"""
            SELECT l_orderkey,
                   SUM(l_extendedprice * (100 - l_discount)) AS revenue,
                   o_orderdate, o_shippriority
            FROM customer, orders, lineitem
            WHERE c_mktsegment = 'BUILDING'
              AND c_custkey = o_custkey AND l_orderkey = o_orderkey
              AND o_orderdate < {cutoff} AND l_shipdate > {cutoff}
            GROUP BY l_orderkey, o_orderdate, o_shippriority
            ORDER BY revenue DESC, o_orderdate LIMIT 10
            """
        ).fetchall()
        # ties on (revenue, orderdate) make trailing rows ambiguous; compare
        # as sets of tuples (the engine and sqlite may break ties apart)
        assert len(rows) == len(oracle)
        assert set(map(tuple, rows)) == set(map(tuple, oracle)) or [
            r[1] for r in rows
        ] == [r[1] for r in oracle]


class TestJoinTypes:
    def test_left_join_emits_unmatched(self, ex, db, conn):
        orders = TableScan("tpch", "orders", ("o_orderkey", "o_custkey"))
        cust = Filter(
            TableScan("tpch", "customer", ("c_custkey", "c_acctbal")),
            ir.call("gt", ir.input_ref(1, DEC), ir.const(900_000, DEC)),
        )
        j = HashJoin(orders, cust, (1,), (0,), join_type="left")
        agg = Aggregation(
            j, (),
            (AggSpec("count_star", None), AggSpec("count", 2)),
        )
        _, rows = ex.execute(Output(agg, ("n", "matched")))
        (n, matched) = rows[0]
        (on,) = db.execute("SELECT COUNT(*) FROM orders").fetchone()
        (om,) = db.execute(
            """SELECT COUNT(*) FROM orders JOIN customer
               ON c_custkey = o_custkey WHERE c_acctbal > 900000"""
        ).fetchone()
        assert n == on  # every order survives a left join on its customer
        assert matched == om

    def test_semi_join_filter(self, ex, db):
        nation = Filter(
            TableScan("tpch", "nation", ("n_nationkey", "n_regionkey")),
            ir.call("eq", ir.input_ref(1, T.BIGINT),
                    ir.const(3, T.BIGINT)),  # EUROPE
        )
        cust = TableScan("tpch", "customer", ("c_custkey", "c_nationkey"))
        semi = HashJoin(cust, nation, (1,), (0,), join_type="semi")
        filt = Filter(semi, ir.input_ref(2, T.BOOLEAN))
        agg = Aggregation(filt, (), (AggSpec("count_star", None),))
        _, rows = ex.execute(Output(agg, ("n",)))
        (expect,) = db.execute(
            """SELECT COUNT(*) FROM customer WHERE c_nationkey IN
               (SELECT n_nationkey FROM nation WHERE n_regionkey = 3)"""
        ).fetchone()
        assert rows[0][0] == expect


class TestDictionaryAggregates:
    def test_min_max_over_varchar_uses_value_order(self, ex, db):
        """min/max over a dictionary column must compare values, not codes
        (l_returnflag dictionary is ['A','R','N'] — code order != value
        order)."""
        scan = TableScan("tpch", "lineitem",
                         ("l_linestatus", "l_returnflag"))
        agg = Aggregation(
            scan, (0,),
            (AggSpec("min", 1), AggSpec("max", 1)),
            capacity=8,
        )
        sort = Sort(agg, (SortKey(0),))
        _, rows = ex.execute(Output(sort, ("ls", "min_rf", "max_rf")))
        oracle = db.execute(
            """SELECT l_linestatus, MIN(l_returnflag), MAX(l_returnflag)
               FROM lineitem GROUP BY 1 ORDER BY 1"""
        ).fetchall()
        rows_match(rows, [tuple(r) for r in oracle])

    def test_global_min_max_varchar(self, ex, db):
        scan = TableScan("tpch", "orders", ("o_orderpriority",))
        agg = Aggregation(
            scan, (), (AggSpec("min", 0), AggSpec("max", 0))
        )
        _, rows = ex.execute(Output(agg, ("lo", "hi")))
        oracle = db.execute(
            "SELECT MIN(o_orderpriority), MAX(o_orderpriority) FROM orders"
        ).fetchone()
        assert rows[0] == tuple(oracle)


class TestLimitsAndSort:
    def test_limit_streaming(self, ex):
        scan = TableScan("tpch", "orders", ("o_orderkey",))
        _, rows = ex.execute(Output(Limit(scan, 17), ("k",)))
        assert len(rows) == 17

    def test_order_by_desc_with_topn_equivalence(self, ex, db):
        scan = TableScan("tpch", "orders", ("o_orderkey", "o_totalprice"))
        topn = TopN(scan, (SortKey(1, ascending=False), SortKey(0)), 5)
        _, rows = ex.execute(Output(topn, ("k", "p")))
        oracle = db.execute(
            """SELECT o_orderkey, o_totalprice FROM orders
               ORDER BY o_totalprice DESC, o_orderkey LIMIT 5"""
        ).fetchall()
        rows_match(rows, [tuple(r) for r in oracle])
