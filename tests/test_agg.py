"""Group-by kernel oracle tests vs pure-numpy/python aggregation (reference
analog: presto-main TestGroupByHash, TestHashAggregationOperator)."""

import jax.numpy as jnp
import numpy as np

from presto_tpu.ops import agg as A
from presto_tpu.ops import keys as K
from presto_tpu import BIGINT, DOUBLE
from presto_tpu.page import Page


def _oracle_groupby(keys_rows, vals, valid):
    """dict: key tuple -> list of (val, isnull) contributing rows."""
    groups = {}
    for i, ok in enumerate(valid):
        if not ok:
            continue
        k = tuple(keys_rows[c][i] for c in range(len(keys_rows)))
        groups.setdefault(k, []).append(vals[i])
    return groups


def test_sorted_groupby_sum_count_min_max(rng):
    n = 200
    cap_out = 64
    k1 = rng.integers(0, 7, size=n)
    k2 = rng.integers(0, 3, size=n)
    v = rng.normal(size=n).round(3)
    vnull = rng.random(n) < 0.2
    valid = rng.random(n) < 0.85

    groups = A.compute_groups_sorted(
        [jnp.asarray(k1).astype(jnp.uint64), jnp.asarray(k2).astype(jnp.uint64)],
        [None, None],
        jnp.asarray(valid),
        cap_out,
    )
    s, s_null = A.aggregate(
        groups, A.SUM, cap_out, jnp.asarray(v), jnp.asarray(vnull)
    )
    c, _ = A.aggregate(
        groups, A.COUNT, cap_out, jnp.asarray(v), jnp.asarray(vnull)
    )
    cs, _ = A.aggregate(groups, A.COUNT_STAR, cap_out)
    mn, mn_null = A.aggregate(
        groups, A.MIN, cap_out, jnp.asarray(v), jnp.asarray(vnull)
    )
    mx, _ = A.aggregate(
        groups, A.MAX, cap_out, jnp.asarray(v), jnp.asarray(vnull)
    )

    oracle = {}
    for i in range(n):
        if not valid[i]:
            continue
        oracle.setdefault((k1[i], k2[i]), []).append(
            (v[i], vnull[i])
        )
    assert int(groups.num_groups) == len(oracle)
    assert not bool(groups.overflow)

    # map each output group to its key via representative row
    rep = np.asarray(groups.rep_index)
    gvalid = np.asarray(groups.group_valid)
    got = {}
    for g in range(cap_out):
        if not gvalid[g]:
            continue
        key = (k1[rep[g]], k2[rep[g]])
        got[key] = dict(
            sum=(float(s[g]), bool(s_null[g])),
            count=int(c[g]),
            count_star=int(cs[g]),
            min=(float(mn[g]), bool(mn_null[g])),
            max=float(mx[g]),
        )
    assert set(got) == set(oracle)
    for key, rows in oracle.items():
        nn = [x for x, isn in rows if not isn]
        g = got[key]
        assert g["count"] == len(nn)
        assert g["count_star"] == len(rows)
        if nn:
            assert not g["sum"][1]
            np.testing.assert_allclose(g["sum"][0], sum(nn), rtol=1e-9)
            np.testing.assert_allclose(g["min"][0], min(nn))
            np.testing.assert_allclose(g["max"], max(nn))
        else:
            assert g["sum"][1] and g["min"][1]


def test_groupby_nulls_form_own_group():
    k = jnp.asarray([1, 1, 2, 0], dtype=jnp.uint64)
    knull = jnp.asarray([False, False, False, True])
    valid = jnp.ones(4, dtype=bool)
    groups = A.compute_groups_sorted([k], [knull], valid, 8)
    assert int(groups.num_groups) == 3  # {1}, {2}, {NULL}


def test_groupby_overflow_flag():
    k = jnp.arange(16, dtype=jnp.uint64)
    valid = jnp.ones(16, dtype=bool)
    groups = A.compute_groups_sorted([k], [None], valid, 4)
    assert bool(groups.overflow)


def test_dense_groupby_matches_sorted(rng):
    n = 128
    codes = rng.integers(0, 6, size=n)
    v = rng.integers(0, 100, size=n).astype(np.int64)
    valid = rng.random(n) < 0.9

    dense = A.compute_groups_dense(jnp.asarray(codes), jnp.asarray(valid), 6)
    s_dense, _ = A.aggregate(dense, A.SUM, 6, jnp.asarray(v))

    srt = A.compute_groups_sorted(
        [jnp.asarray(codes).astype(jnp.uint64)], [None], jnp.asarray(valid), 8
    )
    s_sorted, _ = A.aggregate(srt, A.SUM, 8, jnp.asarray(v))

    # dense output indexed by code; sorted output ordered by key value
    oracle = {}
    for i in range(n):
        if valid[i]:
            oracle[codes[i]] = oracle.get(codes[i], 0) + int(v[i])
    for code, total in oracle.items():
        assert int(s_dense[code]) == total
    present = sorted(oracle)
    for g, code in enumerate(present):
        assert int(s_sorted[g]) == oracle[code]


def test_global_aggregate_empty_input():
    data = jnp.asarray([1.0, 2.0])
    valid = jnp.asarray([False, False])
    s, s_null = A.global_aggregate(A.SUM, valid, data)
    c, _ = A.global_aggregate(A.COUNT_STAR, valid)
    assert bool(s_null) and int(c) == 0


def test_key_encoding_through_blocks(rng):
    """block_key_columns + groupby on a real Page with doubles (float keys
    must group -0.0 with 0.0 and NaN with NaN)."""
    vals = [0.0, -0.0, float("nan"), float("nan"), 1.5, 1.5, None]
    page = Page.from_arrays([vals, [1] * 7], [DOUBLE, BIGINT])
    cols, nulls = K.block_key_columns([page.block(0)])
    groups = A.compute_groups_sorted(cols, nulls, page.valid, 8)
    # groups: {0.0}, {nan}, {1.5}, {NULL}
    assert int(groups.num_groups) == 4


def test_decimal_avg_finalize_huge_group_no_overflow():
    """ADVICE r1 low #1: avg finalize must fold lo's high half into the
    2^32-weighted dividend — a ~2^31-row group's lo segment-sum otherwise
    overflows i64 in (rh << 32) + lo."""
    import jax.numpy as jnp
    from presto_tpu import types as T
    from presto_tpu.exec import agg_states as S

    n = 1 << 31  # rows in the group
    value = 123_456  # unscaled decimal(12,2) cents, same every row
    total = n * value
    # states as _partial/_final produce them: sums of v>>32 and v&0xFFFFFFFF
    hi = jnp.asarray([(value >> 32) * n], jnp.int64)
    lo = jnp.asarray([(value & 0xFFFFFFFF) * n], jnp.int64)
    cnt = jnp.asarray([n], jnp.int64)
    blk = S.finalize(
        "avg", T.DecimalType(12, 2), T.DecimalType(12, 2),
        [(hi, None), (lo, None), (cnt, None)],
    )
    expected = (total + n // 2) // n  # round-half-up
    assert int(blk.data[0]) == expected == value


class TestHashedGroupby:
    """compute_groups_hashed (the vectorized linear-probing GroupByHash that
    replaces the multi-operand lexsort on TPU) vs the sorted oracle."""

    def test_matches_sorted_randomized(self, rng):
        for trial in range(5):
            n = 257
            cap = 256
            k1 = rng.integers(0, 23, size=n).astype(np.uint64)
            k2 = rng.integers(0, 5, size=n).astype(np.uint64)
            k2n = rng.random(n) < 0.3
            v = rng.integers(0, 1000, size=n).astype(np.int64)
            valid = rng.random(n) < 0.85
            cols = [jnp.asarray(k1), jnp.asarray(k2)]
            nulls = [None, jnp.asarray(k2n)]
            hashed = A.compute_groups_hashed(cols, nulls, jnp.asarray(valid), cap)
            srt = A.compute_groups_sorted(cols, nulls, jnp.asarray(valid), cap)
            assert not bool(hashed.overflow)
            assert int(hashed.num_groups) == int(srt.num_groups)
            sh, shn = A.aggregate(hashed, A.SUM, cap, jnp.asarray(v))
            # map group -> (key, sum) via representative rows; compare as sets
            def results(groups, s):
                rep = np.asarray(groups.rep_index)
                gv = np.asarray(groups.group_valid)
                out = {}
                for g in range(cap):
                    if gv[g]:
                        r = rep[g]
                        key = (int(k1[r]), None if k2n[r] else int(k2[r]))
                        out[key] = int(s[g])
                return out
            ss, _ = A.aggregate(srt, A.SUM, cap, jnp.asarray(v))
            assert results(hashed, sh) == results(srt, ss)

    def test_nulls_form_own_group(self):
        k = jnp.asarray([1, 1, 2, 0], dtype=jnp.uint64)
        knull = jnp.asarray([False, False, False, True])
        valid = jnp.ones(4, dtype=bool)
        groups = A.compute_groups_hashed([k], [knull], valid, 8)
        assert int(groups.num_groups) == 3

    def test_overflow_flag(self):
        k = jnp.arange(64, dtype=jnp.uint64)
        valid = jnp.ones(64, dtype=bool)
        groups = A.compute_groups_hashed([k], [None], valid, 4)
        assert bool(groups.overflow)

    def test_adversarial_equal_hashes(self):
        # all rows share one key -> one group regardless of probing dynamics
        k = jnp.zeros(100, dtype=jnp.uint64)
        valid = jnp.ones(100, dtype=bool)
        groups = A.compute_groups_hashed([k], [None], valid, 8)
        assert int(groups.num_groups) == 1
        assert not bool(groups.overflow)

    def test_deterministic(self, rng):
        k = jnp.asarray(rng.integers(0, 50, size=500).astype(np.uint64))
        valid = jnp.ones(500, dtype=bool)
        a = A.compute_groups_hashed([k], [None], valid, 64)
        b = A.compute_groups_hashed([k], [None], valid, 64)
        assert np.array_equal(np.asarray(a.group_ids), np.asarray(b.group_ids))
        assert np.array_equal(np.asarray(a.rep_index), np.asarray(b.rep_index))


def test_matmul_agg_parity(monkeypatch):
    # force the one-hot matmul path on tiny CPU shapes and compare
    # against the scatter path (identical exact semantics required)
    import importlib

    import numpy as np

    from presto_tpu.ops import agg as A

    rng = np.random.default_rng(7)
    n, G = 512, 37
    gids = jnp.asarray(rng.integers(0, G, n))
    valid = jnp.asarray(rng.random(n) < 0.9)
    data = jnp.asarray(
        rng.integers(-(2**40), 2**40, n).astype(np.int64))
    nulls = jnp.asarray(rng.random(n) < 0.2)
    groups = A.GroupbyResult(
        group_ids=gids.astype(jnp.int64), row_valid=valid,
        rep_index=jnp.zeros((G,), jnp.int64),
        group_valid=jnp.ones((G,), bool),
        num_groups=jnp.asarray(G), overflow=jnp.asarray(False),
    )
    results = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("PRESTO_TPU_MM_AGG", flag)
        A._MM_BACKEND = None
        out = {}
        for kind in (A.SUM, A.COUNT, A.COUNT_STAR):
            vals, onulls = A.aggregate(
                groups, kind, G,
                None if kind == A.COUNT_STAR else data,
                None if kind == A.COUNT_STAR else nulls,
            )
            out[kind] = (np.asarray(vals),
                         None if onulls is None else np.asarray(onulls))
        bd = jnp.asarray(rng.random(n) < 0.5)
        for kind in (A.BOOL_OR, A.BOOL_AND):
            vals, onulls = A.aggregate(groups, kind, G, bd, nulls)
            out[kind] = (np.asarray(vals), np.asarray(onulls))
        results[flag] = out
    A._MM_BACKEND = None
    for kind in results["0"]:
        v0, n0 = results["0"][kind]
        v1, n1 = results["1"][kind]
        assert (v0 == v1).all(), kind
        if n0 is not None:
            assert (n0 == n1).all(), kind
