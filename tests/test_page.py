"""Page/Block model round-trip tests (reference analog: presto-spi
TestPage / block tests via BlockAssertions)."""

import jax
import numpy as np
import pytest

from presto_tpu import BIGINT, BOOLEAN, DOUBLE, DecimalType, VarcharType
from presto_tpu.page import Dictionary, Page


def test_roundtrip_simple():
    page = Page.from_arrays(
        [[1, 2, 3], [1.5, None, 2.5], [True, False, None]],
        [BIGINT, DOUBLE, BOOLEAN],
    )
    assert page.capacity >= 3
    assert int(page.num_rows()) == 3
    assert page.to_pylist() == [
        (1, 1.5, True),
        (2, None, False),
        (3, 2.5, None),
    ]


def test_varchar_dictionary_roundtrip():
    page = Page.from_arrays(
        [["apple", "banana", None, "apple"]],
        [VarcharType()],
    )
    blk = page.block(0)
    assert blk.dictionary is not None
    assert page.to_pylist() == [("apple",), ("banana",), (None,), ("apple",)]


def test_long_decimal_roundtrip():
    t = DecimalType(38, 2)
    vals = [10**25 + 7, -(10**30), None, 42]
    page = Page.from_arrays([vals], [t])
    assert page.to_pylist() == [(v,) for v in vals]


def test_page_is_pytree():
    page = Page.from_arrays([[1, 2], ["a", None]], [BIGINT, VarcharType()])
    leaves = jax.tree_util.tree_leaves(page)
    assert len(leaves) >= 3  # two data arrays + valid (+ nulls)
    page2 = jax.tree_util.tree_map(lambda x: x, page)
    assert page2.to_pylist() == page.to_pylist()
    # static aux (types, dictionaries) survive a tree round trip
    assert page2.block(1).dictionary == page.block(1).dictionary


def test_jit_through_page():
    page = Page.from_arrays([[1, 2, 3, 4]], [BIGINT])

    @jax.jit
    def double_it(p: Page) -> Page:
        blk = p.block(0)
        return p.with_blocks([blk.with_data(blk.data * 2)])

    out = double_it(page)
    assert out.to_pylist() == [(2,), (4,), (6,), (8,)]


def test_dictionary_equality_and_hash():
    d1 = Dictionary(["x", "y"])
    d2 = Dictionary(["x", "y"])
    d3 = Dictionary(["x", "z"])
    assert d1 == d2 and hash(d1) == hash(d2)
    assert d1 != d3
    assert d1.code_of("y") == 1
    assert d1.code_of("nope") == -1


def test_capacity_padding_and_masks():
    page = Page.from_arrays([list(range(5))], [BIGINT], capacity=16)
    assert page.capacity == 16
    assert int(page.num_rows()) == 5
    np.testing.assert_array_equal(
        np.asarray(page.valid), [True] * 5 + [False] * 11
    )


def test_overflow_capacity_raises():
    with pytest.raises(ValueError):
        Page.from_arrays([[1, 2, 3]], [BIGINT], capacity=2)


def test_value_missing_from_supplied_dictionary_raises():
    with pytest.raises(ValueError, match="not in supplied dictionary"):
        Page.from_arrays(
            [["a", "x"]],
            [VarcharType()],
            dictionaries=[Dictionary(["a", "b"])],
        )
