"""Plan-fragment serde: the DCN plan-shipping wire format.

Reference: TaskUpdateRequest's serialized PlanFragment round-trips
through jackson JSON; here every physical plan is a frozen-dataclass
tree, so serialized->deserialized equality is exact (==), which these
tests assert over the full TPC-H suite plus breadth shapes (windows,
grouping sets, unnest, lambdas, decimals, IN-lists).
"""

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.dist import plan_serde
from presto_tpu.runner import LocalRunner
from tests.tpch_queries import QUERIES


@pytest.fixture(scope="module")
def runner():
    return LocalRunner({"tpch": TpchConnector(0.001)},
                       default_catalog="tpch")


@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_tpch_plan_roundtrip(runner, qid):
    plan = runner.plan(QUERIES[qid])
    again = plan_serde.loads(plan_serde.dumps(plan))
    assert again == plan


@pytest.mark.parametrize("sql", [
    # window frames + ranking
    "select o_custkey, rank() over (partition by o_custkey "
    "order by o_totalprice desc) from orders",
    "select o_custkey, sum(o_totalprice) over (order by o_orderdate "
    "rows between 2 preceding and current row) from orders",
    # grouping sets -> GroupId node
    "select o_orderstatus, o_orderpriority, count(*) from orders "
    "group by rollup(o_orderstatus, o_orderpriority)",
    # unnest + array constructor
    "select x from unnest(array[1, 2, 3]) as t(x)",
    # lambdas (higher-order IR: Lambda/ParamRef nodes)
    "select transform(array[1, 2], x -> x + 1)",
    # decimals, IN lists, BETWEEN, CASE
    "select case when o_totalprice between 100 and 200 then 'mid' "
    "else 'other' end from orders where o_orderkey in (1, 2, 3)",
    # semi join (EXISTS decorrelation)
    "select c_name from customer where exists "
    "(select 1 from orders where o_custkey = c_custkey)",
])
def test_breadth_plan_roundtrip(runner, sql):
    plan = runner.plan(sql)
    again = plan_serde.loads(plan_serde.dumps(plan))
    assert again == plan


def test_unknown_class_is_loud():
    with pytest.raises(TypeError, match="unknown plan class"):
        plan_serde.from_obj({"$c": "NoSuchNode"})


def test_scalar_edge_values():
    import decimal
    import math

    vals = (b"\x00\xffbytes", decimal.Decimal("1.25"),
            float("nan"), float("inf"), float("-inf"), None,
            True, 0, -1, "s", 1.5)
    out = plan_serde.loads(plan_serde.dumps(vals))
    assert out[0] == vals[0] and out[1] == vals[1]
    assert math.isnan(out[2]) and out[3] == math.inf
    assert out[4] == -math.inf and out[5:] == vals[5:]
