"""Window function correctness vs the sqlite3 oracle (sqlite >= 3.25 has
full window support).

Reference test analog: presto-main operator/TestWindowOperator +
AbstractTestQueries window cases (SURVEY §3.2 WindowOperator -> segmented
scans)."""

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runner import LocalRunner
from tests.oracle import load_sqlite

SF = 0.005


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(SF)


@pytest.fixture(scope="module")
def runner(conn):
    return LocalRunner({"tpch": conn}, page_rows=1 << 13)


@pytest.fixture(scope="module")
def db(conn):
    return load_sqlite(conn, ["nation", "orders", "customer"])


CASES = [
    # ranking trio with partitions and ordering
    """select n_regionkey, n_name,
              row_number() over (partition by n_regionkey order by n_name),
              rank() over (partition by n_regionkey order by n_nationkey),
              dense_rank() over (partition by n_regionkey order by n_nationkey)
       from nation order by n_regionkey, n_name""",
    # rank with ties (duplicate order keys)
    """select o_custkey, o_orderkey,
              rank() over (partition by o_custkey order by o_orderdate),
              dense_rank() over (partition by o_custkey order by o_orderdate),
              row_number() over (partition by o_custkey order by o_orderdate, o_orderkey)
       from orders order by o_custkey, o_orderkey limit 200""",
    # whole-partition aggregates (no order by in the frame)
    """select n_regionkey, n_nationkey,
              count(*) over (partition by n_regionkey),
              sum(n_nationkey) over (partition by n_regionkey),
              min(n_name) over (partition by n_regionkey),
              max(n_name) over (partition by n_regionkey)
       from nation order by n_nationkey""",
    # running aggregates (range frame with peers)
    """select o_custkey, o_orderkey,
              sum(o_totalprice) over (partition by o_custkey order by o_orderdate),
              count(*) over (partition by o_custkey order by o_orderdate),
              min(o_totalprice) over (partition by o_custkey order by o_orderdate),
              max(o_totalprice) over (partition by o_custkey order by o_orderdate)
       from orders order by o_custkey, o_orderkey limit 200""",
    # navigation functions
    """select o_custkey, o_orderkey,
              lag(o_orderkey) over (partition by o_custkey order by o_orderdate, o_orderkey),
              lead(o_orderkey) over (partition by o_custkey order by o_orderdate, o_orderkey),
              lag(o_orderkey, 2) over (partition by o_custkey order by o_orderdate, o_orderkey),
              first_value(o_orderkey) over (partition by o_custkey order by o_orderdate, o_orderkey)
       from orders order by o_custkey, o_orderkey limit 200""",
    # global window (no partition)
    """select n_name, rank() over (order by n_regionkey),
              sum(n_nationkey) over (order by n_regionkey)
       from nation order by n_name""",
    # window + where + expression args
    """select o_orderkey,
              sum(o_totalprice) over (partition by o_orderpriority
                                      order by o_orderkey)
       from orders where o_custkey % 5 = 0
       order by o_orderkey limit 100""",
    # distribution + ntile (round 3: VERDICT r2 weak-8)
    """select o_custkey, o_orderkey,
              ntile(4) over (partition by o_custkey order by o_orderkey),
              percent_rank() over (partition by o_custkey
                                   order by o_orderdate),
              cume_dist() over (partition by o_custkey
                                order by o_orderdate)
       from orders order by o_custkey, o_orderkey limit 200""",
    # explicit ROWS frames: prefix, sliding, empty-capable, suffix
    """select o_custkey, o_orderkey,
              sum(o_totalprice) over (partition by o_custkey
                  order by o_orderkey
                  rows between 2 preceding and current row),
              min(o_totalprice) over (partition by o_custkey
                  order by o_orderkey
                  rows between 1 preceding and 1 following),
              max(o_totalprice) over (partition by o_custkey
                  order by o_orderkey
                  rows between 3 preceding and 1 preceding),
              count(*) over (partition by o_custkey order by o_orderkey
                  rows between current row and unbounded following)
       from orders order by o_custkey, o_orderkey limit 200""",
    # nth_value + last_value over the whole partition (RANGE frame)
    """select o_custkey, o_orderkey,
              nth_value(o_orderkey, 2) over (partition by o_custkey
                  order by o_orderkey
                  rows between unbounded preceding
                           and unbounded following),
              last_value(o_orderkey) over (partition by o_custkey
                  order by o_orderdate
                  range between unbounded preceding
                            and unbounded following)
       from orders order by o_custkey, o_orderkey limit 200""",
]


@pytest.mark.parametrize("case", range(len(CASES)))
def test_window_vs_sqlite(case, runner, db):
    sql = CASES[case]
    got = runner.execute(sql).rows
    want = [tuple(r) for r in db.execute(sql).fetchall()]
    assert len(got) == len(want), (len(got), len(want))
    for i, (g, w) in enumerate(zip(got, want)):
        assert g == w, f"case {case} row {i}: {g} != {w}"


def test_window_then_filter_subquery(runner, db):
    sql = """select * from (
               select n_name, n_regionkey,
                      row_number() over (partition by n_regionkey
                                         order by n_name) rn
               from nation) t
             where rn = 1 order by n_regionkey"""
    got = runner.execute(sql).rows
    want = [tuple(r) for r in db.execute(sql).fetchall()]
    assert got == want


def test_window_over_aggregate_subquery(runner, db):
    # windows over aggregated results via nesting (the supported spelling)
    sql = """select o_custkey, total,
                    rank() over (order by total desc, o_custkey)
             from (select o_custkey, sum(o_totalprice) total
                   from orders group by o_custkey) t
             order by total desc, o_custkey limit 50"""
    got = runner.execute(sql).rows
    want = [tuple(r) for r in db.execute(sql).fetchall()]
    assert got == want


def test_window_with_aggregate_same_block_raises(runner):
    from presto_tpu.sql.planner import PlanningError

    with pytest.raises(Exception):
        runner.execute(
            "select rank() over (order by sum(n_nationkey)) "
            "from nation group by n_regionkey"
        )
