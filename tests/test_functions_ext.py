"""Round-4 function-breadth batch: JSON family, TRY/TRY_CAST, bitwise,
URL, array/map utilities, and higher-order lambdas
(transform/filter/reduce/...), SQL-level against Python expectations.

Reference test pattern: presto-main operator/scalar/TestJsonFunctions,
TestUrlFunctions, TestBitwiseFunctions, TestArrayFunctions,
TestLambdaExpressions — single-expression assertions via
FunctionAssertions; ours drive the whole engine per expression.
"""

import pytest

from presto_tpu import types as T
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runner import LocalRunner


@pytest.fixture(scope="module")
def runner():
    mem = MemoryConnector()
    mem.create_table(
        "t", ["j", "u", "s", "num"],
        [T.VARCHAR, T.VARCHAR, T.VARCHAR, T.VARCHAR],
        [('{"a": {"b": [1, 2, 3]}, "n": 7, "t": true}',
          'https://user@example.com:8080/p/q?x=1&y=2#frag', 'abc', '17'),
         ('[10, 20]', 'http://h/pp', 'def', '  42 '),
         ('{bad json', 'not a url at all', 'ghi', '3.9'),
         (None, None, None, None)],
    )
    return LocalRunner(
        {"mem": mem, "tpch": TpchConnector(0.001)},
        default_catalog="mem",
    )


def col(runner, expr, frm="t"):
    return [r[0] for r in runner.execute(
        f"select {expr} from {frm}").rows]


def one(runner, expr):
    return runner.execute(f"select {expr} from t limit 1").rows[0][0]


# ------------------------------------------------------------------ JSON

@pytest.mark.parametrize("expr,want", [
    ("json_extract(j, '$.a.b')", ["[1,2,3]", None, None, None]),
    ("json_extract(j, '$.a')", ['{"b":[1,2,3]}', None, None, None]),
    ("json_extract_scalar(j, '$.n')", ["7", None, None, None]),
    ("json_extract_scalar(j, '$.t')", ["true", None, None, None]),
    ("json_extract_scalar(j, '$.a')", [None, None, None, None]),
    ("json_extract(j, '$[1]')", [None, "20", None, None]),
    ("json_array_length(j)", [None, 2, None, None]),
    ("json_size(j, '$.a')", [1, None, None, None]),
    ("json_size(j, '$.n')", [0, None, None, None]),
    ("json_array_contains(j, 20)", [None, True, None, None]),
])
def test_json(runner, expr, want):
    assert col(runner, expr) == want


def test_json_parse_canonicalizes(runner):
    got = col(runner, "json_parse(j)")
    assert got[0] == '{"a":{"b":[1,2,3]},"n":7,"t":true}'
    assert got[2] is None  # invalid JSON -> NULL
    assert col(runner, "json_format(json_parse(j))")[1] == "[10,20]"


# ------------------------------------------------------- TRY / TRY_CAST

def test_try_cast(runner):
    assert col(runner, "try_cast(num as bigint)") == [17, 42, None, None]
    assert col(runner, "try_cast(num as double)") == \
        [17.0, 42.0, 3.9, None]
    assert one(runner, "try_cast('2024-02-29' as date)") is not None
    assert one(runner, "try_cast('zzz' as date)") is None


def test_cast_from_varchar(runner):
    assert one(runner, "cast('42' as bigint)") == 42
    assert one(runner, "cast('1.5' as double)") == 1.5
    assert col(runner, "cast(num as bigint)") == [17, 42, None, None]


def test_try_identity(runner):
    assert one(runner, "try(1/0)") is None  # masked-eval divide
    assert one(runner, "try(41 + 1)") == 42


# ---------------------------------------------------------------- bitwise

@pytest.mark.parametrize("expr,want", [
    ("bitwise_and(12, 10)", 8),
    ("bitwise_or(12, 10)", 14),
    ("bitwise_xor(12, 10)", 6),
    ("bitwise_not(0)", -1),
    ("bit_count(255)", 8),
    ("bit_count(-1)", 64),
    ("bit_count(255, 8)", 8),
])
def test_bitwise(runner, expr, want):
    assert one(runner, expr) == want


# -------------------------------------------------------------------- URL

def test_url_functions(runner):
    assert col(runner, "url_extract_host(u)") == \
        ["example.com", "h", None, None]
    assert col(runner, "url_extract_port(u)") == \
        [8080, None, None, None]
    # RFC-3986 treats a bare string as a path (urlsplit semantics)
    assert col(runner, "url_extract_path(u)") == \
        ["/p/q", "/pp", "not a url at all", None]
    assert col(runner, "url_extract_query(u)") == \
        ["x=1&y=2", "", "", None]
    assert col(runner, "url_extract_parameter(u, 'y')") == \
        ["2", None, None, None]
    assert one(runner, "url_encode('a b&c')") == "a%20b%26c"
    assert one(runner, "url_decode('a%20b%26c')") == "a b&c"


# ---------------------------------------------------------- arrays / maps

@pytest.mark.parametrize("expr,want", [
    ("array_distinct(array[1, 2, 2, 3, 1])", (1, 2, 3)),
    ("array_sort(array[3, 1, 2])", (1, 2, 3)),
    ("array_join(array[1, 2, 3], '-')", "1-2-3"),
    ("array_position(array[5, 6, 7], 6)", 2),
    ("array_position(array[5, 6, 7], 9)", 0),
    ("array_remove(array[1, 2, 1, 3], 1)", (2, 3)),
    ("slice(array[1, 2, 3, 4], 2, 2)", (2, 3)),
    ("slice(array[1, 2, 3, 4], -2, 2)", (3, 4)),
    ("sequence(1, 5)", (1, 2, 3, 4, 5)),
    ("sequence(5, 1, -2)", (5, 3, 1)),
    ("repeat(7, 3)", (7, 7, 7)),
    ("reverse(array[1, 2, 3])", (3, 2, 1)),
    ("flatten(array[array[1, 2], array[3]])", (1, 2, 3)),
])
def test_array_functions(runner, expr, want):
    assert one(runner, expr) == want


def test_split(runner):
    assert one(runner, "split('a,b,c', ',')") == ("a", "b", "c")
    assert one(runner, "split('a,b,c', ',', 2)") == ("a", "b,c")


def test_map_entries(runner):
    got = one(runner, "map_entries(map(array['a'], array[1]))")
    assert got == (("a", 1),)


# ----------------------------------------------------------------- lambdas

@pytest.mark.parametrize("expr,want", [
    ("transform(array[1, 2, 3], x -> x * 2)", (2, 4, 6)),
    ("transform(array[1, 2], x -> x + 0.5)", (1.5, 2.5)),
    ("filter(array[1, 2, 3, 4], x -> x > 2)", (3, 4)),
    ("filter(array[1, 2], x -> false)", ()),
    ("any_match(array[1, 2], x -> x > 1)", True),
    ("any_match(array[1, 2], x -> x > 5)", False),
    ("all_match(array[1, 2], x -> x > 0)", True),
    ("all_match(array[1, 2], x -> x > 1)", False),
    ("none_match(array[1, 2], x -> x > 5)", True),
    ("reduce(array[1, 2, 3, 4], 0, (s, x) -> s + x, s -> s)", 10),
    ("reduce(array[2, 3], 1, (s, x) -> s * x, s -> s * 10)", 60),
])
def test_lambdas(runner, expr, want):
    assert one(runner, expr) == want


def test_map_lambdas(runner):
    assert one(
        runner,
        "transform_values(map(array['a','b'], array[1,2]), v -> v * 10)",
    ) == (("a", 10), ("b", 20))
    assert one(
        runner,
        "transform_keys(map(array['a'], array[1]), k -> upper(k))",
    ) == (("A", 1),)
    assert one(
        runner,
        "map_filter(map(array['a','b'], array[1,2]), (k, v) -> v > 1)",
    ) == (("b", 2),)


def test_lambda_capture_rejected(runner):
    with pytest.raises(Exception, match="capture"):
        runner.execute(
            "select transform(array[1], x -> x + "
            "cast(num as bigint)) from t"
        )


def test_lambda_over_string_elements(runner):
    assert one(
        runner,
        "transform(array['a', 'b'], x -> upper(x))",
    ) == ("A", "B")


# ------------------------------------------------------------------- misc

def test_string_misc(runner):
    assert col(runner, "starts_with(s, 'ab')") == \
        [True, False, False, None]
    assert one(runner, "md5('abc')") == \
        "900150983cd24fb0d6963f7d28e17f72"
    assert one(runner, "sha256('abc')") == (
        "ba7816bf8f01cfea414140de5dae2223"
        "b00361a396177a9cb410ff61f20015ad"
    )
    assert one(runner, "to_hex('AB')") == "4142"
    assert one(runner, "from_hex('4142')") == "AB"
    assert one(runner, "to_base64('ab')") == "YWI="
    assert one(runner, "from_base64('YWI=')") == "ab"
    assert one(runner, "chr(65)") == "A"
    assert one(runner, "normalize('Å')") == "Å"


def test_typeof(runner):
    assert one(runner, "typeof(1)") == "bigint"
    assert one(runner, "typeof(num)") == "varchar"


def test_date_parse_and_last_day(runner):
    r = runner.execute(
        "select year(date_parse('2024-02-05', '%Y-%m-%d')), "
        "last_day_of_month(date '2024-02-05') from t limit 1"
    ).rows[0]
    assert r[0] == 2024
    assert str(r[1]) in ("2024-02-29", "19782")  # date days or rendered


def test_registered_count():
    from presto_tpu.expr import functions as F

    assert len(F.registered_names()) >= 150


def test_nested_lambda_outer_param_rejected(runner):
    # outer-lambda params inside a nested lambda would mis-bind
    # (ParamRef indices are frame-local) — must raise, not mis-compute
    with pytest.raises(Exception, match="capture"):
        runner.execute(
            "select transform(sequence(1, 2), "
            "x -> transform(sequence(10, 11), y -> x + y)) from t"
        )
