"""DML (DELETE/UPDATE) over the memory connector — rewrite-through-
SELECT + table replace (reference: sql/tree/Delete, Update;
TableWriter/TableFinish pipeline; columnar stores rewrite rather than
mutate in place)."""

import pytest

from presto_tpu import types as T
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.runner import LocalRunner


@pytest.fixture()
def runner():
    mem = MemoryConnector()
    r = LocalRunner({"memory": mem}, default_catalog="memory",
                    page_rows=1 << 8)
    mem.create_table(
        "t", ["k", "v", "d"],
        [T.BIGINT, T.DecimalType(10, 2), T.DATE],
        [(i, i * 100, 19000 + i) for i in range(100)],
    )
    return r


def test_delete_where(runner):
    res = runner.execute("delete from t where k >= 90")
    assert res.update_type == "DELETE" and res.rows == [(10,)]
    assert runner.execute("select count(*) from t").rows == [(90,)]
    # schema survives the rewrite
    assert runner.execute(
        "select sum(v) from t where k < 2"
    ).rows == [(100,)]


def test_delete_null_predicate_keeps_row(runner):
    mem = runner.catalogs["memory"]
    mem.create_table("n", ["x"], [T.BIGINT], [(1,), (None,), (3,)])
    res = runner.execute("delete from n where x > 1")
    # NULL predicate row is NOT deleted (SQL three-valued logic)
    assert res.rows == [(1,)]
    got = sorted(
        r[0] for r in runner.execute("select x from n").rows
        if r[0] is not None
    )
    assert got == [1]
    assert runner.execute(
        "select count(*) from n"
    ).rows == [(2,)]


def test_update_guarded_and_cast(runner):
    res = runner.execute("update t set v = v * 2 where k < 10")
    assert res.update_type == "UPDATE" and res.rows == [(10,)]
    got = runner.execute("select sum(v) from t").rows[0][0]
    exp = sum(i * 100 for i in range(100)) + sum(
        i * 100 for i in range(10)
    )
    assert got == exp
    # declared column type survives an int-typed assignment expression
    runner.execute("update t set v = 7 where k = 3")
    assert runner.execute(
        "select v from t where k = 3"
    ).rows == [(700,)]  # 7.00 at scale 2


def test_update_all_rows_and_date(runner):
    res = runner.execute("update t set d = date '2020-01-01'")
    assert res.rows == [(100,)]
    assert runner.execute(
        "select min(d), max(d) from t"
    ).rows == [(18262, 18262)]


def test_update_unknown_column(runner):
    with pytest.raises(ValueError):
        runner.execute("update t set nope = 1")


def test_delete_all(runner):
    res = runner.execute("delete from t")
    assert res.rows == [(100,)]
    assert runner.execute("select count(*) from t").rows == [(0,)]


def test_dml_over_the_wire():
    """DELETE/UPDATE through the coordinator protocol."""
    from presto_tpu.client import StatementClient
    from presto_tpu.server import PrestoTpuServer

    mem = MemoryConnector()
    mem.create_table("w", ["k"], [T.BIGINT], [(i,) for i in range(10)])
    srv = PrestoTpuServer({"memory": mem}, default_catalog="memory",
                          port=0)
    srv.start()
    try:
        c = StatementClient(server=f"http://127.0.0.1:{srv.port}")
        res = c.execute("delete from w where k >= 5")
        assert res.update_type == "DELETE"
        assert c.execute("select count(*) from w").rows == [[5]]
    finally:
        srv.stop()


def test_quoted_mixed_case_identifiers():
    mem = MemoryConnector()
    r = LocalRunner({"memory": mem}, default_catalog="memory",
                    page_rows=1 << 8)
    mem.create_table("T", ["Col"], [T.BIGINT], [(i,) for i in range(4)])
    mem.create_table("t", ["x"], [T.BIGINT], [(9,)] * 7)
    res = r.execute('delete from memory."T" where "Col" >= 2')
    assert res.rows == [(2,)]
    # lowercase t untouched, "T" reduced
    assert r.execute('select count(*) from "T"').rows == [(2,)]
    assert r.execute("select count(*) from t").rows == [(7,)]
    res = r.execute('update "T" set "Col" = 100')
    assert res.rows == [(2,)]
    assert sorted(
        x[0] for x in r.execute('select "Col" from "T"').rows
    ) == [100, 100]


def test_subquery_predicate_rejected_clearly(runner):
    with pytest.raises(ValueError):
        runner.execute(
            "delete from t where k in (select k from t where k < 3)"
        )


def test_missing_table_and_duplicate_assignment(runner):
    with pytest.raises(ValueError):
        runner.execute("delete from nosuch")
    with pytest.raises(ValueError):
        runner.execute("update t set v = 1, v = 2")
