"""Compaction/gather oracle tests (reference analog: PageProcessor
selectedPositions materialization tests)."""

import jax
import numpy as np

from presto_tpu import BIGINT, DOUBLE, VarcharType
from presto_tpu.ops.compact import compact_page, concat_pages, gather_rows
from presto_tpu.page import Page

import jax.numpy as jnp


def _page():
    return Page.from_arrays(
        [
            [10, 11, 12, 13, 14, 15],
            [0.5, None, 2.5, 3.5, None, 5.5],
            ["a", "b", "a", None, "c", "b"],
        ],
        [BIGINT, DOUBLE, VarcharType()],
        capacity=8,
    )


def test_compact_preserves_order_and_nulls():
    page = _page()
    keep = jnp.asarray([True, False, True, True, False, False, False, False])
    filtered = page.with_valid(page.valid & keep)
    out = compact_page(filtered)
    assert out.to_pylist() == [(10, 0.5, "a"), (12, 2.5, "a"), (13, 3.5, None)]
    # dense prefix
    v = np.asarray(out.valid)
    assert v[:3].all() and not v[3:].any()


def test_compact_under_jit():
    page = _page()

    @jax.jit
    def go(p):
        return compact_page(p.with_valid(p.valid & (p.block(0).data % 2 == 0)))

    out = go(page)
    assert out.to_pylist() == [(10, 0.5, "a"), (12, 2.5, "a"), (14, None, "c")]


def test_compact_shrink_capacity():
    page = _page()
    out = compact_page(page, out_capacity=4)
    # silently truncates beyond capacity (callers check num_rows first)
    assert len(out.to_pylist()) == 4


def test_gather_rows_with_force_null():
    page = _page()
    idx = jnp.asarray([2, 0, 5], dtype=jnp.int64)
    valid = jnp.asarray([True, True, True])
    force = jnp.asarray([False, True, False])
    out = gather_rows(page, idx, valid, force_null=force)
    assert out.to_pylist() == [
        (12, 2.5, "a"),
        (None, None, None),
        (15, 5.5, "b"),
    ]


def test_concat_pages():
    a = Page.from_arrays([[1, 2]], [BIGINT], capacity=4)
    b = Page.from_arrays([[3]], [BIGINT], capacity=2)
    out = concat_pages(a, b)
    assert out.capacity == 6
    assert sorted(out.to_pylist()) == [(1,), (2,), (3,)]


def test_concat_pages_merges_dictionaries():
    a = Page.from_arrays([["apple", "cherry"]], [VarcharType()])
    b = Page.from_arrays([["banana", "zebra", None]], [VarcharType()])
    out = concat_pages(a, b)
    got = [r[0] for r in out.to_pylist()]
    assert got == ["apple", "cherry", "banana", "zebra", None]
