"""Real-TPU smoke test (VERDICT round-1 item 1's done-criterion).

The suite's conftest forces the CPU backend for determinism, so this test
drives the real chip in a SUBPROCESS with the ambient (axon) environment.
It is opt-in via RUN_TPU_SMOKE=1 — first-compile costs ~1 min and CI time
budgets matter; `python bench.py` exercises the same path with full
timings every round.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCRIPT = r"""
import jax
jax.config.update("jax_compilation_cache_dir", %r)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
assert jax.default_backend() == "tpu", jax.default_backend()
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runner import LocalRunner
from tests.oracle import load_sqlite
from tests.test_sql_tpch import ENGINE_SQL, ORACLE, compare
conn = TpchConnector(scale=0.01)
runner = LocalRunner({"tpch": conn})
db = load_sqlite(conn, ["lineitem"])
got = runner.execute(ENGINE_SQL[6]).rows
want = db.execute(ORACLE[6][0]).fetchall()
compare(6, got, want, ORACLE[6][1])
print("TPU_SMOKE_OK")
"""


@pytest.mark.tpu
@pytest.mark.skipif(
    os.environ.get("RUN_TPU_SMOKE") != "1",
    reason="opt-in (RUN_TPU_SMOKE=1): needs the real chip + ~1 min compile",
)
def test_q6_on_real_tpu():
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-c", SCRIPT % os.path.join(REPO, ".jax_cache")],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO,
    )
    assert "TPU_SMOKE_OK" in out.stdout, (out.stdout[-500:],
                                          out.stderr[-1500:])


PALLAS_SCRIPT = r"""
import jax
jax.config.update("jax_compilation_cache_dir", %r)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
assert jax.default_backend() == "tpu", jax.default_backend()
import jax.numpy as jnp
import numpy as np
from presto_tpu.ops import pallas_join as PJ

rng = np.random.default_rng(5)
nb, np_ = 1800, 100352
bhash = rng.choice(900, size=nb).astype(np.uint64) * np.uint64(
    0x9E3779B97F4A7C15)
bvalid = rng.random(nb) < 0.9
phash = rng.choice(1100, size=np_).astype(np.uint64) * np.uint64(
    0x9E3779B97F4A7C15)
layout = PJ.plan_layout(nb)
assert PJ.layout_lowers_on_tpu(layout), layout
tabs, perm, ovf = PJ.build_index(
    jnp.asarray(bhash), jnp.asarray(bvalid), layout)
start, cnt = PJ.probe_index(
    jnp.asarray(phash), tabs, layout, interpret=False)  # REAL Mosaic
got_s, got_c = np.asarray(start), np.asarray(cnt)
poisoned = np.where(bvalid, bhash, np.uint64(0xFFFFFFFFFFFFFFFF))
sh = poisoned[np.argsort(poisoned, kind="stable")]
lo = np.searchsorted(sh, phash, side="left").astype(np.int32)
wc = (np.searchsorted(sh, phash, side="right") - lo).astype(np.int32)
assert np.array_equal(got_c, wc)
hit = wc > 0
assert np.array_equal(got_s[hit], lo[hit]) and np.all(got_s[~hit] == -1)
assert not bool(ovf)
print("PALLAS_TPU_OK", int(hit.sum()))
"""


@pytest.mark.tpu
@pytest.mark.skipif(
    os.environ.get("RUN_TPU_SMOKE") != "1",
    reason="opt-in (RUN_TPU_SMOKE=1): needs the real chip",
)
def test_pallas_dim_join_kernel_on_real_tpu():
    """The dim-layout Pallas join kernel through REAL Mosaic lowering
    (interpret=False), oracle-checked — the non-interpret parity check
    VERDICT r2 #4 requires. The general radix layout stays interpreted
    on this toolchain (no per-lane wide gather; see ops/pallas_join.py
    module docstring)."""
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-c",
         PALLAS_SCRIPT % os.path.join(REPO, ".jax_cache")],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO,
    )
    assert "PALLAS_TPU_OK" in out.stdout, (out.stdout[-500:],
                                           out.stderr[-1500:])
