"""Real-TPU smoke test (VERDICT round-1 item 1's done-criterion).

The suite's conftest forces the CPU backend for determinism, so this test
drives the real chip in a SUBPROCESS with the ambient (axon) environment.
It is opt-in via RUN_TPU_SMOKE=1 — first-compile costs ~1 min and CI time
budgets matter; `python bench.py` exercises the same path with full
timings every round.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCRIPT = r"""
import jax
jax.config.update("jax_compilation_cache_dir", %r)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
assert jax.default_backend() == "tpu", jax.default_backend()
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runner import LocalRunner
from tests.oracle import load_sqlite
from tests.test_sql_tpch import ENGINE_SQL, ORACLE, compare
conn = TpchConnector(scale=0.01)
runner = LocalRunner({"tpch": conn})
db = load_sqlite(conn, ["lineitem"])
got = runner.execute(ENGINE_SQL[6]).rows
want = db.execute(ORACLE[6][0]).fetchall()
compare(6, got, want, ORACLE[6][1])
print("TPU_SMOKE_OK")
"""


@pytest.mark.tpu
@pytest.mark.skipif(
    os.environ.get("RUN_TPU_SMOKE") != "1",
    reason="opt-in (RUN_TPU_SMOKE=1): needs the real chip + ~1 min compile",
)
def test_q6_on_real_tpu():
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-c", SCRIPT % os.path.join(REPO, ".jax_cache")],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO,
    )
    assert "TPU_SMOKE_OK" in out.stdout, (out.stdout[-500:],
                                          out.stderr[-1500:])
