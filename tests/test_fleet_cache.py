"""ISSUE 19: DCN-shared fragment cache hits (the fleet half of the
tentpole; persistence + subsumption live in tests/test_cache_persist.py).

Covers:
  - the coordinator-side key mirror (dist/cacheprobe.fragment_cache_key)
    computes EXACTLY the keys worker-side executions store;
  - bloom summaries: the common miss is free (no round trip without a
    positive bloom), absent summaries fail closed;
  - probe end-to-end over BOTH dispatch planes (classic cuts and the
    stage-DAG scheduler): second run serves every leaf task from the
    fleet cache with cache_remote_hits >= 1 and identical rows;
  - the cross-process acceptance pin (subprocess workers, disjoint
    caches): a fragment computed on worker A serves a later query
    whose dispatch would have sent that split share to worker B.
"""

import collections
import json
import os
import subprocess
import sys

import pytest

from presto_tpu.cache import shared_cache_if_exists
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.dist.cacheprobe import (
    RemoteCacheIndex,
    bloom_summary,
    fragment_cache_key,
)
from presto_tpu.dist.dcn import DcnRunner
from presto_tpu.runner import LocalRunner
from presto_tpu.server.worker import WorkerServer

SF = 0.01
PAGE_ROWS = 1 << 13

AGG_Q = ("select l_returnflag, count(*) c, sum(l_quantity) q "
         "from lineitem group by l_returnflag")


def rows_equal(a, b):
    return collections.Counter(map(repr, a)) == collections.Counter(
        map(repr, b))


@pytest.fixture(autouse=True)
def _clean_shared_cache():
    rc = shared_cache_if_exists()
    if rc is not None:
        rc.configure(persist_dir="")
        rc.clear()
    yield
    rc = shared_cache_if_exists()
    if rc is not None:
        rc.configure(persist_dir="")
        rc.clear()


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(SF)


# ------------------------------------------------------- bloom index
def test_bloom_index_contract():
    idx = RemoteCacheIndex()
    keys = [f"frag:abc:{i}:k1.p1" for i in range(8)]
    idx.update("http://a", bloom_summary(keys))
    for k in keys:
        assert idx.might_contain("http://a", k)
    # no summary for an unknown peer: FAIL CLOSED (no probe traffic)
    assert not idx.might_contain("http://b", keys[0])
    assert idx.known()
    # garbage summaries un-register the peer rather than crash
    idx.update("http://a", "not base64!!")
    assert not idx.might_contain("http://a", keys[0])


def test_bloom_negative_is_free():
    idx = RemoteCacheIndex()
    idx.update("http://a", bloom_summary(["frag:only:1:k1.p1"]))
    miss = sum(
        idx.might_contain("http://a", f"frag:other:{i}:k1.p1")
        for i in range(64)
    )
    # 1024 bits / 4 hashes over one inserted key: essentially every
    # foreign key answers "definitely not" locally
    assert miss <= 2


# -------------------------------------------------- key mirror + e2e
def _fleet(session_props):
    w1 = WorkerServer({"tpch": TpchConnector(SF)}, node_id="w1",
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    w2 = WorkerServer({"tpch": TpchConnector(SF)}, node_id="w2",
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    uris = [f"http://127.0.0.1:{w1.start()}",
            f"http://127.0.0.1:{w2.start()}"]
    coord = DcnRunner({"tpch": TpchConnector(SF)}, uris,
                      default_catalog="tpch", page_rows=PAGE_ROWS,
                      session_props=session_props)
    return coord, (w1, w2)


def test_probe_key_mirror_matches_worker_keys(conn):
    """fragment_cache_key (coordinator side, no dispatch) computes
    the exact keys the workers' executions stored."""
    coord, ws = _fleet({"result_cache_enabled": "true"})
    try:
        coord.execute(AGG_Q)
        rc = shared_cache_if_exists()
        stored = set(rc.pages_keys())
        assert stored, "worker executions must have cached fragments"
        from presto_tpu.dist.fragmenter import fragment_dag

        dag = fragment_dag(coord.runner.executor,
                           coord.runner.plan(AGG_Q),
                           coord.runner.catalogs)
        mirrored = set()
        for frag in dag.fragments:
            if frag.split_table and not frag.inputs:
                for i in range(2):
                    k = fragment_cache_key(
                        frag.root, coord.runner.catalogs,
                        split_table=frag.split_table,
                        split_index=i, split_count=2,
                        collect_k=coord.runner.executor.collect_k,
                        page_rows=coord.runner.executor.page_rows)
                    assert k is not None
                    mirrored.add(k)
        assert mirrored == stored
    finally:
        coord.close()
        for w in ws:
            w.stop()


@pytest.mark.parametrize("props", [
    {"result_cache_enabled": "true"},                       # classic
    {"result_cache_enabled": "true",
     "stage_scheduler": "true"},                            # DAG
])
def test_fleet_hit_short_circuits_dispatch(props):
    coord, ws = _fleet(props)
    try:
        r1 = coord.execute(AGG_Q)
        assert coord.runner.executor.cache_remote_hits == 0
        coord.heartbeat.check_once()      # pull cacheSummary blooms
        r2 = coord.execute(AGG_Q)
        assert coord.runner.executor.cache_remote_hits >= 1
        assert rows_equal(r1, r2)
        rc = shared_cache_if_exists()
        assert rc.remote_hits >= 1        # workers counted the serve
    finally:
        coord.close()
        for w in ws:
            w.stop()


def test_probe_disabled_by_session_prop(conn):
    coord, ws = _fleet({"result_cache_enabled": "true",
                        "result_cache_remote_probe": "false"})
    try:
        r1 = coord.execute(AGG_Q)
        coord.heartbeat.check_once()
        r2 = coord.execute(AGG_Q)
        assert coord.runner.executor.cache_remote_hits == 0
        assert rows_equal(r1, r2)
    finally:
        coord.close()
        for w in ws:
            w.stop()


# ------------------------------------------- cross-process pin (slow)
def _boot_subprocess_worker():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    for k in ("FAULT_DELAY_MS", "FAULT_DROP_EVERY",
              "FAULT_KILL_AFTER_FETCHES", "FAULT_SUBMIT_DROP_EVERY"):
        env.pop(k, None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "presto_tpu.server.worker",
         "--port", "0", "--suite", "tpch", "--scale", str(SF),
         "--page-rows", str(PAGE_ROWS)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=env, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
        text=True,
    )
    info = json.loads(proc.stdout.readline())
    return proc, f"http://127.0.0.1:{info['port']}"


@pytest.mark.slow
def test_cross_worker_fleet_pin():
    """THE fleet acceptance contract with REAL disjoint caches: after
    [A, B] computes the deck, a coordinator whose dispatch order is
    [B, A] still serves every split share — split 0's pages live only
    on A while B would have recomputed them, so the serve is
    cross-worker by construction (blooms route the probe to the
    holder)."""
    pa, ua = _boot_subprocess_worker()
    pb, ub = _boot_subprocess_worker()
    c1 = c2 = None
    try:
        c1 = DcnRunner({"tpch": TpchConnector(SF)}, [ua, ub],
                       default_catalog="tpch", page_rows=PAGE_ROWS,
                       session_props={"result_cache_enabled": "true"})
        want = c1.execute(AGG_Q)
        assert c1.runner.executor.cache_remote_hits == 0

        c2 = DcnRunner({"tpch": TpchConnector(SF)}, [ub, ua],
                       default_catalog="tpch", page_rows=PAGE_ROWS,
                       session_props={"result_cache_enabled": "true"})
        c2.heartbeat.check_once()
        got = c2.execute(AGG_Q)
        assert c2.runner.executor.cache_remote_hits >= 1
        assert rows_equal(want, got)

        oracle = LocalRunner({"tpch": TpchConnector(SF)},
                             page_rows=PAGE_ROWS)
        assert rows_equal(got, oracle.execute(AGG_Q).rows)
    finally:
        for c in (c1, c2):
            if c is not None:
                c.close()
        for p in (pa, pb):
            p.terminate()
            p.wait(timeout=10)
