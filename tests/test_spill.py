"""Partitioned (grace-style) execution — the spill analog (SURVEY §6.4).

Reference: presto-main spiller/* + SpillableHashAggregationBuilder; the
TPU translation partitions by key hash and re-streams inputs per pass
(generator scans recompute instead of re-reading spilled files), so the
join-build / aggregation-state materialization stays under the
spill_threshold_bytes session property.
"""

import collections

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runner import LocalRunner


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(0.01)


@pytest.fixture(scope="module")
def base(conn):
    return LocalRunner({"tpch": conn}, page_rows=1 << 13)


@pytest.fixture(scope="module")
def spilling(conn):
    r = LocalRunner({"tpch": conn}, page_rows=1 << 13)
    # tiny threshold: every join build / agg state partitions
    r.session.set("spill_threshold_bytes", 1 << 17)
    return r


def _rows_equal(a, b):
    return collections.Counter(map(repr, a)) == collections.Counter(
        map(repr, b)
    )


QUERIES = [
    # fact-fact join + high-cardinality group-by
    "select o_orderkey, sum(l_extendedprice), count(*) "
    "from orders, lineitem where o_orderkey = l_orderkey "
    "group by o_orderkey order by 2 desc limit 7",
    # high-cardinality aggregation alone
    "select l_orderkey, count(*) from lineitem group by l_orderkey "
    "order by 2 desc, 1 limit 5",
    # anti join (null-key semantics must survive partitioning)
    "select c_custkey, c_acctbal from customer where c_custkey not in "
    "(select o_custkey from orders) order by c_custkey limit 5",
    # outer join: null-extension exactly once per unmatched probe row
    "select count(*) from customer left join orders "
    "on c_custkey = o_custkey",
]


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_partitioned_matches_single_pass(base, spilling, qi):
    q = QUERIES[qi]
    a = base.execute(q).rows
    b = spilling.execute(q).rows
    assert spilling.executor.spill_partitions_used > 1, (
        "threshold should have forced partitioned execution"
    )
    assert _rows_equal(a, b), (a[:3], b[:3])


def test_right_join_unmatched_build_rows_once_per_partition(conn, base):
    # the customer build side is small, so force partitioning with a
    # floor-level threshold; a third of customers place no orders and
    # must null-extend exactly once across all passes
    r = LocalRunner({"tpch": conn}, page_rows=1 << 13)
    r.session.set("spill_threshold_bytes", 1 << 13)
    q = ("select count(*) from orders right join customer "
         "on o_custkey = c_custkey")
    a = base.execute(q).rows
    b = r.execute(q).rows
    assert r.executor.spill_partitions_used > 1
    assert a == b


def test_string_keys_fall_back_to_single_pass(spilling):
    # dictionary (string) keys cannot hash consistently across pages —
    # the operator must run unpartitioned rather than wrong
    q = ("select c_mktsegment, count(*) from customer "
         "group by c_mktsegment")
    rows = spilling.execute(q).rows
    assert spilling.executor.spill_partitions_used == 0
    assert sum(r[1] for r in rows) == 1500


def test_spill_respects_memory_budget(conn):
    """The point of spilling: a query that busts the page budget single-
    pass completes under the same budget with partitioning on."""
    from presto_tpu.exec.executor import MemoryBudgetExceeded

    q = ("select o_orderkey, count(*) from orders, lineitem "
         "where o_orderkey = l_orderkey group by o_orderkey "
         "order by 2 desc limit 3")
    strict = LocalRunner({"tpch": conn}, page_rows=1 << 12)
    strict.session.set("query_max_memory_bytes", 1 << 19)
    with pytest.raises(MemoryBudgetExceeded):
        strict.execute(q)
    relieved = LocalRunner({"tpch": conn}, page_rows=1 << 12)
    relieved.session.set("query_max_memory_bytes", 1 << 19)
    relieved.session.set("spill_threshold_bytes", 1 << 15)
    rows = relieved.execute(q).rows
    assert len(rows) == 3


def test_not_in_null_build_partitioned(conn):
    """NOT IN three-valued logic survives partitioning: a NULL in the
    build side must suppress every unmatched probe row in EVERY pass
    (null build rows are routed to all partitions), not just the pass
    its hash lands in."""
    from presto_tpu import types as T
    from presto_tpu.connectors.memory import MemoryConnector

    mem = MemoryConnector()
    r = LocalRunner({"tpch": conn, "memory": mem}, page_rows=1 << 13)
    r.session.set("spill_threshold_bytes", 1 << 13)
    # big enough to cross the threshold; disjoint from o_custkey so
    # every probe row is unmatched — the lone NULL decides everything
    mem.create_table(
        "u", ["y"], [T.BIGINT],
        [(i,) for i in range(100_000, 105_000)] + [(None,)],
    )
    rows = r.execute(
        "select count(*) from orders where o_custkey not in "
        "(select y from memory.u)"
    ).rows
    assert r.executor.spill_partitions_used > 1
    assert rows == [(0,)]
    # sanity: without the NULL, the same query matches many rows
    mem.create_table(
        "u2", ["y"], [T.BIGINT],
        [(i,) for i in range(100_000, 105_000)],
    )
    rows2 = r.execute(
        "select count(*) from orders where o_custkey not in "
        "(select y from memory.u2)"
    ).rows
    assert rows2[0][0] > 0


def test_partition_fold_single_source_pass():
    """parts <= 32 takes the single-pass fold: the (potentially
    expensive) source must stream exactly once, not once per
    partition."""
    conn2 = TpchConnector(0.01)
    r = LocalRunner({"tpch": conn2}, page_rows=1 << 13)
    r.session.set("spill_threshold_bytes", 1 << 17)
    calls = {"n": 0}
    orig = conn2.pages

    def counting(table, *a, **k):
        if table == "lineitem":
            calls["n"] += 1
        return orig(table, *a, **k)

    conn2.pages = counting
    rows = r.execute(
        "select l_orderkey, count(*) from lineitem group by l_orderkey "
        "order by 2 desc, 1 limit 3"
    ).rows
    assert 1 < r.executor.spill_partitions_used <= 32
    assert calls["n"] == 1
    assert len(rows) == 3


def test_partitioned_join_restreams_from_store(base):
    """When a partitioned join consumes another join as a source, that
    expensive subtree must materialize ONCE (PageStore) and restream
    per pass — recompute passes must not COMPOUND down the pipeline
    (the round-2 Q3-SF10 blocker). The plan here is
    (lineitem JOIN orders) JOIN customer: both joins partition, and the
    scan re-stream counts must stay at the INNER join's pass count, not
    inner x outer."""
    conn2 = TpchConnector(0.01)
    r = LocalRunner({"tpch": conn2}, page_rows=1 << 13)
    # low enough that the outer (customer-build) join partitions too
    r.session.set("spill_threshold_bytes", 1 << 12)
    calls = {"orders": 0, "lineitem": 0}
    # these tests exercise the partitioned/materialized build machinery;
    # the build-free generated join (default) would bypass it entirely
    r.session.set("generated_join_enabled", False)
    orig = conn2.pages

    def counting(table, *a, **k):
        if table in calls:
            calls[table] += 1
        return orig(table, *a, **k)

    conn2.pages = counting
    q = (
        "select count(*), sum(l_extendedprice) from lineitem, orders, "
        "customer where l_orderkey = o_orderkey "
        "and o_custkey = c_custkey"
    )
    got = r.execute(q).rows
    # both joins partitioned: max parts across operators > 1, and the
    # scans re-streamed at most max-parts times (inner join passes);
    # without the PageStore the counts would be inner x outer passes
    parts = r.executor.spill_partitions_used
    assert parts > 1
    assert 1 < calls["lineitem"] <= parts
    assert 1 < calls["orders"] <= parts
    assert _rows_equal(got, base.execute(q).rows)


def test_max_join_build_rows_partitions_without_byte_threshold(base):
    """max_join_build_rows partitions a join purely on build-side row
    count (kernel-size ceiling for runtimes that fault on huge buffers)
    even when spill_threshold_bytes is unset."""
    conn2 = TpchConnector(0.01)
    r = LocalRunner({"tpch": conn2}, page_rows=1 << 13)
    r.session.set("max_join_build_rows", 2000)  # orders has 15000 rows
    # these tests exercise the partitioned/materialized build machinery;
    # the build-free generated join (default) would bypass it entirely
    r.session.set("generated_join_enabled", False)
    q = (
        "select count(*), sum(l_extendedprice) from lineitem, orders "
        "where l_orderkey = o_orderkey"
    )
    got = r.execute(q).rows
    assert r.executor.spill_partitions_used == 8  # next_pow2(15000/2000)
    assert _rows_equal(got, base.execute(q).rows)


def test_host_spill_tier_restages(base):
    """With host_spill_bytes set low, materialized intermediates stage
    to host RAM (numpy pytrees) and restage per pass via device_put —
    results identical, host_spill observability counters advance."""
    conn2 = TpchConnector(0.01)
    r = LocalRunner({"tpch": conn2}, page_rows=1 << 13)
    # low enough that the outer join partitions, so its expensive probe
    # side (the inner join) must materialize
    r.session.set("spill_threshold_bytes", 1 << 12)
    r.session.set("host_spill_bytes", 1)  # everything spills to host
    # these tests exercise the partitioned/materialized build machinery;
    # the build-free generated join (default) would bypass it entirely
    r.session.set("generated_join_enabled", False)
    q = (
        "select count(*), sum(l_extendedprice) from lineitem, orders, "
        "customer where l_orderkey = o_orderkey "
        "and o_custkey = c_custkey"
    )
    got = r.execute(q).rows
    assert r.executor.spill_partitions_used > 1
    assert r.executor.host_spill_pages > 0
    assert r.executor.host_spill_bytes_used > 0
    assert _rows_equal(got, base.execute(q).rows)


def test_multipass_beyond_32_partitions(base):
    """parts > 32 falls back to re-streaming passes; results must still
    match single-pass execution exactly."""
    conn3 = TpchConnector(0.01)
    r = LocalRunner({"tpch": conn3}, page_rows=1 << 13)
    r.session.set("spill_threshold_bytes", 1 << 15)
    q = ("select l_orderkey, count(*), sum(l_extendedprice) "
         "from lineitem group by l_orderkey order by 3 desc, 1 limit 5")
    got = r.execute(q).rows
    assert r.executor.spill_partitions_used > 32
    assert _rows_equal(got, base.execute(q).rows)


def test_disk_spill_tier_restages(base, tmp_path):
    """Third spill tier (reference: FileSingleStreamSpiller): with
    disk_spill_bytes set low, materialized intermediates write to .npz
    files under spill_path and restream from disk per pass — results
    identical, files cleaned up when the store is released."""
    import os

    conn2 = TpchConnector(0.01)
    r = LocalRunner({"tpch": conn2}, page_rows=1 << 13)
    r.session.set("spill_threshold_bytes", 1 << 12)
    r.session.set("disk_spill_bytes", 1)  # everything spills to disk
    r.session.set("spill_path", str(tmp_path))
    r.session.set("generated_join_enabled", False)
    q = (
        "select count(*), sum(l_extendedprice) from lineitem, orders, "
        "customer where l_orderkey = o_orderkey "
        "and o_custkey = c_custkey"
    )
    got = r.execute(q).rows
    assert r.executor.spill_partitions_used > 1
    assert r.executor.disk_spill_pages > 0
    # spill files existed under spill_path during the query; release
    # the store and check the directory drained
    r.executor._stream_cache = {}
    import gc

    gc.collect()
    assert os.listdir(tmp_path) == []
    assert _rows_equal(got, base.execute(q).rows)


def test_skew_rebalance_chunks_hot_partition(base):
    """SURVEY §6.7 per-partition rebalancing: a genuinely hot join key
    (one key carrying most build rows) cannot be split by key hash —
    on the boosted retry the hot partition's build rows chunk by
    POSITION into unboosted-size passes (skew_chunks_used advances)
    and the inner join still matches the unspilled engine."""
    from presto_tpu import types as T
    from presto_tpu.connectors.memory import MemoryConnector

    mem = MemoryConnector()
    n = 4000
    # build: 85% of rows share key 7 (hot), the rest spread thinly —
    # the partition holding key 7 dwarfs the others. The probe table
    # must be the BIGGER side so the planner keeps the hot table as
    # the join BUILD (the side the rebalancer chunks).
    mem.create_table(
        "probe", ["pk", "pv"], [T.BIGINT, T.BIGINT],
        rows=[(i % 50, i) for i in range(8000)],
    )
    mem.create_table(
        "build", ["bk", "bv"], [T.BIGINT, T.BIGINT],
        rows=[(7 if i % 100 < 85 else i % 50, i) for i in range(n)],
    )
    single = LocalRunner({"mem": mem}, page_rows=1 << 10,
                         default_catalog="mem")
    q = ("select count(*), sum(pv), sum(bv) from probe, build "
         "where pk = bk")
    want = single.execute(q).rows

    spilling = LocalRunner({"mem": mem}, page_rows=1 << 10,
                           default_catalog="mem")
    # tiny caps: the hot partition overflows its unboosted cap and the
    # retry takes the rebalanced (chunked) path
    spilling.session.set("spill_threshold_bytes", 1 << 12)
    spilling.session.set("generated_join_enabled", False)
    got = spilling.execute(q).rows
    assert spilling.executor.spill_partitions_used > 1
    assert spilling.executor.skew_chunks_used > 1, (
        "hot partition should have chunked on the boosted retry")
    assert _rows_equal(got, want)


def test_skew_rebalance_off_still_correct(base):
    from presto_tpu import types as T
    from presto_tpu.connectors.memory import MemoryConnector

    mem = MemoryConnector()
    mem.create_table(
        "probe", ["pk", "pv"], [T.BIGINT, T.BIGINT],
        rows=[(i % 50, i) for i in range(8000)],
    )
    mem.create_table(
        "build", ["bk", "bv"], [T.BIGINT, T.BIGINT],
        rows=[(7 if i % 100 < 85 else i % 50, i)
              for i in range(4000)],
    )
    single = LocalRunner({"mem": mem}, page_rows=1 << 10,
                         default_catalog="mem")
    q = ("select count(*), sum(pv), sum(bv) from probe, build "
         "where pk = bk")
    want = single.execute(q).rows
    spilling = LocalRunner({"mem": mem}, page_rows=1 << 10,
                           default_catalog="mem")
    spilling.session.set("spill_threshold_bytes", 1 << 12)
    spilling.session.set("join_skew_rebalance", False)
    spilling.session.set("generated_join_enabled", False)
    got = spilling.execute(q).rows
    assert spilling.executor.skew_chunks_used == 0
    assert _rows_equal(got, want)
