"""Skew handling on the mesh (SURVEY §6.7).

The engine's answers to a hot key, each exercised here with one key
owning 50% of all rows on an 8-device mesh:

1. Aggregation: the PARTIAL/FINAL split IS the salting — every device
   pre-reduces its shard to <=1 state row per group BEFORE the
   repartition exchange, so a hot group moves at most D state rows
   (reference: Presto's partial-aggregation pre-reduction, which
   SURVEY §6.7 identifies as the salted two-phase scheme).
2. Repartitioned joins: the hot key's probe rows land on one device;
   per-shard capacity slack plus the deferred-overflow boosted-retry
   ladder absorbs it (correctness never depends on balance).
3. Operator escape: join_distribution_type=broadcast replicates the
   build side so probe rows never move at all.
"""

import collections

import jax
import pytest

from presto_tpu import types as T
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.dist.executor import make_mesh
from presto_tpu.runner import LocalRunner

N_ROWS = 4096  # half carry the hot key


def _skewed_catalog():
    mem = MemoryConnector()
    rows = []
    for i in range(N_ROWS):
        key = 7 if i % 2 == 0 else (i % 97) + 100
        rows.append((key, i, float(i % 13)))
    mem.create_table("fact", ["k", "seq", "v"],
                     [T.BIGINT, T.BIGINT, T.DOUBLE], rows)
    mem.create_table(
        "dim", ["k", "label"], [T.BIGINT, T.BIGINT],
        [(k, k * 10) for k in [7] + [i + 100 for i in range(97)]],
    )
    return mem


@pytest.fixture(scope="module")
def single():
    return LocalRunner({"memory": _skewed_catalog()},
                       default_catalog="memory", page_rows=1 << 10)


@pytest.fixture(scope="module")
def dist():
    assert len(jax.devices()) >= 8
    return LocalRunner(
        {"memory": _skewed_catalog()}, default_catalog="memory",
        page_rows=1 << 10, mesh=make_mesh(8),
        dist_options=dict(broadcast_rows=16, gather_capacity=16),
    )


def rows_eq(a, b):
    return collections.Counter(map(repr, a)) == collections.Counter(
        map(repr, b)
    )


def test_skewed_aggregation_parity(single, dist):
    q = ("select k, count(*), sum(v), max(seq) from fact "
         "group by k")
    a = single.execute(q).rows
    b = dist.execute(q).rows
    assert rows_eq(a, b)
    hot = [r for r in a if r[0] == 7][0]
    assert hot[1] == N_ROWS // 2  # the hot key really is 50%


def test_skewed_repartitioned_join_parity(single, dist):
    # broadcast_rows=16 forces the dim build (98 rows) to partition,
    # so the hot key's probe rows all route to one device — the
    # overflow ladder must absorb the imbalance
    q = ("select count(*), sum(label), sum(v) from fact, dim "
         "where fact.k = dim.k")
    a = single.execute(q).rows
    b = dist.execute(q).rows
    assert rows_eq(a, b)


def test_broadcast_escape_hatch(single):
    # the operator-level skew escape: replicate the small build side
    r = LocalRunner(
        {"memory": _skewed_catalog()}, default_catalog="memory",
        page_rows=1 << 10, mesh=make_mesh(8),
    )
    r.session.set("join_distribution_type", "broadcast")
    q = ("select count(*), sum(label) from fact, dim "
         "where fact.k = dim.k")
    assert rows_eq(r.execute(q).rows, single.execute(q).rows)
