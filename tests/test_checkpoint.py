"""ISSUE 20: durable coordinator query-state checkpointing + re-attach.

Covers the tentpole ring by ring:
  - journal round-trip on the generation-numbered ManifestStore
    (admission / stage / root / token barriers, delivered-record
    removal, reload into a fresh process-stand-in journal);
  - loud-drop recovery: corrupt record line, truncated tail, and
    version-skewed header all reload what survives and count
    checkpoint_drops — never a crash, never silent loss;
  - concurrent barrier writers under the armed lock sanitizer;
  - the kill-the-coordinator acceptance pin: a multi-stage spooled
    query parked at the final drain survives the coordinator being
    replaced — the client's nextUri stream resumes with IDENTICAL
    rows, coordinator_reattaches == 1, and ZERO producer re-launches;
  - dead-spool re-dispatch of only the lost suffix (.ra task ids);
  - mid-stream restart (FINISHED but undelivered): the protocol token
    resumes after sha256 page-digest verification of the delivered
    prefix;
  - non-recoverable records surface FAILED/CoordinatorRestarted —
    loudly, never a hang;
  - FAULT_SPOOL_CORRUPT_EVERY proves the PR-16 PageWireError path:
    sparse corruption recovers via same-token re-fetch, total
    corruption fails the query cleanly (satellite 3).
"""

import json
import threading
import time
import urllib.request

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.dist.checkpoint import (
    CheckpointJournal,
    CoordinatorRestarted,
    page_digest,
)
from presto_tpu.runner import LocalRunner
from presto_tpu.server import PrestoTpuServer
from presto_tpu.server.worker import WorkerServer

SF = 0.01
PAGE_ROWS = 1 << 13

# the 3-stage Q13-family shape (test_stagedag.DAG_QUERY): every
# producer stage spools, the root agg drains stage 2 — the spooled
# surface a coordinator restart must re-attach to
DAG_QUERY = (
    "select n_name, count(*), sum(top.c_count) from nation join ("
    "  select c_nationkey nk, c_custkey ck, count(o_orderkey) c_count"
    "  from customer left join orders on c_custkey = o_custkey"
    "  group by c_nationkey, c_custkey) top on n_nationkey = top.nk "
    "group by n_name order by n_name"
)

HDRS = {"X-Presto-Session": "stage_scheduler=true",
        "Content-Type": "text/plain"}


# ------------------------------------------------------------ helpers


def _get(url):
    with urllib.request.urlopen(url, timeout=60) as r:
        return json.loads(r.read().decode())


def _post_statement(port, sql, headers=HDRS):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/statement",
        data=sql.encode(), headers=headers)
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read().decode())


def _drain(doc):
    """Follow nextUri to the end; returns all rows."""
    rows = []
    while True:
        if doc.get("error"):
            raise RuntimeError(str(doc["error"]))
        rows.extend(doc.get("data") or [])
        nxt = doc.get("nextUri")
        if not nxt:
            return rows
        time.sleep(0.01)
        doc = _get(nxt)


def _sorted(rows):
    return sorted(tuple(r) for r in rows)


def _post_fault(uri, **cfg):
    req = urllib.request.Request(
        f"{uri}/v1/fault", data=json.dumps(cfg).encode(),
        headers={"Content-Type": "application/json"})
    urllib.request.urlopen(req, timeout=5).close()


class _CreateCounter:
    """Record every task the workers are asked to create from arming
    until restore — the producer-re-launch pin's measurement point
    (TaskRuntime._submit_calls only counts under a fault knob, so the
    choke point itself is wrapped)."""

    def __init__(self, workers):
        self.created = []
        self._saved = []
        for _, w in workers:
            orig = w.create_task
            self._saved.append((w, orig))

            def counting(req, _orig=orig):
                self.created.append(req.get("taskId"))
                return _orig(req)

            w.create_task = counting

    def restore(self):
        for w, orig in self._saved:
            w.create_task = orig


# ------------------------------------------------------------ fixtures


@pytest.fixture(scope="module")
def workers():
    w1 = WorkerServer({"tpch": TpchConnector(SF)}, node_id="w1",
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    w2 = WorkerServer({"tpch": TpchConnector(SF)}, node_id="w2",
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    uris = [f"http://127.0.0.1:{w.start()}" for w in (w1, w2)]
    yield list(zip(uris, (w1, w2)))
    for w in (w1, w2):
        w.stop()


@pytest.fixture(scope="module")
def oracle():
    return LocalRunner({"tpch": TpchConnector(SF)},
                       page_rows=PAGE_ROWS)


def _server(workers, ckdir):
    srv = PrestoTpuServer(
        {"tpch": TpchConnector(SF)}, port=0, page_rows=PAGE_ROWS,
        worker_uris=[u for u, _ in workers],
        checkpoint_dir=str(ckdir))
    srv.start()
    return srv


def _park_query_at_root(srv, sql=DAG_QUERY, timeout=90):
    """Submit ``sql`` and park its scheduler just before the final
    drain (every producer stage spooled, nothing consumed) — the
    deterministic coordinator-kill window. The hook RAISES once
    released, so the superseded coordinator's thread dies instead of
    re-draining spools the successor owns. Returns (qid, journal
    record, release-event)."""
    park = threading.Event()

    def hook(sched):
        park.wait(timeout)
        raise RuntimeError("superseded coordinator: parked root "
                           "drain aborted by the test")

    srv._dcn._root_hook = hook
    doc = _post_statement(srv.port, sql)
    qid = doc["id"]
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        rec = srv._journal.pending().get(qid)
        if rec and rec.get("root") and rec.get("root_inputs") and \
                all(str(f) in rec["stages"]
                    for f in rec["root_inputs"]):
            return qid, rec, park
        time.sleep(0.05)
    raise AssertionError("stage/root barriers never reached the journal")


def _kill(srv, qid):
    """Simulate the crash: void the zombie thread's journal handle
    (a dead process cannot write) and take the server down. The park
    event stays UNSET so the thread sits harmlessly until teardown."""
    q = srv.manager.get(qid)
    if q is not None and q.checkpoint is not None:
        q.checkpoint.detach()
    srv.stop()


# ------------------------------------------------- journal round-trip


def test_journal_roundtrip_and_reload(tmp_path):
    j = CheckpointJournal(str(tmp_path))
    h = j.admit("q1", "select 1", {"user": "alice"}, "global")
    h.running()
    h.record_stage(0, key="stage0", parts=2, tasks=[
        {"uri": "http://w", "task_id": "q.f0.t0", "payload": {"a": 1}},
    ], replan_gen=0)
    h.record_root("BLOB", [0])
    h.record_drain(0, 0, 3, "abc")
    h.note_client_token(1, page_digest([[1]]))
    h.finished([{"name": "x", "type": "bigint"}], 1)

    j2 = CheckpointJournal(str(tmp_path))  # fresh process stand-in
    rec = j2.pending()["q1"]
    assert rec["state"] == "finished"
    assert rec["sql"] == "select 1"
    assert rec["session"] == {"user": "alice"}
    assert rec["stages"]["0"]["tasks"][0]["task_id"] == "q.f0.t0"
    assert rec["root"] == "BLOB" and rec["root_inputs"] == [0]
    assert rec["drain"]["0"]["0"] == {"next_token": 3, "sha": "abc"}
    assert rec["token"] == 1
    assert rec["page_sha"]["0"] == page_digest([[1]])

    # claim_once: the re-attach pass runs exactly once per boot
    assert j2.claim_reattach()
    assert not j2.claim_reattach()

    h.delivered()
    assert "q1" not in CheckpointJournal(str(tmp_path)).pending()


def test_detached_handle_never_writes(tmp_path):
    j = CheckpointJournal(str(tmp_path))
    h = j.admit("q1", "select 1", {}, None)
    h.detach()
    h.note_client_token(5, "x")  # must be a no-op, not a crash
    h.failed("boom")
    assert CheckpointJournal(str(tmp_path)).pending()["q1"]["token"] == 0


class _Ctr:
    def __init__(self):
        self.checkpoint_drops = 0
        self.checkpoints_written = 0


def test_journal_corrupt_record_drops_loudly(tmp_path):
    j = CheckpointJournal(str(tmp_path))
    j.admit("q1", "select 1", {}, None)
    j.admit("q2", "select 2", {}, None)
    from presto_tpu.cache.persist import manifest_files

    _, path = manifest_files(str(tmp_path), stem="journal")[0]
    lines = open(path).read().splitlines()
    # bit-rot q2's record line; WAL recovery keeps the intact prefix
    # (header + q1) and drops from the first unparseable line on
    garbled = [ln[: len(ln) // 2] + "#GARBAGE#" if '"q2"' in ln else ln
               for ln in lines]
    open(path, "w").write("\n".join(garbled) + "\n")

    ctr = _Ctr()
    j2 = CheckpointJournal(str(tmp_path), counter_ex=ctr)
    assert "q1" in j2.pending() and "q2" not in j2.pending()
    assert ctr.checkpoint_drops >= 1


def test_journal_truncated_tail_drops_loudly(tmp_path):
    j = CheckpointJournal(str(tmp_path))
    j.admit("q1", "select 1", {}, None)
    j.admit("q2", "select 2", {}, None)
    from presto_tpu.cache.persist import manifest_files

    _, path = manifest_files(str(tmp_path), stem="journal")[0]
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[: len(raw) - 7])  # torn final record

    ctr = _Ctr()
    j2 = CheckpointJournal(str(tmp_path), counter_ex=ctr)
    # the intact prefix survives; the torn tail drops loudly
    assert "q1" in j2.pending()
    assert ctr.checkpoint_drops >= 1
    # and the journal keeps working after recovery
    j2.admit("q3", "select 3", {}, None)
    assert "q3" in CheckpointJournal(str(tmp_path)).pending()


def test_journal_version_skew_drops_loudly(tmp_path):
    j = CheckpointJournal(str(tmp_path))
    j.admit("q1", "select 1", {}, None)
    from presto_tpu.cache.persist import (
        read_manifest_doc,
        rewrite_manifest_doc,
    )

    doc = read_manifest_doc(str(tmp_path), stem="journal")
    doc["version"] = 99
    rewrite_manifest_doc(str(tmp_path), doc, stem="journal")

    ctr = _Ctr()
    j2 = CheckpointJournal(str(tmp_path), counter_ex=ctr)
    assert j2.pending() == {}
    assert ctr.checkpoint_drops >= 1


def test_concurrent_checkpoint_writers(tmp_path):
    from presto_tpu.obs import sanitizer as SAN

    was = SAN.is_armed()
    SAN.arm()
    before = len(SAN.violations())
    try:
        j = CheckpointJournal(str(tmp_path))

        def write(i):
            for n in range(20):
                h = j.admit(f"q{i}_{n}", f"select {n}", {}, None)
                h.running()
                h.note_client_token(1, "sha")
                if n % 3 == 0:
                    h.delivered()
                else:
                    h.finished([], 0)

        threads = [threading.Thread(target=write, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(SAN.violations()) == before
        reloaded = CheckpointJournal(str(tmp_path))
        # n in {0,3,6,9,12,15,18} delivered per writer: 7 of 20 gone
        assert len(reloaded.pending()) == 6 * (20 - 7)
        assert reloaded._store.broken_count == 0
    finally:
        if not was:
            SAN.disarm()


# ------------------------------------------------- crash re-attach


@pytest.mark.slow
def test_reattach_identical_rows_zero_relaunches(
        workers, oracle, tmp_path):
    """THE acceptance pin: coordinator replaced mid-query (all
    producer stages spooled, final drain not started) — the client's
    nextUri stream resumes with identical rows, coordinator_reattaches
    == 1, and the resumed suffix launches ZERO producer tasks."""
    want = _sorted(oracle.execute(DAG_QUERY).rows)
    srv = _server(workers, tmp_path)
    park = None
    ctr = None
    srv2 = None
    try:
        qid, rec, park = _park_query_at_root(srv)
        _kill(srv, qid)

        ctr = _CreateCounter(workers)
        srv2 = _server(workers, tmp_path)
        doc = _get(f"http://127.0.0.1:{srv2.port}/v1/statement/{qid}/0")
        got = _drain(doc)
        assert _sorted(got) == want
        ex = srv2._runner.executor
        assert ex.coordinator_reattaches == 1
        assert ex.reattach_redispatches == 0
        # zero producer re-launches: every stage was served from the
        # surviving spools
        assert ctr.created == []
        # stream delivered -> record dropped (size governance)
        assert qid not in srv2._journal.pending()
    finally:
        if ctr is not None:
            ctr.restore()
        if park is not None:
            park.set()
        if srv2 is not None:
            srv2.stop()


@pytest.mark.slow
def test_reattach_redispatches_dead_spool(workers, oracle, tmp_path):
    """One final-stage spool killed between the crash and the restart:
    ONLY that task re-dispatches (a .ra id from its persisted
    payload); rows stay identical."""
    want = _sorted(oracle.execute(DAG_QUERY).rows)
    srv = _server(workers, tmp_path)
    park = None
    ctr = None
    srv2 = None
    try:
        qid, rec, park = _park_query_at_root(srv)
        _kill(srv, qid)

        fid = rec["root_inputs"][0]
        victim = rec["stages"][str(fid)]["tasks"][0]
        req = urllib.request.Request(
            f"{victim['uri']}/v1/task/{victim['task_id']}",
            method="DELETE")
        urllib.request.urlopen(req, timeout=5).close()

        ctr = _CreateCounter(workers)
        srv2 = _server(workers, tmp_path)
        doc = _get(f"http://127.0.0.1:{srv2.port}/v1/statement/{qid}/0")
        got = _drain(doc)
        assert _sorted(got) == want
        ex = srv2._runner.executor
        assert ex.coordinator_reattaches == 1
        assert ex.reattach_redispatches >= 1
        # only the lost suffix re-dispatched: .ra task ids, and no
        # other producer re-launched
        assert ctr.created and all(".ra" in t for t in ctr.created)
    finally:
        if ctr is not None:
            ctr.restore()
        if park is not None:
            park.set()
        if srv2 is not None:
            srv2.stop()


@pytest.mark.slow
def test_mid_stream_restart_resumes_at_token(workers, oracle, tmp_path):
    """FINISHED but not fully delivered: the restarted coordinator
    regenerates the rows, verifies the delivered prefix against the
    persisted page digests, and the client resumes AT its token —
    no duplicate and no missing rows."""
    sql = ("select l_orderkey, l_linenumber, l_quantity from lineitem "
           "order by l_orderkey, l_linenumber")
    want = _sorted(oracle.execute(sql).rows)
    srv = _server(workers, tmp_path)
    srv2 = None
    try:
        doc = _post_statement(srv.port, sql)
        qid = doc["id"]
        # consume EXACTLY one data page, remember where we stopped
        rows, nxt = [], None
        while True:
            if doc.get("error"):
                raise RuntimeError(str(doc["error"]))
            chunk = doc.get("data") or []
            rows.extend(chunk)
            nxt = doc.get("nextUri")
            if chunk or not nxt:
                break
            time.sleep(0.01)
            doc = _get(nxt)
        assert rows and nxt, "need a multi-page stream to test resume"
        token = int(nxt.rstrip("/").rsplit("/", 1)[1])
        srv.stop()

        srv2 = _server(workers, tmp_path)
        doc = _get(f"http://127.0.0.1:{srv2.port}"
                   f"/v1/statement/{qid}/{token}")
        got = rows + _drain(doc)
        assert len(got) == len(want)
        assert _sorted(got) == want  # no duplicate, no missing rows
        assert srv2._runner.executor.coordinator_reattaches == 1
        assert qid not in srv2._journal.pending()
    finally:
        if srv2 is not None:
            srv2.stop()


def test_nonrecoverable_surfaces_failed(tmp_path):
    """A journaled query with no spools and no re-runnable statement
    must become FAILED/CoordinatorRestarted — loudly, never a hang."""
    j = CheckpointJournal(str(tmp_path))
    j.admit("deadq", "", {}, None)
    del j

    srv = PrestoTpuServer({"tpch": TpchConnector(SF)}, port=0,
                          page_rows=PAGE_ROWS,
                          checkpoint_dir=str(tmp_path))
    try:
        q = srv.manager.get("deadq")
        assert q is not None
        assert q.done.wait(30), "re-attach hung instead of failing"
        assert q.state == "FAILED"
        assert q.error["errorName"] == "CoordinatorRestarted"
        # and the journal remembers the failure for the next boot
        rec = CheckpointJournal(str(tmp_path)).pending()["deadq"]
        assert rec["state"] == "failed"
    finally:
        srv.stop()


def test_reattach_query_no_plane_raises():
    from presto_tpu.dist.checkpoint import reattach_query

    class _Ex:
        coordinator_reattaches = 0

        def count_reattach(self):
            self.coordinator_reattaches += 1

    with pytest.raises(CoordinatorRestarted):
        reattach_query({"sql": "select 1"}, None, _Ex())


# ------------------------------------------- spool-corruption fault


@pytest.mark.slow
def test_spool_corrupt_fault_recovers_and_fails_loudly(
        workers, oracle, tmp_path):
    """FAULT_SPOOL_CORRUPT_EVERY (satellite 3): sparse wire corruption
    recovers via same-token re-fetch through the PageWireError path;
    total corruption climbs the replay ladder and fails the query
    CLEANLY — never garbage rows. Must run over real HTTP: the
    mesh-local fast path has no wire to corrupt."""
    from presto_tpu.dist.dcn import DcnQueryFailed, DcnRunner
    from presto_tpu.server.worker import unregister_local_runtime

    uris = [u for u, _ in workers]
    for u in uris:
        unregister_local_runtime(u)
    coord = DcnRunner(
        {"tpch": TpchConnector(SF)}, uris, default_catalog="tpch",
        page_rows=PAGE_ROWS,
        session_props={"stage_scheduler": "true",
                       "retry_backoff_ms": 20},
    )
    try:
        want = _sorted(oracle.execute(DAG_QUERY).rows)
        # sparse corruption: every 7th served body flips a bit —
        # bounded same-token retries absorb it
        for u in uris:
            _post_fault(u, FAULT_SPOOL_CORRUPT_EVERY=7)
        got = coord.execute(DAG_QUERY)
        assert _sorted(got) == want

        # total corruption: every fetch is garbage — the query fails
        # loudly through the ladder, with the corrupt-frame cause
        for u in uris:
            _post_fault(u, FAULT_SPOOL_CORRUPT_EVERY=1)
        with pytest.raises(DcnQueryFailed, match="PageWireError|corrupt"):
            coord.execute(DAG_QUERY)
    finally:
        for u in uris:
            _post_fault(u)  # {} restores env-ruled (off) fault mode
        coord.close()
