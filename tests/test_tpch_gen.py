"""TPC-H generator tests: cardinalities, key domains, cross-table
consistency (the properties queries rely on)."""

import numpy as np
import pytest

from presto_tpu.connectors.tpch import (
    CURRENTDATE,
    MAX_LINES_PER_ORDER,
    ORDERDATE_MAX,
    STARTDATE,
    TpchConnector,
)


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(0.005)  # 750 customers, 7500 orders


def _host(conn, table, cols=None):
    pages = list(conn.pages(table, columns=cols, target_rows=1 << 20))
    from presto_tpu.exec.executor import concat_all

    page = concat_all(pages) if len(pages) > 1 else pages[0]
    valid = np.asarray(page.valid)
    out = {}
    names = cols or conn.table_schema(table).column_names()
    for name, blk in zip(names, page.blocks):
        if isinstance(blk.data, tuple):
            out[name] = (np.asarray(blk.data[0])[valid],
                         np.asarray(blk.data[1])[valid])
        else:
            out[name] = np.asarray(blk.data)[valid]
    return out


def test_cardinalities(conn):
    assert conn.n_customer == 750
    assert conn.n_orders == 7500
    assert conn.row_count("region") == 5
    assert conn.row_count("nation") == 25
    assert conn.row_count("partsupp") == conn.n_part * 4


def test_orderkeys_sparse_and_unique(conn):
    o = _host(conn, "orders", ["o_orderkey"])["o_orderkey"]
    assert len(np.unique(o)) == conn.n_orders
    # sparse pattern: keys mod 32 land in 1..8
    assert ((o - 1) % 32 < 8).all()


def test_custkey_skips_multiples_of_three(conn):
    ck = _host(conn, "orders", ["o_custkey"])["o_custkey"]
    assert (ck % 3 != 0).all()
    assert ck.min() >= 1 and ck.max() <= conn.n_customer


def test_lineitem_count_and_dates(conn):
    li = _host(conn, "lineitem",
               ["l_orderkey", "l_shipdate", "l_commitdate", "l_receiptdate",
                "l_linenumber"])
    n = len(li["l_orderkey"])
    # expected ~4 lines/order
    assert conn.n_orders * 3 < n < conn.n_orders * 5
    assert (li["l_shipdate"] > STARTDATE).all()
    assert (li["l_receiptdate"] > li["l_shipdate"]).all()
    assert (li["l_linenumber"] >= 1).all()
    assert (li["l_linenumber"] <= MAX_LINES_PER_ORDER).all()


def test_orderdate_window(conn):
    od = _host(conn, "orders", ["o_orderdate"])["o_orderdate"]
    assert od.min() >= STARTDATE and od.max() <= ORDERDATE_MAX


def test_chunking_invariance(conn):
    """Column values are functions of global row keys, independent of split
    boundaries (prereq for mesh sharding)."""
    a = _host(conn, "orders", ["o_orderkey", "o_totalprice"])
    pages = list(conn.pages("orders", ["o_orderkey", "o_totalprice"],
                            target_rows=997))
    ok = np.concatenate(
        [np.asarray(p.block(0).data)[np.asarray(p.valid)] for p in pages]
    )
    tp = np.concatenate(
        [np.asarray(p.block(1).data)[np.asarray(p.valid)] for p in pages]
    )
    np.testing.assert_array_equal(a["o_orderkey"], ok)
    np.testing.assert_array_equal(a["o_totalprice"], tp)


def test_totalprice_consistent_with_lineitems(conn):
    li = _host(conn, "lineitem",
               ["l_orderkey", "l_extendedprice", "l_discount", "l_tax"])
    o = _host(conn, "orders", ["o_orderkey", "o_totalprice"])
    charge = (
        li["l_extendedprice"].astype(object)
        * (100 - li["l_discount"])
        * (100 + li["l_tax"])
        + 5000
    ) // 10000
    sums = {}
    for k, c in zip(li["l_orderkey"], charge):
        sums[k] = sums.get(k, 0) + c
    expect = np.array([sums[k] for k in o["o_orderkey"]], dtype=np.int64)
    np.testing.assert_array_equal(o["o_totalprice"], expect)


def test_lineitem_suppkey_join_consistent(conn):
    """l_(partkey, suppkey) always exists in partsupp (Q9 prerequisite)."""
    li = _host(conn, "lineitem", ["l_partkey", "l_suppkey"])
    ps = _host(conn, "partsupp", ["ps_partkey", "ps_suppkey"])
    pairs = set(zip(ps["ps_partkey"].tolist(), ps["ps_suppkey"].tolist()))
    sample = list(zip(li["l_partkey"].tolist(),
                      li["l_suppkey"].tolist()))[:2000]
    assert all(p in pairs for p in sample)


def test_retailprice_formula(conn):
    p = _host(conn, "part", ["p_partkey", "p_retailprice"])
    pk = p["p_partkey"]
    expect = 90000 + (pk // 10) % 20001 + 100 * (pk % 1000)
    np.testing.assert_array_equal(p["p_retailprice"], expect)


def test_returnflag_linestatus_rule(conn):
    li = _host(conn, "lineitem",
               ["l_shipdate", "l_receiptdate", "l_returnflag",
                "l_linestatus"])
    pages = list(conn.pages("lineitem",
                            ["l_returnflag", "l_linestatus",
                             "l_shipdate", "l_receiptdate"]))
    # decode through dictionaries
    from presto_tpu.exec.executor import concat_all

    page = concat_all(pages) if len(pages) > 1 else pages[0]
    rows = page.to_pylist()
    for rf, ls, ship, receipt in rows[:5000]:
        if receipt <= CURRENTDATE:
            assert rf in ("A", "R")
        else:
            assert rf == "N"
        assert ls == ("O" if ship > CURRENTDATE else "F")


def test_nation_region_fixed(conn):
    n = list(conn.pages("nation"))[0].to_pylist()
    assert len(n) == 25
    assert n[0][1] == "ALGERIA" and n[24][1] == "UNITED STATES"
    r = list(conn.pages("region"))[0].to_pylist()
    assert [row[1] for row in r] == [
        "AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"
    ]


def test_pattern_dictionary_roundtrip(conn):
    c = _host(conn, "customer", ["c_custkey"])
    pages = list(conn.pages("customer", ["c_custkey", "c_name"]))
    from presto_tpu.exec.executor import concat_all

    page = concat_all(pages) if len(pages) > 1 else pages[0]
    for ck, name in page.to_pylist()[:100]:
        assert name == f"Customer#{ck:09d}"
