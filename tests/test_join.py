"""Join kernel oracle tests vs nested-loop Python joins (reference analog:
TestHashJoinOperator)."""

import jax.numpy as jnp
import numpy as np

from presto_tpu.ops import join as J


def _encode(arr):
    return jnp.asarray(np.asarray(arr, dtype=np.int64)).astype(jnp.uint64)


def _oracle_inner(build, probe, bvalid, pvalid):
    out = []
    for pi, pv in enumerate(probe):
        if not pvalid[pi] or pv is None:
            continue
        for bi, bv in enumerate(build):
            if bvalid[bi] and bv is not None and bv == pv:
                out.append((pi, bi))
    return sorted(out)


def test_inner_join_with_duplicates(rng):
    build = rng.integers(0, 10, size=40).tolist()
    probe = rng.integers(0, 12, size=60).tolist()
    bvalid = (rng.random(40) < 0.9).tolist()
    pvalid = (rng.random(60) < 0.9).tolist()

    m = J.hash_join_match(
        [_encode(build)],
        [None],
        jnp.asarray(bvalid),
        [_encode(probe)],
        [None],
        jnp.asarray(pvalid),
        out_capacity=512,
    )
    got = sorted(
        (int(p), int(b))
        for p, b, ok in zip(
            np.asarray(m.probe_idx), np.asarray(m.build_idx), np.asarray(m.match)
        )
        if ok
    )
    assert got == _oracle_inner(build, probe, bvalid, pvalid)
    assert not bool(m.overflow)


def test_join_null_keys_never_match():
    build = [1, 2, 3]
    bnull = jnp.asarray([False, True, False])
    probe = [2, 1, 5]
    pnull = jnp.asarray([False, False, True])
    m = J.hash_join_match(
        [_encode(build)],
        [bnull],
        jnp.ones(3, dtype=bool),
        [_encode(probe)],
        [pnull],
        jnp.ones(3, dtype=bool),
        out_capacity=16,
    )
    got = {
        (int(p), int(b))
        for p, b, ok in zip(
            np.asarray(m.probe_idx), np.asarray(m.build_idx), np.asarray(m.match)
        )
        if ok
    }
    assert got == {(1, 0)}  # probe row 1 (=1) matches build row 0 (=1)


def test_join_null_equals_null_mode():
    build = [1, 0]
    bnull = jnp.asarray([False, True])
    probe = [0, 1]
    pnull = jnp.asarray([True, False])
    m = J.hash_join_match(
        [_encode(build)],
        [bnull],
        jnp.ones(2, dtype=bool),
        [_encode(probe)],
        [pnull],
        jnp.ones(2, dtype=bool),
        out_capacity=8,
        null_equals_null=True,
    )
    got = {
        (int(p), int(b))
        for p, b, ok in zip(
            np.asarray(m.probe_idx), np.asarray(m.build_idx), np.asarray(m.match)
        )
        if ok
    }
    assert got == {(0, 1), (1, 0)}


def test_join_null_equals_null_asymmetric_masks():
    """null_equals_null with a nulls mask on only one side must still match
    (regression: asymmetric key-column counts made hashes diverge)."""
    build = [1, 2, 0]
    bnull = jnp.asarray([False, False, True])
    probe = [1, 2]
    m = J.hash_join_match(
        [_encode(build)],
        [bnull],
        jnp.ones(3, dtype=bool),
        [_encode(probe)],
        [None],
        jnp.ones(2, dtype=bool),
        out_capacity=8,
        null_equals_null=True,
    )
    got = {
        (int(p), int(b))
        for p, b, ok in zip(
            np.asarray(m.probe_idx), np.asarray(m.build_idx), np.asarray(m.match)
        )
        if ok
    }
    assert got == {(0, 0), (1, 1)}


def test_multi_key_join(rng):
    n_b, n_p = 30, 50
    b1 = rng.integers(0, 4, size=n_b)
    b2 = rng.integers(0, 4, size=n_b)
    p1 = rng.integers(0, 4, size=n_p)
    p2 = rng.integers(0, 4, size=n_p)
    m = J.hash_join_match(
        [_encode(b1), _encode(b2)],
        [None, None],
        jnp.ones(n_b, dtype=bool),
        [_encode(p1), _encode(p2)],
        [None, None],
        jnp.ones(n_p, dtype=bool),
        out_capacity=1024,
    )
    got = sorted(
        (int(p), int(b))
        for p, b, ok in zip(
            np.asarray(m.probe_idx), np.asarray(m.build_idx), np.asarray(m.match)
        )
        if ok
    )
    oracle = sorted(
        (pi, bi)
        for pi in range(n_p)
        for bi in range(n_b)
        if b1[bi] == p1[pi] and b2[bi] == p2[pi]
    )
    assert got == oracle


def test_probe_match_count_and_build_matched():
    build = [1, 1, 2, 9]
    probe = [1, 3, 2]
    m = J.hash_join_match(
        [_encode(build)],
        [None],
        jnp.ones(4, dtype=bool),
        [_encode(probe)],
        [None],
        jnp.ones(3, dtype=bool),
        out_capacity=16,
    )
    np.testing.assert_array_equal(np.asarray(m.probe_match_count), [2, 0, 1])
    np.testing.assert_array_equal(
        np.asarray(m.build_matched), [True, True, True, False]
    )


def test_join_overflow_flag():
    build = [7] * 8
    probe = [7] * 8
    m = J.hash_join_match(
        [_encode(build)],
        [None],
        jnp.ones(8, dtype=bool),
        [_encode(probe)],
        [None],
        jnp.ones(8, dtype=bool),
        out_capacity=16,  # need 64
    )
    assert bool(m.overflow)


def test_semi_join_three_valued_logic():
    build = [1, 2, 0]
    bnull = jnp.asarray([False, False, True])
    probe = [1, 5, 0]
    pnull = jnp.asarray([False, False, True])
    has, null_res = J.semi_join_mask(
        [_encode(build)],
        [bnull],
        jnp.ones(3, dtype=bool),
        [_encode(probe)],
        [pnull],
        jnp.ones(3, dtype=bool),
    )
    # 1 IN {1,2,NULL} -> true; 5 IN {...NULL} -> NULL; NULL IN ... -> NULL
    np.testing.assert_array_equal(np.asarray(has), [True, False, False])
    np.testing.assert_array_equal(np.asarray(null_res), [False, True, True])
