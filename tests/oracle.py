"""sqlite3-based SQL oracle for engine correctness tests.

Reference test pattern: presto-tests tests/H2QueryRunner — TPC-H correctness
suites compare engine output against an embedded relational database over
the same data. We load the deterministic TPC-H pages into sqlite with
engine-internal encodings (decimals as unscaled ints, dates as epoch days)
so integer math is exact on both sides.
"""

import sqlite3
from typing import Dict, Iterable, List, Optional

from presto_tpu import types as T
from presto_tpu.connectors.base import Connector


def _sqlite_type(t: T.SqlType) -> str:
    if T.is_string(t):
        return "TEXT"
    if T.is_floating(t):
        return "REAL"
    return "INTEGER"


def load_sqlite(
    connector: Connector,
    tables: Iterable[str],
    target_rows: int = 1 << 20,
) -> sqlite3.Connection:
    db = sqlite3.connect(":memory:")
    for table in tables:
        schema = connector.table_schema(table)
        cols = ", ".join(
            f"{c.name} {_sqlite_type(c.type)}" for c in schema.columns
        )
        db.execute(f"CREATE TABLE {table} ({cols})")
        placeholders = ", ".join("?" for _ in schema.columns)
        rows = connector.host_rows(table, target_rows=target_rows)
        db.executemany(
            f"INSERT INTO {table} VALUES ({placeholders})", rows
        )
    db.commit()
    return db


def rows_match(engine_rows: List[tuple], oracle_rows: List[tuple],
               float_cols: Optional[set] = None, tol: float = 1e-9) -> None:
    """Order-sensitive row comparison with exact ints and tolerant floats."""
    assert len(engine_rows) == len(oracle_rows), (
        f"row count mismatch: engine {len(engine_rows)} vs oracle "
        f"{len(oracle_rows)}\nengine head: {engine_rows[:3]}\n"
        f"oracle head: {oracle_rows[:3]}"
    )
    float_cols = float_cols or set()
    for i, (er, orow) in enumerate(zip(engine_rows, oracle_rows)):
        assert len(er) == len(orow), f"row {i} arity mismatch"
        for j, (ev, ov) in enumerate(zip(er, orow)):
            if j in float_cols and ev is not None and ov is not None:
                assert abs(float(ev) - float(ov)) <= tol * max(
                    1.0, abs(float(ov))
                ), f"row {i} col {j}: {ev} != {ov}"
            else:
                assert ev == ov, f"row {i} col {j}: {ev!r} != {ov!r}"
