"""sqlite3-based SQL oracle for engine correctness tests.

Reference test pattern: presto-tests tests/H2QueryRunner — TPC-H correctness
suites compare engine output against an embedded relational database over
the same data. We load the deterministic TPC-H pages into sqlite with
engine-internal encodings (decimals as unscaled ints, dates as epoch days)
so integer math is exact on both sides.
"""

import hashlib
import os
import sqlite3
import tempfile
from typing import Dict, Iterable, List, Optional

from presto_tpu import types as T
from presto_tpu.connectors.base import Connector

# Disk cache for loaded oracle databases: decoding the deterministic
# generator pages into sqlite is pure (connector class, scale, tables)
# — and slow enough that the bench oracle phase never finished inside
# its 240s reserve (VERDICT Weak #8). Loaded DBs persist as sqlite
# files keyed by the load's content fingerprint; cache hits open the
# file READ-ONLY (uri mode=ro), so a test that tried to mutate a
# shared oracle fails loudly instead of poisoning later runs.
# Point PRESTO_TPU_ORACLE_CACHE_DIR elsewhere, or at "" to disable.
_CACHE_DIR = os.environ.get(
    "PRESTO_TPU_ORACLE_CACHE_DIR", "/tmp/presto_tpu_oracle_cache"
)


def _sqlite_type(t: T.SqlType) -> str:
    if T.is_string(t):
        return "TEXT"
    if T.is_floating(t):
        return "REAL"
    return "INTEGER"


def _cache_key(connector, tables, target_rows: int) -> Optional[str]:
    """Content fingerprint of one oracle load, or None when the load
    is not cacheable. Only the bare deterministic generator connectors
    cache: wrappers (split filtering, caching, memory tables) produce
    host_rows that depend on wrapper state the key cannot see."""
    if not _CACHE_DIR:
        return None
    from presto_tpu.connectors.tpcds import TpcdsConnector
    from presto_tpu.connectors.tpch import TpchConnector

    if type(connector) not in (TpchConnector, TpcdsConnector):
        return None
    h = hashlib.sha1()
    h.update(type(connector).__name__.encode())
    h.update(repr(getattr(connector, "scale", None)).encode())
    h.update(repr(int(target_rows)).encode())
    for table in tables:
        schema = connector.table_schema(table)
        h.update(table.encode())
        h.update(repr(
            [(c.name, str(c.type)) for c in schema.columns]
        ).encode())
        # row_count rides in the key so a generator change that moves
        # cardinality invalidates; value changes at equal cardinality
        # need a cache wipe (the dir is /tmp — cheap and explicit)
        h.update(repr(connector.row_count(table)).encode())
    return h.hexdigest()


def load_sqlite(
    connector: Connector,
    tables: Iterable[str],
    target_rows: int = 1 << 20,
) -> sqlite3.Connection:
    tables = list(tables)
    key = _cache_key(connector, tables, target_rows)
    path = os.path.join(_CACHE_DIR, f"oracle_{key}.db") if key else None
    if path and os.path.exists(path):
        return sqlite3.connect(f"file:{path}?mode=ro", uri=True)
    if path:
        os.makedirs(_CACHE_DIR, exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            dir=_CACHE_DIR, suffix=".db.building"
        )
        os.close(fd)
        db = sqlite3.connect(tmp)
    else:
        tmp = None
        db = sqlite3.connect(":memory:")
    try:
        for table in tables:
            schema = connector.table_schema(table)
            cols = ", ".join(
                f"{c.name} {_sqlite_type(c.type)}"
                for c in schema.columns
            )
            db.execute(f"CREATE TABLE {table} ({cols})")
            placeholders = ", ".join("?" for _ in schema.columns)
            rows = connector.host_rows(table, target_rows=target_rows)
            db.executemany(
                f"INSERT INTO {table} VALUES ({placeholders})", rows
            )
        db.commit()
    except BaseException:
        # the load is the slow phase — an interrupted build must not
        # orphan a partial .db.building file in the shared cache dir
        if tmp is not None:
            db.close()
            try:
                os.unlink(tmp)
            except OSError:
                pass
        raise
    if tmp is not None:
        # atomic publish: concurrent pytest processes building the
        # same key race harmlessly (last rename wins, both complete)
        db.close()
        os.replace(tmp, path)
        return sqlite3.connect(f"file:{path}?mode=ro", uri=True)
    return db


def rows_match(engine_rows: List[tuple], oracle_rows: List[tuple],
               float_cols: Optional[set] = None, tol: float = 1e-9) -> None:
    """Order-sensitive row comparison with exact ints and tolerant floats."""
    assert len(engine_rows) == len(oracle_rows), (
        f"row count mismatch: engine {len(engine_rows)} vs oracle "
        f"{len(oracle_rows)}\nengine head: {engine_rows[:3]}\n"
        f"oracle head: {oracle_rows[:3]}"
    )
    float_cols = float_cols or set()
    for i, (er, orow) in enumerate(zip(engine_rows, oracle_rows)):
        assert len(er) == len(orow), f"row {i} arity mismatch"
        for j, (ev, ov) in enumerate(zip(er, orow)):
            if j in float_cols and ev is not None and ov is not None:
                assert abs(float(ev) - float(ov)) <= tol * max(
                    1.0, abs(float(ov))
                ), f"row {i} col {j}: {ev} != {ov}"
            else:
                assert ev == ov, f"row {i} col {j}: {ev!r} != {ov!r}"
