"""Executor wiring of the Pallas join paths — the unique-key fast path
and the radix-partitioned general join (pallas_join_enabled session
property). Reference: the north-star's Pallas radix hash join (SURVEY
§8.2.2); the kernels are covered by test_pallas_join.py — these tests
cover eligibility selection and end-to-end parity with the sort join."""

import collections

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runner import LocalRunner


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(0.01)


@pytest.fixture(scope="module")
def base(conn):
    return LocalRunner({"tpch": conn}, page_rows=1 << 13)


@pytest.fixture(scope="module")
def pallas(conn):
    r = LocalRunner({"tpch": conn}, page_rows=1 << 13)
    r.session.set("pallas_join_enabled", "true")
    # these tests assert the PALLAS path engages; the build-free
    # generated join (default) would preempt it for generator tables
    r.session.set("generated_join_enabled", False)
    return r


def _same(a, b):
    return collections.Counter(map(repr, a)) == collections.Counter(
        map(repr, b)
    )


def test_inner_join_parity_and_engagement(base, pallas):
    q = ("select o_orderkey, o_totalprice, l_extendedprice from orders, "
         "lineitem where o_orderkey = l_orderkey "
         "order by 1, 3 limit 9")
    before = pallas.executor.pallas_joins_used
    assert _same(base.execute(q).rows, pallas.execute(q).rows)
    assert pallas.executor.pallas_joins_used > before


def test_left_join_null_extension(base, pallas):
    # lineitem pages are 7-aligned (capacity 8190, NOT a Pallas block
    # multiple — exercises probe padding); every lineitem matches an
    # order, so also check an artificial no-match band via a filtered
    # build side (unique o_orderkey survives a Filter)
    q = ("select count(*), sum(o_totalprice) from lineitem "
         "left join orders on l_orderkey = o_orderkey")
    before = pallas.executor.pallas_joins_used
    assert _same(base.execute(q).rows, pallas.execute(q).rows)
    assert pallas.executor.pallas_joins_used > before
    q2 = ("select count(*), count(o_orderkey) from lineitem left join "
          "(select * from orders where o_orderkey < 1000) t "
          "on l_orderkey = o_orderkey")
    before = pallas.executor.pallas_joins_used
    a, b = base.execute(q2).rows, pallas.execute(q2).rows
    assert _same(a, b)
    assert pallas.executor.pallas_joins_used > before
    # unmatched rows null-extended: count(*) > count(o_orderkey)
    assert b[0][0] > b[0][1] > 0


def test_non_unique_build_falls_back(base, pallas):
    # build side lineitem: l_orderkey is NOT declared unique — must
    # take the general join, not the Pallas path
    before = pallas.executor.pallas_joins_used
    q = ("select count(*) from orders where o_orderkey in "
         "(select l_orderkey from lineitem)")
    assert _same(base.execute(q).rows, pallas.execute(q).rows)
    # semi joins are ineligible regardless; counter must not move
    assert pallas.executor.pallas_joins_used == before


def test_aggregate_over_pallas_join(base, pallas):
    q = ("select c_mktsegment, count(*), sum(o_totalprice) from orders, "
         "customer where o_custkey = c_custkey group by c_mktsegment "
         "order by 1")
    assert _same(base.execute(q).rows, pallas.execute(q).rows)


# ----------------------------------------------------- radix general join


def test_radix_duplicate_key_self_join(base, pallas):
    # self-join on NON-unique o_custkey: duplicate build keys fan out —
    # the radix kernel's (start, count) segment ranges, not the unique
    # fast path
    q = ("select count(*), sum(o1.o_totalprice) from orders o1, "
         "orders o2 where o1.o_custkey = o2.o_custkey")
    before = pallas.executor.pallas_joins_used
    assert _same(base.execute(q).rows, pallas.execute(q).rows)
    assert pallas.executor.pallas_joins_used > before


def test_radix_multi_key_join(base, pallas):
    # composite (partkey, suppkey) key: multi-key joins hash-combine
    # into one 64-bit row hash and verify per-column equality after
    # expansion
    q = ("select count(*), sum(ps_availqty) from lineitem, partsupp "
         "where l_partkey = ps_partkey and l_suppkey = ps_suppkey")
    before = pallas.executor.pallas_joins_used
    assert _same(base.execute(q).rows, pallas.execute(q).rows)
    assert pallas.executor.pallas_joins_used > before


def test_radix_outer_join(base, pallas):
    # unmatched-side emission (right/full) rides the radix match stats
    q = ("select count(*), count(o_orderkey), count(c_custkey) from "
         "(select * from orders where o_orderkey < 5000) o right join "
         "customer on o_custkey = c_custkey")
    before = pallas.executor.pallas_joins_used
    a, b = base.execute(q).rows, pallas.execute(q).rows
    assert _same(a, b)
    assert pallas.executor.pallas_joins_used > before


def test_radix_string_key_join(base, pallas):
    # dictionary-coded string keys canonicalize through the merged
    # universe before hashing — eligible for the radix path (the unique
    # fast path refuses strings)
    q = ("select count(*), min(n1.n_nationkey) from nation n1, "
         "nation n2 where n1.n_name = n2.n_name")
    before = pallas.executor.pallas_joins_used
    assert _same(base.execute(q).rows, pallas.execute(q).rows)
    assert pallas.executor.pallas_joins_used > before
