"""Ring-3.5: multi-process (DCN) distributed execution on localhost.

Reference: presto-tests tests/DistributedQueryRunner.java boots real
servers with real HTTP shuffle in one JVM; our DCN analog goes one
step further and uses real OS processes (separate JAX runtimes), per
SURVEY §6.3/§6.8 — the host page proxy is also where faults inject
(delay/drop/kill), since compiled ICI collectives cannot be faulted.

Process workers are expensive to boot (fresh XLA compiles), so most
tests share two in-process WorkerServers (threads — same HTTP protocol,
same serde boundary) and two tests pay for real subprocesses: the
end-to-end parity run and the kill-a-worker failure path.
"""

import collections
import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.dist.dcn import DcnQueryFailed, DcnRunner
from presto_tpu.runner import LocalRunner
from presto_tpu.server.worker import WorkerServer
from tests.tpch_queries import QUERIES

SF = 0.01
PAGE_ROWS = 1 << 13


@pytest.fixture(scope="module")
def single():
    return LocalRunner({"tpch": TpchConnector(SF)}, page_rows=PAGE_ROWS)


@pytest.fixture(scope="module")
def workers():
    w1 = WorkerServer({"tpch": TpchConnector(SF)}, node_id="w1",
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    w2 = WorkerServer({"tpch": TpchConnector(SF)}, node_id="w2",
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    uris = [f"http://127.0.0.1:{w1.start()}",
            f"http://127.0.0.1:{w2.start()}"]
    yield uris
    w1.stop()
    w2.stop()


@pytest.fixture(scope="module")
def coord(workers):
    c = DcnRunner({"tpch": TpchConnector(SF)}, workers,
                  default_catalog="tpch", page_rows=PAGE_ROWS)
    yield c
    c.close()


def rows_equal(a, b):
    return collections.Counter(map(repr, a)) == collections.Counter(
        map(repr, b)
    )


def _post_fault(uri, **cfg):
    """Set a worker's runtime fault overlay via the HTTP surface the
    chaos harness uses (no kwargs = restore env-ruled mode)."""
    req = urllib.request.Request(
        f"{uri}/v1/fault", data=json.dumps(cfg).encode(),
        headers={"Content-Type": "application/json"})
    urllib.request.urlopen(req, timeout=5).close()


@pytest.mark.parametrize("qid", [1, 6, 3])
def test_dcn_matches_single(qid, single, coord):
    want = single.execute(QUERIES[qid]).rows
    got = coord.execute(QUERIES[qid])
    assert rows_equal(want, got), f"Q{qid} diverged"


def test_dcn_approx_distinct(single, coord):
    q = ("select o_orderpriority, approx_distinct(o_custkey), "
         "sum(o_totalprice) from orders group by o_orderpriority")
    assert rows_equal(single.execute(q).rows, coord.execute(q))


def test_heartbeat_sees_workers(coord):
    coord.heartbeat.check_once()
    assert len(coord.heartbeat.alive_nodes()) == 2


def test_fault_delay_and_drop_recovered(workers, single, monkeypatch):
    """Injected page-proxy faults (delay + periodic HTTP 500) must be
    absorbed by the token-acked retry protocol — same rows, no error."""
    monkeypatch.setenv("FAULT_DELAY_MS", "20")
    monkeypatch.setenv("FAULT_DROP_EVERY", "3")
    coord = DcnRunner({"tpch": TpchConnector(SF)}, workers,
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    q = ("select l_returnflag, count(*), sum(l_quantity) "
         "from lineitem group by l_returnflag")
    want = single.execute(q).rows
    got = coord.execute(q)
    assert rows_equal(want, got)


def _boot_subprocess_worker(port_env, extra_env=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    for k in ("FAULT_DELAY_MS", "FAULT_DROP_EVERY",
              "FAULT_KILL_AFTER_FETCHES", "FAULT_SUBMIT_DROP_EVERY"):
        env.pop(k, None)
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "presto_tpu.server.worker",
         "--port", "0", "--suite", "tpch", "--scale", str(SF),
         "--page-rows", str(PAGE_ROWS)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=env, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
        text=True,
    )
    line = proc.stdout.readline()
    info = json.loads(line)
    return proc, f"http://127.0.0.1:{info['port']}"


@pytest.mark.slow
def test_two_real_processes_and_kill(single):
    """The VERDICT ring-3.5 gate, upgraded for fault-tolerant
    execution: Q3 across 2 real OS processes matches single-process;
    a worker that hard-exits MID-QUERY (FAULT_KILL_AFTER_FETCHES) is
    recovered by task re-dispatch — the query COMPLETES with
    single-process-identical rows and task_retries >= 1 — while
    task_retry_attempts=0 pins the old fail-query-cleanly contract."""
    p1, u1 = _boot_subprocess_worker(0)
    # w2 hard-exits after serving one results fetch: worker death in
    # the middle of the fetch loop, not before the query
    p2, u2 = _boot_subprocess_worker(
        0, extra_env={"FAULT_KILL_AFTER_FETCHES": "1"})
    coord = coord0 = None
    try:
        coord = DcnRunner({"tpch": TpchConnector(SF)}, [u1, u2],
                          default_catalog="tpch", page_rows=PAGE_ROWS,
                          fetch_retries=2,
                          session_props={"retry_backoff_ms": 20})
        want = single.execute(QUERIES[3]).rows
        got = coord.execute(QUERIES[3])
        assert rows_equal(want, got), \
            "Q3 with a mid-query worker kill diverged"
        ex = coord.runner.executor
        assert ex.task_retries >= 1, "recovery did not re-dispatch"
        assert ex.workers_excluded >= 1
        p2.wait(timeout=10)  # the fault hook really killed the process
        assert p2.poll() is not None

        # the killed worker stays excluded; a second query sails
        # through on the survivor alone
        got2 = coord.execute(QUERIES[3])
        assert rows_equal(want, got2)

        # pinned mode (task_retry_attempts=0): the classic contract —
        # a dead worker fails the QUERY cleanly, no task recovery
        coord0 = DcnRunner({"tpch": TpchConnector(SF)}, [u1, u2],
                           default_catalog="tpch", page_rows=PAGE_ROWS,
                           fetch_retries=2,
                           session_props={"task_retry_attempts": 0})
        with pytest.raises(DcnQueryFailed):
            coord0.execute(QUERIES[3])
    finally:
        for c in (coord, coord0):
            if c is not None:
                c.close()
        for p in (p1, p2):
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)


def test_submit_drop_recovers_to_other_worker(workers, single):
    """FAULT_SUBMIT_DROP_EVERY=1 makes one worker 500 every task
    submit; the coordinator's submit retry re-dispatches that split
    share to the other ALIVE worker and the query completes."""
    coord = DcnRunner({"tpch": TpchConnector(SF)}, workers,
                      default_catalog="tpch", page_rows=PAGE_ROWS,
                      session_props={"retry_backoff_ms": 10})
    _post_fault(workers[1], FAULT_SUBMIT_DROP_EVERY=1)
    try:
        q = ("select l_returnflag, count(*), sum(l_quantity) "
             "from lineitem group by l_returnflag")
        want = single.execute(q).rows
        got = coord.execute(q)
        assert rows_equal(want, got)
        assert coord.runner.executor.task_retries >= 1
        assert coord.runner.executor.workers_excluded >= 1
    finally:
        _post_fault(workers[1])
        coord.close()


def test_heartbeat_failed_node_never_picked(workers, single):
    """A node the heartbeat marks FAILED is excluded from the submit
    pool up front — the query completes on the survivors with ZERO
    recovery actions (no retries, no exclusions: it was never
    picked)."""
    dead_uri = "http://127.0.0.1:1"  # nothing listens there
    coord = DcnRunner({"tpch": TpchConnector(SF)},
                      list(workers) + [dead_uri],
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    try:
        for _ in range(3):  # fail_after=3 consecutive misses
            coord.heartbeat.check_once()
        assert not coord.heartbeat.is_alive(dead_uri)
        q = ("select o_orderpriority, count(*) from orders "
             "group by o_orderpriority")
        want = single.execute(q).rows
        got = coord.execute(q)
        assert rows_equal(want, got)
        assert coord.last_pool == list(workers)  # FAILED never picked
        assert coord.runner.executor.task_retries == 0
        assert coord.runner.executor.workers_excluded == 0
    finally:
        coord.close()


def test_dcn_query_deadline_expires(workers):
    """query_max_run_time is a real deadline: with a per-fetch injected
    delay longer than the deadline the query surfaces
    QueryDeadlineExceeded instead of hanging (the delay makes expiry
    deterministic even when the compile cache is warm)."""
    from presto_tpu.exec.executor import QueryDeadlineExceeded

    coord = DcnRunner({"tpch": TpchConnector(SF)}, workers,
                      default_catalog="tpch", page_rows=PAGE_ROWS,
                      session_props={"query_max_run_time": 400})
    _post_fault(workers[0], FAULT_DELAY_MS=600)
    try:
        with pytest.raises(QueryDeadlineExceeded):
            coord.execute(QUERIES[1])
    finally:
        _post_fault(workers[0])
        coord.close()


def test_runtime_fault_config_overlays_env(monkeypatch):
    """The /v1/fault config is an OVERLAY: posted keys win (explicit 0
    disables an env-seeded fault), absent keys fall back to the
    environment, `{}` restores env-ruled mode — never one-way."""
    from presto_tpu.server import worker as W

    ws = W.WorkerServer.__new__(W.WorkerServer)
    ws.fault_config = {}
    monkeypatch.setenv("FAULT_DELAY_MS", "500")
    assert ws._fault("FAULT_DELAY_MS") == 500  # env rules with no post
    ws.fault_config = {"FAULT_DELAY_MS": 0}  # explicit 0 disables env
    assert ws._fault("FAULT_DELAY_MS") == 0
    ws.fault_config = {"FAULT_DELAY_MS": 7}
    assert ws._fault("FAULT_DELAY_MS") == 7
    ws.fault_config = {}  # {} = back to env-ruled mode
    assert ws._fault("FAULT_DELAY_MS") == 500


def test_nondistributable_runs_locally_with_all_workers_down(single):
    """An empty ALIVE pool only fails queries that NEED workers: a bare
    scan (nothing distributable) still falls back to local execution —
    the pre-FTE contract, kept."""
    dead = ["http://127.0.0.1:1", "http://127.0.0.1:2"]
    coord = DcnRunner({"tpch": TpchConnector(SF)}, dead,
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    try:
        for _ in range(3):
            coord.heartbeat.check_once()
        q = "select r_name from region"
        got = coord.execute(q)
        assert rows_equal(got, single.execute(q).rows)
        assert coord.last_distribution == "local"
        # but a distributable aggregation with no workers fails loudly
        with pytest.raises(DcnQueryFailed, match="no ALIVE workers"):
            coord.execute("select count(*) from region")
    finally:
        coord.close()


def test_task_retry_event_dispatched(workers, single):
    """TaskRetryEvent reaches registered EventListeners on every
    re-dispatch (the events.py half of the observability contract)."""
    from presto_tpu import events as E

    seen = []

    class Listener(E.EventListener):
        def task_retried(self, event):
            seen.append(event)

    coord = DcnRunner({"tpch": TpchConnector(SF)}, workers,
                      default_catalog="tpch", page_rows=PAGE_ROWS,
                      session_props={"retry_backoff_ms": 10},
                      listeners=[Listener()])
    _post_fault(workers[0], FAULT_SUBMIT_DROP_EVERY=1)
    try:
        q = "select count(*), sum(l_quantity) from lineitem"
        got = coord.execute(q)
        assert rows_equal(got, single.execute(q).rows)
        assert seen, "no TaskRetryEvent dispatched"
        ev = seen[0]
        assert ev.from_uri == workers[0]
        assert ev.to_uri in workers
        assert ev.attempt == 1
    finally:
        _post_fault(workers[0])
        coord.close()


def test_bare_scan_query_falls_back_local(coord, single):
    # a bare scan has no useful union cut (generation is cheaper than
    # the wire) — runs locally
    q = "select r_regionkey, r_name from region order by r_regionkey"
    assert coord.execute(q) == single.execute(q).rows
    assert coord.last_distribution == "local"


def test_union_cut_multijoin_distributes(coord, single):
    """VERDICT r4 #7 done-criterion: a multi-join query with NO
    aggregation distributes across 2 workers (union cut: workers run
    the row-local join subtree over their split share, shipped as a
    serialized fragment; the coordinator unions the pages)."""
    q = ("select c_name, o_orderkey, l_quantity from customer "
         "join orders on c_custkey = o_custkey "
         "join lineitem on l_orderkey = o_orderkey "
         "where l_quantity > 45")
    want = single.execute(q).rows
    got = coord.execute(q)
    assert coord.last_distribution.startswith("union")
    assert rows_equal(got, want)


def test_union_cut_under_topn(coord, single):
    # coordinator-side TopN over the unioned worker pages
    q = ("select o_orderkey, l_extendedprice from orders "
         "join lineitem on l_orderkey = o_orderkey "
         "order by l_extendedprice desc, o_orderkey limit 7")
    want = single.execute(q).rows
    got = coord.execute(q)
    assert coord.last_distribution.startswith("union")
    assert got == want


def test_union_cut_hash_partitioned(workers, single):
    # both big sides of the join hash-co-partition (union-hash):
    # worker build state is 1/N even with no aggregation in the plan
    coord = DcnRunner({"tpch": TpchConnector(SF)}, workers,
                      default_catalog="tpch", page_rows=PAGE_ROWS,
                      partition_threshold=10_000)
    q = ("select o_orderpriority, l_shipmode from orders "
         "join lineitem on l_orderkey = o_orderkey "
         "where l_quantity > 49")
    want = single.execute(q).rows
    got = coord.execute(q)
    assert coord.last_distribution == "union-hash"
    assert rows_equal(got, want)


def test_shipped_fragment_is_executed_verbatim(workers, single):
    """Plan SHIPPING (not replay): POST a hand-edited fragment that no
    SQL replay could produce and check the worker executes exactly it."""
    import urllib.request

    from presto_tpu.dist import plan_serde, serde
    from presto_tpu.exec import plan as P
    from presto_tpu.expr import ir as E

    plan = single.plan("select o_orderkey from orders")
    # wrap the scan subtree in an extra filter the SQL never had
    scan = plan
    while not isinstance(scan, P.TableScan):
        scan = scan.children()[0]
    fragment = P.Filter(
        source=P.Project(source=scan, exprs=(
            E.input_ref(0, single.executor.output_types(scan)[0]),)),
        predicate=E.call("lt", E.input_ref(
            0, single.executor.output_types(scan)[0]),
            E.const(100, single.executor.output_types(scan)[0])),
    )
    payload = {
        "taskId": "ship-test.0",
        "fragment": plan_serde.dumps(fragment),
        "splitTable": "orders",
        "splitIndex": 0,
        "splitCount": 1,
        "session": {},
    }
    req = urllib.request.Request(
        f"{workers[0]}/v1/task", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    urllib.request.urlopen(req, timeout=30).close()
    rows = []
    token = 0
    deadline = time.time() + 120
    while time.time() < deadline:
        r = urllib.request.urlopen(
            f"{workers[0]}/v1/task/ship-test.0/results/{token}",
            timeout=30)
        if r.status == 204:
            if r.headers.get("X-Done") == "1":
                break
            continue
        body = r.read()
        token = int(r.headers["X-Next-Token"])
        rows.extend(serde.deserialize_page(body).to_pylist())
    want = [r for r in single.execute(
        "select o_orderkey from orders").rows if r[0] < 100]
    assert rows_equal(rows, want)


@pytest.mark.parametrize("q", [
    # DISTINCT masks: MarkDistinct below the cut would double-count
    # values spanning workers — must fall back local, stay correct
    "select count(distinct o_custkey) from orders",
    # outer join below the cut: null-extension is not split-safe
    "select count(*) from customer left join orders "
    "on c_custkey = o_custkey",
    # NOT IN (anti join) below the cut
    "select count(*) from customer where c_custkey not in "
    "(select o_custkey from orders)",
])
def test_unsafe_shapes_fall_back_local(coord, single, q):
    assert rows_equal(coord.execute(q), single.execute(q).rows)


def test_self_join_of_fact_table_falls_back(coord, single):
    q = ("select count(*) from orders o1, orders o2 "
         "where o1.o_orderkey = o2.o_orderkey")
    assert rows_equal(coord.execute(q), single.execute(q).rows)


def test_session_props_reach_both_halves(workers, single):
    coord = DcnRunner(
        {"tpch": TpchConnector(SF)}, workers,
        default_catalog="tpch", page_rows=PAGE_ROWS,
        session_props={"spill_threshold_bytes": 1 << 15},
    )
    q = ("select o_custkey, count(*) from orders group by o_custkey "
         "order by 2 desc, 1 limit 5")
    got = coord.execute(q)
    assert rows_equal(got, single.execute(q).rows)
    # the coordinator-side final stage honored the session (spill knob
    # reached the shared executor through apply_session)
    assert coord.runner.executor.spill_bytes == 1 << 15


def test_partitioned_join_across_workers(workers, single):
    """VERDICT r3 #5: a PARTITIONED join (both sides hash-split on the
    join key — the DCN repartition exchange) across 2 workers matches
    single-process. partition_threshold=1 forces every scanned table
    into the co-partitioned set at this tiny SF."""
    # threshold between customer (1.5k) and orders (15k) at SF0.01:
    # orders+lineitem co-partition on orderkey, customer replicates.
    # (threshold=1 would make customer "big" too — orders would then
    # need BOTH o_custkey and o_orderkey partition keys, which the
    # analyzer correctly refuses.)
    coord = DcnRunner({"tpch": TpchConnector(SF)}, workers,
                      default_catalog="tpch", page_rows=PAGE_ROWS,
                      partition_threshold=10_000)
    want = single.execute(QUERIES[3]).rows
    got = coord.execute(QUERIES[3])
    assert coord.last_distribution == "hash"
    assert rows_equal(want, got), "partitioned Q3 diverged"


def test_partitioned_join_covers_null_keys(workers, single):
    # rows with NULL partition keys land on exactly one worker; an
    # inner join drops them either way but the partial agg below the
    # cut must not double-count them
    coord = DcnRunner({"tpch": TpchConnector(SF)}, workers,
                      default_catalog="tpch", page_rows=PAGE_ROWS,
                      partition_threshold=10_000)
    q = ("select o_orderpriority, count(*), sum(l_quantity) "
         "from orders, lineitem where o_orderkey = l_orderkey "
         "group by o_orderpriority")
    want = single.execute(q).rows
    got = coord.execute(q)
    assert coord.last_distribution == "hash"
    assert rows_equal(want, got)


def test_hash_fanout_shape_analysis(single):
    from presto_tpu.server.worker import find_partial_cut, hash_fanout_plan

    plan = single.plan(QUERIES[3])
    cut = find_partial_cut(plan)
    # threshold=1: customer+orders+lineitem all "big" — orders would
    # need both o_custkey and o_orderkey, so the analyzer must refuse
    assert hash_fanout_plan(cut, single.catalogs,
                            partition_threshold=1) is None
    # realistic threshold: orders+lineitem co-partition on orderkey
    parts = hash_fanout_plan(cut, single.catalogs,
                             partition_threshold=10_000)
    assert parts == {"tpch.orders": "o_orderkey",
                     "tpch.lineitem": "l_orderkey"}
