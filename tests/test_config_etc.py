"""etc/ deployment config + hierarchical resource groups.

Reference: presto-server's etc/config.properties +
etc/catalog/*.properties (StaticCatalogStore), and resourceGroups/*
nested quotas (InternalResourceGroup).
"""

import threading
import time

import pytest

from presto_tpu.config import (
    load_catalogs,
    load_node_config,
    parse_properties,
    server_from_etc,
)
from presto_tpu.server.resource_groups import (
    QueryQueueFullError,
    ResourceGroupManager,
    ResourceGroupSpec,
)


@pytest.fixture()
def etc(tmp_path):
    (tmp_path / "catalog").mkdir()
    (tmp_path / "config.properties").write_text(
        "# node tier\n"
        "http-server.http.port=0\n"
        "default-catalog=tiny\n"
    )
    (tmp_path / "catalog" / "tiny.properties").write_text(
        "connector.name=tpch\n"
        "tpch.scale-factor=0.001\n"
    )
    (tmp_path / "catalog" / "mem.properties").write_text(
        "connector.name=memory\n"
    )
    return str(tmp_path)


def test_parse_properties(tmp_path):
    p = tmp_path / "x.properties"
    p.write_text("# c\n a = b \n\n! bang\nk=v=w\n")
    assert parse_properties(str(p)) == {"a": "b", "k": "v=w"}
    p.write_text("nokey\n")
    with pytest.raises(ValueError, match="key=value"):
        parse_properties(str(p))


def test_load_catalogs(etc):
    cats = load_catalogs(etc)
    assert sorted(cats) == ["mem", "tiny"]
    assert "lineitem" in cats["tiny"].tables()
    assert load_node_config(etc)["default-catalog"] == "tiny"


def test_load_catalogs_unknown_connector(tmp_path):
    (tmp_path / "catalog").mkdir()
    (tmp_path / "catalog" / "bad.properties").write_text(
        "connector.name=hive\n"
    )
    with pytest.raises(ValueError, match="unknown connector.name"):
        load_catalogs(str(tmp_path))


def test_server_from_etc(etc):
    srv = server_from_etc(etc)
    srv.start()
    try:
        from presto_tpu.client import StatementClient

        c = StatementClient(server=f"http://127.0.0.1:{srv.port}")
        assert c.execute(
            "select count(*) from nation"
        ).rows[0][0] == 25
    finally:
        srv.stop()


# ------------------------------------------------- hierarchical groups

def _tree():
    return ResourceGroupManager([
        ResourceGroupSpec(
            "global", hard_concurrency=2, max_queued=10,
            max_memory_bytes=1000,
            sub_groups=(
                ResourceGroupSpec("etl", user_pattern="etl_.*",
                                  hard_concurrency=1, max_queued=1),
                ResourceGroupSpec("adhoc", hard_concurrency=2,
                                  max_queued=10,
                                  max_memory_bytes=600),
            ),
        )
    ])


def test_leaf_selection_and_paths():
    m = _tree()
    s = m.select("etl_nightly")
    assert s.paths == ("global", "global.etl")
    s2 = m.select("alice")
    assert s2.paths == ("global", "global.adhoc")


def test_queue_limit_at_every_level():
    m = _tree()
    a = m.admit("etl_1")
    assert m.acquire(a)
    b = m.admit("etl_2")  # queued in global.etl (limit 1)
    with pytest.raises(QueryQueueFullError, match="global.etl"):
        m.admit("etl_3")
    m.cancel_queued(b)
    m.release(a)


def test_parent_concurrency_caps_children():
    # global allows 2; adhoc allows 2; etl allows 1 — a 3rd query
    # blocks on the PARENT even though adhoc has a free slot
    m = _tree()
    a = m.admit("alice")
    assert m.acquire(a)
    b = m.admit("etl_x")
    assert m.acquire(b)
    c = m.admit("bob")
    got = []
    t = threading.Thread(target=lambda: got.append(m.acquire(c)))
    t.start()
    time.sleep(0.15)
    assert not got, "third query must wait on the parent quota"
    m.release(a)
    t.join(timeout=2)
    assert got == [True]
    m.release(b)
    m.release(c)


def test_memory_quota_per_level():
    m = _tree()
    a = m.admit("alice")
    assert m.acquire(a)
    assert m.reserve_memory(a, 500)
    b = m.admit("bob")
    assert m.acquire(b)
    done = []
    t = threading.Thread(
        target=lambda: done.append(m.reserve_memory(b, 500))
    )
    t.start()
    time.sleep(0.15)
    assert not done, "500+500 exceeds adhoc's 600-byte quota"
    m.release_memory(a, 500)
    t.join(timeout=2)
    assert done == [True]
    m.release_memory(b, 500)
    m.release(a)
    m.release(b)


def test_snapshot_reports_tree():
    m = _tree()
    names = [s["name"] for s in m.snapshot()]
    assert names == ["global", "global.etl", "global.adhoc"]
