"""etc/ deployment config + hierarchical resource groups.

Reference: presto-server's etc/config.properties +
etc/catalog/*.properties (StaticCatalogStore), and resourceGroups/*
nested quotas (InternalResourceGroup).
"""

import threading
import time

import pytest

from presto_tpu.config import (
    ETC_SESSION_KEYS,
    _ETC_STRUCTURAL_KEYS,
    load_catalogs,
    load_node_config,
    parse_properties,
    server_from_etc,
)
from presto_tpu.server.resource_groups import (
    QueryQueueFullError,
    ResourceGroupManager,
    ResourceGroupSpec,
)


@pytest.fixture()
def etc(tmp_path):
    (tmp_path / "catalog").mkdir()
    (tmp_path / "config.properties").write_text(
        "# node tier\n"
        "http-server.http.port=0\n"
        "default-catalog=tiny\n"
    )
    (tmp_path / "catalog" / "tiny.properties").write_text(
        "connector.name=tpch\n"
        "tpch.scale-factor=0.001\n"
    )
    (tmp_path / "catalog" / "mem.properties").write_text(
        "connector.name=memory\n"
    )
    return str(tmp_path)


def test_parse_properties(tmp_path):
    p = tmp_path / "x.properties"
    p.write_text("# c\n a = b \n\n! bang\nk=v=w\n")
    assert parse_properties(str(p)) == {"a": "b", "k": "v=w"}
    p.write_text("nokey\n")
    with pytest.raises(ValueError, match="key=value"):
        parse_properties(str(p))


def test_load_catalogs(etc):
    cats = load_catalogs(etc)
    assert sorted(cats) == ["mem", "tiny"]
    assert "lineitem" in cats["tiny"].tables()
    assert load_node_config(etc)["default-catalog"] == "tiny"


def test_load_catalogs_unknown_connector(tmp_path):
    (tmp_path / "catalog").mkdir()
    (tmp_path / "catalog" / "bad.properties").write_text(
        "connector.name=hive\n"
    )
    with pytest.raises(ValueError, match="unknown connector.name"):
        load_catalogs(str(tmp_path))


def test_server_from_etc(etc):
    srv = server_from_etc(etc)
    srv.start()
    try:
        from presto_tpu.client import StatementClient

        c = StatementClient(server=f"http://127.0.0.1:{srv.port}")
        assert c.execute(
            "select count(*) from nation"
        ).rows[0][0] == 25
    finally:
        srv.stop()


# ---------------------------------------- etc-key <-> session registry
# These assertions are GENERATED from config.ETC_SESSION_KEYS (ISSUE 6
# satellite: no hand-maintained prop list to drift) — adding a session
# property without registering an etc key fails tools/lint, and a
# registered key that doesn't plumb through to a session default fails
# here.

def test_registry_covers_every_session_property():
    from presto_tpu.session import SYSTEM_SESSION_PROPERTIES

    mapped = set(ETC_SESSION_KEYS.values())
    props = set(SYSTEM_SESSION_PROPERTIES)
    assert props - mapped == set(), (
        f"session properties without an etc key: {props - mapped}")
    assert mapped - props == set(), (
        f"etc keys naming unknown session properties: {mapped - props}")
    assert _ETC_STRUCTURAL_KEYS <= set(ETC_SESSION_KEYS)


def test_every_registered_etc_key_seeds_its_session_default(tmp_path):
    """One synthesized config.properties row per NON-structural
    registry entry; the server's session must show the seeded value
    for every property (bool/int/str alike)."""
    from presto_tpu.session import SYSTEM_SESSION_PROPERTIES, Session

    def synth(prop):
        """A value distinguishable from the default, valid per type."""
        p = SYSTEM_SESSION_PROPERTIES[prop]
        if p.type is bool:
            return str(not p.default).lower()
        if p.type is int:
            return str(int(p.default) + 7)
        if p.validate is not None:  # enum-domain strings
            for cand in ("true", "false", "broadcast", "partitioned"):
                if p.validate(cand) and cand != p.default:
                    return cand
        return "/tmp/etc-seeded" if "dir" in prop or "path" in prop \
            else "etc-seeded"

    (tmp_path / "catalog").mkdir()
    (tmp_path / "catalog" / "tiny.properties").write_text(
        "connector.name=tpch\ntpch.scale-factor=0.001\n")
    lines = ["http-server.http.port=0"]
    expect = {}
    for etc_key, prop in sorted(ETC_SESSION_KEYS.items()):
        if etc_key in _ETC_STRUCTURAL_KEYS:
            # node-tier keys (incl. compile-cache.dir, whose seeding
            # would re-run process-global cache setup per query) are
            # consumed by the server constructor, not session defaults
            continue
        val = synth(prop)
        lines.append(f"{etc_key}={val}")
        expect[prop] = val
    (tmp_path / "config.properties").write_text(
        "\n".join(lines) + "\n")
    srv = server_from_etc(str(tmp_path))
    # the server seeds these into every query session that didn't set
    # them (runner_factory); each must parse under the property's type
    for prop, raw in sorted(expect.items()):
        assert srv.session_defaults.get(prop) == raw, (
            f"{prop}: etc key value {raw!r} did not reach the "
            f"server's session defaults "
            f"(got {srv.session_defaults.get(prop)!r})")
        s = Session(properties={prop: raw})
        assert s.is_set(prop)


# ------------------------------------------------- hierarchical groups

def _tree():
    return ResourceGroupManager([
        ResourceGroupSpec(
            "global", hard_concurrency=2, max_queued=10,
            max_memory_bytes=1000,
            sub_groups=(
                ResourceGroupSpec("etl", user_pattern="etl_.*",
                                  hard_concurrency=1, max_queued=1),
                ResourceGroupSpec("adhoc", hard_concurrency=2,
                                  max_queued=10,
                                  max_memory_bytes=600),
            ),
        )
    ])


def test_leaf_selection_and_paths():
    m = _tree()
    s = m.select("etl_nightly")
    assert s.paths == ("global", "global.etl")
    s2 = m.select("alice")
    assert s2.paths == ("global", "global.adhoc")


def test_queue_limit_at_every_level():
    m = _tree()
    a = m.admit("etl_1")
    assert m.acquire(a)
    b = m.admit("etl_2")  # queued in global.etl (limit 1)
    with pytest.raises(QueryQueueFullError, match="global.etl"):
        m.admit("etl_3")
    m.cancel_queued(b)
    m.release(a)


def test_parent_concurrency_caps_children():
    # global allows 2; adhoc allows 2; etl allows 1 — a 3rd query
    # blocks on the PARENT even though adhoc has a free slot
    m = _tree()
    a = m.admit("alice")
    assert m.acquire(a)
    b = m.admit("etl_x")
    assert m.acquire(b)
    c = m.admit("bob")
    got = []
    t = threading.Thread(target=lambda: got.append(m.acquire(c)))
    t.start()
    time.sleep(0.15)
    assert not got, "third query must wait on the parent quota"
    m.release(a)
    t.join(timeout=2)
    assert got == [True]
    m.release(b)
    m.release(c)


def test_memory_quota_per_level():
    m = _tree()
    a = m.admit("alice")
    assert m.acquire(a)
    assert m.reserve_memory(a, 500)
    b = m.admit("bob")
    assert m.acquire(b)
    done = []
    t = threading.Thread(
        target=lambda: done.append(m.reserve_memory(b, 500))
    )
    t.start()
    time.sleep(0.15)
    assert not done, "500+500 exceeds adhoc's 600-byte quota"
    m.release_memory(a, 500)
    t.join(timeout=2)
    assert done == [True]
    m.release_memory(b, 500)
    m.release(a)
    m.release(b)


def test_snapshot_reports_tree():
    m = _tree()
    names = [s["name"] for s in m.snapshot()]
    assert names == ["global", "global.etl", "global.adhoc"]
