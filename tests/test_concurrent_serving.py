"""ISSUE 11 satellite: the concurrent serving path as a gated
invariant — N protocol clients x the CONCURRENT QueryManager path
(memory arbiter on, per-query runners) x the process-shared result
cache x the armed lock sanitizer, raced deliberately in tier-1.

This is ROADMAP item 1(d)'s "result cache on by default for the
server" prerequisite turned into a test: before the cache can default
on, concurrent clients hammering the shared store must produce
IDENTICAL rows per statement and ZERO sanitizer violations (no
lock-order inversion, no unlocked shared-attr write anywhere in the
engine while the race runs). tools/loadbench.py --sanitize is the
same gate at benchmark scale.

ISSUE 17 extends the suite to the multi-tenant dispatch plane:
cross-query launch batching (batched vs solo vs sqlite-oracle row
parity, queries_per_launch > 1 actually recorded), fair scheduling
(a short interactive query overtakes a queue of long scans by
completion ORDER — wall-clock assertions don't survive a 2-core CI
box), and per-group HBM shares (peak_device_bytes governed under the
group's resolved budget).
"""

import threading
import time

import pytest

from presto_tpu.obs import sanitizer as SAN

CLIENTS = 8
ROUNDS = 3

# small repeated deck (dashboard shape): after each statement's first
# execution the rest should collapse onto the shared result cache —
# which is exactly the cross-thread traffic being raced
STATEMENTS = (
    "select count(*), sum(n_nationkey) from nation",
    "select r_name, count(*) from region group by r_name "
    "order by r_name",
    "select n_regionkey, count(*), max(n_name) from nation "
    "group by n_regionkey order by n_regionkey",
)


@pytest.fixture(scope="module")
def server_url():
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.server.http_server import PrestoTpuServer

    # memory arbiter on => the CONCURRENT path: every query gets its
    # own runner/executor; the result-cache store, jit cache, views,
    # and histograms are the process-shared surfaces under race
    srv = PrestoTpuServer(
        {"tpch": TpchConnector(scale=0.01)},
        port=0, memory_budget_bytes=1 << 32,
    )
    port = srv.start()
    yield f"http://127.0.0.1:{port}"
    srv.stop()


def test_concurrent_clients_cache_on_zero_sanitizer_violations(
        server_url):
    if not SAN.is_armed():
        pytest.skip("sanitizer disarmed via PRESTO_TPU_LOCK_SANITIZER")
    from presto_tpu.client import StatementClient

    SAN.reset()
    results = [[] for _ in range(CLIENTS)]
    errors = []

    def client(idx: int) -> None:
        cl = StatementClient(server_url, user=f"race{idx}",
                             catalog="tpch")
        cl.session_properties["result_cache_enabled"] = "true"
        for _ in range(ROUNDS):
            for sql in STATEMENTS:
                try:
                    res = cl.execute(sql)
                except Exception as e:  # noqa: BLE001 - the assertion
                    errors.append(repr(e))  # below reports transport
                    continue  # failures with full context
                if res.error is not None:
                    errors.append(str(res.error))
                else:
                    results[idx].append(
                        (sql, tuple(map(tuple, res.rows))))

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(CLIENTS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not any(t.is_alive() for t in threads), "client hung"
    assert not errors, errors[:5]

    # every client saw every statement every round...
    for idx in range(CLIENTS):
        assert len(results[idx]) == ROUNDS * len(STATEMENTS)
    # ...and all of them identical rows (a cache serving one client a
    # torn/stale page set would diverge here)
    by_sql = {}
    for idx in range(CLIENTS):
        for sql, rows in results[idx]:
            by_sql.setdefault(sql, set()).add(rows)
    for sql, variants in by_sql.items():
        assert len(variants) == 1, \
            f"divergent rows across clients for {sql!r}"

    # the cache actually engaged across the race (the point of the
    # exercise: hits ARE the contended path)
    from presto_tpu.cache import shared_cache_if_exists

    rc = shared_cache_if_exists()
    assert rc is not None and rc.hits > 0

    # and the armed sanitizer observed ZERO violations anywhere in
    # the engine while 8 threads raced it
    assert SAN.violation_count() == 0, SAN.report()


def _race(server_url, batching: str, rounds: int = ROUNDS):
    """Run the CLIENTS x STATEMENTS deck with the result cache OFF
    (every statement executes — replays would launch nothing and
    flatter the batching numbers) and the cross_query_batching knob
    pinned. Returns {sql: {rows-variant, ...}} across every client
    and round, plus transport errors."""
    from presto_tpu.client import StatementClient

    results = [[] for _ in range(CLIENTS)]
    errors = []

    def client(idx: int) -> None:
        cl = StatementClient(server_url, user=f"xq{idx}",
                             catalog="tpch")
        cl.session_properties["result_cache_enabled"] = "false"
        cl.session_properties["cross_query_batching"] = batching
        # a wide gather window makes 8-thread overlap near-certain on
        # a 2-core box; correctness must hold at ANY window
        cl.session_properties["cross_query_batch_wait_ms"] = "50"
        for _ in range(rounds):
            for sql in STATEMENTS:
                try:
                    res = cl.execute(sql)
                except Exception as e:  # noqa: BLE001 - reported below
                    errors.append(repr(e))
                    continue
                if res.error is not None:
                    errors.append(str(res.error))
                else:
                    results[idx].append(
                        (sql, tuple(map(tuple, res.rows))))

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(CLIENTS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not any(t.is_alive() for t in threads), "client hung"
    by_sql = {}
    for idx in range(CLIENTS):
        for sql, rows in results[idx]:
            by_sql.setdefault(sql, set()).add(rows)
    return by_sql, errors


def _scrape(server_url: str, name: str) -> int:
    import re
    import urllib.request

    with urllib.request.urlopen(server_url + "/metrics",
                                timeout=30) as r:
        text = r.read().decode()
    m = re.search(rf"^{re.escape(name)} (\d+)", text, re.M)
    return int(m.group(1)) if m else 0


def test_batched_vs_solo_row_parity_and_width(server_url):
    """ISSUE 17 acceptance: under 8 concurrent clients with the cache
    off, the batched path must return rows identical to the solo path
    AND to the sqlite oracle, while actually riding shared launches
    (queries_per_launch > 1) — and the armed sanitizer must stay
    silent through both passes."""
    if SAN.is_armed():
        SAN.reset()
    solo, errs_solo = _race(server_url, "false")
    batched, errs_b = _race(server_url, "true")
    assert not errs_solo, errs_solo[:5]
    assert not errs_b, errs_b[:5]

    # each pass internally consistent, and batched == solo per
    # statement (the in-program demux never leaks another query's
    # slot or a padded lane)
    for sql in STATEMENTS:
        assert len(solo[sql]) == 1, f"solo divergence for {sql!r}"
        assert len(batched[sql]) == 1, \
            f"batched divergence for {sql!r}"
        assert solo[sql] == batched[sql], \
            f"batched rows differ from solo for {sql!r}"

    # ...and both match the sqlite oracle over the same generated data
    from presto_tpu.connectors.tpch import TpchConnector
    from tests.oracle import load_sqlite, rows_match

    db = load_sqlite(TpchConnector(scale=0.01), ["nation", "region"])
    for sql in STATEMENTS:
        engine_rows = [tuple(r) for r in next(iter(batched[sql]))]
        oracle_rows = [tuple(r) for r in db.execute(sql).fetchall()]
        rows_match(engine_rows, oracle_rows)

    # the batched pass actually shared launches: the process-wide
    # gauge (max across completed queries) recorded a width > 1
    width = _scrape(server_url, "presto_tpu_queries_per_launch")
    assert width > 1, (
        f"queries_per_launch={width}: no launch was ever shared "
        f"across queries under an 8-client race")
    assert _scrape(
        server_url, "presto_tpu_cross_query_batches_total") > 0

    if SAN.is_armed():
        assert SAN.violation_count() == 0, SAN.report()


def test_priority_scheduling_interactive_overtakes_scans():
    """Fair scheduling (ISSUE 17), asserted by completion ORDER: with
    one global concurrency slot held by a long scan and three more
    long scans queued ahead of it, a high-priority interactive query
    must finish next (position 1), not last — FIFO would starve it
    behind every scan. Aging is the converse guarantee (the scans'
    effective priority grows while queued), so the scans must all
    still complete."""
    from presto_tpu.client import StatementClient
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.server.http_server import PrestoTpuServer
    from presto_tpu.server.resource_groups import (
        ResourceGroupManager,
        ResourceGroupSpec,
    )

    if SAN.is_armed():
        SAN.reset()
    rg = ResourceGroupManager([ResourceGroupSpec(
        "global", ".*", hard_concurrency=1, max_queued=64,
        sub_groups=(
            ResourceGroupSpec("inter", "inter.*",
                              hard_concurrency=1, max_queued=64,
                              priority=100),
            ResourceGroupSpec("batch", "batch.*",
                              hard_concurrency=1, max_queued=64),
        ))])
    srv = PrestoTpuServer(
        {"tpch": TpchConnector(scale=0.003)},
        port=0, memory_budget_bytes=1 << 32, resource_groups=rg,
    )
    srv.start()
    base = f"http://127.0.0.1:{srv.port}"
    scan_sql = ("select count(*) from lineitem l1, lineitem l2 "
                "where l1.l_orderkey = l2.l_orderkey")
    quick_sql = "select count(*) from nation"
    try:
        # prewarm both programs off the raced path (shared jit cache)
        for user, sql in (("batchwarm", scan_sql),
                          ("interwarm", quick_sql)):
            c = StatementClient(base, user=user, catalog="tpch")
            c.session_properties["result_cache_enabled"] = "false"
            r = c.execute(sql)
            assert r.error is None, r.error

        order = []
        olock = threading.Lock()
        started = threading.Event()

        def run(label: str, user: str, sql: str, delay: float):
            started.wait()
            time.sleep(delay)
            cl = StatementClient(base, user=user, catalog="tpch")
            cl.session_properties["result_cache_enabled"] = "false"
            res = cl.execute(sql)
            with olock:
                order.append((label, res.error))

        threads = [
            threading.Thread(
                target=run, args=(f"scan{i}", f"batch{i}", scan_sql,
                                  i * 0.05), daemon=True)
            for i in range(4)
        ] + [
            threading.Thread(
                target=run, args=("inter", "inter0", quick_sql, 0.6),
                daemon=True)
        ]
        for t in threads:
            t.start()
        started.set()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads), "query hung"
        labels = [lab for lab, _ in order]
        errors = [(lab, e) for lab, e in order if e is not None]
        assert not errors, errors
        assert len(labels) == 5
        pos = labels.index("inter")
        # one scan may already hold (or just have freed) the slot when
        # the interactive query arrives; everything QUEUED must yield
        assert pos <= 2, (
            f"interactive query finished at position {pos} of "
            f"{labels}: starved behind queued scans")
    finally:
        srv.stop()
    if SAN.is_armed():
        assert SAN.violation_count() == 0, SAN.report()


def test_group_memory_share_governs_peak():
    """Per-group HBM shares (ISSUE 17): a query admitted through a
    group with a tiny memory_share runs with its device budget seeded
    from exec/membudget.group_share_bytes — EXPLAIN ANALYZE's
    peak_device_bytes must come in under that resolved share (the
    governor chunks instead of colliding into the group's slice)."""
    import re

    from presto_tpu.client import StatementClient
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.exec import membudget as MB
    from presto_tpu.server.http_server import PrestoTpuServer
    from presto_tpu.server.resource_groups import (
        ResourceGroupManager,
        ResourceGroupSpec,
    )

    if SAN.is_armed():
        SAN.reset()
    share = 2.0 ** -12
    budget = MB.group_share_bytes(share)
    assert budget == 1 << 24  # the floor engaged: 16 MiB

    rg = ResourceGroupManager([ResourceGroupSpec(
        "global", ".*", hard_concurrency=4, max_queued=64,
        sub_groups=(
            ResourceGroupSpec("small", "small.*",
                              hard_concurrency=2, max_queued=64,
                              memory_share=share),
            ResourceGroupSpec("rest", ".*",
                              hard_concurrency=2, max_queued=64),
        ))])
    srv = PrestoTpuServer(
        {"tpch": TpchConnector(scale=0.01)},
        port=0, memory_budget_bytes=1 << 32, resource_groups=rg,
    )
    srv.start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        cl = StatementClient(base, user="small0", catalog="tpch")
        cl.session_properties["result_cache_enabled"] = "false"
        res = cl.execute(
            "explain analyze select l_returnflag, count(*), "
            "sum(l_extendedprice) from lineitem "
            "group by l_returnflag order by l_returnflag")
        assert res.error is None, res.error
        text = "\n".join(str(r[0]) for r in res.rows)
        m = re.search(r"peak_device_bytes=(\d+)", text)
        assert m is not None, f"no peak_device_bytes in:\n{text}"
        peak = int(m.group(1))
        assert 0 < peak <= budget, (
            f"peak_device_bytes={peak} exceeds the group's resolved "
            f"share {budget}")
    finally:
        srv.stop()
    if SAN.is_armed():
        assert SAN.violation_count() == 0, SAN.report()
