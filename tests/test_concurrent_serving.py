"""ISSUE 11 satellite: the concurrent serving path as a gated
invariant — N protocol clients x the CONCURRENT QueryManager path
(memory arbiter on, per-query runners) x the process-shared result
cache x the armed lock sanitizer, raced deliberately in tier-1.

This is ROADMAP item 1(d)'s "result cache on by default for the
server" prerequisite turned into a test: before the cache can default
on, concurrent clients hammering the shared store must produce
IDENTICAL rows per statement and ZERO sanitizer violations (no
lock-order inversion, no unlocked shared-attr write anywhere in the
engine while the race runs). tools/loadbench.py --sanitize is the
same gate at benchmark scale.
"""

import threading

import pytest

from presto_tpu.obs import sanitizer as SAN

CLIENTS = 8
ROUNDS = 3

# small repeated deck (dashboard shape): after each statement's first
# execution the rest should collapse onto the shared result cache —
# which is exactly the cross-thread traffic being raced
STATEMENTS = (
    "select count(*), sum(n_nationkey) from nation",
    "select r_name, count(*) from region group by r_name "
    "order by r_name",
    "select n_regionkey, count(*), max(n_name) from nation "
    "group by n_regionkey order by n_regionkey",
)


@pytest.fixture(scope="module")
def server_url():
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.server.http_server import PrestoTpuServer

    # memory arbiter on => the CONCURRENT path: every query gets its
    # own runner/executor; the result-cache store, jit cache, views,
    # and histograms are the process-shared surfaces under race
    srv = PrestoTpuServer(
        {"tpch": TpchConnector(scale=0.01)},
        port=0, memory_budget_bytes=1 << 32,
    )
    port = srv.start()
    yield f"http://127.0.0.1:{port}"
    srv.stop()


def test_concurrent_clients_cache_on_zero_sanitizer_violations(
        server_url):
    if not SAN.is_armed():
        pytest.skip("sanitizer disarmed via PRESTO_TPU_LOCK_SANITIZER")
    from presto_tpu.client import StatementClient

    SAN.reset()
    results = [[] for _ in range(CLIENTS)]
    errors = []

    def client(idx: int) -> None:
        cl = StatementClient(server_url, user=f"race{idx}",
                             catalog="tpch")
        cl.session_properties["result_cache_enabled"] = "true"
        for _ in range(ROUNDS):
            for sql in STATEMENTS:
                try:
                    res = cl.execute(sql)
                except Exception as e:  # noqa: BLE001 - the assertion
                    errors.append(repr(e))  # below reports transport
                    continue  # failures with full context
                if res.error is not None:
                    errors.append(str(res.error))
                else:
                    results[idx].append(
                        (sql, tuple(map(tuple, res.rows))))

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(CLIENTS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not any(t.is_alive() for t in threads), "client hung"
    assert not errors, errors[:5]

    # every client saw every statement every round...
    for idx in range(CLIENTS):
        assert len(results[idx]) == ROUNDS * len(STATEMENTS)
    # ...and all of them identical rows (a cache serving one client a
    # torn/stale page set would diverge here)
    by_sql = {}
    for idx in range(CLIENTS):
        for sql, rows in results[idx]:
            by_sql.setdefault(sql, set()).add(rows)
    for sql, variants in by_sql.items():
        assert len(variants) == 1, \
            f"divergent rows across clients for {sql!r}"

    # the cache actually engaged across the race (the point of the
    # exercise: hits ARE the contended path)
    from presto_tpu.cache import shared_cache_if_exists

    rc = shared_cache_if_exists()
    assert rc is not None and rc.hits > 0

    # and the armed sanitizer observed ZERO violations anywhere in
    # the engine while 8 threads raced it
    assert SAN.violation_count() == 0, SAN.report()
