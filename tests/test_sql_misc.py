"""SQL frontend regression tests beyond the TPC-H suite — subquery
scoping, set operations, ordinals, scalar-count decorrelation (cases found
by review: each was a silent wrong-answer before the fix)."""

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runner import LocalRunner
from presto_tpu.sql.planner import PlanningError


@pytest.fixture(scope="module")
def runner():
    return LocalRunner({"tpch": TpchConnector(0.002)}, page_rows=1 << 14)


class TestSetOps:
    def test_union_order_limit_applies_to_whole_union(self, runner):
        res = runner.execute(
            "select o_orderkey from orders union all "
            "select o_orderkey from orders order by o_orderkey limit 3"
        )
        assert len(res.rows) == 3
        # smallest key twice, then next — proves both branches contribute
        assert res.rows[0][0] == res.rows[1][0]

    def test_union_coerces_types(self, runner):
        # common type is decimal(scale 1); engine returns unscaled ints at
        # the python boundary, so 1 -> 10 and 2.5 -> 25
        res = runner.execute("select 1 as x union all select 2.5")
        vals = sorted(int(r[0]) for r in res.rows)
        assert vals == [10, 25]

    def test_union_distinct(self, runner):
        res = runner.execute("select 1 as x union select 1 union select 2")
        assert sorted(r[0] for r in res.rows) == [1, 2]


class TestSubqueryScoping:
    def test_scalar_subquery_agg_stays_inner(self, runner):
        res = runner.execute(
            "select (select max(o_orderkey) from orders) as m, o_orderkey "
            "from orders order by o_orderkey limit 5"
        )
        # outer query must NOT collapse into a global aggregation
        assert len(res.rows) == 5
        assert all(r[0] >= r[1] for r in res.rows)

    def test_correlated_count_zero_groups(self, runner):
        # customers with custkey % 3 == 0 place no orders (generator rule);
        # count(*) over an empty correlated set must be 0, not NULL
        res = runner.execute(
            "select count(*) from customer where 0 = "
            "(select count(*) from orders where o_custkey = c_custkey)"
        )
        assert res.rows[0][0] >= 100  # the one-third inactive customers

    def test_exists_over_aggregated_subquery_rejected(self, runner):
        with pytest.raises(PlanningError):
            runner.execute(
                "select count(*) from customer where exists "
                "(select count(*) from orders where o_custkey = c_custkey "
                "group by o_orderstatus having count(*) > 100)"
            )


class TestOrdinals:
    def test_order_by_ordinal(self, runner):
        res = runner.execute(
            "select o_orderkey, o_custkey from orders order by 1 limit 3"
        )
        keys = [r[0] for r in res.rows]
        assert keys == sorted(keys)

    def test_ordinal_out_of_range(self, runner):
        with pytest.raises(PlanningError):
            runner.execute("select o_orderkey from orders order by 0")
        with pytest.raises(PlanningError):
            runner.execute("select o_orderkey from orders order by 5")
        with pytest.raises(PlanningError):
            runner.execute(
                "select o_orderkey, count(*) from orders group by 3"
            )


class TestMisc:
    def test_limit_offset(self, runner):
        all_rows = runner.execute(
            "select o_orderkey from orders order by o_orderkey limit 10"
        ).rows
        page2 = runner.execute(
            "select o_orderkey from orders order by o_orderkey "
            "limit 5 offset 5"
        ).rows
        assert page2 == all_rows[5:]

    def test_distinct(self, runner):
        res = runner.execute("select distinct o_orderstatus from orders")
        assert sorted(r[0] for r in res.rows) == ["F", "O", "P"]

    def test_select_star(self, runner):
        res = runner.execute("select * from region order by r_regionkey")
        assert len(res.rows) == 5
        assert res.column_names[:2] == ["r_regionkey", "r_name"]

    def test_group_by_expression(self, runner):
        res = runner.execute(
            "select o_orderkey % 2 as parity, count(*) from orders "
            "group by o_orderkey % 2 order by parity"
        )
        assert len(res.rows) == 2
        assert sum(r[1] for r in res.rows) == 3000  # n_orders at SF0.002


class TestAdviceRound1Regressions:
    """Regressions for the round-1 advisor findings (ADVICE.md)."""

    def test_case_mixing_two_dictionary_columns(self, runner):
        # CASE selecting between two differently-coded string columns must
        # decode each branch through its own values, not one branch's dict
        res = runner.execute(
            "select c_custkey, case when c_custkey % 2 = 0 then c_mktsegment "
            "else c_name end from customer order by c_custkey limit 6"
        )
        for key, v in res.rows:
            if key % 2 == 0:
                assert v in {"AUTOMOBILE", "BUILDING", "FURNITURE",
                             "HOUSEHOLD", "MACHINERY"}, v
            else:
                assert v.startswith("Customer#"), v

    def test_case_string_literal_vs_column(self, runner):
        res = runner.execute(
            "select case when c_custkey % 2 = 0 then 'even' "
            "else c_mktsegment end from customer limit 50"
        )
        vals = {r[0] for r in res.rows}
        assert "even" in vals
        assert any(v != "even" for v in vals)

    def test_coalesce_string_literal_default(self, runner):
        res = runner.execute(
            "select coalesce(c_mktsegment, 'missing') from customer limit 5"
        )
        assert all(r[0] != "missing" for r in res.rows)

    def test_semi_join_on_transformed_dictionary(self, runner):
        # substr-produced dictionaries carry duplicate values; the join path
        # must canonicalize codes by value (advisor high #2)
        direct = runner.execute(
            "select count(*) from customer where substr(c_phone, 1, 2) = "
            "(select substr(c_phone, 1, 2) from customer where c_custkey = 1)"
        ).rows[0][0]
        via_in = runner.execute(
            "select count(*) from customer where substr(c_phone, 1, 2) in "
            "(select substr(c_phone, 1, 2) from customer where c_custkey = 1)"
        ).rows[0][0]
        assert direct == via_in and direct >= 1

    def test_power_negative_base_fractional_exponent_nan(self, runner):
        import math
        res = runner.execute("select power(-8.0, 0.5), power(-8.0, 2.0), "
                             "power(-2.0, 3.0)")
        assert math.isnan(res.rows[0][0])
        assert res.rows[0][1] == 64.0
        assert res.rows[0][2] == -8.0

    def test_uncorrelated_subquery_error_not_misrouted(self, runner):
        # a typo'd column inside an uncorrelated scalar subquery must raise
        # "column not found", not a decorrelator shape error
        with pytest.raises(PlanningError, match="column not found"):
            runner.execute(
                "select count(*) from customer where c_custkey = "
                "(select max(no_such_col) from orders)"
            )


def test_memory_budget_enforced(runner):
    from presto_tpu.exec.executor import MemoryBudgetExceeded

    runner.execute("set session query_max_memory_bytes = 1024")
    try:
        import pytest

        with pytest.raises(MemoryBudgetExceeded):
            runner.execute("select count(*) from lineitem")
        r = runner.execute("set session query_max_memory_bytes = 0")
        assert runner.execute(
            "select count(*) from region"
        ).rows == [(5,)]
    finally:
        runner.execute("set session query_max_memory_bytes = 0")


class TestVarianceFamily:
    """stddev/variance aggregates (reference: operator/aggregation/
    VarianceAggregation — Welford state; ours is moment sums, see
    exec/agg_states.py)."""

    def test_grouped_vs_numpy(self, runner):
        import collections

        import numpy as np

        rows = runner.execute(
            "select l_returnflag, l_quantity, l_extendedprice "
            "from lineitem"
        ).rows
        by = collections.defaultdict(list)
        for f, q, e in rows:
            by[f].append((q / 100.0, e / 100.0))
        got = runner.execute(
            "select l_returnflag, stddev(l_quantity), "
            "var_samp(l_quantity), stddev_pop(l_extendedprice), "
            "var_pop(l_extendedprice), variance(l_orderkey) "
            "from lineitem group by l_returnflag"
        ).rows
        assert len(got) == 3
        for f, sd, vs, sp, vp, vk in got:
            a = np.array(by[f])
            np.testing.assert_allclose(sd, np.std(a[:, 0], ddof=1),
                                       rtol=1e-9)
            np.testing.assert_allclose(vs, np.var(a[:, 0], ddof=1),
                                       rtol=1e-9)
            np.testing.assert_allclose(sp, np.std(a[:, 1], ddof=0),
                                       rtol=1e-9)
            np.testing.assert_allclose(vp, np.var(a[:, 1], ddof=0),
                                       rtol=1e-9)

    def test_global_and_edge_counts(self, runner):
        # global (ungrouped) path + n<2 null semantics
        r = runner.execute(
            "select stddev(l_quantity), var_pop(l_quantity) "
            "from lineitem where l_orderkey < 0"
        ).rows
        assert r[0][0] is None and r[0][1] is None
        one = runner.execute(
            "select var_samp(x), var_pop(x), stddev_pop(x) from "
            "(select 5 as x) t"
        ).rows[0]
        assert one[0] is None and one[1] == 0.0 and one[2] == 0.0


class TestDistinctAggregates:
    """MarkDistinct-backed DISTINCT aggregates (reference:
    MarkDistinctOperator + AggregationNode mask symbols): mixed
    DISTINCT/plain and multiple distinct argument columns."""

    def test_multiple_distinct_columns(self, runner):
        # regression: this returned (25, 25) when the dedup ran over the
        # combined (a, b) space instead of per-argument marks
        got = runner.execute(
            "select count(distinct n_regionkey), count(distinct n_name) "
            "from nation"
        ).rows
        assert got == [(5, 25)]

    def test_mixed_distinct_and_plain(self, runner):
        got = runner.execute(
            "select count(distinct o_custkey), count(*), "
            "sum(o_totalprice) from orders"
        ).rows[0]
        plain = runner.execute(
            "select count(*), sum(o_totalprice) from orders"
        ).rows[0]
        dcust = runner.execute(
            "select count(*) from "
            "(select distinct o_custkey from orders) t"
        ).rows[0]
        assert got == (dcust[0], plain[0], plain[1])

    def test_grouped_mixed_vs_manual(self, runner):
        got = runner.execute(
            "select l_returnflag, count(distinct l_suppkey), "
            "count(distinct l_partkey), sum(l_quantity) "
            "from lineitem group by l_returnflag order by 1"
        ).rows
        for flag, dsupp, dpart, qty in got:
            m = runner.execute(
                f"select count(distinct l_suppkey) from lineitem "
                f"where l_returnflag = '{flag}'"
            ).rows[0][0]
            m2 = runner.execute(
                f"select count(distinct l_partkey) from lineitem "
                f"where l_returnflag = '{flag}'"
            ).rows[0][0]
            m3 = runner.execute(
                f"select sum(l_quantity) from lineitem "
                f"where l_returnflag = '{flag}'"
            ).rows[0][0]
            assert (dsupp, dpart, qty) == (m, m2, m3)

    def test_sum_distinct(self, runner):
        got = runner.execute(
            "select sum(distinct n_regionkey), count(*) from nation"
        ).rows
        assert got == [(0 + 1 + 2 + 3 + 4, 25)]


class TestUsingJoins:
    """JOIN ... USING (reference: StatementAnalyzer's USING scope
    rules): one unqualified copy of each using column, coalesced for
    FULL joins, then the remaining columns of both sides."""

    def test_inner_using_matches_on(self, runner):
        a = runner.execute(
            "select k, count(*), sum(l_extendedprice) from "
            "(select o_orderkey k, o_totalprice from orders) "
            "join (select l_orderkey k, l_extendedprice from lineitem) "
            "using (k) group by k order by k limit 5"
        ).rows
        b = runner.execute(
            "select a.k, count(*), sum(l_extendedprice) from "
            "(select o_orderkey k, o_totalprice from orders) a "
            "join (select l_orderkey k, l_extendedprice from lineitem) b "
            "on a.k = b.k group by a.k order by a.k limit 5"
        ).rows
        assert a == b and len(a) == 5

    def test_using_output_shape(self, runner):
        res = runner.execute(
            "select * from (select n_nationkey k, n_name from nation) "
            "join (select r_regionkey k, r_name from region) using (k) "
            "order by k limit 2"
        )
        # one k column, then n_name, then r_name
        assert res.column_names == ["k", "n_name", "r_name"]
        assert res.rows[0][0] == 0

    def test_left_and_full_using_coalesce(self, runner):
        left = runner.execute(
            "select k, r_name from "
            "(select n_nationkey k, n_name from nation) "
            "left join (select r_regionkey k, r_name from region) "
            "using (k) order by k"
        ).rows
        assert len(left) == 25
        # keys 0..4 match regions; 5..24 null-extended
        assert left[0][1] is not None and left[10][1] is None
        full = runner.execute(
            "select k from "
            "(select r_regionkey k from region) "
            "full join (select n_nationkey k from nation where "
            "n_nationkey >= 3) using (k) order by k"
        ).rows
        # coalesced key: 0..2 from left only, 3,4 both, 5..24 right only
        assert [r[0] for r in full] == list(range(25))

    def test_using_missing_column_errors(self, runner):
        with pytest.raises(PlanningError):
            runner.execute(
                "select * from nation join region using (nope)"
            )
