"""ISSUE 19: fleet-wide result reuse — the persistence + subsumption
halves of the tentpole (the DCN probe half lives in
tests/test_fleet_cache.py).

Covers the acceptance contracts:
  - warm-start pin: cacheable deck -> process "restart" (shared store
    torn down, fresh LocalRunner) -> rerun completes with
    cache_warm_loads >= 1, result_cache_hits >= 1 and
    program_launches == 0 on the hit path; rows identical to the cold
    run AND to the sqlite oracle;
  - DML between runs forces a miss with fresh rows (warm-loaded entry
    invalidated by the write like any live entry);
  - out-of-band snapshot bump + restart: warm load PROVES the token
    moved, drops the entry loudly (cache_manifest_drops), recomputes;
  - manifest corruption trio: truncated manifest / missing entry file
    / serde-fingerprint mismatch each load ZERO entries, count drops,
    and never crash or serve stale rows;
  - stream watermarks (ISSUE 14) survive the persist round trip;
  - overlapping-predicate subsumption: a cached WHERE d < 10 fragment
    answers WHERE d < 5 via residual re-filter (cache_subsumed_hits,
    oracle-identical rows); non-contained predicates miss.
"""

import collections
import os

import pytest

from presto_tpu.cache import ResultCache, shared_cache_if_exists
from presto_tpu.cache import store as cache_store
from presto_tpu.cache.persist import (ManifestStore, manifest_files,
                                      read_manifest_doc,
                                      rewrite_manifest_doc)
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runner import LocalRunner

SF = 0.01
PAGE_ROWS = 1 << 13

AGG_Q = ("select l_returnflag, l_linestatus, count(*) c, "
         "sum(l_quantity) q from lineitem "
         "group by l_returnflag, l_linestatus "
         "order by l_returnflag, l_linestatus")


def _rows_equal(a, b):
    return collections.Counter(map(repr, a)) == collections.Counter(
        map(repr, b))


@pytest.fixture(autouse=True)
def _clean_shared_cache():
    """Persistence tests simulate process restarts by tearing the
    process-shared store down; leave no store (and no persister bound
    to a deleted tmp dir) behind for other tests."""
    rc = shared_cache_if_exists()
    if rc is not None:
        rc.configure(persist_dir="")
        rc.clear()
    cache_store._shared = None
    yield
    rc = shared_cache_if_exists()
    if rc is not None:
        rc.configure(persist_dir="")
        rc.clear()
    cache_store._shared = None


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(SF)


def _persist_runner(conn, persist_dir, **extra):
    r = LocalRunner({"tpch": conn}, page_rows=PAGE_ROWS)
    r.session.set("result_cache_enabled", True)
    r.session.set("result_cache_persist_dir", str(persist_dir))
    for k, v in extra.items():
        r.session.set(k, v)
    return r


def _restart():
    """Simulate process death: the shared store (and its in-memory
    entries) vanish; the manifest + payload files on disk survive."""
    cache_store._shared = None


# ----------------------------------------------------- warm-start pin
def test_warm_start_pin(tmp_path, conn):
    """THE restart acceptance contract, plus oracle parity on the
    warm-served rows."""
    d = tmp_path / "rc"
    r1 = _persist_runner(conn, d)
    cold = r1.execute(AGG_Q).rows
    assert r1.executor.result_cache_misses >= 1
    assert manifest_files(str(d)), "a manifest generation must exist"

    _restart()
    r2 = _persist_runner(conn, d)
    warm = r2.execute(AGG_Q).rows
    ex = r2.executor
    assert ex.cache_warm_loads >= 1, "manifest entries must re-admit"
    assert ex.result_cache_hits >= 1
    assert ex.program_launches == 0, (
        "a warm-start hit must not launch fused-scan programs")
    assert warm == cold

    from tests.oracle import load_sqlite

    db = load_sqlite(conn, ["lineitem"])
    want = db.execute(
        "select l_returnflag, l_linestatus, count(*), "
        "sum(l_quantity) from lineitem "
        "group by l_returnflag, l_linestatus "
        "order by l_returnflag, l_linestatus").fetchall()
    assert [tuple(x) for x in want] == [tuple(x) for x in warm]


def test_warm_load_runs_once_per_persister(tmp_path, conn):
    r1 = _persist_runner(conn, tmp_path / "rc")
    r1.execute(AGG_Q)
    _restart()
    r2 = _persist_runner(conn, tmp_path / "rc")
    r2.execute(AGG_Q)
    loads0 = r2.executor.cache_warm_loads
    r2.execute(AGG_Q)  # same persister: no second load pass
    assert r2.executor.cache_warm_loads == loads0
    rc = shared_cache_if_exists()
    assert rc.warm_loads == loads0


# -------------------------------------------------- DML interactions
def test_dml_between_runs_forces_miss(tmp_path):
    mem = MemoryConnector()
    r1 = LocalRunner({"mem": mem}, default_catalog="mem")
    r1.session.set("result_cache_enabled", True)
    r1.session.set("result_cache_persist_dir", str(tmp_path / "rc"))
    r1.execute("create table t as select 1 x, 10 y")
    q = "select count(*) c, sum(y) s from t"
    assert r1.execute(q).rows == [(1, 10)]

    _restart()
    r2 = LocalRunner({"mem": mem}, default_catalog="mem")
    r2.session.set("result_cache_enabled", True)
    r2.session.set("result_cache_persist_dir", str(tmp_path / "rc"))
    # the INSERT's apply_session warm-loads the persisted entry, then
    # the write invalidates it — exactly a live entry's lifecycle
    r2.execute("insert into t select 2, 20")
    hits0 = r2.executor.result_cache_hits
    assert r2.execute(q).rows == [(2, 30)], "fresh rows, never stale"
    assert r2.executor.result_cache_hits == hits0


def test_out_of_band_snapshot_bump_drops_on_warm_load(tmp_path):
    """The snapshot token moved while no cache-enabled session was
    watching (no invalidation hook ran): warm load must PROVE the
    mismatch against the live connector and drop loudly."""
    mem = MemoryConnector()
    r1 = LocalRunner({"mem": mem}, default_catalog="mem")
    r1.session.set("result_cache_enabled", True)
    r1.session.set("result_cache_persist_dir", str(tmp_path / "rc"))
    r1.execute("create table t as select 1 x, 10 y")
    q = "select count(*) c, sum(y) s from t"
    assert r1.execute(q).rows == [(1, 10)]

    _restart()
    # cache-blind writer (result cache off): snapshot bumps, manifest
    # does not hear about it
    blind = LocalRunner({"mem": mem}, default_catalog="mem")
    blind.execute("insert into t select 2, 20")

    r2 = LocalRunner({"mem": mem}, default_catalog="mem")
    r2.session.set("result_cache_enabled", True)
    r2.session.set("result_cache_persist_dir", str(tmp_path / "rc"))
    assert r2.execute(q).rows == [(2, 30)]
    assert r2.executor.cache_manifest_drops >= 1
    assert r2.executor.result_cache_hits == 0


# ---------------------------------------------- manifest corruption
def _seed_persisted(tmp_path, conn):
    d = tmp_path / "rc"
    r = _persist_runner(conn, d)
    cold = r.execute(AGG_Q).rows
    assert manifest_files(str(d)), "a manifest generation must exist"
    _restart()
    return d, cold


def test_truncated_manifest_loads_zero_loudly(tmp_path, conn):
    """A crash mid-append leaves a torn trailing record: the loader
    keeps the parsed prefix and drops the tail loudly. Truncating
    inside the FIRST record line means zero entries survive."""
    d, cold = _seed_persisted(tmp_path, conn)
    _, path = manifest_files(str(d))[0]
    blob = open(path, "rb").read()
    header_len = blob.index(b"\n") + 1
    with open(path, "wb") as f:
        f.write(blob[:header_len + 10])
    r = _persist_runner(conn, d)
    rows = r.execute(AGG_Q).rows
    assert rows == cold                      # recomputed, not crashed
    assert r.executor.cache_warm_loads == 0
    assert r.executor.cache_manifest_drops >= 1


def test_missing_entry_file_drops_that_entry(tmp_path, conn):
    d, cold = _seed_persisted(tmp_path, conn)
    doc = read_manifest_doc(str(d))
    assert doc["entries"], "seed must have persisted entries"
    for meta in doc["entries"].values():
        os.unlink(d / meta["file"])
    r = _persist_runner(conn, d)
    rows = r.execute(AGG_Q).rows
    assert rows == cold
    assert r.executor.cache_warm_loads == 0
    assert r.executor.cache_manifest_drops >= len(doc["entries"])
    # the dead rows were pruned, then the recompute re-published its
    # fragment: every manifest row's payload file exists again
    doc2 = read_manifest_doc(str(d))
    for meta in doc2["entries"].values():
        assert os.path.exists(d / meta["file"])


def test_serde_fingerprint_mismatch_drops_all(tmp_path, conn):
    d, cold = _seed_persisted(tmp_path, conn)
    doc = read_manifest_doc(str(d))
    n = len(doc["entries"])
    assert n >= 1
    doc["serde"] = "XXX0"
    rewrite_manifest_doc(str(d), doc)
    r = _persist_runner(conn, d)
    rows = r.execute(AGG_Q).rows
    assert rows == cold
    assert r.executor.cache_warm_loads == 0
    assert r.executor.cache_manifest_drops >= n


def test_manifest_version_skew_drops_loudly(tmp_path, conn):
    d, cold = _seed_persisted(tmp_path, conn)
    doc = read_manifest_doc(str(d))
    doc["version"] = 99
    rewrite_manifest_doc(str(d), doc)
    r = _persist_runner(conn, d)
    assert r.execute(AGG_Q).rows == cold
    assert r.executor.cache_warm_loads == 0
    assert r.executor.cache_manifest_drops >= 1


# ------------------------------- generation manifest (ISSUE 20 sat 1)
def test_manifest_publish_appends_single_generation(tmp_path):
    """Below the compaction threshold every publish is an O(1) append
    to ONE generation file — no whole-manifest rewrite."""
    d = str(tmp_path / "m")
    st = ManifestStore(d, compact_threshold=1000)
    for i in range(20):
        st.publish(f"k{i}", {"v": i})
    files = manifest_files(d)
    assert len(files) == 1
    assert files[0][0] == 0
    doc = read_manifest_doc(d)
    assert len(doc["entries"]) == 20
    # removals are records too (tombstones), not rewrites
    st.remove(["k0", "k1"])
    assert len(manifest_files(d)) == 1
    st2 = ManifestStore(d, compact_threshold=1000)
    snap = st2.entries_snapshot()
    assert len(snap) == 18 and "k0" not in snap


def test_manifest_compacts_past_threshold(tmp_path):
    """Past the record threshold the store rolls the live map into the
    next generation and unlinks the old files (size governance)."""
    d = str(tmp_path / "m")
    st = ManifestStore(d, compact_threshold=8)
    for i in range(30):
        st.publish(f"k{i % 5}", {"v": i})     # churny upserts
    files = manifest_files(d)
    assert len(files) == 1, "old generations must be unlinked"
    assert files[0][0] >= 1, "compaction must advance the generation"
    doc = read_manifest_doc(d)
    assert len(doc["entries"]) == 5
    st2 = ManifestStore(d, compact_threshold=8)
    assert st2.entries_snapshot() == st.entries_snapshot()
    assert st2.broken_count == 0


def test_partial_compaction_falls_back_a_generation(tmp_path):
    """A compaction that died after creating a garbage newest file:
    the loader drops it loudly and recovers the previous generation
    intact."""
    d = str(tmp_path / "m")
    st = ManifestStore(d, compact_threshold=1000)
    for i in range(4):
        st.publish(f"k{i}", {"v": i})
    gen, _ = manifest_files(d)[0]
    bad = os.path.join(d, f"manifest.g{gen + 1:06d}.jsonl")
    with open(bad, "wb") as f:
        f.write(b"\x00garbage{{{not json\n")
    st2 = ManifestStore(d, compact_threshold=1000)
    assert len(st2.entries_snapshot()) == 4
    assert st2.broken_count >= 1
    assert any("garbage" in r or "g%06d" % (gen + 1) in r
               for r in st2.broken_reasons)
    # the fresh store keeps publishing without tripping over the corpse
    st2.publish("k9", {"v": 9})
    st3 = ManifestStore(d, compact_threshold=1000)
    assert "k9" in st3.entries_snapshot()


def test_manifest_concurrent_publishers(tmp_path):
    """Racing publishers (the concurrent-serving shape) all land: the
    drain loop serializes file appends while the map stays coherent —
    graded under the tier-1 lock sanitizer."""
    import threading

    d = str(tmp_path / "m")
    st = ManifestStore(d, compact_threshold=64)
    def worker(tid):
        for i in range(40):
            st.publish(f"t{tid}.k{i}", {"v": i})
    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(st.entries_snapshot()) == 240
    st2 = ManifestStore(d, compact_threshold=64)
    assert len(st2.entries_snapshot()) == 240
    assert st2.broken_count == 0


# ------------------------------------------------ watermark roundtrip
def test_stream_watermark_survives_roundtrip(tmp_path, conn):
    """ISSUE 14 watermarks ride the manifest: a pinned-prefix entry
    re-admits with its append-log offset intact."""
    from presto_tpu.cache.rules import snapshot_of

    d = str(tmp_path / "rc")
    rc1 = ResultCache()
    rc1.configure(persist_dir=d)
    r = LocalRunner({"tpch": conn}, page_rows=PAGE_ROWS)
    plan = r.plan("select l_returnflag from lineitem "
                  "where l_quantity < 1")
    pages = [pg for pg in r.executor.pages(plan)]
    snap = (("tpch", "lineitem",
             snapshot_of(conn, "lineitem")),)
    rc1.put_pages("frag:wmtest:k1.p1", [p for p in pages],
                  frozenset({("tpch", "lineitem")}), watermark=4096,
                  snap=snap)
    assert rc1.entry_count == 1

    rc2 = ResultCache()
    rc2.configure(persist_dir=d)
    loaded, drops = rc2.warm_load({"tpch": conn})
    assert (loaded, drops) == (1, 0)
    with rc2._lock:
        e = rc2._entries["frag:wmtest:k1.p1"]
        assert e.watermark == 4096
        assert e.snap == snap


# --------------------------------------------------- subsumption pin
NARROW_Q = ("select l_orderkey, l_quantity from lineitem "
            "where l_quantity < 5 order by l_orderkey, l_quantity")
WIDE_Q = ("select l_orderkey, l_quantity from lineitem "
          "where l_quantity < 10 order by l_orderkey, l_quantity")
DISJOINT_Q = ("select l_orderkey, l_quantity from lineitem "
              "where l_quantity < 20 order by l_orderkey, "
              "l_quantity")


@pytest.fixture()
def sub_runner(conn):
    r = LocalRunner({"tpch": conn}, page_rows=PAGE_ROWS)
    r.session.set("result_cache_enabled", True)
    r.session.set("result_cache_subsumption", True)
    return r


def test_subsumption_pin(sub_runner, conn):
    """THE subsumption acceptance contract: WHERE d < 10 cached, then
    WHERE d < 5 serves from it via residual re-filter — >=1
    cache_subsumed_hits, rows identical to the sqlite oracle."""
    r = sub_runner
    wide = r.execute(WIDE_Q).rows
    assert r.executor.cache_subsumed_hits == 0
    narrow = r.execute(NARROW_Q).rows
    ex = r.executor
    assert ex.cache_subsumed_hits >= 1
    assert ex.result_cache_hits >= 1

    from tests.oracle import load_sqlite

    db = load_sqlite(conn, ["lineitem"])
    # l_quantity is decimal(12,2): unscaled ints on the oracle side
    want = db.execute(
        "select l_orderkey, l_quantity from lineitem "
        "where l_quantity < 500 "
        "order by l_orderkey, l_quantity").fetchall()
    assert [tuple(x) for x in want] == [tuple(x) for x in narrow]
    want_wide = db.execute(
        "select l_orderkey, l_quantity from lineitem "
        "where l_quantity < 1000 "
        "order by l_orderkey, l_quantity").fetchall()
    assert [tuple(x) for x in want_wide] == [tuple(x) for x in wide]


def test_subsumption_noncontained_misses(sub_runner):
    """d < 20 is NOT contained in the cached d < 10 — it must compute
    (no subsumed hit, correct rows)."""
    r = sub_runner
    r.execute(WIDE_Q)
    sub0 = r.executor.cache_subsumed_hits
    got = r.execute(DISJOINT_Q).rows
    assert r.executor.cache_subsumed_hits == sub0
    fresh = LocalRunner({"tpch": r.catalogs["tpch"]},
                        page_rows=PAGE_ROWS)
    assert _rows_equal(got, fresh.execute(DISJOINT_Q).rows)


def test_subsumed_result_republishes_exact_key(sub_runner):
    """The narrow answer is published under its exact key: a repeat
    of the narrow query is an ordinary exact hit, not a second
    subsumption replay."""
    r = sub_runner
    r.execute(WIDE_Q)
    r.execute(NARROW_Q)
    sub0 = r.executor.cache_subsumed_hits
    hits0 = r.executor.result_cache_hits
    rows = r.execute(NARROW_Q).rows
    assert r.executor.cache_subsumed_hits == sub0
    assert r.executor.result_cache_hits > hits0
    assert rows == r.execute(NARROW_Q).rows


# ------------------------------------------ cache-aware admission
def test_estimate_memory_discounts_cached_fragments(conn):
    """ISSUE 19 admission satellite: the membudget arbiter sizes a
    query by estimate_memory — a plan whose fragments are RESIDENT in
    the cache replays host pages and must not reserve join-build/sort
    HBM. Advisory: clearing the cache restores the full estimate."""
    r = LocalRunner({"tpch": conn}, page_rows=PAGE_ROWS)
    r.session.set("result_cache_enabled", True)
    q = ("select * from orders join lineitem "
         "on o_orderkey = l_orderkey order by o_totalprice")
    cold = r.estimate_memory(q)
    r.execute(q)
    warm = r.estimate_memory(q)
    assert warm < cold, (cold, warm)
    shared_cache_if_exists().clear()
    assert r.estimate_memory(q) == cold


def test_subsumption_off_by_default(conn):
    r = LocalRunner({"tpch": conn}, page_rows=PAGE_ROWS)
    r.session.set("result_cache_enabled", True)
    r.execute(WIDE_Q)
    r.execute(NARROW_Q)
    assert r.executor.cache_subsumed_hits == 0
