"""Build-free generated joins (Connector.key_inverse + gen_at).

Reference: presto-main operator/{HashBuilderOperator,LookupJoinOperator}
— for deterministic generator tables the TPU engine collapses both into
pure per-element compute: probe keys invert to build-table row indices
in closed form and the carried build columns are GENERATED at those
indices (exec/executor._generated_join_page). These tests pin the
semantics against (a) the materialized-build paths via the
generated_join_enabled session property and (b) the sqlite oracle.
"""

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runner import LocalRunner
from tests.oracle import load_sqlite


@pytest.fixture(scope="module")
def rig():
    conn = TpchConnector(scale=0.01)
    runner = LocalRunner({"tpch": conn})
    db = load_sqlite(conn, ["lineitem", "orders", "customer", "nation",
                            "supplier", "region"])
    return runner, db


def _run(runner, sql, generated=True):
    runner.session.set("generated_join_enabled", generated)
    try:
        res = runner.execute(sql)
        return sorted(res.rows), runner.executor.generated_joins_used
    finally:
        runner.session.unset("generated_join_enabled")


def test_inner_fk_join_matches_materialized_and_oracle(rig):
    runner, db = rig
    sql = (
        "select o_orderdate, count(*), sum(l_extendedprice) "
        "from lineitem join orders on l_orderkey = o_orderkey "
        "where o_orderdate < date '1995-03-15' "
        "group by o_orderdate order by 1 limit 50"
    )
    got, used = _run(runner, sql, generated=True)
    assert used > 0, "generated join did not engage"
    base, used0 = _run(runner, sql, generated=False)
    assert got == base
    # oracle cross-check on the aggregate row counts (full value-level
    # TPC-H parity lives in test_sql_tpch, which runs both join modes'
    # shared operator stack)
    want = db.execute(
        "select count(distinct o_orderdate) "
        "from lineitem join orders on l_orderkey = o_orderkey "
        "where o_orderdate < 9204"
    ).fetchone()[0]
    assert len(got) == min(want, 50)


def test_left_join_unmatched_probe_rows_null_build_side(rig):
    runner, _ = rig
    # +1 lands on a hole of the sparse orderkey pattern for 7 of every
    # 8 keys, so most probe rows are unmatched
    sql = (
        "select count(*), count(o_orderkey) from ("
        "  select l_orderkey + 1 as k from lineitem"
        ") left join orders on k = o_orderkey"
    )
    got, used = _run(runner, sql, generated=True)
    assert used > 0
    base, _ = _run(runner, sql, generated=False)
    assert got == base
    total, matched = got[0]
    assert total > matched  # unmatched probe rows kept, build side null


def test_null_probe_keys_never_match(rig):
    runner, _ = rig
    sql = (
        "select count(*), count(o_orderkey) from ("
        "  select case when l_linenumber = 1 then null "
        "         else l_orderkey end as k from lineitem"
        ") left join orders on k = o_orderkey"
    )
    got, used = _run(runner, sql, generated=True)
    base, _ = _run(runner, sql, generated=False)
    assert got == base


def test_multi_key_join_extra_equality(rig):
    runner, db = rig
    # two-key join against nation: n_nationkey inverts; the second key
    # pair (c_nationkey = s_nationkey via the shared nation row) checks
    # the non-pivot equality path
    sql = (
        "select n_name, count(*) from supplier, customer, nation "
        "where s_nationkey = n_nationkey and c_nationkey = n_nationkey "
        "group by n_name order by 2 desc, 1 limit 5"
    )
    got, used = _run(runner, sql, generated=True)
    assert used > 0
    base, _ = _run(runner, sql, generated=False)
    assert got == base


def test_build_side_filter_replayed(rig):
    runner, _ = rig
    sql = (
        "select count(*) from lineitem join orders "
        "on l_orderkey = o_orderkey where o_orderdate >= date '1997-01-01'"
    )
    got, used = _run(runner, sql, generated=True)
    assert used > 0
    base, _ = _run(runner, sql, generated=False)
    assert got == base


def test_disabled_falls_back_to_materialized(rig):
    runner, _ = rig
    sql = (
        "select count(*) from lineitem join orders "
        "on l_orderkey = o_orderkey"
    )
    runner.session.set("generated_join_enabled", False)
    try:
        before = runner.executor.generated_joins_used
        runner.execute(sql)
        assert runner.executor.generated_joins_used == before
    finally:
        runner.session.unset("generated_join_enabled")
