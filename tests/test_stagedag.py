"""ISSUE 7: the general fragment-DAG scheduler with spooled exchanges.

Covers the whole subsystem ring by ring:
  - fragment_dag cuts arbitrary plans into verified stage DAGs
    (structure of a 3-stage TPC-H-Q13-shaped plan the legacy cuts
    cannot distribute; refusal of bare scans and DAG-unsafe shapes;
    string-key repartition degradation);
  - the spool fetch/ack data plane (partitioned PageStore-backed
    buffers, token-dedupe, partition release);
  - end-to-end parity across 2 workers through dist/scheduler.py,
    including forced-DAG mode over repartitioned joins;
  - straggler speculation dedupe and mid-query worker re-admission;
  - payload/DAG static checks (exec/plan_check.py);
  - (slow) a real-subprocess mid-query kill of a NON-LEAF stage
    recovering via spooled replay — the acceptance gate.
"""

import collections
import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.dist.dcn import DcnRunner
from presto_tpu.dist.fragmenter import fragment_dag, stage_key
from presto_tpu.exec import plan_check as PC
from presto_tpu.runner import LocalRunner
from presto_tpu.server.worker import WorkerServer
from tests.tpch_queries import QUERIES

SF = 0.01
PAGE_ROWS = 1 << 13

# 3-stage shape the OLD fragmenter could NOT distribute: a left join
# feeding a hash aggregation feeding a join feeding another
# aggregation (the TPC-H Q13 family). find_partial_cut lands on the
# OUTER agg whose subtree is not row-local, and the union cut dies on
# the left join — legacy distribution falls back to a single process.
DAG_QUERY = (
    "select n_name, count(*), sum(top.c_count) from nation join ("
    "  select c_nationkey nk, c_custkey ck, count(o_orderkey) c_count"
    "  from customer left join orders on c_custkey = o_custkey"
    "  group by c_nationkey, c_custkey) top on n_nationkey = top.nk "
    "group by n_name order by n_name"
)


@pytest.fixture(scope="module")
def single():
    return LocalRunner({"tpch": TpchConnector(SF)}, page_rows=PAGE_ROWS)


@pytest.fixture(scope="module")
def workers():
    w1 = WorkerServer({"tpch": TpchConnector(SF)}, node_id="w1",
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    w2 = WorkerServer({"tpch": TpchConnector(SF)}, node_id="w2",
                      default_catalog="tpch", page_rows=PAGE_ROWS)
    uris = [f"http://127.0.0.1:{w1.start()}",
            f"http://127.0.0.1:{w2.start()}"]
    yield uris
    w1.stop()
    w2.stop()


def rows_equal(a, b):
    return collections.Counter(map(repr, a)) == collections.Counter(
        map(repr, b)
    )


def _post_fault(uri, **cfg):
    req = urllib.request.Request(
        f"{uri}/v1/fault", data=json.dumps(cfg).encode(),
        headers={"Content-Type": "application/json"})
    urllib.request.urlopen(req, timeout=5).close()


def _make_coord(workers, **props):
    defaults = {"retry_backoff_ms": 20}
    defaults.update(props)
    return DcnRunner({"tpch": TpchConnector(SF)}, workers,
                     default_catalog="tpch", page_rows=PAGE_ROWS,
                     session_props=defaults)


# ------------------------------------------------------ fragmentation
def test_fragment_dag_three_stage_shape(single):
    plan = single.plan(DAG_QUERY)
    dag = fragment_dag(single.executor, plan, single.catalogs,
                       gather_capacity=64)
    assert dag is not None
    assert len(dag.fragments) >= 3
    kinds = [f.output_kind for f in dag.fragments]
    # the inner group-by (int keys, capacity forced past the gather
    # cap) repartitions; the build sides broadcast; the final edge to
    # the coordinator gathers
    assert "repartition" in kinds
    assert "broadcast" in kinds
    assert "gather" in kinds
    repart = [f for f in dag.fragments
              if f.output_kind == "repartition"]
    assert all(f.output_keys for f in repart)
    # non-leaf fragments exist (inputs from upstream stages) — the
    # shapes whose loss PR-5 could not recover
    assert any(f.inputs for f in dag.fragments)
    # leaf fragments carry a deterministic split table
    leaves = [f for f in dag.fragments if not f.inputs]
    assert all(f.split_table for f in leaves if f.sharded)
    # the whole DAG passes the static verifier (RemoteSource types vs
    # origin-fragment output across every exchange hop)
    PC.verify_dag(single.executor, dag)
    # ... and every fragment root ships through plan serde verbatim
    from presto_tpu.dist import plan_serde

    for f in dag.fragments:
        assert plan_serde.dumps(plan_serde.loads(
            plan_serde.dumps(f.root))) == plan_serde.dumps(f.root)


def test_fragment_dag_refuses_bare_scan(single):
    plan = single.plan("select r_name from region")
    assert fragment_dag(single.executor, plan,
                        single.catalogs) is None


def test_fragment_dag_refuses_sharded_unique_id(single):
    from presto_tpu.exec import plan as P
    from presto_tpu.expr import ir as E

    scan = single.plan("select o_orderkey from orders")
    while not isinstance(scan, P.TableScan):
        scan = scan.children()[0]
    t = single.executor.output_types(scan)[0]
    plan = P.Output(
        source=P.UniqueId(source=P.Filter(
            source=scan,
            predicate=E.call("lt", E.input_ref(0, t),
                             E.const(100, t)))),
        names=("k", "uid"))
    # per-task unique-id counters would collide across tasks
    assert fragment_dag(single.executor, plan,
                        single.catalogs) is None


def test_string_repartition_degrades_to_gather(single):
    # group keys are dictionary-coded strings: codes are producer-
    # local, so the exchange must degrade to a gather instead of
    # hash-repartitioning on codes
    q = ("select o_orderpriority, l_shipmode, count(*) "
         "from orders join lineitem on o_orderkey = l_orderkey "
         "group by o_orderpriority, l_shipmode")
    plan = single.plan(q)
    dag = fragment_dag(single.executor, plan, single.catalogs,
                       gather_capacity=1)
    assert dag is not None
    for f in dag.fragments:
        assert f.output_kind != "repartition", (
            f"stage {f.fid} repartitions on string keys")


# ------------------------------------------------- spool fetch / ack
def test_spool_fetch_and_ack_endpoints(single, workers):
    """The spooled-exchange data plane directly: a task with
    outputPartitions=2 hash-partitions its pages into PageStore-backed
    buffers; partitions fetch token-indexed and disjoint, re-fetch is
    byte-identical (dedupe), and ack releases the partition."""
    from presto_tpu.dist import plan_serde, serde
    from presto_tpu.exec import plan as P

    plan = single.plan("select o_orderkey from orders")
    scan = plan
    while not isinstance(scan, P.TableScan):
        scan = scan.children()[0]
    payload = {
        "taskId": "spool-test.0",
        "fragment": plan_serde.dumps(scan),
        "splitTable": "orders",
        "splitIndex": 0,
        "splitCount": 1,
        "outputPartitions": 2,
        "outputKeys": [0],
        "session": {},
    }
    req = urllib.request.Request(
        f"{workers[0]}/v1/task", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    urllib.request.urlopen(req, timeout=30).close()

    def fetch_part(part):
        rows, blobs, token = [], [], 0
        deadline = time.time() + 120
        while time.time() < deadline:
            r = urllib.request.urlopen(
                f"{workers[0]}/v1/task/spool-test.0/results/{token}"
                f"?part={part}", timeout=30)
            if r.status == 204:
                if r.headers.get("X-Done") == "1":
                    return rows, blobs
                continue
            body = r.read()
            token = int(r.headers["X-Next-Token"])
            blobs.append(body)
            rows.extend(serde.deserialize_page(body).to_pylist())
        raise AssertionError("spool fetch timed out")

    rows0, blobs0 = fetch_part(0)
    rows1, _ = fetch_part(1)
    want = single.execute("select o_orderkey from orders").rows
    # disjoint union across partitions = the full result
    assert rows_equal(rows0 + rows1, want)
    keys0 = {r[0] for r in rows0}
    keys1 = {r[0] for r in rows1}
    assert not (keys0 & keys1)
    assert rows0 and rows1  # both partitions non-trivial
    # token re-fetch is byte-identical (at-least-once + dedupe)
    r = urllib.request.urlopen(
        f"{workers[0]}/v1/task/spool-test.0/results/0?part=0",
        timeout=30)
    assert r.read() == blobs0[0]
    # ack releases partition 0; further fetch answers 410 GONE
    req = urllib.request.Request(
        f"{workers[0]}/v1/task/spool-test.0/spool/0", method="DELETE")
    urllib.request.urlopen(req, timeout=5).close()
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            f"{workers[0]}/v1/task/spool-test.0/results/0?part=0",
            timeout=5)
    assert ei.value.code == 410
    # partition 1 is untouched by partition 0's ack
    rows1b, _ = fetch_part(1)
    assert rows_equal(rows1b, rows1)
    req = urllib.request.Request(
        f"{workers[0]}/v1/task/spool-test.0", method="DELETE")
    urllib.request.urlopen(req, timeout=5).close()


# --------------------------------------------------------- end to end
def test_dag_distributes_shape_legacy_could_not(single, workers):
    """The acceptance shape: legacy cuts fall back LOCAL on the
    3-stage plan; the stage scheduler runs it across 2 workers with
    identical rows and spooled exchanges."""
    legacy = _make_coord(workers, stage_scheduler="false")
    dag_coord = _make_coord(workers, agg_gather_capacity=64)
    try:
        want = single.execute(DAG_QUERY).rows
        got_legacy = legacy.execute(DAG_QUERY)
        assert legacy.last_distribution == "local"
        assert rows_equal(got_legacy, want)

        ex = dag_coord.runner.executor
        stages0 = ex.stages_scheduled
        got = dag_coord.execute(DAG_QUERY)
        assert dag_coord.last_distribution == "stage-dag"
        assert rows_equal(got, want), "stage-DAG rows diverged"
        sched = dag_coord.last_scheduler
        assert ex.stages_scheduled - stages0 >= 3
        assert ex.spooled_exchange_pages > 0
        # both workers actually ran tasks
        used = {t.placement.uri for ts in sched.tasks.values()
                for t in ts}
        assert used == set(workers)
        # the new counters ride the registry into every surface
        from presto_tpu.exec.counters import QUERY_COUNTERS, snapshot

        snap = snapshot(ex)
        for name in ("stages_scheduled", "spooled_exchange_pages",
                     "nonleaf_replays", "speculative_tasks_won",
                     "speculative_tasks_lost"):
            assert name in QUERY_COUNTERS and name in snap
    finally:
        legacy.close()
        dag_coord.close()


def test_dag_forced_mode_partitioned_join_parity(single, workers):
    """stage_scheduler=true forces DAG-first even for legacy-capable
    shapes; join_distribution_type=partitioned exercises the
    hash-repartition spool partitions on both join sides."""
    coord = _make_coord(workers, stage_scheduler="true",
                        join_distribution_type="partitioned")
    try:
        want = single.execute(QUERIES[3]).rows
        got = coord.execute(QUERIES[3])
        assert coord.last_distribution == "stage-dag"
        assert rows_equal(got, want)
        # a repartition edge was actually scheduled
        dag = coord.last_scheduler.dag
        assert any(f.output_kind == "repartition"
                   for f in dag.fragments)
    finally:
        coord.close()


def test_dag_auto_falls_back_local_with_dead_pool(single):
    """Auto mode preserves the pre-DAG contract: a DAG-distributable
    query with NO alive workers still runs locally instead of failing
    (forced mode and legacy-distributable shapes keep failing loudly,
    as before)."""
    dead = ["http://127.0.0.1:1", "http://127.0.0.1:2"]
    coord = DcnRunner({"tpch": TpchConnector(SF)}, dead,
                      default_catalog="tpch", page_rows=PAGE_ROWS,
                      session_props={"agg_gather_capacity": 64})
    try:
        for _ in range(3):  # fail_after=3 consecutive misses
            coord.heartbeat.check_once()
        got = coord.execute(DAG_QUERY)
        assert coord.last_distribution == "local"
        assert rows_equal(got, single.execute(DAG_QUERY).rows)
    finally:
        coord.close()


def test_dag_auto_keeps_legacy_shapes_on_legacy_path(single, workers):
    coord = _make_coord(workers)
    try:
        q = ("select l_returnflag, count(*), sum(l_quantity) "
             "from lineitem group by l_returnflag")
        got = coord.execute(q)
        assert coord.last_distribution in ("hash", "roundrobin")
        assert rows_equal(got, single.execute(q).rows)
    finally:
        coord.close()


# ------------------------------------------------ scheduler policies
def test_speculation_dedupe(single, workers):
    """A deterministic straggler (FAULT_TASK_EXEC_DELAY_MS) is raced
    by a re-dispatched copy on the other worker; the copy wins, the
    loser is cancelled, and rows stay exactly-once."""
    coord = _make_coord(workers, stage_scheduler="true",
                        speculation_enabled=True,
                        agg_gather_capacity=64)
    _post_fault(workers[1], FAULT_TASK_EXEC_DELAY_MS=15000)
    try:
        ex = coord.runner.executor
        won0 = ex.speculative_tasks_won
        want = single.execute(DAG_QUERY).rows
        t0 = time.monotonic()
        got = coord.execute(DAG_QUERY)
        wall = time.monotonic() - t0
        assert rows_equal(got, want), "speculation duplicated rows"
        assert ex.speculative_tasks_won > won0
        # the race genuinely beat the 15s straggler sleep per stage
        assert wall < 60
    finally:
        _post_fault(workers[1])
        coord.close()


def test_midquery_worker_readmission(single, workers):
    """An excluded worker whose heartbeat recovers rejoins the pool at
    the NEXT STAGE of the same query (before ISSUE 7, _excluded nodes
    only rejoined between queries)."""
    coord = _make_coord(workers, stage_scheduler="true",
                        agg_gather_capacity=64)
    excluded_at = {}

    def hook(fid):
        if not excluded_at:
            # simulate a mid-query exclusion of a HEALTHY worker
            # after the first stage completes
            coord._excluded.add(workers[1])
            excluded_at["fid"] = fid

    coord._stage_hook = hook
    try:
        want = single.execute(DAG_QUERY).rows
        got = coord.execute(DAG_QUERY)
        assert rows_equal(got, want)
        pools = coord.last_scheduler.stage_pools
        assert len(pools) >= 3
        # the stage right after the exclusion re-probed the live
        # worker and re-admitted it mid-query
        assert workers[1] in pools[-1]
        assert workers[1] not in coord._excluded
    finally:
        coord._stage_hook = None
        coord.close()


# ------------------------------------------------------ static checks
def test_check_task_payload_sources():
    base = {
        "taskId": "q.f1.t0", "splitIndex": 0, "splitCount": 2,
        "fragment": "{}", "outputPartitions": 1,
        "sources": {"stage0": {
            "partition": 0,
            "tasks": [{"uri": "http://h:1", "taskId": "q.f0.t0"}],
        }},
    }
    PC.check_task_payload(base)  # non-leaf payload: sources suffice
    bad = dict(base, sources={"stage0": {"partition": 0, "tasks": []}})
    with pytest.raises(PC.PlanCheckError, match="producer placements"):
        PC.check_task_payload(bad)
    bad = dict(base, sources={"stage0": {
        "partition": -1,
        "tasks": [{"uri": "http://h:1", "taskId": "t"}]}})
    with pytest.raises(PC.PlanCheckError, match="negative spool"):
        PC.check_task_payload(bad)
    bad = dict(base, outputPartitions=4)
    with pytest.raises(PC.PlanCheckError, match="outputKeys"):
        PC.check_task_payload(bad)
    bad = {k: v for k, v in base.items() if k != "sources"}
    with pytest.raises(PC.PlanCheckError, match="splitTable"):
        PC.check_task_payload(bad)


def test_verify_dag_catches_bad_repartition_keys(single):
    import dataclasses

    plan = single.plan(DAG_QUERY)
    dag = fragment_dag(single.executor, plan, single.catalogs,
                       gather_capacity=64)
    PC.verify_dag(single.executor, dag)  # clean as fragmented
    idx = next(i for i, f in enumerate(dag.fragments)
               if f.output_kind == "repartition")
    dag.fragments[idx] = dataclasses.replace(
        dag.fragments[idx], output_keys=(99,))
    with pytest.raises(PC.PlanCheckError, match="out of range"):
        PC.verify_dag(single.executor, dag)


def test_verify_dag_catches_unknown_edge(single):
    plan = single.plan(DAG_QUERY)
    dag = fragment_dag(single.executor, plan, single.catalogs,
                       gather_capacity=64)
    dag.fragments.pop(0)  # stage0 vanishes; its consumers still
    with pytest.raises(PC.PlanCheckError,
                       match="names no fragment"):
        PC.verify_dag(single.executor, dag)


def test_clip_for_shipping_bounds_payloads(single):
    """Shipped fragment blobs keep only the origin chains type
    resolution needs: a final agg's partial origin survives, other
    RemoteSource origins drop — payloads stay linear in plan size."""
    from presto_tpu.dist import plan_serde
    from presto_tpu.dist.fragmenter import clip_for_shipping
    from presto_tpu.exec import plan as P

    plan = single.plan(DAG_QUERY)
    dag = fragment_dag(single.executor, plan, single.catalogs,
                       gather_capacity=64)
    ex = single.executor
    for f in dag.fragments:
        clipped = clip_for_shipping(f.root)
        # type resolution still works on the clipped tree (the worker
        # runs plan_check + output_types on exactly this)
        assert [t.display() for t in ex.output_types(clipped)] == \
            [t.display() for t in ex.output_types(f.root)]
        assert len(plan_serde.dumps(clipped)) <= \
            len(plan_serde.dumps(f.root))

        def walk(n, under_final_source=False):
            if isinstance(n, P.RemoteSource):
                if not under_final_source:
                    assert n.origin is None, \
                        "non-type-recovery origin survived clipping"
                return
            if isinstance(n, P.Aggregation) and n.step == "final":
                walk(n.source, under_final_source=True)
                return
            for c in n.children():
                walk(c)

        walk(clipped)


def test_stage_key_is_canonical():
    assert stage_key(3) == "stage3"  # stable across queries: jit-key
    # material derived from RemoteSource.key must not vary per query


def test_coordinator_serves_worker_task_plane(single):
    """PrestoTpuServer(worker_tasks=True) is a full DCN peer: the
    coordinator HTTP server serves the /v1/task control plane and the
    spool fetch data plane through the shared route functions — a
    coordinator+worker single-process deployment."""
    from presto_tpu.dist import plan_serde, serde
    from presto_tpu.exec import plan as P
    from presto_tpu.server.http_server import PrestoTpuServer

    srv = PrestoTpuServer({"tpch": TpchConnector(SF)}, port=0,
                          default_catalog="tpch",
                          page_rows=PAGE_ROWS, worker_tasks=True)
    srv.start()
    uri = f"http://127.0.0.1:{srv.port}"
    try:
        plan = single.plan("select n_nationkey from nation")
        scan = plan
        while not isinstance(scan, P.TableScan):
            scan = scan.children()[0]
        payload = {
            "taskId": "coord-task.0",
            "fragment": plan_serde.dumps(scan),
            "splitTable": "nation", "splitIndex": 0, "splitCount": 1,
            "outputPartitions": 1, "session": {},
        }
        req = urllib.request.Request(
            f"{uri}/v1/task", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=30).close()
        rows, token = [], 0
        deadline = time.time() + 120
        while time.time() < deadline:
            r = urllib.request.urlopen(
                f"{uri}/v1/task/coord-task.0/results/{token}?part=0",
                timeout=30)
            if r.status == 204:
                if r.headers.get("X-Done") == "1":
                    break
                continue
            token = int(r.headers["X-Next-Token"])
            rows.extend(serde.deserialize_page(r.read()).to_pylist())
        want = single.execute("select n_nationkey from nation").rows
        assert rows_equal(rows, want)
        # ... while the statement surface still answers on the same port
        with urllib.request.urlopen(f"{uri}/v1/info", timeout=5) as r:
            assert json.loads(r.read())["coordinator"] is True
    finally:
        srv.stop()


# ------------------------------------------------- the acceptance gate
def _boot_subprocess_worker(extra_env=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    for k in ("FAULT_DELAY_MS", "FAULT_DROP_EVERY",
              "FAULT_KILL_AFTER_FETCHES", "FAULT_SUBMIT_DROP_EVERY",
              "FAULT_TASK_EXEC_DELAY_MS"):
        env.pop(k, None)
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "presto_tpu.server.worker",
         "--port", "0", "--suite", "tpch", "--scale", str(SF),
         "--page-rows", str(PAGE_ROWS)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=env, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
        text=True,
    )
    info = json.loads(proc.stdout.readline())
    return proc, f"http://127.0.0.1:{info['port']}"


@pytest.mark.slow
def test_nonleaf_kill_recovers_via_spool_replay(single):
    """ISSUE 7 acceptance: a worker hard-killed MID-QUERY while the
    DAG's non-leaf stages run (it hosts spools AND a non-leaf task) is
    recovered by spooled replay — the query completes with
    single-process-identical rows and nonleaf_replays >= 1 reaches
    EXPLAIN ANALYZE through the counter registry."""
    p1, u1 = _boot_subprocess_worker()
    p2, u2 = _boot_subprocess_worker(
        {"FAULT_KILL_AFTER_FETCHES": "2"})
    coord = None
    try:
        coord = DcnRunner(
            {"tpch": TpchConnector(SF)}, [u1, u2],
            default_catalog="tpch", page_rows=PAGE_ROWS,
            fetch_retries=2,
            session_props={"agg_gather_capacity": 64,
                           "retry_backoff_ms": 20})
        want = single.execute(DAG_QUERY).rows
        got = coord.execute(DAG_QUERY)
        assert coord.last_distribution == "stage-dag"
        assert rows_equal(got, want), \
            "DAG with a mid-query non-leaf kill diverged"
        ex = coord.runner.executor
        assert ex.nonleaf_replays >= 1, \
            "recovery did not replay a non-leaf task from spools"
        assert ex.workers_excluded >= 1
        p2.wait(timeout=10)
        assert p2.poll() is not None  # the kill was real
        from presto_tpu.exec.counters import snapshot

        assert snapshot(ex)["nonleaf_replays"] >= 1
    finally:
        if coord is not None:
            coord.close()
        for p in (p1, p2):
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
