"""Views (CREATE/DROP VIEW, analysis-time expansion) and prepared
statements (PREPARE/EXECUTE/DEALLOCATE with ? parameters).

Reference: sql/tree/{CreateView,Prepare,Execute,Deallocate,Parameter} +
StatementAnalyzer view expansion; Presto stores a view as SQL text and
re-analyzes it per query, so views always reflect current base data.
"""

import pytest

from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.runner import LocalRunner


@pytest.fixture()
def runner():
    return LocalRunner(
        {"tpch": TpchConnector(0.01), "memory": MemoryConnector()},
        page_rows=1 << 13,
    )


def test_create_query_drop_view(runner):
    runner.execute(
        "create view big_nations as "
        "select n_regionkey, count(*) cnt from nation group by 1"
    )
    got = runner.execute(
        "select * from big_nations order by n_regionkey"
    ).rows
    want = runner.execute(
        "select n_regionkey, count(*) from nation group by 1 order by 1"
    ).rows
    assert got == want
    # views compose with joins and further aggregation
    j = runner.execute(
        "select r_name, cnt from region, big_nations "
        "where r_regionkey = n_regionkey order by 1"
    ).rows
    assert len(j) == 5
    runner.execute("drop view big_nations")
    with pytest.raises(Exception):
        runner.execute("select * from big_nations")


def test_view_reflects_current_data(runner):
    runner.execute(
        "create table memory.t as select 1 as x union all select 2"
    )
    runner.execute("create view memory.v as select sum(x) s from memory.t")
    assert runner.execute("select s from memory.v").rows[0][0] == 3
    runner.execute("insert into memory.t select 10")
    # re-expanded at analysis: sees the inserted row (reference
    # semantics: views are SQL text, not materialized)
    assert runner.execute("select s from memory.v").rows[0][0] == 13


def test_view_replace_duplicate_and_cycle(runner):
    runner.execute("create view v1 as select 1 as x")
    with pytest.raises(Exception):
        runner.execute("create view v1 as select 2 as x")
    runner.execute("create or replace view v1 as select 2 as x")
    assert runner.execute("select x from v1").rows == [(2,)]
    # invalid definitions are rejected at creation (analyzer-style)
    with pytest.raises(Exception):
        runner.execute("create view bad as select no_such_col from nation")


def test_prepare_execute_deallocate(runner):
    runner.execute(
        "prepare q1 from select count(*), sum(o_totalprice) from orders "
        "where o_custkey < ? and o_orderpriority = ?"
    )
    got = runner.execute(
        "execute q1 using 500, '1-URGENT'"
    ).rows
    want = runner.execute(
        "select count(*), sum(o_totalprice) from orders "
        "where o_custkey < 500 and o_orderpriority = '1-URGENT'"
    ).rows
    assert got == want
    # rebind with different values, same compiled shapes
    got2 = runner.execute("execute q1 using 100, '5-LOW'").rows
    want2 = runner.execute(
        "select count(*), sum(o_totalprice) from orders "
        "where o_custkey < 100 and o_orderpriority = '5-LOW'"
    ).rows
    assert got2 == want2
    runner.execute("deallocate prepare q1")
    with pytest.raises(Exception):
        runner.execute("execute q1 using 1, 'x'")


def test_views_persist_on_concurrent_server():
    # the arbiter path builds a fresh runner per query — view and
    # prepared-statement registries must be server-wide, like the
    # reference's connector-metadata views and session preparation
    from presto_tpu.client import StatementClient
    from presto_tpu.server.http_server import PrestoTpuServer

    srv = PrestoTpuServer(
        {"tpch": TpchConnector(0.01)}, port=0, page_rows=1 << 13,
        memory_budget_bytes=1 << 32,
    )
    srv.start()
    try:
        c = StatementClient(server=f"http://127.0.0.1:{srv.port}")
        c.execute("create view sv as select count(*) c from nation")
        assert int(c.execute("select c from sv").rows[0][0]) == 25
        c.execute("prepare sp from select ? * 2")
        assert int(c.execute("execute sp using 21").rows[0][0]) == 42
    finally:
        srv.stop()


def test_execute_missing_or_unbound(runner):
    with pytest.raises(Exception):
        runner.execute("execute nope using 1")
    runner.execute("prepare p2 from select ? + 1")
    with pytest.raises(Exception):
        runner.execute("execute p2")  # parameter not bound
    assert runner.execute("execute p2 using 41").rows == [(42,)]


def test_prepared_statements_scoped_per_user(runner):
    # ADVICE r3: one user must not see / EXECUTE / DEALLOCATE another
    # user's prepared statements (reference scopes them per session)
    runner.session.user = "alice"
    runner.execute("prepare mine from select 1")
    runner.session.user = "bob"
    with pytest.raises(Exception):
        runner.execute("execute mine")
    with pytest.raises(Exception):
        runner.execute("deallocate prepare mine")
    runner.execute("prepare mine from select 2")  # no name collision
    assert runner.execute("execute mine").rows == [(2,)]
    runner.session.user = "alice"
    assert runner.execute("execute mine").rows == [(1,)]


def test_prepare_validates_statement(runner):
    with pytest.raises(Exception):
        runner.execute("prepare bad from select from from")


def test_prepared_dml_parameters(runner):
    """? parameters substitute into DELETE/UPDATE raw-SQL slices
    positionally (assignments left-to-right, then WHERE); '?' inside a
    string literal is data (reference: sql/tree/Parameter binding over
    Delete/Update)."""
    runner.execute(
        "create table memory.pt as select 1 a, 'x' b "
        "union all select 2, 'y' union all select 3, 'z'"
    )
    runner.execute("prepare pd from delete from memory.pt where a = ?")
    runner.execute("execute pd using 2")
    assert runner.execute(
        "select a, b from memory.pt order by 1"
    ).rows == [(1, "x"), (3, "z")]
    runner.execute(
        "prepare pu from update memory.pt set b = ? where a = ?"
    )
    runner.execute("execute pu using 'it''s', 3")
    assert runner.execute(
        "select a, b from memory.pt order by 1"
    ).rows == [(1, "x"), (3, "it's")]
    # arity mismatch is a clear error
    with pytest.raises(Exception):
        runner.execute("execute pd using 1, 2")
    # '?' inside a string literal is NOT a parameter
    runner.execute(
        "prepare pq from delete from memory.pt where b = '?'"
    )
    runner.execute("execute pq")
    assert len(runner.execute("select a from memory.pt").rows) == 2


def test_projected_string_constants_decode(runner):
    """A projected string constant (and casts of it) is first-class:
    it decodes as its value, not its dictionary code."""
    assert runner.execute("select 'x'").rows == [("x",)]
    assert runner.execute(
        "select 'x' union all select 'y'"
    ).rows in ([("x",), ("y",)], [("y",), ("x",)])
    assert runner.execute(
        "select cast('q' as varchar)"
    ).rows == [("q",)]
    runner.execute("create table memory.sc as select 1 a, 'w' b")
    assert runner.execute(
        "select b from memory.sc"
    ).rows == [("w",)]
