"""ISSUE 6: the static-analysis layer — plan verifier + engine lint.

Reference: presto-main's PlanSanityChecker tests (every validation
pass has a seeded-broken-plan test proving it rejects) and the
build-time config/doc validations. Three groups:

  1. the repo itself is lint-clean (the rules run in tier-1, so a PR
     that un-documents a session property or adds an unsurfaced
     counter fails here, not in review);
  2. rule sensitivity: each lint rule catches a seeded violation in a
     synthetic file (a rule that cannot fail is not a check);
  3. the plan-verifier mutation suite: deliberately broken plans —
     schema-mismatched edges, off-ladder capacities, over-fault-line
     buffers, non-canonical jit keys, missing split-determinism
     fields, mismatched exchange partitioning — each rejected with a
     POINTED, actionable message.

The lint group needs no JAX; plan checks use tiny CPU plans.
"""

import dataclasses
import textwrap

import pytest

from presto_tpu import types as T
from presto_tpu.exec import plan as P
from presto_tpu.exec import plan_check as PC
from presto_tpu.exec import shapes as SH
from presto_tpu.expr import ir as E

# --------------------------------------------------------------- lint


def test_repo_is_lint_clean():
    """THE gate: zero findings across every rule on the repo itself.
    A finding here is a real plumbing gap — fix the engine (or, for a
    legitimately-broad except, annotate WHY), don't relax the rule."""
    from tools.lint import run_lint

    findings = run_lint()
    assert not findings, "\n".join(str(f) for f in findings)


def _tmp_py(tmp_path, body: str) -> str:
    p = tmp_path / "seeded.py"
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_excepts_rule_catches_bare_and_broad(tmp_path):
    from tools.lint import check_excepts

    path = _tmp_py(tmp_path, """
        def f():
            try:
                pass
            except:
                pass
            try:
                pass
            except Exception:
                pass
            try:
                pass
            except Exception:  # noqa: BLE001 - explained, allowed
                pass
            try:
                pass
            except Exception as e:
                raise RuntimeError("x") from e
    """)
    found = check_excepts([path])
    msgs = [f.message for f in found]
    assert len(found) == 2, msgs
    assert any("bare" in m for m in msgs)
    assert any("broad" in m for m in msgs)


def test_locks_rule_catches_undeclared_and_unlocked(tmp_path):
    from tools.lint import check_locks

    path = _tmp_py(tmp_path, """
        import threading

        class Undeclared:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
            def bump(self):
                with self._lock:
                    self.n += 1

        class Racy:
            _shared_attrs = ("n",)
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
            def bump(self):
                self.n += 1  # write OUTSIDE the lock
    """)
    found = check_locks(paths=[path])
    msgs = [f.message for f in found]
    assert any("declares no `_shared_attrs`" in m for m in msgs), msgs
    assert any("OUTSIDE" in m for m in msgs), msgs


def test_purity_rule_catches_impure_keys_and_traced_code(tmp_path):
    from tools.lint import check_purity

    path = _tmp_py(tmp_path, """
        import time
        import jax

        class X:
            def _jit(self, key, fn):
                return fn
            def bad_key(self, node, fn):
                return self._jit(("agg", id(node)), fn)
            def bad_traced(self):
                def kern(x):
                    return x * time.time()
                return jax.jit(kern)
    """)
    found = check_purity(paths=[path])
    msgs = [f.message for f in found]
    assert any("id()" in m and "key" in m for m in msgs), msgs
    assert any("time.time" in m and "traced" in m for m in msgs), msgs


def test_purity_rule_covers_direct_cache_stores(tmp_path):
    """The dist executor's `self._jit_cache[key] = jax.jit(body)`
    pattern: the key variable resolves in the ENCLOSING function (an
    unrelated `key = id(...)` in another method must not bleed in),
    and shard_map bodies count as traced entry points."""
    from tools.lint import check_purity

    path = _tmp_py(tmp_path, """
        import time
        import jax

        class X:
            def impure_store(self, node):
                key = ("d_repart", id(node))
                self._jit_cache[key] = jax.jit(lambda x: x)
            def unrelated_memo(self, node):
                key = id(node)          # NOT a jit cache — no finding
                self._memo[key] = node
            def traced_shard_body(self):
                def body(x):
                    return x + time.time()
                self._jit_cache["k"] = jax.jit(
                    jax.shard_map(body, mesh=None))
    """)
    found = check_purity(paths=[path])
    msgs = [f.message for f in found]
    assert any("id()" in m and "key" in m for m in msgs), msgs
    assert any("time.time" in m and "'body'" in m for m in msgs), msgs
    assert len([m for m in msgs if "id()" in m]) == 1, msgs


def test_counters_registry_matches_executor():
    """Every registry counter exists on a bare Executor (the snapshot
    never fabricates attributes) and is an int."""
    from presto_tpu.exec import counters as CTRS
    from presto_tpu.exec.executor import Executor

    ex = Executor({})
    for name in CTRS.QUERY_COUNTERS:
        assert isinstance(getattr(ex, name), int), name
    snap = CTRS.snapshot(ex)
    assert set(snap) == set(CTRS.QUERY_COUNTERS)


# ------------------------------------------- counter surfacing contract


@pytest.fixture(scope="module")
def tiny_runner():
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.runner import LocalRunner

    r = LocalRunner({"tpch": TpchConnector(scale=0.001)},
                    default_catalog="tpch")
    r.apply_session()
    return r


def test_every_registry_counter_reaches_explain_analyze(tiny_runner):
    from presto_tpu.exec import counters as CTRS
    from presto_tpu.runner import explain_text

    plan = tiny_runner.plan(
        "select count(*), sum(n_nationkey) from nation")
    _n, _r, stats = tiny_runner.executor.execute_with_stats(plan)
    ctr = stats["counters"]
    missing = set(CTRS.QUERY_COUNTERS) - set(ctr)
    assert not missing, f"counters dict missing {missing}"
    for name in CTRS.COMPUTED_COUNTERS:
        assert name in ctr, f"computed entry {name} missing"
    text = explain_text(plan, stats=stats)
    # the EXPLAIN ANALYZE text renders the whole dict — spot-check the
    # counters the pre-registry wiring dropped (ISSUE 6 satellite)
    for name in ("split_batch_fallbacks", "release_skips",
                 "spill_partitions_used", "gathers_deferred"):
        assert name in text, f"{name} not rendered in EXPLAIN ANALYZE"


def test_every_registry_counter_reaches_metrics_surfaces(tiny_runner):
    """/metrics exposition and the system.metrics table render the
    full registry (the wiring iterates QUERY_COUNTERS — this pins the
    contract so a revert to hand-listing fails)."""
    from presto_tpu.exec import counters as CTRS
    from presto_tpu.server.http_server import QueryManager

    mgr = QueryManager(lambda s: tiny_runner)
    text = mgr.metrics_text(1.0, executor=tiny_runner.executor)
    for name, (kind, _h) in CTRS.QUERY_COUNTERS.items():
        suffix = "_total" if kind == "counter" else ""
        assert f"presto_tpu_{name}{suffix} " in text, name
    rows = dict(
        (name, val) for name, val in
        [("device_memory_budget_bytes", 0)] +
        list(CTRS.snapshot(tiny_runner.executor).items())
    )
    assert set(CTRS.QUERY_COUNTERS) <= set(rows)
    # analyze_rung prints every key of the stats counters dict
    # (sorted(ctr) in tools/analyze_rung.py), so the EXPLAIN ANALYZE
    # contract above IS the analyze_rung contract.


# --------------------------------------------------- plan_check wiring


def test_plan_check_auto_on_under_pytest(tiny_runner):
    ex = tiny_runner.executor
    assert ex.plan_check == "auto"
    assert ex._plan_check_on()  # PYTEST_CURRENT_TEST is set
    ex.plan_check = "false"
    try:
        assert not ex._plan_check_on()
    finally:
        ex.plan_check = "auto"


def test_plan_check_session_prop_plumbs(tiny_runner):
    tiny_runner.session.set("plan_check", "false")
    try:
        tiny_runner.apply_session()
        assert tiny_runner.executor.plan_check == "false"
    finally:
        tiny_runner.session.unset("plan_check")
        tiny_runner.apply_session()


def test_execute_rejects_broken_plan_before_compile(tiny_runner):
    """The wiring, end to end: a broken plan handed to execute() fails
    with PlanCheckError (pre-compile), not a downstream shape error."""
    scan = P.TableScan("tpch", "nation", ("n_nationkey", "n_name"))
    bad = P.Output(
        source=P.Filter(source=scan,
                        predicate=E.input_ref(9, T.BOOLEAN)),
        names=("a", "b"),
    )
    with pytest.raises(PC.PlanCheckError, match="channel #9"):
        tiny_runner.executor.execute(bad)


# ------------------------------------------------------ mutation suite
# Each seeded-broken plan must be rejected with a message pointing at
# the exact invariant — these are the drifts VERDICT round 5 lost
# correctness gates to.

_VALUES2 = P.Values(types=(T.BIGINT, T.DOUBLE), rows=((1, 2.0),))


def _verify(ex, plan, **kw):
    with pytest.raises(PC.PlanCheckError) as ei:
        PC.verify(ex, plan, **kw)
    return ei.value


def test_mutation_schema_mismatched_edge(tiny_runner):
    plan = P.Filter(source=_VALUES2,
                    predicate=E.input_ref(5, T.BOOLEAN))
    err = _verify(tiny_runner.executor, plan)
    assert "channel #5" in str(err) and "2 channels" in str(err)


def test_mutation_project_stale_channel(tiny_runner):
    plan = P.Project(source=_VALUES2,
                     exprs=(E.input_ref(3, T.BIGINT),))
    err = _verify(tiny_runner.executor, plan)
    assert "expr #0" in str(err) and "stale channel mapping" in str(err)


def test_mutation_join_key_arity_mismatch(tiny_runner):
    plan = P.HashJoin(left=_VALUES2, right=_VALUES2,
                      left_keys=(0, 1), right_keys=(0,))
    err = _verify(tiny_runner.executor, plan)
    assert "arity mismatch" in str(err)


def test_mutation_join_key_type_mismatch(tiny_runner):
    strings = P.Values(types=(T.VARCHAR,), rows=(("x",),))
    plan = P.HashJoin(left=_VALUES2, right=strings,
                      left_keys=(0,), right_keys=(0,))
    err = _verify(tiny_runner.executor, plan)
    assert "type mismatch" in str(err) and "never match" in str(err)


def test_mutation_mismatched_exchange_partitioning(tiny_runner):
    left = P.Exchange(source=_VALUES2, kind="repartition", keys=(0,))
    right = P.Exchange(source=_VALUES2, kind="repartition", keys=(1,))
    plan = P.HashJoin(left=left, right=right,
                      left_keys=(0,), right_keys=(0,))
    err = _verify(tiny_runner.executor, plan)
    assert "partitioning disagrees" in str(err)
    assert "co-locate" in str(err)


def test_mutation_broadcast_exchange_with_keys(tiny_runner):
    plan = P.Exchange(source=_VALUES2, kind="broadcast", keys=(0,))
    err = _verify(tiny_runner.executor, plan)
    assert "only repartition partitions by key" in str(err)


def test_mutation_off_ladder_capacity():
    """A buffer capacity that bypassed SH.bucket is flagged as
    off-ladder (the program-shape canonicalization invariant)."""
    from presto_tpu.exec import membudget as MB

    report = MB.AuditReport(
        budget=1 << 34, fault_rows=None,
        buffers=[MB.BufferPlan("join build inner (1/1 pass)",
                               rows=3000, row_bytes=16)],
    )
    violations = []
    PC.check_buffers(report, violations)
    assert violations and "OFF the shapes.py bucket ladder" in \
        violations[0]
    assert "3000" in violations[0]


def test_mutation_over_fault_line_buffer():
    """A plan whose blocking merge exceeds the governed fault line is
    rejected in strict (audit-gate) mode with the chunking hint."""
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.ops.sort import SortKey
    from presto_tpu.runner import LocalRunner

    r = LocalRunner({"tpch": TpchConnector(scale=0.1)},
                    default_catalog="tpch")
    ex = r.executor
    scan = P.TableScan("tpch", "lineitem", ("l_orderkey",))
    plan = P.Sort(source=scan, keys=(SortKey(channel=0),))
    ex.fault_rows = 1 << 12  # lineitem@SF0.1 ~600k rows >> line
    err = _verify(ex, plan, strict=True)
    assert "past the governed device fault line" in str(err)
    assert "chunk" in str(err)


def test_mutation_over_budget_buffer():
    from presto_tpu.exec import membudget as MB

    report = MB.AuditReport(
        budget=1 << 20, fault_rows=None,
        buffers=[MB.BufferPlan("agg state", rows=1 << 20,
                               row_bytes=64)],
    )
    violations = []
    PC.check_buffers(report, violations)
    assert violations and "past the device-memory budget" in \
        violations[0]


def test_mutation_non_canonical_jit_key_dict(tiny_runner):
    """A dict smuggled into plan content (= jit-key material) is
    rejected for iteration-order dependence. (A dict in a scan
    CONSTRAINT is caught even earlier, by the malformed-constraint
    schema check — also pinned here.)"""
    bad = P.Values(types=(T.BIGINT,), rows=(({"a": 1},),))
    err = _verify(tiny_runner.executor, bad)
    assert "non-canonical jit-key material" in str(err)
    assert "dict" in str(err)
    scan = P.TableScan("tpch", "nation", ("n_nationkey",))
    bad2 = dataclasses.replace(scan, constraint={"n_nationkey": 1})
    err2 = _verify(tiny_runner.executor, bad2)
    assert "constraint" in str(err2)


def test_mutation_non_canonical_jit_key_object():
    violations = []

    class Opaque:
        pass

    PC.check_canonical_key_material(
        P.Values(types=(T.BIGINT,), rows=((Opaque(),),)), violations)
    assert violations and "id() leaks" in violations[0]


def test_canonical_rekey_is_byte_identical(tiny_runner):
    """The positive half of invariant 3: a real plan re-keys
    byte-identically across a serde roundtrip."""
    plan = tiny_runner.plan(
        "select n_name, count(*) from nation group by 1")
    violations = []
    PC.check_canonical_key_material(plan, violations)
    assert violations == []


def test_mutation_remote_source_schema_mismatch(tiny_runner):
    agg = P.Aggregation(
        source=_VALUES2, group_channels=(0,),
        aggregates=(P.AggSpec("sum", channel=1),), step="partial")
    remote = P.RemoteSource(types=(T.BIGINT,), key="k", origin=agg)
    err = _verify(tiny_runner.executor, remote)
    assert "schema-inconsistent fragment edge" in str(err)


def test_mutation_output_names_arity(tiny_runner):
    plan = P.Output(source=_VALUES2, names=("only_one",))
    err = _verify(tiny_runner.executor, plan)
    assert "1 output names for 2 channels" in str(err)


def test_mutation_bad_agg_step_and_capacity(tiny_runner):
    plan = P.Aggregation(
        source=_VALUES2, group_channels=(0,),
        aggregates=(P.AggSpec("sum", channel=1),),
        capacity=-4, step="both")
    err = _verify(tiny_runner.executor, plan)
    assert "unknown step" in str(err)
    assert "negative group capacity" in str(err)


def test_mutation_unknown_scan_column(tiny_runner):
    plan = P.TableScan("tpch", "nation", ("n_nationkey", "bogus"))
    err = _verify(tiny_runner.executor, plan)
    assert "'bogus'" in str(err) and "nation" in str(err)


def test_verifier_reports_all_violations_at_once(tiny_runner):
    """The verifier collects findings instead of stopping at the
    first — one run, the whole fix list."""
    plan = P.Output(
        source=P.HashJoin(left=_VALUES2, right=_VALUES2,
                          left_keys=(0, 1), right_keys=(5,)),
        names=("a",),
    )
    err = _verify(tiny_runner.executor, plan)
    assert len(err.violations) >= 3  # arity + range + names


# ------------------------------------------- split-determinism payloads


def _payload(**over):
    base = {
        "taskId": "q.0", "fragment": "{}", "splitTable": "lineitem",
        "splitIndex": 0, "splitCount": 4, "session": {},
    }
    base.update(over)
    for k, v in list(base.items()):
        if v is _MISSING:
            del base[k]
    return base


_MISSING = object()


def test_payload_ok():
    PC.check_task_payload(_payload())
    PC.check_task_payload(_payload(
        splitMode="hash",
        partitionColumns={"tpch.lineitem": "l_orderkey"}))


def test_mutation_payload_missing_split_fields():
    with pytest.raises(PC.PlanCheckError, match="splitIndex"):
        PC.check_task_payload(_payload(splitIndex=_MISSING))
    with pytest.raises(PC.PlanCheckError, match="splitCount"):
        PC.check_task_payload(_payload(splitCount=_MISSING))


def test_mutation_payload_split_out_of_range():
    with pytest.raises(PC.PlanCheckError, match="outside"):
        PC.check_task_payload(_payload(splitIndex=4))


def test_mutation_payload_hash_without_partition_columns():
    with pytest.raises(PC.PlanCheckError, match="partitionColumns"):
        PC.check_task_payload(_payload(splitMode="hash"))


def test_mutation_payload_no_split_table():
    with pytest.raises(PC.PlanCheckError, match="splitTable"):
        PC.check_task_payload(_payload(splitTable=None))


# ----------------------------------------------------- clean-plan sweep


def test_tpch_corpus_verifies_clean(tiny_runner):
    """Every TPC-H plan the engine's own planner emits passes the
    verifier — the zero-false-positive contract that lets plan_check
    run on every pytest execution."""
    from tests.tpch_queries import QUERIES

    for qid in sorted(QUERIES):
        plan = tiny_runner.plan(QUERIES[qid])
        PC.verify(tiny_runner.executor, plan)  # must not raise


def test_distributed_plans_verify_clean(tiny_runner):
    from presto_tpu.dist.fragmenter import add_exchanges
    from tests.tpch_queries import QUERIES

    for qid in (1, 3, 5):
        plan = tiny_runner.plan(QUERIES[qid])
        dplan, _ = add_exchanges(plan, tiny_runner.catalogs)
        PC.verify(tiny_runner.executor, dplan)


def test_hash_partition_count_is_wired(tiny_runner):
    """The plumbing gap the session-props lint surfaced: the
    hash_partition_count property now reaches the dist executor's
    routing (DistExecutor._route_devices)."""
    from presto_tpu.dist.executor import DistExecutor

    tiny_runner.session.set("hash_partition_count", 3)
    try:
        tiny_runner.apply_session()
        assert tiny_runner.executor.hash_partitions == 3
    finally:
        tiny_runner.session.unset("hash_partition_count")
        tiny_runner.apply_session()
    ex = DistExecutor.__new__(DistExecutor)  # routing math only
    ex.D = 8
    for hp, want in ((0, 8), (3, 3), (100, 8)):
        ex.hash_partitions = hp
        assert ex._route_devices() == want, (hp, want)


def test_ladder_is_fixed_point():
    """bucket() output always re-buckets to itself (the property the
    off-ladder check relies on)."""
    for n in (1, 7, 8, 100, 4096, 4097, 1 << 20):
        b = SH.bucket(n)
        assert SH.bucket(b) == b
