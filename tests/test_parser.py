"""SQL parser tests: all 22 TPC-H queries parse; targeted shape checks."""

import pytest

from presto_tpu.sql import ast_nodes as N
from presto_tpu.sql.parser import SqlSyntaxError, parse
from tests.tpch_queries import QUERIES


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_parses(qnum):
    q = parse(QUERIES[qnum])
    assert isinstance(q, N.Query)


def test_q1_shape():
    q = parse(QUERIES[1])
    spec = q.body
    assert isinstance(spec, N.QuerySpec)
    assert len(spec.select) == 10
    assert spec.select[2].alias == "sum_qty"
    assert len(spec.group_by) == 2
    assert len(q.order_by) == 2  # ORDER BY binds at query level
    # date minus interval
    w = spec.where
    assert isinstance(w, N.BinaryOp) and w.op == "<="
    assert isinstance(w.right, N.BinaryOp) and w.right.op == "-"
    assert w.right.right.kind == "interval"
    assert w.right.right.value == (90, "day")


def test_precedence():
    q = parse("select 1 + 2 * 3 as x")
    e = q.body.select[0].expr
    assert e.op == "+" and e.right.op == "*"
    q = parse("select a or b and c from t")
    e = q.body.select[0].expr
    assert e.op == "or" and e.right.op == "and"
    q = parse("select not a = b from t")
    e = q.body.select[0].expr
    assert isinstance(e, N.UnaryOp) and e.op == "not"
    assert e.operand.op == "="


def test_between_not_in_like():
    q = parse("select * from t where x not between 1 and 2")
    assert isinstance(q.body.where, N.Between) and q.body.where.negated
    q = parse("select * from t where x not in (1, 2)")
    assert isinstance(q.body.where, N.InList) and q.body.where.negated
    q = parse("select * from t where x not like 'a%' escape '#'")
    assert isinstance(q.body.where, N.Like) and q.body.where.negated
    assert q.body.where.escape.value == "#"


def test_join_forms():
    q = parse("""select * from a left outer join b on a.x = b.y
                 join c on b.z = c.z cross join d""")
    j = q.body.from_[0]
    assert isinstance(j, N.JoinRelation) and j.join_type == "cross"
    assert j.left.join_type == "inner"
    assert j.left.left.join_type == "left"


def test_aliases_and_derived_tables():
    q = parse("select s.x y from (select 1 as x) as s (x)")
    item = q.body.select[0]
    assert item.alias == "y"
    rel = q.body.from_[0]
    assert isinstance(rel, N.AliasedRelation)
    assert rel.alias == "s" and rel.column_aliases == ("x",)
    assert isinstance(rel.relation, N.SubqueryRelation)


def test_with_and_setops():
    q = parse("""with r (a) as (select 1) select a from r
                 union all select 2""")
    assert q.withs[0].name == "r"
    assert isinstance(q.body, N.SetOp) and q.body.op == "union_all"


def test_case_forms():
    q = parse("""select case when a > 1 then 'x' else 'y' end,
                        case b when 1 then 'p' end from t""")
    searched, simple = (i.expr for i in q.body.select)
    assert searched.operand is None and searched.default is not None
    assert simple.operand is not None and simple.default is None


def test_scalar_subquery_and_exists():
    q = parse("""select * from t where x = (select max(y) from u)
                 and exists (select * from v)""")
    w = q.body.where
    assert isinstance(w.left.right, N.ScalarSubquery)
    assert isinstance(w.right, N.Exists)


def test_count_star_and_distinct():
    q = parse("select count(*), count(distinct x), sum(all y) from t")
    c, d, s = (i.expr for i in q.body.select)
    assert c.is_star and not c.args
    assert d.distinct
    assert not s.distinct


def test_substring_from_for():
    q = parse("select substring(x from 1 for 2), substring(x, 3) from t")
    a, b = (i.expr for i in q.body.select)
    assert a.name == "substr" and len(a.args) == 3
    assert b.name == "substr" and len(b.args) == 2


def test_cast_types():
    q = parse("select cast(x as decimal(12,2)), cast(y as bigint) from t")
    a, b = (i.expr for i in q.body.select)
    assert a.type_name == "decimal(12,2)"
    assert b.type_name == "bigint"


def test_syntax_errors():
    with pytest.raises(SqlSyntaxError):
        parse("select from where")
    with pytest.raises(SqlSyntaxError):
        parse("select 1 extra_token !")
    with pytest.raises(SqlSyntaxError):
        parse("select * from t where x between 1")


def test_comments_and_case_insensitivity():
    q = parse("""-- leading comment
        SELECT /* block
        comment */ X FROM T""")
    assert isinstance(q.body.select[0].expr, N.Identifier)
    assert q.body.select[0].expr.name == "x"
