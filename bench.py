#!/usr/bin/env python3
"""Benchmark ladder on the real TPU chip (BASELINE.md configs).

Driver contract: prints exactly ONE JSON line to stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
and writes BENCH_DETAILS.json with every rung measured.

Process architecture (hard-won; see .claude/skills/verify/SKILL.md):
the axon TPU runtime permanently degrades every kernel launch in a
process after ANY device->host read, and the dominant per-process cost
is loading compiled programs through the tunnel (~10s of wall per rung
even on a warm persistent compile cache, nearly zero host CPU). So
bench.py is a pure HOST-side orchestrator — it never imports jax — and
runs each phase as a bounded subprocess holding the chip exclusively:

  1. --group-child <rung>: ONE child PER RUNG (round 15 — per-rung
     isolation, so a slow/hanging rung can only lose itself), each
     preceded by a bounded per-rung --prewarm child that pays the
     compile bill into the persistent cache off the timed path (and
     whose strict plan-check/HBM-audit verdict VETOES timing a plan
     the model says faults).
     Timing protocol (round-4 discovery): on axon block_until_ready
     returns at DISPATCH — it does not wait for the device. Honest
     wall-clock = dispatch + a one-element device->host read that
     drains the FIFO execution queue (see drain() below); cycles of
     dispatch+drain are stable and repeatable. Rounds 2-3 numbers
     measured without the drain were dispatch time only. The last
     timed run's pages double as the validation artifact: bulk decode
     happens after ALL timing, and overflow-free decode at the same
     initial capacities certifies the timed runs (capacity_boost==1).
     A faulting rung loses only its group.
  2. --oracle-child: engine-vs-sqlite correctness at ORACLE_SF.
  3. --sqlite-child: wall-clock sqlite3 baselines on CPU jax (cached in
     bench_baseline.json; the child never touches the TPU).

A global deadline (BENCH_BUDGET_S, default 1200s) bounds the ladder:
each phase gets min(its cap, remaining budget); whatever happens, the
final driver JSON line prints (phases skipped for budget are recorded
in BENCH_DETAILS.json, never silently dropped).

vs_baseline: speedup vs sqlite3 executing the adapted query over the
same generated rows on this host (single-node CPU engine stand-in; the
reference repo publishes no numbers — see BASELINE.md).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# (rung name, suite, query id, scale factor, session props).
# BASELINE.md ramp order.
#
# 1M-row pages quarter the per-query launch count vs the 256k default;
# at ~6ms of axon tunnel overhead per launch that is the difference
# between overhead-bound and bandwidth-bound (round-4 roofline).
#
# The SF10 join rungs ran for three rounds behind a
# BENCH_INCLUDE_SF10_JOINS opt-in because fixed session thresholds
# (spill_threshold_bytes / max_join_build_rows) demonstrably failed to
# keep join-pipeline intermediates under the axon >=4M-row device
# fault line. The memory governor (exec/membudget.py) now sizes every
# buffer from the footprint model — builds, probe chunks, outputs,
# scan pages all stay under the fault line BY CONSTRUCTION — so the
# rungs run unconditionally with no hand-tuned props.
#
# q1_sf100 is the north-star on-ramp (BASELINE.json): the scan-agg
# pipeline streams 600M lineitem rows through fixed-size
# generation-chunked buffers batched via the split-batch path; the
# governor bounds the resident set, so scale only costs wall clock.
BIG_PAGES = ("page_rows=1048576",)
RUNGS = [
    ("q1_sf1", "tpch", 1, 1.0, BIG_PAGES),
    ("q6_sf1", "tpch", 6, 1.0, BIG_PAGES),
    ("q3_sf01", "tpch", 3, 0.1, ()),
    ("q1_sf10", "tpch", 1, 10.0, BIG_PAGES),
    ("q6_sf10", "tpch", 6, 10.0, BIG_PAGES),
    ("q3_sf1", "tpch", 3, 1.0, BIG_PAGES),
    # BASELINE rung 4 family: Q5 became plannable at scale once the
    # join tree orders FK-safe (unique-key) builds first — the
    # c_nationkey fan-out join is gone (sql/planner.py
    # _build_join_tree)
    ("q5_sf1", "tpch", 5, 1.0, BIG_PAGES),
    # BASELINE rung 5 (TPC-DS). SF0.25 keeps the largest join build
    # (store_returns, next_pow2 of 1.32M slots) under the same line.
    ("q17_sf025", "tpcds", 17, 0.25, ()),
    # BASELINE rungs 3-4 at stated scale (memory-governed; see above)
    ("q3_sf10", "tpch", 3, 10.0, ()),
    ("q5_sf10", "tpch", 5, 10.0, ()),
    # the SF100 on-ramp: scan-agg only, no join risk
    ("q1_sf100", "tpch", 1, 100.0, BIG_PAGES),
]
HEADLINE = "q1_sf1"
ORACLE_SF = 0.01  # small-SF correctness cross-check (fast)
MAX_SQLITE_SF = 1.0  # sqlite cannot hold SF10 in RAM in reasonable time
REPS = 3
DETAILS_PATH = os.path.join(REPO, "BENCH_DETAILS.json")

# columns each query touches (for the fast sqlite loader)
QUERY_COLS = {
    ("tpch", 1): {
        "lineitem": ["l_returnflag", "l_linestatus", "l_quantity",
                     "l_extendedprice", "l_discount", "l_tax",
                     "l_shipdate"]},
    ("tpch", 6): {
        "lineitem": ["l_shipdate", "l_discount", "l_quantity",
                     "l_extendedprice"]},
    ("tpch", 3): {
        "customer": ["c_custkey", "c_mktsegment"],
        "orders": ["o_orderkey", "o_custkey", "o_orderdate",
                   "o_shippriority"],
        "lineitem": ["l_orderkey", "l_extendedprice", "l_discount",
                     "l_shipdate"]},
    ("tpch", 5): {
        "customer": ["c_custkey", "c_nationkey"],
        "orders": ["o_orderkey", "o_custkey", "o_orderdate"],
        "lineitem": ["l_orderkey", "l_suppkey", "l_extendedprice",
                     "l_discount"],
        "supplier": ["s_suppkey", "s_nationkey"],
        "nation": ["n_nationkey", "n_name", "n_regionkey"],
        "region": ["r_regionkey", "r_name"]},
    ("tpcds", 17): {
        "store_sales": ["ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
                        "ss_store_sk", "ss_ticket_number", "ss_quantity"],
        "store_returns": ["sr_returned_date_sk", "sr_item_sk",
                          "sr_customer_sk", "sr_ticket_number",
                          "sr_return_quantity"],
        "catalog_sales": ["cs_sold_date_sk", "cs_bill_customer_sk",
                          "cs_item_sk", "cs_quantity"],
        "date_dim": ["d_date_sk", "d_quarter_name"],
        "store": ["s_store_sk", "s_state"],
        "item": ["i_item_sk", "i_item_id", "i_item_desc"]},
}


def _read_details():
    if os.path.exists(DETAILS_PATH):
        with open(DETAILS_PATH) as f:
            return json.load(f)
    return {"rungs": {}}


def _write_details(details) -> None:
    with open(DETAILS_PATH, "w") as f:
        json.dump(details, f, indent=1, sort_keys=True)


def _run_child(args, timeout, env=None):
    """Run a child, return (last stdout line parsed as JSON or None,
    stderr tail)."""
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    try:
        proc = subprocess.run(
            args, capture_output=True, text=True, timeout=timeout,
            env=full_env, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return None, "timeout"
    for line in reversed(proc.stdout.strip().splitlines() or []):
        if line.startswith("{"):
            try:
                return json.loads(line), proc.stderr[-300:]
            except json.JSONDecodeError:
                break
    return None, (proc.stderr[-300:] or f"rc={proc.returncode}")


# --------------------------------------------------------- orchestrator


def _groups():
    """ONE GROUP PER RUNG (ISSUE 15 satellite, ROADMAP item 2
    remainder): every rung times and validates inside its OWN
    subprocess under its own budget, so a slow or hanging rung can
    only ever lose itself — the BENCH_r03/r04 rc=124 failure mode
    (one shared-group timeout zeroing every rung's certification,
    repeated by r05's headline group) becomes structurally
    impossible. The shared program-load bill the old (suite, sf,
    props) grouping amortized is paid instead by the per-rung
    --prewarm child into the PERSISTENT compile cache, off the timed
    path, so the timing child loads executables from disk."""
    return [[rung] for rung in RUNGS]


def _group_cap(group) -> int:
    """Wall cap for one group child, sized from the MEASURED round-5
    compile bills (BENCH_r05 driver artifact: q1 86s, q6 90s, q3 338s,
    q5 133s of first-run compile on the committed cache, plus ~45s of
    gen-compile and up to ~70s resident-first each — the round-4 model
    under-capped the group and every rung lost its validation to the
    hard kill). The child also receives an internal deadline
    (BENCH_CHILD_DEADLINE_S) so it stops TIMING in time to
    decode+validate what already ran."""
    cap = 240
    for _name, suite, qid, sf, _props in group:
        is_join = (suite, qid) not in (("tpch", 1), ("tpch", 6))
        # scan-agg: 90s compile + 45s gen-compile + 70s resident-first
        # + reps/decode; join: q3 measured 338s compile + gen + reps
        cap += 600 if is_join else 300
        if suite == "tpcds":
            # Q17's 8-table cross-channel join compiles ~600s fresh
            cap += 600
        if sf >= 10:
            cap += 480 if is_join else 120
        if sf >= 100:
            cap += 900
    return cap


def main() -> int:
    import time

    # 1200s default: the driver's own (unknown) outer window killed the
    # r3 AND r4 ladders at a harder 2400s budget before the finally
    # could print — the in-process guarantee cannot survive an outer
    # SIGKILL, so the whole ladder must finish comfortably early.
    budget = float(os.environ.get("BENCH_BUDGET_S", "1200"))
    deadline = time.time() + budget
    # the oracle phase is BASELINE.md's per-rung correctness gate; r4
    # skipped it for budget. Reserve its slice up front so the timing
    # groups cannot starve it.
    oracle_reserve = float(os.environ.get("BENCH_ORACLE_RESERVE_S", "240"))
    timing_deadline = deadline - oracle_reserve
    # Stale results must not survive an early child crash: start clean.
    if os.path.exists(DETAILS_PATH):
        os.remove(DETAILS_PATH)
    details = {"rungs": {}}
    try:
        # ---- phase 1+2: timing + validation, one child per group — a
        # rung that faults the device or hangs loses only its group
        # (observed round 3: a q3_sf10 fault killed queued timings).
        for group in _groups():
            names = [g[0] for g in group]
            remaining = timing_deadline - time.time()
            if remaining < 90:
                details = _read_details()
                for n in names:
                    details["rungs"].setdefault(n, {})[
                        "time_error"] = "skipped: bench budget exhausted"
                _write_details(details)
                print(f"# group {names}: SKIPPED (budget)",
                      file=sys.stderr)
                continue
            # ---- per-rung prewarm child (ISSUE 15 satellite): pay
            # the compile bill into the persistent cache OFF the
            # timed path — bounded on its own, so a hung compile
            # costs the rung its prewarm, never its timing budget.
            # Also runs the strict plan check + static HBM audit, so
            # a rung that would fault surfaces here. Skipped when the
            # remaining budget could not fund prewarm AND timing.
            pre_cap = min(_group_cap(group),
                          remaining - _group_cap(group) * 0.5)
            if pre_cap >= 60 and not os.environ.get(
                    "BENCH_NO_PREWARM"):
                t0 = time.time()
                pinfo, perr = _run_child(
                    [sys.executable, __file__, "--prewarm",
                     ",".join(names)],
                    timeout=pre_cap,
                )
                # the prewarm child prints its JSON even when it
                # exits nonzero — its audit VERDICTS, not just its
                # parseability, decide whether timing may proceed
                vetoed = set()
                if pinfo is not None:
                    vetoed = (set(pinfo.get("hbm_audit_failed") or ())
                              | set(pinfo.get("plan_check_failed")
                                    or ()))
                details = _read_details()
                for n in names:
                    r = details["rungs"].setdefault(n, {})
                    r["prewarm_s"] = round(time.time() - t0, 1)
                    if pinfo is None:
                        r["prewarm_error"] = perr
                    elif n in vetoed:
                        r["prewarm_error"] = (
                            "static audit failed (see prewarm child "
                            "output): plan-check/HBM verdict vetoes "
                            "timing")
                        r["time_error"] = (
                            "skipped: prewarm audit veto — launching "
                            "a plan the model says faults is the "
                            "hang the audit exists to prevent")
                    else:
                        r.pop("prewarm_error", None)
                _write_details(details)
                print(f"# prewarm {names}: "
                      f"{round(time.time() - t0, 1)}s"
                      + (f" VETOED {sorted(vetoed)}" if vetoed else
                         ("" if pinfo is not None
                          else f" FAILED: {perr[:120]}")),
                      file=sys.stderr)
                if vetoed:
                    # do NOT launch the timing child on a plan the
                    # static audit refused to execute
                    continue
                remaining = timing_deadline - time.time()
                if remaining < 90:
                    details = _read_details()
                    for n in names:
                        details["rungs"].setdefault(n, {})[
                            "time_error"] = ("skipped: bench budget "
                                             "exhausted after prewarm")
                    _write_details(details)
                    continue
            cap = min(_group_cap(group), remaining)
            info, err = _run_child(
                [sys.executable, __file__, "--group-child",
                 ",".join(names)],
                timeout=cap,
                # leave room to decode+validate completed rungs before
                # the hard kill
                env={"BENCH_CHILD_DEADLINE_S": str(max(cap - 90, 60))},
            )
            if info is None and err != "timeout":
                # transient axon compile-service failures (HTTP 500 /
                # connection resets) deserve ONE retry when budget
                # remains; a timeout does not (it would double-spend)
                remaining = timing_deadline - time.time()
                if remaining > 120:
                    print(f"# group {names}: retrying after: "
                          f"{err[:120]}", file=sys.stderr)
                    cap = min(_group_cap(group), remaining)
                    info, err = _run_child(
                        [sys.executable, __file__, "--group-child",
                         ",".join(names)],
                        timeout=cap,
                        env={"BENCH_CHILD_DEADLINE_S":
                             str(max(cap - 90, 60))},
                    )
            details = _read_details()
            if info is None:
                for n in names:
                    r = details["rungs"].setdefault(n, {})
                    if "steady_s" not in r:
                        r["time_error"] = err
                    elif "result_rows" not in r:
                        r["validate_error"] = err
                _write_details(details)
                print(f"# group {names} failed: {err}", file=sys.stderr)
        for name, *_rest in RUNGS:
            r = details["rungs"].setdefault(name, {})
            # valid = timed at a SETTLED boost whose decode was
            # overflow-free (group_child's boost ladder; absent
            # capacity_boost => the run was never certified). A rung
            # that needed a boosted capacity is still honest — the
            # timed reps ran AT that boost — it is just recorded.
            r["valid"] = bool(
                r.get("result_rows", 0) > 0  # ladder rungs are non-empty
                and r.get("capacity_boost", 0) >= 1
                and not r.get("validate_error")
            )
        _write_details(details)
        if not any(
            "steady_s" in r for r in details.get("rungs", {}).values()
        ):
            print("# all timing children failed", file=sys.stderr)
            return 1

        # ---- phase 3: sqlite baselines on CPU (cached, so usually ~0s;
        # bench_baseline.json is committed pre-populated — an uncached
        # entry is the exception, so the cap stays small and the oracle
        # reserve is honored)
        sq_budget = max(
            60, min(300, deadline - oracle_reserve - time.time())
        )
        info, err = _run_child(
            [sys.executable, __file__, "--sqlite-child"],
            timeout=sq_budget + 30,
            env={"JAX_PLATFORMS": "cpu",
                 "BENCH_SQLITE_BUDGET_S": str(sq_budget)},
        )
        cache = info or {}
        if not cache:
            # child died mid-compute: fall back to the persisted cache
            # so already-measured baselines still publish
            bp = os.path.join(REPO, "bench_baseline.json")
            if os.path.exists(bp):
                with open(bp) as f:
                    cache = json.load(f)
        for name, suite, qid, sf, _props in RUNGS:
            prefix = "" if suite == "tpch" else f"{suite}_"
            key = f"{prefix}q{qid}_sf{sf}"
            r = details["rungs"][name]
            r["sqlite_s"] = cache.get(key)
            if cache.get(key) and r.get("steady_s"):
                r["speedup_vs_sqlite"] = round(
                    cache[key] / r["steady_s"], 1
                )
        _write_details(details)

        # ---- phase 4: oracle child (engine vs sqlite at small SF) —
        # BASELINE.md's per-rung correctness gate, protected by the
        # up-front oracle_reserve so it actually runs (r4 skipped it)
        details["oracle_sf"] = ORACLE_SF
        remaining = deadline - time.time()
        if remaining < 60:
            details["oracle_ok"] = {"skipped": "bench budget exhausted"}
        else:
            info, err = _run_child(
                [sys.executable, __file__, "--oracle-child"],
                timeout=remaining,
            )
            details["oracle_ok"] = (
                info if info is not None else {"error": err}
            )
        _write_details(details)

        # ---- phase 5 (ISSUE 17): the concurrent-serving rung — the
        # loadbench batching A/B child, recorded per round like every
        # other rung. Skip-on-budget, and an SLO failure is reported
        # in the details, never allowed to zero the ladder's exit.
        remaining = deadline - time.time()
        if remaining < 180:
            details["load_skipped"] = "bench budget exhausted"
            _write_details(details)
        else:
            info, err = _run_child(
                [sys.executable, __file__, "--load"],
                timeout=remaining,
            )
            # the child wrote details["load"] itself — re-read before
            # adding the summary so it survives
            details = _read_details()
            details["load_summary"] = (
                info if info is not None else {"error": err}
            )
            _write_details(details)
        return 0
    finally:
        # the driver contract: exactly one JSON line, no matter what
        head = details.get("rungs", {}).get(HEADLINE, {})
        print(json.dumps({
            "metric": f"tpch_{HEADLINE}_wall",
            "value": head.get("steady_s", 0),
            "unit": "s",
            "vs_baseline": head.get("speedup_vs_sqlite") or 0.0,
        }))


# -------------------------------------------------------------- children


# HBM bandwidth of one v5e chip, for the efficiency metric
HBM_GBPS = 819.0
# rungs that get the device-resident (memory-connector analog) timing:
# scan = HBM read, separating data generation from query compute
RESIDENT = {"q1_sf1", "q6_sf1", "q1_sf10", "q6_sf10"}


def _col_byte_width(t) -> int:
    import numpy as np

    from presto_tpu import types as T

    if T.is_string(t):
        return 4  # dictionary codes
    if isinstance(t, T.DecimalType) and not t.is_short:
        return 16
    try:
        return np.dtype(t.numpy_dtype).itemsize
    except (TypeError, AttributeError):  # dict-coded/state types
        return 8


def group_child(only_names) -> int:
    """Time then validate the named rungs (one (suite, sf, props) group)
    in one process. D2H discipline (module docstring): all timing first,
    then validation re-runs with results kept on device, decode last.

    Attribution per rung (VERDICT r2 #3): gen_s times the on-device
    generation of exactly the columns the query touches (scan==generate
    for the generator connectors, SURVEY §8.2.6), so steady_s can be
    read as generation + query compute. resident_steady_s (RESIDENT
    rungs) times the query over a device-resident page cache — the
    memory-connector analog where a scan is an HBM read — with
    touched_gb / eff_gbps / pct_hbm quantifying how close the query
    kernel runs to the chip's HBM bandwidth."""
    import statistics
    import time

    from tools._common import configure_jax, make_runner, queries

    jax = configure_jax()
    # merge into what earlier group children wrote
    details = _read_details()
    details["backend"] = jax.default_backend()
    details["device"] = str(jax.devices()[0])
    runners = {}

    def runner_for(suite, sf, props):
        key = (suite, sf, props)
        if key not in runners:
            runners[key] = make_runner(suite, sf, props)
        return runners[key]

    profile_dir = (
        os.path.join(REPO, "bench_profile")
        if os.environ.get("BENCH_PROFILE") else None
    )

    import zlib

    from presto_tpu import compilecache as cc
    from presto_tpu.devsync import drain

    # in-child deadline (set by the orchestrator): when timing a rung
    # would run past it, skip the REMAINING rungs and decode what
    # already timed — a hard kill would lose every rung's validation
    child_deadline = None
    if os.environ.get("BENCH_CHILD_DEADLINE_S"):
        child_deadline = (
            time.time() + float(os.environ["BENCH_CHILD_DEADLINE_S"])
        )

    selected = [r for r in RUNGS if only_names is None
                or r[0] in only_names]
    for name, suite, qid, sf, props in selected:
        if (child_deadline is not None
                and time.time() > child_deadline):
            details["rungs"].setdefault(name, {})["time_error"] = (
                "skipped: group deadline reached"
            )
            _write_details(details)
            print(f"# {name}: SKIPPED (group deadline)",
                  file=sys.stderr)
            continue
        runner = runner_for(suite, sf, props)
        ex = runner.executor
        plan = runner.plan(queries(suite)[qid])

        def run_device(ex=ex, plan=plan):
            ex._pending_overflow = []
            # transfer ledger (ISSUE 12): per-run crossing tallies so
            # BENCH_DETAILS records each rung's copy tax
            ex._reset_transfer_gauges()
            # per-run path attribution (VERDICT Weak #4: rung
            # discrepancies were unexplainable without it): which
            # execution paths actually engaged, and how many fused-scan
            # launches the split batching left
            ex.pallas_joins_used = 0
            ex.pallas_kernels_used = 0
            ex.generated_joins_used = 0
            ex.fused_partial_aggs = 0
            ex.program_launches = 0
            ex.splits_scanned = 0
            ex.memory_chunked_pipelines = 0
            ex.peak_memory_bytes = 0
            # device-resident data plane (ISSUE 13): these never pass
            # through _begin_attempt on the raw pages() drive, so the
            # per-run reset lives here — recorded values are THIS
            # run's, not a settle+timed cumulative
            ex.buffers_donated = 0
            ex.mesh_local_exchanges = 0
            # ICI exchange plane (ISSUE 18): per-run, same reasoning
            ex.ici_exchanges = 0
            ex.ici_bytes = 0
            ex.mesh_exchange_fallbacks = 0
            ex.adaptive_replans = 0
            ex.adaptive_dist_flips = 0
            ex.adaptive_capacity_seeds = 0
            ex.adaptive_replan_rejected = 0
            ex.skew_preempted = 0
            ex.exchange_wire_bytes = 0
            ex.exchange_raw_bytes = 0
            ex.exchange_fetch_reused_conns = 0
            pages = list(ex.pages(plan))
            drain(pages)
            flags = list(ex._pending_overflow)
            # free materialized intermediates AND close their
            # PageStores: the governed tier selection can route
            # intermediates to host/disk stores with no spill props
            # set, and a bare dict reset would leak spill dirs across
            # the settle/timed/profile runs of a whole group child
            ex._release_stream_cache()
            return pages, flags

        def path_counters(ex=ex):
            return {
                "pallas_joins_used": ex.pallas_joins_used,
                # every Pallas engagement of ANY kind (joins, the
                # segmented-reduction agg, the exchange partition-id
                # pass) — ISSUE 18's kernel-coverage counter
                "pallas_kernels_used": ex.pallas_kernels_used,
                "generated_joins_used": ex.generated_joins_used,
                "fused_partial_aggs": ex.fused_partial_aggs,
                "program_launches": ex.program_launches,
                "splits_per_launch": (
                    round(ex.splits_scanned / ex.program_launches, 1)
                    if ex.program_launches else 0.0
                ),
                # memory governor (exec/membudget.py): largest single
                # device buffer this run + governed chunked rewrites
                "peak_device_bytes": ex.peak_memory_bytes,
                "memory_chunked_pipelines": ex.memory_chunked_pipelines,
                # fault tolerance: >0 means this rung survived a real
                # (or injected) device fault via the OOM-degradation
                # ladder — a slow correct rung, not a crashed one
                "device_oom_retries": ex.device_oom_retries,
                # transfer ledger (ISSUE 12, exec/xfer.py): the rung's
                # host<->device copy tax — ROADMAP item 6's
                # device-resident work is graded against these
                "h2d_bytes": ex.h2d_bytes,
                "d2h_bytes": ex.d2h_bytes,
                "h2d_transfers": ex.h2d_transfers,
                "d2h_transfers": ex.d2h_transfers,
                "transfer_wall_s": round(ex.transfer_wall_s, 6),
                # device-resident data plane (ISSUE 13): serde-free
                # same-process exchange edges + donated-program
                # invocations on the successful attempt
                "mesh_local_exchanges": ex.mesh_local_exchanges,
                "buffers_donated": ex.buffers_donated,
                # ICI exchange plane (ISSUE 18): repartition edges
                # lowered to in-program all_to_all + the bytes they
                # routed over the interconnect instead of the spool
                # serde/HTTP plane (0 on the local pages() drive —
                # nonzero only under the DCN stage scheduler, same
                # contract as adaptive_replans)
                "ici_exchanges": ex.ici_exchanges,
                "ici_bytes": ex.ici_bytes,
                "mesh_exchange_fallbacks": ex.mesh_exchange_fallbacks,
                # adaptive execution (ISSUE 15): re-plans applied at
                # stage boundaries (0 on the local pages() drive —
                # nonzero only when a rung runs the DCN stage
                # scheduler; recorded so BENCH_DETAILS carries the
                # full counter surface either way)
                "adaptive_replans": ex.adaptive_replans,
                "adaptive_dist_flips": ex.adaptive_dist_flips,
                "adaptive_capacity_seeds": ex.adaptive_capacity_seeds,
                "adaptive_replan_rejected":
                    ex.adaptive_replan_rejected,
                "skew_preempted": ex.skew_preempted,
                # wire-efficient exchange plane (ISSUE 16, dist/serde
                # + dist/connpool): post-codec vs pre-codec exchange
                # bytes and keep-alive reuse (0 on the local pages()
                # drive — the DCN boundary is where pages serialize)
                "exchange_wire_bytes": ex.exchange_wire_bytes,
                "exchange_raw_bytes": ex.exchange_raw_bytes,
                "exchange_fetch_reused_conns":
                    ex.exchange_fetch_reused_conns,
            }

        # ---- first (warm-up) run doubles as the BOOST-SETTLE loop:
        # a rung whose initial capacities overflow re-runs on the
        # shared boost ladder until its flags are clean, and the timed
        # reps then run AT the settled boost — so the recorded steady_s
        # times the configuration that actually produces correct
        # results, and validation can certify it honestly (r05's
        # q17_sf025 was timed at capacities whose output was truncated
        # and could never validate). Compile wall and steady wall stay
        # REPORTED SEPARATELY (compilecache.py counters), and the
        # first-run record persists BEFORE the timed reps — a
        # compile-bound rung that later hits the group deadline keeps
        # an honest first_run_s/compile_wall_s instead of vanishing
        # into a group timeout (BENCH_r05's q1/q6/q3/q5 group)
        from presto_tpu.exec import shapes as SH

        cc_base = cc.snapshot()
        t0 = time.time()
        ex._capacity_boost = 1
        for _attempt in range(6):
            pages, flags = run_device()
            if not any(bool(f) for f in flags):
                break
            ex._capacity_boost = SH.next_boost(ex._capacity_boost)
            print(f"# {name}: capacity overflow, retrying at boost "
                  f"{ex._capacity_boost}", file=sys.stderr)
        first_run = time.time() - t0
        ccd = cc.delta(cc_base)
        table = "lineitem" if suite == "tpch" else "store_sales"
        slots_in = runner.catalogs[suite].row_count(table)
        r = details["rungs"].setdefault(name, {})
        r.update({
            "suite": suite,
            "query": qid,
            "sf": sf,
            "props": list(props),
            "first_run_s": round(first_run, 3),
            "compile_s": round(first_run, 3),  # legacy alias
            "compile_wall_s": ccd["compile_wall_s"],
            "programs_compiled": ccd["programs_compiled"],
            "program_cache_hits": ccd["program_cache_hits"],
            "fact_slots": slots_in,
        })
        _write_details(details)
        print(f"# {name}: first run {first_run:.1f}s "
              f"(compile wall {ccd['compile_wall_s']}s over "
              f"{ccd['programs_compiled']} programs, "
              f"{ccd['program_cache_hits']} cache hits)",
              file=sys.stderr)
        if (child_deadline is not None
                and time.time() > child_deadline):
            r["time_error"] = (
                "timed reps skipped: group deadline (first run + "
                "compile wall recorded above)"
            )
            _write_details(details)
            continue
        times = []
        # adaptive reps: a rung whose first timed run is already slow
        # gets one rep — median-of-3 precision is not worth 2 extra
        # minutes of budget on a 60s+ rung
        reps = REPS
        for i in range(reps):
            t0 = time.time()
            pages, flags = run_device()
            dt = time.time() - t0
            times.append(dt)
            if i == 0 and dt > 60:
                break
        steady = statistics.median(times)
        if profile_dir and name == HEADLINE:
            # device-level (XLA/TPU) trace for the headline rung —
            # the jax.profiler hook complementing the engine-level
            # Chrome trace below (BENCH_PROFILE=1 enables)
            with jax.profiler.trace(profile_dir):
                run_device()
            r["device_profile_dir"] = profile_dir
        r.update({
            "steady_s": round(steady, 5),
            "times_s": [round(t, 5) for t in times],
            "slots_per_s": round(slots_in / steady),
            # rep-latency spread (ISSUE 9): with <=3 reps p99 is the
            # max — honest for the artifact, and the field names match
            # what the concurrent-load benchmark (ROADMAP item 1) will
            # report at real sample counts
            "p50_s": round(statistics.median(times), 5),
            "p99_s": round(max(times), 5),
        })
        r.pop("time_error", None)  # a retried group child succeeded
        print(f"# {name}: steady {steady*1e3:.1f} ms "
              f"({slots_in/steady/1e6:.0f}M slots/s), "
              f"first run {first_run:.0f}s", file=sys.stderr)
        _write_details(details)

        # ---- decode+validate IMMEDIATELY (VERDICT r5 Weak #2: batching
        # validation at group end meant one slow rung could void every
        # rung's certification when the group hit its deadline). The
        # last timed run's pages ARE the validation artifact — same
        # plan, same settled boost; an overflow-free decode certifies
        # the timed reps. The D2H decode cost is paid per rung now, but
        # the timing loop for THIS rung has already finished and later
        # rungs' launches were already post-first-drain.
        t0 = time.time()
        overflow = any(bool(f) for f in flags)
        rows = []
        for page in pages:
            rows.extend(page.to_pylist())
        csum = 0
        for row in rows:
            csum = (csum + zlib.crc32(repr(row).encode())) & 0xFFFFFFFF
        decode_s = time.time() - t0
        r["result_rows"] = len(rows)
        r["checksum_crc32"] = csum
        r["decode_s"] = round(decode_s, 3)
        r["wall_with_decode_s"] = round(steady + decode_s, 2)
        # path attribution for the timed run (VERDICT r2 #4 / Weak #4)
        # + the memory governor's peak_device_bytes /
        # memory_chunked_pipelines
        r.update(path_counters())
        if overflow:
            r["validate_error"] = (
                "capacity overflow persisted through the boost ladder"
            )
        else:
            # the boost the timed reps actually ran at; 1 = initial
            # capacities, >1 = honest but boosted (recorded, valid)
            r["capacity_boost"] = ex._capacity_boost
            r.pop("validate_error", None)
        _write_details(details)
        with open(os.path.join(REPO, f"val_{name}.json"), "w") as f:
            json.dump({
                "rows": len(rows),
                "wall_with_decode_s": r["wall_with_decode_s"],
                "checksum_crc32": csum,
                "capacity_boost": r.get("capacity_boost", 0),
                "head": [str(v)[:24]
                         for v in (rows[0] if rows else [])],
            }, f)
        print(f"# validate {name}: rows={len(rows)} "
              f"decode {decode_s:.2f}s overflow={overflow} "
              f"boost={ex._capacity_boost}", file=sys.stderr)
        del pages, rows

        # ---- lifecycle trace export (ISSUE 9): one extra traced run
        # per rung when BENCH_TRACE_DIR is set — off the timed path
        # and after path_counters() snapshotted the timed run, so the
        # trace run's counter resets cannot contaminate the artifact.
        # The Chrome JSON loads in Perfetto; BENCH_DETAILS records the
        # path so the driver's artifact links timing to its timeline.
        trace_dir = os.environ.get("BENCH_TRACE_DIR")
        if trace_dir:
            from presto_tpu import obs as OBS

            tr = OBS.QueryTrace(name)
            OBS.attach(ex, tr)
            try:
                ex.execute(plan)
            finally:
                OBS.finalize(ex, tr, trace_dir)
            r["trace_path"] = os.path.join(
                trace_dir, f"{name}.trace.json")
            _write_details(details)

        # ---- generation-only attribution
        cols = QUERY_COLS.get((suite, qid))
        if cols:
            conn = runner.catalogs[suite]
            page_rows = int(runner.session.get("page_rows"))
            touched = 0
            for t, cs in cols.items():
                schema = conn.table_schema(t)
                touched += conn.row_count(t) * sum(
                    _col_byte_width(schema.column_type(c)) for c in cs
                )

            def run_gen(conn=conn, cols=cols, page_rows=page_rows):
                out = None
                for t, cs in cols.items():
                    out = list(
                        conn.pages(t, cs, target_rows=page_rows)
                    )
                drain(out)

            t0 = time.time()
            run_gen()
            gen_compile = time.time() - t0
            gtimes = []
            for _ in range(3):
                t0 = time.time()
                run_gen()
                gtimes.append(time.time() - t0)
            gen_s = statistics.median(gtimes)
            r["gen_s"] = round(gen_s, 5)
            r["gen_compile_s"] = round(gen_compile, 3)
            r["touched_gb"] = round(touched / 1e9, 3)
            r["gen_gbps"] = round(touched / gen_s / 1e9, 2)
            r["eff_gbps"] = round(touched / steady / 1e9, 2)
            r["pct_hbm"] = round(
                100.0 * touched / steady / 1e9 / HBM_GBPS, 2
            )
            print(f"# {name}: gen {gen_s*1e3:.1f} ms "
                  f"({r['gen_gbps']} GB/s), query+gen eff "
                  f"{r['eff_gbps']} GB/s = {r['pct_hbm']}% HBM",
                  file=sys.stderr)
            _write_details(details)

        # ---- device-resident (memory-connector analog) timing
        if name in RESIDENT:
            rr = make_runner(suite, sf, props, cached=True)
            rex = rr.executor
            rplan = rr.plan(queries(suite)[qid])

            def run_res(rex=rex, rplan=rplan):
                rex._pending_overflow = []
                pages = list(rex.pages(rplan))
                drain(pages)
                rex._release_stream_cache()

            t0 = time.time()
            run_res()  # fills the page cache + compiles
            res_first = time.time() - t0
            rtimes = []
            for _ in range(REPS):
                t0 = time.time()
                run_res()
                rtimes.append(time.time() - t0)
            res_steady = statistics.median(rtimes)
            r["resident_first_s"] = round(res_first, 3)
            r["resident_steady_s"] = round(res_steady, 5)
            r["resident_slots_per_s"] = round(slots_in / res_steady)
            if cols:
                r["resident_eff_gbps"] = round(
                    touched / res_steady / 1e9, 2
                )
                r["resident_pct_hbm"] = round(
                    100.0 * touched / res_steady / 1e9 / HBM_GBPS, 2
                )
            print(f"# {name}: resident steady "
                  f"{res_steady*1e3:.1f} ms "
                  f"({slots_in/res_steady/1e6:.0f}M slots/s"
                  + (f", {r['resident_pct_hbm']}% HBM" if cols else "")
                  + ")", file=sys.stderr)
            del rr, rex, rplan  # free the cached pages
            _write_details(details)

    print(json.dumps({"ok": True}))
    return 0


def prewarm_child(only_names) -> int:
    """Compile the named rungs' program sets into the persistent cache
    WITHOUT timing them (run once, results discarded): later group
    children — and later processes on this machine — load executables
    from disk instead of re-invoking the compiler. This is the SF100
    on-ramp: pay the 40+ minute partitioned-join compile once, off the
    timed path. Prints one JSON line of per-rung compile stats."""
    import time

    from tools._common import configure_jax, make_runner, queries

    configure_jax()
    from presto_tpu import compilecache as cc
    from presto_tpu.devsync import drain

    out = {"cache_dir": None, "rungs": {}}
    audit_failed = []
    plan_check_failed = []  # separate list: a schema/jit-key
    # violation is not an HBM failure and must not be reported as one
    selected = [r for r in RUNGS
                if only_names is None or r[0] in only_names]
    for name, suite, qid, sf, props in selected:
        runner = make_runner(suite, sf, props)
        ex = runner.executor
        plan = runner.plan(queries(suite)[qid])
        # pre-compile plan verification (exec/plan_check.py, strict):
        # schema edges, ladder capacities, canonical jit keys — the
        # same gate tools/plan_audit.py sweeps; a violating rung
        # surfaces here instead of minting a wrong program set
        from presto_tpu.exec import plan_check as PC

        try:
            PC.verify(ex, plan, strict=True)
        except PC.PlanCheckError as e:
            plan_check_failed.append(name)
            print(f"# prewarm {name}: PLAN CHECK FAILED\n{e}",
                  file=sys.stderr)
            out["rungs"][name] = {"plan_check_ok": False}
            continue
        # static HBM audit BEFORE anything launches (tools/hbm_audit.py
        # shares the same model): a rung whose plan would exceed the
        # budget or cross the device fault line surfaces HERE, off the
        # timed path, instead of hanging a group child
        from presto_tpu.exec import membudget as MB

        report = MB.audit(ex, plan)
        bad = report.over_fault_line() + report.over_budget()
        if bad:
            audit_failed.append(name)
            print(f"# prewarm {name}: HBM AUDIT FAILED\n"
                  + MB.render(report), file=sys.stderr)
            out["rungs"][name] = {
                "hbm_audit_ok": False,
                "planned_peak_bytes": report.peak_bytes,
            }
            # do NOT execute a plan the model says crosses the fault
            # line — launching it is exactly the hang this audit exists
            # to keep off the prewarm path
            continue
        base = cc.snapshot()
        t0 = time.time()
        ex._pending_overflow = []
        pages = list(ex.pages(plan))
        drain(pages)
        ex._release_stream_cache()  # closes disk-tier spill dirs too
        d = cc.delta(base)
        d["wall_s"] = round(time.time() - t0, 3)
        d["hbm_audit_ok"] = True  # failed-audit rungs continue'd above
        d["planned_peak_bytes"] = report.peak_bytes
        out["rungs"][name] = d
        print(f"# prewarm {name}: {d['programs_compiled']} programs, "
              f"compile wall {d['compile_wall_s']}s, "
              f"{d['program_cache_hits']} cache hits", file=sys.stderr)
    out["cache_dir"] = cc.cache_dir()
    out["hbm_audit_failed"] = audit_failed
    out["plan_check_failed"] = plan_check_failed
    print(json.dumps(out))
    return 1 if audit_failed or plan_check_failed else 0


def replay_child(only_names) -> int:
    """Result-cache replay attribution (ISSUE 10): run each selected
    rung's statement TWICE through a runner with the result cache
    enabled and record cold vs cached wall in BENCH_DETAILS —
    `replay_cold_s` is ordinary execution (plus the one publication
    D2H), `replay_cached_s` is a pure page replay that skips
    compile+launch (`replay_cache_hits` >= 1 certifies the second run
    actually served from the cache; a rung whose plan is uncacheable
    records `replay_uncacheable` instead of fake numbers). Runs as its
    own child for the same chip-isolation reasons as every other
    phase. Invoke: `python bench.py --replay [r1,r2,...]`."""
    import time

    from tools._common import configure_jax, make_runner, queries

    configure_jax()
    from presto_tpu.cache import ResultCache, uncacheable_reason
    from presto_tpu.devsync import drain

    details = _read_details()
    selected = [r for r in RUNGS
                if only_names is None or r[0] in only_names]
    out = {"rungs": {}}
    for name, suite, qid, sf, props in selected:
        runner = make_runner(suite, sf, props)
        ex = runner.executor
        plan = runner.plan(queries(suite)[qid])
        r = details["rungs"].setdefault(name, {})
        reason = uncacheable_reason(plan, runner.catalogs)
        if reason is not None:
            r["replay_uncacheable"] = reason
            out["rungs"][name] = {"uncacheable": reason}
            _write_details(details)
            continue
        # a fresh per-rung store: replay attribution, not cross-rung
        # sharing (budget sized to the rung — the point is the wall
        # delta, not eviction behavior)
        ex.result_cache = ResultCache(budget_bytes=1 << 31)
        base_hits = ex.result_cache_hits
        # un-timed warm-up: compile wall must not contaminate the
        # cold-vs-cached delta (this direct pages() stream sets no
        # cache points, so it cannot pre-populate the store either)
        ex._pending_overflow = []
        pages = list(ex.pages(plan))
        drain(pages)
        flags = list(ex._pending_overflow)
        ex._release_stream_cache()
        t0 = time.time()
        ex.execute(plan)
        cold = time.time() - t0
        t0 = time.time()
        ex.execute(plan)
        cached = time.time() - t0
        hits = ex.result_cache_hits - base_hits
        if hits == 0:
            # both passes executed for real (cacheable plan but no
            # worth-caching point selected, or the entry exceeded the
            # budget): recording a "speedup" would be run-to-run
            # variance dressed up as cache effect
            r["replay_uncacheable"] = (
                "no cache hit on the second run (no cache point "
                "selected or entry not admitted)"
            )
            out["rungs"][name] = {"uncacheable": r["replay_uncacheable"]}
            _write_details(details)
            ex.result_cache = None
            continue
        r.pop("replay_uncacheable", None)
        r.update({
            "replay_cold_s": round(cold, 5),
            "replay_cached_s": round(cached, 5),
            "replay_cache_hits": hits,
            "replay_speedup": (round(cold / cached, 1)
                               if cached > 0 else None),
        })
        out["rungs"][name] = {
            "cold_s": r["replay_cold_s"],
            "cached_s": r["replay_cached_s"],
            "hits": hits,
            "overflow_seen": any(bool(f) for f in flags),
        }
        _write_details(details)
        print(f"# replay {name}: cold {cold:.3f}s -> cached "
              f"{cached:.4f}s ({hits} cache hits)", file=sys.stderr)
        ex.result_cache = None
    print(json.dumps(out))
    return 0


def oracle_child() -> int:
    """Engine-vs-sqlite correctness at ORACLE_SF using the test suites'
    adapted oracle queries."""
    out = {}
    try:
        from tests.oracle import load_sqlite
        from tests.test_sql_tpch import ENGINE_SQL, ORACLE, compare
        from tools._common import configure_jax, make_runner

        configure_jax()
        suite_qids = sorted({(s, q) for _, s, q, _, _ in RUNGS})
        runner = make_runner("tpch", ORACLE_SF)
        db = load_sqlite(runner.catalogs["tpch"],
                         runner.catalogs["tpch"].tables())
        for suite, qid in suite_qids:
            if suite != "tpch":
                continue
            try:
                got = runner.execute(ENGINE_SQL[qid]).rows
                want = db.execute(ORACLE[qid][0]).fetchall()
                compare(qid, got, want, ORACLE[qid][1])
                out[str(qid)] = True
            except AssertionError as e:
                out[str(qid)] = f"MISMATCH: {str(e)[:200]}"
        if any(s == "tpcds" for s, _ in suite_qids):
            from tests.test_sql_tpcds import (
                _compare,
                _StddevSamp,
                ds_oracle,
            )

            dsrunner = make_runner("tpcds", ORACLE_SF)
            dsdb = load_sqlite(dsrunner.catalogs["tpcds"],
                               dsrunner.catalogs["tpcds"].tables())
            dsdb.create_aggregate("stddev_samp", 1, _StddevSamp)
            from tests.tpcds_queries import QUERIES as DS_QUERIES

            for suite, qid in suite_qids:
                if suite != "tpcds":
                    continue
                try:
                    oracle_sql, float_cols = ds_oracle(qid)
                    got = dsrunner.execute(DS_QUERIES[qid]).rows
                    want = dsdb.execute(oracle_sql).fetchall()
                    _compare(got, want, float_cols, f"Q{qid}")
                    out[f"tpcds_{qid}"] = True
                except AssertionError as e:
                    out[f"tpcds_{qid}"] = f"MISMATCH: {str(e)[:200]}"
    # noqa: BLE001 - the oracle child must ALWAYS print its JSON
    # verdict; any engine/sqlite error becomes the recorded outcome
    except Exception as e:  # noqa: BLE001 - verdict must print
        out["error"] = repr(e)[:300]
    print(json.dumps(out))
    return 0


def sqlite_child() -> int:
    """sqlite3 wall-clock baselines over the same generated rows
    (single-node CPU SQL engine stand-in); cached because they are slow
    and stable. Runs with JAX_PLATFORMS=cpu — never touches the TPU."""
    import time

    import numpy as np

    from presto_tpu import types as T
    from tools._common import make_runner

    cache_path = os.path.join(REPO, "bench_baseline.json")
    cache = {}
    if os.path.exists(cache_path):
        with open(cache_path) as f:
            cache = json.load(f)
    # computing a MISSING baseline loads whole tables into sqlite
    # (minutes at SF1); respect the orchestrator's budget and always
    # print whatever the cache holds rather than dying mid-compute
    deadline = time.time() + float(
        os.environ.get("BENCH_SQLITE_BUDGET_S", "1800")
    )

    def fast_load(connector, needed):
        import sqlite3

        db = sqlite3.connect(":memory:")
        for table, cols in needed.items():
            schema = connector.table_schema(table)

            def styp(t):
                if T.is_string(t):
                    return "TEXT"
                if T.is_floating(t):
                    return "REAL"
                return "INTEGER"

            decl = ", ".join(
                f"{c} {styp(schema.column_type(c))}" for c in cols
            )
            db.execute(f"CREATE TABLE {table} ({decl})")
            # join-key indexes: without them sqlite nested-loops the
            # multi-way joins (observed: Q5 SF1 > 35 min un-indexed);
            # indexing is standard practice for a comparison engine
            # and makes the baseline FAIRER to sqlite, not worse
            for c in cols:
                if c.endswith("key") or c.endswith("_sk"):
                    db.execute(
                        f"CREATE INDEX idx_{table}_{c} ON {table}({c})"
                    )
            ins = (f"INSERT INTO {table} VALUES "
                   f"({', '.join('?' for _ in cols)})")
            for page in connector.pages(table, cols):
                idx = np.nonzero(np.asarray(page.valid))[0]
                arrays = []
                for blk in page.blocks:
                    if isinstance(blk.data, tuple):
                        hi = np.asarray(blk.data[0])[idx].astype(object)
                        lo = np.asarray(blk.data[1])[idx].astype(object)
                        col = (hi * (1 << 64)) + (lo & ((1 << 64) - 1))
                    elif blk.dictionary is not None:
                        col = blk.dictionary.decode(
                            np.asarray(blk.data)[idx])
                    else:
                        col = np.asarray(blk.data)[idx].tolist()
                    arrays.append(col)
                db.executemany(ins, zip(*arrays))
        db.commit()
        return db

    def oracle_sql(suite, qid):
        if suite == "tpch":
            from tests.test_sql_tpch import ORACLE

            return ORACLE[qid][0]
        from tests.test_sql_tpcds import _StddevSamp, ds_oracle

        return ds_oracle(qid)[0]

    for name, suite, qid, sf, _props in RUNGS:
        prefix = "" if suite == "tpch" else f"{suite}_"
        key = f"{prefix}q{qid}_sf{sf}"
        if cache.get(key) is not None or sf > MAX_SQLITE_SF:
            continue
        if time.time() > deadline - 600:
            # one uncached rung costs MINUTES (table load + query);
            # a 60s margin would start a rung it cannot finish and the
            # orchestrator would lose the whole child to the hard kill
            print(f"# sqlite {key}: skipped (budget)", file=sys.stderr)
            continue
        try:
            runner = make_runner(suite, sf)
            t0 = time.time()
            db = fast_load(runner.catalogs[suite],
                           QUERY_COLS[(suite, qid)])
            if suite == "tpcds":
                from tests.test_sql_tpcds import _StddevSamp

                db.create_aggregate("stddev_samp", 1, _StddevSamp)
            print(f"# sqlite load {key}: {time.time()-t0:.0f}s",
                  file=sys.stderr)
            sql = oracle_sql(suite, qid)
            t0 = time.time()
            db.execute(sql).fetchall()
            first = time.time() - t0
            t0 = time.time()
            db.execute(sql).fetchall()
            cache[key] = min(first, time.time() - t0)
            # persist per entry: a later rung's timeout must not lose
            # this one's minutes of work
            with open(cache_path, "w") as f:
                json.dump(
                    {k: v for k, v in cache.items() if v is not None},
                    f, indent=1, sort_keys=True)
        except Exception:  # noqa: BLE001 - never poison the cache file
            cache[key] = None
    with open(cache_path, "w") as f:
        json.dump({k: v for k, v in cache.items() if v is not None},
                  f, indent=1, sort_keys=True)
    print(json.dumps({k: v for k, v in cache.items()
                      if v is not None}))
    return 0


def load_child() -> int:
    """ISSUE 17: the concurrent-serving rung. Runs tools/loadbench.py
    twice over the SAME fixed mixed deck (8 clients, 80% repeated
    statements, fixed seed) — cross-query batching pinned OFF, then
    ON — and records QPS / p50 / p99 / cache hit rate /
    queries_per_launch / launches_per_query for both passes into
    BENCH_DETAILS.json under "load". Passes run --no-cache so every
    statement actually executes: the A/B grades the DISPATCH plane,
    and replays launch nothing.

    SLO gate (the exit code): the batched pass must not regress p99
    past BENCH_LOAD_P99_SLO_MS (default 60000 — a hang-catcher, not a
    latency promise: BENCH_LOAD_WARMUP_S of unmeasured deck keeps
    MOST compile bills out of the window, but a fresh server can
    still mint late-width batch programs inside it; deployments
    tighten the bound via the env) and must not lose QPS to the solo
    pass beyond 20%. Like every child, the last stdout line is one
    JSON object for the driver."""
    duration = float(os.environ.get("BENCH_LOAD_DURATION_S", "10"))
    warmup = float(os.environ.get("BENCH_LOAD_WARMUP_S", "6"))
    slo_ms = float(os.environ.get("BENCH_LOAD_P99_SLO_MS", "60000"))
    out = {}
    for label, knob in (("solo", "false"), ("batched", "true")):
        info, err = _run_child(
            [sys.executable, "-m", "tools.loadbench",
             "--clients", "8", "--duration", str(duration),
             "--warmup", str(warmup),
             "--repeat-frac", "0.8", "--seed", "42", "--no-cache",
             "--batching", knob],
            timeout=(duration + warmup) * 10 + 300,
        )
        out[label] = info if info is not None else {"error": err}
        print(f"# load ({label}): "
              + (json.dumps(info, sort_keys=True) if info else err),
              file=sys.stderr)
    details = _read_details()
    details["load"] = out
    _write_details(details)
    b, s = out["batched"], out["solo"]
    failures = []
    if "error" in b or "error" in s:
        failures.append("load pass failed: "
                        + str(b.get("error") or s.get("error")))
    else:
        if b["p99_ms"] > slo_ms:
            failures.append(
                f"p99 SLO: batched {b['p99_ms']}ms > {slo_ms}ms")
        if s["qps"] > 0 and b["qps"] < 0.8 * s["qps"]:
            failures.append(
                f"QPS regression: batched {b['qps']} < 80% of "
                f"solo {s['qps']}")
    summary = {
        "metric": "loadbench_batched_p99",
        "value": b.get("p99_ms", 0),
        "unit": "ms",
        "qps_batched": b.get("qps", 0),
        "qps_solo": s.get("qps", 0),
        "queries_per_launch": b.get("queries_per_launch", 0),
        "launches_per_query_batched": b.get("launches_per_query", 0),
        "launches_per_query_solo": s.get("launches_per_query", 0),
        "slo_failures": failures,
    }
    print(json.dumps(summary))
    if failures:
        for f in failures:
            print(f"# load SLO FAILED: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    if "--group-child" in sys.argv:
        i = sys.argv.index("--group-child")
        only = (
            sys.argv[i + 1].split(",")
            if len(sys.argv) > i + 1
            and not sys.argv[i + 1].startswith("-") else None
        )
        sys.exit(group_child(only))
    if "--prewarm" in sys.argv:
        i = sys.argv.index("--prewarm")
        only = (
            sys.argv[i + 1].split(",")
            if len(sys.argv) > i + 1
            and not sys.argv[i + 1].startswith("-") else None
        )
        sys.exit(prewarm_child(only))
    if "--replay" in sys.argv:
        i = sys.argv.index("--replay")
        only = (
            sys.argv[i + 1].split(",")
            if len(sys.argv) > i + 1
            and not sys.argv[i + 1].startswith("-") else None
        )
        sys.exit(replay_child(only))
    if "--oracle-child" in sys.argv:
        sys.exit(oracle_child())
    if "--sqlite-child" in sys.argv:
        sys.exit(sqlite_child())
    if "--load" in sys.argv:
        sys.exit(load_child())
    sys.exit(main())
