#!/usr/bin/env python3
"""Benchmark ladder on the real TPU chip (BASELINE.md configs).

Driver contract: prints exactly ONE JSON line to stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
and writes BENCH_DETAILS.json with every rung measured.

Measurement discipline: the axon TPU runtime permanently degrades kernel
launches after any device->host read (see presto_tpu/exec/executor.py), so
ALL timed device runs for ALL rungs happen before ANY result decode or
oracle work. Timing = wall-clock of the full plan (on-device generate ->
scan -> ... -> final page) with jax.block_until_ready on every output
leaf. Afterwards: capacity-overflow flags are verified clear, results are
decoded, and correctness is cross-checked against a sqlite3 oracle at a
small scale factor (the SF-independent plan/kernels are what's validated;
tests/test_sql_tpch.py covers all 22 queries the same way).

vs_baseline: speedup vs sqlite3 executing the adapted query over the same
generated rows on this host (single-node CPU engine stand-in; the
reference repo publishes no numbers — see BASELINE.md). sqlite times are
cached in bench_baseline.json since they are slow to measure and stable.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import numpy as np  # noqa: E402

try:
    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass

from presto_tpu.connectors.tpcds import TpcdsConnector  # noqa: E402
from presto_tpu.connectors.tpch import TpchConnector  # noqa: E402
from presto_tpu.runner import LocalRunner  # noqa: E402
from tests.tpch_queries import QUERIES  # noqa: E402
from tests.tpcds_queries import QUERIES as DS_QUERIES  # noqa: E402

# (rung name, suite, query id, scale factor). BASELINE.md ramp order; Q3
# joins the ladder once the high-cardinality group-by path lands.
RUNGS = [
    ("q1_sf1", "tpch", 1, 1.0),
    ("q6_sf1", "tpch", 6, 1.0),
    ("q3_sf01", "tpch", 3, 0.1),
    ("q1_sf10", "tpch", 1, 10.0),
    ("q6_sf10", "tpch", 6, 10.0),
    # q3 at SF1 became runnable once join-output capacities stopped
    # compounding (oc clamp) and partial-agg pages fold incrementally —
    # both keep every buffer under the axon >=4M-row fault line. SF10
    # still needs host-side re-streamable intermediates (next round).
    ("q3_sf1", "tpch", 3, 1.0),
    # BASELINE rung 5 (TPC-DS). SF0.25: the binding constraint is the
    # JOIN BUILD materialization, which compacts to next_pow2(slots) —
    # store_returns at SF0.5 (2.64M slots) rounds to 4.19M and trips the
    # >=4M-row axon kernel fault (observed: silently-fast q17 steady,
    # then every decode in the process raising UNAVAILABLE). SF0.25
    # keeps the largest build at 2.1M.
    ("q17_sf025", "tpcds", 17, 0.25),
]
HEADLINE = "q1_sf1"
ORACLE_SF = 0.01  # small-SF correctness cross-check (fast)
MAX_SQLITE_SF = 1.0  # sqlite cannot hold SF10 in RAM in reasonable time
REPS = 5

# columns each query touches (for the fast sqlite loader)
QUERY_COLS = {
    ("tpch", 1): {
        "lineitem": ["l_returnflag", "l_linestatus", "l_quantity",
                     "l_extendedprice", "l_discount", "l_tax",
                     "l_shipdate"]},
    ("tpch", 6): {
        "lineitem": ["l_shipdate", "l_discount", "l_quantity",
                     "l_extendedprice"]},
    ("tpch", 3): {
        "customer": ["c_custkey", "c_mktsegment"],
        "orders": ["o_orderkey", "o_custkey", "o_orderdate",
                   "o_shippriority"],
        "lineitem": ["l_orderkey", "l_extendedprice", "l_discount",
                     "l_shipdate"]},
    ("tpcds", 17): {
        "store_sales": ["ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
                        "ss_store_sk", "ss_ticket_number", "ss_quantity"],
        "store_returns": ["sr_returned_date_sk", "sr_item_sk",
                          "sr_customer_sk", "sr_ticket_number",
                          "sr_return_quantity"],
        "catalog_sales": ["cs_sold_date_sk", "cs_bill_customer_sk",
                          "cs_item_sk", "cs_quantity"],
        "date_dim": ["d_date_sk", "d_quarter_name"],
        "store": ["s_store_sk", "s_state"],
        "item": ["i_item_sk", "i_item_id", "i_item_desc"]},
}

SUITES = {
    "tpch": (TpchConnector, QUERIES),
    "tpcds": (TpcdsConnector, DS_QUERIES),
}


def run_device(ex, plan):
    ex._pending_overflow = []
    pages = list(ex.pages(plan))
    jax.block_until_ready(jax.tree_util.tree_leaves(pages))
    return pages, list(ex._pending_overflow)


def main() -> int:
    details = {"rungs": {}, "backend": jax.default_backend(),
               "device": str(jax.devices()[0])}
    runners = {}

    def runner_for(suite, sf):
        if (suite, sf) not in runners:
            cls, _q = SUITES[suite]
            runners[(suite, sf)] = LocalRunner(
                {suite: cls(scale=sf)}, default_catalog=suite
            )
        return runners[(suite, sf)]

    def fact_slots(runner, suite):
        table = "lineitem" if suite == "tpch" else "store_sales"
        return runner.catalogs[suite].row_count(table)

    # ---- phase 1: compile + timed device runs (NO host reads) ----
    rung_state = {}
    for name, suite, qid, sf in RUNGS:
        runner = runner_for(suite, sf)
        plan = runner.plan(SUITES[suite][1][qid])
        t0 = time.time()
        run_device(runner.executor, plan)
        compile_s = time.time() - t0
        times = []
        pages = flags = None
        for _ in range(REPS):
            t0 = time.time()
            pages, flags = run_device(runner.executor, plan)
            times.append(time.time() - t0)
        steady = statistics.median(times)
        # slot space of the driving fact table (padded capacity; true
        # rows arrive via validity masks)
        slots_in = fact_slots(runner, suite)
        details["rungs"][name] = {
            "suite": suite,
            "query": qid,
            "sf": sf,
            "compile_s": round(compile_s, 3),
            "steady_s": round(steady, 5),
            "times_s": [round(t, 5) for t in times],
            "fact_slots": slots_in,
            "slots_per_s": round(slots_in / steady),
        }
        rung_state[name] = (pages, flags)
        print(f"# {name}: steady {steady*1e3:.1f} ms "
              f"({slots_in/steady/1e6:.0f}M slots/s), compile {compile_s:.0f}s",
              file=sys.stderr)

    # timing data is safe on disk before any device->host read: the
    # first D2H can fault on a flaky tunnel, and the timed numbers
    # (block_until_ready only) must survive that
    _write_details(details)

    # ---- phase 2: overflow + decode + small-SF correctness ----
    for name, (pages, flags) in rung_state.items():
        try:
            overflow = any(bool(f) for f in flags)
            rows = []
            for p in pages:
                rows.extend(p.to_pylist())
            details["rungs"][name]["overflow"] = overflow
            details["rungs"][name]["result_rows"] = len(rows)
            details["rungs"][name]["valid"] = not overflow
        except Exception as e:  # pragma: no cover - device faults
            details["rungs"][name]["decode_error"] = repr(e)[:200]
    _write_details(details)

    details["oracle_sf"] = ORACLE_SF
    try:
        details["oracle_ok"] = _small_sf_check(
            sorted({(s, q) for _, s, q, _ in RUNGS})
        )
    except Exception as e:  # pragma: no cover
        details["oracle_ok"] = {"error": repr(e)[:200]}

    # ---- phase 3: sqlite wall-clock baseline (cached) ----
    cache_path = os.path.join(REPO, "bench_baseline.json")
    cache = {}
    if os.path.exists(cache_path):
        with open(cache_path) as f:
            cache = json.load(f)
    for name, suite, qid, sf in RUNGS:
        prefix = "" if suite == "tpch" else f"{suite}_"
        key = f"{prefix}q{qid}_sf{sf}"
        if cache.get(key) is None:
            # None never sticks: a transient sqlite failure must retry on
            # the next bench run instead of poisoning the cache file
            if sf <= MAX_SQLITE_SF:
                try:
                    cache[key] = _sqlite_time(
                        runner_for(suite, sf), suite, qid
                    )
                except Exception:  # pragma: no cover
                    cache[key] = None
            else:
                cache[key] = None
        details["rungs"][name]["sqlite_s"] = cache[key]
        if cache[key]:
            details["rungs"][name]["speedup_vs_sqlite"] = round(
                cache[key] / details["rungs"][name]["steady_s"], 1
            )
    with open(cache_path, "w") as f:
        json.dump(cache, f, indent=1, sort_keys=True)

    _write_details(details)

    head = details["rungs"][HEADLINE]
    print(json.dumps({
        "metric": f"tpch_{HEADLINE}_wall",
        "value": head["steady_s"],
        "unit": "s",
        "vs_baseline": head.get("speedup_vs_sqlite") or 0.0,
    }))
    return 0


def _write_details(details) -> None:
    with open(os.path.join(REPO, "BENCH_DETAILS.json"), "w") as f:
        json.dump(details, f, indent=1, sort_keys=True)


def _small_sf_check(suite_qids):
    """Engine-vs-sqlite correctness at ORACLE_SF using the test suites'
    adapted oracle queries (tests/test_sql_tpch.py, test_sql_tpcds.py)."""
    out = {}
    try:
        from tests.oracle import load_sqlite
        from tests.test_sql_tpch import ENGINE_SQL, ORACLE, compare

        conn = TpchConnector(scale=ORACLE_SF)
        runner = LocalRunner({"tpch": conn})
        db = load_sqlite(conn, conn.tables())
        for suite, qid in suite_qids:
            if suite != "tpch":
                continue
            try:
                got = runner.execute(ENGINE_SQL[qid]).rows
                want = db.execute(ORACLE[qid][0]).fetchall()
                compare(qid, got, want, ORACLE[qid][1])
                out[str(qid)] = True
            except AssertionError as e:
                out[str(qid)] = f"MISMATCH: {str(e)[:200]}"
        if any(s == "tpcds" for s, _ in suite_qids):
            from tests.test_sql_tpcds import (
                _compare,
                _StddevSamp,
                ds_oracle,
            )

            dsconn = TpcdsConnector(scale=ORACLE_SF)
            dsrunner = LocalRunner({"tpcds": dsconn},
                                   default_catalog="tpcds")
            dsdb = load_sqlite(dsconn, dsconn.tables())
            dsdb.create_aggregate("stddev_samp", 1, _StddevSamp)
            for suite, qid in suite_qids:
                if suite != "tpcds":
                    continue
                try:
                    oracle_sql, float_cols = ds_oracle(qid)
                    got = dsrunner.execute(DS_QUERIES[qid]).rows
                    want = dsdb.execute(oracle_sql).fetchall()
                    _compare(got, want, float_cols, f"Q{qid}")
                    out[f"tpcds_{qid}"] = True
                except AssertionError as e:
                    out[f"tpcds_{qid}"] = f"MISMATCH: {str(e)[:200]}"
    except Exception as e:  # pragma: no cover
        out["error"] = repr(e)[:300]
    return out


def _fast_load_sqlite(connector, needed):
    """Load only the needed columns into sqlite via vectorized numpy
    decode (tests/oracle.load_sqlite goes row-at-a-time through
    to_pylist, far too slow at SF1)."""
    import sqlite3

    db = sqlite3.connect(":memory:")
    for table, cols in needed.items():
        schema = connector.table_schema(table)
        from presto_tpu import types as T

        def styp(t):
            if T.is_string(t):
                return "TEXT"
            if T.is_floating(t):
                return "REAL"
            return "INTEGER"

        decl = ", ".join(
            f"{c} {styp(schema.column_type(c))}" for c in cols
        )
        db.execute(f"CREATE TABLE {table} ({decl})")
        ins = (f"INSERT INTO {table} VALUES "
               f"({', '.join('?' for _ in cols)})")
        for page in connector.pages(table, cols):
            idx = np.nonzero(np.asarray(page.valid))[0]
            arrays = []
            for blk in page.blocks:
                if isinstance(blk.data, tuple):
                    hi = np.asarray(blk.data[0])[idx].astype(object)
                    lo = np.asarray(blk.data[1])[idx].astype(object)
                    col = (hi * (1 << 64)) + (lo & ((1 << 64) - 1))
                elif blk.dictionary is not None:
                    col = blk.dictionary.decode(np.asarray(blk.data)[idx])
                else:
                    col = np.asarray(blk.data)[idx].tolist()
                arrays.append(col)
            db.executemany(ins, zip(*arrays))
    db.commit()
    return db


def _sqlite_time(runner, suite: str, qid: int) -> float:
    """Wall-clock of the adapted oracle query in sqlite3 over the same
    generated rows (single-node CPU SQL engine baseline)."""
    if suite == "tpch":
        from tests.test_sql_tpch import ORACLE

        sql = ORACLE[qid][0]
    else:
        from tests.test_sql_tpcds import ds_oracle

        sql = ds_oracle(qid)[0]
    t0 = time.time()
    db = _fast_load_sqlite(
        runner.catalogs[suite], QUERY_COLS[(suite, qid)]
    )
    if suite == "tpcds":
        from tests.test_sql_tpcds import _StddevSamp

        db.create_aggregate("stddev_samp", 1, _StddevSamp)
    load_s = time.time() - t0
    print(f"# sqlite load for {suite} q{qid}: {load_s:.0f}s",
          file=sys.stderr)
    t0 = time.time()
    db.execute(sql).fetchall()
    first = time.time() - t0
    t0 = time.time()
    db.execute(sql).fetchall()
    return min(first, time.time() - t0)


if __name__ == "__main__":
    sys.exit(main())
