"""Window function kernels: segmented scans over a partition-sorted
permutation.

Reference: presto-main operator/WindowOperator.java + operator/window/*
(PagesIndex sorted by partition+order keys, per-partition frame walks).
TPU-native redesign (SURVEY §3.2 "WindowOperator -> segmented scans"):

  1. one stable sort by (validity, partition equality words, order words)
     — bit-packed into few u64 operands (ops/keys.pack_sort_keys);
  2. partition/peer boundaries by adjacent-word comparison;
  3. rank/row_number from boundary positions, running aggregates from
     prefix sums re-based at segment starts, min/max via a segmented
     associative scan, lag/lead/first/last as bounded gathers;
  4. scatter results back to input row order.

Default SQL frames are honored: with ORDER BY the frame is RANGE
UNBOUNDED PRECEDING..CURRENT ROW (peer-extended running values), without
ORDER BY it is the whole partition.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu import types as T
from presto_tpu.ops import keys as K
from presto_tpu.ops.sort import SortKey
from presto_tpu.page import Block, Page

# functions producing BIGINT positions
RANKING = ("row_number", "rank", "dense_rank", "ntile")
# distribution functions producing DOUBLE
DISTRIBUTION = ("percent_rank", "cume_dist")
# running/frame aggregates
AGGREGATES = ("sum", "count", "count_star", "avg", "min", "max")
# offset/navigation functions
NAVIGATION = ("lag", "lead", "first_value", "last_value", "nth_value")


@dataclasses.dataclass(frozen=True)
class WindowFunc:
    function: str
    arg_channel: Optional[int] = None
    offset: int = 1  # lag/lead offset, ntile bucket count, nth_value n
    default_null: bool = True  # lag/lead default is NULL
    # explicit frame (unit, (start_kind, n), (end_kind, n)) per
    # sql/tree/WindowFrame; None = SQL default (RANGE UNBOUNDED
    # PRECEDING..CURRENT ROW with ORDER BY, whole partition without)
    frame: Optional[Tuple] = None


def result_type(fn: WindowFunc, in_type: Optional[T.SqlType]) -> T.SqlType:
    from presto_tpu.exec import agg_states as S

    if fn.function in RANKING or fn.function in ("count", "count_star"):
        return T.BIGINT
    if fn.function in DISTRIBUTION:
        return T.DOUBLE
    if fn.function in ("sum", "avg", "min", "max"):
        rt = S.result_type(fn.function, in_type)
        if isinstance(rt, T.DecimalType) and not rt.is_short:
            # window frames are per-partition prefixes; sums stay within
            # i64 at any realistic partition size, so keep the fast short
            # representation (the grouped-agg path uses 128-bit limbs)
            return T.DecimalType(18, rt.scale)
        return rt
    return in_type  # lag/lead/first_value/last_value/nth_value


def _scan_max(x: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.associative_scan(jnp.maximum, x)


def _suffix_min(x: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.associative_scan(jnp.minimum, x, reverse=True)


def _segmented_scan(op, values: jnp.ndarray, boundary: jnp.ndarray):
    """Inclusive per-segment scan: resets at each boundary (classic
    segmented-scan combine, associative)."""

    def combine(a, b):
        ab, av = a
        bb, bv = b
        return ab | bb, jnp.where(bb, bv, op(av, bv))

    _, out = jax.lax.associative_scan(combine, (boundary, values))
    return out


def window_page(
    partition_channels: Tuple[int, ...],
    order_keys: Tuple[SortKey, ...],
    functions: Tuple[WindowFunc, ...],
    out_types: Tuple[T.SqlType, ...],
    page: Page,
) -> Page:
    """Compute all window functions sharing one OVER clause; returns the
    input page with one appended Block per function."""
    n = page.capacity
    iota = jnp.arange(n, dtype=jnp.int64)

    # ---- 1. sort permutation: valid, partition words, order words ----
    parts: List = [(jnp.where(page.valid, jnp.uint64(0), jnp.uint64(1)), 1)]
    part_cols, part_nulls = K.block_key_columns(
        [page.block(c) for c in partition_channels]
    )
    for col, null in zip(part_cols, part_nulls):
        if null is not None:
            parts.append((null.astype(jnp.uint64), 1))
            col = jnp.where(null, jnp.uint64(0), col)
        parts.append((col, 64))
    for sk in order_keys:
        parts.extend(
            K.order_encoding_parts(
                page.block(sk.channel),
                ascending=sk.ascending,
                nulls_first=sk.resolved_nulls_first(),
            )
        )
    from presto_tpu.ops.sort import packed_argsort

    words = K.pack_sort_keys(parts)
    perm = packed_argsort(words, n)
    inv = jnp.zeros((n,), dtype=jnp.int64).at[perm].set(iota)
    svalid = page.valid[perm]

    # ---- 2. boundaries in sorted order ----
    def changed(ws):
        ch = jnp.zeros((n,), dtype=jnp.bool_).at[0].set(True)
        for w in ws:
            sw = w[perm]
            ch = ch | jnp.concatenate(
                [jnp.ones((1,), jnp.bool_), sw[1:] != sw[:-1]]
            )
        return ch

    # partition words: null flags + null-masked equality encodings
    pw: List[jnp.ndarray] = []
    for col, null in zip(part_cols, part_nulls):
        if null is not None:
            pw.append(null.astype(jnp.uint64))
            pw.append(jnp.where(null, jnp.uint64(0), col))
        else:
            pw.append(col)
    part_boundary = changed(pw) | ~svalid  # invalid rows: own segments
    order_words: List[jnp.ndarray] = []
    for sk in order_keys:
        for w, _bits in K.order_encoding_parts(
            page.block(sk.channel), ascending=sk.ascending,
            nulls_first=sk.resolved_nulls_first(),
        ):
            order_words.append(w)
    peer_boundary = part_boundary | (
        changed(order_words) if order_words else part_boundary
    )
    has_order = bool(order_keys)

    seg_start = _scan_max(jnp.where(part_boundary, iota, jnp.int64(0)))
    peer_start = _scan_max(jnp.where(peer_boundary, iota, jnp.int64(0)))
    # segment/peer end: next boundary - 1 (suffix-min of boundary starts)
    nxt_part = _suffix_min(
        jnp.where(
            jnp.concatenate([part_boundary[1:],
                             jnp.ones((1,), jnp.bool_)]),
            iota, jnp.int64(n - 1),
        )
    )
    nxt_peer = _suffix_min(
        jnp.where(
            jnp.concatenate([peer_boundary[1:],
                             jnp.ones((1,), jnp.bool_)]),
            iota, jnp.int64(n - 1),
        )
    )
    seg_end = nxt_part
    peer_end = nxt_peer

    cum_peer = jnp.cumsum(peer_boundary.astype(jnp.int64))

    out_blocks: List[Block] = []
    for fn, out_t in zip(functions, out_types):
        blk = (
            page.block(fn.arg_channel)
            if fn.arg_channel is not None else None
        )
        res_data, res_nulls, dic = _one_function(
            fn, blk, page, perm, inv, svalid, iota, n,
            seg_start, seg_end, peer_end, peer_start, cum_peer,
            has_order, out_t,
        )
        out_blocks.append(
            Block(data=res_data, type=out_t, nulls=res_nulls,
                  dictionary=dic)
        )
    return Page(blocks=page.blocks + tuple(out_blocks), valid=page.valid)


def _frame_bounds(fn, iota, n, seg_start, seg_end, peer_start, peer_end,
                  has_order):
    """Per-row frame [fs, fe] in sorted coordinates (fe < fs = empty).

    Reference: operator/window/FramedWindowFunction + WindowFrame.
    Default: RANGE UNBOUNDED PRECEDING..CURRENT ROW with ORDER BY
    (frame end = current peer group end), whole partition without."""
    if fn.frame is None:
        return seg_start, (peer_end if has_order else seg_end)
    unit, (sk, sn), (ek, en) = fn.frame

    def bound(kind, nn):
        if kind == "unbounded_preceding":
            return seg_start
        if kind == "unbounded_following":
            return seg_end
        if unit == "range":
            # planner admits only UNBOUNDED/CURRENT for RANGE frames
            return peer_start if kind == "current" else peer_end
        if kind == "current":
            return iota
        if kind == "preceding":
            return iota - int(nn)
        return iota + int(nn)  # following

    fs = bound(sk, sn)
    fe = bound(ek, en) if unit == "rows" else bound_end_range(
        ek, peer_end, seg_start, seg_end
    )
    fs = jnp.clip(fs, seg_start, seg_end + 1)
    fe = jnp.clip(fe, seg_start - 1, seg_end)
    return fs, fe


def bound_end_range(kind, peer_end, seg_start, seg_end):
    if kind == "unbounded_following":
        return seg_end
    if kind == "unbounded_preceding":
        return seg_start
    return peer_end  # current row extends to its peers


def _one_function(fn, blk, page, perm, inv, svalid, iota, n,
                  seg_start, seg_end, peer_end, peer_start, cum_peer,
                  has_order, out_t):
    """Result arrays in INPUT row order for one window function."""
    if fn.function == "row_number":
        res = iota - seg_start + 1
        return res[inv], None, None
    if fn.function == "rank":
        res = peer_start - seg_start + 1
        return res[inv], None, None
    if fn.function == "dense_rank":
        res = cum_peer - cum_peer[jnp.clip(seg_start, 0, n - 1)] + 1
        return res[inv], None, None
    if fn.function == "ntile":
        # SQL ntile(b): first (size % b) buckets get ceil(size/b) rows
        size = seg_end - seg_start + 1
        k = iota - seg_start  # 0-based row number
        b = jnp.int64(max(fn.offset, 1))
        q = size // b
        r = size % b
        big = r * (q + 1)
        res = jnp.where(
            k < big,
            k // jnp.maximum(q + 1, 1),
            r + (k - big) // jnp.maximum(q, 1),
        ) + 1
        return res[inv], None, None
    if fn.function == "percent_rank":
        size = seg_end - seg_start + 1
        rank = peer_start - seg_start
        res = jnp.where(
            size > 1,
            rank.astype(jnp.float64)
            / jnp.maximum(size - 1, 1).astype(jnp.float64),
            0.0,
        )
        return res[inv], None, None
    if fn.function == "cume_dist":
        size = seg_end - seg_start + 1
        res = (peer_end - seg_start + 1).astype(jnp.float64) / size.astype(
            jnp.float64
        )
        return res[inv], None, None

    fs, fe = _frame_bounds(
        fn, iota, n, seg_start, seg_end, peer_start, peer_end, has_order
    )

    if fn.function in ("lag", "lead", "first_value", "last_value",
                       "nth_value"):
        data = blk.data
        is_tuple = isinstance(data, tuple)
        snulls = (
            blk.nulls[perm] if blk.nulls is not None else None
        )
        if fn.function == "lag":
            src = iota - fn.offset
            ok = src >= seg_start
        elif fn.function == "lead":
            src = iota + fn.offset
            ok = src <= seg_end
        elif fn.function == "first_value":
            src = fs
            ok = fe >= fs
        elif fn.function == "nth_value":
            src = fs + fn.offset - 1
            ok = (src <= fe) & (fe >= fs)
        else:  # last_value = frame end
            src = fe
            ok = fe >= fs
        srcc = jnp.clip(src, 0, n - 1)

        def gather(d):
            sd = d[perm]
            return sd[srcc]

        out = (
            tuple(gather(d) for d in data) if is_tuple else gather(data)
        )
        nulls = jnp.where(ok, False, True)
        if snulls is not None:
            nulls = nulls | snulls[srcc]
        # back to input order
        if is_tuple:
            out = tuple(d[inv] for d in out)
        else:
            out = out[inv]
        return out, nulls[inv], blk.dictionary

    # ---- frame aggregates: per-row [fs, fe] in sorted coordinates ----
    contributing = svalid
    if blk is not None and blk.nulls is not None:
        contributing = contributing & ~blk.nulls[perm]

    def ranged(cum):
        """cum[fe] - cum[fs-1] over per-row frames, 0 when empty."""
        base = jnp.where(fs > 0, cum[jnp.clip(fs - 1, 0, n - 1)], 0)
        out = cum[jnp.clip(fe, 0, n - 1)] - base
        return jnp.where(fe >= fs, out, jnp.zeros((), dtype=cum.dtype))

    frame_count = ranged(jnp.cumsum(contributing.astype(jnp.int64)))

    if fn.function in ("count", "count_star"):
        if fn.arg_channel is None:
            res = ranged(jnp.cumsum(svalid.astype(jnp.int64)))
        else:
            res = frame_count
        return res[inv], None, None

    data = blk.data
    if isinstance(data, tuple):
        raise NotImplementedError(
            "window aggregates over long decimals not supported yet"
        )
    dic = blk.dictionary
    inv_rank = None
    if dic is not None and fn.function in ("min", "max") and len(dic):
        rank = jnp.asarray(dic.sort_rank().astype(np.int64))
        inv_rank = jnp.asarray(np.argsort(dic.sort_rank()).astype(np.int64))
        data = rank[jnp.clip(data, 0, len(dic) - 1)]

    sdata = data[perm]
    empty = frame_count == 0

    if fn.function in ("sum", "avg"):
        acc = jnp.where(contributing, sdata, 0).astype(
            jnp.float64 if jnp.issubdtype(sdata.dtype, jnp.floating)
            else jnp.int64
        )
        total = ranged(jnp.cumsum(acc))
        if fn.function == "sum":
            res = total.astype(np.dtype(out_t.numpy_dtype))
            return res[inv], empty[inv], None
        # avg
        cnt = jnp.maximum(frame_count, 1)
        if T.is_floating(out_t):
            res = total.astype(jnp.float64) / cnt.astype(jnp.float64)
        else:
            # integer/decimal: round-half-up like the aggregation path
            tot = total.astype(jnp.int64)
            sign = jnp.where(tot < 0, -1, 1)
            res = sign * ((jnp.abs(tot) + cnt // 2) // cnt)
        res = res.astype(np.dtype(out_t.numpy_dtype))
        return res[inv], empty[inv], None

    if fn.function in ("min", "max"):
        op = jnp.minimum if fn.function == "min" else jnp.maximum
        if jnp.issubdtype(sdata.dtype, jnp.floating):
            ident = jnp.inf if fn.function == "min" else -jnp.inf
        else:
            info = jnp.iinfo(sdata.dtype)
            ident = info.max if fn.function == "min" else info.min
        filled = jnp.where(contributing, sdata,
                           jnp.asarray(ident, dtype=sdata.dtype))
        if fn.frame is None or fn.frame[1][0] == "unbounded_preceding":
            # prefix frames: inclusive running value to the frame end
            part_boundary = seg_start == iota
            run = _segmented_scan(op, filled, part_boundary)
            res = run[jnp.clip(fe, 0, n - 1)]
        else:
            # sliding frames: sparse-table range query (O(n log n)
            # build, O(1) per row — reference walks the frame per row,
            # operator/window/AggregateWindowFunction)
            res = _range_query(op, filled, fs, fe, ident)
        if inv_rank is not None:
            res = inv_rank[jnp.clip(res, 0, inv_rank.shape[0] - 1)].astype(
                data.dtype
            )
        res = jnp.where(empty, jnp.zeros((), dtype=res.dtype), res)
        return res[inv], empty[inv], dic
    raise ValueError(f"unknown window function {fn.function!r}")


def _range_query(op, filled, fs, fe, ident):
    """Sparse-table RMQ: per-row op-reduction over [fs, fe] (callers
    handle empty frames). Levels L[k][i] = op over filled[i : i+2^k);
    query = op(L[k][fs], L[k][fe-2^k+1]) with k = floor(log2(len))."""
    n = filled.shape[0]
    levels = [filled]
    k = 0
    while (1 << (k + 1)) <= n:
        cur = levels[-1]
        step = 1 << k
        shifted = jnp.concatenate(
            [cur[step:], jnp.full((step,), ident, dtype=cur.dtype)]
        )
        levels.append(op(cur, shifted))
        k += 1
    L = jnp.stack(levels)  # (K, n)
    length = jnp.maximum(fe - fs + 1, 1)
    # floor(log2(length)) branch-free: count leading bit positions
    kk = jnp.zeros(length.shape, jnp.int64)
    for b in range(1, len(levels)):
        kk = jnp.where(length >= (1 << b), b, kk)
    a = L[kk, jnp.clip(fs, 0, n - 1)]
    b_idx = jnp.clip(fe - (jnp.int64(1) << kk) + 1, 0, n - 1)
    b = L[kk, b_idx]
    return op(a, b)
