"""Window function kernels: segmented scans over a partition-sorted
permutation.

Reference: presto-main operator/WindowOperator.java + operator/window/*
(PagesIndex sorted by partition+order keys, per-partition frame walks).
TPU-native redesign (SURVEY §3.2 "WindowOperator -> segmented scans"):

  1. one stable sort by (validity, partition equality words, order words)
     — bit-packed into few u64 operands (ops/keys.pack_sort_keys);
  2. partition/peer boundaries by adjacent-word comparison;
  3. rank/row_number from boundary positions, running aggregates from
     prefix sums re-based at segment starts, min/max via a segmented
     associative scan, lag/lead/first/last as bounded gathers;
  4. scatter results back to input row order.

Default SQL frames are honored: with ORDER BY the frame is RANGE
UNBOUNDED PRECEDING..CURRENT ROW (peer-extended running values), without
ORDER BY it is the whole partition.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu import types as T
from presto_tpu.ops import keys as K
from presto_tpu.ops.sort import SortKey
from presto_tpu.page import Block, Page

# functions producing BIGINT positions
RANKING = ("row_number", "rank", "dense_rank")
# running/frame aggregates
AGGREGATES = ("sum", "count", "count_star", "avg", "min", "max")
# offset/navigation functions
NAVIGATION = ("lag", "lead", "first_value", "last_value")


@dataclasses.dataclass(frozen=True)
class WindowFunc:
    function: str
    arg_channel: Optional[int] = None
    offset: int = 1  # lag/lead
    default_null: bool = True  # lag/lead default is NULL


def result_type(fn: WindowFunc, in_type: Optional[T.SqlType]) -> T.SqlType:
    from presto_tpu.exec import agg_states as S

    if fn.function in RANKING or fn.function in ("count", "count_star"):
        return T.BIGINT
    if fn.function in ("sum", "avg", "min", "max"):
        rt = S.result_type(fn.function, in_type)
        if isinstance(rt, T.DecimalType) and not rt.is_short:
            # window frames are per-partition prefixes; sums stay within
            # i64 at any realistic partition size, so keep the fast short
            # representation (the grouped-agg path uses 128-bit limbs)
            return T.DecimalType(18, rt.scale)
        return rt
    return in_type  # lag/lead/first_value/last_value


def _scan_max(x: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.associative_scan(jnp.maximum, x)


def _suffix_min(x: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.associative_scan(jnp.minimum, x, reverse=True)


def _segmented_scan(op, values: jnp.ndarray, boundary: jnp.ndarray):
    """Inclusive per-segment scan: resets at each boundary (classic
    segmented-scan combine, associative)."""

    def combine(a, b):
        ab, av = a
        bb, bv = b
        return ab | bb, jnp.where(bb, bv, op(av, bv))

    _, out = jax.lax.associative_scan(combine, (boundary, values))
    return out


def window_page(
    partition_channels: Tuple[int, ...],
    order_keys: Tuple[SortKey, ...],
    functions: Tuple[WindowFunc, ...],
    out_types: Tuple[T.SqlType, ...],
    page: Page,
) -> Page:
    """Compute all window functions sharing one OVER clause; returns the
    input page with one appended Block per function."""
    n = page.capacity
    iota = jnp.arange(n, dtype=jnp.int64)

    # ---- 1. sort permutation: valid, partition words, order words ----
    parts: List = [(jnp.where(page.valid, jnp.uint64(0), jnp.uint64(1)), 1)]
    part_cols, part_nulls = K.block_key_columns(
        [page.block(c) for c in partition_channels]
    )
    for col, null in zip(part_cols, part_nulls):
        if null is not None:
            parts.append((null.astype(jnp.uint64), 1))
            col = jnp.where(null, jnp.uint64(0), col)
        parts.append((col, 64))
    for sk in order_keys:
        parts.extend(
            K.order_encoding_parts(
                page.block(sk.channel),
                ascending=sk.ascending,
                nulls_first=sk.resolved_nulls_first(),
            )
        )
    from presto_tpu.ops.sort import packed_argsort

    words = K.pack_sort_keys(parts)
    perm = packed_argsort(words, n)
    inv = jnp.zeros((n,), dtype=jnp.int64).at[perm].set(iota)
    svalid = page.valid[perm]

    # ---- 2. boundaries in sorted order ----
    def changed(ws):
        ch = jnp.zeros((n,), dtype=jnp.bool_).at[0].set(True)
        for w in ws:
            sw = w[perm]
            ch = ch | jnp.concatenate(
                [jnp.ones((1,), jnp.bool_), sw[1:] != sw[:-1]]
            )
        return ch

    # partition words: null flags + null-masked equality encodings
    pw: List[jnp.ndarray] = []
    for col, null in zip(part_cols, part_nulls):
        if null is not None:
            pw.append(null.astype(jnp.uint64))
            pw.append(jnp.where(null, jnp.uint64(0), col))
        else:
            pw.append(col)
    part_boundary = changed(pw) | ~svalid  # invalid rows: own segments
    order_words: List[jnp.ndarray] = []
    for sk in order_keys:
        for w, _bits in K.order_encoding_parts(
            page.block(sk.channel), ascending=sk.ascending,
            nulls_first=sk.resolved_nulls_first(),
        ):
            order_words.append(w)
    peer_boundary = part_boundary | (
        changed(order_words) if order_words else part_boundary
    )
    has_order = bool(order_keys)

    seg_start = _scan_max(jnp.where(part_boundary, iota, jnp.int64(0)))
    peer_start = _scan_max(jnp.where(peer_boundary, iota, jnp.int64(0)))
    # segment/peer end: next boundary - 1 (suffix-min of boundary starts)
    nxt_part = _suffix_min(
        jnp.where(
            jnp.concatenate([part_boundary[1:],
                             jnp.ones((1,), jnp.bool_)]),
            iota, jnp.int64(n - 1),
        )
    )
    nxt_peer = _suffix_min(
        jnp.where(
            jnp.concatenate([peer_boundary[1:],
                             jnp.ones((1,), jnp.bool_)]),
            iota, jnp.int64(n - 1),
        )
    )
    seg_end = nxt_part
    peer_end = nxt_peer

    cum_peer = jnp.cumsum(peer_boundary.astype(jnp.int64))

    out_blocks: List[Block] = []
    for fn, out_t in zip(functions, out_types):
        blk = (
            page.block(fn.arg_channel)
            if fn.arg_channel is not None else None
        )
        res_data, res_nulls, dic = _one_function(
            fn, blk, page, perm, inv, svalid, iota, n,
            seg_start, seg_end, peer_end, peer_start, cum_peer,
            has_order, out_t,
        )
        out_blocks.append(
            Block(data=res_data, type=out_t, nulls=res_nulls,
                  dictionary=dic)
        )
    return Page(blocks=page.blocks + tuple(out_blocks), valid=page.valid)


def _one_function(fn, blk, page, perm, inv, svalid, iota, n,
                  seg_start, seg_end, peer_end, peer_start, cum_peer,
                  has_order, out_t):
    """Result arrays in INPUT row order for one window function."""
    if fn.function == "row_number":
        res = iota - seg_start + 1
        return res[inv], None, None
    if fn.function == "rank":
        res = peer_start - seg_start + 1
        return res[inv], None, None
    if fn.function == "dense_rank":
        res = cum_peer - cum_peer[jnp.clip(seg_start, 0, n - 1)] + 1
        return res[inv], None, None

    if fn.function in ("lag", "lead", "first_value", "last_value"):
        data = blk.data
        is_tuple = isinstance(data, tuple)
        snulls = (
            blk.nulls[perm] if blk.nulls is not None else None
        )
        if fn.function == "lag":
            src = iota - fn.offset
            ok = src >= seg_start
        elif fn.function == "lead":
            src = iota + fn.offset
            ok = src <= seg_end
        elif fn.function == "first_value":
            src = seg_start
            ok = jnp.ones((n,), jnp.bool_)
        else:  # last_value over default frame = end of current peer group
            src = peer_end if has_order else seg_end
            ok = jnp.ones((n,), jnp.bool_)
        srcc = jnp.clip(src, 0, n - 1)

        def gather(d):
            sd = d[perm]
            return sd[srcc]

        out = (
            tuple(gather(d) for d in data) if is_tuple else gather(data)
        )
        nulls = jnp.where(ok, False, True)
        if snulls is not None:
            nulls = nulls | snulls[srcc]
        # back to input order
        if is_tuple:
            out = tuple(d[inv] for d in out)
        else:
            out = out[inv]
        return out, nulls[inv], blk.dictionary

    # ---- running / whole-partition aggregates ----
    contributing = svalid
    if blk is not None and blk.nulls is not None:
        contributing = contributing & ~blk.nulls[perm]
    # frame end in sorted coordinates: RANGE peers with ORDER BY, whole
    # partition without
    f_end = peer_end if has_order else seg_end

    ones = contributing.astype(jnp.int64)
    cnt_cum = jnp.cumsum(ones)
    cnt_base = jnp.where(
        seg_start > 0, cnt_cum[jnp.clip(seg_start - 1, 0, n - 1)], 0
    )
    count_to = lambda idx: cnt_cum[jnp.clip(idx, 0, n - 1)] - cnt_base  # noqa: E731
    frame_count = count_to(f_end)

    if fn.function in ("count", "count_star"):
        if fn.arg_channel is None:
            valid_ones = svalid.astype(jnp.int64)
            vc = jnp.cumsum(valid_ones)
            vb = jnp.where(
                seg_start > 0, vc[jnp.clip(seg_start - 1, 0, n - 1)], 0
            )
            res = vc[jnp.clip(f_end, 0, n - 1)] - vb
        else:
            res = frame_count
        return res[inv], None, None

    data = blk.data
    if isinstance(data, tuple):
        raise NotImplementedError(
            "window aggregates over long decimals not supported yet"
        )
    dic = blk.dictionary
    inv_rank = None
    if dic is not None and fn.function in ("min", "max") and len(dic):
        rank = jnp.asarray(dic.sort_rank().astype(np.int64))
        inv_rank = jnp.asarray(np.argsort(dic.sort_rank()).astype(np.int64))
        data = rank[jnp.clip(data, 0, len(dic) - 1)]

    sdata = data[perm]
    empty = frame_count == 0

    if fn.function in ("sum", "avg"):
        acc = jnp.where(contributing, sdata, 0).astype(
            jnp.float64 if jnp.issubdtype(sdata.dtype, jnp.floating)
            else jnp.int64
        )
        cum = jnp.cumsum(acc)
        base = jnp.where(
            seg_start > 0, cum[jnp.clip(seg_start - 1, 0, n - 1)], 0
        )
        total = cum[jnp.clip(f_end, 0, n - 1)] - base
        if fn.function == "sum":
            res = total.astype(np.dtype(out_t.numpy_dtype))
            return res[inv], empty[inv], None
        # avg
        cnt = jnp.maximum(frame_count, 1)
        if T.is_floating(out_t):
            res = total.astype(jnp.float64) / cnt.astype(jnp.float64)
        else:
            # integer/decimal: round-half-up like the aggregation path
            tot = total.astype(jnp.int64)
            sign = jnp.where(tot < 0, -1, 1)
            res = sign * ((jnp.abs(tot) + cnt // 2) // cnt)
        res = res.astype(np.dtype(out_t.numpy_dtype))
        return res[inv], empty[inv], None

    if fn.function in ("min", "max"):
        op = jnp.minimum if fn.function == "min" else jnp.maximum
        if jnp.issubdtype(sdata.dtype, jnp.floating):
            ident = jnp.inf if fn.function == "min" else -jnp.inf
        else:
            info = jnp.iinfo(sdata.dtype)
            ident = info.max if fn.function == "min" else info.min
        filled = jnp.where(contributing, sdata,
                           jnp.asarray(ident, dtype=sdata.dtype))
        # inclusive running value, then extend to the frame end
        part_boundary = seg_start == iota
        run = _segmented_scan(op, filled, part_boundary)
        res = run[jnp.clip(f_end, 0, n - 1)]
        if inv_rank is not None:
            res = inv_rank[jnp.clip(res, 0, inv_rank.shape[0] - 1)].astype(
                data.dtype
            )
        res = jnp.where(empty, jnp.zeros((), dtype=res.dtype), res)
        return res[inv], empty[inv], dic
    raise ValueError(f"unknown window function {fn.function!r}")
