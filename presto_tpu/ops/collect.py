"""Bounded per-group value collection — the state behind array_agg /
map_agg / approx_percentile.

Reference: presto-main operator/aggregation/ArrayAggregationFunction
(grouped BlockBuilder state), MapAggregationFunction, and
ApproximatePercentileAggregations (qdigest sketch). The TPU translation
keeps static shapes: every group owns K slots of a [cap, K] int64 state
matrix (K = the ``array_agg_max_elements`` session property); a group
exceeding K raises a clear error rather than silently truncating.
Values encode into int64 (ints/dates/bools/short decimals directly,
dictionary-coded types by code, floats via an ORDER-PRESERVING
arithmetic sign/exponent/mantissa pack — see executor._collect_encode;
no 64-bit bitcast compiles on the axon TPU toolchain).
approx_percentile finalizes by sorting each group's K slots and
selecting — EXACT percentiles within the K bound, strictly stronger
than the reference's sketch.

Null semantics (reference parity): array_agg INCLUDES null elements
(a parallel null-flag matrix rides the state); map_agg skips null keys
but preserves null values; approx_percentile ignores nulls. Row order
within a group follows input order (the reference's array_agg order is
unspecified)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

EMPTY = jnp.int64(0)


def _group_ranks(ids: jnp.ndarray, n_invalid_id: int):
    """rank of each row within its group (stable input order). ids of
    invalid rows must equal n_invalid_id (sorted to the end)."""
    n = ids.shape[0]
    perm = jnp.argsort(ids, stable=True)
    sid = ids[perm]
    idxs = jnp.arange(n, dtype=jnp.int64)
    boundary = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sid[1:] != sid[:-1]]
    )
    run_start = jax.lax.cummax(jnp.where(boundary, idxs, 0))
    rank_sorted = idxs - run_start
    return perm, sid, rank_sorted


def insert(
    group_ids: jnp.ndarray,
    contributing: jnp.ndarray,
    out_cap: int,
    vals_i64: jnp.ndarray,
    K: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Collect contributing rows' values into [out_cap, K] group slots
    (input order). Returns (state, overflow: any group exceeded K)."""
    ids = jnp.where(contributing, group_ids.astype(jnp.int64), out_cap)
    perm, sid, rank = _group_ranks(ids, out_cap)
    flat = jnp.where(
        (sid < out_cap) & (rank < K), sid * K + rank, out_cap * K
    )
    state = (
        jnp.zeros((out_cap * K + 1,), dtype=jnp.int64)
        .at[flat]
        .set(vals_i64[perm], mode="drop")[: out_cap * K]
        .reshape(out_cap, K)
    )
    overflow = jnp.any((sid < out_cap) & (rank >= K))
    return state, overflow


def merge(
    group_ids: jnp.ndarray,
    row_valid: jnp.ndarray,
    out_cap: int,
    state: jnp.ndarray,
    counts: jnp.ndarray,
    K: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge partial collect states: each input row carries a [K] slot
    vector holding ``counts`` values; concatenate per output group in
    row order. Returns (merged [out_cap, K], overflow)."""
    n = row_valid.shape[0]
    counts = jnp.where(row_valid, counts.astype(jnp.int64), 0)
    ids = jnp.where(row_valid, group_ids.astype(jnp.int64), out_cap)
    perm, sid, _rank = _group_ranks(ids, out_cap)
    csort = counts[perm]
    # base offset of each input row inside its output group = prefix
    # sum of earlier member rows' counts (segmented prefix sum)
    cum = jnp.cumsum(csort)
    idxs = jnp.arange(n, dtype=jnp.int64)
    boundary = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sid[1:] != sid[:-1]]
    )
    excl = cum - csort  # exclusive prefix over all rows
    run_base = jax.lax.cummax(jnp.where(boundary, excl, 0))
    base = excl - run_base
    # scatter each row's first `count` slots to group base offsets
    k_idx = jnp.arange(K, dtype=jnp.int64)[None, :]
    tgt_rank = base[:, None] + k_idx  # [n, K]
    live = (k_idx < csort[:, None]) & (sid[:, None] < out_cap)
    flat = jnp.where(
        live & (tgt_rank < K),
        sid[:, None] * K + tgt_rank,
        out_cap * K,
    )
    vals_sorted = state[perm]  # [n, K]
    merged = (
        jnp.zeros((out_cap * K + 1,), dtype=jnp.int64)
        .at[flat.reshape(-1)]
        .set(vals_sorted.reshape(-1), mode="drop")[: out_cap * K]
        .reshape(out_cap, K)
    )
    overflow = jnp.any(live & (tgt_rank >= K))
    return merged, overflow


def percentile_select(
    state: jnp.ndarray,
    counts: jnp.ndarray,
    fraction: float,
    K: int,
) -> jnp.ndarray:
    """Per-group percentile over collected values: mask-pad, sort each
    row, select index ceil(p * count) - 1 (reference semantics:
    lower-interpolation percentile of the value multiset). The float
    slot-encoding (exec/executor._collect_encode) is order-preserving,
    so plain int64 ordering is correct for every element type."""
    k_idx = jnp.arange(K, dtype=jnp.int64)[None, :]
    live = k_idx < counts[:, None]
    big = jnp.iinfo(jnp.int64).max
    padded = jnp.where(live, state, big)
    s = jnp.sort(padded, axis=-1)
    want = jnp.ceil(fraction * counts.astype(jnp.float64)).astype(
        jnp.int64
    )
    pick = jnp.clip(want - 1, 0, jnp.maximum(counts - 1, 0))
    return jnp.take_along_axis(s, pick[:, None], axis=-1)[:, 0]
