"""Vectorized relational operator kernels (the TPU analog of presto-main
operator/*). Array-in/array-out, statically shaped, jit-friendly; Page-level
wiring lives in presto_tpu.exec."""
