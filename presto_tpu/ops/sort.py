"""Sort and Top-N kernels.

Reference: presto-main operator/OrderByOperator.java (accumulate into
PagesIndex, quicksort an address list, stream out) and operator/TopNOperator
(bounded heap). TPU-native: build uint64 order encodings per sort key
(presto_tpu.ops.keys), jnp.lexsort (stable, vectorized bitonic/radix under
XLA), gather rows by the permutation. Top-N is sort + head — for the page
capacities we run (<= a few hundred K rows) a full vectorized sort beats a
sequential heap by orders of magnitude on the VPU; a lax.top_k fast path
applies when there is a single numeric key.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import jax.numpy as jnp

from presto_tpu.ops import keys as K
from presto_tpu.ops.compact import gather_rows
from presto_tpu.page import Page


@dataclasses.dataclass(frozen=True)
class SortKey:
    channel: int
    ascending: bool = True
    # None = engine default (reference: unspecified null ordering maps to
    # *_NULLS_LAST for both directions)
    nulls_first: bool | None = None

    def resolved_nulls_first(self) -> bool:
        if self.nulls_first is None:
            return False
        return self.nulls_first


def sort_permutation(
    page: Page, sort_keys: Sequence[SortKey]
) -> jnp.ndarray:
    """Stable permutation ordering valid rows by keys (invalid rows last).

    Keys are bit-packed into as few u64 words as possible
    (ops/keys.pack_sort_keys) because XLA:TPU sort compile time roughly
    doubles per sort operand; typical ORDER BY clauses (dictionary columns,
    dates, one 64-bit measure) pack into 1-2 words.
    """
    import jax.lax as lax

    parts = [(jnp.where(page.valid, jnp.uint64(0), jnp.uint64(1)), 1)]
    for sk in sort_keys:
        parts.extend(
            K.order_encoding_parts(
                page.block(sk.channel),
                ascending=sk.ascending,
                nulls_first=sk.resolved_nulls_first(),
            )
        )
    words = K.pack_sort_keys(parts)
    return packed_argsort(words, page.capacity)


def packed_argsort(words, n: int) -> jnp.ndarray:
    """Stable permutation ordering rows by the MSB-first word sequence.

    Implemented as least-significant-word-first chained stable argsorts:
    XLA:TPU sort compile time grows roughly exponentially with operand
    count (a 3-operand 2M-row sort compiles in minutes), while each
    single-word argsort is a cheap 2-operand sort — k passes compile and
    run in seconds total.
    """
    perm = jnp.arange(n, dtype=jnp.int64)
    for word in reversed(words):
        w = word[perm]
        p = jnp.argsort(w, stable=True)
        perm = perm[p]
    return perm


def sort_page(
    page: Page,
    sort_keys: Sequence[SortKey],
    limit: Optional[int] = None,
    offset: int = 0,
) -> Page:
    """ORDER BY [LIMIT/OFFSET]: returns a page whose dense prefix is the
    sorted result. With a limit, output capacity shrinks to limit rows."""
    perm = sort_permutation(page, sort_keys)
    num = page.num_rows()
    if offset:
        perm = perm[offset:]
        num = jnp.maximum(num - offset, 0)
    if limit is not None and limit < perm.shape[0]:
        perm = perm[:limit]
    out_n = jnp.minimum(num, perm.shape[0])
    out_valid = jnp.arange(perm.shape[0], dtype=jnp.int64) < out_n
    return gather_rows(page, perm, out_valid)


def limit_page(page: Page, limit: int, offset: int = 0) -> Page:
    """LIMIT without ORDER BY (reference: operator/LimitOperator.java): keep
    the first `limit` valid rows in page order."""
    rank = jnp.cumsum(page.valid.astype(jnp.int64)) - 1
    keep = page.valid & (rank >= offset) & (rank < offset + limit)
    return page.with_valid(keep)
