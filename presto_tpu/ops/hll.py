"""HyperLogLog approx_distinct as segmented reductions.

Reference: presto-main operator/aggregation/ApproximateCountDistinct-
Aggregation.java (airlift-stats HyperLogLog: dense 2048-register HLL,
~2.3% standard error). The TPU translation:

- registers: M_REGS = 256 byte-wide registers per group (standard error
  1.04/sqrt(256) ~= 6.5%; the register count trades accuracy against
  per-group state bytes and is documented in the function registry).
- insert: one xxhash64 per row; low bits pick the register, the rank =
  1 + count-leading-zeros of the remaining bits. A SINGLE
  jax.ops.segment_max over composite segment ids (group * M_REGS +
  register) computes every (group, register) max in one scatter —
  the open-addressed per-row HLL update of the reference collapsed
  into one vectorized primitive.
- state: the [cap, M_REGS] byte matrix packs into WORDS = 32 i64
  columns carried as ONE tuple-data Block (same mechanism as the
  long-decimal (hi, lo) limb blocks), so HLL state pages flow through
  compaction, gathering, concatenation, and exchanges like any other
  page.
- merge: unpack to bytes, segment_max per (group, register), repack —
  HLL union is register-wise max, exactly mergeable across partials
  (PARTIAL/FINAL split and mesh repartition both preserved).
- estimate: alpha_m * m^2 / sum(2^-reg) with the standard small-range
  linear-counting correction (Flajolet et al. 2007).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

M_REGS = 256  # registers per group (SE ~= 1.04/sqrt(256) ~= 6.5%)
WORDS = M_REGS // 8  # i64 words per group (8-bit registers)
# alpha_256 per the HLL paper (m >= 128: 0.7213 / (1 + 1.079/m))
_ALPHA = 0.7213 / (1.0 + 1.079 / M_REGS)


def _reg_rank(h: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(register index, rank) per row from a u64 hash: low log2(m) bits
    pick the register; rank = 1 + leading zeros of the top 56 bits
    (max rank 57 fits comfortably in a byte)."""
    reg = (h & jnp.uint64(M_REGS - 1)).astype(jnp.int64)
    rest = h >> jnp.uint64(8)  # 56 significant bits
    # exact integer highest-set-bit via bisection (no float rounding)
    x = rest
    pos = jnp.zeros(h.shape, dtype=jnp.int64)
    for s in (32, 16, 8, 4, 2, 1):
        y = x >> jnp.uint64(s)
        take = y != 0
        pos = pos + jnp.where(take, jnp.int64(s), jnp.int64(0))
        x = jnp.where(take, y, x)
    # rest > 0: highest set bit at position pos (0-based within 56
    # bits) -> leading zeros = 55 - pos -> rank = 56 - pos;
    # rest == 0 -> rank 57
    rank = jnp.where(rest == 0, jnp.int64(57), jnp.int64(56) - pos)
    return reg, rank


def _pack(bytes2d: jnp.ndarray) -> Tuple[jnp.ndarray, ...]:
    """[cap, M_REGS] int64 byte values -> WORDS i64 arrays of [cap]."""
    out = []
    for w in range(WORDS):
        word = jnp.zeros(bytes2d.shape[:1], dtype=jnp.int64)
        for k in range(8):
            word = word | (bytes2d[:, 8 * w + k] << jnp.int64(8 * k))
        out.append(word)
    return tuple(out)


def _unpack(words: Tuple[jnp.ndarray, ...]) -> jnp.ndarray:
    """WORDS i64 arrays of [cap] -> [cap, M_REGS] int64 byte values."""
    cols = []
    for w in range(WORDS):
        for k in range(8):
            cols.append((words[w] >> jnp.int64(8 * k)) & jnp.int64(0xFF))
    return jnp.stack(cols, axis=1)


def insert(
    group_ids: jnp.ndarray,
    contributing: jnp.ndarray,
    out_capacity: int,
    hashes: jnp.ndarray,
) -> Tuple[jnp.ndarray, ...]:
    """Per-group HLL registers from raw input hashes (the PARTIAL input
    step). Returns WORDS packed i64 arrays of [out_capacity]."""
    reg, rank = _reg_rank(hashes)
    seg = jnp.where(
        contributing,
        group_ids * M_REGS + reg,
        jnp.int64(out_capacity * M_REGS),
    )
    flat = jax.ops.segment_max(
        jnp.where(contributing, rank, jnp.int64(0)),
        seg,
        num_segments=out_capacity * M_REGS + 1,
    )[: out_capacity * M_REGS]
    flat = jnp.maximum(flat, 0)  # segment_max identity is INT_MIN
    return _pack(flat.reshape(out_capacity, M_REGS))


def merge(
    group_ids: jnp.ndarray,
    contributing: jnp.ndarray,
    out_capacity: int,
    words: Tuple[jnp.ndarray, ...],
) -> Tuple[jnp.ndarray, ...]:
    """Merge partial HLL states by group (register-wise max)."""
    n = group_ids.shape[0]
    bytes2d = _unpack(words)  # [n, M_REGS]
    regs = jnp.broadcast_to(
        jnp.arange(M_REGS, dtype=jnp.int64)[None, :], (n, M_REGS)
    )
    seg = jnp.where(
        contributing[:, None],
        group_ids[:, None] * M_REGS + regs,
        jnp.int64(out_capacity * M_REGS),
    )
    flat = jax.ops.segment_max(
        jnp.where(contributing[:, None], bytes2d, 0).reshape(-1),
        seg.reshape(-1),
        num_segments=out_capacity * M_REGS + 1,
    )[: out_capacity * M_REGS]
    flat = jnp.maximum(flat, 0)
    return _pack(flat.reshape(out_capacity, M_REGS))


def estimate(words: Tuple[jnp.ndarray, ...]) -> jnp.ndarray:
    """HLL cardinality estimate per group: [cap] int64."""
    bytes2d = _unpack(words).astype(jnp.float64)  # [cap, M_REGS]
    inv_sum = jnp.sum(jnp.exp2(-bytes2d), axis=1)
    raw = _ALPHA * M_REGS * M_REGS / inv_sum
    zeros = jnp.sum((bytes2d == 0).astype(jnp.float64), axis=1)
    # small-range correction: linear counting while any register is
    # empty and the raw estimate is below 2.5m
    lc = M_REGS * jnp.log(M_REGS / jnp.maximum(zeros, 1.0))
    use_lc = (raw <= 2.5 * M_REGS) & (zeros > 0)
    est = jnp.where(use_lc, lc, raw)
    return jnp.round(est).astype(jnp.int64)


def global_insert(
    valid: jnp.ndarray, hashes: jnp.ndarray
) -> Tuple[jnp.ndarray, ...]:
    """Ungrouped insert: one group's registers as WORDS scalars-of-[1]."""
    gids = jnp.zeros(valid.shape, dtype=jnp.int64)
    return insert(gids, valid, 1, hashes)


def global_merge(
    valid: jnp.ndarray, words: Tuple[jnp.ndarray, ...]
) -> Tuple[jnp.ndarray, ...]:
    gids = jnp.zeros(valid.shape, dtype=jnp.int64)
    return merge(gids, valid, 1, words)
