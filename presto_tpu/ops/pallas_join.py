"""Pallas TPU hash-join kernels (north-star: "hash join as a Pallas
radix-partitioned join", SURVEY §8.2.2).

The join contract is shared with the sort join (ops/join.py): an index
over the HASH-SORTED build side where equal-hash rows form contiguous
segments, and a probe that returns, per probe row, the segment range
(start, count) of equal-hash build rows. ops/join.expand_matches then
flattens ranges into verified matches identically for every range
finder — searchsorted (sort join) or the open-addressing tables here.

Two table layouts, picked by plan_layout(build_capacity):

1. **"dim"** — dimension-table layout, up to DIM_MAX_BUILD build rows.
   The table is T radix tiles of 128 entries; each tile is replicated
   across the 8 sublanes, so a probe block gathers entries with the ONE
   per-lane gather this Mosaic toolchain lowers: jnp.take_along_axis on
   an (8, 128) value along the lane axis (verified on hardware; every
   wider/per-ref gather form crashes the tpu_compile_helper). Collision
   chains stay inside a tile's 128 lanes. This is the REAL compiled
   kernel and the default on TPU (pallas_join_enabled=auto) — it serves
   the broadcast-side joins of star schemas (region/nation in Q5).

2. **"radix"** — general bucketed layout up to RADIX_MAX_BUILD rows:
   VMEM-sized buckets addressed by the hash's top bits, one (hash,
   start, count) entry per unique hash. The probe is a true radix-
   partitioned pass (ISSUE 18): a host-side partition-id pass bucket-
   sorts the probe rows, then a 1-D grid probes each padded block
   against the ONE bucket slice it belongs to, the block -> bucket map
   riding in as a scalar-prefetch operand — O(N) HBM traffic instead
   of the old (bucket x block) cross-product's O(buckets * N). The
   kernel is correct and covered by the CPU suite in interpret mode,
   but its per-lane table gather exceeds what this Mosaic version can
   lower, so on TPU it runs only when forced (pallas_join_enabled=true)
   and then in interpret mode (XLA-emulated grid). The blueprint is
   written for the day the toolchain grows vector gather; until then
   big builds default to the sort join, which is the better TPU
   program anyway.

Reference: presto-main operator/{PagesIndex,JoinHash}.java — the
address-sorted PagesIndex plus an open-addressing hash over row
addresses is exactly this index, minus the pointer chasing.

u64 handling: TPU lanes are 32-bit, so hashes travel as (lo32, hi32)
int32 pairs and tables are int32 throughout. Loop carries in kernels are
int32/int32-vectors only — boolean vector carries crash this compiler
(bisected on hardware).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# dim layout: T (pow2) tiles x 128 lanes, row-replicated; chains wrap
# within a tile's 128 lanes. 2x-entries load factor => builds up to
# DIM_TILES_MAX * 128 / 2 rows.
DIM_TILES_MAX = 32
DIM_MAX_BUILD = DIM_TILES_MAX * 128 // 2  # 2048 rows
# probe groups of (8, 128) keys processed per grid step (amortizes the
# per-step fixed cost)
_DIM_GROUPS = 16

# radix layout: buckets of 2^14 entries (4 x int32 arrays = 256 KB per
# bucket slice)
BUCKET_CAP = 1 << 14
RADIX_MAX_BUILD = 1 << 20

_MAX_ITERS = 64


def _split64(keys: jnp.ndarray):
    u = keys.astype(jnp.uint64)
    lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32).astype(jnp.int32)
    hi = (u >> jnp.uint64(32)).astype(jnp.uint32).astype(jnp.int32)
    return lo, hi


def _mix32(lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    """32-bit finalizer (murmur3 fmix32 over both words) for slot
    addressing; equality is verified on the full (lo, hi) pair."""
    h = lo.astype(jnp.uint32) ^ (hi.astype(jnp.uint32) *
                                 jnp.uint32(0x85EBCA6B))
    h ^= h >> jnp.uint32(16)
    h = h * jnp.uint32(0x85EBCA6B)
    h ^= h >> jnp.uint32(13)
    h = h * jnp.uint32(0xC2B2AE35)
    h ^= h >> jnp.uint32(16)
    return h


def plan_layout(build_cap: int):
    """Static layout choice for a build of `build_cap` rows:
    ("dim", tiles) or ("radix", (num_buckets, bucket_cap)). Hashable —
    executors put it in jit cache keys."""
    if build_cap <= DIM_MAX_BUILD:
        total = max(128, 1 << (2 * build_cap - 1).bit_length())
        return ("dim", total // 128)
    total = max(BUCKET_CAP, 1 << (2 * build_cap - 1).bit_length())
    return ("radix", (total // BUCKET_CAP, BUCKET_CAP))


# ----------------------------------------------------------- index build


def _sorted_segments(bhash: jnp.ndarray, bvalid: jnp.ndarray):
    """Hash-sort the build side; equal-hash runs become segments. Per
    sorted row: the segment's first VALID position and valid count.
    Invalid rows poison to the max hash and sort last, so ordinary
    segments hold only valid rows. Callers must exclude VALID rows
    carrying the poison hash itself beforehand (build_index does, via
    the overflow escape): inside the max-hash segment the stable sort
    preserves the original valid/invalid interleaving, so (vstart,
    vcnt) would cover a non-contiguous valid set and drop matches."""
    n = bhash.shape[0]
    poisoned = jnp.where(bvalid, bhash, jnp.uint64(0xFFFFFFFFFFFFFFFF))
    perm = jnp.argsort(poisoned)
    sorted_h = poisoned[perm]
    valid_s = bvalid[perm]
    idx = jnp.arange(n, dtype=jnp.int32)
    seg_start = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_h[1:] != sorted_h[:-1]]
    )
    seg_id = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
    vcnt = (
        jnp.zeros((n,), jnp.int32).at[seg_id].add(valid_s.astype(jnp.int32))
    )[seg_id]
    vstart = (
        jnp.full((n,), n, jnp.int32)
        .at[jnp.where(valid_s, seg_id, n)]
        .min(idx, mode="drop")
    )[seg_id]
    # one entry per segment with >=1 valid row, anchored at its first
    # valid sorted position
    entry = valid_s & (idx == vstart) & (vcnt > 0)
    return perm, sorted_h, entry, vstart, vcnt


def _insert(sorted_h, entry, vstart, vcnt, base, width, table_cap,
            max_iters: int = _MAX_ITERS):
    """Vectorized open-addressing insert of segment entries by
    scatter-min, lockstep linear probing within each entry's [base,
    base+width) span. Returns flat (lo, hi, start, count) int32 tables
    and an overflow flag (unsettled after max_iters — callers fall back
    to the sort join)."""
    n = sorted_h.shape[0]
    lo, hi = _split64(sorted_h)
    h32 = _mix32(lo, hi)
    wmask = jnp.uint32(width - 1)
    slot0 = base + (h32 & wmask).astype(jnp.int32)
    idx = jnp.arange(n, dtype=jnp.int32)
    BIG = jnp.int32(n)

    def settled(owner, slot):
        return entry & (owner[slot] == idx)

    def cond(state):
        owner, slot, it = state
        return jnp.any(entry & ~settled(owner, slot)) & (it < max_iters)

    def body(state):
        owner, slot, it = state
        done = settled(owner, slot)
        claim = jnp.where(done | ~entry, BIG, idx)
        owner = owner.at[slot].min(claim)
        done2 = settled(owner, slot)
        within = (slot - base).astype(jnp.uint32)
        nxt = base + ((within + jnp.uint32(1)) & wmask).astype(jnp.int32)
        slot = jnp.where(done2 | ~entry, slot, nxt)
        return owner, slot, it + 1

    owner0 = jnp.full((table_cap,), BIG, dtype=jnp.int32)
    owner, slot, _ = jax.lax.while_loop(
        cond, body, (owner0, slot0, jnp.int32(0))
    )
    ok = settled(owner, slot)
    overflow = jnp.any(entry & ~ok)
    tgt = jnp.where(ok, slot, table_cap)
    tab_lo = jnp.zeros((table_cap,), jnp.int32).at[tgt].set(lo, mode="drop")
    tab_hi = jnp.zeros((table_cap,), jnp.int32).at[tgt].set(hi, mode="drop")
    tab_start = jnp.zeros((table_cap,), jnp.int32).at[tgt].set(
        vstart, mode="drop")
    tab_count = jnp.zeros((table_cap,), jnp.int32).at[tgt].set(
        vcnt, mode="drop")
    return (tab_lo, tab_hi, tab_start, tab_count), overflow


def build_index(bhash: jnp.ndarray, bvalid: jnp.ndarray, layout):
    """Build the (start, count) range index for `layout` (plan_layout).

    Returns (tables, perm, overflow): `perm` is the hash-sorted build
    order that start/count ranges refer to; `tables` is layout-shaped:
      dim:   4 x int32[T, 8, 128] (row-replicated tiles)
      radix: 4 x int32[num_buckets * bucket_cap] (flat bucketed)
    """
    # a VALID row whose hash equals the poison value would interleave
    # with poisoned invalid rows inside the max-hash segment and lose
    # matches (stable sort keeps original order there) — exclude such
    # rows and raise overflow so the query retries on the exact sort
    # join. Identity-encoded keys hit this for BIGINT -1; real hashes
    # at 2^-64.
    MAXU = jnp.uint64(0xFFFFFFFFFFFFFFFF)
    poison_conflict = jnp.any(bvalid & (bhash == MAXU))
    bvalid = bvalid & (bhash != MAXU)
    perm, sorted_h, entry, vstart, vcnt = _sorted_segments(bhash, bvalid)
    lo, hi = _split64(sorted_h)
    h32 = _mix32(lo, hi)
    kind, spec = layout
    if kind == "dim":
        tiles = spec
        tile = (
            ((h32 >> jnp.uint32(7))
             & jnp.uint32(tiles - 1)).astype(jnp.int32)
            if tiles > 1 else jnp.zeros(h32.shape, jnp.int32)
        )
        tabs, overflow = _insert(
            sorted_h, entry, vstart, vcnt, tile * 128, 128, tiles * 128
        )
        tabs = tuple(
            jnp.broadcast_to(t.reshape(tiles, 1, 128), (tiles, 8, 128))
            for t in tabs
        )
        return tabs, perm, overflow | poison_conflict
    num_buckets, bucket_cap = spec
    log2b = (num_buckets - 1).bit_length() if num_buckets > 1 else 0
    bucket = (
        (h32 >> jnp.uint32(32 - log2b)).astype(jnp.int32)
        if log2b else jnp.zeros(h32.shape, jnp.int32)
    )
    tabs, overflow = _insert(
        sorted_h, entry, vstart, vcnt, bucket * bucket_cap, bucket_cap,
        num_buckets * bucket_cap,
    )
    return tabs, perm, overflow | poison_conflict


# ------------------------------------------------------------ dim probe

# the ONE per-lane gather this Mosaic version lowers: within-row gather
# along the lane axis of an (8, 128) value, batched over sublanes.
# jnp.take_along_axis builds the same GatherDimensionNumbers but
# promotes indices to int64 under jax_enable_x64, which Mosaic rejects —
# so call lax.gather directly with int32 indices.
_LANE_GATHER_DNUMS = jax.lax.GatherDimensionNumbers(
    offset_dims=(),
    collapsed_slice_dims=(1,),
    start_index_map=(1,),
    operand_batching_dims=(0,),
    start_indices_batching_dims=(0,),
)


def _gather_lanes(x: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """out[i, j] = x[i, idx[i, j]] for (8, 128) int32 operands."""
    return jax.lax.gather(
        x, idx[..., None], _LANE_GATHER_DNUMS, (1, 1),
        mode=jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS,
    )


def _dim_kernel(plo_ref, phi_ref, tlo_ref, thi_ref, tstart_ref,
                tcnt_ref, start_ref, cnt_ref, *, tiles: int,
                groups: int, max_probes: int):
    for g in range(groups):
        sl = slice(g * 128, (g + 1) * 128)
        plo = plo_ref[:, sl]
        phi = phi_ref[:, sl]
        h32 = _mix32(plo, phi)
        tile_k = (
            ((h32 >> jnp.uint32(7))
             & jnp.uint32(tiles - 1)).astype(jnp.int32)
            if tiles > 1 else jnp.zeros(plo.shape, jnp.int32)
        )
        slot = (h32 & jnp.uint32(127)).astype(jnp.int32)
        start = jnp.full(plo.shape, -1, jnp.int32)
        cnt = jnp.zeros(plo.shape, jnp.int32)
        live = jnp.ones(plo.shape, jnp.int32)  # int32: bool vector
        # loop carries crash this Mosaic version (bisected)

        def cond(c):
            i, slot, start, cnt, live = c
            # int32 max-reduction: jnp.any's bool reduction trips the
            # Mosaic squeeze lowering under jax_enable_x64
            return (i < max_probes) & (jnp.max(live) > 0)

        def body(c):
            i, slot, start, cnt, live = c
            live_b = live > 0
            die = jnp.zeros(plo.shape, jnp.bool_)
            for t in range(tiles):
                sel = live_b & (tile_k == t) if tiles > 1 else live_b
                glo = _gather_lanes(tlo_ref[t], slot)
                ghi = _gather_lanes(thi_ref[t], slot)
                gc = _gather_lanes(tcnt_ref[t], slot)
                occupied = gc > 0
                hit = sel & occupied & (glo == plo) & (ghi == phi)
                start = jnp.where(
                    hit, _gather_lanes(tstart_ref[t], slot), start
                )
                cnt = jnp.where(hit, gc, cnt)
                die = die | (sel & (hit | ~occupied))
            # jnp.int32(0), not 0: a bare python int becomes an i64
            # scalar under jax_enable_x64 and Mosaic has no 64-bit
            live = jnp.where(die, jnp.int32(0), live)
            slot = jnp.where(live > 0, (slot + 1) & 127, slot)
            return i + 1, slot, start, cnt, live

        _, slot, start, cnt, live = jax.lax.while_loop(
            cond, body,
            (jnp.int32(0), slot, start, cnt, live),
        )
        start_ref[:, sl] = start
        cnt_ref[:, sl] = cnt


def _probe_dim(probe_hash, tables, tiles, *, interpret,
               max_probes: int = _MAX_ITERS + 1):
    from jax.experimental import pallas as pl

    n = probe_hash.shape[0]
    groups = _DIM_GROUPS
    block_keys = 8 * 128 * groups
    if n <= 8 * 128:
        groups, block_keys = 1, 8 * 128
    pad = (-n) % block_keys
    if pad:
        probe_hash = jnp.concatenate(
            [probe_hash, jnp.zeros((pad,), probe_hash.dtype)]
        )
    rows = probe_hash.shape[0] // (128 * groups)
    plo, phi = _split64(probe_hash)
    plo2 = plo.reshape(rows, 128 * groups)
    phi2 = phi.reshape(rows, 128 * groups)

    grid = (rows // 8,)
    pblk = pl.BlockSpec((8, 128 * groups), lambda j: (j, 0))
    tblk = pl.BlockSpec((tiles, 8, 128), lambda j: (0, 0, 0))
    kernel = functools.partial(
        _dim_kernel, tiles=tiles, groups=groups, max_probes=max_probes
    )

    def call():
        return pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((rows, 128 * groups), jnp.int32),
                jax.ShapeDtypeStruct((rows, 128 * groups), jnp.int32),
            ),
            grid=grid,
            in_specs=[pblk, pblk, tblk, tblk, tblk, tblk],
            out_specs=(pblk, pblk),
            interpret=interpret,
        )(plo2, phi2, *tables)

    if interpret:
        start, cnt = call()
    else:
        # the engine runs with jax_enable_x64 for i64 columns, but x64
        # tracing breaks Mosaic's loop legalization (bisected on
        # hardware); the kernel is all-32-bit, so trace it in a local
        # x64-off context
        with jax.enable_x64(False):
            start, cnt = call()
    return start.reshape(-1)[:n], cnt.reshape(-1)[:n]


# ---------------------------------------------------------- radix probe


def _radix_kernel(plo_ref, phi_ref, tlo_ref, thi_ref, tstart_ref,
                  tcnt_ref, start_ref, cnt_ref, *, bucket_cap: int,
                  log2b: int, max_probes: int):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    plo = plo_ref[:]
    phi = phi_ref[:]
    h32 = _mix32(plo, phi)
    if log2b:
        live0 = (
            (h32 >> jnp.uint32(32 - log2b)).astype(jnp.int32) == b
        )
    else:
        live0 = jnp.ones(plo.shape, jnp.bool_)
    mask = jnp.uint32(bucket_cap - 1)
    slot = (h32 & mask).astype(jnp.int32)
    start = jnp.full(plo.shape, -1, dtype=jnp.int32)
    cnt = jnp.zeros(plo.shape, dtype=jnp.int32)
    live = live0.astype(jnp.int32)

    def body(_i, carry):
        slot, start, cnt, live = carry
        live_b = live > 0
        tlo = tlo_ref[slot]
        thi = thi_ref[slot]
        tc = tcnt_ref[slot]
        occupied = tc > 0
        hit = live_b & occupied & (tlo == plo) & (thi == phi)
        start = jnp.where(hit, tstart_ref[slot], start)
        cnt = jnp.where(hit, tc, cnt)
        live = jnp.where(hit | ~occupied, jnp.int32(0), live)
        nxt = (slot.astype(jnp.uint32) + jnp.uint32(1)) & mask
        slot = jnp.where(live > 0, nxt.astype(jnp.int32), slot)
        return slot, start, cnt, live

    slot, start, cnt, live = jax.lax.fori_loop(
        0, max_probes, body, (slot, start, cnt, live)
    )
    start_ref[:] = start
    cnt_ref[:] = cnt


def _probe_radix(probe_hash, tables, num_buckets, bucket_cap, *,
                 interpret, block_rows: int = 2048,
                 max_probes: int = _MAX_ITERS + 1):
    """Partition-id pass + per-bucket probe (ISSUE 18).

    The old shape ran a (num_buckets, nblocks) cross-product grid —
    every probe block re-read against EVERY bucket slice, O(B * N)
    HBM traffic with each row live in exactly one step. Now a host-
    side partition-id pass buckets the rows first: sort probe rows by
    their hash's bucket id, pad each bucket's run to a block_rows
    multiple (<= num_buckets * (block_rows - 1) pad rows, static
    bound), and run a 1-D (nblocks,) grid where each block probes
    exactly the ONE bucket slice it belongs to. The block -> bucket
    map is data-dependent, so it rides in as a scalar-prefetch operand
    driving the table BlockSpec index_map — the Pallas radix-join
    shape from the north-star (partition pass, then per-partition
    build/probe with grid-blocked HBM tiling).

    Pad slots carry hash 0 and probe like real rows (bounded by
    max_probes), but their results are never gathered back."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    log2b = (num_buckets - 1).bit_length() if num_buckets > 1 else 0
    n = probe_hash.shape[0]
    plo, phi = _split64(probe_hash)
    h32 = _mix32(plo, phi)
    bucket = (
        (h32 >> jnp.uint32(32 - log2b)).astype(jnp.int32)
        if log2b else jnp.zeros(h32.shape, jnp.int32)
    )
    # partition-id pass: stable bucket sort + padded per-bucket runs
    perm = jnp.argsort(bucket)
    sbucket = bucket[perm]
    counts = jnp.zeros((num_buckets,), jnp.int32).at[bucket].add(
        jnp.int32(1)
    )
    padded = (
        (counts + jnp.int32(block_rows - 1)) // jnp.int32(block_rows)
    ) * jnp.int32(block_rows)
    off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts).astype(jnp.int32)]
    )
    pad_off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(padded).astype(jnp.int32)]
    )
    idx = jnp.arange(n, dtype=jnp.int32)
    # padded position of sorted row i: bucket base + rank within bucket
    ppos = pad_off[sbucket] + (idx - off[sbucket])
    # static ceiling: every bucket pads by < block_rows
    npad = -(-(n + num_buckets * (block_rows - 1)) // block_rows)
    npad *= block_rows
    nblocks = npad // block_rows
    plo_p = jnp.zeros((npad,), jnp.int32).at[ppos].set(
        plo[perm], mode="drop")
    phi_p = jnp.zeros((npad,), jnp.int32).at[ppos].set(
        phi[perm], mode="drop")
    # block -> bucket map (scalar prefetch): block k serves the bucket
    # whose padded run covers row k * block_rows; trailing blocks past
    # the last padded row clip to the final bucket and probe pad slots
    bstarts = jnp.arange(nblocks, dtype=jnp.int32) * jnp.int32(
        block_rows)
    bmap = jnp.clip(
        jnp.searchsorted(pad_off[1:], bstarts, side="right").astype(
            jnp.int32),
        0, num_buckets - 1,
    )
    pblk = pl.BlockSpec((block_rows,), lambda j, bmap: (j,))
    tblk = pl.BlockSpec((bucket_cap,), lambda j, bmap: (bmap[j],))
    # in-bucket rows need no bucket-id filter (log2b=0 => all live):
    # the partition pass already routed each block to its one bucket
    inner = functools.partial(
        _radix_kernel, bucket_cap=bucket_cap, log2b=0,
        max_probes=max_probes,
    )

    def kernel(bmap_ref, *refs):
        # the scalar-prefetch operand only drives the index_maps; the
        # probe body never reads it
        inner(*refs)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblocks,),
        in_specs=[pblk, pblk, tblk, tblk, tblk, tblk],
        out_specs=(pblk, pblk),
    )
    start_p, cnt_p = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((npad,), jnp.int32),
            jax.ShapeDtypeStruct((npad,), jnp.int32),
        ),
        interpret=interpret,
    )(bmap, plo_p, phi_p, *tables)
    # gather each row's result from its padded slot, then undo the
    # bucket sort
    start = jnp.full((n,), -1, jnp.int32).at[perm].set(start_p[ppos])
    cnt = jnp.zeros((n,), jnp.int32).at[perm].set(cnt_p[ppos])
    return start, cnt


def probe_index(probe_hash: jnp.ndarray, tables, layout, *,
                interpret: bool = False):
    """Per probe row, the hash-sorted build segment (start, count) of
    equal-hash valid build rows ((-1, 0) when none)."""
    kind, spec = layout
    if kind == "dim":
        return _probe_dim(probe_hash, tables, spec, interpret=interpret)
    nb, bc = spec
    return _probe_radix(probe_hash, tables, nb, bc, interpret=interpret)


def layout_lowers_on_tpu(layout) -> bool:
    """Whether this layout's probe kernel actually lowers through
    Mosaic on the current toolchain (the dim kernel does; the radix
    kernel's per-lane table gather does not and must run interpreted —
    see module docstring)."""
    return layout[0] == "dim"


# ------------------------------------------------------- unique wrapper


def join_unique(
    build_keys: jnp.ndarray,
    build_valid: jnp.ndarray,
    probe_keys: jnp.ndarray,
    probe_valid: jnp.ndarray,
    *,
    interpret: bool = False,
):
    """Unique-build-key inner-join mapping: per probe row the matching
    VALID build row id, or -1. Uses the IDENTITY u64 encoding as the
    hash, so in-kernel (lo, hi) equality IS key equality — callers may
    extend rows by the returned id without re-verification.

    Returns (row_ids int32, overflow)."""
    nb = int(build_keys.shape[0])
    layout = plan_layout(nb)
    tables, perm, overflow = build_index(
        build_keys.astype(jnp.uint64), build_valid, layout
    )
    start, cnt = probe_index(
        probe_keys.astype(jnp.uint64), tables, layout, interpret=interpret
    )
    hit = probe_valid & (cnt > 0)
    rid = jnp.where(
        hit,
        perm[jnp.clip(start, 0, None)].astype(jnp.int32),
        jnp.int32(-1),
    )
    return rid, overflow
